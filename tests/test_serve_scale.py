"""The serving scaling half (ISSUE 15): packed binary wire codec v2
(gf2_packed layout on the wire, negotiated at connect, v1 JSON clients
still served, fuzz/robustness against torn and malformed binary frames),
cross-session fused dispatch (one cell-fused program per bucket family,
bit-exact vs the per-session path AND offline ``decode_batch`` with zero
warm-path retraces, counted fallbacks), hot-session mesh sharding (shot
axis over a mesh, bit-exact, unshard degrade rung), the admission-driven
autoscaler (deterministic injected ``now``, ``scale_event`` telemetry,
/varz exposure), the v5 event-schema back-compat chain, and the
bench_compare gates for the new wire/fused fields."""
import json
import os
import socket
import struct
import sys
import threading
import time
from collections import deque

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)
SCRIPTS = os.path.join(REPO_ROOT, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
from qldpc_fault_tolerance_tpu.parallel import shot_mesh
from qldpc_fault_tolerance_tpu.serve import (
    AutoScaler,
    ContinuousBatcher,
    DecodeClient,
    DecodeSession,
    FusedDecodeGroup,
    ScalePolicy,
    SLOEngine,
    SLOPolicy,
    bucket_family,
    start_server_thread,
)
from qldpc_fault_tolerance_tpu.serve import wire
from qldpc_fault_tolerance_tpu.serve.ops import OpsServer
from qldpc_fault_tolerance_tpu.utils import (
    faultinject,
    resilience,
    telemetry,
)

DEC_CLS = BP_Decoder_Class(4, "minimum_sum", 0.625)
CODE3 = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
CODE4 = hgp(rep_code(4), rep_code(4), name="hgp_rep4")
P = 0.05

TRIVIAL_POLICY = resilience.RetryPolicy(max_attempts=1)
FAST_POLICY = resilience.RetryPolicy(
    max_attempts=2, base_delay=0.01, backoff=1.0, jitter=0.0,
    reset_caches=False, degrade_after=1)


@pytest.fixture(autouse=True)
def _clean_world():
    telemetry.disable()
    telemetry.reset()
    faultinject.deactivate()
    prev_policy = resilience.current_policy()
    yield
    resilience.set_default_policy(prev_policy)
    faultinject.deactivate()
    telemetry.disable()
    telemetry.reset()


def _params(code, p=P):
    return {"h": code.hx, "p_data": p}


def _session(name, code, p=P, buckets=(8, 32, 128), mesh=None):
    return DecodeSession(name, decoder_class=DEC_CLS,
                         params=_params(code, p), buckets=buckets,
                         mesh=mesh)


def _synd(code, k, rng, p=P):
    err = (rng.random((k, code.N)) < p).astype(np.uint8)
    return (err @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)


def _offline(code, synd, p=P):
    return DEC_CLS.GetDecoder(_params(code, p)).decode_batch(synd)


def _counter(name):
    return telemetry.snapshot().get(name, {}).get("value", 0)


def _retraces():
    return telemetry.compile_stats().get("jax.retraces", 0)


# ---------------------------------------------------------------------------
# wire codec v2: layout contract + frame round-trips
# ---------------------------------------------------------------------------
def test_pack_plane_matches_gf2_packed_bodies():
    """The wire layout IS the device layout: pack_plane's words equal
    ops/gf2_packed.pack_shots' words bit for bit (ragged tails included),
    and unpack_plane inverts both."""
    from qldpc_fault_tolerance_tpu.ops import gf2_packed

    rng = np.random.default_rng(3)
    for b, cols in ((1, 3), (17, 42), (32, 6), (33, 13), (96, 25),
                    (100, 1)):
        dense = (rng.random((b, cols)) < 0.4).astype(np.uint8)
        full = gf2_packed.num_words(b) * gf2_packed.LANE
        padded = np.zeros((full, cols), np.uint8)
        padded[:b] = dense
        ref = np.asarray(gf2_packed.pack_shots(padded), np.uint32)
        data = wire.pack_plane(dense)
        assert len(data) == gf2_packed.num_words(b) * cols * 4
        assert np.array_equal(
            np.frombuffer(data, "<u4").reshape(ref.shape), ref)
        assert np.array_equal(wire.unpack_plane(data, b, cols), dense)


def test_request_and_response_frames_roundtrip_both_codecs():
    rng = np.random.default_rng(5)
    synd = _synd(CODE4, 9, rng)
    msg = {"op": "decode", "id": "r-1", "session": "s", "tenant": "t",
           "idem": "k-1", "syndromes": synd}
    # v1 is byte-compatible with plain JSON framing
    obj = json.loads(wire.encode_request_frame(msg, 1)[4:])
    assert obj["syndromes"] == synd.tolist() and obj["idem"] == "k-1"
    # v2 round-trips the dense plane + every header field
    out = wire.decode_payload(wire.encode_request_frame(msg, 2)[4:])
    assert out["_codec"] == 2 and out["op"] == "decode"
    assert out["id"] == "r-1" and out["idem"] == "k-1"
    assert np.array_equal(out["syndromes"], synd)

    cor = (rng.random((9, CODE4.N)) < 0.5).astype(np.uint8)
    conv = [bool(x) for x in rng.random(9) < 0.7]
    payload = {"id": "r-1", "ok": True, "corrections": cor,
               "converged": conv, "latency_ms": 1.5, "trace_id": "ab"}
    out = wire.decode_payload(wire.encode_response_frame(payload, 2)[4:])
    assert np.array_equal(out["corrections"], cor)
    assert out["converged"] == conv and out["trace_id"] == "ab"
    # converged=None round-trips as None
    payload["converged"] = None
    out = wire.decode_payload(wire.encode_response_frame(payload, 2)[4:])
    assert out["converged"] is None


def test_malformed_binary_payloads_raise_wire_codec_error():
    """Every malformed-binary shape is a WireCodecError (recoverable
    per-request), never a crash or a silent wrong plane."""
    good = wire.encode_request_frame(
        {"op": "decode", "id": "x", "session": "s",
         "syndromes": np.zeros((3, 5), np.uint8)}, 2)[4:]
    cases = [
        good[:4],                                    # shorter than header
        b"QW" + bytes([9, 1]) + good[4:],            # unknown version
        b"QW" + bytes([2, 7]) + good[4:],            # unknown kind
        good[:4] + struct.pack(">I", 1 << 20) + good[8:],  # header overrun
        good[:8] + b"not json" + good[8 + 8:],       # unparseable header
    ]
    for payload in cases:
        with pytest.raises(wire.WireCodecError):
            wire.decode_payload(payload)
    # body length mismatch carries the request id for the error reply
    torn = good[:-4]
    with pytest.raises(wire.WireCodecError) as exc:
        wire.decode_payload(torn)
    assert exc.value.request_id == "x"
    # a hostile header cannot claim an OOM-sized dense plane
    with pytest.raises(wire.WireCodecError):
        wire.unpack_plane(b"", 10 ** 9, 10 ** 4)
    # JSON payloads keep their pre-v2 error types
    with pytest.raises(json.JSONDecodeError):
        wire.decode_payload(b"{torn")


# ---------------------------------------------------------------------------
# mixed v1/v2 clients on one live server, bit-exact + structured errors
# ---------------------------------------------------------------------------
def test_mixed_codec_clients_bitexact_and_negotiation():
    """A JSON v1 client and a negotiated packed v2 client on ONE server
    decode the same syndromes to identical corrections (and both equal
    offline); codec negotiation reports what each client sends; the
    bytes counters see both directions."""
    telemetry.enable()
    sessions = {"hgp_rep3": _session("hgp_rep3", CODE3),
                "hgp_rep4": _session("hgp_rep4", CODE4)}
    bat = ContinuousBatcher(sessions, max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        cli1 = DecodeClient(host, port, codec=1)
        cli2 = DecodeClient(host, port)  # auto -> packed
        assert cli1.wire_codec == 1 and cli2.wire_codec == 2
        rng = np.random.default_rng(11)
        for code, name in ((CODE3, "hgp_rep3"), (CODE4, "hgp_rep4")):
            synd = _synd(code, 13, rng)
            r1 = cli1.decode(name, synd)
            r2 = cli2.decode(name, synd)
            off = _offline(code, synd)
            assert np.array_equal(r1.corrections, off)
            assert np.array_equal(r2.corrections, off)
            assert r1.converged == r2.converged
        # explicit codec=2 against a v2 server works; traced v2 requests
        # echo the trace id through the binary header
        cli3 = DecodeClient(host, port, codec=2, traced=True)
        synd = _synd(CODE3, 4, rng)
        res = cli3.decode("hgp_rep3", synd)
        assert res.trace_id is not None
        assert np.array_equal(res.corrections, _offline(CODE3, synd))
        assert _counter("serve.bytes_rx") > 0
        assert _counter("serve.bytes_tx") > 0
        assert _counter("serve.client.bytes_tx") > 0
        assert telemetry.snapshot().get(
            "wire.codec_version", {}).get("value") == 2
        cli1.close(), cli2.close(), cli3.close()
    finally:
        handle.stop(drain=True)


def test_idempotent_replay_over_binary_wire():
    """The exactly-once journal semantics survive the codec: two binary
    submits with one idempotency key decode once (dedupe counters), and
    the replayed answer is bit-identical."""
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session("hgp_rep3", CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        cli = DecodeClient(host, port, codec=2, idempotent=True)
        rng = np.random.default_rng(2)
        synd = _synd(CODE3, 6, rng)
        first = cli.decode("hgp_rep3", synd)
        # resubmit the same logical request by hand: same idem, new id
        frame_msg = {"op": "decode", "id": "dup-1",
                     "session": "hgp_rep3", "tenant": "default",
                     "syndromes": synd,
                     wire.IDEM_FIELD: "fixed-key"}
        raw = socket.create_connection((host, port), timeout=10)
        try:
            raw.sendall(wire.encode_request_frame(frame_msg, 2))
            raw.sendall(wire.encode_request_frame(
                {**frame_msg, "id": "dup-2"}, 2))
            got = {}
            buf = b""
            while len(got) < 2:
                chunk = raw.recv(1 << 16)
                assert chunk, "server closed mid-replay"
                buf += chunk
                while len(buf) >= 4:
                    (length,) = struct.unpack(">I", buf[:4])
                    if len(buf) < 4 + length:
                        break
                    msg = wire.decode_payload(buf[4:4 + length])
                    buf = buf[4 + length:]
                    got[msg["id"]] = msg
        finally:
            raw.close()
        assert np.array_equal(got["dup-1"]["corrections"],
                              got["dup-2"]["corrections"])
        assert np.array_equal(got["dup-1"]["corrections"],
                              first.corrections)
        assert (_counter("serve.dedup.attached")
                + _counter("serve.dedup.replayed")) >= 1
        cli.close()
    finally:
        handle.stop(drain=True)


def test_server_answers_malformed_binary_and_keeps_serving():
    """A malformed v2 payload (framing intact) gets a structured error
    reply naming the request and the CONNECTION KEEPS SERVING — unlike a
    v1 framing error, the binary header's outer length still delimits
    the stream.  An oversized dense claim is refused the same way."""
    bat = ContinuousBatcher({"hgp_rep3": _session("hgp_rep3", CODE3)},
                            max_batch_shots=32, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        raw = socket.create_connection((host, port), timeout=10)

        def send_payload(payload):
            raw.sendall(struct.pack(">I", len(payload)) + payload)

        def read_msg():
            buf = b""
            while len(buf) < 4:
                buf += raw.recv(4 - len(buf))
            (length,) = struct.unpack(">I", buf)
            body = b""
            while len(body) < length:
                chunk = raw.recv(length - len(body))
                assert chunk
                body += chunk
            return wire.decode_payload(body)

        # bad version byte
        send_payload(b"QW" + bytes([9, 1]) + b"\x00\x00\x00\x00")
        msg = read_msg()
        assert msg["ok"] is False and "bad frame" in msg["error"]
        # body length mismatch: error names the request id
        good = wire.encode_request_frame(
            {"op": "decode", "id": "short-body", "session": "hgp_rep3",
             "syndromes": np.zeros((3, CODE3.hx.shape[0]), np.uint8)},
            2)[4:]
        send_payload(good[:-4])
        msg = read_msg()
        assert msg["ok"] is False and msg["id"] == "short-body"
        # oversized packed payload claim -> structured error
        huge = wire._binary_frame(
            {"op": "decode", "id": "huge", "session": "hgp_rep3",
             "shots": 10 ** 9, "width": 10 ** 4}, b"", wire.BIN_KIND_REQUEST)
        send_payload(huge[4:])
        msg = read_msg()
        assert msg["ok"] is False and msg["id"] == "huge"
        # ... and the connection still decodes fine afterwards
        rng = np.random.default_rng(0)
        synd = _synd(CODE3, 3, rng)
        raw.sendall(wire.encode_request_frame(
            {"op": "decode", "id": "ok-1", "session": "hgp_rep3",
             "syndromes": synd}, 2))
        msg = read_msg()
        assert msg["ok"] is True and msg["id"] == "ok-1"
        assert np.array_equal(msg["corrections"], _offline(CODE3, synd))
        raw.close()
    finally:
        handle.stop(drain=True)


def test_torn_binary_frame_mid_body_is_clean_disconnect():
    """A client dying mid-binary-frame (header promised more bytes) takes
    the clean-disconnect path; the server stays healthy for the next
    connection."""
    bat = ContinuousBatcher({"hgp_rep3": _session("hgp_rep3", CODE3)},
                            max_batch_shots=32, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        frame = wire.encode_request_frame(
            {"op": "decode", "id": "t", "session": "hgp_rep3",
             "syndromes": np.zeros((8, CODE3.hx.shape[0]), np.uint8)}, 2)
        raw = socket.create_connection((host, port), timeout=10)
        raw.sendall(frame[:len(frame) // 2])  # torn mid-frame
        raw.close()
        time.sleep(0.05)
        cli = DecodeClient(host, port)
        rng = np.random.default_rng(1)
        synd = _synd(CODE3, 2, rng)
        out = cli.decode("hgp_rep3", synd)
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
        cli.close()
    finally:
        handle.stop(drain=True)


def test_conn_drop_chaos_recovers_over_binary_codec():
    """The PR 14 chaos sites cover the binary codec — including its
    NEGOTIATION: the injected conn_drop at serve_conn_rx eats the hello
    frame (the first frame on the wire), so the client degrades to JSON
    on a transport the server already aborted, reconnects, renegotiates
    the packed codec on the fresh dial and decodes — answered exactly
    once, bit-exact."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session("hgp_rep3", CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(7)
        synd = _synd(CODE3, 4, rng)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_conn_rx", kind="conn_drop")])
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=30.0) as cli:
                out = cli.submit("hgp_rep3", synd).result(timeout=60)
                # the redial renegotiated the packed codec
                assert cli.wire_codec == 2
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
        assert _counter("serve.client.reconnects") >= 1
        assert bat.completed == 1  # exactly once
    finally:
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# FusedDecodeGroup: bit-exactness, program reuse, restack semantics
# ---------------------------------------------------------------------------
def test_bucket_family_groups_same_shape_sessions_only():
    a = _session("a", CODE3, p=0.02)
    b = _session("b", CODE3, p=0.08)
    c = _session("c", CODE4)
    assert bucket_family(a) == bucket_family(b)
    assert bucket_family(a) != bucket_family(c)
    with pytest.raises(ValueError):
        FusedDecodeGroup([a, c])
    with pytest.raises(ValueError):
        FusedDecodeGroup([a])


def test_fused_group_bitexact_vs_per_session_and_offline():
    """The cell-fused program's lanes equal the per-session programs AND
    offline decode_batch bit for bit — for full rounds, subsets (traced
    lane_cell) and ragged per-lane sizes."""
    sessions = [_session("a", CODE3, p=0.02),
                _session("b", CODE3, p=0.05),
                _session("c", CODE3, p=0.09)]
    grp = FusedDecodeGroup(sessions)
    rng = np.random.default_rng(0)
    s0, s1, s2 = (_synd(CODE3, k, rng) for k in (3, 17, 8))
    outs = grp.decode([(0, s0), (1, s1), (2, s2)])
    for sess, synd, out in zip(sessions, (s0, s1, s2), outs):
        per = sess.decode(synd)
        assert np.array_equal(out.corrections, per.corrections)
        assert np.array_equal(out.converged, per.converged)
        off = DEC_CLS.GetDecoder(
            {"h": CODE3.hx,
             "p_data": {"a": 0.02, "b": 0.05, "c": 0.09}[sess.name]}
        ).decode_batch(synd)
        assert np.array_equal(out.corrections, off)
    # member SUBSETS reuse the (n_lanes, bucket) programs via the traced
    # lane_cell — once the shape set is warm, ANY same-shape subset
    # compiles nothing
    grp.warm(32)
    compiles = grp.compiles
    sub = grp.decode([(2, s2), (0, s0)])
    assert np.array_equal(sub[0].corrections,
                          sessions[2].decode(s2).corrections)
    sub2 = grp.decode([(1, s1), (2, s2)])
    assert np.array_equal(sub2[0].corrections,
                          sessions[1].decode(s1).corrections)
    assert grp.compiles == compiles  # same-shape subsets: zero compiles


def test_fused_group_warm_path_zero_retraces():
    telemetry.enable()
    sessions = [_session("a", CODE3, p=0.03),
                _session("b", CODE3, p=0.07)]
    grp = FusedDecodeGroup(sessions)
    grp.warm(32, lanes=(1, 2))
    rng = np.random.default_rng(1)
    before = _retraces()
    for ks in ((1, 2), (5, 9), (32, 32), (2, 31)):
        grp.decode([(0, _synd(CODE3, ks[0], rng)),
                    (1, _synd(CODE3, ks[1], rng))])
        grp.decode([(1, _synd(CODE3, ks[0], rng))])
    assert _retraces() - before == 0


def test_fused_group_restacks_on_heal_without_recompiling():
    sessions = [_session("a", CODE3, p=0.02),
                _session("b", CODE3, p=0.06)]
    grp = FusedDecodeGroup(sessions)
    rng = np.random.default_rng(4)
    synd = _synd(CODE3, 7, rng)
    base = grp.decode([(0, synd), (1, synd)])
    compiles = grp.compiles
    assert grp.ensure_fresh() is False  # steady state: no restack
    sessions[1].heal(reason="test")
    assert grp.ensure_fresh() is True
    after = grp.decode([(0, synd), (1, synd)])
    assert np.array_equal(base[1].corrections, after[1].corrections)
    assert grp.compiles == compiles  # state is an argument: no recompile


# ---------------------------------------------------------------------------
# Scheduler: cross-session fused rounds + fallback accounting + health
# ---------------------------------------------------------------------------
def _storm_batcher(fused=True, mesh=None):
    sessions = {
        "fam_a": _session("fam_a", CODE3, p=0.03, mesh=mesh),
        "fam_b": _session("fam_b", CODE3, p=0.07),
        "other": _session("other", CODE4),
    }
    bat = ContinuousBatcher(sessions, max_batch_shots=64,
                            max_wait_s=0.004, fused=fused)
    return sessions, bat


def test_scheduler_fuses_co_family_rounds_bitexact():
    """Concurrent submits to two co-family sessions + a third code ride
    fused dispatches (counted, eligible in health()), per-session
    corrections bit-exact vs offline; the serve_batch events carry the
    v5 fused fields and validate."""
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    _sessions, bat = _storm_batcher()
    bat.warm()
    try:
        rng = np.random.default_rng(5)
        rows = {"fam_a": [], "fam_b": [], "other": []}
        futs = []
        for i in range(45):
            name = ("fam_a", "fam_b", "other")[i % 3]
            code = CODE4 if name == "other" else CODE3
            synd = _synd(code, int(rng.integers(1, 9)), rng)
            futs.append((name, synd, bat.submit(name, synd,
                                                tenant=f"t{i % 2}")))
        for name, synd, fut in futs:
            rows[name].append((synd, fut.result(timeout=60).corrections))
        for name, p in (("fam_a", 0.03), ("fam_b", 0.07), ("other", P)):
            code = CODE4 if name == "other" else CODE3
            synd = np.concatenate([s for s, _ in rows[name]])
            served = np.concatenate([c for _, c in rows[name]])
            off = DEC_CLS.GetDecoder(
                {"h": code.hx, "p_data": p}).decode_batch(synd)
            assert np.array_equal(served, off), name
        assert bat.fused_dispatches >= 1
        health = bat.health()
        assert health["fused"]["enabled"] is True
        assert health["fused"]["dispatches"] == bat.fused_dispatches
        fams = health["fused"]["families"]
        assert any(st["eligible"] and set(st["sessions"]) ==
                   {"fam_a", "fam_b"} for st in fams.values())
        fused_events = [r for r in sink.records
                        if r.get("kind") == "serve_batch" and r.get("fused")]
        assert fused_events and all(
            telemetry.validate_event(e) == [] for e in fused_events)
        assert all(e["lanes"] >= 2 and "family" in e for e in fused_events)
    finally:
        telemetry.remove_sink(sink)
        bat.drain(timeout=30)


def test_scheduler_oversize_round_falls_back_counted():
    """A co-family round past the top bucket dispatches per-session —
    and the fallback is COUNTED (health + counter), never silent."""
    telemetry.enable()
    sessions = {"fa": _session("fa", CODE3, p=0.03, buckets=(8, 16)),
                "fb": _session("fb", CODE3, p=0.07, buckets=(8, 16))}
    bat = ContinuousBatcher(sessions, max_batch_shots=64, max_wait_s=0.02)
    try:
        rng = np.random.default_rng(9)
        rows = []
        # oversize (> top bucket 16) rounds for both sessions, queued
        # within one deadline window so they co-pick
        for name in ("fa", "fb"):
            synd = _synd(CODE3, 24, rng)
            rows.append((name, synd, bat.submit(name, synd)))
        for name, synd, fut in rows:
            out = fut.result(timeout=60)
            p = 0.03 if name == "fa" else 0.07
            off = DEC_CLS.GetDecoder(
                {"h": CODE3.hx, "p_data": p}).decode_batch(synd)
            assert np.array_equal(out.corrections, off)
        # the oversize fallback may or may not co-pick depending on
        # timing; force one deterministic co-pick through drain
        futs = [bat.submit(n, _synd(CODE3, 24, rng)) for n in ("fa", "fb")]
        bat.drain(timeout=30)
        for f in futs:
            f.result(timeout=5)
        assert bat.fused_fallbacks >= 1
        assert _counter("serve.fused.fallback.oversize") >= 1
        health = bat.health()
        assert health["fused"]["fallbacks"] == bat.fused_fallbacks
        assert any(st["last_fallback"] == "oversize"
                   for st in health["fused"]["families"].values())
    finally:
        bat.close()


def test_fused_dispatch_failure_requeues_and_heals_all_members():
    """A transiently-failed FUSED dispatch re-queues every lane's
    requests (exactly-once re-dispatch) and records one incident PER
    member session, so the health probe heals each of them."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    _sessions, bat = _storm_batcher()
    bat.warm()
    try:
        plan = faultinject.FaultPlan([faultinject.Fault(
            site="serve_fused_dispatch", kind="raise")])
        rng = np.random.default_rng(3)
        sa, sb = _synd(CODE3, 4, rng), _synd(CODE3, 5, rng)
        with plan.active():
            fa = bat.submit("fam_a", sa)
            fb = bat.submit("fam_b", sb)
            ra, rb = fa.result(timeout=60), fb.result(timeout=60)
        assert np.array_equal(
            ra.corrections,
            DEC_CLS.GetDecoder(
                {"h": CODE3.hx, "p_data": 0.03}).decode_batch(sa))
        assert np.array_equal(
            rb.corrections,
            DEC_CLS.GetDecoder(
                {"h": CODE3.hx, "p_data": 0.07}).decode_batch(sb))
        incidents = bat.take_incidents()
        names = {i["session"] for i in incidents}
        assert {"fam_a", "fam_b"} <= names
        assert bat.redispatched >= 2 and bat.failed == 0
    finally:
        bat.drain(timeout=30)


# ---------------------------------------------------------------------------
# Hot-session mesh sharding
# ---------------------------------------------------------------------------
def test_mesh_sharded_session_bitexact_and_unshard_rung():
    """shard() serves bit-exact through the mesh program (shot axis
    sharded, state replicated); a transiently-failing dispatch steps the
    serve_mesh_unshard rung first — the session retires its mesh and the
    retry answers bit-exact on the single-device twin."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    mesh = shot_mesh()
    sess = _session("hot", CODE3, mesh=mesh, buckets=(8, 32))
    rng = np.random.default_rng(8)
    synd = _synd(CODE3, 21, rng)
    base = sess.decode(synd)
    assert sess.shard() and sess.sharded
    out = sess.decode(synd)
    assert np.array_equal(out.corrections, base.corrections)
    assert np.array_equal(out.converged, base.converged)
    # heal recompiles the sharded warm set too
    sess.heal(reason="test")
    assert np.array_equal(sess.decode(synd).corrections, base.corrections)
    # dispatch fault with the session sharded: the ladder unshards first
    bat = ContinuousBatcher({"hot": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    try:
        plan = faultinject.FaultPlan([faultinject.Fault(
            site="serve_dispatch", kind="raise")])
        with plan.active():
            res = bat.submit("hot", synd).result(timeout=60)
        assert np.array_equal(res.corrections, base.corrections)
        assert not sess.sharded  # the rung retired the mesh
        assert _counter("serve.session.unshards") >= 1
    finally:
        bat.drain(timeout=30)


# ---------------------------------------------------------------------------
# AutoScaler: deterministic control law + exposure
# ---------------------------------------------------------------------------
def test_autoscaler_reacts_to_synthetic_slo_burn():
    """A synthetic latency burn (injected now) grows the batch target and
    cuts the wait; when the burn clears and the queue empties the scaler
    walks both knobs back; every action is a validating scale_event."""
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    bat = ContinuousBatcher({"s": _session("s", CODE3)},
                            max_batch_shots=128, max_wait_s=0.002)
    slo = SLOEngine(SLOPolicy(latency_target_s=0.01, min_requests=5,
                              window_s=30.0))
    sc = AutoScaler(bat, slo=slo,
                    policy=ScalePolicy(cooldown_s=1.0,
                                       grow_queue_depth=1000),
                    start=False)
    try:
        for i in range(20):
            slo.observe_request("t", 0.5, ok=True, now=100.0 + i * 0.01)
        slo.evaluate(now=101.0)
        acts = sc.evaluate_once(now=101.0)
        kinds = [a["action"] for a in acts]
        assert "grow_batch" in kinds and "cut_wait" in kinds
        assert bat.max_batch_shots == 256
        assert bat.max_wait_s == sc.policy.overload_wait_s
        # cooldown: an immediate second pass is a no-op
        assert sc.evaluate_once(now=101.5) == []
        # burn clears + empty queue: walk back toward the base targets
        slo.evaluate(now=200.0)  # window aged out
        acts = sc.evaluate_once(now=200.0)
        kinds = [a["action"] for a in acts]
        assert "shrink_batch" in kinds and "restore_wait" in kinds
        assert bat.max_batch_shots == sc.base_batch_shots
        assert bat.max_wait_s == sc.base_wait_s
        events = [r for r in sink.records if r.get("kind") == "scale_event"]
        assert len(events) >= 4
        assert all(telemetry.validate_event(e) == [] for e in events)
        assert sc.report()["actions"] == len(events)
    finally:
        telemetry.remove_sink(sink)
        bat.close()


def test_autoscaler_shards_hot_session_and_retires_it():
    """Per-session queue pressure past the threshold shards the session
    across the mesh; cooling below the retire threshold unshards —
    hysteresis between, scale_events name the session."""
    telemetry.enable()
    mesh = shot_mesh()
    sess = _session("hot", CODE3, mesh=mesh, buckets=(8, 32))
    bat = ContinuousBatcher({"hot": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    sc = AutoScaler(bat, policy=ScalePolicy(cooldown_s=0.0,
                                            shard_queued_shots=100,
                                            unshard_queued_shots=10),
                    start=False)
    try:
        depth_box = {"queued_shots": {"hot": 500}, "queued_requests": 50}
        bat.queue_stats = lambda: depth_box  # deterministic pressure
        acts = sc.evaluate_once(now=10.0)
        assert any(a["action"] == "shard" and a["session"] == "hot"
                   for a in acts)
        assert sess.sharded
        # hysteresis: between the thresholds nothing happens
        depth_box = {"queued_shots": {"hot": 50}, "queued_requests": 5}
        bat.queue_stats = lambda: depth_box
        assert not any(a["action"] in ("shard", "unshard")
                       for a in sc.evaluate_once(now=20.0))
        assert sess.sharded
        depth_box = {"queued_shots": {"hot": 0}, "queued_requests": 0}
        bat.queue_stats = lambda: depth_box
        acts = sc.evaluate_once(now=30.0)
        assert any(a["action"] == "unshard" for a in acts)
        assert not sess.sharded
        # decode still bit-exact after the full shard/unshard cycle
        rng = np.random.default_rng(1)
        synd = _synd(CODE3, 9, rng)
        assert np.array_equal(sess.decode(synd).corrections,
                              _offline(CODE3, synd))
    finally:
        bat.close()


def test_ops_plane_exposes_autoscaler():
    bat = ContinuousBatcher({"s": _session("s", CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    sc = AutoScaler(bat, start=False)
    try:
        ops = OpsServer(batcher=bat, scaler=sc)
        assert ops.varz()["autoscale"]["max_batch_shots"] == 64
        hz = ops.healthz()
        assert hz["autoscale"]["base_batch_shots"] == 64
        assert hz["fused"]["enabled"] is True  # batcher health block
    finally:
        bat.close()


# ---------------------------------------------------------------------------
# v5 schema back-compat chain
# ---------------------------------------------------------------------------
def test_v5_schema_backcompat_chain():
    """The frozen v1..v4 kind sets are untouched, v5 adds exactly
    scale_event, every frozen kind still has a registry entry, and the
    new additive serve fields validate."""
    frozen = [telemetry._V1_EVENT_KINDS, telemetry._V2_EVENT_KINDS,
              telemetry._V3_EVENT_KINDS, telemetry._V4_EVENT_KINDS,
              telemetry._V5_EVENT_KINDS]
    assert telemetry._V5_EVENT_KINDS == frozenset({"scale_event"})
    assert len(telemetry._V4_EVENT_KINDS) == 3
    seen = set()
    for s in frozen:
        assert not (s & seen)  # pairwise disjoint
        assert s <= set(telemetry.EVENT_SCHEMAS)
        seen |= s
    assert telemetry.EVENT_SCHEMA_VERSION >= 5
    samples = {
        "scale_event": {"action": "grow_batch", "target":
                        "max_batch_shots", "from_value": 128,
                        "to_value": 256, "queue_depth": 80,
                        "burn_rate": 3.2, "reason": "queue_depth"},
        "serve_batch": {"session": "s", "requests": 3, "shots": 12,
                        "bucket": 32, "fused": True, "lanes": 2,
                        "family": "bp.w6.abc123", "ok": True},
        "serve_session": {"session": "s", "event": "fused_compile",
                          "lanes": 3, "family": "bp.w6.abc123",
                          "bucket": 32, "sharded": False},
    }
    for kind, fields in samples.items():
        assert telemetry.validate_event(
            {"ts": 1.0, "kind": kind, **fields}) == [], kind


# ---------------------------------------------------------------------------
# telemetry_report serve block: bytes + fused counters
# ---------------------------------------------------------------------------
def test_telemetry_report_renders_wire_and_fused_counters():
    import importlib

    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    try:
        _sessions, bat = _storm_batcher()
        bat.warm()
        handle = start_server_thread(bat)
        host, port = handle.address
        cli = DecodeClient(host, port)
        rng = np.random.default_rng(2)
        futs = [cli.submit(n, _synd(CODE3 if n != "other" else CODE4,
                                    3, rng))
                for n in ("fam_a", "fam_b", "other") for _ in range(3)]
        for f in futs:
            f.result(timeout=60)
        cli.close()
        handle.stop(drain=True)
        telemetry.write_snapshot_event()
        events = list(sink.records)
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()

    report = importlib.import_module("scripts.telemetry_report")
    summary = report.summarize(events)
    srv = summary["serve"]
    assert srv["bytes_rx"] > 0 and srv["bytes_tx"] > 0
    assert srv["wire_codec_version"] == 2
    text = report.render(summary)
    assert "wire bytes rx/tx" in text


# ---------------------------------------------------------------------------
# bench_compare gates the scaling-half fields
# ---------------------------------------------------------------------------
def test_bench_compare_gates_wire_and_fused_fields(tmp_path):
    import importlib

    bench_compare = importlib.import_module("bench_compare")

    def write_round(n, qps, packed_bpr, fused_rps):
        obj = {"schema": 2, "round": n, "result": {
            "metric": "decode-service sustained QPS", "value": qps,
            "unit": "req/s",
            "wire_ab": {"packed_bytes_per_req": packed_bpr},
            "fused_ab": {"fused_req_per_s": fused_rps}}}
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps(obj))
        return str(path)

    a = write_round(6, 300.0, 600.0, 9000.0)
    # packed bytes/request UP = wire regression (lower-is-better field)
    b = write_round(7, 305.0, 900.0, 9100.0)
    assert bench_compare.main(["--gate", a, b]) == 1
    # fused req/s DOWN = fused-dispatch regression
    c = write_round(8, 305.0, 610.0, 5000.0)
    assert bench_compare.main(["--gate", a, c]) == 1
    # within band passes
    d = write_round(9, 310.0, 590.0, 9300.0)
    assert bench_compare.main(["--gate", a, d]) == 0


# ---------------------------------------------------------------------------
# acceptance: mixed-code 3-tenant storm, fused + packed, zero retraces
# ---------------------------------------------------------------------------
def test_acceptance_fused_packed_storm_bitexact_zero_retraces():
    """ISSUE 15 acceptance: a mixed-code 3-tenant storm through the full
    TCP stack with cross-session fused dispatch AND the packed binary
    wire — every served correction bit-exact vs offline decode_batch,
    fused dispatches happened, zero retraces after warmup."""
    telemetry.enable()
    _sessions, bat = _storm_batcher()
    bat.warm()
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        warm_rng = np.random.default_rng(0)

        def run_storm(n_per_tenant, rows):
            errors = []

            def worker(idx):
                try:
                    cli = DecodeClient(host, port, tenant=f"tenant{idx}")
                    assert cli.wire_codec == 2
                    rng = np.random.default_rng(100 + idx)
                    pending = deque()
                    for i in range(n_per_tenant):
                        name = ("fam_a", "fam_b", "other")[(i + idx) % 3]
                        code = CODE4 if name == "other" else CODE3
                        synd = _synd(code, int(rng.integers(1, 9)), rng)
                        pending.append(
                            (name, synd, cli.submit(name, synd)))
                        if len(pending) >= 8:
                            n_, s_, f_ = pending.popleft()
                            rows.append((n_, s_,
                                         f_.result(timeout=60)))
                    while pending:
                        n_, s_, f_ = pending.popleft()
                        rows.append((n_, s_, f_.result(timeout=60)))
                    cli.close()
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors[0]

        run_storm(8, rows=[])  # warm the wire/dispatch path
        _synd(CODE3, 1, warm_rng)
        before = _retraces()
        rows: list = []
        run_storm(15, rows)
        assert _retraces() - before == 0
        assert bat.fused_dispatches >= 1
        for name, p, code in (("fam_a", 0.03, CODE3),
                              ("fam_b", 0.07, CODE3),
                              ("other", P, CODE4)):
            pairs = [(s, r.corrections) for n, s, r in rows if n == name]
            assert pairs, name
            synd = np.concatenate([s for s, _ in pairs])
            served = np.concatenate([c for _, c in pairs])
            off = DEC_CLS.GetDecoder(
                {"h": code.hx, "p_data": p}).decode_batch(synd)
            assert np.array_equal(served, off), name
    finally:
        handle.stop(drain=True)
