"""Time-series retention core tests (ISSUE 17 tentpole, part 1): windowed
counter rates with reset handling, gauge last-value series carrying the
registry's last-set staleness stamp, histogram quantiles from cumulative
bucket deltas, bucket-boundary inference, deadman ages, and the background
Scraper (zero-cost when telemetry is disabled, tick hooks isolated from
hook failures, optional snapshot-event emission for offline --rates
reconstruction)."""
import threading

import pytest

from qldpc_fault_tolerance_tpu.utils import telemetry, timeseries
from qldpc_fault_tolerance_tpu.utils.timeseries import (
    Scraper,
    SeriesStore,
    hist_quantile,
)


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _counter(v):
    return {"type": "counter", "value": v}


def _gauge(v, ts=None):
    return {"type": "gauge", "value": v, "max": v, "ts": ts}


def _hist(buckets, counts, total, count):
    return {"type": "histogram", "buckets": list(buckets),
            "counts": list(counts), "sum": total, "count": count}


# ---------------------------------------------------------------------------
# hist_quantile: the shared interpolation primitive
# ---------------------------------------------------------------------------
def test_hist_quantile_interpolation_and_edges():
    buckets = (1.0, 2.0, 4.0)
    # 10 observations in (1, 2]: the median interpolates to the bucket
    # midpoint
    assert hist_quantile(buckets, [0, 10, 0, 0], 0.5) == pytest.approx(1.5)
    # all mass in the first bucket: q interpolates from 0
    assert hist_quantile(buckets, [4, 0, 0, 0], 0.25) == pytest.approx(0.25)
    # empty window -> None, never 0.0 (no data is not "fast")
    assert hist_quantile(buckets, [0, 0, 0, 0], 0.99) is None
    # quantile landing in overflow clamps to the last finite edge
    assert hist_quantile(buckets, [0, 0, 0, 5], 0.99) == 4.0


# ---------------------------------------------------------------------------
# SeriesStore: ingestion + windowed derivations
# ---------------------------------------------------------------------------
def test_counter_rate_windowed():
    st = SeriesStore()
    for i in range(10):  # +100 per second for 10 s
        st.ingest(float(i), {"c": _counter(100 * i)})
    assert st.rate("c", window_s=None, now=9.0) == pytest.approx(100.0)
    # trailing window sees only its own samples
    assert st.rate("c", window_s=3.0, now=9.0) == pytest.approx(100.0)
    # fewer than two samples in the window -> None (can't form a delta)
    assert st.rate("c", window_s=0.5, now=9.0) is None
    assert st.rate("missing", window_s=60.0, now=9.0) is None


def test_counter_reset_is_not_negative_traffic():
    st = SeriesStore()
    # 0 -> 500, process restart (value drops to 0), 0 -> 300
    for ts, v in [(0, 0), (1, 500), (2, 0), (3, 300)]:
        st.ingest(float(ts), {"c": _counter(v)})
    # positive-delta sum = 500 + 300 over 3 s; the reset contributes zero
    assert st.rate("c", window_s=None, now=3.0) == pytest.approx(800 / 3)


def test_gauge_last_value_and_staleness_stamp():
    st = SeriesStore()
    st.ingest(10.0, {"g": _gauge(7.0, ts=9.5)})
    st.ingest(20.0, {"g": _gauge(7.0, ts=9.5)})  # re-scraped, not re-set
    assert st.last_value("g") == 7.0
    # the registry's last-SET stamp survives retention: the gauge froze at
    # 9.5 even though the newest scrape is at 20.0
    assert st.gauge_set_ts("g") == 9.5
    assert st.kind("g") == "gauge"


def test_histogram_windowed_quantile_from_bucket_deltas():
    st = SeriesStore()
    buckets = (0.01, 0.1, 1.0)
    telemetry.set_default_buckets("h", buckets)  # pin the boundary spec
    try:
        # old traffic: 100 fast observations, then a slow regime moves in
        st.ingest(0.0, {"h": _hist(buckets, [100, 0, 0, 0], 0.5, 100)})
        st.ingest(10.0, {"h": _hist(buckets, [100, 0, 20, 0], 10.5, 120)})
        # window_s=None diffs the retained span's edge samples: the 100
        # fast observations predate the first sample, so only the 20 slow
        # ones count and p50 sits inside (0.1, 1.0] — NOT the <0.01 a
        # whole-lifetime cumulative read would give
        assert 0.1 < st.quantile("h", 0.5, window_s=None, now=10.0) <= 1.0
        # an explicit trailing window derives the same bucket delta
        got = st.window_hist("h", 8.0, now=10.0)
        assert got is not None
        wb, wc, wsum, wcount = got
        assert wb == buckets and wc == [0, 0, 20, 0]
        assert wcount == 20 and wsum == pytest.approx(10.0)
        q50 = st.quantile("h", 0.5, window_s=8.0, now=10.0)
        assert 0.1 < q50 <= 1.0
    finally:
        telemetry.set_default_buckets("h", None)


def test_histogram_single_sample_window_uses_prior_base():
    st = SeriesStore()
    buckets = (1.0, 2.0)
    st.ingest(0.0, {"h": _hist(buckets, [5, 0, 0], 2.5, 5)})
    st.ingest(10.0, {"h": _hist(buckets, [5, 3, 0], 7.0, 8)})
    # only the ts=10 sample is inside the window, but the delta is taken
    # against the newest sample BEFORE it -> the window still sees traffic
    _, wc, _, wcount = st.window_hist("h", 2.0, now=10.0)
    assert wc == [0, 3, 0] and wcount == 3
    # a mid-window histogram reset (count decreased) falls back to the
    # lifetime cumulative counts instead of reporting negatives
    st.ingest(11.0, {"h": _hist(buckets, [1, 0, 0], 0.1, 1)})
    _, wc, _, wcount = st.window_hist("h", 5.0, now=11.0)
    assert wc == [1, 0, 0] and wcount == 1


def test_bucket_boundary_inference():
    st = SeriesStore()
    # a registered default spec with matching arity wins
    telemetry.set_default_buckets("custom.h", (5.0, 10.0))
    try:
        st.ingest(0.0, {"custom.h": _hist((5.0, 10.0), [0, 0, 0], 0.0, 0)})
        st.ingest(1.0, {"custom.h": _hist((5.0, 10.0), [0, 4, 0], 30.0, 4)})
        assert st.quantile("custom.h", 0.5, None, now=1.0) == pytest.approx(
            7.5)
    finally:
        telemetry.set_default_buckets("custom.h", None)
    # unregistered: the shipped ladders are inferred by count arity
    n = len(telemetry.LATENCY_BUCKETS)
    st.ingest(0.0, {"lat.h": _hist(telemetry.LATENCY_BUCKETS,
                                   [0] * (n + 1), 0.0, 0)})
    got = st.window_hist("lat.h", None)
    assert got[0] == tuple(telemetry.LATENCY_BUCKETS)


def test_age_tracks_last_change_not_last_scrape():
    st = SeriesStore()
    assert st.age("c") is None  # never seen: no heartbeat, not a healthy one
    st.ingest(0.0, {"c": _counter(5)})
    st.ingest(10.0, {"c": _counter(5)})  # scraped but unchanged
    assert st.age("c", now=12.0) == pytest.approx(12.0)
    st.ingest(20.0, {"c": _counter(6)})  # the counter moved: heartbeat
    assert st.age("c", now=21.0) == pytest.approx(1.0)


def test_retention_is_bounded():
    st = SeriesStore(retention=4)
    for i in range(10):
        st.ingest(float(i), {"c": _counter(i)})
    pts = st.samples("c")
    assert len(pts) == 4 and pts[0][0] == 6.0 and pts[-1][0] == 9.0
    # the windowed rate still works off the retained ring
    assert st.rate("c", window_s=None, now=9.0) == pytest.approx(1.0)


def test_type_reregistration_replaces_series():
    st = SeriesStore()
    st.ingest(0.0, {"x": _counter(3)})
    st.ingest(1.0, {"x": _gauge(9.0, ts=1.0)})
    assert st.kind("x") == "gauge" and st.last_value("x") == 9.0
    assert len(st.samples("x")) == 1  # the counter history is gone


# ---------------------------------------------------------------------------
# Scraper: the background sampler
# ---------------------------------------------------------------------------
def test_scraper_zero_cost_when_disabled():
    sc = Scraper(interval_s=0.01)
    assert sc.scrape_once(now=1.0) is False
    assert sc.store.names() == []  # nothing sampled, nothing retained


def test_scraper_tick_ingests_and_counts():
    telemetry.enable()
    sc = Scraper(interval_s=0.01, now=lambda: 0.0)
    telemetry.count("bp.shots", 100)
    assert sc.scrape_once(now=1.0) is True
    telemetry.count("bp.shots", 100)
    assert sc.scrape_once(now=2.0) is True
    assert sc.store.rate("bp.shots", window_s=None, now=2.0) == \
        pytest.approx(100.0)
    # the scraper heartbeats its own tick counter (the deadman rides it)
    assert telemetry.snapshot()["timeseries.scrapes"]["value"] == 2


def test_scraper_hook_errors_counted_not_raised():
    telemetry.enable()
    sc = Scraper(interval_s=0.01)
    seen = []

    def good(store, now):
        seen.append(now)

    def bad(store, now):
        raise RuntimeError("broken rule")

    sc.add_tick_hook(bad)
    sc.add_tick_hook(good)
    assert sc.scrape_once(now=5.0) is True  # the bad hook did not kill it
    assert seen == [5.0]
    assert telemetry.snapshot()["timeseries.hook_errors"]["value"] == 1


def test_scraper_snapshot_events_rebuild_the_store_offline():
    """emit_snapshot_events bridges live retention to the JSONL stream:
    a store rebuilt from the emitted snapshot events derives the SAME
    rate as the live one (telemetry_report --rates runs this path)."""
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sc = Scraper(interval_s=0.01, emit_snapshot_events=True)
        for i in range(1, 4):
            telemetry.count("bp.shots", 50)
            sc.scrape_once(now=float(i))
        snaps = [r for r in sink.records if r["kind"] == "snapshot"]
        assert len(snaps) == 3
        rebuilt = SeriesStore()
        for i, rec in enumerate(snaps, start=1):
            rebuilt.ingest(float(i), rec["metrics"])
        assert rebuilt.rate("bp.shots", window_s=None, now=3.0) == \
            sc.store.rate("bp.shots", window_s=None, now=3.0)
    finally:
        telemetry.remove_sink(sink)


def test_scraper_thread_start_stop():
    telemetry.enable()
    sc = Scraper(interval_s=0.005)
    sc.start()
    try:
        assert sc.start() is sc  # idempotent while running
        deadline = threading.Event()
        for _ in range(200):  # up to ~2 s for a few ticks
            if telemetry.snapshot().get(
                    "timeseries.scrapes", {}).get("value", 0) >= 2:
                break
            deadline.wait(0.01)
    finally:
        sc.stop()
    assert telemetry.snapshot()["timeseries.scrapes"]["value"] >= 2
    assert sc._thread is None  # restartable after stop
