"""Physics-parity regression pins.

PARITY_r2.md establishes agreement with the reference's published
thresholds by multi-seed Monte-Carlo on TPU; re-running that is far too
slow for CI.  Instead this pins one *deterministic* notebook-convention
cell (fixed PRNG keys -> bit-reproducible counts on the CPU test backend):
any future change to the samplers, BP kernel, OSD, or engine round
structure that alters physics shifts this value and fails loudly.

The pinned value was computed with the exact code that produced the
round-2 parity results (toric d5, Threshold-cell-25 conventions: q=0,
BP(N/30) ext dec1, BPOSD(N/10, osd_e-10) dec2, msf 0.625).
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))


def test_toric_phenl_cell_pinned():
    import parity

    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code

    code = hgp(ring_code(5), ring_code(5), name="toric_d5")
    wer = parity.phenl_cell_wer(code, 0.016, 15, 2048, seed=42,
                                batch_size=1024)
    # deterministic on THE SUITE BACKEND (8-virtual-device CPU, conftest):
    # fixed fold_in streams, f32 BP, deterministic OSD tie-breaking.  The
    # value is backend-specific (XLA codegen changes with the virtual
    # device flag); the statistical-band test below is the env-robust one.
    # Re-pinned at ISSUE 13 (was 0.005333239320124417): BPOSD now runs its
    # OSD stage device-resident by default on every backend, and float32
    # device costs resolve a handful of ML ties differently from the host
    # float64 path — a tie-breaking change inside the documented parity
    # contract, not a physics change (the band test pins that).
    np.testing.assert_allclose(wer, 0.005231307090348414, rtol=1e-12)


def test_toric_phenl_cell_statistical_band():
    """Same cell, independent seed: the WER must stay inside a generous
    binomial band around the pinned estimate — a backend-robust check that
    survives platform-dependent tie-breaking."""
    import parity

    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code

    code = hgp(ring_code(5), ring_code(5), name="toric_d5")
    wer = parity.phenl_cell_wer(code, 0.016, 15, 2048, seed=1042,
                                batch_size=1024)
    assert 0.003 < wer < 0.008, wer
