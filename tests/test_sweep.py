"""Tests for the sweep layer: fits on synthetic data, family orchestration."""
import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BPOSD_Decoder_Class,
    BP_Decoder_Class,
    ST_BP_Decoder_Circuit_Class,
    ST_BPOSD_Decoder_Circuit_Class,
)
from qldpc_fault_tolerance_tpu.sweep import (
    CodeFamily,
    CodeFamily_SpaceTime,
    DistanceEst,
    FitSusThreshold,
    SustainableThresholdEst,
    ThresholdEst_extrapolation,
)


# ------------------------------------------------------------------- fits
def test_distance_est_recovers_exponent():
    p = np.array([0.002, 0.004, 0.008, 0.016])
    pl = [0.5 * p ** (3 / 2), 0.2 * p ** (5 / 2)]  # d=3 and d=5 codes
    d = DistanceEst(p, pl)
    assert d[0] == pytest.approx(3, rel=1e-3)
    assert d[1] == pytest.approx(5, rel=1e-3)


def test_threshold_extrapolation_recovers_pc():
    pc, A = 0.05, 0.3
    p = 10 ** np.linspace(np.log10(pc * 0.4), np.log10(pc * 0.8), 6)
    pl = np.array([A * (p / pc) ** (d / 2) for d in (3, 5, 7)])
    est = ThresholdEst_extrapolation(p, pl, verbose=False)
    assert est == pytest.approx(pc, rel=0.05)


def test_sustainable_threshold_fit():
    p_sus, p0, gamma = 0.02, 0.06, 0.3
    cycles = np.array([5, 10, 15, 20, 25, 30])
    th = FitSusThreshold(cycles, p_sus, p0, gamma)
    est = SustainableThresholdEst(cycles, th)
    assert est == pytest.approx(p_sus, rel=1e-3)


# ----------------------------------------------------------- CodeFamily
@pytest.fixture(scope="module")
def family_codes():
    return [hgp(rep_code(3), rep_code(3)), hgp(rep_code(5), rep_code(5))]


def test_code_family_data_sweep(family_codes):
    fam = CodeFamily(
        family_codes,
        decoder1_class=BP_Decoder_Class(10, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(10, "minimum_sum", 0.625, "osd_e", 4),
        batch_size=128, seed=1,
    )
    p_list = [0.02, 0.08]
    wer = fam.EvalWER("data", "Total", p_list, num_samples=256, if_plot=False)
    assert wer.shape == (2, 2)
    assert (wer >= 0).all() and (wer <= 1).all()
    # higher p must not give a lower WER for the small code
    assert wer[0, 1] >= wer[0, 0]
    # at low p the larger code beats the smaller one
    assert wer[1, 0] <= wer[0, 0] + 0.02


def test_code_family_phenl_smoke(family_codes):
    fam = CodeFamily(
        [family_codes[0]],
        decoder1_class=BP_Decoder_Class(1, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(3, "minimum_sum", 0.625, "osd_e", 4),
        batch_size=64, seed=2,
    )
    wer = fam.EvalWER("phenl", "Total", [0.01], num_samples=128,
                      num_cycles=3, if_plot=False)
    assert wer.shape == (1, 1)
    assert 0 <= wer[0, 0] <= 1


def test_code_family_circuit_smoke(family_codes):
    fam = CodeFamily(
        [family_codes[0]],
        decoder1_class=BP_Decoder_Class(1, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(3, "minimum_sum", 0.625, "osd_e", 4),
        batch_size=64, seed=3,
    )
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0}
    wer = fam.EvalWER("circuit", "Z", [0.004], num_samples=128, num_cycles=3,
                      circuit_error_params=ep, if_plot=False)
    assert wer.shape == (1, 1)
    assert 0 <= wer[0, 0] <= 0.5


# -------------------------------------------------- CodeFamily_SpaceTime
def test_code_family_spacetime_circuit(family_codes):
    fam = CodeFamily_SpaceTime(
        [family_codes[0]],
        decoder1_class=ST_BP_Decoder_Circuit_Class(1, "minimum_sum", 0.625),
        decoder2_class=ST_BPOSD_Decoder_Circuit_Class(
            1, "minimum_sum", 0.625, "osd_e", 4),
        batch_size=64, seed=4,
    )
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0}
    wer_list, p_list = fam.EvalWER(
        "circuit", "Z", [0.003], num_samples=128, num_cycles=7, num_rep=3,
        circuit_error_params=ep, if_plot=False,
    )
    assert len(wer_list) == 1 and len(p_list) == 1
    assert wer_list[0].shape == (1,)
    assert 0 <= wer_list[0][0] <= 0.5


def test_code_family_spacetime_adaptive_pruning(family_codes):
    fam = CodeFamily_SpaceTime(
        [family_codes[0]],
        decoder1_class=ST_BP_Decoder_Circuit_Class(1, "minimum_sum", 0.625),
        decoder2_class=ST_BPOSD_Decoder_Circuit_Class(
            1, "minimum_sum", 0.625, "osd_e", 4),
        batch_size=32, seed=5,
    )
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0}
    adaptive = {"WEREst": lambda N, p: p, "min_wer": 0.005}
    wer_list, p_adapt = fam.EvalWER(
        "circuit", "Z", [0.001, 0.01], num_samples=32, num_cycles=7,
        num_rep=3, circuit_error_params=ep, if_plot=False,
        if_adaptive=True, adaptive_params=adaptive,
    )
    # 0.001 pruned away by the predictor
    assert list(p_adapt[0]) == [0.01]
    assert wer_list[0].shape == (1,)
