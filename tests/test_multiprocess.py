"""Real multi-process DCN validation of the sweep-grid sharding.

parallel/grid.py splits the (code, p) grid round-robin across JAX processes
and merges scalar results with one allgather over DCN.  The rest of the
suite exercises it with process_count == 1; here an actual 2-process JAX
program (jax.distributed over a local gRPC coordinator, CPU backend) runs a
CodeFamily.EvalWER with ``shard_across_processes=True`` and must produce the
same grid as the single-process run — each process computes only its own
cells (asserted), and the DCN merge fills in the rest.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
# the image's sitecustomize eagerly initializes the TPU backend, which would
# make BOTH workers report process_index 0 (single-chip view) — tear it down
# and pin the CPU platform before the distributed service comes up
from qldpc_fault_tolerance_tpu.utils.backend import force_virtual_cpu
import jax

jax.distributed.initialize(
    coordinator_address={coord!r},
    num_processes=2,
    process_id={pid},
)
assert force_virtual_cpu(1), "could not force CPU platform"
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == {pid}, jax.process_index()
import numpy as np
from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
from qldpc_fault_tolerance_tpu.sweep import CodeFamily

fam = CodeFamily(
    [hgp(rep_code(3), rep_code(3))],
    decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
    decoder2_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
    batch_size=64, seed=0,
)
from qldpc_fault_tolerance_tpu.utils.observability import timings

wer = fam.EvalWER("data", "Total", [0.02, 0.05, 0.08], 128, if_plot=False,
                  shard_across_processes=True)
cells_run = timings().get("cell:data", {{}}).get("count", 0)
print("RESULT" + str({pid}) + json.dumps(
    {{"wer": wer.tolist(), "cells_run": cells_run}}))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_grid_shard_matches_single_process():
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    })
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER.format(repo=REPO, coord=coord, pid=pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, err[-3000:]
        outs.append(out)

    results, cells_run = {}, {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                rec = json.loads(line[7:])
                results[int(line[6])] = np.asarray(rec["wer"])
                cells_run[int(line[6])] = rec["cells_run"]
    assert set(results) == {0, 1}
    # the grid really was SPLIT: 3 cells round-robin over 2 processes means
    # process 0 computed 2 and process 1 computed 1 — not 3 and 3
    assert cells_run == {0: 2, 1: 1}, cells_run
    # both processes hold the fully-merged grid
    np.testing.assert_array_equal(results[0], results[1])
    merged = results[0]
    assert merged.shape == (1, 3)
    assert not np.isnan(merged).any()

    # single-process reference with the same seed/config
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    fam = CodeFamily(
        [hgp(rep_code(3), rep_code(3))],
        decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        batch_size=64, seed=0,
    )
    single = fam.EvalWER("data", "Total", [0.02, 0.05, 0.08], 128,
                         if_plot=False)
    np.testing.assert_allclose(merged, np.asarray(single))
