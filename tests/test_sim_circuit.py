"""Tests for the circuit-level engines (plain + space-time).

Physics sanity model: the d=3 rotated-free surface code hgp(rep3, rep3).
With only CX depolarizing noise at small p, the logical error rate must be
small and grow with p; at p=0 no shot may fail."""
import numpy as np
import jax
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BPDecoder,
    BPOSD_Decoder,
    ST_BP_Decoder_Circuit,
    ST_BPOSD_Decoder_Circuit,
)
from qldpc_fault_tolerance_tpu.sim import (
    CodeSimulator_Circuit,
    CodeSimulator_Circuit_SpaceTime,
    build_memory_circuit,
)
from qldpc_fault_tolerance_tpu.circuits import FrameSampler, ColorationCircuit


ERROR_PARAMS_CX_ONLY = {
    "p_i": 0.0, "p_state_p": 0.0, "p_m": 0.0, "p_CX": 0.004,
    "p_idling_gate": 0.0,
}


@pytest.fixture(scope="module")
def surface3():
    return hgp(rep_code(3), rep_code(3))


def _plain_sim(code, p_cx, num_cycles=3, batch_size=64):
    ep = dict(ERROR_PARAMS_CX_ONLY, p_CX=p_cx)
    n = code.N
    hx_ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    p_data = max(p_cx, 1e-6)
    dec1 = BPDecoder(hx_ext, np.full(hx_ext.shape[1], p_data), max_iter=20)
    dec2 = BPOSD_Decoder(code.hx, np.full(n, p_data), max_iter=30, osd_order=4)
    return CodeSimulator_Circuit(
        code=code, decoder1_z=dec1, decoder2_z=dec2, p=p_cx, num_cycles=num_cycles,
        error_params=ep, eval_logical_type="Z", batch_size=batch_size, seed=7,
    )


def test_circuit_structure(surface3):
    code = surface3
    ep = dict(ERROR_PARAMS_CX_ONLY)
    sx = ColorationCircuit(code.hx)
    sz = ColorationCircuit(code.hz)
    c = build_memory_circuit(code, 5, ep, sx, sz)
    m = code.hx.shape[0]
    assert c.num_detectors == 5 * m
    assert c.num_observables == code.lx.shape[0]
    # cycles-1 rounds of ancilla MR + final data MX
    assert c.num_measurements == 4 * (code.hx.shape[0] + code.hz.shape[0]) + code.N
    # CX noise present
    assert "DEPOLARIZE2" in str(c)


def test_plain_circuit_noiseless_never_fails(surface3):
    sim = _plain_sim(surface3, 0.0)
    fails = sim.run_batch(jax.random.PRNGKey(0))
    assert not fails.any()


def test_plain_circuit_wer_small_at_low_p(surface3):
    sim = _plain_sim(surface3, 0.004, batch_size=256)
    wer, _ = sim.WordErrorRate(512, key=jax.random.PRNGKey(1))
    assert 0 <= wer < 0.05


def test_plain_circuit_wer_monotone_in_p(surface3):
    lo = _plain_sim(surface3, 0.002, batch_size=256)
    hi = _plain_sim(surface3, 0.03, batch_size=256)
    f_lo = sum(
        lo.run_batch(jax.random.fold_in(jax.random.PRNGKey(2), i)).sum()
        for i in range(4)
    )
    f_hi = sum(
        hi.run_batch(jax.random.fold_in(jax.random.PRNGKey(2), i)).sum()
        for i in range(4)
    )
    assert f_hi >= f_lo


def _st_sim(code, p_cx, num_cycles=7, num_rep=3, batch_size=64):
    ep = dict(ERROR_PARAMS_CX_ONLY, p_CX=p_cx)
    sim = CodeSimulator_Circuit_SpaceTime(
        code=code, p=p_cx, num_cycles=num_cycles, num_rep=num_rep,
        error_params=ep, eval_logical_type="Z", batch_size=batch_size, seed=11,
    )
    sim._generate_circuit()
    sim._generate_circuit_graph()
    g = sim.circuit_graph
    ps1 = np.clip(np.asarray(g["channel_ps1"], float), 1e-9, 0.49)
    ps2 = np.clip(np.asarray(g["channel_ps2"], float), 1e-9, 0.49)
    sim.decoder1_z = ST_BP_Decoder_Circuit(g["h1"], ps1, max_iter=30)
    sim.decoder2_z = ST_BPOSD_Decoder_Circuit(g["h2"], ps2, max_iter=30, osd_order=4)
    return sim


def test_st_circuit_graph_shapes(surface3):
    code = surface3
    sim = _st_sim(code, 0.003)
    m = code.hx.shape[0]
    g = sim.circuit_graph
    assert g["h1"].shape[0] == sim.num_rep * m
    assert g["h2"].shape[0] == m
    assert g["L1"].shape[0] == code.lx.shape[0]
    assert len(g["channel_ps1"]) == g["h1"].shape[1]
    assert sim.h1_space_cor.shape == (m, g["h1"].shape[1])
    # every first-window fault must touch at least one window detector
    assert (g["h1"].sum(axis=0) > 0).all()


def test_st_circuit_noiseless_never_fails(surface3):
    # with p_CX=0 there are no faults at all (empty DEM), so build without
    # decoders and only check the sampler is deterministic-zero
    sim = CodeSimulator_Circuit_SpaceTime(
        code=surface3, p=0.0, num_cycles=7, num_rep=3,
        error_params=dict(ERROR_PARAMS_CX_ONLY, p_CX=0.0),
        eval_logical_type="Z", batch_size=32, seed=11,
    )
    sim._generate_circuit()
    dets, obs = sim.detector_sampler.sample(jax.random.PRNGKey(0), 32)
    assert not np.asarray(dets).any()
    assert not np.asarray(obs).any()


def test_st_circuit_wer_small_at_low_p(surface3):
    sim = _st_sim(surface3, 0.003, batch_size=256)
    wer, _ = sim.WordErrorRate(512, key=jax.random.PRNGKey(3))
    assert 0 <= wer < 0.05


def test_st_target_failure_sampling(surface3):
    sim = _st_sim(surface3, 0.02, batch_size=64)
    wer, total = sim.WordErrorRate_TargetFailure(
        target_failures=1, batch_size=64, max_batches=8,
        key=jax.random.PRNGKey(4),
    )
    assert total % 64 == 0 and total <= 8 * 64
    assert wer >= 0


def test_pz_alias(surface3):
    """Notebook-era `pz=` keyword maps onto p for both circuit engines
    (Threshold ckpt cell 4 passes pz=p; the current reference renamed the
    parameter at src/Simulators.py:388) — API_PARITY.md divergence #3."""
    ep = dict(ERROR_PARAMS_CX_ONLY)
    sim = CodeSimulator_Circuit(code=surface3, num_cycles=3,
                                error_params=ep, pz=0.0123)
    assert sim.pz == 0.0123 and sim.synd_prob == 0.0123
    sim_st = CodeSimulator_Circuit_SpaceTime(code=surface3, num_cycles=7,
                                             num_rep=3, error_params=ep,
                                             pz=0.0123)
    assert sim_st.pz == 0.0123
