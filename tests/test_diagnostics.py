"""Statistical observability layer (ISSUE 7): uncertainty intervals vs
closed-form/SciPy references, event-schema validation, anomaly monitors
(incl. the forced-ladder-step satellite), fit diagnostics with bootstrap
CIs and the converged:false failure path, the run ledger, the sweep
dashboard rendering from files alone, and the end-to-end fused-sweep
acceptance: diagnostics on vs off is bit-exact."""
import importlib
import json
import os
import sys

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class, BPDecoder
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sweep import CodeFamily, fits
from qldpc_fault_tolerance_tpu.utils import (
    diagnostics,
    faultinject,
    resilience,
    telemetry,
)


@pytest.fixture(autouse=True)
def _clean_state():
    """Every test starts with telemetry off, an empty registry, and the
    diagnostics switch back in auto mode."""
    telemetry.disable()
    telemetry.reset()
    diagnostics.auto()
    yield
    diagnostics.auto()
    telemetry.disable()
    telemetry.reset()


def _family(codes=None, batch=64, seed=1):
    codes = codes or [hgp(rep_code(3), rep_code(3), name="hgp_rep3")]
    return CodeFamily(
        codes, BP_Decoder_Class(4, "minimum_sum", 0.625),
        BP_Decoder_Class(4, "minimum_sum", 0.625),
        batch_size=batch, seed=seed)


def _assert_all_events_valid(records):
    problems = [p for r in records for p in telemetry.validate_event(r)]
    assert not problems, "schema violations:\n" + "\n".join(problems)


# ---------------------------------------------------------------------------
# intervals vs independent references
# ---------------------------------------------------------------------------
def test_wilson_matches_scipy_and_quadratic_root_reference():
    """The Wilson interval is the root pair of
    (phat - p)^2 = z^2 p (1-p) / n — solve that quadratic independently
    (np.roots) and, where available, cross-check scipy's binomtest
    proportion_ci; both must agree to 1e-12."""
    z = diagnostics.Z_95
    for f, n in [(0, 64), (1, 64), (5, 100), (50, 100), (99, 100),
                 (100, 100), (3, 7), (1234, 100000)]:
        lo, hi = diagnostics.wilson_interval(f, n, z)
        phat = f / n
        # quadratic: (1 + z²/n) p² - (2 phat + z²/n) p + phat² = 0
        a = 1.0 + z * z / n
        b = -(2.0 * phat + z * z / n)
        c = phat * phat
        roots = sorted(np.roots([a, b, c]).real)
        assert abs(lo - max(roots[0], 0.0)) < 1e-12, (f, n)
        assert abs(hi - min(roots[1], 1.0)) < 1e-12, (f, n)
        try:
            from scipy.stats import binomtest

            ci = binomtest(f, n).proportion_ci(confidence_level=0.95,
                                               method="wilson")
            assert abs(lo - ci.low) < 1e-12
            assert abs(hi - ci.high) < 1e-12
        except (ImportError, AttributeError, TypeError):
            pass  # old scipy: the quadratic-root check above stands


def test_clopper_pearson_matches_scipy_beta():
    from scipy.stats import beta

    for f, n in [(0, 50), (1, 50), (7, 64), (64, 64)]:
        lo, hi = diagnostics.clopper_pearson_interval(f, n)
        ref_lo = 0.0 if f == 0 else beta.ppf(0.025, f, n - f + 1)
        ref_hi = 1.0 if f == n else beta.ppf(0.975, f + 1, n - f)
        assert abs(lo - ref_lo) < 1e-12
        assert abs(hi - ref_hi) < 1e-12


def test_ci_fields_edge_cases():
    empty = diagnostics.ci_fields(0, 0)
    assert empty["ci_low"] == 0.0 and empty["ci_high"] == 1.0
    assert empty["rel_ci_width"] is None and empty["rse"] is None
    zero_fail = diagnostics.ci_fields(0, 128)
    assert zero_fail["rate"] == 0.0 and zero_fail["rse"] is None
    assert zero_fail["ci_high"] < 0.1  # informative upper bound
    full = diagnostics.ci_fields(64, 64)
    assert full["rate"] == 1.0 and full["rse"] == 0.0
    some = diagnostics.ci_fields(9, 100)
    assert some["ci_low"] < 0.09 < some["ci_high"]
    assert some["rse"] == pytest.approx(np.sqrt(0.91 / 9))
    # everything must be JSON-round-trippable
    assert json.loads(json.dumps(some)) == some


# ---------------------------------------------------------------------------
# event schema registry
# ---------------------------------------------------------------------------
def test_validate_event_flags_drift():
    ok = {"ts": 1.0, "kind": "wer_run", "engine": "data", "shots": 10,
          "failures": 1, "wer": 0.1}
    assert telemetry.validate_event(ok) == []
    missing = dict(ok)
    del missing["shots"]
    assert any("shots" in p for p in telemetry.validate_event(missing))
    mistyped = dict(ok, failures="1")
    assert any("failures" in p for p in telemetry.validate_event(mistyped))
    assert telemetry.validate_event({"ts": 1.0, "kind": "nope"})
    # every registered kind names its required fields
    for kind, schema in telemetry.EVENT_SCHEMAS.items():
        assert isinstance(schema["required"], dict), kind


# ---------------------------------------------------------------------------
# anomaly monitors (synthetic feeds)
# ---------------------------------------------------------------------------
def _cell_key(p, code="c0"):
    return {"code": code, "noise": "data", "type": "Total", "p": float(p),
            "cycles": 1, "samples": 64}


def test_monitor_flags_non_monotone_beyond_ci_overlap():
    telemetry.enable()
    mon = diagnostics.SweepMonitor()
    # decisively decreasing rate with p: 60/1000 at p=0.02 vs 5/1000 at
    # p=0.04 — disjoint CIs -> anomaly
    mon.note_cell(_cell_key(0.02), 0.06, diagnostics.ci_fields(60, 1000))
    mon.note_cell(_cell_key(0.04), 0.005, diagnostics.ci_fields(5, 1000))
    mon.finalize()
    kinds = [a["anomaly"] for a in mon.anomalies]
    assert "non_monotone_wer" in kinds
    snap = telemetry.snapshot()
    assert snap["diag.anomaly.non_monotone_wer"]["value"] == 1

    # overlapping CIs (10 vs 9 failures in 1000) are noise, not an anomaly
    mon2 = diagnostics.SweepMonitor()
    mon2.note_cell(_cell_key(0.02), 0.01, diagnostics.ci_fields(10, 1000))
    mon2.note_cell(_cell_key(0.04), 0.009, diagnostics.ci_fields(9, 1000))
    mon2.finalize()
    assert not [a for a in mon2.anomalies
                if a["anomaly"] == "non_monotone_wer"]


def test_monitor_flags_stalled_convergence_and_iteration_drift():
    telemetry.enable()
    mon = diagnostics.SweepMonitor(min_shots=100)
    nb = len(telemetry.ITER_BUCKETS) + 1
    hist = telemetry.histogram("bp.iterations", telemetry.ITER_BUCKETS)

    # cell 1: healthy — 95% converged, iterations concentrated low
    telemetry.count("bp.shots", 1000)
    telemetry.count("bp.converged", 950)
    hist.merge_counts([950] + [0] * (nb - 1), 950.0, 950)
    mon.note_cell(_cell_key(0.01), 0.01, None)
    assert not mon.anomalies

    # cell 2: stalled (20% converged) AND iteration mass moved to the top
    telemetry.count("bp.shots", 1000)
    telemetry.count("bp.converged", 200)
    hist.merge_counts([0] * (nb - 1) + [200], 12800.0, 200)
    mon.note_cell(_cell_key(0.02), 0.2, None)
    kinds = [a["anomaly"] for a in mon.anomalies]
    assert "stalled_convergence" in kinds
    assert "bp_iteration_drift" in kinds


def test_monitor_substrate_mismatch_on_partial_degrade():
    telemetry.enable()
    mon = diagnostics.SweepMonitor()
    telemetry.add_sink(mon)
    try:
        telemetry.event("degrade", rung="packed->dense")
        mon.note_cell(_cell_key(0.02), 0.01,
                      diagnostics.ci_fields(10, 1000))
        mon.note_cell(_cell_key(0.04), 0.02,
                      diagnostics.ci_fields(20, 1000))
    finally:
        telemetry.remove_sink(mon)
    mon.finalize()
    kinds = [a["anomaly"] for a in mon.anomalies]
    assert "ladder_degrade" in kinds
    assert "substrate_mismatch" in kinds
    ladder = next(a for a in mon.anomalies
                  if a["anomaly"] == "ladder_degrade")
    assert ladder["cell"]["p"] == 0.02  # names the cell...
    assert "packed->dense" in ladder["rungs"]  # ...and the rung


# ---------------------------------------------------------------------------
# forced ladder step through a REAL sweep (satellite)
# ---------------------------------------------------------------------------
@pytest.mark.faults
def test_forced_ladder_step_raises_grid_visible_anomaly(tmp_path):
    """A fault-injected ladder step inside one cell of a CodeFamily sweep
    must surface as a grid-visible anomaly event naming the cell and the
    substrate rung (ISSUE 7 satellite)."""
    fam = _family()
    key_p = [0.02, 0.06]
    clean = fam.EvalWER("data", "Total", key_p, num_samples=64,
                        if_plot=False, fused=False)
    # two transient faults at the data engine's WER entry: with
    # degrade_after=1 the first failure steps packed->dense, and the cell
    # then completes on the fallback substrate (bit-exact rung)
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="wer.data", kind="raise", count=2),
    ])
    pol = resilience.RetryPolicy(max_attempts=4, base_delay=0.0,
                                 jitter=0.0, reset_caches=False,
                                 degrade_after=1)
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        with resilience.policy_override(pol), plan.active():
            with telemetry.session(reset_metrics=True) as reg:
                faulted = _family().EvalWER(
                    "data", "Total", key_p, num_samples=64,
                    if_plot=False, fused=False)
                snap = reg.snapshot()
    finally:
        telemetry.remove_sink(sink)
    assert np.array_equal(faulted, clean)  # the rung is bit-exact
    anomalies = [r for r in sink.records if r["kind"] == "anomaly"]
    ladder = [a for a in anomalies if a["anomaly"] == "ladder_degrade"]
    assert ladder, f"no ladder anomaly in {[a['anomaly'] for a in anomalies]}"
    assert ladder[0]["cell"]["code"] == "hgp_rep3"
    assert ladder[0]["cell"]["p"] == key_p[0]
    assert "packed->dense" in ladder[0]["rungs"]
    # only one of the two cells degraded -> the grid is substrate-mixed
    assert [a for a in anomalies if a["anomaly"] == "substrate_mismatch"]
    assert snap["diag.anomaly.ladder_degrade"]["value"] >= 1
    _assert_all_events_valid(sink.records)


def test_fused_bucket_degrade_labels_every_cell():
    """One device run serves every cell of a fused bucket: a ladder step
    during it must label ALL the bucket's cells (one bucket-level anomaly,
    no spurious substrate_mismatch from a half-labeled bucket)."""
    telemetry.enable()
    with diagnostics.sweep_run({"grid": "fused"}) as run:
        diagnostics.notify_degrade("packed->dense")
        rungs = diagnostics.drain_degrade_rungs()
        assert rungs == ["packed->dense"]
        cells = [_cell_key(0.02), _cell_key(0.04)]
        diagnostics.report_ladder_anomaly(cells, rungs)
        for ck, f in zip(cells, (10, 20)):
            diagnostics.record_cell(ck, f / 1000,
                                    diagnostics.ci_fields(f, 1000),
                                    rungs=rungs)
        mon = run.monitor
    kinds = [a["anomaly"] for a in mon.anomalies]
    assert kinds.count("ladder_degrade") == 1  # one bucket-level anomaly
    ladder = next(a for a in mon.anomalies
                  if a["anomaly"] == "ladder_degrade")
    assert len(ladder["cells"]) == 2  # ...naming every cell it served
    # every cell carries the substrate -> uniform grid, no mismatch alarm
    assert all(c.get("substrate") == "packed->dense" for c in mon.cells)
    assert "substrate_mismatch" not in kinds


@pytest.mark.faults
def test_ledger_only_run_still_flags_ladder_anomaly(tmp_path):
    """Ledger-only mode (telemetry DISABLED): ladder steps reach the grid
    monitor via the direct resilience->diagnostics notification, not the
    (dead) event stream, so the ledger record still carries the
    anomaly."""
    assert not telemetry.enabled()
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="wer.data", kind="raise", count=2),
    ])
    pol = resilience.RetryPolicy(max_attempts=4, base_delay=0.0,
                                 jitter=0.0, reset_caches=False,
                                 degrade_after=1)
    led = str(tmp_path / "ledger")
    with resilience.policy_override(pol), plan.active():
        _family().EvalWER("data", "Total", [0.02, 0.06], num_samples=64,
                          if_plot=False, fused=False, ledger=led)
    recs = diagnostics.load_ledger(led)
    assert recs and recs[-1]["complete"] is True
    kinds = [a["anomaly"] for a in recs[-1]["anomalies"]]
    assert "ladder_degrade" in kinds
    assert "substrate_mismatch" in kinds
    assert all("ci_low" in c for c in recs[-1]["cells"])


def test_aborted_sweep_marked_incomplete_and_drift_skips_it(tmp_path):
    """A sweep that raises mid-grid still appends its ledger record, but
    marked complete: false with the error — and drift compares skip it
    instead of gating against a truncated run."""
    dash = importlib.import_module("scripts.sweep_dashboard")
    led = diagnostics.RunLedger(str(tmp_path))
    with diagnostics.sweep_run({"grid": 1}, ledger=led):
        diagnostics.record_cell(_cell_key(0.02), 0.01,
                                diagnostics.ci_fields(10, 1000))
    with pytest.raises(RuntimeError, match="boom"):
        with diagnostics.sweep_run({"grid": 1}, ledger=led):
            diagnostics.record_cell(_cell_key(0.02), 0.08,
                                    diagnostics.ci_fields(80, 1000))
            raise RuntimeError("boom")
    recs = led.load()
    assert recs[0]["complete"] is True
    assert recs[1]["complete"] is False and "boom" in recs[1]["error"]
    assert dash.drift_report(recs) is None  # one complete run: no pair
    # CI bootstrap semantics: nothing to gate yet -> --gate passes (0),
    # while a bare --drift query still reports failure (1)
    assert dash.main([str(tmp_path), "--drift", "--gate", "3"]) == 0
    assert dash.main([str(tmp_path), "--drift"]) == 1
    with diagnostics.sweep_run({"grid": 1}, ledger=led):
        diagnostics.record_cell(_cell_key(0.02), 0.011,
                                diagnostics.ci_fields(11, 1000))
    report = dash.drift_report(led.load())
    # pairs with the FIRST run, skipping the aborted one in between
    assert report["prior_run"] == recs[0]["run_id"]
    assert report["max_abs_z"] < 1.0


# ---------------------------------------------------------------------------
# fit diagnostics
# ---------------------------------------------------------------------------
def test_fit_distance_report_diagnostics_and_bootstrap():
    rng = np.random.default_rng(3)
    p = np.logspace(-3, -2, 6)
    true_A, true_d = 40.0, 4.0
    pl = fits.FitDistance(p, true_A, true_d) * rng.normal(1.0, 0.03, p.size)
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        report = fits.fit_distance_report(p, pl, bootstrap=80)
    finally:
        telemetry.remove_sink(sink)
    assert report["converged"] is True
    assert report["d_eff"] == pytest.approx(true_d, rel=0.1)
    assert report["d_ci"][0] < true_d < report["d_ci"][1]
    assert report["r2"] > 0.9
    assert report["stderr"]["d_eff"] is not None
    events = [r for r in sink.records if r["kind"] == "fit_report"]
    assert events and events[-1]["d_eff"] == report["d_eff"]
    _assert_all_events_valid(sink.records)


def test_threshold_fit_report_bootstrap_ci_contains_truth():
    # synthetic family generated FROM the fit ansatz with mild noise
    rng = np.random.default_rng(7)
    true_pc = 0.04
    p = np.linspace(0.016, 0.032, 6)
    d_list = [3.0, 5.0]
    pl = np.array([
        fits.EmpericalFit((p, d), true_pc, 0.1)
        * rng.normal(1.0, 0.05, p.size)
        for d in d_list
    ])
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        report = fits.threshold_fit_report(p, pl, bootstrap=100)
        pc_legacy = fits.ThresholdEst_extrapolation(p, pl, verbose=False)
    finally:
        telemetry.remove_sink(sink)
    assert report["converged"] is True
    assert report["p_c"] == pytest.approx(true_pc, rel=0.15)
    assert report["pc_ci"][0] < report["p_c"] < report["pc_ci"][1]
    assert len(report["d_per_code"]) == 2
    # legacy surface unchanged: ThresholdEst returns the same point estimate
    assert pc_legacy == pytest.approx(report["p_c"], abs=1e-12)
    _assert_all_events_valid(sink.records)


def test_threshold_fit_forwards_sigma_and_bootstrap_to_distance_fits():
    """An explicit bootstrap count and per-cell sigma reach the per-code
    distance fits (and the bootstrap replicates refit the same weighted
    estimator as the point fit)."""
    rng = np.random.default_rng(11)
    p = np.linspace(0.016, 0.032, 6)
    pl = np.array([
        fits.EmpericalFit((p, d), 0.04, 0.1) * rng.normal(1.0, 0.05, p.size)
        for d in (3.0, 5.0)
    ])
    sigma = 0.1 * pl + 1e-6
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        report = fits.threshold_fit_report(p, pl, sigma=sigma, bootstrap=30)
    finally:
        telemetry.remove_sink(sink)
    assert report["bootstrap"] == 30 and "pc_ci" in report
    assert "chi2" in report  # sigma-weighted goodness-of-fit present
    dist = [r for r in sink.records if r["kind"] == "fit_report"
            and r["fit"] == "distance"]
    assert len(dist) == 2
    for r in dist:
        assert r["bootstrap"] == 30 and "d_ci" in r
        assert "chi2" in r


def test_failed_fit_emits_converged_false_fit_report():
    """scipy's max-iteration failure path must be machine-visible as a
    structured fit_report with converged: false, not just a raised line
    (ISSUE 7 satellite)."""
    p = np.logspace(-3, -2, 6)
    pl = fits.FitDistance(p, 40.0, 4.0)
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        with pytest.raises(RuntimeError, match="maxfev"):
            fits.fit_distance_report(p, pl, bootstrap=0, maxfev=1)
    finally:
        telemetry.remove_sink(sink)
    reports = [r for r in sink.records if r["kind"] == "fit_report"]
    assert len(reports) == 1
    assert reports[0]["converged"] is False
    assert "maxfev" in reports[0]["error"]
    assert telemetry.snapshot()["fits.failed"]["value"] == 1
    _assert_all_events_valid(sink.records)


# ---------------------------------------------------------------------------
# run ledger + drift
# ---------------------------------------------------------------------------
def _synthetic_ledger_record(run_id, fingerprint, failures):
    cells = []
    for p, f in zip([0.02, 0.04], failures):
        cells.append({"cell": _cell_key(p), "wer": f / 1000,
                      **diagnostics.ci_fields(f, 1000)})
    return {"v": 1, "run_id": run_id, "ts": 0.0, "fingerprint": fingerprint,
            "config": {}, "cells": cells, "fits": [], "anomalies": []}


def test_ledger_round_trip_and_fingerprint_stability(tmp_path):
    led = diagnostics.RunLedger(str(tmp_path / "ledger"))
    led.append(_synthetic_ledger_record("r1", "fp", [10, 20]))
    led.append(_synthetic_ledger_record("r2", "fp", [12, 21]))
    recs = led.load()
    assert [r["run_id"] for r in recs] == ["r1", "r2"]
    # fingerprint: float formatting must not matter, config content must
    cfg = {"p_list": [0.02, 0.04], "codes": ["a"]}
    assert diagnostics.config_signature(cfg) == \
        diagnostics.config_signature({"codes": ["a"],
                                        "p_list": [0.020000000000000004 - 4e-18,
                                                   0.04]})
    assert diagnostics.config_signature(cfg) != \
        diagnostics.config_signature({**cfg, "codes": ["b"]})


def test_dashboard_drift_compare_and_gate(tmp_path):
    dash = importlib.import_module("scripts.sweep_dashboard")
    led = diagnostics.RunLedger(str(tmp_path))
    led.append(_synthetic_ledger_record("r1", "fp", [10, 20]))
    led.append(_synthetic_ledger_record("rX", "OTHER", [10, 20]))
    led.append(_synthetic_ledger_record("r2", "fp", [80, 21]))
    report = dash.drift_report(led.load())
    # matches against r1 (same fingerprint), skipping the OTHER-config run
    assert report["prior_run"] == "r1" and report["now_run"] == "r2"
    z_by_p = {r["cell"][3]: r["z"] for r in report["cells"]}
    assert abs(z_by_p[0.04]) < 1.0  # 20 -> 21 failures: noise
    assert z_by_p[0.02] > 5.0       # 10 -> 80 failures: drift
    assert report["max_abs_z"] == pytest.approx(z_by_p[0.02])
    text = dash.render_drift(report)
    assert "r1 -> r2" in text
    # CLI gate: exit 1 beyond the z threshold, 0 within
    assert dash.main([str(tmp_path), "--drift", "--gate", "3"]) == 1
    assert dash.main([str(tmp_path), "--drift", "--gate", "100"]) == 0


def test_ledger_records_carry_env_and_drift_flags_changes(tmp_path):
    """Provenance satellite (ISSUE 11): sweep_run embeds the process_info
    block in every ledger record, and --drift surfaces environment deltas
    between the compared runs so a WER shift that coincides with a
    jax/backend/host change reads as an environment story."""
    dash = importlib.import_module("scripts.sweep_dashboard")
    led = diagnostics.RunLedger(str(tmp_path))
    with diagnostics.sweep_run({"grid": 1}, ledger=led) as run:
        run.note_cell(_cell_key(0.02), 0.01,
                      diagnostics.ci_fields(10, 1000))
    rec = led.load()[-1]
    assert rec["env"]["pid"] == os.getpid()
    assert rec["env"]["hostname"]
    # same env: drift reports no changes
    led.append(_synthetic_ledger_record("r1", "fp", [10, 20]))
    led.append(_synthetic_ledger_record("r2", "fp", [12, 21]))
    report = dash.drift_report(led.load())
    assert report["env_changes"] == []
    assert "environment unchanged" in dash.render_drift(report)
    # a jax bump between runs is flagged by key with both values
    r3 = _synthetic_ledger_record("r3", "fp", [12, 21])
    r3["env"] = {"jax": "0.4.37", "git_sha": "aaa"}
    r4 = _synthetic_ledger_record("r4", "fp", [13, 20])
    r4["env"] = {"jax": "0.5.0", "git_sha": "aaa"}
    led.append(r3)
    led.append(r4)
    report = dash.drift_report(led.load())
    assert report["env_changes"] == [
        {"key": "jax", "prior": "0.4.37", "now": "0.5.0"}]
    text = dash.render_drift(report)
    assert "environment changed" in text and "0.5.0" in text


# ---------------------------------------------------------------------------
# telemetry_report --follow
# ---------------------------------------------------------------------------
def test_follow_reader_consumes_only_complete_lines(tmp_path):
    report = importlib.import_module("scripts.telemetry_report")
    path = str(tmp_path / "run.jsonl")
    reader = report.FollowReader(path)
    assert reader.poll() == []  # not created yet
    with open(path, "w") as fh:
        fh.write(json.dumps({"kind": "wer_run", "ts": 1.0}) + "\n")
        fh.write('{"kind": "hea')  # torn tail mid-flush
    first = reader.poll()
    assert [e["kind"] for e in first] == ["wer_run"]
    with open(path, "a") as fh:
        fh.write('rtbeat", "ts": 2.0}\n')
    second = reader.poll()
    assert [e["kind"] for e in second] == ["heartbeat"]
    assert reader.poll() == []
    # the follow loop renders incrementally without waiting for run end
    import io

    out = io.StringIO()
    assert report.follow(path, interval=0.0, out=out, max_polls=2) == 0
    assert "telemetry report" in out.getvalue()


# ---------------------------------------------------------------------------
# end-to-end acceptance: fused sweep with ledger, bit-exact on/off
# ---------------------------------------------------------------------------
def test_e2e_fused_sweep_ledger_dashboard_bitexact(tmp_path):
    """The ISSUE 7 acceptance path: a small fused CodeFamily sweep with
    the ledger enabled yields (a) cell events whose Wilson intervals match
    the closed-form reference to 1e-12, (b) threshold fit_report with
    bootstrap CI on p_c, (d) the dashboard rendering from the ledger/JSONL
    alone — with WER bit-exact diagnostics-on vs off.  (The injected
    ladder fault, (c), is test_forced_ladder_step_raises_grid_visible_
    anomaly above.)"""
    from qldpc_fault_tolerance_tpu.utils.checkpoint import SweepCheckpoint

    codes = [hgp(rep_code(3), rep_code(3), name="hgp_rep3"),
             hgp(rep_code(4), rep_code(4), name="hgp_rep4")]
    p_list = [0.02, 0.06]
    wer_off = _family(codes).EvalWER("data", "Total", p_list,
                                     num_samples=64, if_plot=False)

    jsonl = str(tmp_path / "run.jsonl")
    ledger_dir = str(tmp_path / "ledger")
    ckpt = SweepCheckpoint(str(tmp_path / "sweep_ckpt.jsonl"))
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        with telemetry.session(jsonl):
            wer_on = _family(codes).EvalWER(
                "data", "Total", p_list, num_samples=64, if_plot=False,
                ledger=ledger_dir, checkpoint=ckpt)
    finally:
        telemetry.remove_sink(sink)
    # diagnostics are host-side bookkeeping only: WER bit-exact on vs off
    assert np.array_equal(wer_on, wer_off)

    # (a) every cell event carries a Wilson interval matching the
    # closed-form reference to 1e-12
    cell_dones = [r for r in sink.records if r["kind"] == "cell_done"]
    assert len(cell_dones) == len(codes) * len(p_list)
    for e in cell_dones:
        assert {"failures", "shots", "ci_low", "ci_high"} <= set(e)
        lo, hi = diagnostics.wilson_interval(e["failures"], e["shots"])
        assert abs(e["ci_low"] - lo) < 1e-12
        assert abs(e["ci_high"] - hi) < 1e-12
    # live per-cell publishing at the existing syncs: cell_progress events
    # (the checkpointed fused run streams per-megabatch) + interval gauges
    progress_events = [r for r in sink.records
                       if r["kind"] == "cell_progress"]
    assert progress_events
    assert progress_events[-1]["ci_low"]
    # checkpoint cursors carry intervals too (additive keys)
    with open(ckpt.path) as fh:
        progress_lines = [json.loads(line) for line in fh
                          if '"progress"' in line]
    assert progress_lines
    assert "ci_low" in progress_lines[-1]["progress"]
    # every emitted event validates against the schema registry
    _assert_all_events_valid(sink.records)

    # ledger record: per-cell counts + CIs, fingerprint, anomalies list
    recs = diagnostics.load_ledger(ledger_dir)
    assert len(recs) == 1
    assert len(recs[0]["cells"]) == len(codes) * len(p_list)
    assert all("ci_low" in c for c in recs[0]["cells"])

    # (d) dashboard renders the grid from the ledger alone and from the
    # JSONL sink alone — no live process
    dash = importlib.import_module("scripts.sweep_dashboard")
    for source in (ledger_dir, jsonl):
        text = dash.render_grid(dash.build_grid(dash.load_lines(
            dash.resolve_path(source))))
        assert "hgp_rep3" in text and "hgp_rep4" in text
        assert "p=0.02" in text and "p=0.06" in text
        assert "2e-01" in text or "e-0" in text  # a rendered WER

    # (b) a threshold fit over the same family emits a fit_report with a
    # bootstrap CI on p_c, landing in the SAME ledger as its grid
    sink2 = telemetry.MemorySink()
    telemetry.add_sink(sink2)
    try:
        with telemetry.session(reset_metrics=True):
            pc = _family(codes).EvalThreshold(
                "data", "Total", "extrapolation", est_threshold=0.07,
                num_samples=64, ledger=ledger_dir)
    finally:
        telemetry.remove_sink(sink2)
    assert 0 < pc
    fit_events = [r for r in sink2.records if r["kind"] == "fit_report"
                  and r["fit"] == "threshold"]
    assert fit_events and "pc_ci" in fit_events[-1]
    assert fit_events[-1]["pc_ci"][0] <= fit_events[-1]["p_c"] \
        <= fit_events[-1]["pc_ci"][1]
    _assert_all_events_valid(sink2.records)
    recs = diagnostics.load_ledger(ledger_dir)
    assert len(recs) == 2
    assert any(f.get("fit") == "threshold" for f in recs[-1]["fits"])


def test_wer_run_event_and_heartbeat_enriched():
    code = hgp(rep_code(3), rep_code(3))
    p = 0.05
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        sim = CodeSimulator_DataError(
            code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
            pauli_error_probs=[p / 3] * 3, batch_size=32, seed=0)
        sim.WordErrorRate(64)
    finally:
        telemetry.remove_sink(sink)
    runs = [r for r in sink.records if r["kind"] == "wer_run"]
    assert runs and "ci_low" in runs[-1] and "rse" in runs[-1]
    lo, hi = diagnostics.wilson_interval(runs[-1]["failures"],
                                         runs[-1]["shots"])
    assert runs[-1]["ci_low"] == pytest.approx(lo, abs=1e-15)
    hbs = [r for r in sink.records if r["kind"] == "heartbeat"]
    assert hbs and "rse" in hbs[-1]
    _assert_all_events_valid(sink.records)


def test_diagnostics_disabled_is_plain():
    """Forced-off diagnostics under enabled telemetry: no ci fields on
    events, no monitor, no ledger side effects — the bench A/B's off arm."""
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        diagnostics.disable()
        assert not diagnostics.active()
        _family().EvalWER("data", "Total", [0.04], num_samples=64,
                          if_plot=False)
    finally:
        diagnostics.auto()
        telemetry.remove_sink(sink)
    cell_dones = [r for r in sink.records if r["kind"] == "cell_done"]
    assert cell_dones and "ci_low" not in cell_dones[-1]
    assert not [r for r in sink.records if r["kind"] == "ledger"]


def test_no_ledger_dir_side_effect_by_default(tmp_path, monkeypatch):
    """Without a ledger= knob or QLDPC_LEDGER_DIR, no ledger/ dir appears
    — enabling telemetry must not write to the working tree."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("QLDPC_LEDGER_DIR", raising=False)
    telemetry.enable()
    _family().EvalWER("data", "Total", [0.04], num_samples=64,
                      if_plot=False)
    assert not os.path.exists(tmp_path / "ledger")
