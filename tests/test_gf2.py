import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import gf2


def random_mat(rng, m, n, density=0.3):
    return (rng.random((m, n)) < density).astype(np.uint8)


@pytest.mark.parametrize("seed", range(5))
def test_rref_reproduces_rowspace(seed):
    rng = np.random.default_rng(seed)
    a = random_mat(rng, 12, 20)
    r, pivots = gf2.rref(a)
    assert gf2.rank(a) == len(pivots)
    # row space preserved: every original row solvable in terms of reduced rows
    basis = r[: len(pivots)]
    for row in a:
        assert gf2.solve(basis.T, row) is not None


@pytest.mark.parametrize("seed", range(5))
def test_nullspace_annihilates(seed):
    rng = np.random.default_rng(seed + 100)
    a = random_mat(rng, 10, 25)
    ns = gf2.nullspace(a)
    assert ns.shape[0] == 25 - gf2.rank(a)
    if ns.shape[0]:
        assert not gf2.gf2_mul(a, ns.T).any()
        assert gf2.rank(ns) == ns.shape[0]


def test_rank_against_known():
    a = np.array([[1, 1, 0], [0, 1, 1], [1, 0, 1]])  # rank 2 over GF(2)
    assert gf2.rank(a) == 2
    assert gf2.rank(np.eye(4)) == 4
    assert gf2.rank(np.zeros((3, 3))) == 0


@pytest.mark.parametrize("seed", range(5))
def test_solve_roundtrip(seed):
    rng = np.random.default_rng(seed + 200)
    a = random_mat(rng, 15, 10)
    x_true = (rng.random(10) < 0.5).astype(np.uint8)
    b = gf2.gf2_mul(a, x_true[:, None]).ravel()
    x = gf2.solve(a, b)
    assert x is not None
    assert np.array_equal(gf2.gf2_mul(a, x[:, None]).ravel(), b)


def test_solve_inconsistent():
    a = np.array([[1, 0], [1, 0]])
    assert gf2.solve(a, np.array([1, 0])) is None


def test_incremental_reducer():
    red = gf2.IncrementalRowReducer(4)
    assert red.add([1, 1, 0, 0])
    assert red.add([0, 1, 1, 0])
    assert not red.add([1, 0, 1, 0])  # sum of the first two
    assert red.rank == 2
