"""Source-tree hygiene guards: no bytecode / native build artifacts can
leak into the package or the git index.

Motivation: a stray ``decoders/__pycache__`` (or a tracked ``.pyc``/``.so``)
next to the modules is silently importable and shadows source edits — the
classic "my fix does nothing" failure.  ``.gitignore`` must cover the
artifact patterns everywhere, and nothing of the kind may be tracked.
"""
import os
import subprocess

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = os.path.join(REPO_ROOT, "qldpc_fault_tolerance_tpu")


def test_gitignore_covers_bytecode_everywhere():
    with open(os.path.join(REPO_ROOT, ".gitignore")) as f:
        patterns = {line.strip() for line in f if line.strip()}
    # unanchored patterns apply at every depth — exactly what keeps a
    # decoders/__pycache__ out of the index
    assert "__pycache__/" in patterns
    assert "*.pyc" in patterns
    assert "*.so" in patterns
    # program-cache artifacts (ISSUE 20): serialized executables are
    # machine/toolchain-local — never commit a cache dir
    assert ".qldpc_progcache/" in patterns
    assert "*.qpc" in patterns


def _tracked_files():
    try:
        out = subprocess.run(
            ["git", "ls-files"], cwd=REPO_ROOT, check=True,
            capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.SubprocessError):
        pytest.skip("git not available")
    return out.stdout.splitlines()


def test_no_tracked_bytecode_or_native_artifacts():
    """Nothing importable-but-not-source may be tracked — except the
    intentionally shipped prebuilt native library under ``_native/`` (the
    one directory whose .so IS the artifact of record)."""
    native_prefix = "qldpc_fault_tolerance_tpu/_native/"
    bad = [
        p for p in _tracked_files()
        if (p.endswith((".pyc", ".pyo", ".qpc"))
            or "__pycache__" in p.split("/")
            or ".qldpc_progcache" in p.split("/")
            or (p.endswith(".so") and not p.startswith(native_prefix)))
    ]
    assert not bad, f"build artifacts tracked by git: {bad}"


def test_no_importable_artifacts_in_source_tree():
    """No ``.so`` outside ``_native/`` and no loose ``.pyc`` next to the
    modules (bytecode inside ``__pycache__`` is how CPython caches and is
    gitignored; a SIBLING .pyc would be importable and shadow the .py)."""
    bad = []
    for root, dirs, files in os.walk(PKG_ROOT):
        in_pycache = os.path.basename(root) == "__pycache__"
        in_native = os.path.relpath(root, PKG_ROOT).split(os.sep)[0] == \
            "_native"
        for name in files:
            if name.endswith(".so") and not in_native:
                bad.append(os.path.join(root, name))
            if name.endswith((".pyc", ".pyo")) and not in_pycache:
                bad.append(os.path.join(root, name))
    assert not bad, f"importable build artifacts in the source tree: {bad}"
