"""Request-tracing + flight-recorder tests (ISSUE 11): trace-context wire
round-trip and hostile-input hygiene, span recording into the ring and the
telemetry event stream (schema-validated), span-tree reassembly, the
bounded flight-recorder ring and its postmortem dumps, and the
resilience/faultinject black-box hooks (watchdog timeout, ladder degrade,
exhausted retries each ship the in-flight ring)."""
import json
import os
import threading

import pytest

from qldpc_fault_tolerance_tpu.utils import (
    faultinject,
    resilience,
    telemetry,
    tracing,
)


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    tracing.recorder().clear()
    tracing.configure(postmortem_dir="")
    yield
    telemetry.disable()
    telemetry.reset()
    tracing.recorder().clear()
    tracing.configure(postmortem_dir="")


# ---------------------------------------------------------------------------
# ids + trace context
# ---------------------------------------------------------------------------
def test_new_id_unique_and_sized():
    ids = {tracing.new_id() for _ in range(10_000)}
    assert len(ids) == 10_000
    assert all(len(i) == 16 for i in ids)
    assert len(tracing.new_id(16)) == 32


def test_trace_context_wire_round_trip():
    ctx = tracing.TraceContext()
    back = tracing.TraceContext.from_wire(ctx.to_wire())
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id


def test_trace_context_from_wire_drops_malformed():
    """A bad trace annotation must never fail the decode it rides on:
    wrong types, missing/oversized ids all parse to None (or a repaired
    context), not an exception."""
    assert tracing.TraceContext.from_wire(None) is None
    assert tracing.TraceContext.from_wire("not-a-dict") is None
    assert tracing.TraceContext.from_wire([1, 2]) is None
    assert tracing.TraceContext.from_wire({}) is None
    assert tracing.TraceContext.from_wire({"trace_id": 123}) is None
    assert tracing.TraceContext.from_wire({"trace_id": ""}) is None
    assert tracing.TraceContext.from_wire({"trace_id": "x" * 65}) is None
    # a valid trace id with a junk span id gets a FRESH span id
    fixed = tracing.TraceContext.from_wire(
        {"trace_id": "abc", "span_id": {"nested": 1}})
    assert fixed.trace_id == "abc"
    assert isinstance(fixed.span_id, str) and fixed.span_id


# ---------------------------------------------------------------------------
# span recording
# ---------------------------------------------------------------------------
def test_record_span_none_ctx_is_noop():
    assert tracing.record_span("queue_wait", None, dur_s=0.1) is None
    assert len(tracing.recorder()) == 0


def test_record_span_lands_in_ring_and_event_stream():
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    ctx = tracing.TraceContext()
    rec = tracing.record_span("device_decode", ctx, dur_s=0.25,
                              amortized_over=3, shots=7)
    assert rec["trace_id"] == ctx.trace_id
    assert rec["parent_id"] == ctx.span_id  # default parent: the request
    ring = [r for r in tracing.recorder().snapshot()
            if r["kind"] == "trace"]
    assert len(ring) == 1 and ring[0]["name"] == "device_decode"
    evs = [e for e in sink.records if e["kind"] == "trace"]
    assert len(evs) == 1
    assert telemetry.validate_event(evs[0]) == []


def test_record_span_parent_and_span_id_overrides():
    ctx = tracing.TraceContext()
    root = tracing.record_span("serve.request", ctx, span_id=ctx.span_id,
                               parent_id=None, dur_s=0.5)
    assert root["span_id"] == ctx.span_id
    assert "parent_id" not in root
    explicit = tracing.record_span("respond", ctx, parent_id="pp",
                                   dur_s=0.1)
    assert explicit["parent_id"] == "pp"


def test_span_context_manager_times_and_flags_errors():
    ctx = tracing.TraceContext()
    with tracing.span("slice", ctx, shots=4) as sp:
        pass
    assert sp.record["name"] == "slice" and sp.record["shots"] == 4
    assert sp.record["dur_s"] >= 0.0
    with pytest.raises(ValueError):
        with tracing.span("bad_stage", ctx) as sp2:
            raise ValueError("boom")
    assert sp2.record["ok"] is False
    assert "ValueError" in sp2.record["error"]
    # untraced fast path: the shared no-op, no ring growth
    before = len(tracing.recorder())
    with tracing.span("ignored", None):
        pass
    assert len(tracing.recorder()) == before


# ---------------------------------------------------------------------------
# trace reassembly
# ---------------------------------------------------------------------------
def _mk_span(tid, sid, parent=None, name="s", dur=0.1, ts=1.0, **kw):
    rec = {"kind": "trace", "trace_id": tid, "span_id": sid,
           "name": name, "dur_s": dur, "ts": ts, **kw}
    if parent is not None:
        rec["parent_id"] = parent
    return rec


def test_traces_from_records_groups_by_trace_id():
    records = [_mk_span("a", "1"), _mk_span("b", "2"),
               _mk_span("a", "3"), {"kind": "request"}]
    grouped = tracing.traces_from_records(records)
    assert sorted(grouped) == ["a", "b"]
    assert [s["span_id"] for s in grouped["a"]] == ["1", "3"]


def test_trace_tree_links_children_and_orphan_roots():
    spans = [
        _mk_span("t", "root", parent="client-side", name="serve.request"),
        _mk_span("t", "q", parent="root", name="queue_wait"),
        _mk_span("t", "d", parent="root", name="device_decode"),
    ]
    tree = tracing.trace_tree(spans)
    assert tree["spans"] == 3
    # the client's span is not among the records -> serve.request is root
    assert len(tree["roots"]) == 1
    root = tree["roots"][0]
    assert root["span"]["name"] == "serve.request"
    assert sorted(c["span"]["name"] for c in root["children"]) == \
        ["device_decode", "queue_wait"]


def test_trace_summaries_filters_slow_and_errored():
    records = [
        _mk_span("fast", "1", dur=0.001, ts=1.0),
        _mk_span("slow", "2", dur=0.5, ts=2.0),
        _mk_span("bad", "3", dur=0.002, ts=3.0, ok=False, error="x"),
    ]
    rows = tracing.trace_summaries(records, limit=10)
    assert [r["trace_id"] for r in rows] == ["bad", "slow", "fast"]
    slow = tracing.trace_summaries(records, slow_s=0.1)
    assert [r["trace_id"] for r in slow] == ["slow"]
    errored = tracing.trace_summaries(records, errored_only=True)
    assert [r["trace_id"] for r in errored] == ["bad"]
    assert errored[0]["errored"] is True
    assert len(tracing.trace_summaries(records, limit=1)) == 1


# ---------------------------------------------------------------------------
# flight recorder: bounded ring + postmortems
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_is_bounded():
    fr = tracing.FlightRecorder(capacity=32)
    for i in range(100):
        fr.record("request", i=i)
    snap = fr.snapshot()
    assert len(snap) == 32
    assert snap[0]["i"] == 68 and snap[-1]["i"] == 99  # newest N survive


def test_flight_recorder_dump_format(tmp_path):
    fr = tracing.FlightRecorder(capacity=16)
    fr.record("request", id="r1")
    fr.record("trace", trace_id="t", span_id="s", name="n", dur_s=0.1)
    path = fr.dump("watchdog: fired!", str(tmp_path),
                   extra={"label": "serve"})
    assert os.path.basename(path).startswith("postmortem-")
    assert "/" not in os.path.basename(path).replace(".jsonl", "") \
        .split("postmortem-")[-1]
    lines = [json.loads(x) for x in
             open(path, encoding="utf-8").read().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["kind"] == "postmortem"
    assert header["reason"] == "watchdog: fired!"
    assert header["label"] == "serve"
    assert header["records"] == 2 == len(records)
    assert [r["kind"] for r in records] == ["request", "trace"]


def test_configure_resizes_ring_keeping_newest():
    tracing.flight_record("request", i=0)
    tracing.flight_record("request", i=1)
    fr = tracing.configure(capacity=17)
    assert fr.capacity == 17
    assert [r["i"] for r in fr.snapshot()] == [0, 1]
    assert tracing.recorder() is fr
    # restore the default capacity for other tests
    tracing.configure(capacity=4096)


def test_dump_postmortem_noop_without_directory(tmp_path, monkeypatch):
    monkeypatch.delenv("QLDPC_POSTMORTEM_DIR", raising=False)
    tracing.flight_record("request", id="r")
    assert tracing.dump_postmortem("reason") is None
    # env var path
    monkeypatch.setenv("QLDPC_POSTMORTEM_DIR", str(tmp_path))
    path = tracing.dump_postmortem("envdir")
    assert path is not None and os.path.dirname(path) == str(tmp_path)
    # configure() wins over the env var
    sub = tmp_path / "cfg"
    tracing.configure(postmortem_dir=str(sub))
    path2 = tracing.dump_postmortem("cfgdir")
    assert os.path.dirname(path2) == str(sub)


def test_note_failure_records_and_ships(tmp_path):
    tracing.configure(postmortem_dir=str(tmp_path))
    tracing.flight_record("request", id="inflight-1")
    path = tracing.note_failure("serve_dispatch_failed",
                                request_ids=["inflight-1"])
    assert path is not None
    lines = [json.loads(x) for x in
             open(path, encoding="utf-8").read().splitlines()]
    kinds = [r["kind"] for r in lines]
    assert kinds[0] == "postmortem"
    assert "request" in kinds and "failure" in kinds
    failure = next(r for r in lines if r["kind"] == "failure")
    assert failure["request_ids"] == ["inflight-1"]


def test_ring_appends_are_safe_under_threads():
    fr = tracing.FlightRecorder(capacity=512)
    n_threads, per = 8, 200

    def hammer(t):
        for i in range(per):
            fr.record("request", t=t, i=i)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = fr.snapshot()
    assert len(snap) == 512  # bounded, newest-on-top, no corruption
    assert all(r["kind"] == "request" for r in snap)


# ---------------------------------------------------------------------------
# resilience/faultinject black-box hooks
# ---------------------------------------------------------------------------
def test_watchdog_timeout_ships_postmortem(tmp_path):
    tracing.configure(postmortem_dir=str(tmp_path))
    tracing.flight_record("request", id="hung-req")
    with pytest.raises(resilience.WatchdogTimeout):
        resilience.fetch_with_watchdog(
            lambda: threading.Event().wait(30), label="hung_fetch",
            timeout_s=0.05)
    dumps = list(tmp_path.glob("postmortem-*-watchdog_timeout.jsonl"))
    assert len(dumps) == 1
    lines = [json.loads(x) for x in
             dumps[0].read_text().splitlines()]
    assert lines[0]["label"] == "hung_fetch"
    assert any(r.get("id") == "hung-req" for r in lines)


def test_retry_exhausted_ships_postmortem(tmp_path):
    tracing.configure(postmortem_dir=str(tmp_path))
    policy = resilience.RetryPolicy(max_attempts=2, base_delay=0.0,
                                    jitter=0.0, reset_caches=False)

    def die():
        raise resilience.TransientFault("injected worker death")

    with resilience.policy_override(policy):
        with pytest.raises(resilience.TransientFault):
            resilience.run_cell(die, label="doomed")
    dumps = list(tmp_path.glob("postmortem-*-retry_exhausted.jsonl"))
    assert len(dumps) == 1
    records = [json.loads(x) for x in dumps[0].read_text().splitlines()]
    # the retry that preceded exhaustion is in the ring the dump shipped
    assert any(r["kind"] == "retry" for r in records)
    assert any(r["kind"] == "failure"
               and r["reason"] == "retry_exhausted" for r in records)


def test_degrade_ships_postmortem(tmp_path):
    tracing.configure(postmortem_dir=str(tmp_path))
    ladder = resilience.DegradationLadder([("fused->xla", lambda: None)])
    assert ladder.step() == "fused->xla"
    dumps = list(tmp_path.glob("postmortem-*-degrade.jsonl"))
    assert len(dumps) == 1
    records = [json.loads(x) for x in dumps[0].read_text().splitlines()]
    failure = next(r for r in records if r["kind"] == "failure")
    assert failure["rung"] == "fused->xla"


def test_faultinject_records_into_ring():
    plan = faultinject.FaultPlan(
        [faultinject.Fault(site="test_site", kind="raise")])
    with plan.active():
        with pytest.raises(faultinject.InjectedFault):
            faultinject.site("test_site")
    ring = tracing.recorder().snapshot()
    hits = [r for r in ring if r["kind"] == "fault_injected"]
    assert len(hits) == 1
    assert hits[0]["site"] == "test_site"
    assert hits[0]["fault_kind"] == "raise"
