"""Resilient execution layer: fault injection, retry/backoff, watchdogs,
degradation ladder, mid-cell resume (utils/resilience.py +
utils/faultinject.py), plus the SweepCheckpoint crash-tolerance satellites.

Every recovery path runs here on CPU via the deterministic fault plans in
utils.faultinject — the real failure modes (tunneled-worker death, hung
drains, kills mid-checkpoint-append) cannot be produced on demand in CI.
"""
import json
import os
import time

import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon
from qldpc_fault_tolerance_tpu.utils import faultinject, resilience, telemetry
from qldpc_fault_tolerance_tpu.utils.checkpoint import (
    CellProgress,
    SweepCheckpoint,
)

LIB_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
) + "/qldpc_fault_tolerance_tpu"

pytestmark = pytest.mark.faults


def fast_policy(**kw):
    """Retry policy with no real backoff (tests must not sleep)."""
    kw.setdefault("max_attempts", 4)
    kw.setdefault("base_delay", 0.0)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("reset_caches", False)  # keep CPU tests snappy
    return resilience.RetryPolicy(**kw)


def data_sim(**kw):
    code = hgp(rep_code(3), rep_code(3))
    p = kw.pop("p", 0.05)
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    kw.setdefault("batch_size", 64)
    kw.setdefault("scan_chunk", 2)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, seed=0, **kw)


def phenom_sim(**kw):
    code = hgp(rep_code(3), rep_code(3))
    p = kw.pop("p", 0.04)
    ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    extz = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
    d1 = lambda h: BPDecoder(  # noqa: E731
        h, np.full(h.shape[1], p), max_iter=4)
    d2 = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    kw.setdefault("batch_size", 64)
    kw.setdefault("scan_chunk", 2)
    return CodeSimulator_Phenon(
        code=code, decoder1_x=d1(extz), decoder1_z=d1(ext),
        decoder2_x=d2(code.hz), decoder2_z=d2(code.hx),
        pauli_error_probs=[p / 3] * 3, q=p, seed=0, **kw)


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------
def test_classify_error():
    assert resilience.classify_error(
        faultinject.InjectedFault("boom")) == "transient"
    assert resilience.classify_error(
        resilience.WatchdogTimeout("hung")) == "transient"
    assert resilience.classify_error(TimeoutError("t")) == "transient"
    assert resilience.classify_error(ValueError("bad")) == "deterministic"
    assert resilience.classify_error(
        faultinject.InjectedDeterministicFault("bug")) == "deterministic"
    assert resilience.classify_error(
        jax.errors.JaxRuntimeError("INTERNAL: worker died")) == "transient"
    assert resilience.classify_error(
        jax.errors.JaxRuntimeError("INVALID_ARGUMENT: bad shape")
    ) == "deterministic"


def test_resource_errors_step_ladder_not_retry_in_place():
    """RESOURCE_EXHAUSTED: retrying the same rung is a guaranteed loss, but
    a ladder step can clear it; with no ladder left it fails fast."""
    assert resilience.classify_error(
        jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: oom")) == "resource"
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: oom")
        return "ok"

    ladder = resilience.DegradationLadder([("a->b", lambda: None)])
    assert fast_policy().run(flaky, label="t", degrade=ladder.step) == "ok"
    assert ladder.remaining == 0  # the rung was actually consumed
    calls["n"] = 0
    with pytest.raises(jax.errors.JaxRuntimeError):
        fast_policy().run(flaky, label="t")  # no ladder -> fail fast
    assert calls["n"] == 1


def test_retry_policy_backoff_is_jittered_exponential():
    pol = resilience.RetryPolicy(base_delay=1.0, backoff=2.0, max_delay=16.0,
                                 jitter=0.25, seed=7)
    delays = [pol.delay(i) for i in range(4)]
    for i, d in enumerate(delays):
        nominal = min(1.0 * 2.0 ** i, 16.0)
        assert 0.75 * nominal <= d <= 1.25 * nominal
    # deterministic per seed
    pol2 = resilience.RetryPolicy(base_delay=1.0, backoff=2.0, max_delay=16.0,
                                  jitter=0.25, seed=7)
    assert delays == [pol2.delay(i) for i in range(4)]


# ---------------------------------------------------------------------------
# (a) transient faults retry and converge bit-exact
# ---------------------------------------------------------------------------
def test_transient_fault_mid_megabatch_retries_bitexact_data():
    key = jax.random.PRNGKey(11)
    clean = data_sim().WordErrorRate(64 * 8, key=key)
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=1),
    ])
    with resilience.policy_override(fast_policy()), plan.active():
        with telemetry.session(reset_metrics=True) as reg:
            faulted = data_sim().WordErrorRate(64 * 8, key=key)
            snap = reg.snapshot()
    assert faulted == clean
    assert snap["faultinject.injected"]["value"] == 1
    assert snap["resilience.retries"]["value"] == 1


def test_transient_fault_retries_bitexact_phenom():
    key = jax.random.PRNGKey(12)
    clean = phenom_sim().WordErrorRate(num_rounds=3, num_samples=64 * 4,
                                       key=key)
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="wer.phenl", kind="raise", after=0),
    ])
    with resilience.policy_override(fast_policy()), plan.active():
        with telemetry.session(reset_metrics=True) as reg:
            faulted = phenom_sim().WordErrorRate(num_rounds=3,
                                                 num_samples=64 * 4, key=key)
            snap = reg.snapshot()
    assert faulted == clean
    assert snap["resilience.retries"]["value"] == 1


# ---------------------------------------------------------------------------
# (b) deterministic faults fail fast without burning the backoff budget
# ---------------------------------------------------------------------------
def test_deterministic_fault_fails_fast():
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="deterministic",
                          count=99),
    ])
    # a policy whose backoff would be unmissable if it ran
    pol = fast_policy(max_attempts=5, base_delay=30.0)
    t0 = time.perf_counter()
    with resilience.policy_override(pol), plan.active():
        with telemetry.session(reset_metrics=True) as reg:
            with pytest.raises(faultinject.InjectedDeterministicFault):
                data_sim().WordErrorRate(64 * 4, key=jax.random.PRNGKey(0))
            snap = reg.snapshot()
    assert time.perf_counter() - t0 < 10.0  # no 30 s backoff was burned
    assert plan.hits("megabatch_dispatch") == 1  # exactly one attempt
    # counted once per policy layer that saw it (dispatch + engine)
    assert snap["resilience.deterministic_failures"]["value"] >= 1
    assert "resilience.retries" not in snap


def test_retry_budget_exhaustion_reraises():
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="wer.data", kind="raise", count=99),
    ])
    with resilience.policy_override(fast_policy(max_attempts=2)):
        with plan.active():
            with telemetry.session(reset_metrics=True) as reg:
                with pytest.raises(faultinject.InjectedFault):
                    data_sim().WordErrorRate(64 * 2,
                                             key=jax.random.PRNGKey(1))
                snap = reg.snapshot()
    assert snap["resilience.exhausted"]["value"] >= 1


# ---------------------------------------------------------------------------
# (c) watchdog fires on a stalled drain
# ---------------------------------------------------------------------------
def test_watchdog_fires_on_stalled_drain_and_run_completes():
    key = jax.random.PRNGKey(13)
    clean = data_sim(p=0.2).WordErrorRate(64 * 8, key=key, target_failures=10 ** 9)
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_drain", kind="stall", stall_s=2.0),
    ])
    pol = fast_policy(watchdog_s=0.2)
    with resilience.policy_override(pol), plan.active():
        with telemetry.session(reset_metrics=True) as reg:
            faulted = data_sim(p=0.2).WordErrorRate(64 * 8, key=key,
                                                    target_failures=10 ** 9)
            snap = reg.snapshot()
    assert faulted == clean
    assert snap["resilience.watchdog_fires"]["value"] >= 1
    assert snap["resilience.retries"]["value"] >= 1


def test_fetch_with_watchdog_direct():
    with pytest.raises(resilience.WatchdogTimeout):
        resilience.fetch_with_watchdog(lambda: time.sleep(1.0) or 1,
                                       label="t", timeout_s=0.05)
    assert resilience.fetch_with_watchdog(lambda: 42, label="t",
                                          timeout_s=5.0) == 42
    assert resilience.fetch_with_watchdog(lambda: 43, label="t") == 43


# ---------------------------------------------------------------------------
# (d) mid-cell resume reproduces the uninterrupted WER seed-for-seed
# ---------------------------------------------------------------------------
def test_mid_cell_resume_bitexact_data(tmp_path):
    key = jax.random.PRNGKey(21)
    shots = 64 * 16  # 16 batches = 8 megabatches at scan_chunk 2
    clean = data_sim().WordErrorRate(shots, key=key)

    ckpt_path = str(tmp_path / "cells.jsonl")
    cell_key = {"code": "rep3hgp", "noise": "data", "p": 0.05}

    # run 1: killed mid-cell after a few megabatches persisted progress
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=3,
                          count=99),
    ])
    progress = CellProgress(SweepCheckpoint(ckpt_path), cell_key, every=1)
    with resilience.policy_override(fast_policy(max_attempts=1)):
        with plan.active():
            with pytest.raises(faultinject.InjectedFault):
                data_sim().WordErrorRate(shots, key=key, progress=progress)

    # run 2: fresh process state, no faults — resumes from the cursor.
    # Megabatches 1-3 computed but the double-buffered drain only persisted
    # 1-2 before the kill (megabatch 3's carry never crossed the wire), so
    # the cursor sits at 4 batches and the resume replays the remaining 6
    # megabatches.
    ckpt = SweepCheckpoint(ckpt_path)
    st = ckpt.get_progress(cell_key)
    assert st is not None and st["batches_done"] == 4
    progress2 = CellProgress(ckpt, cell_key, every=1)
    with telemetry.session(reset_metrics=True) as reg:
        sim = data_sim()
        resumed = sim.WordErrorRate(shots, key=key, progress=progress2)
        snap = reg.snapshot()
    assert resumed == clean  # seed-for-seed identical
    assert snap["resilience.resumes"]["value"] == 1
    assert sim.last_dispatches == 6  # only the remaining 6 of 8 megabatches


def test_mid_cell_resume_bitexact_phenom(tmp_path):
    key = jax.random.PRNGKey(22)
    samples = 64 * 8
    clean = phenom_sim().WordErrorRate(num_rounds=3, num_samples=samples,
                                       key=key)
    ckpt_path = str(tmp_path / "cells.jsonl")
    cell_key = {"code": "rep3hgp", "noise": "phenl", "p": 0.04}
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=2,
                          count=99),
    ])
    progress = CellProgress(SweepCheckpoint(ckpt_path), cell_key)
    with resilience.policy_override(fast_policy(max_attempts=1)):
        with plan.active():
            with pytest.raises(faultinject.InjectedFault):
                phenom_sim().WordErrorRate(num_rounds=3, num_samples=samples,
                                           key=key, progress=progress)
    ckpt = SweepCheckpoint(ckpt_path)
    # double-buffered drain: only megabatch 1 (2 batches) was persisted
    # before the kill on megabatch 3's dispatch
    assert ckpt.get_progress(cell_key)["batches_done"] == 2
    resumed = phenom_sim().WordErrorRate(
        num_rounds=3, num_samples=samples, key=key,
        progress=CellProgress(ckpt, cell_key))
    assert resumed == clean


def test_resume_with_crossed_target_does_not_overrun(tmp_path):
    """A cursor persisted at the early-stop crossing (run killed between
    the crossing megabatch's save and the cell record) must resume to the
    SAME (failures, shots) — not stream another megabatch."""
    key = jax.random.PRNGKey(24)
    ckpt = SweepCheckpoint(str(tmp_path / "cells.jsonl"))
    cell_key = {"code": "rep3hgp", "noise": "data", "p": 0.2}
    sim = data_sim(p=0.2)
    first = sim.WordErrorRate(64 * 16, key=key, target_failures=1,
                              progress=CellProgress(ckpt, cell_key))
    assert ckpt.get_progress(cell_key) is not None  # cursor left behind
    # "resume" from the leftover cursor (as after a kill before put):
    sim2 = data_sim(p=0.2)
    resumed = sim2.WordErrorRate(64 * 16, key=key, target_failures=1,
                                 progress=CellProgress(ckpt, cell_key))
    assert resumed == first
    assert sim2.last_dispatches == 0  # nothing re-streamed


def test_resume_ignores_stale_fingerprint(tmp_path):
    key = jax.random.PRNGKey(23)
    ckpt_path = str(tmp_path / "cells.jsonl")
    cell_key = {"code": "rep3hgp", "noise": "data", "p": 0.05}
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=2,
                          count=99),
    ])
    progress = CellProgress(SweepCheckpoint(ckpt_path), cell_key)
    with resilience.policy_override(fast_policy(max_attempts=1)):
        with plan.active():
            with pytest.raises(faultinject.InjectedFault):
                data_sim().WordErrorRate(64 * 16, key=key, progress=progress)
    # different key => different stream => the cursor must NOT be honored
    ckpt = SweepCheckpoint(ckpt_path)
    other_key = jax.random.PRNGKey(99)
    clean = data_sim().WordErrorRate(64 * 16, key=other_key)
    with pytest.warns(UserWarning, match="fingerprint"):
        resumed = data_sim().WordErrorRate(
            64 * 16, key=other_key, progress=CellProgress(ckpt, cell_key))
    assert resumed == clean


def test_combined_kill_plus_stall_plan_bitexact_both_engines(tmp_path):
    """The acceptance scenario: one plan with a kill mid-megabatch AND a
    drain stall; a data_error and a phenom WER run both complete bit-exact
    vs the fault-free run, with retry/watchdog counters in the snapshot."""
    pol = fast_policy(max_attempts=4, watchdog_s=0.2)

    def make_plan():
        return faultinject.FaultPlan([
            faultinject.Fault(site="megabatch_dispatch", kind="raise",
                              after=1),
            faultinject.Fault(site="megabatch_drain", kind="stall",
                              stall_s=2.0),
        ])

    key = jax.random.PRNGKey(41)
    # data engine: target_failures engages the streamed (drained) path
    clean_d = data_sim().WordErrorRate(64 * 8, key=key,
                                       target_failures=10 ** 9)
    with resilience.policy_override(pol), make_plan().active():
        with telemetry.session(reset_metrics=True) as reg:
            faulted_d = data_sim().WordErrorRate(64 * 8, key=key,
                                                 target_failures=10 ** 9)
            snap_d = reg.snapshot()
    assert faulted_d == clean_d
    assert snap_d["faultinject.injected"]["value"] == 2
    assert snap_d["resilience.retries"]["value"] >= 2
    assert snap_d["resilience.watchdog_fires"]["value"] >= 1

    # phenom engine: a progress cursor engages the streamed path
    clean_p = phenom_sim().WordErrorRate(num_rounds=3, num_samples=64 * 8,
                                         key=key)
    ckpt = SweepCheckpoint(str(tmp_path / "cells.jsonl"))
    with resilience.policy_override(pol), make_plan().active():
        with telemetry.session(reset_metrics=True) as reg:
            faulted_p = phenom_sim().WordErrorRate(
                num_rounds=3, num_samples=64 * 8, key=key,
                progress=CellProgress(ckpt, {"cell": "phenl"}))
            snap_p = reg.snapshot()
    assert faulted_p == clean_p
    assert snap_p["resilience.retries"]["value"] >= 2
    assert snap_p["resilience.watchdog_fires"]["value"] >= 1


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------
def test_degradation_ladder_steps_packed_to_dense_bitexact():
    key = jax.random.PRNGKey(31)
    clean = data_sim().WordErrorRate(64 * 4, key=key)
    # every engine-level attempt faults twice => degrade_after=1 steps the
    # ladder after the first failure; the packed->dense rung is bit-exact
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="wer.data", kind="raise", count=2),
    ])
    pol = fast_policy(max_attempts=4, degrade_after=1)
    with resilience.policy_override(pol), plan.active():
        with telemetry.session(reset_metrics=True) as reg:
            sim = data_sim()
            degraded = sim.WordErrorRate(64 * 4, key=key)
            snap = reg.snapshot()
    assert degraded == clean
    assert not sim._packed  # the ladder actually stepped
    assert snap["resilience.degrades"]["value"] >= 1


def test_degradation_ladder_order_data():
    sim = data_sim()
    assert sim._degrade_once() == "packed->dense"
    assert sim._packed is False
    assert sim._degrade_once() is None  # CPU backend: ladder exhausted
    sim2 = phenom_sim()
    assert sim2._degrade_once() == "packed->dense"
    assert sim2._degrade_once() is None


# ---------------------------------------------------------------------------
# SweepCheckpoint hardening satellites
# ---------------------------------------------------------------------------
def test_checkpoint_skips_corrupt_trailing_line(tmp_path):
    path = str(tmp_path / "sweep.jsonl")
    good = {"key": {"p": 0.01}, "record": {"wer": 0.5}}
    with open(path, "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write('{"key": {"p": 0.02}, "record": {"wer"')  # torn mid-append
    with telemetry.session(reset_metrics=True) as reg:
        with pytest.warns(UserWarning, match="corrupt checkpoint line"):
            ckpt = SweepCheckpoint(path)
        snap = reg.snapshot()
    assert len(ckpt) == 1
    assert ckpt.get({"p": 0.01}) == {"wer": 0.5}
    assert ckpt.get({"p": 0.02}) is None
    assert snap["ckpt.corrupt_lines"]["value"] == 1
    # the resume still works: the lost cell simply reruns
    ckpt.put({"p": 0.02}, {"wer": 0.25})
    ckpt2 = SweepCheckpoint(path)  # trailing garbage now mid-file; still ok
    assert len(ckpt2) == 2


def test_checkpoint_write_kill_injection_roundtrip(tmp_path):
    path = str(tmp_path / "sweep.jsonl")
    ckpt = SweepCheckpoint(path)
    ckpt.put({"p": 0.01}, {"wer": 0.5})
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="sweep_ckpt_put", kind="truncate"),
    ])
    with plan.active():
        with pytest.raises(faultinject.InjectedFault):
            ckpt.put({"p": 0.02}, {"wer": 0.25})
    # the SAME (surviving) process appends again: the torn tail must not
    # corrupt the next record (the writer starts it on a fresh line)
    ckpt.put({"p": 0.03}, {"wer": 0.125})
    with pytest.warns(UserWarning, match="corrupt checkpoint line"):
        ckpt2 = SweepCheckpoint(path)
    assert len(ckpt2) == 2
    assert ckpt2.get({"p": 0.01}) == {"wer": 0.5}
    assert ckpt2.get({"p": 0.03}) == {"wer": 0.125}
    assert ckpt2.get({"p": 0.02}) is None  # the killed append is lost


def test_checkpoint_progress_records_roundtrip(tmp_path):
    path = str(tmp_path / "sweep.jsonl")
    ckpt = SweepCheckpoint(path)
    key = {"p": 0.01}
    ckpt.put_progress(key, {"v": 2, "batches_done": 4, "failures": 1,
                            "min_w": 9, "fingerprint": {"k": 1}})
    ckpt.put_progress(key, {"v": 2, "batches_done": 8, "failures": 3,
                            "min_w": 9, "fingerprint": {"k": 1}})
    # latest progress line wins on reload; cell is NOT finished
    ckpt2 = SweepCheckpoint(path)
    assert key not in ckpt2 and len(ckpt2) == 0
    assert ckpt2.get_progress(key)["batches_done"] == 8
    # a finished cell supersedes its progress
    ckpt2.put(key, {"wer": 0.1})
    ckpt3 = SweepCheckpoint(path)
    assert ckpt3.get(key) == {"wer": 0.1}
    assert ckpt3.get_progress(key) is None


def test_sweep_eval_wer_resumes_through_checkpoint(tmp_path):
    """End-to-end: a CodeFamily sweep killed mid-cell resumes through the
    SAME checkpoint file and produces the uninterrupted result."""
    from qldpc_fault_tolerance_tpu.decoders import (
        BPOSD_Decoder_Class,
        BP_Decoder_Class,
    )
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    # plain-BP decoder2 keeps the data engine on the pure-device megabatch
    # path (host-postprocess paths have no mid-cell cursor)
    fam_args = dict(
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(6, "minimum_sum", 0.625),
        batch_size=64, seed=1)
    codes = [hgp(rep_code(3), rep_code(3))]
    # 32 batches of 64 at the engine's default scan_chunk 8 = 4 megabatches
    shots = 64 * 32
    clean = CodeFamily(codes, **fam_args).EvalWER(
        "data", "Total", [0.05], num_samples=shots, if_plot=False)

    path = str(tmp_path / "sweep.jsonl")
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=2,
                          count=99),
    ])
    with resilience.policy_override(fast_policy(max_attempts=1)):
        with plan.active():
            with pytest.raises(faultinject.InjectedFault):
                CodeFamily(codes, **fam_args).EvalWER(
                    "data", "Total", [0.05], num_samples=shots,
                    if_plot=False, checkpoint=SweepCheckpoint(path))
    resumed = CodeFamily(codes, **fam_args).EvalWER(
        "data", "Total", [0.05], num_samples=shots, if_plot=False,
        checkpoint=SweepCheckpoint(path))
    np.testing.assert_array_equal(resumed, clean)


# ---------------------------------------------------------------------------
# guard: no bare sleeps / ad-hoc retry loops outside utils/resilience.py
# ---------------------------------------------------------------------------
def test_no_bare_sleep_or_retry_loops_in_library():
    """Thin shim (ISSUE 12): the PR-7 grep guard migrated into qldpc-lint
    as rule R102 so guard logic lives in exactly one engine.  This asserts
    the rule stays enabled with the same scope (library + scripts/parity.py,
    utils/resilience.py exempt); enforcement over the real tree is
    tests/test_analysis.py's full-package gate."""
    from qldpc_fault_tolerance_tpu import analysis

    rules = {r.id: r for r in analysis.default_rules()}
    assert "R102" in rules, "bare-sleep rule dropped from default set"
    r102 = rules["R102"]
    assert not r102.applies("qldpc_fault_tolerance_tpu/utils/resilience.py")
    assert r102.applies("qldpc_fault_tolerance_tpu/sweep/family.py")
    assert r102.applies("scripts/parity.py")
    # the migrated rule fires on what the grep guard fired on
    from qldpc_fault_tolerance_tpu.analysis import (AnalysisContext,
                                                    SourceModule,
                                                    run_analysis)

    mod = SourceModule.parse(
        "scripts/parity.py",
        "import time\n\ndef f():\n    for attempt in range(5):\n"
        "        time.sleep(1.0)\n")
    res = run_analysis([mod], [r102], ctx=AnalysisContext([mod]))
    assert {f.rule for f in res.findings} == {"R102"}
    assert len(res.findings) == 2


# ---------------------------------------------------------------------------
# env-var plan activation (subprocess/CI path)
# ---------------------------------------------------------------------------
def test_env_var_plan_json_roundtrip():
    plan = faultinject.FaultPlan.from_json(
        '{"seed": 3, "faults": [{"site": "wer.data", "kind": "raise", '
        '"after": 1, "count": 2}]}')
    assert plan.seed == 3
    f = plan.faults[0]
    assert (f.site, f.kind, f.after, f.count) == ("wer.data", "raise", 1, 2)
    assert not f.matches(1) and f.matches(2) and f.matches(3) \
        and not f.matches(4)
    # bare-list form
    plan2 = faultinject.FaultPlan.from_json('[{"site": "s", "kind": "stall"}]')
    assert plan2.faults[0].kind == "stall"


def test_env_plan_activation(monkeypatch):
    """QLDPC_FAULT_PLAN installs a plan on the first site() call — the
    subprocess/CI activation path."""
    monkeypatch.setenv("QLDPC_FAULT_PLAN",
                       '[{"site": "env_site", "kind": "raise"}]')
    monkeypatch.setattr(faultinject, "_ENV_CHECKED", False)
    monkeypatch.setattr(faultinject, "_ACTIVE", None)
    faultinject.site("other_site")  # no fault for other sites
    with pytest.raises(faultinject.InjectedFault):
        faultinject.site("env_site")
    faultinject.site("env_site")  # count=1: fired once, then inert
    faultinject.deactivate()
