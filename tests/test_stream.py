"""Streaming space-time decode (ISSUE 16): windowed overlap-commit
sessions with O(window) cost per committed cycle.

The correctness gate: windowed commits are BIT-EXACT vs the whole-history
space-time decode on the same shots, for both the phenomenological and the
circuit-level engines — the streaming step is the batch engines' own
window-commit body, extracted, so equality is structural, and these tests
pin it numerically.  Plus: the fixed-shape step program retraces zero
times across >= 100 consecutive window steps; the stream wire framing
round-trips on both codecs and answers malformed chunks with structured
errors (validate_event checks the new v6 stream events); the StreamSession
ledger enforces exactly-once commits (replay / stale / gap / busy); and
the window-count helpers pin the reference's float-division and
silent-truncation boundary bugs."""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BPOSD_Decoder,
    ST_BP_Decoder_Circuit,
    ST_BP_Decoder_Class,
    ST_BPOSD_Decoder_Circuit,
    ST_BP_Decoder_syndrome,
)
from qldpc_fault_tolerance_tpu.serve import (
    ContinuousBatcher,
    DecodeClient,
    DecodeSession,
    start_server_thread,
)
from qldpc_fault_tolerance_tpu.serve.session import (
    StreamProfile,
    StreamProtocolError,
    StreamSession,
)
from qldpc_fault_tolerance_tpu.serve import wire
from qldpc_fault_tolerance_tpu.sim import (
    CircuitStreamDriver,
    CodeSimulator_Circuit_SpaceTime,
    CodeSimulator_Phenon_SpaceTime,
    PhenomStreamDriver,
    st_round_counts,
    st_window_count,
)
from qldpc_fault_tolerance_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


CODE = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
ST_CLS = ST_BP_Decoder_Class(2, "minimum_sum", 0.625)


# ---------------------------------------------------------------------------
# window-count helpers: the reference's boundary bugs, pinned
# ---------------------------------------------------------------------------
def test_st_round_counts_matches_reference_small():
    # phenom grouping: ceil-to-window + odd-total contract
    assert st_round_counts(1, 2) == (1, 1)
    assert st_round_counts(2, 2) == (1, 1)
    assert st_round_counts(3, 2) == (2, 3)
    assert st_round_counts(7, 3) == (3, 7)
    assert st_round_counts(8, 3) == (3, 7)


def test_st_round_counts_no_float_drift_at_large_counts():
    # the reference computes int((num_cycles - 1) / num_rep + 1): above
    # 2**53 the float division drifts a full round.  The integer helper
    # must not.
    num_cycles = 36028797018963967  # (2**55 // 3) * 3 + 1
    exact = (num_cycles - 1) // 3 + 1
    assert int((num_cycles - 1) / 3 + 1) != exact  # the bug being pinned
    assert st_round_counts(num_cycles, 3)[0] == exact


def test_st_window_count_exact_and_rejects_non_multiple():
    assert st_window_count(7, 3) == 2
    assert st_window_count(201, 200) == 1
    with pytest.raises(ValueError):
        st_window_count(8, 3)
    # the reference's abs(rounds - int(rounds)) < 1e-2 assert PASSES for
    # num_rep=200, num_cycles=202 (201/200 = 1.005) and silently drops a
    # cycle; the helper must refuse instead
    with pytest.raises(ValueError):
        st_window_count(202, 200)


def test_st_count_helpers_validate():
    for bad in (0, -1):
        with pytest.raises(ValueError):
            st_round_counts(bad, 2)
        with pytest.raises(ValueError):
            st_round_counts(5, bad)
        with pytest.raises(ValueError):
            st_window_count(bad, 2)


# ---------------------------------------------------------------------------
# phenom streaming: bit-exact vs the batch engine, window by window
# ---------------------------------------------------------------------------
def _phenom_st_sim(num_rep, batch_size=16, p=0.03, q=0.03):
    dec1_z = ST_BP_Decoder_syndrome(CODE.hx, p_data=p, p_synd=q, max_iter=12,
                                    num_rep=num_rep)
    dec1_x = ST_BP_Decoder_syndrome(CODE.hz, p_data=p, p_synd=q, max_iter=12,
                                    num_rep=num_rep)
    dec2_z = BPOSD_Decoder(CODE.hx, np.full(CODE.N, p), max_iter=12,
                           osd_order=4)
    dec2_x = BPOSD_Decoder(CODE.hz, np.full(CODE.N, p), max_iter=12,
                           osd_order=4)
    return CodeSimulator_Phenon_SpaceTime(
        code=CODE, decoder1_x=dec1_x, decoder1_z=dec1_z,
        decoder2_x=dec2_x, decoder2_z=dec2_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], q=q, num_rep=num_rep,
        batch_size=batch_size,
    )


def test_phenom_stream_carry_bitexact_vs_batch():
    """After k streamed windows the carry equals the batch fori_loop's
    carry after k+1 rounds (the batch runs num_rounds-1 noisy windows) —
    same key schedule, same window body, bit for bit."""
    sim = _phenom_st_sim(num_rep=3, batch_size=16)
    key = jax.random.PRNGKey(42)
    for num_rounds in (1, 2, 4):
        drv = PhenomStreamDriver(sim, batch_size=16).reset(key)
        for _ in range(num_rounds - 1):
            drv.step()
        ref_x, ref_z = sim._noisy_rounds_device(key, 16, num_rounds)
        got_x, got_z = drv.carry
        assert np.array_equal(np.asarray(got_x), np.asarray(ref_x))
        assert np.array_equal(np.asarray(got_z), np.asarray(ref_z))
        assert drv.committed_cycles == (num_rounds - 1) * 3


def test_phenom_stream_finalize_bitexact_vs_run_batch():
    """End to end: streamed windows + finalize == run_batch on the same
    key, including the num_rounds=1 boundary (ZERO noisy windows — the
    final perfect round runs on an all-zero carry; an off-by-one in the
    boundary-syndrome handling would flip parity here first)."""
    sim = _phenom_st_sim(num_rep=3, batch_size=16)
    for num_rounds in (1, 2, 3):
        key = jax.random.PRNGKey(100 + num_rounds)
        ref = sim.run_batch(key, num_rounds, 16)
        k_rounds, k_final = jax.random.split(key)
        drv = PhenomStreamDriver(sim, batch_size=16).reset(k_rounds)
        for _ in range(num_rounds - 1):
            drv.step()
        got = drv.finalize(k_final)
        assert np.array_equal(got, ref), f"num_rounds={num_rounds}"


# ---------------------------------------------------------------------------
# circuit streaming: bit-exact vs the whole-history window scan
# ---------------------------------------------------------------------------
def _circuit_st_sim(num_cycles=7, num_rep=3, batch_size=8, p_cx=0.004):
    ep = {"p_i": 0.0, "p_state_p": 0.0, "p_m": 0.0, "p_CX": p_cx,
          "p_idling_gate": 0.0}
    sim = CodeSimulator_Circuit_SpaceTime(
        code=CODE, p=p_cx, num_cycles=num_cycles, num_rep=num_rep,
        error_params=ep, eval_logical_type="Z", batch_size=batch_size,
        seed=11,
    )
    sim._generate_circuit()
    sim._generate_circuit_graph()
    g = sim.circuit_graph
    ps1 = np.clip(np.asarray(g["channel_ps1"], float), 1e-9, 0.49)
    ps2 = np.clip(np.asarray(g["channel_ps2"], float), 1e-9, 0.49)
    sim.decoder1_z = ST_BP_Decoder_Circuit(g["h1"], ps1, max_iter=12)
    sim.decoder2_z = ST_BPOSD_Decoder_Circuit(g["h2"], ps2, max_iter=12,
                                              osd_order=4)
    return sim


def test_circuit_stream_bitexact_vs_windows_decode():
    sim = _circuit_st_sim(batch_size=8)
    key = jax.random.PRNGKey(7)
    bs = 8
    ref_obs, ref_log, ref_syn, ref_cor, _ = (
        sim._sample_and_decode_windows(key, bs))
    # the same shots, fed through the streaming driver window by window
    cfg = sim._cfg(bs)
    state = sim._dev_state
    m = sim.num_checks
    dets, obs = cfg[6]._sample_impl(key, state["probs"], bs)
    hist = np.asarray(dets).reshape(bs, sim.num_cycles, m)
    windows = hist[:, : sim.num_rounds * sim.num_rep].reshape(
        bs, sim.num_rounds, sim.num_rep * m)
    drv = CircuitStreamDriver(sim, batch_size=bs)
    for j in range(sim.num_rounds):
        drv.step(windows[:, j])
    got_log, got_syn, got_cor, _ = drv.finalize(hist[:, -1])
    assert np.array_equal(np.asarray(obs), np.asarray(ref_obs))
    assert np.array_equal(np.asarray(got_log), np.asarray(ref_log))
    assert np.array_equal(np.asarray(got_syn), np.asarray(ref_syn))
    assert np.array_equal(np.asarray(got_cor), np.asarray(ref_cor))
    assert drv.committed_cycles == sim.num_rounds * sim.num_rep


def test_circuit_stream_rejects_bad_window_shape():
    sim = _circuit_st_sim(batch_size=8)
    drv = CircuitStreamDriver(sim, batch_size=8)
    with pytest.raises(ValueError):
        drv.step(np.zeros((8, 7), np.uint8))


# ---------------------------------------------------------------------------
# zero warm-path retraces across >= 100 consecutive window steps
# ---------------------------------------------------------------------------
def _st_session(name="st_w3", w=3, lanes=8):
    return DecodeSession(
        name, decoder_class=ST_CLS,
        params={"h": CODE.hx, "p_data": 0.01, "p_syndrome": True,
                "num_rep": w},
        buckets=(lanes,))


def test_stream_session_100_steps_zero_retraces():
    """The serving stream path (StreamSession ledger over the session's
    AOT program) is one fixed-shape executable: >= 100 consecutive window
    steps retrace nothing after the warmup step."""
    telemetry.enable()
    sess = _st_session()
    stream = StreamSession("st-test", sess, lanes=8)
    rng = np.random.default_rng(3)
    width = sess.syndrome_width

    def one_step(seq):
        chunk = (rng.random((8, width)) < 0.05).astype(np.uint8)
        action, staged = stream.prepare(seq, chunk)
        assert action == "decode"
        out = sess.decode(staged)
        return stream.commit(seq, out.corrections, converged=out.converged)

    one_step(1)  # warmup: first decode compiles the AOT program
    warm = telemetry.compile_stats().get("jax.retraces", 0)
    for seq in range(2, 103):
        payload = one_step(seq)
        assert payload["committed"] == seq
    assert telemetry.compile_stats().get("jax.retraces", 0) == warm
    assert stream.committed == 102
    assert stream.committed_cycles == 102 * 3


def test_phenom_stream_driver_steps_zero_retraces():
    telemetry.enable()
    sim = _phenom_st_sim(num_rep=2, batch_size=8)
    drv = PhenomStreamDriver(sim, batch_size=8).reset(jax.random.PRNGKey(5))
    drv.step()  # compiles the fixed-shape step program
    warm = telemetry.compile_stats().get("jax.retraces", 0)
    for _ in range(100):
        drv.step()
    assert telemetry.compile_stats().get("jax.retraces", 0) == warm


# ---------------------------------------------------------------------------
# StreamSession ledger: exactly-once semantics
# ---------------------------------------------------------------------------
def test_stream_session_replay_stale_gap_busy():
    sess = _st_session()
    stream = StreamSession("st-u", sess, lanes=4)
    width = sess.syndrome_width
    rng = np.random.default_rng(0)
    chunk = (rng.random((4, width)) < 0.05).astype(np.uint8)

    # commit without prepare: the ledger refuses
    with pytest.raises(StreamProtocolError) as ei:
        stream.commit(1, np.zeros((4, CODE.N), np.uint8))
    assert ei.value.code == "commit"

    action, staged = stream.prepare(1, chunk)
    assert action == "decode"
    # concurrent second transmission of the in-flight seq: busy
    with pytest.raises(StreamProtocolError) as ei:
        stream.prepare(1, chunk)
    assert ei.value.code == "busy"
    out = sess.decode(staged)
    payload = stream.commit(1, out.corrections)
    assert payload["committed"] == 1

    # replay of the committed seq: served from cache, not re-prepared
    action, cached = stream.prepare(1, chunk)
    assert action == "replay"
    assert np.array_equal(np.asarray(cached["corrections"]),
                          np.asarray(payload["corrections"]))

    stream.prepare(2, chunk)
    stream.commit(2, out.corrections)
    # seq already superseded: stale (no cached payload that far back)
    with pytest.raises(StreamProtocolError) as ei:
        stream.prepare(1, chunk)
    assert ei.value.code == "stale"
    # skipping ahead: gap
    with pytest.raises(StreamProtocolError) as ei:
        stream.prepare(9, chunk)
    assert ei.value.code == "gap"
    # wrong lane shape
    with pytest.raises(StreamProtocolError) as ei:
        stream.prepare(3, chunk[:2])
    assert ei.value.code == "shape"
    stream.close()
    with pytest.raises(StreamProtocolError) as ei:
        stream.prepare(3, chunk)
    assert ei.value.code == "closed"


def test_stream_session_frame_fold_is_xor_of_commits():
    sess = _st_session()
    stream = StreamSession("st-f", sess, lanes=4)
    width = sess.syndrome_width
    rng = np.random.default_rng(1)
    acc = np.zeros((4, CODE.N), np.uint8)
    for seq in (1, 2, 3):
        chunk = (rng.random((4, width)) < 0.05).astype(np.uint8)
        _, staged = stream.prepare(seq, chunk)
        out = sess.decode(staged)
        stream.commit(seq, out.corrections)
        acc ^= np.asarray(out.corrections, np.uint8)
    assert np.array_equal(stream.frame(), acc)


def test_stream_session_circuit_mode_carry_matches_driver():
    """A circuit-profile StreamSession (space_cor/log_mat) folds commits
    exactly like the sim-level CircuitStreamDriver on the same windows."""
    sim = _circuit_st_sim(batch_size=4)
    drv = CircuitStreamDriver(sim, batch_size=4)  # also ensures device state
    m = sim.num_checks
    w = sim.num_rep
    sess = DecodeSession(
        "st_circ", decoder=sim.decoder1_z, buckets=(4,))
    stream = StreamSession(
        "st-c", sess, lanes=4,
        # StreamSession folds cor @ space_cor / cor @ log_mat — the same
        # transposed matrices the device state carries
        space_cor=np.asarray(sim.h1_space_cor).T.astype(np.uint8),
        log_mat=np.asarray(sim.circuit_graph["L1"]).T.astype(np.uint8),
        cycles_per_window=w)
    rng = np.random.default_rng(2)
    for seq in (1, 2):
        window = (rng.random((4, w * m)) < 0.02).astype(np.uint8)
        _, staged = stream.prepare(seq, window)
        out = sess.decode(staged)
        stream.commit(seq, out.corrections)
        drv.step(window)
    total_space, total_log = drv.carry
    snap = stream.snapshot()
    assert snap["committed"] == 2
    assert snap["committed_cycles"] == 2 * w
    assert np.array_equal(stream._carry_space, np.asarray(total_space))
    assert np.array_equal(stream._carry_log, np.asarray(total_log))


# ---------------------------------------------------------------------------
# wire framing: round trip + malformed-chunk structured errors
# ---------------------------------------------------------------------------
def test_stream_chunk_frame_round_trip_both_codecs():
    rng = np.random.default_rng(4)
    chunk = (rng.random((6, 36)) < 0.3).astype(np.uint8)
    msg = {"op": "stream_chunk", "stream": "st-0001", "seq": 3,
           "chunk": chunk, "id": "r-1"}
    for codec in (wire.WIRE_CODEC_JSON, wire.WIRE_CODEC_PACKED):
        frame = wire.encode_stream_chunk_frame(dict(msg), codec)
        got = wire.decode_payload(frame[wire.HEADER.size:])
        assert got["op"] == "stream_chunk"
        assert got["stream"] == "st-0001"
        assert got["seq"] == 3
        assert np.array_equal(np.asarray(got["chunk"], np.uint8), chunk)


def test_stream_chunk_binary_malformed_structured_errors():
    rng = np.random.default_rng(5)
    chunk = (rng.random((2, 18)) < 0.3).astype(np.uint8)
    good = wire.encode_stream_chunk_frame(
        {"op": "stream_chunk", "stream": "s", "seq": 1, "chunk": chunk,
         "id": "rid-7"}, wire.WIRE_CODEC_PACKED)[wire.HEADER.size:]

    # missing header fields
    for drop in ("stream", "seq"):
        frame = wire.encode_stream_chunk_frame(
            {k: v for k, v in
             {"op": "stream_chunk", "stream": "s", "seq": 1,
              "chunk": chunk, "id": "rid-7"}.items() if k != drop},
            wire.WIRE_CODEC_PACKED)[wire.HEADER.size:]
        with pytest.raises(wire.WireCodecError) as ei:
            wire.decode_payload(frame)
        assert ei.value.request_id == "rid-7"

    # non-positive / non-int seq
    for bad_seq in (0, -1, "3", True):
        frame = wire.encode_stream_chunk_frame(
            {"op": "stream_chunk", "stream": "s", "seq": bad_seq,
             "chunk": chunk, "id": "rid-7"}, wire.WIRE_CODEC_PACKED)
        with pytest.raises(wire.WireCodecError):
            wire.decode_payload(frame[wire.HEADER.size:])

    # truncated body: the packed plane no longer matches shots*width
    with pytest.raises(wire.WireCodecError):
        wire.decode_payload(good[:-1])


# ---------------------------------------------------------------------------
# live serve path: open / chunk / commit / close, both codecs
# ---------------------------------------------------------------------------
def test_server_stream_end_to_end_bitexact_and_replayed():
    telemetry.enable()
    sess = _st_session("st_w3", w=3, lanes=4)
    bat = ContinuousBatcher({"st_w3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    handle = start_server_thread(bat)
    host, port = handle.address
    try:
        for codec in (2, 1):
            cli = DecodeClient(host, port, codec=codec, reconnect=True)
            try:
                ack = cli.stream_open("st_w3", lanes=4)
                sid = ack["stream"]
                assert ack["cycles_per_window"] == 3
                rng = np.random.default_rng(6)
                width = ack["width"]
                offline = ST_CLS.GetDecoder(
                    {"h": CODE.hx, "p_data": 0.01, "p_syndrome": True,
                     "num_rep": 3})
                frame = np.zeros((4, CODE.N), np.uint8)
                for seq in (1, 2, 3):
                    chunk = (rng.random((4, width)) < 0.05).astype(np.uint8)
                    res = cli.stream_step(sid, seq, chunk)
                    assert res.get("ok"), res
                    cor = np.asarray(res["corrections"], np.uint8)
                    ref = offline.decode_batch(chunk.reshape(4, 3, -1))
                    assert np.array_equal(cor, np.asarray(ref, np.uint8))
                    frame ^= cor
                    assert res["committed"] == seq
                    assert res["committed_cycles"] == seq * 3
                    # a retry of the committed seq replays from cache —
                    # never re-decodes, never re-folds
                    rep = cli.stream_step(sid, seq, chunk)
                    assert rep.get("replayed"), rep
                    assert np.array_equal(
                        np.asarray(rep["corrections"], np.uint8), cor)
                bad = cli.stream_step(sid, 99, chunk)
                assert bad.get("stream_error") == "gap"
                wm = cli.stream_commit(sid)
                assert wm["committed"] == 3
                fin = cli.stream_commit(sid, close=True)
                assert fin.get("closed")
                gone = cli.stream_step(sid, 4, chunk)
                assert gone.get("stream_unknown"), gone
            finally:
                cli.close()
    finally:
        handle.stop(drain=True)
    snap = telemetry.snapshot()

    def val(name):
        return snap.get(name, {}).get("value", 0)

    assert val("stream.opens") == 2
    assert val("stream.commits") == 6
    assert val("stream.cycles") == 18
    assert val("stream.replays") == 6


def test_server_stream_open_unknown_profile_is_structured_error():
    sess = _st_session("st_w3", w=3, lanes=4)
    bat = ContinuousBatcher({"st_w3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    handle = start_server_thread(bat)
    host, port = handle.address
    try:
        with DecodeClient(host, port, codec=1) as cli:
            with pytest.raises(RuntimeError, match="unknown stream"):
                cli.stream_open("nope", lanes=4)
    finally:
        handle.stop(drain=True)


def test_server_stream_profile_registration():
    """A registered StreamProfile names its backing session; hello
    advertises stream support."""
    sess = _st_session("st_w3", w=3, lanes=4)
    bat = ContinuousBatcher({"st_w3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    handle = start_server_thread(
        bat, stream_profiles={
            "phenom_frame": StreamProfile(session="st_w3")})
    host, port = handle.address
    try:
        with DecodeClient(host, port, codec=1) as cli:
            ack = cli.stream_open("phenom_frame", lanes=2)
            assert ack["ok"] and ack["width"] == sess.syndrome_width
            cli.stream_commit(ack["stream"], close=True)
    finally:
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# v6 stream events: schema-validated, back-compat chain intact
# ---------------------------------------------------------------------------
def test_stream_events_validate_and_v6_chain():
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    sess = _st_session("st_w3", w=3, lanes=2)
    bat = ContinuousBatcher({"st_w3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    handle = start_server_thread(bat)
    host, port = handle.address
    try:
        with DecodeClient(host, port, codec=1) as cli:
            ack = cli.stream_open("st_w3", lanes=2)
            cli.stream_commit(ack["stream"], close=True)
    finally:
        handle.stop(drain=True)
        telemetry.remove_sink(sink)
    kinds = {}
    for rec in sink.records:
        kinds.setdefault(rec["kind"], rec)
    assert "stream_open" in kinds and "stream_close" in kinds
    for kind in ("stream_open", "stream_close"):
        assert telemetry.validate_event(kinds[kind]) == []
    # a synthetic shed record validates too (the live shed path is
    # exercised in test_chaos.py)
    shed = dict(kind="stream_shed", ts=0.0, stream="st-0001",
                tenant="default", committed=3, burn_rate=9.0,
                signal="shed")
    assert telemetry.validate_event(shed) == []
    # the frozen-version chain: v6 kinds exist in the registry, and every
    # frozen set up the chain still validates (append-never)
    assert telemetry._V6_EVENT_KINDS == frozenset(
        {"stream_open", "stream_close", "stream_shed"})
    for ks in (telemetry._V1_EVENT_KINDS, telemetry._V2_EVENT_KINDS,
               telemetry._V3_EVENT_KINDS, telemetry._V4_EVENT_KINDS,
               telemetry._V5_EVENT_KINDS, telemetry._V6_EVENT_KINDS):
        assert ks <= set(telemetry.EVENT_SCHEMAS)
    assert telemetry.EVENT_SCHEMA_VERSION >= 6
