"""Tests for the circuit layer: scheduling, IR round-trip, noise plugin,
Pauli-frame sampler, and DEM derivation."""
import numpy as np
import jax
import pytest

from qldpc_fault_tolerance_tpu.circuits import (
    AddCXError,
    Circuit,
    ColorationCircuit,
    FrameSampler,
    GenCorrecHyperGraph,
    GenFaultHyperGraph,
    RandomCircuit,
    detector_error_model,
    target_rec,
    validate_schedule,
)
from qldpc_fault_tolerance_tpu.codes import hgp, rep_code


@pytest.fixture(scope="module")
def surface3():
    return hgp(rep_code(3), rep_code(3))


# ------------------------------------------------------------- scheduling
def test_coloration_schedule_valid(surface3):
    for H in (surface3.hx, surface3.hz):
        sched = ColorationCircuit(H)
        validate_schedule(H, sched, require_disjoint_qubits=True)


def test_coloration_depth_bounded(surface3):
    H = surface3.hx
    sched = ColorationCircuit(H)
    delta = int(max(H.sum(1).max(), H.sum(0).max()))
    assert len(sched) <= delta + 2  # padded-graph degree


def test_random_schedule_valid(surface3):
    H = surface3.hz
    sched = RandomCircuit(H)
    # random schedules may reuse a qubit within a timestep
    validate_schedule(H, sched, require_disjoint_qubits=False)
    assert len(sched) == int(H.sum(1).max())


def test_random_schedule_deterministic(surface3):
    a = RandomCircuit(surface3.hx)
    b = RandomCircuit(surface3.hx)
    assert a == b


# --------------------------------------------------------------------- IR
def test_ir_text_round_trip():
    c = Circuit()
    c.append("RX", [0, 1, 2])
    c.append("H", [3])
    c.append("CX", [3, 0])
    c.append("DEPOLARIZE2", [3, 0], 0.01)
    c.append("MR", [3])
    c.append("DETECTOR", [target_rec(-1)], (0,))
    c.append("SHIFT_COORDS", [], (1,))
    c.append("MX", [0, 1, 2])
    c.append("OBSERVABLE_INCLUDE", [target_rec(-3), target_rec(-2)], (0,))
    text = str(c)
    assert Circuit(text) == c


def test_ir_repeat_block():
    body = Circuit().append("MR", [0])
    c = Circuit().append("R", [0]) + 5 * body
    assert "REPEAT 5 {" in str(c)
    assert c.num_measurements == 5
    assert Circuit(str(c)) == c


def test_ir_counts():
    c = Circuit()
    c.append("MR", [0, 1])
    c.append("DETECTOR", [target_rec(-2)])
    c.append("DETECTOR", [target_rec(-1)])
    c.append("OBSERVABLE_INCLUDE", [target_rec(-1)], (2,))
    assert c.num_measurements == 2
    assert c.num_detectors == 2
    assert c.num_observables == 3
    assert c.num_qubits == 2


# ------------------------------------------------------------ error plugin
def test_add_cx_error():
    c = Circuit()
    c.append("CX", [0, 1])
    c.append("CX", [2, 3])
    noisy = AddCXError(c, "DEPOLARIZE2(0.25)")
    text = str(noisy)
    assert text.count("DEPOLARIZE2(0.25) 0 1") == 1
    assert text.count("DEPOLARIZE2(0.25) 2 3") == 1
    # error follows its gate
    assert text.index("CX 0 1") < text.index("DEPOLARIZE2(0.25) 0 1")


# ---------------------------------------------------------------- sampler
def _rep3_two_rounds(p_data: float) -> Circuit:
    """3-qubit repetition code, two Z-check extraction rounds with an
    X_ERROR(p) on the middle data qubit between them."""
    c = Circuit()
    c.append("R", [0, 1, 2, 3, 4])
    for ctrl, tgt in [(0, 3), (1, 3), (1, 4), (2, 4)]:
        c.append("CX", [ctrl, tgt])
    c.append("MR", [3, 4])
    c.append("DETECTOR", [target_rec(-2)])
    c.append("DETECTOR", [target_rec(-1)])
    c.append("X_ERROR", [1], p_data)
    for ctrl, tgt in [(0, 3), (1, 3), (1, 4), (2, 4)]:
        c.append("CX", [ctrl, tgt])
    c.append("MR", [3, 4])
    c.append("DETECTOR", [target_rec(-2), target_rec(-4)])
    c.append("DETECTOR", [target_rec(-1), target_rec(-3)])
    c.append("M", [0, 1, 2])
    c.append("OBSERVABLE_INCLUDE", [target_rec(-2)], (0,))
    return c


def test_sampler_noiseless_deterministic():
    c = _rep3_two_rounds(0.0)
    s = FrameSampler(c)
    dets, obs = s.sample(jax.random.PRNGKey(0), 64)
    assert not np.asarray(dets).any()
    assert not np.asarray(obs).any()


def test_sampler_single_fault_statistics():
    p = 0.3
    s = FrameSampler(_rep3_two_rounds(p))
    n = 20000
    dets, obs = s.sample(jax.random.PRNGKey(1), n)
    dets = np.asarray(dets)
    # first-round detectors never fire; both second-round difference
    # detectors fire exactly when the X error occurred
    assert not dets[:, :2].any()
    rate = dets[:, 2].mean()
    assert abs(rate - p) < 4 * np.sqrt(p * (1 - p) / n)
    assert np.array_equal(dets[:, 2], dets[:, 3])
    # the data error flips the final measurement of qubit 1 = observable 0
    assert np.array_equal(np.asarray(obs)[:, 0], dets[:, 2])


def test_sampler_repeat_block_matches_unrolled():
    """A REPEAT-compiled circuit must sample the same *distribution* as its
    unrolled form; with p=0/1 noise it must match exactly."""
    body = Circuit()
    body.append("X_ERROR", [0], 1.0)
    body.append("MR", [0])
    body.append("DETECTOR", [target_rec(-1)])
    rep = Circuit().append("R", [0]) + 4 * body
    s = FrameSampler(rep)
    dets, _ = s.sample(jax.random.PRNGKey(0), 8)
    # X before every MR: every detector fires every shot
    assert np.asarray(dets).all()


def test_sampler_mr_resets_frame():
    c = Circuit()
    c.append("R", [0])
    c.append("X_ERROR", [0], 1.0)
    c.append("MR", [0])
    c.append("DETECTOR", [target_rec(-1)])
    c.append("MR", [0])
    c.append("DETECTOR", [target_rec(-1)])
    s = FrameSampler(c)
    dets, _ = s.sample(jax.random.PRNGKey(0), 4)
    dets = np.asarray(dets)
    assert dets[:, 0].all()  # error seen once
    assert not dets[:, 1].any()  # MR reset the frame


def test_sampler_depolarize2_propagation():
    """DEPOLARIZE2(1.0) after CX: ancilla X-flip component rate = 8/15."""
    c = Circuit()
    c.append("R", [0, 1])
    c.append("CX", [0, 1])
    c.append("DEPOLARIZE2", [0, 1], 1.0)
    c.append("MR", [1])
    c.append("DETECTOR", [target_rec(-1)])
    s = FrameSampler(c)
    n = 30000
    dets, _ = s.sample(jax.random.PRNGKey(2), n)
    rate = np.asarray(dets)[:, 0].mean()
    # components flipping x of qubit 1: second Pauli in {X,Y}: 8 of 15
    assert abs(rate - 8 / 15) < 4 * np.sqrt((8 / 15) * (7 / 15) / n)


def test_sampler_chained_cx_sequential_semantics():
    """'CX 0 1 1 2' (one instruction, qubit 1 on both sides) must apply the
    pairs sequentially like stim: an X on qubit 0 propagates 0 -> 1 -> 2.  A
    simultaneous scatter would read qubit 1's pre-update frame and leave
    qubit 2 unflipped."""
    c = Circuit()
    c.append("R", [0, 1, 2])
    c.append("X_ERROR", [0], 1.0)
    c.append("CX", [0, 1, 1, 2])
    c.append("M", [0, 1, 2])
    for k in (-3, -2, -1):
        c.append("DETECTOR", [target_rec(k)])
    s = FrameSampler(c)
    dets, _ = s.sample(jax.random.PRNGKey(0), 4)
    assert np.asarray(dets).all()
    # the DEM propagator shares the lowering, so its fault must hit all three
    dem = str(detector_error_model(c))
    assert "D0 D1 D2" in dem


# -------------------------------------------------------------------- DEM
def test_dem_single_fault():
    c = _rep3_two_rounds(0.125)
    dem = detector_error_model(c)
    assert len(dem.errors) == 1
    p, dets, obs = dem.errors[0]
    assert abs(p - 0.125) < 1e-12
    assert dets == (2, 3)
    assert obs == (0,)


def test_dem_merges_identical_symptoms():
    c = Circuit()
    c.append("R", [0])
    c.append("X_ERROR", [0], 0.1)
    c.append("X_ERROR", [0], 0.2)
    c.append("MR", [0])
    c.append("DETECTOR", [target_rec(-1)])
    dem = detector_error_model(c)
    assert len(dem.errors) == 1
    # XOR-combination: 0.1*0.8 + 0.2*0.9
    assert abs(dem.errors[0][0] - 0.26) < 1e-12


def test_dem_marginals_match_sampler():
    """Detector marginals from the sampler must match the DEM prediction
    P(det) = (1 - prod(1-2p_i)) / 2 over the errors touching it."""
    p = 0.05
    c = Circuit()
    c.append("R", [0, 1, 2, 3, 4])
    for ctrl, tgt in [(0, 3), (1, 3), (1, 4), (2, 4)]:
        c.append("CX", [ctrl, tgt])
        c.append("DEPOLARIZE2", [ctrl, tgt], p)
    c.append("MR", [3, 4])
    c.append("DETECTOR", [target_rec(-2)])
    c.append("DETECTOR", [target_rec(-1)])
    dem = detector_error_model(c)
    pred = np.zeros(2)
    for d in range(2):
        prod = 1.0
        for q, dets, _ in dem.errors:
            if d in dets:
                prod *= 1 - 2 * q
        pred[d] = (1 - prod) / 2

    s = FrameSampler(c)
    n = 40000
    dets, _ = s.sample(jax.random.PRNGKey(3), n)
    rates = np.asarray(dets).mean(axis=0)
    for d in range(2):
        assert abs(rates[d] - pred[d]) < 5 * np.sqrt(pred[d] * (1 - pred[d]) / n)


def test_dem_text_and_hypergraph_round_trip():
    """DEM text layout must drive the (window, final) layer extraction."""
    m = 2  # checks
    c = Circuit()
    c.append("R", [0, 1, 2, 3, 4])
    # window: 2 sub-rounds of extraction with data noise, coordinate shift
    # before the window detectors (reference rep1 layout)
    c.append("SHIFT_COORDS", [], (1,))
    for rep in range(2):
        c.append("X_ERROR", [1], 0.1)
        for ctrl, tgt in [(0, 3), (1, 3), (1, 4), (2, 4)]:
            c.append("CX", [ctrl, tgt])
        c.append("MR", [3, 4])
        if rep == 0:
            c.append("DETECTOR", [target_rec(-2)], (0,))
            c.append("DETECTOR", [target_rec(-1)], (0,))
        else:
            c.append("DETECTOR", [target_rec(-2), target_rec(-4)], (0,))
            c.append("DETECTOR", [target_rec(-1), target_rec(-3)], (0,))
    # final layer
    c.append("SHIFT_COORDS", [], (1,))
    c.append("M", [0, 1, 2])
    c.append("DETECTOR", [target_rec(-3), target_rec(-2)], (0,))
    c.append("DETECTOR", [target_rec(-2), target_rec(-1)], (0,))
    c.append("OBSERVABLE_INCLUDE", [target_rec(-3)], (0,))

    dem = detector_error_model(c)
    text = str(dem)
    assert "shift_detectors(1) 0" in text
    H_list, L_list, ps_list = GenFaultHyperGraph(
        text, num_rounds=1, num_rep=2, num_logicals=1
    )
    # first layer holds the 2-sub-round window (4 detectors), last the final 2
    assert H_list[0].shape[0] == 2 * m
    assert H_list[1].shape[0] == m
    assert L_list[0].shape[0] == 1
    assert len(ps_list[0]) == H_list[0].shape[1]
    h_cor = GenCorrecHyperGraph(
        text, num_rounds=1, num_rep=2, num_checks=m, num_logicals=1
    )
    assert h_cor.shape[0] == m
    assert h_cor.shape[1] == H_list[0].shape[1]


# --------------------------------------------- code-review regression tests
def test_observable_inside_repeat_block():
    """OBSERVABLE_INCLUDE inside a REPEAT block must accumulate record
    columns from every iteration, not just the first."""
    body = Circuit()
    body.append("X_ERROR", [0], 1.0)
    body.append("MR", [0])
    body.append("OBSERVABLE_INCLUDE", [target_rec(-1)], (0,))
    c = Circuit().append("R", [0]) + 3 * body
    from qldpc_fault_tolerance_tpu.circuits.lowering import compile_circuit

    compiled = compile_circuit(c)
    assert compiled.obs_cols == [[0, 1, 2]]
    s = FrameSampler(c)
    _, obs = s.sample(jax.random.PRNGKey(0), 4)
    # X before every MR flips every measurement: XOR of 3 ones = 1
    assert np.asarray(obs)[:, 0].all()


def test_add_measurement_error_adjacent_lines():
    """Adjacent M lines must each get their error (conscious fix of the
    reference's newline-consuming regexes, SURVEY §2.4)."""
    from qldpc_fault_tolerance_tpu.circuits import AddMeasurementError

    c = Circuit()
    c.append("M", [0])
    c.append("M", [1])
    text = str(AddMeasurementError(c, 0.125))
    assert text.count("X_ERROR(0.125)") == 2


def test_tiny_probability_survives_text_round_trip():
    from qldpc_fault_tolerance_tpu.circuits.ir import fmt_float

    assert float(fmt_float(1e-7)) == pytest.approx(1e-7)
    c = Circuit()
    c.append("CX", [0, 1])
    noisy = AddCXError(c, f"DEPOLARIZE2({fmt_float(1e-7)})")
    from qldpc_fault_tolerance_tpu.circuits.lowering import compile_circuit

    ops = [op for op, _ in compile_circuit(noisy).flattened_ops()]
    assert any(op.kind == "dep2" and op.p > 0 for op in ops)


def test_dem_measurement_collapse_conjugate_plane():
    """A Z fault consumed by a Z-basis measurement must not propagate
    further in the DEM (projective collapse clears the conjugate plane)."""
    c = Circuit()
    c.append("R", [0])
    c.append("Z_ERROR", [0], 0.25)
    c.append("M", [0])
    c.append("H", [0])
    c.append("M", [0])
    c.append("DETECTOR", [target_rec(-1)])
    dem = detector_error_model(c)
    assert dem.errors == []


def test_sampler_structure_cache_shares_compile_but_not_probs():
    """Two memory circuits differing only in error rate share one compiled
    sampler (structure_key equal) yet sample from their own probabilities:
    the noise rides in as a traced argument, never baked."""
    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.circuits import FrameSampler
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.sim.circuit import build_memory_circuit
    from qldpc_fault_tolerance_tpu.circuits import ColorationCircuit

    code = hgp(rep_code(3), rep_code(3))
    sx, sz = ColorationCircuit(code.hx), ColorationCircuit(code.hz)

    def sampler(p):
        ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p,
              "p_idling_gate": 0}
        circ = build_memory_circuit(code, 3, ep, sx, sz, spacetime=False)
        return FrameSampler(circ)

    lo, hi = sampler(0.001), sampler(0.2)
    assert lo._structure_key == hi._structure_key
    assert lo == hi and hash(lo) == hash(hi)
    key = jax.random.PRNGKey(0)
    d_lo, _ = lo.sample(key, 512)
    d_hi, _ = hi.sample(key, 512)
    # same compiled program, different probs -> very different detector rates
    r_lo = float(np.asarray(d_lo).mean())
    r_hi = float(np.asarray(d_hi).mean())
    assert r_lo < 0.02 < r_hi
    # different structure (cycle count) -> different key
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 0.001,
          "p_idling_gate": 0}
    other = FrameSampler(build_memory_circuit(code, 5, ep, sx, sz,
                                              spacetime=False))
    assert other._structure_key != lo._structure_key


def test_compile_circuit_template_cache_instantiates_probabilities():
    """compile_circuit memoizes lowering on p-canonicalized text; two
    same-structure circuits at different probabilities must share structure
    (same structure_key, same fused op shapes) while carrying their OWN
    probabilities — and a zero probability must change the structure (the
    op is dropped), not silently reuse the nonzero template."""
    from qldpc_fault_tolerance_tpu.circuits.ir import Circuit
    from qldpc_fault_tolerance_tpu.circuits.lowering import compile_circuit

    def build(p_cx, p_m):
        c = Circuit()
        c.append("RX", [0, 1, 2])
        c.append("CX", [0, 1])
        c.append("DEPOLARIZE2", [0, 1], p_cx)
        c.append("DEPOLARIZE1", [2], p_m)
        c.append("MX", [0, 1, 2])
        c.append("DETECTOR", [target_rec(-1)])
        return c

    a = compile_circuit(build(0.01, 0.002))
    b = compile_circuit(build(0.03, 0.004))
    assert a.structure_key() == b.structure_key()
    def noise_ps(cc):
        return sorted(op.p for s in cc.segments for op in s.ops
                      if op.kind in ("dep1", "dep2", "perr"))

    pa, pb = noise_ps(a), noise_ps(b)
    assert pa == [0.002, 0.01] and pb == [0.004, 0.03]
    # equal probabilities fuse-compatible pattern: same p on both ops gives
    # the same key as itself but zero-p drops the op -> different key
    z = compile_circuit(build(0.01, 0.0))
    assert z.structure_key() != a.structure_key()
    assert noise_ps(z) == [0.01]
