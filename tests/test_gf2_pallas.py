"""Fused sample→syndrome→check kernels (ops/gf2_pallas).

The Pallas kernels run in interpreter mode here (CPU suite; the Mosaic path
is exercised on TPU by bench.py BENCH_FUSED=1), and must be bit-exact
word-for-word against their XLA twins — same counters, same Threefry, same
GF(2) algebra.  The twin itself is validated against jax's reference
Threefry cipher and the dense pipeline.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.ops import gf2_pallas as gp
from qldpc_fault_tolerance_tpu.ops.gf2_packed import pack_shots, unpack_shots
from qldpc_fault_tolerance_tpu.ops.linalg import gf2_matmul


@pytest.fixture(scope="module")
def spec():
    code = hgp(rep_code(4), rep_code(5))
    return code, gp.build_fused_spec(code.hx, code.hz, code.lx, code.lz,
                                     (0.012, 0.008, 0.02))


def test_threefry_matches_jax_reference_cipher():
    try:
        from jax._src.prng import threefry_2x32 as ref
    except ImportError:
        pytest.skip("jax internal threefry not importable")
    k = jnp.array([0xDEADBEEF, 0x12345678], dtype=jnp.uint32)
    c = jnp.arange(64, dtype=jnp.uint32)
    ours = np.stack([np.asarray(a) for a in
                     gp.threefry2x32(k[0], k[1], c[:32], c[32:])])
    theirs = np.asarray(ref(k, c)).reshape(2, 32)
    np.testing.assert_array_equal(ours, theirs)


def test_sample_syndrome_kernel_bit_exact_vs_twin(spec):
    code, fspec = spec
    key = jax.random.PRNGKey(11)
    b = 512  # 16 lane words = 2 blocks of block_w=8
    ref = gp.sample_syndrome(fspec, key, b, backend="xla")
    ker = gp.sample_syndrome(fspec, key, b, backend="pallas", interpret=True)
    assert len(ref) == len(ker) == 4
    for r, k_ in zip(ref, ker):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(k_))
    # syndromes-only variant returns the same syndrome words
    sx, sz = gp.sample_syndrome(fspec, key, b, backend="pallas",
                                interpret=True, emit_errors=False)
    np.testing.assert_array_equal(np.asarray(sx), np.asarray(ref[2]))
    np.testing.assert_array_equal(np.asarray(sz), np.asarray(ref[3]))


def test_sampled_syndromes_consistent_with_dense_algebra(spec):
    code, fspec = spec
    key = jax.random.PRNGKey(3)
    b = 256
    exp, ezp, sxp, szp = gp.sample_syndrome(fspec, key, b, backend="xla")
    ex = np.asarray(unpack_shots(exp, b))
    ez = np.asarray(unpack_shots(ezp, b))
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(szp, b)), ez @ code.hx.T % 2)
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(sxp, b)), ex @ code.hz.T % 2)
    # marginal sanity: X-flip rate ~ px + py
    assert abs(ex.mean() - 0.02) < 0.005


@pytest.mark.parametrize("eval_type", ["X", "Z", "Total"])
def test_residual_check_kernel_bit_exact_vs_twin(spec, eval_type):
    code, fspec = spec
    key = jax.random.PRNGKey(29)
    b = 256
    rng = np.random.default_rng(5)
    corx = pack_shots((rng.random((b, code.N)) < 0.02).astype(np.uint8))
    corz = pack_shots((rng.random((b, code.N)) < 0.02).astype(np.uint8))
    ref = gp.residual_check_stats(fspec, key, b, corx, corz, eval_type,
                                  backend="xla")
    ker = gp.residual_check_stats(fspec, key, b, corx, corz, eval_type,
                                  backend="pallas", interpret=True)
    assert int(ref[0]) == int(ker[0])
    assert int(ref[1]) == int(ker[1])


def test_residual_check_matches_dense_reference(spec):
    """The twin's scalars equal a from-scratch dense computation of the
    stabilizer/logical checks on the regenerated error."""
    code, fspec = spec
    key = jax.random.PRNGKey(8)
    b = 96
    k0, k1 = gp._key_words(key)
    r = gp.counter_draws(k0, k1, b, code.N)
    ex, ez = gp._errors_from_draws(r, fspec.cuts)
    ex, ez = np.asarray(ex, np.uint8), np.asarray(ez, np.uint8)
    rng = np.random.default_rng(9)
    cx = (rng.random((b, code.N)) < 0.02).astype(np.uint8)
    cz = (rng.random((b, code.N)) < 0.02).astype(np.uint8)
    res_x, res_z = ex ^ cx, ez ^ cz
    x_fail = ((res_x @ code.hz.T % 2).any(1)) | ((res_x @ code.lz.T % 2).any(1))
    z_fail = ((res_z @ code.hx.T % 2).any(1)) | ((res_z @ code.lx.T % 2).any(1))
    want = int((x_fail | z_fail).sum())
    cnt, _ = gp.residual_check_stats(
        fspec, key, b, pack_shots(cx), pack_shots(cz), "Total", backend="xla")
    assert int(cnt) == want


def test_fused_sim_stats_backends_agree(spec):
    """The full fused stats batch (sample → BP → regenerate-and-check)
    produces identical scalars whether the kernels run as XLA twins or as
    interpreted Pallas."""
    code, _ = spec
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim import data_error as de

    p = 0.03
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=8)  # noqa: E731
    sim = de.CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=256, seed=1,
        fused_sampler=True,
    )
    key = jax.random.PRNGKey(77)
    cfg = sim._cfg(256)
    (cnt_xla, mw_xla), _, _ = de._stats_fused(cfg, sim._dev_state, key)
    # force the pallas-interpret route through the public dispatchers
    spec_ = sim._dev_state["fspec"]
    sxp, szp = gp.sample_syndrome(spec_, key, 256, backend="pallas",
                                  interpret=True, emit_errors=False)
    from qldpc_fault_tolerance_tpu.decoders.bp_decoders import decode_device

    cor_z, _ = decode_device(cfg[4], sim._dev_state["dz"],
                             unpack_shots(szp, 256))
    cor_x, _ = decode_device(cfg[3], sim._dev_state["dx"],
                             unpack_shots(sxp, 256))
    cnt_pl, mw_pl = gp.residual_check_stats(
        spec_, key, 256, pack_shots(cor_x), pack_shots(cor_z), cfg[2],
        backend="pallas", interpret=True)
    assert int(cnt_xla) == int(cnt_pl)
    assert int(mw_xla) == int(mw_pl)
