"""Golden validation of the BP kernel and OSD against independent oracles.

The native ``ldpc``/``bposd`` packages are not installable in this image, so
golden vectors cannot be captured from them directly (SURVEY §7 step 2).
Instead the kernel is pinned against two *independent* implementations that
share no code with ops/bp.py:

  * a textbook flooding scaled-min-sum decoder written directly from the
    update equations in plain numpy (dense matrices, explicit message
    dictionaries — deliberately naive);
  * exhaustive maximum-likelihood / minimum-weight coset decoding on small
    codes, which BP must match on cycle-free graphs (BP is exact on trees)
    and BP+OSD must match wherever the true error is unique.

Any divergence between ops/bp.py and these oracles is a real defect, not a
convention mismatch: the oracle follows the same conventions the reference's
native decoder uses (LLR = log((1-p)/p), syndrome-sign min-sum with scaling
factor, hard decision on negative posterior, return-on-convergence).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import rep_code
from qldpc_fault_tolerance_tpu.ops import bp


def oracle_minsum(h, syndrome, probs, max_iter, msf=0.625):
    """Flooding scaled min-sum, dense/naive, independent of ops/bp.py.

    Returns (hard_decision, converged, iterations_used, posterior_llr).
    Messages freeze at convergence (return-on-convergence semantics).
    """
    h = np.asarray(h) % 2
    m, n = h.shape
    syndrome = np.asarray(syndrome) % 2
    llr0 = np.log((1 - probs) / probs)
    # v2c messages indexed [check, var] (only where h=1)
    v2c = np.where(h, llr0[None, :], 0.0).astype(np.float64)
    c2v = np.zeros((m, n))
    posterior = llr0.copy()
    for it in range(1, max_iter + 1):
        # check update: scaled min-sum with syndrome sign
        for c in range(m):
            vs = np.nonzero(h[c])[0]
            for v in vs:
                others = [u for u in vs if u != v]
                sgn = np.prod(np.sign(v2c[c, others])) if others else 1.0
                sgn = sgn if sgn != 0 else 1.0
                if syndrome[c]:
                    sgn = -sgn
                mag = min(abs(v2c[c, u]) for u in others) if others else 0.0
                c2v[c, v] = msf * sgn * mag
        # var update + posterior
        for v in range(n):
            cs = np.nonzero(h[:, v])[0]
            total = llr0[v] + sum(c2v[c, v] for c in cs)
            posterior[v] = total
            for c in cs:
                v2c[c, v] = total - c2v[c, v]
        hard = (posterior < 0).astype(np.uint8)
        if np.array_equal(h @ hard % 2, syndrome):
            return hard, True, it, posterior
    return hard, False, max_iter, posterior


def kernel_decode(h, syndromes, probs, max_iter, msf=0.625):
    graph = bp.build_tanner_graph(np.asarray(h, dtype=np.uint8))
    res = bp.bp_decode(
        graph, jnp.asarray(np.atleast_2d(syndromes), jnp.uint8),
        bp.llr_from_probs(probs), max_iter=max_iter,
        ms_scaling_factor=msf,
    )
    return (np.asarray(res.error), np.asarray(res.converged),
            np.asarray(res.iterations), np.asarray(res.posterior_llr))


HAMMING_74 = np.array([
    [1, 0, 1, 0, 1, 0, 1],
    [0, 1, 1, 0, 0, 1, 1],
    [0, 0, 0, 1, 1, 1, 1],
], dtype=np.uint8)


@pytest.mark.parametrize("h,name", [
    (rep_code(5), "rep5"),
    (HAMMING_74, "hamming74"),
])
def test_kernel_matches_oracle_exhaustive_syndromes(h, name):
    """Every syndrome of small codes: identical hard decisions, convergence
    flags, iteration counts, and posteriors vs the naive oracle."""
    h = np.asarray(h) % 2
    m, n = h.shape
    probs = np.full(n, 0.05)
    for max_iter in (1, 3, 12):
        for s_int in range(2 ** m):
            synd = np.array([(s_int >> i) & 1 for i in range(m)], np.uint8)
            o_hard, o_conv, o_it, o_post = oracle_minsum(
                h, synd, probs, max_iter)
            k_hard, k_conv, k_it, k_post = kernel_decode(
                h, synd, probs, max_iter)
            assert np.array_equal(k_hard[0], o_hard), (name, max_iter, s_int)
            assert bool(k_conv[0]) == o_conv, (name, max_iter, s_int)
            if o_conv:
                assert int(k_it[0]) == o_it, (name, max_iter, s_int)
            np.testing.assert_allclose(
                k_post[0], o_post, rtol=2e-5, atol=2e-4,
                err_msg=f"{name} iter={max_iter} synd={s_int}")


def test_kernel_matches_oracle_random_ldpc():
    """Random sparse 10x20 matrix, random syndromes, non-uniform channel."""
    rng = np.random.default_rng(7)
    h = (rng.random((10, 20)) < 0.18).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1  # no empty columns
    probs = rng.uniform(0.01, 0.2, 20)
    for trial in range(25):
        synd = rng.integers(0, 2, 10).astype(np.uint8)
        for max_iter in (2, 9):
            o_hard, o_conv, _, o_post = oracle_minsum(h, synd, probs, max_iter)
            k_hard, k_conv, _, k_post = kernel_decode(h, synd, probs, max_iter)
            assert np.array_equal(k_hard[0], o_hard), (trial, max_iter)
            assert bool(k_conv[0]) == o_conv, (trial, max_iter)
            np.testing.assert_allclose(k_post[0], o_post, rtol=2e-5, atol=2e-4)


def test_bp_exact_on_tree_matches_ml():
    """rep_code(7) has a cycle-free Tanner graph: unscaled min-sum
    (msf = 1.0, i.e. max-product) is exact there, so converged BP must
    return the maximum-likelihood (minimum-weight, p<0.5 uniform) coset
    error.  (With msf = 0.625 the scaling perturbs tree-exactness — the
    reference's native decoder behaves the same way.)"""
    h = rep_code(7)
    m, n = h.shape
    probs = np.full(n, 0.08)
    for s_int in range(2 ** m):
        synd = np.array([(s_int >> i) & 1 for i in range(m)], np.uint8)
        # exhaustive ML: lowest-weight error matching the syndrome
        best, best_w = None, n + 1
        ties = 0
        for e_int in range(2 ** n):
            e = np.array([(e_int >> i) & 1 for i in range(n)], np.uint8)
            if np.array_equal(h @ e % 2, synd):
                w = int(e.sum())
                if w < best_w:
                    best, best_w, ties = e, w, 1
                elif w == best_w:
                    ties += 1
        k_hard, k_conv, _, _ = kernel_decode(h, synd, probs, max_iter=30,
                                             msf=1.0)
        assert bool(k_conv[0])
        if ties == 1:  # unique ML solution: BP must find exactly it
            assert np.array_equal(k_hard[0], best), s_int
        else:  # degenerate: any minimum-weight solution is correct
            assert np.array_equal(h @ k_hard[0] % 2, synd)
            assert int(k_hard[0].sum()) == best_w


def test_bposd_osd_path_matches_minimum_weight_on_small_code():
    """The OSD stage (osd_e, order 10) on the Hamming code: with order
    10 >= n - rank the reprocessing search covers the whole coset, so the
    output must be a minimum-weight (uniform-prior ML) syndrome match.

    The OSD path is forced explicitly (converged=False): like the native
    bposd, a BP-converged shot returns the BP solution untouched even when
    it is not minimum weight, so plain .decode() carries no such guarantee.
    """
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder

    h = HAMMING_74
    m, n = h.shape
    dec = BPOSD_Decoder(h, np.full(n, 0.05), max_iter=2,
                        osd_method="osd_e", osd_order=10)
    for s_int in range(2 ** m):
        synd = np.array([(s_int >> i) & 1 for i in range(m)], np.uint8)
        # uniform posteriors, convergence flag off -> pure OSD
        cor = dec.osd_host(
            synd[None], np.zeros((1, n), np.uint8),
            np.zeros(1, bool), np.full((1, n), 1.0, np.float32),
        )[0]
        assert np.array_equal(h @ cor % 2, synd), s_int
        # exhaustive minimum weight
        best_w = min(
            int(np.array([(e >> i) & 1 for i in range(n)]).sum())
            for e in range(2 ** n)
            if np.array_equal(
                h @ np.array([(e >> i) & 1 for i in range(n)]) % 2, synd)
        )
        assert int(np.asarray(cor).sum()) == best_w, s_int
        # and the end-to-end decode is always at least syndrome-consistent
        full = dec.decode(synd)
        assert np.array_equal(h @ full % 2, synd), s_int
