"""Device (TPU-style batched) OSD vs the numpy oracle and the host path.

The device kernel must reproduce _native/osd.cpp's semantics; the shared
numpy oracle (decoders/osd.py:_osd_numpy) is the spec.  Degenerate ML ties
may resolve differently across float32 (device) / float64 (host) cost sums,
so mismatching bit patterns are accepted only when both are
syndrome-consistent with equal total cost.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code, ring_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
from qldpc_fault_tolerance_tpu.decoders.osd import _channel_cost, _osd_numpy
from qldpc_fault_tolerance_tpu.ops.osd_device import (
    build_osd_plan,
    osd_decode_device,
)


def _assert_matches_oracle(h, probs, synds, llrs, order):
    h = (np.asarray(h) != 0).astype(np.uint8)
    plan = build_osd_plan(h, probs)
    dev = np.asarray(
        osd_decode_device(plan, jnp.asarray(synds), jnp.asarray(llrs),
                          osd_order=order)
    )
    cost = _channel_cost(probs)
    ref = _osd_numpy(h, synds, llrs.astype(np.float64), cost,
                     1 if order else 0, order)
    exact = (dev == ref).all(axis=1)
    dcost = (dev * cost[None]).sum(1)
    rcost = (ref * cost[None]).sum(1)
    synd_ok = ((dev @ h.T % 2) == synds).all(axis=1)
    ok = exact | ((np.abs(dcost - rcost) < 1e-4) & synd_ok)
    assert ok.all(), np.nonzero(~ok)
    return exact.mean()


@pytest.mark.parametrize("order", [0, 4, 10])
def test_device_osd_matches_oracle_random_ldpc(order):
    rng = np.random.default_rng(3)
    h = (rng.random((12, 24)) < 0.22).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    probs = rng.uniform(0.01, 0.3, 24)
    synds = ((rng.random((24, 24)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 2, (24, 24)).astype(np.float32)
    _assert_matches_oracle(h, probs, synds, llrs, order)


def test_device_osd_matches_oracle_rank_deficient():
    """Toric hx has dependent rows — rank < m must work (through the full
    default path: on this CPU suite the elimination routes to the XLA twin
    of the blocked kernel)."""
    rng = np.random.default_rng(5)
    code = hgp(ring_code(4), ring_code(4))
    h = code.hx.astype(np.uint8)
    n = h.shape[1]
    probs = np.full(n, 0.06)
    synds = ((rng.random((16, n)) < 0.08).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 1.5, (16, n)).astype(np.float32)
    _assert_matches_oracle(h, probs, synds, llrs, 10)


@pytest.mark.parametrize("order", [0, 8])
def test_device_osd_matches_oracle_tall_h(order):
    """Tall H (m > n, rank-deficient): every pivot column is reached before
    the words run out and the free panel stays consistent — through the
    full default (twin-elimination) path, at osd_order 0 and 8."""
    rng = np.random.default_rng(17)
    h = (rng.random((40, 18)) < 0.3).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    n = h.shape[1]
    probs = rng.uniform(0.01, 0.3, n)
    synds = ((rng.random((16, n)) < 0.15).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 2, (16, n)).astype(np.float32)
    _assert_matches_oracle(h, probs, synds, llrs, order)


def test_device_osd_prior_above_half():
    h = np.array([[1, 1, 0], [0, 1, 1]], dtype=np.uint8)
    probs = np.array([0.01, 0.01, 0.9])
    plan = build_osd_plan(h, probs)
    out = np.asarray(
        osd_decode_device(plan, jnp.asarray([[0, 1]], dtype=jnp.uint8),
                          jnp.zeros((1, 3), jnp.float32), osd_order=3)
    )
    assert out[0].tolist() == [0, 0, 1]


def test_bposd_device_path_equals_host_path():
    """BPOSD_Decoder(device_osd=True) must agree with the host C++/numpy
    path decode-for-decode (same BP, same OSD semantics)."""
    rng = np.random.default_rng(9)
    h = rep_code(9)
    n = h.shape[1]
    probs = np.full(n, 0.1)
    host = BPOSD_Decoder(h, probs, max_iter=2, device_osd=False)
    dev = BPOSD_Decoder(h, probs, max_iter=2, device_osd=True)
    assert host.needs_host_postprocess and not dev.needs_host_postprocess
    synds = ((rng.random((32, n)) < 0.2).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    a = host.decode_batch(synds)
    b = dev.decode_batch(synds)
    cost = _channel_cost(probs)
    exact = (a == b).all(axis=1)
    tie = (np.abs((a * cost).sum(1) - (b * cost).sum(1)) < 1e-4)
    assert (exact | tie).all()


def test_bposd_device_default_engages_off_tpu():
    """ISSUE 13 tentpole: device OSD is the default BPOSD backend on EVERY
    substrate — on this CPU suite the decoder must come up device-resident
    (bposd_dev static, no host postprocess) without any opt-in."""
    h = rep_code(9)
    dec = BPOSD_Decoder(h, np.full(h.shape[1], 0.1), max_iter=4)
    assert dec.device_osd
    assert not dec.needs_host_postprocess
    assert dec.device_static[0] == "bposd_dev"
    # ISSUE 19: osd_cs is device-resident too — the combination sweep
    # decodes on device (static names the method; host demoted to oracle)
    cs = BPOSD_Decoder(h, np.full(h.shape[1], 0.1), max_iter=4,
                       osd_method="osd_cs")
    assert cs.device_osd and not cs.needs_host_postprocess
    assert cs.device_static[0] == "bposd_dev"
    assert cs.device_static[6] == "osd_cs"


def _host_oracle_wer(code, p, max_iter, shots, seed, K):
    """Host-OSD-path Monte-Carlo oracle for the sweep-consistency test: an
    engine-free loop (the engines no longer run host-OSD decoders) over
    numpy-sampled depolarizing errors, decoding both sectors with the
    demoted host path and applying the reference residual checks."""
    from qldpc_fault_tolerance_tpu.sim.common import wer_single_shot

    rng = np.random.default_rng(seed)
    n = code.N
    dx = BPOSD_Decoder(code.hz, np.full(n, p), max_iter=max_iter,
                       device_osd=False)
    dz = BPOSD_Decoder(code.hx, np.full(n, p), max_iter=max_iter,
                       device_osd=False)
    assert dx.needs_host_postprocess
    u = rng.random((shots, n))
    ex = ((u < p / 3) | ((u >= p / 3) & (u < 2 * p / 3))).astype(np.uint8)
    ez = ((u >= p / 3) & (u < p)).astype(np.uint8)
    cor_z = dz.decode_batch((ez @ code.hx.T % 2).astype(np.uint8))
    cor_x = dx.decode_batch((ex @ code.hz.T % 2).astype(np.uint8))
    rx, rz = ex ^ cor_x, ez ^ cor_z
    x_fail = ((rx @ code.hz.T % 2).any(1)) | ((rx @ code.lz.T % 2).any(1))
    z_fail = ((rz @ code.hx.T % 2).any(1)) | ((rz @ code.lx.T % 2).any(1))
    fails = int((x_fail | z_fail).sum())
    return wer_single_shot(fails, shots, K)


def test_bposd_device_sweep_zero_host_round_trips_and_wer_consistent():
    """ISSUE 13 acceptance: a data-noise BPOSD sweep (hgp_rep3,
    target_failures mode) completes with ``osd.host_round_trips == 0`` —
    the whole BP->OSD->check pipeline inside the megabatch carry — and a
    WER statistically consistent (3 sigma) with the host-OSD path."""
    import jax

    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
    from qldpc_fault_tolerance_tpu.utils import telemetry

    code = hgp(rep_code(3), rep_code(3))
    p = 0.08
    dx = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=4)
    dz = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=4)
    assert not dx.needs_host_postprocess  # device default
    telemetry.reset()
    telemetry.enable()
    try:
        sim = CodeSimulator_DataError(
            code=code, decoder_x=dx, decoder_z=dz,
            pauli_error_probs=[p / 3] * 3, batch_size=256, seed=0,
        )
        wer_dev, eb_dev = sim.WordErrorRate(
            4096, key=jax.random.PRNGKey(2), target_failures=200)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    assert snap.get("osd.host_round_trips", {}).get("value", 0) == 0
    assert snap["osd.device_shots"]["value"] > 0  # OSD really engaged
    wer_host, eb_host = _host_oracle_wer(code, p, max_iter=4, shots=4096,
                                         seed=77, K=code.K)
    sigma = np.sqrt(eb_dev ** 2 + eb_host ** 2)
    assert abs(wer_dev - wer_host) < 3 * sigma, (wer_dev, wer_host, sigma)


def test_bposd_compaction_tier_equivalence():
    """Tier selection changes the program PATH only, never a shot's
    result: a batch whose straggler count engages a compaction tier must
    return exactly what the full-batch OSD stage would for every
    BP-failed shot (and BP's output for every converged one)."""
    from qldpc_fault_tolerance_tpu.decoders.bp_decoders import (
        osd_compaction_tiers,
    )
    from qldpc_fault_tolerance_tpu.ops.osd_device import osd_decode_values

    rng = np.random.default_rng(21)
    code = hgp(rep_code(5), rep_code(5))
    h = code.hz
    n = code.N
    p = 0.05  # low enough that stragglers fit the compaction tier
    B = 2048
    dec = BPOSD_Decoder(h, np.full(n, p), max_iter=6, osd_order=6)
    assert osd_compaction_tiers(B) == (128, 512)
    errs = (rng.random((B, n)) < p).astype(np.uint8)
    synds = (errs @ h.T % 2).astype(np.uint8)
    out, aux = dec.decode_batch_device(jnp.asarray(synds))
    out = np.asarray(out)
    conv = np.asarray(aux["converged"])
    n_bad = int((~conv).sum())
    assert 0 < n_bad <= 512, n_bad  # a compaction tier actually ran
    # full-batch reference: OSD every shot, keep BP output where converged
    res = dec.bp_batch_device(jnp.asarray(synds))
    order = 0 if dec.osd_method in ("osd0", "osd_0") else dec.osd_order
    full = np.asarray(osd_decode_values(
        (n, dec._osd_plan.rank, order, 256, "twin"),
        dec._osd_plan.packed, dec._osd_plan.cost,
        jnp.asarray(synds), res.posterior_llr))
    expect = np.where(conv[:, None], np.asarray(res.error), full)
    assert np.array_equal(out, expect)


def test_bposd_device_all_converged_skips_osd():
    """B >= 64 batch where every shot converges must return BP's output
    (the n_bad == 0 cond branch) — trivially true for zero syndromes."""
    h = rep_code(9)
    n = h.shape[1]
    dec = BPOSD_Decoder(h, np.full(n, 0.1), max_iter=4, device_osd=True)
    out, aux = dec.decode_batch_device(jnp.zeros((128, h.shape[0]), jnp.uint8))
    assert np.asarray(aux["converged"]).all()
    assert not np.asarray(out).any()


def test_pallas_elimination_matches_xla_interpret():
    """The experimental Pallas RREF (interpret mode on CPU) must be
    bit-identical to the XLA elimination on every output."""
    import jax

    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(3)
    h = (rng.random((12, 24)) < 0.22).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, 24))
    synds = ((rng.random((8, 24)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 2, (8, 24)).astype(np.float32)
    perm = jnp.argsort(jnp.asarray(llrs), axis=1, stable=True).astype(
        jnp.int32)
    ref = od._eliminate(plan, perm, jnp.asarray(synds))
    pal = od._eliminate_pallas(plan, perm, jnp.asarray(synds), bt=8,
                               interpret=True)
    for a, b in zip(ref, pal):
        a = np.asarray(a)
        assert np.array_equal(a, np.asarray(b).astype(a.dtype))


def test_blocked_elimination_matches_percol():
    """The 32-column blocked elimination must be bit-identical to the
    per-column reference on every output."""
    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(11)
    for _ in range(3):
        m = int(rng.integers(4, 36))
        n = int(rng.integers(m + 2, 90))
        h = (rng.random((m, n)) < 0.25).astype(np.uint8)
        h[:, h.sum(0) == 0] = 1
        plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
        B = 16
        perm = jnp.argsort(
            jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
            axis=1, stable=True).astype(jnp.int32)
        synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T
                 % 2).astype(np.uint8)
        ref = od._eliminate(plan, perm, jnp.asarray(synds))
        blk = od._eliminate_blocked(plan, perm, jnp.asarray(synds))
        for a, b in zip(ref, blk):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_blocked_pallas_matches_xla_interpret():
    """The VMEM-resident blocked kernel (interpret mode on CPU) must agree
    with the XLA blocked elimination: same reduced syndrome, pivots, free
    positions, and free-panel bits (the T matrix OSD-E scores with)."""
    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(12)
    m, n, B, w = 14, 40, 16, 8
    h = (rng.random((m, n)) < 0.25).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
    perm = jnp.argsort(
        jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
        axis=1, stable=True).astype(jnp.int32)
    synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    u_a, pr_a, pc_a, ip_a, packed_a = od._eliminate_blocked(
        plan, perm, jnp.asarray(synds))
    synd_r, pr_b, pc_b, fword, fpos = od._eliminate_pallas_blocked(
        plan, perm, jnp.asarray(synds), fcap=w, bt=8, interpret=True)
    _check_blocked_freepanel_outputs(
        plan, w, u_a, pr_a, pc_a, ip_a, packed_a,
        synd_r, pr_b, pc_b, fword, fpos)


def _check_blocked_freepanel_outputs(plan, w, u_a, pr_a, pc_a, ip_a,
                                     packed_a, synd_r, pr_b, pc_b, fword,
                                     fpos):
    """Shared assertions: a free-panel elimination (Pallas kernel or its
    XLA twin) must agree with the per-column/blocked XLA reference on the
    reduced syndrome, pivots, free positions, and free-panel bits."""
    B = np.asarray(pr_a).shape[1]
    assert np.array_equal(
        np.asarray(u_a),
        np.asarray(jnp.take_along_axis(synd_r, pr_b, axis=0)))
    assert np.array_equal(np.asarray(pr_a), np.asarray(pr_b))
    assert np.array_equal(np.asarray(pc_a), np.asarray(pc_b))
    ip = np.asarray(ip_a)
    fp = np.asarray(fpos)
    pk = np.asarray(packed_a)
    fw_piv = np.asarray(jnp.take_along_axis(fword, pr_b, axis=0))
    pr = np.asarray(pr_a)
    for b in range(B):
        freecols = np.nonzero(~ip[:, b])[0][:w]
        assert np.array_equal(freecols, fp[:w, b])
        for i in range(plan.rank):
            for k in range(len(freecols)):
                t = fp[k, b]
                bit_ref = (pk[t >> 5, pr[i, b], b] >> (t & 31)) & 1
                assert bit_ref == (fw_piv[i, b] >> k) & 1


def test_blocked_twin_matches_xla_blocked():
    """The XLA twin of the blocked kernel (ISSUE 13 — the default CPU
    elimination behind device OSD) must agree with the independent blocked
    XLA reference on every output, across shapes including tall and
    rank-deficient H.  The twin is built from the SAME phase-A/phase-B
    bodies as the Pallas kernel (R007 'osd_elim_blocked' contract), so
    this pins the whole kernel/twin pair against the reference."""
    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(12)
    for m, n, B, w in [(14, 40, 16, 8), (12, 24, 24, 10), (40, 18, 8, 6),
                       (6, 90, 16, 12)]:
        h = (rng.random((m, n)) < 0.25).astype(np.uint8)
        h[:, h.sum(0) == 0] = 1
        plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
        perm = jnp.argsort(
            jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
            axis=1, stable=True).astype(jnp.int32)
        synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T
                 % 2).astype(np.uint8)
        u_a, pr_a, pc_a, ip_a, packed_a = od._eliminate_blocked(
            plan, perm, jnp.asarray(synds))
        synd_r, pr_b, pc_b, fword, fpos = od._eliminate_blocked_twin(
            plan, perm, jnp.asarray(synds), fcap=w)
        _check_blocked_freepanel_outputs(
            plan, min(w, n - plan.rank), u_a, pr_a, pc_a, ip_a, packed_a,
            synd_r, pr_b, pc_b, fword, fpos)
