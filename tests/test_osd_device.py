"""Device (TPU-style batched) OSD vs the numpy oracle and the host path.

The device kernel must reproduce _native/osd.cpp's semantics; the shared
numpy oracle (decoders/osd.py:_osd_numpy) is the spec.  Degenerate ML ties
may resolve differently across float32 (device) / float64 (host) cost sums,
so mismatching bit patterns are accepted only when both are
syndrome-consistent with equal total cost.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code, ring_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
from qldpc_fault_tolerance_tpu.decoders.osd import _channel_cost, _osd_numpy
from qldpc_fault_tolerance_tpu.ops.osd_device import (
    build_osd_plan,
    osd_decode_device,
)


def _assert_matches_oracle(h, probs, synds, llrs, order):
    h = (np.asarray(h) != 0).astype(np.uint8)
    plan = build_osd_plan(h, probs)
    dev = np.asarray(
        osd_decode_device(plan, jnp.asarray(synds), jnp.asarray(llrs),
                          osd_order=order)
    )
    cost = _channel_cost(probs)
    ref = _osd_numpy(h, synds, llrs.astype(np.float64), cost,
                     1 if order else 0, order)
    exact = (dev == ref).all(axis=1)
    dcost = (dev * cost[None]).sum(1)
    rcost = (ref * cost[None]).sum(1)
    synd_ok = ((dev @ h.T % 2) == synds).all(axis=1)
    ok = exact | ((np.abs(dcost - rcost) < 1e-4) & synd_ok)
    assert ok.all(), np.nonzero(~ok)
    return exact.mean()


@pytest.mark.parametrize("order", [0, 4, 10])
def test_device_osd_matches_oracle_random_ldpc(order):
    rng = np.random.default_rng(3)
    h = (rng.random((12, 24)) < 0.22).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    probs = rng.uniform(0.01, 0.3, 24)
    synds = ((rng.random((24, 24)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 2, (24, 24)).astype(np.float32)
    _assert_matches_oracle(h, probs, synds, llrs, order)


def test_device_osd_matches_oracle_rank_deficient():
    """Toric hx has dependent rows — rank < m must work."""
    rng = np.random.default_rng(5)
    code = hgp(ring_code(4), ring_code(4))
    h = code.hx.astype(np.uint8)
    n = h.shape[1]
    probs = np.full(n, 0.06)
    synds = ((rng.random((16, n)) < 0.08).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 1.5, (16, n)).astype(np.float32)
    _assert_matches_oracle(h, probs, synds, llrs, 10)


def test_device_osd_prior_above_half():
    h = np.array([[1, 1, 0], [0, 1, 1]], dtype=np.uint8)
    probs = np.array([0.01, 0.01, 0.9])
    plan = build_osd_plan(h, probs)
    out = np.asarray(
        osd_decode_device(plan, jnp.asarray([[0, 1]], dtype=jnp.uint8),
                          jnp.zeros((1, 3), jnp.float32), osd_order=3)
    )
    assert out[0].tolist() == [0, 0, 1]


def test_bposd_device_path_equals_host_path():
    """BPOSD_Decoder(device_osd=True) must agree with the host C++/numpy
    path decode-for-decode (same BP, same OSD semantics)."""
    rng = np.random.default_rng(9)
    h = rep_code(9)
    n = h.shape[1]
    probs = np.full(n, 0.1)
    host = BPOSD_Decoder(h, probs, max_iter=2, device_osd=False)
    dev = BPOSD_Decoder(h, probs, max_iter=2, device_osd=True)
    assert host.needs_host_postprocess and not dev.needs_host_postprocess
    synds = ((rng.random((32, n)) < 0.2).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    a = host.decode_batch(synds)
    b = dev.decode_batch(synds)
    cost = _channel_cost(probs)
    exact = (a == b).all(axis=1)
    tie = (np.abs((a * cost).sum(1) - (b * cost).sum(1)) < 1e-4)
    assert (exact | tie).all()


def test_bposd_device_inside_engine_matches_host_engine():
    """A data-noise engine with device-OSD BPOSD must produce statistically
    identical WER flags to the host-OSD engine on the same shot stream
    (same PRNG keys; only OSD-tie resolution may differ)."""
    import jax

    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = hgp(rep_code(3), rep_code(3))
    p = 0.06

    def make(device_osd):
        dx = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=4,
                           device_osd=device_osd)
        dz = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=4,
                           device_osd=device_osd)
        return CodeSimulator_DataError(
            code=code, decoder_x=dx, decoder_z=dz,
            pauli_error_probs=[p / 3] * 3, batch_size=128, seed=0,
        )

    key = jax.random.PRNGKey(2)
    wer_host, _ = make(False).WordErrorRate(512, key=key)
    wer_dev, _ = make(True).WordErrorRate(512, key=key)
    # identical shot streams; OSD ties can flip individual corrections but
    # the corrected-vs-failed outcome distribution must agree closely
    assert abs(wer_host - wer_dev) < 0.05


def test_bposd_device_all_converged_skips_osd():
    """B >= 64 batch where every shot converges must return BP's output
    (the n_bad == 0 cond branch) — trivially true for zero syndromes."""
    h = rep_code(9)
    n = h.shape[1]
    dec = BPOSD_Decoder(h, np.full(n, 0.1), max_iter=4, device_osd=True)
    out, aux = dec.decode_batch_device(jnp.zeros((128, h.shape[0]), jnp.uint8))
    assert np.asarray(aux["converged"]).all()
    assert not np.asarray(out).any()


def test_pallas_elimination_matches_xla_interpret():
    """The experimental Pallas RREF (interpret mode on CPU) must be
    bit-identical to the XLA elimination on every output."""
    import jax

    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(3)
    h = (rng.random((12, 24)) < 0.22).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, 24))
    synds = ((rng.random((8, 24)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    llrs = rng.normal(0, 2, (8, 24)).astype(np.float32)
    perm = jnp.argsort(jnp.asarray(llrs), axis=1, stable=True).astype(
        jnp.int32)
    ref = od._eliminate(plan, perm, jnp.asarray(synds))
    pal = od._eliminate_pallas(plan, perm, jnp.asarray(synds), bt=8,
                               interpret=True)
    for a, b in zip(ref, pal):
        a = np.asarray(a)
        assert np.array_equal(a, np.asarray(b).astype(a.dtype))


def test_blocked_elimination_matches_percol():
    """The 32-column blocked elimination must be bit-identical to the
    per-column reference on every output."""
    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(11)
    for _ in range(3):
        m = int(rng.integers(4, 36))
        n = int(rng.integers(m + 2, 90))
        h = (rng.random((m, n)) < 0.25).astype(np.uint8)
        h[:, h.sum(0) == 0] = 1
        plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
        B = 16
        perm = jnp.argsort(
            jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
            axis=1, stable=True).astype(jnp.int32)
        synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T
                 % 2).astype(np.uint8)
        ref = od._eliminate(plan, perm, jnp.asarray(synds))
        blk = od._eliminate_blocked(plan, perm, jnp.asarray(synds))
        for a, b in zip(ref, blk):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_blocked_pallas_matches_xla_interpret():
    """The VMEM-resident blocked kernel (interpret mode on CPU) must agree
    with the XLA blocked elimination: same reduced syndrome, pivots, free
    positions, and free-panel bits (the T matrix OSD-E scores with)."""
    from qldpc_fault_tolerance_tpu.ops import osd_device as od

    rng = np.random.default_rng(12)
    m, n, B, w = 14, 40, 16, 8
    h = (rng.random((m, n)) < 0.25).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
    perm = jnp.argsort(
        jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
        axis=1, stable=True).astype(jnp.int32)
    synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    u_a, pr_a, pc_a, ip_a, packed_a = od._eliminate_blocked(
        plan, perm, jnp.asarray(synds))
    synd_r, pr_b, pc_b, fword, fpos = od._eliminate_pallas_blocked(
        plan, perm, jnp.asarray(synds), fcap=w, bt=8, interpret=True)
    assert np.array_equal(
        np.asarray(u_a),
        np.asarray(jnp.take_along_axis(synd_r, pr_b, axis=0)))
    assert np.array_equal(np.asarray(pr_a), np.asarray(pr_b))
    assert np.array_equal(np.asarray(pc_a), np.asarray(pc_b))
    ip = np.asarray(ip_a)
    fp = np.asarray(fpos)
    pk = np.asarray(packed_a)
    fw_piv = np.asarray(jnp.take_along_axis(fword, pr_b, axis=0))
    pr = np.asarray(pr_a)
    for b in range(B):
        freecols = np.nonzero(~ip[:, b])[0][:w]
        assert np.array_equal(freecols, fp[:w, b])
        for i in range(plan.rank):
            for k in range(len(freecols)):
                t = fp[k, b]
                bit_ref = (pk[t >> 5, pr[i, b], b] >> (t & 31)) & 1
                assert bit_ref == (fw_piv[i, b] >> k) & 1
