import glob
import os

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import (
    CssCode,
    classical_code_distance,
    gf2,
    hgp,
    load_code,
    load_mat_pair,
    load_npy_pair,
    load_pickle_code,
    rep_code,
    ring_code,
)
from conftest import REFERENCE_CODES_LIB


def test_rep_and_ring_codes():
    assert rep_code(3).shape == (2, 3)
    assert ring_code(3).shape == (3, 3)
    assert classical_code_distance(rep_code(5)) == 5
    assert classical_code_distance(ring_code(4)) == 4


def test_surface_code_from_hgp():
    # hgp(rep_code(d), rep_code(d)) is the distance-d surface code
    d = 3
    code = hgp(rep_code(d), rep_code(d), compute_distance=True)
    assert code.N == d * d + (d - 1) * (d - 1)  # 13
    assert code.K == 1
    code.validate()
    assert code.D == d


def test_toric_code_from_hgp():
    # hgp(ring_code(d), ring_code(d)) is the [[2d^2, 2, d]] toric code
    # (SpaceTimeDecodingDemo cell 1 uses d=3)
    d = 3
    code = hgp(ring_code(d), ring_code(d))
    assert code.N == 2 * d * d
    assert code.K == 2
    code.validate()


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl")),
    reason="reference codes_lib not mounted",
)
def test_hgp_matches_reference_pickle_exactly():
    """Our hgp() convention must reproduce bposd's hx/hz bit-for-bit."""
    import pickle

    from qldpc_fault_tolerance_tpu.codes.loaders import load_object

    obj = load_object(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl"))
    h1 = gf2.to_gf2(obj.__dict__["h1"])
    ref_hx = gf2.to_gf2(obj.__dict__["hx"])
    ref_hz = gf2.to_gf2(obj.__dict__["hz"])
    code = hgp(h1, h1)
    assert np.array_equal(code.hx, ref_hx)
    assert np.array_equal(code.hz, ref_hz)
    assert code.N == 225 and code.K == 17


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl")),
    reason="reference codes_lib not mounted",
)
def test_load_pickle_code():
    code = load_pickle_code(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl"))
    assert (code.N, code.K) == (225, 17)
    code.validate()


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_CODES_LIB, "GenBicycleA1_hx.mat")),
    reason="reference codes_lib not mounted",
)
@pytest.mark.parametrize(
    "stem,expected",
    [("GenBicycleA1", (126, 12)), ("GenBicycleA2", (254, 14)), ("GenBicycleA3", (510, 16))],
)
def test_load_gb_codes(stem, expected):
    code = load_mat_pair(os.path.join(REFERENCE_CODES_LIB, stem + "_hx.mat"))
    assert (code.N, code.K) == expected
    code.validate()


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_CODES_LIB, "LP_Matg8_L16_Dmin12_hx.mat")),
    reason="reference codes_lib not mounted",
)
def test_load_lp_code():
    code = load_mat_pair(
        os.path.join(REFERENCE_CODES_LIB, "LP_Matg8_L16_Dmin12_hx.mat")
    )
    assert (code.N, code.K) == (544, 80)


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_CODES_LIB, "tanner_code1_hx.npy")),
    reason="reference codes_lib not mounted",
)
def test_load_tanner_npy():
    code = load_npy_pair(os.path.join(REFERENCE_CODES_LIB, "tanner_code1_hx.npy"))
    assert code.hx.shape == (240, 360)
    assert code.hz.shape == (120, 360)


def test_save_load_roundtrip(tmp_path):
    from qldpc_fault_tolerance_tpu.codes import save_code

    code = hgp(rep_code(3), rep_code(3))
    code.D = 3
    p = str(tmp_path / "c.npz")
    save_code(code, p)
    code2 = load_code(p)
    assert np.array_equal(code.hx, code2.hx)
    assert np.array_equal(code.lz, code2.lz)
    assert code2.D == 3


def test_css_rejects_invalid():
    with pytest.raises(ValueError):
        CssCode(hx=np.array([[1, 1, 0]]), hz=np.array([[1, 0, 0]]))


REPO_CODES_LIB = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                              "codes_lib_tpu")


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO_CODES_LIB, "hgp_34_n225.npz")),
    reason="regenerated family not present",
)
def test_family_matches_published_parameters():
    """The regenerated hgp_34 family must carry the published dimensions
    ([[225,17]]/[[625,25]]/[[1225,49]]/[[1600,64]], BASELINE.md)."""
    expected = {"n225": (225, 17), "n625": (625, 25),
                "n1225": (1225, 49), "n1600": (1600, 64)}
    for tag, (n, k) in expected.items():
        code = load_code(os.path.join(REPO_CODES_LIB, f"hgp_34_{tag}.npz"))
        assert (code.N, code.K) == (n, k), tag


@pytest.mark.skipif(
    not (os.path.exists(os.path.join(REPO_CODES_LIB, "hgp_34_n225.npz"))
         and os.path.exists(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl"))),
    reason="needs both regenerated npz and reference pickle",
)
def test_family_n225_is_exact_reference_code():
    """n225 is built from the seed extracted out of the reference pickle, so
    hx/hz must be bit-identical and the logicals span-equivalent."""
    ours = load_code(os.path.join(REPO_CODES_LIB, "hgp_34_n225.npz"))
    ref = load_pickle_code(os.path.join(REFERENCE_CODES_LIB, "hgp_34_n225.pkl"))
    assert np.array_equal(ours.hx, ref.hx)
    assert np.array_equal(ours.hz, ref.hz)
    both = np.vstack([ours.lx, ref.lx, ours.hx])
    assert gf2.rank(both) == gf2.rank(np.vstack([ours.lx, ours.hx]))
