"""BP kernel v2 (ISSUE 9): sparse index-gather incidence, int8 min-sum,
whole-pipeline fusion, kernel-variant telemetry, VMEM gate consistency.

Kernels run in interpret mode (CPU); the real mosaic path is gated by the
calibrated VMEM table and exercised by bench.py / the driver on TPU.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.noise import depolarizing_xz
from qldpc_fault_tolerance_tpu.ops import bp, bp_pallas, gf2_pallas
from qldpc_fault_tolerance_tpu.ops.linalg import ParityOp
from qldpc_fault_tolerance_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _irregular_h(seed=0, m=24, n=48):
    """A parity-check matrix with IRREGULAR row weights (2..6) so the
    slot-major layout has genuinely padded slots on most rows."""
    rng = np.random.default_rng(seed)
    h = np.zeros((m, n), np.uint8)
    for i in range(m):
        w = int(rng.integers(2, 7))
        h[i, rng.choice(n, size=w, replace=False)] = 1
    # every column needs at least one check (keeps the graph connected
    # enough for BP to make progress)
    for j in np.nonzero(h.sum(0) == 0)[0]:
        h[rng.integers(0, m), j] = 1
    return h


def _synd_batch(h, b=128, p=0.05, seed=3):
    key = jax.random.PRNGKey(seed)
    _, ez = depolarizing_xz(key, (b, h.shape[1]), (p / 3, p / 3, p / 3))
    return ParityOp(h)(ez)


def _results_equal(a, b):
    for fa, fb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


# ---------------------------------------------------------------------------
# bit-exactness: sparse kernel vs dense v1 kernel vs XLA twin
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("hseed", [0, 1])
def test_sparse_bitexact_vs_dense_and_twin_irregular(hseed):
    """The v2 kernel synthesizes the SAME one-hot operands v1 loads and
    shares its loop body, so across irregular row weights (padded slots)
    every output plane is bit-exact: v1 kernel == v2 kernel == v2 twin."""
    h = _irregular_h(seed=hseed)
    graph = bp.build_tanner_graph_host(h)
    pg = bp_pallas.build_pallas_head(graph)
    sg = bp_pallas.build_sparse_head(graph)
    assert sg.rw == pg.rw and sg.m == pg.m and sg.n == pg.n
    # the v2 incidence is orders of magnitude smaller than the v1 stack
    assert sg.idx_bytes < pg.scat_bytes
    llr0 = bp.llr_from_probs(np.full(h.shape[1], 0.05))
    synd = _synd_batch(h)

    v1 = bp_pallas.bp_head_pallas(pg, synd, llr0, head_iters=6,
                                  block_b=64, interpret=True)
    v2k = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=6,
                                   block_b=64, interpret=True)
    v2t = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=6,
                                   block_b=64, backend="xla")
    _results_equal(v1, v2k)
    _results_equal(v2k, v2t)


def test_sparse_early_stop_freeze_semantics():
    h = _irregular_h(seed=2)
    sg = bp_pallas.build_sparse_head(bp.build_tanner_graph_host(h))
    llr0 = bp.llr_from_probs(np.full(h.shape[1], 0.05))
    synd = _synd_batch(h)
    fixed = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=12,
                                     block_b=64, backend="xla")
    early = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=12,
                                     block_b=64, backend="xla",
                                     early_stop=True)
    np.testing.assert_array_equal(np.asarray(fixed.converged),
                                  np.asarray(early.converged))
    conv = np.asarray(fixed.converged)
    np.testing.assert_array_equal(np.asarray(fixed.error)[conv],
                                  np.asarray(early.error)[conv])


def test_int8_kernel_vs_twin_bitexact_and_valid():
    """int8 kernel (MXU int8 product) and twin (index scatter-add) share
    exact integer accumulation, so they are bit-exact; converged int8
    shots must still satisfy their syndrome exactly (the parity check is
    computed on the dequantized totals, exact GF(2))."""
    h = _irregular_h(seed=4)
    sg = bp_pallas.build_sparse_head(bp.build_tanner_graph_host(h))
    llr0 = bp.llr_from_probs(np.full(h.shape[1], 0.05))
    synd = _synd_batch(h)
    k = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=16,
                                 block_b=64, interpret=True,
                                 quantize="int8", early_stop=True)
    t = bp_pallas.bp_head_sparse(sg, synd, llr0, head_iters=16,
                                 block_b=64, backend="xla",
                                 quantize="int8", early_stop=True)
    _results_equal(k, t)
    conv = np.asarray(k.converged)
    assert conv.mean() > 0.5  # int8 still decodes this easy cell
    par = np.asarray(k.error) @ h.T % 2
    np.testing.assert_array_equal(par[conv], np.asarray(synd)[conv])


# ---------------------------------------------------------------------------
# int8 WER parity on the hgp_rep3 / hgp_rep4 parity cells
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("d", [3, 4])
def test_int8_wer_parity_contract(d):
    """A quantize='int8' BPDecoder's WER matches the f32 decoder's within
    the documented contract (ops/bp_pallas.int8_parity_tolerance) on the
    hgp_rep parity cells — the tier-1 half of the quantization contract
    (bench.py BENCH_QUANT=1 is the perf half, same tolerance helper)."""
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    code = hgp(rep_code(d), rep_code(d))
    p = 0.06
    shots = 4096

    def run(quantize):
        dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=20,
                          quantize=quantize)
        dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=20,
                          quantize=quantize)
        sim = CodeSimulator_DataError(
            code=code, decoder_x=dec_x, decoder_z=dec_z,
            pauli_error_probs=[p / 3] * 3, batch_size=512, seed=11,
            scan_chunk=4)
        return sim.WordErrorRate(shots)[0]

    wer_f32 = run(None)
    wer_int8 = run("int8")
    tol = bp_pallas.int8_parity_tolerance(wer_f32, shots)
    assert abs(wer_int8 - wer_f32) <= tol, (
        f"int8 WER {wer_int8} vs f32 {wer_f32}: delta "
        f"{abs(wer_int8 - wer_f32)} exceeds the contract tolerance {tol}")


# ---------------------------------------------------------------------------
# whole-pipeline fused v2
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def small_code():
    return hgp(rep_code(3), rep_code(3))


def _fspec2(code, p):
    llr = bp.llr_from_probs(np.full(code.N, p))
    return gf2_pallas.build_fused_decode_spec(
        code.hx, code.hz, code.lx, code.lz, (p / 3,) * 3, llr, llr)


@pytest.mark.parametrize("quantize", [None, "int8"])
def test_fused_v2_kernel_vs_twin_bitexact(small_code, quantize):
    spec2 = _fspec2(small_code, 0.05)
    key = jax.random.PRNGKey(9)
    kw = dict(eval_type="Total", max_iter_z=20, max_iter_x=20,
              quantize=quantize)
    cnt_t, mw_t, ax_t, az_t = gf2_pallas.fused_decode_stats(
        spec2, key, 256, backend="xla", **kw)
    cnt_k, mw_k, ax_k, az_k = gf2_pallas.fused_decode_stats(
        spec2, key, 256, interpret=True, **kw)
    assert int(cnt_t) == int(cnt_k)
    assert int(mw_t) == int(mw_k)
    for a, b in ((ax_t, ax_k), (az_t, az_k)):
        np.testing.assert_array_equal(np.asarray(a["converged"]),
                                      np.asarray(b["converged"]))
        np.testing.assert_array_equal(np.asarray(a["iterations"]),
                                      np.asarray(b["iterations"]))


def test_fused_v2_zero_noise_zero_failures(small_code):
    spec2 = _fspec2(small_code, 1e-9)
    cnt, mw, ax, az = gf2_pallas.fused_decode_stats(
        spec2, jax.random.PRNGKey(1), 256, eval_type="Total",
        max_iter_z=20, max_iter_x=20, backend="xla")
    assert int(cnt) == 0
    assert np.asarray(ax["converged"]).all()


def test_fused_v2_engine_matches_direct_call(small_code):
    """The engine's fused_sampler="v2" unit returns exactly what the
    dispatcher returns for the same key (the megabatch carry folds these
    device scalars)."""
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim import data_error as de

    code = small_code
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=20)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=20)
    sim = de.CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=256, seed=0,
        fused_sampler="v2")
    key = jax.random.PRNGKey(5)
    cfg = sim._cfg(256)
    cnt, mw = de._stats_one_batch(cfg, sim._dev_state, key)
    cnt_d, mw_d, _ax, _az = gf2_pallas.fused_decode_stats(
        sim._dev_state["fspec2"], key, 256, eval_type="Total",
        max_iter_z=20, max_iter_x=20, backend="xla")
    assert int(cnt) == int(cnt_d)
    assert int(mw) == int(mw_d)


def test_fused_v2_ladder_rungs(small_code):
    """fused_v2 -> fused_pallas -> fused_xla -> packed are the first
    rungs of the v2 engine's degradation ladder, in order."""
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder

    code = small_code
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=20)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=20)
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=256, seed=0,
        fused_sampler="v2")
    try:
        assert sim._degrade_once() == "fused_v2->fused_pallas"
        assert sim._fused_sampler is True
        assert sim._degrade_once() == "fused_pallas->fused_xla"
        assert gf2_pallas.FORCE_XLA_TWIN
        assert sim._degrade_once() == "fused->packed"
        assert sim._fused_sampler is False
    finally:
        gf2_pallas.FORCE_XLA_TWIN = False


def test_fused_v2_warm_p_sweep_adds_zero_retraces(small_code):
    """Retrace-budget guard (PR-2 tracker): a warm fused-v2 run at NEW
    p-values must add zero retraces — every p-dependent array (cuts, LLR
    priors) rides the traced FusedDecodeSpec, so baking a p into the
    program would recompile per point."""
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    code = small_code

    def run(p):
        dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=20)
        dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=20)
        sim = CodeSimulator_DataError(
            code=code, decoder_x=dec_x, decoder_z=dec_z,
            pauli_error_probs=[p / 3] * 3, batch_size=256, seed=0,
            scan_chunk=2, fused_sampler="v2")
        sim.WordErrorRate(512)

    telemetry.reset()
    telemetry.enable()
    try:
        for p in (0.03, 0.05):
            run(p)
        before = telemetry.compile_stats().get("jax.retraces", 0)
        for p in (0.04, 0.06):
            run(p)
        after = telemetry.compile_stats().get("jax.retraces", 0)
    finally:
        telemetry.disable()
    assert after - before == 0, (
        f"{after - before} retraces on a warm fused-v2 p-sweep")


# ---------------------------------------------------------------------------
# VMEM calibration: v2 gate keys + estimator-vs-probe consistency
# ---------------------------------------------------------------------------
def _table():
    with open(os.path.join(REPO, "calibration", "vmem_table.json")) as fh:
        return json.load(fh)


def test_v2_gate_keys_exist_in_checked_in_table():
    table = _table()
    gates = table.get("gates", {})
    for key in ("bp_head_scat_limit_bytes", "bp_head_v2_fixed_limit_bytes"):
        assert isinstance(gates.get(key), (int, float)) and gates[key] > 0, (
            f"gates.{key} missing from the checked-in calibration table")
    kernels = {e["kernel"] for e in table["entries"]}
    assert {"bp_head_v2", "fused_decode"} <= kernels
    # every shipped shape (incl. the n1225/n1600 unlock targets) is probed
    v2_n = {e["n"] for e in table["entries"] if e["kernel"] == "bp_head_v2"}
    assert {1225, 1600} <= v2_n


def test_v2_estimator_never_exceeds_probed_failure_point():
    """For every bp_head_v2 entry: the estimator must not claim a block
    the probe recorded as FAILING, and must admit the probed max block
    (the table and the runtime gate agree about the feasible frontier)."""
    table = _table()
    for e in table["entries"]:
        if e["kernel"] != "bp_head_v2":
            continue
        per_shot = e["analytic_per_shot_bytes"]
        budget = 30 * 1024 * 1024 - e["fixed_overhead_bytes"]
        assert budget > 0, f"{e['code']}: fixed overhead busts the budget"
        if e["max_block_b"]:
            assert e["max_block_b"] * per_shot <= budget, (
                f"{e['code']}: probed block {e['max_block_b']} exceeds "
                "the estimator budget — estimator and probe disagree")
        for att in e["attempts"]:
            if not att["ok"] and att["block"] * per_shot <= budget \
                    and e["probe_batch"] % att["block"] == 0:
                raise AssertionError(
                    f"{e['code']}: estimator admits block {att['block']} "
                    "that the probe recorded as failing")


def test_n1225_n1600_route_onto_v2_vmem_path():
    """The tentpole unlock: shapes the v1 scat gate rejects (>8 MB
    resident stack) fit the v2 gate and get a feasible batch tile."""
    from qldpc_fault_tolerance_tpu.codes import load_code

    for name in ("hgp_34_n1225", "hgp_34_n1600"):
        path = os.path.join(REPO, "codes_lib_tpu", f"{name}.npz")
        if not os.path.exists(path):
            pytest.skip(f"{name} not shipped")
        c = load_code(path)
        g = bp.build_tanner_graph_host(c.hx)
        v1 = bp_pallas.build_pallas_head(g)
        sg = bp_pallas.build_sparse_head(g)
        assert not v1.fits_vmem(), f"{name}: v1 gate unexpectedly admits"
        assert sg.fits_vmem(), f"{name}: v2 gate rejects"
        assert sg.max_block_b(16384) > 0, f"{name}: no feasible v2 tile"


# ---------------------------------------------------------------------------
# kernel-variant telemetry
# ---------------------------------------------------------------------------
def test_kernel_variant_resolution(small_code):
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.decoders.bp_decoders import (
        kernel_variant,
    )

    code = small_code
    dec = BPDecoder(code.hx, np.full(code.N, 0.05), max_iter=20)
    # CPU: no head -> xla_twin; the static still names its routing tag
    assert dec.kernel_variant == "xla_twin"
    assert dec.device_static[5] == "none"
    dec8 = BPDecoder(code.hx, np.full(code.N, 0.05), max_iter=20,
                     quantize="int8")
    assert dec8.device_static[5] == "v2_int8"
    # off-TPU the int8 head serves through the twin -> xla_twin variant
    assert dec8.kernel_variant == "xla_twin"
    # synthetic statics: TPU routing names the kernels
    import qldpc_fault_tolerance_tpu.ops.bp_pallas as bpp

    orig = bpp.sparse_serves_pallas
    bpp.sparse_serves_pallas = lambda: True
    try:
        st = ("bp", 20, "minimum_sum", 0.625, True, "v2")
        assert kernel_variant(st, {}) == "sparse_gather"
        st8 = ("bp", 20, "minimum_sum", 0.625, True, "v2_int8")
        assert kernel_variant(st8, {}) == "sparse_int8"
        # per-batch engage gates: decodes the head disengages from report
        # the exact-f32 path they really run, not the head's tag —
        # sub-TWO_PHASE_MIN_BATCH request, non-dividing bucket, vs an
        # engaged full batch
        state8 = dec8.device_state
        st8_real = dec8.device_static
        assert kernel_variant(st8_real, state8, 8) == "xla_twin"
        assert kernel_variant(st8_real, state8, 96) == "xla_twin"
        assert kernel_variant(st8_real, state8, 512) == "sparse_int8"
    finally:
        bpp.sparse_serves_pallas = orig
    assert kernel_variant(("bp", 20, "minimum_sum", 0.625, True, "v1"),
                          {}) == "dense_onehot"
    # bposd/space-time wrappers resolve through to the inner BP static
    inner = ("bp", 20, "minimum_sum", 0.625, True, "none")
    assert kernel_variant(("bposd_dev", inner, 13, 6, 10, "pallas"),
                          {}) == "xla_twin"
    assert kernel_variant(("st_syndrome", 2, 6, 13, inner), {}) == "xla_twin"


def test_wer_run_event_carries_kernel_variant(small_code):
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    code = small_code
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=20)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=20)
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=256, seed=0)
    telemetry.reset()
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sim.WordErrorRate(512)
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    evs = [e for e in sink.records if e.get("kind") == "wer_run"]
    assert evs and evs[-1]["kernel_variant"] == "xla_twin"
    assert not telemetry.validate_event(evs[-1])
    snap = telemetry.snapshot()
    assert snap["bp.kernel_variant"]["value"] == \
        bp_pallas.KERNEL_VARIANTS.index("xla_twin")
    assert snap["bp.kernel_variant.xla_twin"]["value"] >= 1


# ---------------------------------------------------------------------------
# serve integration: sessions record (and match) the offline variant
# ---------------------------------------------------------------------------
def test_serve_session_variant_matches_offline(small_code):
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.serve.session import DecodeSession

    code = small_code
    dec = BPDecoder(code.hx, np.full(code.N, 0.05), max_iter=20)
    sess = DecodeSession("s-v2", decoder=dec)
    # the AOT programs compile from the SAME (static, state) pair, so the
    # warm serving path's kernel routing equals the offline decode's
    assert sess.kernel_variant == dec.kernel_variant
    telemetry.reset()
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        synd = np.asarray(_synd_batch(np.asarray(code.hx), b=8))
        out = sess.decode(synd)
        np.testing.assert_array_equal(out.corrections,
                                      dec.decode_batch(synd))
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    compiles = [e for e in sink.records
                if e.get("kind") == "serve_session"
                and e.get("event") == "compile"]
    assert compiles
    assert compiles[-1]["kernel_variant"] == dec.kernel_variant
    assert not telemetry.validate_event(compiles[-1])


def test_factory_state_matches_built_decoder_with_quantize(small_code):
    """GetDecoderState fast path stays pinned to the full build under the
    new static layout (head tag + quantize)."""
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class

    code = small_code
    params = {"h": np.asarray(code.hx), "p_data": 0.05}
    for quant in (None, "int8"):
        cls = BP_Decoder_Class(max_iter_ratio=10, bp_method="minimum_sum",
                               ms_scaling_factor=0.625, quantize=quant)
        static, state = cls.GetDecoderState(dict(params))
        dec = cls.GetDecoder(dict(params))
        assert static == dec.device_static
        np.testing.assert_allclose(np.asarray(state["llr0"]),
                                   np.asarray(dec.device_state["llr0"]))
        if quant:
            assert static[5] == "v2_int8"
            assert state["pallas"] is not None
