"""Pallas BP kernel vs the XLA reference implementation.

Runs in interpreter mode so it exercises the kernel logic on CPU; the real
Mosaic compilation path is exercised by bench.py / the driver on TPU.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.noise import depolarizing_xz
from qldpc_fault_tolerance_tpu.ops import bp
from qldpc_fault_tolerance_tpu.ops.bp_pallas import (
    bp_head_pallas,
    build_pallas_head,
)
from qldpc_fault_tolerance_tpu.ops.linalg import ParityOp


@pytest.fixture(scope="module")
def setup():
    code = hgp(rep_code(4), rep_code(5))
    p = 0.04
    graph = bp.build_tanner_graph(code.hx)
    pg = build_pallas_head(graph)
    llr0 = bp.llr_from_probs(np.full(code.N, p))
    key = jax.random.PRNGKey(3)
    _, ez = depolarizing_xz(key, (128, code.N), (p / 3, p / 3, p / 3))
    synd = ParityOp(code.hx)(ez)
    return code, graph, pg, llr0, synd


def test_head_matches_xla_reference(setup):
    code, graph, pg, llr0, synd = setup
    ref = bp.bp_decode(graph, synd, llr0, max_iter=3)
    res = bp_head_pallas(pg, synd, llr0, head_iters=3, block_b=64,
                         interpret=True)
    # converged flags must agree with the f32 path on this easy batch, and
    # every converged shot must satisfy its syndrome exactly
    np.testing.assert_array_equal(
        np.asarray(ref.converged), np.asarray(res.converged)
    )
    conv = np.asarray(res.converged)
    par = np.asarray(res.error) @ code.hx.T % 2
    np.testing.assert_array_equal(par[conv], np.asarray(synd)[conv])
    agree = (np.asarray(ref.error) == np.asarray(res.error)).all(axis=1)
    assert agree[conv].mean() > 0.98


def test_early_stop_matches_fixed_iters(setup):
    code, graph, pg, llr0, synd = setup
    fixed = bp_head_pallas(pg, synd, llr0, head_iters=12, block_b=64,
                           interpret=True)
    early = bp_head_pallas(pg, synd, llr0, head_iters=12, block_b=64,
                           early_stop=True, interpret=True)
    # freeze-at-convergence makes outputs independent of when the loop exits
    np.testing.assert_array_equal(
        np.asarray(fixed.converged), np.asarray(early.converged)
    )
    conv = np.asarray(fixed.converged)
    np.testing.assert_array_equal(
        np.asarray(fixed.error)[conv], np.asarray(early.error)[conv]
    )
    np.testing.assert_array_equal(
        np.asarray(fixed.iterations)[conv], np.asarray(early.iterations)[conv]
    )


def test_two_phase_pallas_plumbing(setup):
    """two_phase with a pallas head/tail returns valid corrections for
    converged shots and the same convergence pattern as the XLA path."""
    code, graph, pg, llr0, synd = setup
    # interpret-mode pallas inside jitted two_phase is exercised via direct
    # call (the decoder only enables the pallas path on a real TPU backend)
    ref = bp.bp_decode_two_phase(graph, synd, llr0, max_iter=12)
    res = bp.bp_decode_two_phase(
        graph, synd, llr0, max_iter=12, tail_capacity=64,
    )
    np.testing.assert_array_equal(
        np.asarray(ref.converged), np.asarray(res.converged)
    )
