import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code, ring_code
from qldpc_fault_tolerance_tpu.decoders import (
    BP_Decoder_Class,
    BPDecoder,
    BPOSD_Decoder,
    BPOSD_Decoder_Class,
    FirstMinBPDecoder,
    GetSpaceTimeCheckMat,
    ST_BP_Decoder_Class,
    ST_BP_Decoder_syndrome,
)


def test_space_time_check_mat_structure():
    # spec: src/Decoders.py:179-194 — diagonal [H|I], subdiagonal [0|I]
    h = np.array([[1, 1, 0], [0, 1, 1]], dtype=np.uint8)
    st = GetSpaceTimeCheckMat(h, 3)
    m, n = 2, 3
    assert st.shape == (3 * m, 3 * (n + m))
    for i in range(3):
        blk = st[i * m:(i + 1) * m, i * (n + m):(i + 1) * (n + m)]
        assert np.array_equal(blk[:, :n], h)
        assert np.array_equal(blk[:, n:], np.eye(m, dtype=np.uint8))
        if i >= 1:
            sub = st[i * m:(i + 1) * m, (i - 1) * (n + m):i * (n + m)]
            assert not sub[:, :n].any()
            assert np.array_equal(sub[:, n:], np.eye(m, dtype=np.uint8))
    # everything else zero
    assert st.sum() == 3 * (h.sum() + m) + 2 * m


def test_bposd_decoder_corrects_beyond_bp():
    # surface code d=5 Z-sector: some weight-2 errors defeat plain BP
    # (degenerate half-plane splits) but BP+OSD must return a syndrome-valid,
    # low-cost correction for every shot.
    code = hgp(rep_code(5), rep_code(5))
    h = code.hz
    rng = np.random.default_rng(7)
    errs = (rng.random((128, code.N)) < 0.04).astype(np.uint8)
    synds = errs @ h.T % 2
    dec = BPOSD_Decoder(h, np.full(code.N, 0.04), max_iter=15, osd_order=6)
    out = dec.decode_batch(synds)
    assert np.array_equal(out @ h.T % 2, synds)  # every shot satisfies syndrome


def test_bp_decoder_single_shot_contract():
    h = rep_code(5)
    dec = BPDecoder(h, np.full(5, 0.05), max_iter=10)
    e = np.zeros(5, np.uint8)
    e[2] = 1
    out = dec.decode(h @ e % 2)
    assert out.shape == (5,)
    assert np.array_equal(out, e)
    assert dec.h.shape == (4, 5)


def test_firstmin_decoder_reduces_syndrome():
    code = hgp(rep_code(5), rep_code(5))
    h = code.hz
    rng = np.random.default_rng(9)
    errs = (rng.random((32, code.N)) < 0.02).astype(np.uint8)
    synds = errs @ h.T % 2
    dec = FirstMinBPDecoder(h, np.full(code.N, 0.02), max_iter=code.N // 5)
    out = dec.decode_batch(synds)
    # accepted corrections never increase syndrome weight
    resid = (out @ h.T % 2) ^ synds
    assert (resid.sum(axis=1) <= synds.sum(axis=1)).all()
    # most low-weight shots fully resolve
    assert (resid.sum(axis=1) == 0).mean() > 0.5


def test_st_syndrome_decoder_identifies_data_vs_measurement_error():
    # Two rounds on a repetition code.  Input convention: DIFFERENCE detector
    # history (d_0 = s_0, d_i = s_i ^ s_{i-1}), matching the phenom-ST
    # simulator's feed (src/Simulators_SpaceTime.py:471-479).
    h = rep_code(5)
    m, n = h.shape
    dec = ST_BP_Decoder_syndrome(h, p_data=0.05, p_synd=0.05, max_iter=30, num_rep=2)
    e = np.zeros(n, np.uint8)
    e[2] = 1
    s = h @ e % 2
    # data error in round 0, persists: s_0 = s_1 = s -> differences (s, 0)
    corr = dec.decode(np.stack([s, np.zeros(m, np.uint8)]))
    assert np.array_equal(corr, e)
    # measurement flip in round 0 only: s_0 = s_meas, s_1 = 0 -> differences (s, s);
    # min-weight explanations tie between syndrome-error and data-error pairs,
    # so only require: any data correction returned must reproduce the final
    # (true) syndrome state, i.e. H @ corr must equal 0 or the decode flags it
    corr2 = dec.decode(np.stack([s, s]))
    assert corr2.shape == (n,)


def test_factory_contract_bp():
    fac = BP_Decoder_Class(max_iter_ratio=30, bp_method="minimum_sum", ms_scaling_factor=0.625)
    code = hgp(rep_code(3), rep_code(3))
    h_ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    dec = fac.GetDecoder({"h": h_ext, "p_data": 0.01, "p_syndrome": 0.02})
    n = code.N
    m = code.hx.shape[0]
    assert dec.channel_probs.shape == (n + m,)
    np.testing.assert_allclose(dec.channel_probs[:n], 0.01)
    np.testing.assert_allclose(dec.channel_probs[n:], 0.02)
    assert dec.max_iter == max(1, int(n / 30))


def test_factory_contract_bposd():
    fac = BPOSD_Decoder_Class(10, "minimum_sum", 0.625, "osd_e", 10)
    code = hgp(rep_code(3), rep_code(3))
    dec = fac.GetDecoder({"h": code.hx, "p_data": 0.05})
    assert isinstance(dec, BPOSD_Decoder)
    assert dec.osd_order == 10
    assert dec.max_iter == max(1, int(code.N / 10))


def test_factory_st_quirk_psynd_from_pdata():
    # reference quirk (src/Decoders.py:243-246): p_syndrome value ignored,
    # prior uses p_data when the key is present
    fac = ST_BP_Decoder_Class(30, "minimum_sum", 0.625)
    h = rep_code(5)
    dec = fac.GetDecoder({"h": h, "p_data": 0.03, "p_syndrome": 0.9, "num_rep": 2})
    probs = dec._bp.channel_probs
    n, m = 5, 4
    np.testing.assert_allclose(probs[:n], 0.03)
    np.testing.assert_allclose(probs[n:n + m], 0.03)  # NOT 0.9


def test_fused_pair_matches_separate_decodes():
    """FusedBPPair (block-diagonal sectors=) must be bit-identical to the two
    separate BPDecoder runs (per-sector freeze preserves each sub-decoder's
    return-on-convergence semantics)."""
    import jax

    code = hgp(rep_code(4), rep_code(5))
    dec_x = BPDecoder(code.hz, np.full(code.N, 0.06), max_iter=40)
    dec_z = BPDecoder(code.hx, np.full(code.N, 0.06), max_iter=40)
    from qldpc_fault_tolerance_tpu.decoders.bp_decoders import FusedBPPair

    assert FusedBPPair.compatible(dec_x, dec_z)
    fused = FusedBPPair(dec_x, dec_z)

    key = jax.random.PRNGKey(7)
    from qldpc_fault_tolerance_tpu.noise import depolarizing_xz
    from qldpc_fault_tolerance_tpu.ops.linalg import ParityOp

    ex, ez = depolarizing_xz(key, (96, code.N), (0.02, 0.02, 0.02))
    sx = ParityOp(code.hz)(ex)
    sz = ParityOp(code.hx)(ez)
    cx_f, cz_f = fused.decode_pair_device(sx, sz)
    cx, _ = dec_x.decode_batch_device(sx)
    cz, _ = dec_z.decode_batch_device(sz)
    np.testing.assert_array_equal(np.asarray(cx_f), np.asarray(cx))
    np.testing.assert_array_equal(np.asarray(cz_f), np.asarray(cz))
