"""Device OSD-CS (ISSUE 19): the batched order-w combination sweep.

Covers the tentpole contracts: host-oracle parity at osd_order 0/4/10 on
tall, rank-deficient, and random H (bit-equal or the documented
float32-tie on a syndrome-consistent candidate), sweep kernel == XLA
twin bit-exactness on irregular shapes, the full-maintenance blocked
elimination twin vs the per-column blocked oracle, the loud
OSD_CS_MAX_ORDER cap, warm-sweep zero retraces + zero host round-trips
with the osd.cs_* device-tele counters, the device_cs serve backend, and
the n1225 mesh-sharded BPOSD bucket smoke (CPU mesh)."""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, load_code, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
from qldpc_fault_tolerance_tpu.decoders.osd import (
    OSD_CS_MAX_ORDER,
    _channel_cost,
    osd_decode_batch,
)
from qldpc_fault_tolerance_tpu.ops import osd_cs_device as cs
from qldpc_fault_tolerance_tpu.ops import osd_device as od
from qldpc_fault_tolerance_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture_h(kind, rng):
    if kind == "tall":
        # more checks than columns — typically full column rank, so the
        # sweep degenerates to f == 0 free columns (the OSD-0 edge)
        h = (rng.random((48, 40)) < 0.2).astype(np.uint8)
    elif kind == "rank_deficient":
        h = (rng.random((24, 60)) < 0.18).astype(np.uint8)
        h[-1] = h[0]  # duplicated check: rank < m
    else:
        h = (rng.random((20, 48)) < 0.22).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    return h


# ---------------------------------------------------------------------------
# host-oracle parity (the PR-13 float32-tie contract, now for osd_cs)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("order", [0, 4, 10])
@pytest.mark.parametrize("kind", ["tall", "rank_deficient", "random"])
def test_osd_cs_device_matches_host_oracle(kind, order):
    """Every compared shot must be bit-equal with the demoted host
    combination loop, or a float32/64 cost tie on a syndrome-consistent
    candidate — the same parity contract device OSD-E ships under."""
    rng = np.random.default_rng(5)
    h = _fixture_h(kind, rng)
    n = h.shape[1]
    probs = rng.uniform(0.01, 0.2, n)
    B = 96
    errs = (rng.random((B, n)) < 0.06).astype(np.uint8)
    synds = (errs @ h.T % 2).astype(np.uint8)
    dev = BPOSD_Decoder(h, probs, max_iter=8, osd_method="osd_cs",
                        osd_order=order)
    host = BPOSD_Decoder(h, probs, max_iter=8, osd_method="osd_cs",
                         osd_order=order, device_osd=False)
    assert dev.device_osd and not dev.needs_host_postprocess
    assert not host.device_osd and host.needs_host_postprocess
    a = np.asarray(dev.decode_batch(synds))
    b = np.asarray(host.decode_batch(synds))
    cost = _channel_cost(probs)
    exact = (a == b).all(axis=1)
    synd_ok = ((a @ h.T % 2) == synds).all(axis=1)
    tie = np.abs((a * cost[None]).sum(1) - (b * cost[None]).sum(1)) < 1e-4
    assert (exact | (tie & synd_ok)).all(), (
        f"{kind}/order={order}: "
        f"{int((~(exact | (tie & synd_ok))).sum())} shots outside the "
        f"parity contract")


def test_osd_cs_order_cap_is_loud():
    """Satellite (a): osd_order above the shared OSD_CS_MAX_ORDER raises
    a ValueError on BOTH the device decoder and the host batch entry —
    never a silent clamp."""
    h = np.eye(6, dtype=np.uint8)
    probs = np.full(6, 0.05)
    with pytest.raises(ValueError, match="OSD_CS_MAX_ORDER"):
        BPOSD_Decoder(h, probs, max_iter=4, osd_method="osd_cs",
                      osd_order=OSD_CS_MAX_ORDER + 1)
    with pytest.raises(ValueError, match="OSD_CS_MAX_ORDER"):
        osd_decode_batch(h, np.zeros((2, 6), np.uint8),
                         np.zeros((2, 6), np.float32), probs,
                         osd_method="osd_cs",
                         osd_order=OSD_CS_MAX_ORDER + 1)


# ---------------------------------------------------------------------------
# kernel == twin (R007 "osd_cs_sweep") and the full-maintenance elimination
# ---------------------------------------------------------------------------
def test_cs_sweep_kernel_matches_twin_bit_exact():
    """The Pallas sweep (interpret mode off-TPU) and its XLA twin share
    one chunk body — cost AND winner index must match bit for bit on an
    irregular shape (f=14, w=5, chunk=8: 25 candidates pad to 32, a
    ragged final chunk of pad rows)."""
    rng = np.random.default_rng(3)
    f, w, chunk, B, bt = 14, 5, 8, 256, 128
    e1t, e2t, _j1, _j2, n_cand, n_pad = cs._cs_plane(f, w, chunk)
    assert n_pad % chunk == 0 and n_pad > n_cand  # ragged final chunk
    dplane = jnp.asarray(rng.normal(size=(f, B)).astype(np.float32))
    xflat = jnp.asarray(rng.normal(size=(w * w, B)).astype(np.float32))
    base = jnp.asarray(rng.normal(size=(B,)).astype(np.float32))
    tc, ti = cs._cs_sweep_xla(jnp.asarray(e1t), jnp.asarray(e2t),
                              dplane, xflat, base, chunk)
    kc, ki = cs._cs_sweep_pallas(jnp.asarray(e1t), jnp.asarray(e2t),
                                 dplane, xflat, base, chunk, bt=bt,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(tc), np.asarray(kc))
    np.testing.assert_array_equal(np.asarray(ti), np.asarray(ki))


def test_full_elimination_twin_matches_blocked_oracle():
    """CS needs every word of the reduced PIVOT rows maintained (weight-1
    spans ALL free columns): the ``full=True`` blocked twin must
    reproduce the per-column blocked oracle's pivots and the full-width
    bitplanes of every pivot row — the rows the sweep's dplane/X
    decomposition gathers.  (Rows that never pivot are dead to the
    decode and outside the contract.)"""
    rng = np.random.default_rng(12)
    m, n, B = 14, 40, 16
    h = (rng.random((m, n)) < 0.25).astype(np.uint8)
    h[:, h.sum(0) == 0] = 1
    plan = od.build_osd_plan(h, rng.uniform(0.01, 0.3, n))
    perm = jnp.argsort(
        jnp.asarray(rng.normal(size=(B, n)).astype(np.float32)),
        axis=1, stable=True).astype(jnp.int32)
    synds = ((rng.random((B, n)) < 0.1).astype(np.uint8) @ h.T % 2).astype(
        np.uint8)
    _u_a, pr_a, pc_a, _ip_a, packed_a = od._eliminate_blocked(
        plan, perm, jnp.asarray(synds))
    _synd_b, pr_b, pc_b, _fw, _fp, packed_b = od._eliminate_blocked_twin(
        plan, perm, jnp.asarray(synds), fcap=0, full=True)
    np.testing.assert_array_equal(np.asarray(pr_a), np.asarray(pr_b))
    np.testing.assert_array_equal(np.asarray(pc_a), np.asarray(pc_b))
    # bit-compare as uint32: the oracle packs uint32, the twin rides the
    # kernel's int32 lanes — same bits, different sign interpretation
    rows_a = np.take_along_axis(np.asarray(packed_a).view(np.uint32),
                                np.asarray(pr_a)[None, :, :], axis=1)
    rows_b = np.take_along_axis(np.asarray(packed_b).view(np.uint32),
                                np.asarray(pr_b)[None, :, :], axis=1)
    np.testing.assert_array_equal(rows_a, rows_b)


# ---------------------------------------------------------------------------
# warm-path retraces, host round-trips, device-tele counters
# ---------------------------------------------------------------------------
def test_osd_cs_warm_sweep_zero_retraces_zero_host_round_trips():
    """Acceptance: a warm osd_cs BPOSD sweep at NEW p-values adds zero
    retraces (the index plane and pat_chunk are static per (H, w)),
    completes with ``osd.host_round_trips == 0`` through the megabatch
    carry, and the satellite ``osd.cs_candidates`` / ``osd.cs_chunks``
    device-tele counters surface the sweep's real shape."""
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    code = hgp(rep_code(3), rep_code(3))

    def run(p):
        def mk(h):
            return BPOSD_Decoder(h, np.full(code.N, p), max_iter=4,
                                 osd_method="osd_cs", osd_order=4)

        sim = CodeSimulator_DataError(
            code=code, decoder_x=mk(code.hz), decoder_z=mk(code.hx),
            pauli_error_probs=[p / 3] * 3, batch_size=128, seed=0,
            scan_chunk=2)
        sim.WordErrorRate(256)

    telemetry.reset()
    telemetry.enable()
    try:
        for p in (0.06, 0.1):
            run(p)
        before = telemetry.compile_stats().get("jax.retraces", 0)
        for p in (0.08, 0.12):
            run(p)
        after = telemetry.compile_stats().get("jax.retraces", 0)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    assert after - before == 0, (
        f"{after - before} retraces on a warm osd_cs p-sweep")
    assert snap.get("osd.host_round_trips", {}).get("value", 0) == 0
    assert snap.get("osd.host_fallbacks", {}).get("value", 0) == 0
    rank = BPOSD_Decoder(code.hz, np.full(code.N, 0.06), max_iter=4,
                         osd_method="osd_cs",
                         osd_order=4).device_static[3]
    n_cand, _n_chunks = cs.cs_sweep_shape(code.N, int(rank), 4)
    cands = snap.get("osd.cs_candidates", {}).get("value", 0)
    chunks = snap.get("osd.cs_chunks", {}).get("value", 0)
    assert cands > 0 and chunks > 0
    # counters are multiples of the sweep's real shape (per bad shot /
    # per engaged batch)
    assert cands % n_cand == 0


# ---------------------------------------------------------------------------
# serving: the osd_cs bucket names its backend and stays bit-exact
# ---------------------------------------------------------------------------
def test_bposd_cs_session_serves_device_cs_bit_exact():
    """Satellite (b)+(tentpole wiring): an osd_cs BPOSD factory serves
    through DecodeSession on this CPU substrate (no host demotion), the
    session names ``osd_backend == "device_cs"``, and served corrections
    match offline decode_batch bit for bit."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import DecodeSession

    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    params = {"h": code.hx, "p_data": 0.05}
    cls = BPOSD_Decoder_Class(8, "minimum_sum", 0.625, "osd_cs", 6)
    sess = DecodeSession("bposd_cs", decoder_class=cls, params=params,
                         buckets=(32, 64))
    assert sess.osd_backend == "device_cs"
    assert sess.static[0] == "bposd_dev" and sess.static[6] == "osd_cs"
    rng = np.random.default_rng(2)
    errs = (rng.random((40, code.N)) < 0.1).astype(np.uint8)
    synd = (errs @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)
    out = sess.decode(synd)
    off = cls.GetDecoder(params).decode_batch(synd)
    np.testing.assert_array_equal(out.corrections, np.asarray(off))


# ---------------------------------------------------------------------------
# mesh-sharded n1225 bucket smoke (tentpole acceptance, CPU mesh)
# ---------------------------------------------------------------------------
def test_bposd_cs_mesh_sharded_n1225_bucket_smoke():
    """An hgp_34_n1225 osd_cs BPOSD cell runs through the cell-fused
    driver on the 8-device virtual CPU mesh: shots shard across the mesh,
    counts come back sane, and the whole decode stays host-free."""
    from qldpc_fault_tolerance_tpu.parallel import shot_mesh
    from qldpc_fault_tolerance_tpu.sim import common as simc
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    code = load_code(os.path.join(REPO, "codes_lib_tpu",
                                  "hgp_34_n1225.npz"))
    p = 0.01

    def mk(h):
        return BPOSD_Decoder(h, np.full(code.N, p), max_iter=4,
                             osd_method="osd_cs", osd_order=10)

    sim = CodeSimulator_DataError(
        code=code, decoder_x=mk(code.hz), decoder_z=mk(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=16, seed=0,
        scan_chunk=1)
    mesh = shot_mesh()
    n_dev = mesh.devices.size
    telemetry.reset()
    telemetry.enable()
    try:
        prog = CodeSimulator_DataError.fused_cells_program(
            [sim], 16, mesh=mesh)
        f, sh, _ = simc.fused_cell_finish(simc.fused_cell_launch(prog)[0])
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    assert (sh == prog.n_batches * 16 * n_dev).all()
    assert (f >= 0).all() and (f <= sh).all()
    assert snap.get("osd.host_round_trips", {}).get("value", 0) == 0
    assert snap.get("osd.host_fallbacks", {}).get("value", 0) == 0
