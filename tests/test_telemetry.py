"""Telemetry layer tests (ISSUE 2): registry semantics, span nesting,
disabled-mode no-op, JSONL round-trip through scripts/telemetry_report,
device/host metric accumulation from real WordErrorRate runs on CPU, and
the no-bare-print library guard."""
import importlib
import json
import os
import threading

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.utils import telemetry

LIB_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "qldpc_fault_tolerance_tpu")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts disabled with an empty registry and leaves no
    enabled switch behind."""
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------
def test_counter_gauge_histogram_semantics():
    telemetry.enable()
    telemetry.count("c", 2)
    telemetry.count("c")
    telemetry.set_gauge("g", 7)
    telemetry.set_gauge("g", 3)
    for v in (0.5, 1.5, 99.0):
        telemetry.observe("h", v, buckets=(1.0, 10.0))
    snap = telemetry.snapshot()
    assert snap["c"] == {"type": "counter", "value": 3}
    assert snap["g"]["value"] == 3 and snap["g"]["max"] == 7
    h = snap["h"]
    assert h["counts"] == [1, 1, 1]  # <=1, <=10, overflow
    assert h["count"] == 3 and h["sum"] == pytest.approx(101.0)
    assert h["mean"] == pytest.approx(101.0 / 3)


def test_metric_kind_collision_raises():
    telemetry.enable()
    telemetry.count("m")
    with pytest.raises(TypeError):
        telemetry.registry().gauge("m")


def test_histogram_merge_counts_matches_observe():
    telemetry.enable()
    h = telemetry.histogram("merge", buckets=telemetry.ITER_BUCKETS)
    h.merge_counts([1] * (len(telemetry.ITER_BUCKETS) + 1), 100.0, 13)
    assert h.count == 13
    assert sum(h.counts) == 13
    with pytest.raises(AssertionError):
        h.merge_counts([1, 2], 0, 3)  # wrong bucket shape must not corrupt


def test_registry_thread_safety():
    telemetry.enable()

    def work():
        for _ in range(1000):
            telemetry.count("t.c")
            telemetry.observe("t.h", 0.01)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = telemetry.snapshot()
    assert snap["t.c"]["value"] == 8000
    assert snap["t.h"]["count"] == 8000


def test_stage_timer_thread_safety():
    """Satellite: the legacy _TIMINGS global must survive concurrent
    append + snapshot (windowed_count launches from in-flight batches)."""
    from qldpc_fault_tolerance_tpu.utils.observability import (
        reset_timings, stage_timer, timings)

    reset_timings()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            with stage_timer("mt-stage"):
                pass

    def reader():
        while not stop.is_set():
            timings()

    threads = [threading.Thread(target=writer) for _ in range(4)] + [
        threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join()
    assert timings()["mt-stage"]["count"] > 0
    reset_timings()


# ---------------------------------------------------------------------------
# enable switch / disabled no-op
# ---------------------------------------------------------------------------
def test_disabled_mode_is_noop():
    assert not telemetry.enabled()
    telemetry.count("nope")
    telemetry.set_gauge("nope.g", 1)
    telemetry.observe("nope.h", 1.0)
    telemetry.event("nope_event", x=1)
    with telemetry.span("nope.span"):
        pass
    assert telemetry.snapshot() == {}


def test_disabled_span_is_shared_noop_object():
    a = telemetry.span("x")
    b = telemetry.span("y")
    assert a is b  # no per-call allocation on the disabled hot path


def test_span_nesting_builds_paths():
    telemetry.enable()
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    snap = telemetry.snapshot()
    assert "span.outer.seconds" in snap
    assert "span.outer/inner.seconds" in snap
    assert snap["span.outer/inner.seconds"]["count"] == 1


def test_stage_timer_feeds_spans_when_enabled():
    from qldpc_fault_tolerance_tpu.utils.observability import (
        reset_timings, stage_timer, timings)

    reset_timings()
    telemetry.enable()
    with stage_timer("bridged"):
        pass
    assert timings()["bridged"]["count"] == 1  # legacy dict still fed
    assert "span.bridged.seconds" in telemetry.snapshot()
    reset_timings()


def test_session_nested_inside_enabled_region(tmp_path):
    """A session() inside an already-enabled region (parity.py env-var
    scenario) must keep the outer enable + metrics alive, not duplicate
    sinks, and still stream its own JSONL."""
    outer = telemetry.MemorySink()
    telemetry.add_sink(outer)
    try:
        telemetry.enable()
        telemetry.enable()  # idempotent: no second sink, no error
        telemetry.count("outer.c", 7)
        inner_path = str(tmp_path / "inner.jsonl")
        with telemetry.session(inner_path):
            telemetry.count("outer.c", 1)
        assert telemetry.enabled(), "nested session killed the outer enable"
        # reset_metrics must not wipe the outer region's registry
        assert telemetry.snapshot()["outer.c"]["value"] == 8
        telemetry.event("after_inner")
        assert any(r["kind"] == "after_inner" for r in outer.records)
        inner = [json.loads(line) for line in open(inner_path)]
        assert any(e["kind"] == "snapshot" for e in inner)
        assert not any(e["kind"] == "after_inner" for e in inner)
    finally:
        telemetry.remove_sink(outer)


def test_session_context_round_trip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with telemetry.session(path):
        assert telemetry.enabled()
        telemetry.count("s.c", 5)
    assert not telemetry.enabled()
    events = [json.loads(line) for line in open(path)]
    kinds = [e["kind"] for e in events]
    assert "telemetry_enabled" in kinds and "snapshot" in kinds
    snap = [e for e in events if e["kind"] == "snapshot"][-1]
    assert snap["metrics"]["s.c"]["value"] == 5


# ---------------------------------------------------------------------------
# sinks / exposition / report CLI
# ---------------------------------------------------------------------------
def test_memory_sink_receives_events():
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        telemetry.enable()
        telemetry.event("unit", a=1)
        assert sink.records[-1]["kind"] == "unit"
        assert sink.records[-1]["a"] == 1
        assert "ts" in sink.records[-1]
    finally:
        telemetry.remove_sink(sink)


def test_prometheus_text_format():
    telemetry.enable()
    telemetry.count("p.c", 4)
    telemetry.observe("p.h", 0.5, buckets=(1.0,))
    text = telemetry.prometheus_text()
    assert "# TYPE qldpc_p_c counter" in text
    assert "qldpc_p_c 4" in text
    assert 'qldpc_p_h_bucket{le="1.0"} 1' in text
    assert 'qldpc_p_h_bucket{le="+Inf"} 1' in text
    assert "qldpc_p_h_count 1" in text


def test_jsonl_report_round_trip(tmp_path):
    report = importlib.import_module("scripts.telemetry_report")
    path = str(tmp_path / "run.jsonl")
    with telemetry.session(path):
        telemetry.count("sim.shots", 1000)
        telemetry.count("sim.failures", 10)
        telemetry.count("driver.dispatches", 4)
        telemetry.count("bp.shots", 2000)
        telemetry.count("bp.converged", 1900)
        telemetry.count("osd.invocations", 3)
        telemetry.histogram("bp.iterations",
                            telemetry.ITER_BUCKETS).observe(2)
        telemetry.event("wer_run", engine="data", shots=1000, failures=10,
                        wer=0.01)
    events = report.load_events(path)
    summary = report.summarize(events)
    assert summary["shots"] == 1000
    assert summary["failures"] == 10
    assert summary["dispatches"] == 4
    assert summary["bp"]["converged_fraction"] == pytest.approx(0.95)
    assert summary["osd"]["invocations"] == 3
    assert summary["events"]["wer_run"] == 1
    text = report.render(summary)
    assert "telemetry report" in text
    assert "converged" in text
    # --json path exercises the argparse front door too
    assert report.main([path, "--json"]) == 0


# ---------------------------------------------------------------------------
# compile/retrace tracker
# ---------------------------------------------------------------------------
def test_retrace_tracker_counts_fresh_compiles():
    import jax
    import jax.numpy as jnp

    telemetry.enable()

    @jax.jit
    def fresh(x):
        return x * 2 + 1

    fresh(jnp.ones((3,))).block_until_ready()
    stats = telemetry.compile_stats()
    assert stats["jax.retraces"] >= 1


# ---------------------------------------------------------------------------
# engine smoke: metric names populated by real runs on CPU
# ---------------------------------------------------------------------------
def _small_code():
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code

    return hgp(rep_code(3), rep_code(3))


def test_wer_run_populates_metrics_bp():
    """Pure-device BP run: metrics arrive via the device telemetry vector
    folded through the megabatch carry."""
    import jax

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    code = _small_code()
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=32, seed=0)
    wer_off = sim.WordErrorRate(128, key=jax.random.PRNGKey(3))
    telemetry.enable()
    sim2 = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=32, seed=0)
    wer_on = sim2.WordErrorRate(128, key=jax.random.PRNGKey(3))
    # telemetry must not perturb the estimate (bit-exact, same keys)
    assert wer_on == wer_off
    snap = telemetry.snapshot()
    for name in ("sim.shots", "sim.failures", "sim.runs",
                 "driver.dispatches", "bp.shots", "bp.converged",
                 "bp.iterations"):
        assert name in snap, f"missing metric {name}"
    assert snap["sim.shots"]["value"] == 128
    assert snap["bp.shots"]["value"] == 256  # both sectors
    # iteration stats cover converged shots only (non-converged sit at
    # max_iter and would inflate the mean)
    assert snap["bp.iterations"]["count"] == snap["bp.converged"]["value"]
    assert 0 < snap["bp.converged"]["value"] <= 256
    assert "span.wer.data.seconds" in snap


def test_wer_run_populates_metrics_bposd_device():
    """Device-OSD run (the ISSUE 13 default for BPOSD on every backend):
    the whole BP->OSD pipeline folds through the megabatch carry — the
    device tele vector carries OSD shots and compaction-tier occupancy,
    zero host round-trips, and the wer_run event names the backend."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    code = _small_code()
    p = 0.12  # high p so some shots fail BP and exercise OSD
    dec_x = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=3,
                          osd_method="osd_e", osd_order=2)
    dec_z = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=3,
                          osd_method="osd_e", osd_order=2)
    assert not dec_x.needs_host_postprocess  # device OSD, every backend
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sim = CodeSimulator_DataError(
            code=code, decoder_x=dec_x, decoder_z=dec_z,
            pauli_error_probs=[p / 3] * 3, batch_size=64, seed=0)
        sim.WordErrorRate(128)
        snap = telemetry.snapshot()
    finally:
        telemetry.remove_sink(sink)
    assert snap["sim.shots"]["value"] == 128
    assert snap["bp.shots"]["value"] == 256
    assert snap["osd.device_shots"]["value"] >= 1
    assert snap.get("osd.host_round_trips", {}).get("value", 0) == 0
    # compaction-tier occupancy: 4 OSD stages ran (2 megabatches x 2
    # sectors), each landing in exactly one tier counter
    tiers = sum(snap.get(k, {}).get("value", 0)
                for k in ("osd.tier_none", "osd.tier_compacted",
                          "osd.tier_full"))
    assert tiers == 4
    # ONE megabatch dispatch covers both batches — the host-assisted path
    # paid one launch per batch; the carry-resident pipeline amortizes
    assert snap["driver.dispatches"]["value"] == 1
    wer_events = [r for r in sink.records if r["kind"] == "wer_run"]
    assert wer_events and wer_events[0]["osd_backend"] == "device"
    assert telemetry.validate_event(wer_events[0]) == []


def test_osd_host_counters_via_decoder_oracle():
    """The demoted host path (device_osd=False — resilience rung / test
    oracle) still counts its OSD invocations/shots/round-trips when driven
    through decoder.decode_batch."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder

    code = _small_code()
    p = 0.12
    rng = np.random.default_rng(3)
    dec = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=3,
                        osd_method="osd_e", osd_order=2, device_osd=False)
    assert dec.needs_host_postprocess
    errs = (rng.random((64, code.N)) < p).astype(np.uint8)
    synds = (errs @ code.hx.T % 2).astype(np.uint8)
    telemetry.enable()
    dec.decode_batch(synds)
    snap = telemetry.snapshot()
    assert snap["osd.invocations"]["value"] >= 1
    assert snap["osd.shots"]["value"] >= 1
    assert "span.osd_host.seconds" in snap


def test_wer_run_populates_metrics_phenom():
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon

    code = _small_code()
    p, q = 0.03, 0.03
    ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    extz = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
    d1x = BPDecoder(extz, np.full(extz.shape[1], p), max_iter=8)
    d1z = BPDecoder(ext, np.full(ext.shape[1], p), max_iter=8)
    d2x = BPDecoder(code.hz, np.full(code.N, p), max_iter=8)
    d2z = BPDecoder(code.hx, np.full(code.N, p), max_iter=8)
    telemetry.enable()
    sim = CodeSimulator_Phenon(
        code=code, decoder1_x=d1x, decoder1_z=d1z, decoder2_x=d2x,
        decoder2_z=d2z, pauli_error_probs=[p / 3] * 3, q=q,
        batch_size=32, seed=0)
    sim.WordErrorRate(num_rounds=3, num_samples=64)
    snap = telemetry.snapshot()
    assert snap["sim.shots"]["value"] == 64
    # final-round (decoder-2) aux only — documented scope
    assert snap["bp.shots"]["value"] == 128
    assert "span.wer.phenl.seconds" in snap


def test_wer_run_populates_metrics_mesh():
    """Sharded (mesh) runs must report decoder statistics too: the
    telemetry vector psum-reduces over the mesh alongside the failure
    count (conftest forces 8 virtual CPU devices)."""
    import jax

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.parallel import shot_mesh
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    code = _small_code()
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)

    def make():
        return CodeSimulator_DataError(
            code=code, decoder_x=dec_x, decoder_z=dec_z,
            pauli_error_probs=[p / 3] * 3, batch_size=16, seed=0,
            mesh=shot_mesh())

    key = jax.random.PRNGKey(7)
    wer_off = make().WordErrorRate(256, key=key)
    telemetry.enable()
    wer_on = make().WordErrorRate(256, key=key)
    assert wer_on == wer_off  # the tele fold must not perturb the stats
    snap = telemetry.snapshot()
    assert snap["sim.shots"]["value"] == 256
    assert snap["bp.shots"]["value"] == 512  # both sectors
    assert snap["bp.iterations"]["count"] == snap["bp.converged"]["value"]


def test_target_failures_early_stop_counted():
    import jax

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    code = _small_code()
    p = 0.2  # fails fast => early stop fires on the first megabatch
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=5)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=5)
    telemetry.enable()
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=32, seed=0,
        scan_chunk=2)
    sim.WordErrorRate(64 * 32, key=jax.random.PRNGKey(0), target_failures=1)
    snap = telemetry.snapshot()
    assert snap["driver.early_stops"]["value"] == 1
    assert snap["sim.shots"]["value"] < 64 * 32


# ---------------------------------------------------------------------------
# guard: no bare print() in library code
# ---------------------------------------------------------------------------
def test_no_bare_print_in_library():
    """Thin shim (ISSUE 12): the PR-2 grep guard migrated into qldpc-lint
    as rule R101 so guard logic lives in exactly one engine.  This asserts
    the rule stays enabled with the same exemptions; enforcement over the
    real tree is tests/test_analysis.py's full-package gate."""
    from qldpc_fault_tolerance_tpu import analysis

    rules = {r.id: r for r in analysis.default_rules()}
    assert "R101" in rules, "bare-print rule dropped from default set"
    r101 = rules["R101"]
    # the teaching module keeps its exemption (its prints ARE the product)
    assert not r101.applies("qldpc_fault_tolerance_tpu/utils/par2gen.py")
    assert r101.applies("qldpc_fault_tolerance_tpu/sim/common.py")
    # the migrated rule fires on what the grep guard fired on
    from qldpc_fault_tolerance_tpu.analysis import (AnalysisContext,
                                                    SourceModule,
                                                    run_analysis)

    mod = SourceModule.parse("qldpc_fault_tolerance_tpu/sim/x.py",
                             "def f():\n    print('no')\n")
    res = run_analysis([mod], [r101], ctx=AnalysisContext([mod]))
    assert len(res.findings) == 1 and res.findings[0].rule == "R101"


# ---------------------------------------------------------------------------
# ISSUE 11 satellites: configurable histogram buckets
# ---------------------------------------------------------------------------
def test_set_default_buckets_applies_to_new_histograms():
    telemetry.enable()
    telemetry.set_default_buckets("custom.metric", (1.0, 2.0, 4.0))
    try:
        assert telemetry.default_buckets("custom.metric") == (1.0, 2.0, 4.0)
        telemetry.observe("custom.metric", 1.5)
        h = telemetry.snapshot()["custom.metric"]
        assert h["buckets"] == [1.0, 2.0, 4.0]
        assert h["counts"] == [0, 1, 0, 0]
        # an unregistered metric keeps the global time ladder
        telemetry.observe("plain.metric", 1.5)
        assert telemetry.snapshot()["plain.metric"]["buckets"] == \
            list(telemetry.DEFAULT_TIME_BUCKETS)
    finally:
        telemetry.set_default_buckets("custom.metric", None)
        assert telemetry.default_buckets("custom.metric") is None


def test_serve_latency_gets_log_spaced_buckets():
    """The shipped spec: serve.latency_s resolves sub-ms tails (the fixed
    half-decade ladder lumped entire TPU-speed latency distributions into
    one or two buckets, making p50/p99 useless)."""
    telemetry.enable()
    telemetry.observe("serve.latency_s", 5e-4)
    h = telemetry.snapshot()["serve.latency_s"]
    assert h["buckets"] == list(telemetry.LATENCY_BUCKETS)
    assert len(telemetry.LATENCY_BUCKETS) == 21
    assert telemetry.LATENCY_BUCKETS[0] == pytest.approx(1e-4)
    assert telemetry.LATENCY_BUCKETS[-1] == pytest.approx(10.0)
    # 4 edges per decade: 5 decades resolved
    assert telemetry.LATENCY_BUCKETS[4] == pytest.approx(1e-3)


def test_report_quantiles_correct_on_custom_buckets():
    """telemetry_report's bucket-interpolated quantiles must follow the
    histogram's OWN boundaries: with the log-spaced latency ladder, a
    sub-ms distribution's p50/p99 resolve to the right sub-ms bucket
    instead of saturating the first coarse edge."""
    report = importlib.import_module("scripts.telemetry_report")
    telemetry.enable()
    for _ in range(100):
        telemetry.observe("serve.latency_s", 5e-4)
    m = telemetry.snapshot()["serve.latency_s"]
    p50 = report._hist_quantile(m, 0.50)
    p99 = report._hist_quantile(m, 0.99)
    # 5e-4 lands in the (3.16e-4, 5.62e-4] bucket of LATENCY_BUCKETS
    assert 3e-4 < p50 <= 5.7e-4
    assert 3e-4 < p99 <= 5.7e-4
    # overflow reports the top edge, not a fabricated value
    for _ in range(1000):
        telemetry.observe("over.metric", 99.0, buckets=(1.0, 2.0))
    assert report._hist_quantile(
        telemetry.snapshot()["over.metric"], 0.5) == 2.0


def test_env_bucket_spec_override(monkeypatch):
    monkeypatch.setenv("QLDPC_HIST_BUCKETS",
                       json.dumps({"env.metric": [0.5, 5.0]}))
    telemetry._install_env_bucket_specs()
    try:
        assert telemetry.default_buckets("env.metric") == (0.5, 5.0)
    finally:
        telemetry.set_default_buckets("env.metric", None)
    monkeypatch.setenv("QLDPC_HIST_BUCKETS", "not json")
    with pytest.warns(UserWarning):
        telemetry._install_env_bucket_specs()


# ---------------------------------------------------------------------------
# ISSUE 11 satellites: process provenance
# ---------------------------------------------------------------------------
def test_process_info_event_heads_every_stream(tmp_path):
    report = importlib.import_module("scripts.telemetry_report")
    path = str(tmp_path / "run.jsonl")
    telemetry.enable(path)
    telemetry.disable()
    events = report.load_events(path)
    info = [e for e in events if e["kind"] == "process_info"]
    assert len(info) == 1
    assert telemetry.validate_event(info[0]) == []
    assert info[0]["pid"] == os.getpid()
    assert info[0]["hostname"]
    assert info[0]["schema_version"] == telemetry.EVENT_SCHEMA_VERSION
    # this repo is a git checkout: the SHA is resolvable and cached
    assert info[0]["git_sha"]
    assert telemetry.process_info()["git_sha"] == info[0]["git_sha"]


def test_process_info_reports_jax_when_loaded():
    import jax  # noqa: F401 — ensure the module is live

    info = telemetry.process_info(refresh=True)
    assert info["jax"] and info["jaxlib"]
    assert info["backend"] == "cpu"


# ---------------------------------------------------------------------------
# ISSUE 11 satellites: concurrent JsonlSink writers
# ---------------------------------------------------------------------------
def test_jsonl_sink_concurrent_writers_no_torn_lines(tmp_path):
    """8 threads hammering one JsonlSink: every line must parse (no torn
    or interleaved writes) and FollowReader must round-trip the stream
    intact."""
    report = importlib.import_module("scripts.telemetry_report")
    path = str(tmp_path / "hammer.jsonl")
    telemetry.enable(path)
    n_threads, per = 8, 250
    payload = "x" * 200  # long enough that a torn write would shear JSON

    def hammer(t):
        for i in range(per):
            telemetry.event("heartbeat", engine=f"t{t}", shots=i,
                            blob=payload)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    telemetry.disable()

    raw = open(path, encoding="utf-8").read().splitlines()
    events = [json.loads(line) for line in raw]  # every line parses
    beats = [e for e in events if e["kind"] == "heartbeat"]
    assert len(beats) == n_threads * per
    assert all(e["blob"] == payload for e in beats)  # no interleaving
    # every (thread, i) pair arrived exactly once
    seen = {(e["engine"], e["shots"]) for e in beats}
    assert len(seen) == n_threads * per
    # FollowReader round-trips the identical stream incrementally
    reader = report.FollowReader(path)
    followed = []
    while True:
        fresh = reader.poll()
        if not fresh:
            break
        followed.extend(fresh)
    assert followed == events


# ---------------------------------------------------------------------------
# ISSUE 11 satellite: schema-coverage guard
# ---------------------------------------------------------------------------
def test_every_event_kind_is_emitted_and_test_validated():
    """Tier-1 schema-coverage guard: every kind in EVENT_SCHEMAS must (a)
    have a literal emission site in the library — a schema for an event
    nothing emits is dead weight — and (b) appear in at least one test
    file that validates events against the registry, so an added kind
    cannot ship untested.  Adding a kind to EVENT_SCHEMAS without both
    fails here."""
    import re

    lib_src = []
    for dirpath, _dirnames, filenames in os.walk(LIB_ROOT):
        for fn in filenames:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as fh:
                    lib_src.append(fh.read())
    lib_src = "\n".join(lib_src)

    dead = [k for k in telemetry.EVENT_SCHEMAS
            if not re.search(r'event\(\s*["\']' + re.escape(k) + r'["\']',
                             lib_src)]
    assert not dead, (
        f"EVENT_SCHEMAS kinds never emitted by the library: {dead}")

    tests_dir = os.path.dirname(os.path.abspath(__file__))
    validated_src = []
    for fn in os.listdir(tests_dir):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(tests_dir, fn), encoding="utf-8") as fh:
            text = fh.read()
        if "validate_event" in text:
            validated_src.append(text)
    validated_src = "\n".join(validated_src)

    untested = [k for k in telemetry.EVENT_SCHEMAS
                if f'"{k}"' not in validated_src
                and f"'{k}'" not in validated_src]
    assert not untested, (
        f"EVENT_SCHEMAS kinds not exercised by any schema-validating "
        f"test: {untested}")
