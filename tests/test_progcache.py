"""Persistent AOT program cache (ISSUE 20 tentpole): key anatomy and
fingerprinting, cache-or-compile round trips that stay bit-exact per
decoder substrate, corruption tolerance (garbled artifact -> recompile
and REPLACE; tampered fingerprint -> miss, never a crash), single-flight
population under a concurrent cold start, session-ladder warm restarts
resolving from the cache with zero compiles, stale-artifact
invalidation, and the fleet warm-start push end to end under a seeded
``host_kill``."""
import os
import pickle
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BP_Decoder_Class,
    BPOSD_Decoder_Class,
)
from qldpc_fault_tolerance_tpu.serve import (
    DecodeClient,
    DecodeSession,
    LocalFleet,
)
from qldpc_fault_tolerance_tpu.utils import (
    faultinject,
    progcache,
    resilience,
    telemetry,
)

pytestmark = pytest.mark.faults

CODE3 = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
P = 0.05
BP_CLS = BP_Decoder_Class(4, "minimum_sum", 0.625)
BPOSD_CLS = BPOSD_Decoder_Class(8, "minimum_sum", 0.625, "osd_e", 6)

FAST_POLICY = resilience.RetryPolicy(
    max_attempts=2, base_delay=0.01, backoff=1.0, jitter=0.0,
    reset_caches=False, degrade_after=1)


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    telemetry.disable()
    telemetry.reset()
    faultinject.deactivate()
    prev_policy = resilience.current_policy()
    progcache.reset(purge_stats=True)
    yield
    resilience.set_default_policy(prev_policy)
    faultinject.deactivate()
    progcache.reset(purge_stats=True)
    telemetry.disable()
    telemetry.reset()


def _params(code=CODE3):
    return {"h": code.hx, "p_data": P}


def _session(cls=BP_CLS, buckets=(8, 32), name="hgp_rep3"):
    return DecodeSession(name, decoder_class=cls, params=_params(),
                         buckets=buckets)


def _synd(k, rng, code=CODE3):
    err = (rng.random((k, code.N)) < P).astype(np.uint8)
    return (err @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)


def _counter(name):
    return telemetry.snapshot().get(name, {}).get("value", 0)


# ---------------------------------------------------------------------------
# key anatomy / activation
# ---------------------------------------------------------------------------
def test_inactive_by_default_compiles_inline():
    assert not progcache.active()
    compiled, source = progcache.compile_cached(
        jax.jit(lambda x: x + 1), (jnp.zeros(4),), kind="t", parts={})
    assert source == "compile"
    assert np.array_equal(np.asarray(compiled(jnp.zeros(4))), np.ones(4))
    assert progcache.stats()["misses"] == 0  # inactive: not even counted


def test_cache_key_stable_and_salted(tmp_path, monkeypatch):
    parts = {"static": ("a", 1, 2.0), "bucket": 32}
    k1 = progcache.cache_key("serve.session", parts)
    k2 = progcache.cache_key("serve.session", dict(parts))
    assert k1 == k2
    assert progcache.cache_key("sweep.fused", parts) != k1
    assert progcache.cache_key("serve.session",
                               {**parts, "bucket": 64}) != k1
    monkeypatch.setenv("QLDPC_PROGCACHE_SALT", "bump")
    assert progcache.fingerprint(refresh=True)["salt"] == "bump"
    assert progcache.cache_key("serve.session", parts) != k1


# ---------------------------------------------------------------------------
# cache-or-compile round trip, bit-exact per substrate
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("cls", [BP_CLS, BPOSD_CLS],
                         ids=["bp", "bposd_dev"])
def test_warm_restart_is_loads_only_and_bitexact(cls, tmp_path):
    """The tentpole acceptance at unit scale: cold ladder compiles and
    stores, a simulated restart (cleared jit caches, NEW session) resolves
    every rung from the cache — zero compiles — and the served
    corrections are bit-exact vs the fresh-compile arm."""
    progcache.configure(str(tmp_path))
    rng = np.random.default_rng(0)
    synd = _synd(8, rng)

    cold = _session(cls)
    cold.warm()
    out_cold = cold.decode(synd)
    assert cold.compiles == len(cold.buckets)
    assert progcache.stats()["misses"] == len(cold.buckets)
    assert progcache.stats()["stores"] == len(cold.buckets)

    jax.clear_caches()  # restart: every jit/trace cache gone
    warm = _session(cls)
    warm.warm()
    out_warm = warm.decode(synd)
    assert warm.compiles == 0
    assert warm.loads == len(warm.buckets)
    assert np.array_equal(out_warm.corrections, out_cold.corrections)
    assert progcache.hit_rate() >= 0.5


def test_disk_artifacts_written_and_format_honest(tmp_path):
    """Every store lands one ``.qpc`` artifact; the format matches what
    the backend supports (exec only where serialized executables verify a
    same-process round trip at store time)."""
    progcache.configure(str(tmp_path))
    sess = _session()
    sess.warm()
    arts = list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))
    assert len(arts) == len(sess.buckets)
    with open(arts[0], "rb") as fh:
        doc = pickle.load(fh)
    assert doc["schema"] == 1
    assert doc["meta"]["fingerprint"] == progcache.fingerprint()
    supported = progcache.exec_roundtrip_supported()
    assert supported in (True, False)  # stores happened: probed
    assert doc["format"] == ("exec" if supported else "stablehlo")


# ---------------------------------------------------------------------------
# corruption tolerance
# ---------------------------------------------------------------------------
def test_corrupt_artifact_recompiles_and_replaces(tmp_path):
    progcache.configure(str(tmp_path))
    sess = _session(buckets=(8,))
    sess.warm()
    [art] = list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))
    art.write_bytes(b"\x80garbage, not a pickle")
    stats0 = progcache.stats()

    progcache.clear_memory()  # force the next resolve through disk
    jax.clear_caches()
    again = _session(buckets=(8,))
    again.warm()
    out = again.decode(_synd(8, np.random.default_rng(0)))
    assert out.corrections.shape[0] == 8
    stats = progcache.stats()
    assert stats["load_errors"] == stats0["load_errors"] + 1
    assert stats["stores"] == stats0["stores"] + 1  # REPLACED
    [art2] = list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))
    with open(art2, "rb") as fh:
        assert pickle.load(fh)["schema"] == 1  # valid again


def test_fingerprint_mismatch_is_miss_not_crash(tmp_path):
    progcache.configure(str(tmp_path))
    sess = _session(buckets=(8,))
    sess.warm()
    [art] = list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))
    with open(art, "rb") as fh:
        doc = pickle.load(fh)
    doc["meta"]["fingerprint"] = {"jaxlib": "9.9.9"}  # foreign toolchain
    with open(art, "wb") as fh:
        pickle.dump(doc, fh)
    stats0 = progcache.stats()

    progcache.clear_memory()
    jax.clear_caches()
    again = _session(buckets=(8,))
    again.warm()  # miss -> recompile; never deserializes foreign payloads
    assert again.compiles == 1
    stats = progcache.stats()
    assert stats["fingerprint_rejects"] == stats0["fingerprint_rejects"] + 1
    assert stats["load_errors"] == stats0["load_errors"]


def test_stale_artifact_invalidation_evicts_disk(tmp_path):
    """``invalidate()`` default keeps artifacts (dead device buffers —
    the program description is still right); ``stale_artifact=True``
    evicts the warm keys' disk entries too."""
    progcache.configure(str(tmp_path))
    sess = _session(buckets=(8,))
    sess.warm()
    assert len(list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))) == 1
    sess.invalidate()  # dead buffers: disk survives
    assert len(list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX))) == 1
    sess.warm()
    sess.invalidate(stale_artifact=True)  # suspect program: disk evicted
    assert list(tmp_path.rglob("*" + progcache.ARTIFACT_SUFFIX)) == []


# ---------------------------------------------------------------------------
# single-flight
# ---------------------------------------------------------------------------
def test_concurrent_cold_start_single_flight(tmp_path):
    """N threads racing one key: exactly ONE lower+compile happens; the
    losers block on the winner and share its program."""
    progcache.configure(str(tmp_path))
    lowers = []
    lock = threading.Lock()
    inner = jax.jit(lambda x: x * 2)

    class CountingJit:
        def lower(self, *a, **k):
            with lock:
                lowers.append(1)
            return inner.lower(*a, **k)

    results, errors = [], []
    barrier = threading.Barrier(6)

    def racer():
        try:
            barrier.wait(timeout=30)
            compiled, source = progcache.compile_cached(
                CountingJit(), (jnp.arange(4.0),),
                kind="t.race", parts={"shape": (4,)})
            results.append((np.asarray(compiled(jnp.arange(4.0))), source))
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=racer) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert len(lowers) == 1
    assert sum(1 for _r, s in results if s == "compile") == 1
    assert sum(1 for _r, s in results if s == "mem") == 5
    for r, _s in results:
        assert np.array_equal(r, np.arange(4.0) * 2)
    assert progcache.stats()["misses"] == 1


# ---------------------------------------------------------------------------
# fleet warm-start push under host_kill chaos
# ---------------------------------------------------------------------------
def test_fleet_handoff_warm_push_end_to_end(tmp_path):
    """ISSUE 20 acceptance: a seeded ``host_kill`` against a COLD 2-host
    fleet with the program cache active.  The router pre-pushes the dying
    family's program keys alongside the journal; the successor loads them
    at adopt time (``serve.session.warm_loads``, no misses) so the first
    adopted frame finds its program resident — and the storm stays
    exactly-once, bit-exact vs the offline decode."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    progcache.configure(str(tmp_path))
    reqs = 10
    fleet = LocalFleet(
        lambda: {"hgp_rep3": _session(buckets=(8, 32))},
        n_hosts=2, warm=False)
    try:
        host, port = fleet.address
        plan = faultinject.FaultPlan([
            faultinject.Fault(site="fleet_host_tick", kind="host_kill",
                              after=reqs)], seed=20)
        rng = np.random.default_rng(20)
        answered = []
        with plan.active(), DecodeClient(host, port, reconnect=True,
                                         timeout=60.0) as cli:
            for _ in range(3 * reqs):
                synd = _synd(int(rng.integers(1, 8)), rng)
                res = cli.submit("hgp_rep3", synd).result(timeout=120)
                answered.append((synd, res.corrections))
                fleet.chaos_tick()
        assert _counter("serve.host_kills") == 1
        assert _counter("router.handoffs") >= 1
        assert _counter("router.program_pushes") >= 1
        assert _counter("serve.session.warm_loads") >= 1
        assert _counter("serve.session.warm_load_misses") == 0
        assert len(answered) == 3 * reqs  # exactly once
        synd = np.concatenate([s for s, _ in answered])
        served = np.concatenate([c for _, c in answered])
        offline = BP_CLS.GetDecoder(_params()).decode_batch(synd)
        assert np.array_equal(served, offline)
    finally:
        fleet.stop()
