"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately compile-checks the TPU
path).  Must run before anything imports jax.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

REFERENCE_CODES_LIB = "/root/reference/codes_lib"
