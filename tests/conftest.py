"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately compile-checks the TPU
path).  The environment's sitecustomize eagerly initializes the TPU ('axon')
backend before pytest starts, so env vars alone are not enough — we force the
platform through jax.config and drop any already-initialized backends.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from qldpc_fault_tolerance_tpu.utils.backend import force_virtual_cpu  # noqa: E402

assert force_virtual_cpu(8), (
    "could not force an 8-device virtual CPU mesh — sharding tests would "
    "run degenerate; check JAX private-API drift in utils/backend.py"
)

REFERENCE_CODES_LIB = os.environ.get("QLDPC_REF_CODES_LIB",
                                     "/root/reference/codes_lib")
