"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately compile-checks the TPU
path).  The environment's sitecustomize eagerly initializes the TPU ('axon')
backend before pytest starts, so env vars alone are not enough — we force the
platform through jax.config and drop any already-initialized backends.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()
except Exception:  # pragma: no cover - best effort; env may already be clean
    pass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

REFERENCE_CODES_LIB = "/root/reference/codes_lib"
