"""Rare-event estimation subsystem (qldpc_fault_tolerance_tpu.rare):
estimator correctness, zero-tilt bit-exactness against the direct engines,
ESS-aware uncertainty, kill+resume of weighted streams, the weighted fused
sweep, and the v3 event schema.

The load-bearing contracts, in the order the issue pins them:

  * the ESS interval path reproduces Wilson to 1e-12 in the uniform-weight
    limit (summed weights must never masquerade as shot counts);
  * the zero-tilt configuration (tilt == channel probs) is bit-exact with
    the existing data/phenom engines seed-for-seed;
  * tilted and direct estimators agree within combined CIs in the overlap
    regime (a p both can resolve);
  * a killed weighted stream resumes seed-for-seed through the v2
    checkpoint cursor (weight moments persisted alongside the counts).
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder
from qldpc_fault_tolerance_tpu.noise import (
    bit_flips,
    bit_flips_tilted,
    bit_flips_tilted_packed,
    depolarizing_xz,
    depolarizing_xz_stratum,
    depolarizing_xz_tilted,
    depolarizing_xz_tilted_packed,
    fixed_weight_flips,
    stratum_log_weight,
)
from qldpc_fault_tolerance_tpu.rare import (
    auto_tilt,
    eval_rare_grid,
    eval_weighted_cells,
    fit_rare_distance,
    rare_fit_points,
    stratified_wer,
    tilt_channel,
    tilted_wer,
    variance_reduction,
    weighted_fit_point,
)
from qldpc_fault_tolerance_tpu.sim.common import (
    WeightedStats,
    wer_per_cycle,
    wer_per_cycle_weighted,
    wer_single_shot,
    wer_single_shot_weighted,
)
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon
from qldpc_fault_tolerance_tpu.utils import diagnostics, telemetry

CODE = hgp(rep_code(3), rep_code(3), name="rep3hgp")


def data_sim(p=0.05, seed=0, **kw):
    dec = lambda h: BPDecoder(h, np.full(CODE.N, p), max_iter=6)  # noqa: E731
    kw.setdefault("batch_size", 64)
    kw.setdefault("scan_chunk", 2)
    return CodeSimulator_DataError(
        code=CODE, decoder_x=dec(CODE.hz), decoder_z=dec(CODE.hx),
        pauli_error_probs=[p / 3] * 3, seed=seed, **kw)


def phenom_sim(p=0.04, seed=0, **kw):
    ext = np.hstack([CODE.hx, np.eye(CODE.hx.shape[0], dtype=np.uint8)])
    extz = np.hstack([CODE.hz, np.eye(CODE.hz.shape[0], dtype=np.uint8)])
    d1 = lambda h: BPDecoder(  # noqa: E731
        h, np.full(h.shape[1], p), max_iter=4)
    d2 = lambda h: BPDecoder(h, np.full(CODE.N, p), max_iter=6)  # noqa: E731
    kw.setdefault("batch_size", 64)
    kw.setdefault("scan_chunk", 2)
    return CodeSimulator_Phenon(
        code=CODE, decoder1_x=d1(extz), decoder1_z=d1(ext),
        decoder2_x=d2(CODE.hz), decoder2_z=d2(CODE.hx),
        pauli_error_probs=[p / 3] * 3, q=p, seed=seed, **kw)


# ---------------------------------------------------------------------------
# Tilted samplers
# ---------------------------------------------------------------------------
def test_tilted_depolarizing_zero_tilt_bitexact():
    """tilt == p consumes the same uniform draw with the same thresholds,
    so the error planes are bit-identical and the log weight exactly 0."""
    key = jax.random.PRNGKey(3)
    probs = [0.02, 0.01, 0.03]
    ex0, ez0 = depolarizing_xz(key, (32, CODE.N), probs)
    ex1, ez1, lw = depolarizing_xz_tilted(key, (32, CODE.N), probs, probs)
    assert jnp.array_equal(ex0, ex1) and jnp.array_equal(ez0, ez1)
    assert jnp.all(lw == 0.0)  # exact zero, not approximately


def test_tilted_bit_flips_zero_tilt_bitexact():
    key = jax.random.PRNGKey(4)
    f0 = bit_flips(key, (16, 40), 0.03)
    f1, lw = bit_flips_tilted(key, (16, 40), 0.03, 0.03)
    assert jnp.array_equal(f0, f1)
    assert jnp.all(lw == 0.0)


def test_tilted_log_weight_matches_analytic():
    """The per-shot log weight is the sum over sites of the exact
    per-outcome log likelihood ratio — recomputable from the planes."""
    key = jax.random.PRNGKey(5)
    probs, tilt = [0.01, 0.005, 0.02], [0.04, 0.02, 0.08]
    ex, ez, lw = depolarizing_xz_tilted(key, (64, CODE.N), probs, tilt)
    px, py, pz = probs
    qx, qy, qz = tilt
    is_y = (ex == 1) & (ez == 1)
    is_x = (ex == 1) & (ez == 0)
    is_z = (ex == 0) & (ez == 1)
    terms = np.where(
        is_y, math.log(py) - math.log(qy),
        np.where(is_x, math.log(px) - math.log(qx),
                 np.where(is_z, math.log(pz) - math.log(qz),
                          math.log1p(-sum(probs))
                          - math.log1p(-sum(tilt)))))
    expect = np.asarray(terms, np.float32).sum(axis=1)
    np.testing.assert_allclose(np.asarray(lw), expect, rtol=1e-5,
                               atol=1e-6)


def test_tilted_packed_matches_dense():
    from qldpc_fault_tolerance_tpu.ops.gf2_packed import pack_shots

    key = jax.random.PRNGKey(6)
    probs, tilt = [0.02] * 3, [0.06] * 3
    ex, ez, lw = depolarizing_xz_tilted(key, (64, CODE.N), probs, tilt)
    exp, ezp, lwp = depolarizing_xz_tilted_packed(
        key, (64, CODE.N), probs, tilt)
    assert jnp.array_equal(exp, pack_shots(ex))
    assert jnp.array_equal(ezp, pack_shots(ez))
    assert jnp.array_equal(lw, lwp)
    fp, lwf = bit_flips_tilted_packed(key, (64, 40), 0.03, 0.09)
    f, lwd = bit_flips_tilted(key, (64, 40), 0.03, 0.09)
    assert jnp.array_equal(fp, pack_shots(f)) and jnp.array_equal(lwf, lwd)


def test_fixed_weight_flips_exact_weight():
    for k in (1, 3, 7):
        flips = fixed_weight_flips(jax.random.PRNGKey(k), (128, 20), k)
        assert jnp.all(flips.sum(axis=1) == k)
    # traced k: one program serves every stratum
    fn = jax.jit(lambda kk, k: fixed_weight_flips(kk, (64, 20), k))
    for k in (2, 5):
        assert jnp.all(fn(jax.random.PRNGKey(0), k).sum(axis=1) == k)


def test_stratum_log_weight_matches_binomial():
    n, k, p = 25, 4, 0.03
    expect = (math.lgamma(n + 1) - math.lgamma(k + 1)
              - math.lgamma(n - k + 1)
              + k * math.log(p) + (n - k) * math.log1p(-p))
    assert abs(float(stratum_log_weight(n, k, p)) - expect) < 1e-4


def test_depolarizing_stratum_exact_weight_and_types():
    key = jax.random.PRNGKey(8)
    ex, ez, lw = depolarizing_xz_stratum(
        key, (256, CODE.N), [0.02, 0.01, 0.03], 3)
    w = np.asarray((ex.astype(bool) | ez.astype(bool)).sum(axis=1))
    assert (w == 3).all()  # total Pauli weight is exactly the stratum
    assert np.allclose(np.asarray(lw), float(lw[0]))  # constant per stratum


# ---------------------------------------------------------------------------
# ESS-aware uncertainty (utils.diagnostics)
# ---------------------------------------------------------------------------
def test_ess_interval_uniform_limit_matches_wilson_1e12():
    """Uniform weights (s1 = s2 = failures): the ESS interval IS Wilson.
    The issue pins 1e-12."""
    for f, n in [(0, 100), (1, 100), (17, 1000), (350, 4096), (999, 1000)]:
        lo_w, hi_w = diagnostics.wilson_interval(f, n)
        lo_e, hi_e = diagnostics.ess_interval(float(f), float(f), n)
        assert abs(lo_w - lo_e) < 1e-12 and abs(hi_w - hi_e) < 1e-12, (f, n)


def test_weighted_ci_fields_uniform_limit_matches_ci_fields():
    f, n = 23, 2048
    direct = diagnostics.ci_fields(f, n)
    weighted = diagnostics.weighted_ci_fields(
        f, float(f), float(f), float(n), float(n), n)
    for key in ("rate", "ci_low", "ci_high", "rel_ci_width"):
        assert abs(direct[key] - weighted[key]) < 1e-12, key
    assert weighted["failures"] == f and weighted["shots"] == n
    assert abs(weighted["ess"] - n) < 1e-9
    assert abs(weighted["ess_failures"] - f) < 1e-9


def test_ess_interval_widens_under_weight_degeneracy():
    """Same summed failure weight, degenerate distribution (one dominant
    weight): the honest interval must be wider than the uniform one."""
    n = 1000
    lo_u, hi_u = diagnostics.ess_interval(10.0, 10.0, n)   # 10 weight-1
    lo_d, hi_d = diagnostics.ess_interval(10.0, 100.0, n)  # 1 weight-10
    assert (hi_d - lo_d) > (hi_u - lo_u)


def test_effective_sample_size():
    assert diagnostics.effective_sample_size(100.0, 100.0) == 100.0
    assert diagnostics.effective_sample_size(10.0, 100.0) == 1.0
    assert diagnostics.effective_sample_size(0.0, 0.0) == 0.0


# ---------------------------------------------------------------------------
# WeightedStats + weighted WER transforms
# ---------------------------------------------------------------------------
def test_weighted_stats_uniform_limit_collapses_to_direct():
    f, n, K = 37, 4096, CODE.K
    ws = WeightedStats(failures=f, shots=n, s1=float(f), s2=float(f),
                       w1=float(n), w2=float(n))
    assert ws.rate == f / n
    assert abs(ws.ess - n) < 1e-9
    w_w, _ = wer_single_shot_weighted(ws, K)
    w_d, _ = wer_single_shot(f, n, K)
    assert abs(w_w - w_d) < 1e-12
    pc_w, _ = wer_per_cycle_weighted(ws, K, 5)
    pc_d, _ = wer_per_cycle(f, n, K, 5)
    assert abs(pc_w - pc_d) < 1e-12


def test_weighted_stats_merge():
    a = WeightedStats(failures=3, shots=100, s1=2.0, s2=1.5, w1=90.0,
                      w2=85.0, min_w=4)
    b = WeightedStats(failures=1, shots=50, s1=0.5, s2=0.3, w1=45.0,
                      w2=44.0, min_w=3)
    m = a.merge(b)
    assert m.failures == 4 and m.shots == 150 and m.min_w == 3
    assert m.s1 == 2.5 and m.w1 == 135.0


# ---------------------------------------------------------------------------
# Zero-tilt bit-exactness against the direct engines (seed-for-seed)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("packed", [True, False])
def test_data_zero_tilt_bitexact(packed):
    shots = 64 * 8
    direct = data_sim(packed=packed).WordErrorRate(shots)
    sim = data_sim(packed=packed)
    weighted = sim.WeightedWordErrorRate(shots)
    ws = sim.last_weighted
    assert weighted[0] == direct[0]
    # the uniform-weight limit collapses every moment onto the counts
    assert ws.s1 == ws.failures and ws.s2 == ws.failures
    assert ws.w1 == ws.shots and ws.w2 == ws.shots


def test_phenom_zero_tilt_bitexact():
    samples = 64 * 4
    direct = phenom_sim().WordErrorRate(num_rounds=3, num_samples=samples)
    sim = phenom_sim()
    weighted = sim.WeightedWordErrorRate(num_rounds=3, num_samples=samples)
    ws = sim.last_weighted
    assert weighted[0] == direct[0]
    assert ws.s1 == ws.failures and ws.w1 == ws.shots


def test_data_weighted_rejects_unsupported_paths():
    sim = data_sim()
    sim._needs_host = True
    with pytest.raises(ValueError, match="pure-device"):
        sim.WeightedWordErrorRate(64)


# ---------------------------------------------------------------------------
# Overlap-regime parity: tilted vs direct where both resolve the rate
# ---------------------------------------------------------------------------
def test_overlap_regime_tilted_matches_direct():
    """At a p near threshold both estimators resolve the failure rate; the
    tilted one must agree within combined CIs (fixed seeds, so this is a
    deterministic regression test, not a flaky statistical one)."""
    shots = 4096
    sim_d = data_sim(p=0.05, seed=2, batch_size=256)
    sim_d.WordErrorRate(shots)
    # direct failure rate from its own weighted view at zero tilt (same
    # counts, gives us the binomial moments without a private attribute)
    sim_0 = data_sim(p=0.05, seed=2, batch_size=256)
    sim_0.WeightedWordErrorRate(shots)
    direct = sim_0.last_weighted
    sim_w = data_sim(p=0.05, seed=2, batch_size=256)
    tilt = tilt_channel([0.05 / 3] * 3, 0.10)
    sim_w.WeightedWordErrorRate(shots, tilt_probs=tilt)
    tilted = sim_w.last_weighted
    assert tilted.failures > 50  # the tilt boosts the failure yield
    var_d = direct.rate * (1 - direct.rate) / direct.shots
    sigma = math.sqrt(tilted.variance + var_d)
    assert abs(tilted.rate - direct.rate) <= 3.0 * sigma
    # and the tilt reduced the variance on this sub-threshold cell
    vrf = variance_reduction(tilted)
    assert vrf is not None and vrf > 1.0


# ---------------------------------------------------------------------------
# Kill + resume of a weighted stream (v2 checkpoint, seed-for-seed)
# ---------------------------------------------------------------------------
@pytest.mark.faults
def test_weighted_kill_resume_seed_for_seed(tmp_path):
    from qldpc_fault_tolerance_tpu.utils import faultinject, resilience
    from qldpc_fault_tolerance_tpu.utils.checkpoint import (
        CellProgress,
        SweepCheckpoint,
    )

    key = jax.random.PRNGKey(31)
    shots = 64 * 16  # 16 batches = 8 megabatches at scan_chunk 2
    tilt = tilt_channel([0.05 / 3] * 3, 0.12)
    clean_sim = data_sim()
    clean = clean_sim.WeightedWordErrorRate(shots, tilt_probs=tilt, key=key)
    clean_ws = clean_sim.last_weighted

    ckpt_path = str(tmp_path / "cells.jsonl")
    cell_key = {"code": "rep3hgp", "noise": "data-w", "p": 0.05}
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=3,
                          count=99),
    ])
    policy = resilience.RetryPolicy(max_attempts=1, base_delay=0.0,
                                    jitter=0.0, reset_caches=False)
    progress = CellProgress(SweepCheckpoint(ckpt_path), cell_key, every=1)
    with resilience.policy_override(policy):
        with plan.active():
            with pytest.raises(faultinject.InjectedFault):
                data_sim().WeightedWordErrorRate(
                    shots, tilt_probs=tilt, key=key, progress=progress)

    # the persisted cursor carries the weighted block (v2, additive)
    st = SweepCheckpoint(ckpt_path).get_progress(cell_key)
    assert st is not None and st["batches_done"] > 0
    assert set(st["weighted"]) == {"s1", "s2", "w1", "w2"}

    progress2 = CellProgress(SweepCheckpoint(ckpt_path), cell_key, every=1)
    sim = data_sim()
    resumed = sim.WeightedWordErrorRate(shots, tilt_probs=tilt, key=key,
                                        progress=progress2)
    ws = sim.last_weighted
    assert resumed == clean  # seed-for-seed identical WER + error bar
    assert (ws.failures, ws.shots) == (clean_ws.failures, clean_ws.shots)
    assert (ws.s1, ws.s2, ws.w1, ws.w2) == (
        clean_ws.s1, clean_ws.s2, clean_ws.w1, clean_ws.w2)
    assert sim.last_dispatches < 8  # it resumed, not re-ran


# ---------------------------------------------------------------------------
# Weighted fused cells (rare/sweep.py)
# ---------------------------------------------------------------------------
def _rung_sims(ps, seed=17, batch=64):
    sims = []
    for p in ps:
        dec = lambda h: BPDecoder(  # noqa: E731
            h, np.full(CODE.N, p), max_iter=6)
        sims.append(CodeSimulator_DataError(
            code=CODE, decoder_x=dec(CODE.hz), decoder_z=dec(CODE.hx),
            pauli_error_probs=[p / 3] * 3, seed=seed, batch_size=batch,
            scan_chunk=2))
    return sims


def test_weighted_cells_match_serial_weighted():
    """The fused rung ladder reproduces each rung's serial
    WeightedWordErrorRate seed-for-seed: same counts, same moments."""
    ps = [0.05, 0.03]
    tilts = [tilt_channel([p / 3] * 3, 0.1) for p in ps]
    shots = 64 * 4
    cells = eval_weighted_cells(_rung_sims(ps), tilts, shots)
    for p, tilt, cell in zip(ps, tilts, cells):
        serial = _rung_sims([p])[0]
        serial.WeightedWordErrorRate(shots, tilt_probs=tilt)
        sw = serial.last_weighted
        fw = cell["stats"]
        assert (fw.failures, fw.shots) == (sw.failures, sw.shots)
        np.testing.assert_allclose(
            [fw.s1, fw.s2, fw.w1, fw.w2],
            [sw.s1, sw.s2, sw.w1, sw.w2], rtol=1e-6)


def test_weighted_cells_zero_tilt_matches_direct_fused():
    """A rung tilted to its own channel probs runs the zero-tilt
    configuration inside the fused program too."""
    ps = [0.06, 0.04]
    tilts = [[p / 3] * 3 for p in ps]  # zero tilt on every rung
    shots = 64 * 4
    cells = eval_weighted_cells(_rung_sims(ps), tilts, shots)
    for cell in cells:
        ws = cell["stats"]
        assert ws.s1 == ws.failures and ws.w1 == ws.shots


def test_weighted_cells_adaptive_donates_lanes():
    """target_rse: converged (shallow) rungs stop consuming lanes and the
    deep rung keeps running — the ESS-aware twin of the adaptive fused
    sweep.  Convergence is checked on the weighted rse."""
    ps = [0.08, 0.05]
    tilts = [tilt_channel([p / 3] * 3, 0.12) for p in ps]
    with telemetry.session(reset_metrics=True) as reg:
        cells = eval_weighted_cells(
            _rung_sims(ps, batch=64), tilts, 64 * 64,
            target_rse=0.25, min_failures=5)
        snap = reg.snapshot()
    for cell in cells:
        ws = cell["stats"]
        assert ws.failures >= 5
        rse = ws.rse
        # every rung either hit the target or ran the full budget
        assert (rse is not None and rse <= 0.25) or ws.shots == 64 * 64
    assert snap.get("driver.early_stops", {}).get("value", 0) >= 1


def test_eval_rare_grid_factory_entry():
    """The sweep-layer entry builds rungs through the decoder factory with
    CodeFamily's channel conventions and returns fit-ready points keyed on
    the sweep's eval_p axis."""
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class

    p_list = [0.04, 0.02]
    points = eval_rare_grid(
        CODE, BP_Decoder_Class(6, "minimum_sum", 0.625), p_list, 64 * 4,
        d_eff=3.0, batch_size=64, seed=13)
    assert [pt["p"] for pt in points] == p_list  # eval_p, not 1.5*eval_p
    for pt in points:
        assert pt["stats"].shots == 64 * 4
        assert pt["tilt"] >= 0.04 * 1.5  # tilted above every rung's rate


def test_weighted_cells_checkpoint_resume(tmp_path):
    """A finished weighted fused grid re-invoked with the same checkpoint
    resumes past the end: persisted counters come back, no new dispatches,
    seed-for-seed equal results."""
    from qldpc_fault_tolerance_tpu.utils.checkpoint import SweepCheckpoint

    ps = [0.05, 0.03]
    tilts = [tilt_channel([p / 3] * 3, 0.1) for p in ps]
    shots = 64 * 4
    path = str(tmp_path / "rare_ckpt.jsonl")
    first = eval_weighted_cells(_rung_sims(ps), tilts, shots,
                                checkpoint=SweepCheckpoint(path))
    second = eval_weighted_cells(_rung_sims(ps), tilts, shots,
                                 checkpoint=SweepCheckpoint(path))
    for a, b in zip(first, second):
        assert a["wer"] == b["wer"]
        assert a["stats"].failures == b["stats"].failures
        np.testing.assert_allclose(
            [a["stats"].s1, a["stats"].w2], [b["stats"].s1, b["stats"].w2],
            rtol=1e-6)


# ---------------------------------------------------------------------------
# Stratified (fixed-weight subset) estimator
# ---------------------------------------------------------------------------
def test_stratified_masses_and_rows():
    sim = data_sim(p=0.06, seed=3)
    res = stratified_wer(sim, range(2, 6), 128)
    assert 0.0 <= res["rate"] <= 1.0
    # covered + head + tail account for the full weight distribution
    assert abs(res["covered_mass"] + res["head_mass"] + res["tail_mass"]
               - 1.0) < 1e-9
    # head mass (k<2: the decoder-correctable shell) dominates at this p
    # and must NOT be reported as truncation error
    assert res["head_mass"] > 0.5
    assert res["tail_mass"] < 0.2
    assert [r["stratum"] for r in res["strata"]] == [2, 3, 4, 5]
    for row in res["strata"]:
        pmf = math.exp(
            math.lgamma(CODE.N + 1) - math.lgamma(row["stratum"] + 1)
            - math.lgamma(CODE.N - row["stratum"] + 1)
            + row["stratum"] * math.log(0.06)
            + (CODE.N - row["stratum"]) * math.log1p(-0.06))
        assert abs(row["weight"] - pmf) < 1e-12


def test_stratified_consistent_with_direct():
    """Σ_k P(W=k) r_k over a wide stratum range estimates the same failure
    rate direct MC sees (within combined statistical error)."""
    p = 0.08
    res = stratified_wer(data_sim(p=p, seed=5, batch_size=256),
                         range(1, 9), 2048)
    sim0 = data_sim(p=p, seed=6, batch_size=256)
    sim0.WeightedWordErrorRate(8192)  # zero tilt == direct counts
    direct = sim0.last_weighted
    var_d = direct.rate * (1 - direct.rate) / direct.shots
    sigma = math.sqrt(res["variance"] + var_d)
    assert abs(res["rate"] - direct.rate) <= 4.0 * sigma
    assert res["tail_mass"] < 0.01  # range covers the relevant strata


# ---------------------------------------------------------------------------
# Tilt selection + fit plumbing
# ---------------------------------------------------------------------------
def test_auto_tilt_bounds():
    assert auto_tilt(0.001) == pytest.approx(0.004)  # factor fallback
    # distance-aimed: q = (d_eff/2)/n
    assert auto_tilt(0.001, n=100, d_eff=10.0) == pytest.approx(0.05)
    assert auto_tilt(0.2, n=100, d_eff=2.0) == 0.2  # never below p
    assert auto_tilt(0.001, n=4, d_eff=8.0) == 0.25  # capped
    with pytest.raises(ValueError):
        auto_tilt(0.0)


def test_tilt_channel_preserves_ratios():
    tilt = tilt_channel([0.01, 0.02, 0.03], 0.12)
    assert sum(tilt) == pytest.approx(0.12)
    assert tilt[1] / tilt[0] == pytest.approx(2.0)
    with pytest.raises(ValueError):
        tilt_channel([0.0, 0.0, 0.0], 0.1)


def test_weighted_fit_point_and_fit_rare_distance():
    """Synthetic rare-event points on an exact pl = A p^{d/2} curve: the
    sigma-weighted fit recovers d within its own CI."""
    A, d = 30.0, 4.0
    points = []
    for p in (0.001, 0.002, 0.004, 0.008):
        pl = A * p ** (d / 2)
        n = 100000
        # synthetic weighted stats with a plausible second moment
        s1 = pl * n
        ws = WeightedStats(failures=max(int(pl * n * 2), 10), shots=n,
                           s1=s1, s2=s1 * 2e-3, w1=float(n),
                           w2=float(n) * 1.1)
        points.append(weighted_fit_point(p, ws, K=1, tilt=0.05))
    ps, wers, sigmas = rare_fit_points(points)
    assert len(ps) == 4 and all(s > 0 for s in sigmas)
    report = fit_rare_distance(points)
    assert report["converged"]
    assert report["d_eff"] == pytest.approx(d, rel=0.05)


def test_rare_fit_points_drops_sigma_less_cells():
    ws0 = WeightedStats(failures=0, shots=100, s1=0.0, s2=0.0, w1=100.0,
                        w2=100.0)
    pt0 = weighted_fit_point(0.001, ws0, K=1)
    assert pt0["sigma"] is None
    ws1 = WeightedStats(failures=5, shots=100, s1=0.05, s2=0.01,
                        w1=100.0, w2=101.0)
    pt1 = weighted_fit_point(0.002, ws1, K=1)
    ps, _, _ = rare_fit_points([pt0, pt1])
    assert ps == [0.002]


def test_variance_reduction_none_without_failures():
    ws = WeightedStats(failures=0, shots=100, s1=0.0, s2=0.0, w1=100.0,
                       w2=100.0)
    assert variance_reduction(ws) is None


# ---------------------------------------------------------------------------
# Telemetry: v3 events validate, weighted runs carry the new fields
# ---------------------------------------------------------------------------
def test_weighted_events_validate_against_schema_v3():
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    try:
        with diagnostics.sweep_run(config={"test": "rare"}):
            sim = data_sim(p=0.05, seed=4)
            sim.WeightedWordErrorRate(
                128, tilt_probs=tilt_channel([0.05 / 3] * 3, 0.1))
            stratified_wer(data_sim(p=0.05, seed=4), [2, 3], 64)
            tilts = [tilt_channel([0.05 / 3] * 3, 0.1)]
            eval_weighted_cells(_rung_sims([0.05]), tilts, 128)
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    kinds = {e["kind"] for e in sink.records}
    assert {"wer_run", "rare_stratum", "cell_done"} <= kinds
    problems = [p for e in sink.records for p in telemetry.validate_event(e)]
    assert problems == [], problems
    weighted_runs = [e for e in sink.records if e["kind"] == "wer_run"
                     and "ess" in e]
    assert weighted_runs, "weighted wer_run events must carry ess"
    for e in weighted_runs:
        assert e["log_weight_sum"] is None or e["log_weight_sum"] > 0
        assert e["ess"] > 0
    done = [e for e in sink.records if e["kind"] == "cell_done"]
    assert done and all("ess" in e and "tilt" in e for e in done)


def test_tilted_wer_returns_fit_point():
    pt = tilted_wer(data_sim(p=0.05, seed=8), 256, q_total=0.1)
    assert set(pt) >= {"p", "wer", "wer_eb", "sigma", "ess", "tilt"}
    assert pt["p"] == pytest.approx(0.05)
    assert pt["tilt"] == pytest.approx(0.1)


def test_weighted_tilt_support_validation():
    """The entry points reject tilts the estimator cannot be unbiased
    under: support violations (an outcome the channel produces that the
    proposal never draws) and non-sub-probability triples fail loudly
    instead of returning a healthy-looking biased number."""
    sim = data_sim(p=0.03)
    with pytest.raises(ValueError, match="support"):
        sim.WeightedWordErrorRate(64, tilt_probs=[0.0, 0.02, 0.02])
    with pytest.raises(ValueError, match="sub-probability"):
        sim.WeightedWordErrorRate(64, tilt_probs=[0.5, 0.4, 0.2])
    with pytest.raises(ValueError, match="components"):
        sim.WeightedWordErrorRate(64, tilt_probs=[0.1, 0.1])
    ps = phenom_sim(p=0.03)
    with pytest.raises(ValueError, match="support"):
        ps.WeightedWordErrorRate(2, 64, tilt_probs=[0.0, 0.02, 0.02])
    with pytest.raises(ValueError, match="tilt_q"):
        ps.WeightedWordErrorRate(2, 64, tilt_q=0.0)
    # the fused weighted grid validates per cell through the same gate
    with pytest.raises(ValueError, match="support"):
        eval_weighted_cells([data_sim(p=0.03)], [[0.0, 0.02, 0.02]], 64)
