"""Alert-rules engine + cross-host federation tests (ISSUE 17 tentpole,
parts 2-3): declarative rules over the time-series store (a ``for_s``
threshold rule fires and resolves deterministically on an injectable
clock, deadman rules page on missing heartbeats), transition-only v7
events ("alert_fired" / "alert_resolved") that validate against the
schema registry, the /alertz surface, bit-exact snapshot merging, and the
FleetGateway end to end over two LIVE ops HTTP servers — including the
host-kill -> fleet /healthz flip + host-down deadman the ISSUE's
acceptance demands.  Prometheus exposition conformance (# HELP lines,
text/plain; version=0.0.4) rides here too."""
import json
import os
import sys
import urllib.error
import urllib.request

import pytest

from qldpc_fault_tolerance_tpu.serve import ops
from qldpc_fault_tolerance_tpu.serve.fleet import (
    FleetGateway,
    merge_snapshots,
    start_fleet_thread,
)
from qldpc_fault_tolerance_tpu.serve.ops import (
    AlertEngine,
    AlertRule,
    default_alert_rules,
    start_ops_thread,
)
from qldpc_fault_tolerance_tpu.utils import telemetry, timeseries

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _counter(v):
    return {"type": "counter", "value": v}


# ---------------------------------------------------------------------------
# AlertRule / AlertEngine
# ---------------------------------------------------------------------------
def test_alert_rule_validation():
    with pytest.raises(ValueError):
        AlertRule(name="r", metric="m", kind="nope")
    with pytest.raises(ValueError):
        AlertRule(name="r", metric="m", mode="median")
    with pytest.raises(ValueError):
        AlertRule(name="r", metric="m", op="==")
    eng = AlertEngine([AlertRule(name="r", metric="m")])
    with pytest.raises(ValueError):  # duplicate rule names
        eng.add_rule(AlertRule(name="r", metric="m"))


def test_threshold_for_s_fires_and_resolves_deterministically():
    """The ISSUE's acceptance demo: a rate rule with a ``for_s`` fuse on an
    injectable clock — pending while the fuse burns, ONE alert_fired on
    expiry, silent while firing, ONE alert_resolved on the first healthy
    tick — and both transition events validate against the v7 registry."""
    store = timeseries.SeriesStore()
    rule = AlertRule(name="hot_rate", metric="c", mode="rate",
                     window_s=10.0, op=">", threshold=50.0, for_s=5.0,
                     severity="critical")
    eng = AlertEngine([rule], store=store)
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    try:
        # counter climbing 100/s: breach appears once two samples exist
        v = 0
        for t in (0.0, 1.0, 2.0):
            store.ingest(t, {"c": _counter(v)})
            v += 100
        assert eng.evaluate(now=2.0) == {"hot_rate": "pending"}
        store.ingest(4.0, {"c": _counter(v)})
        assert eng.evaluate(now=4.0) == {"hot_rate": "pending"}  # fuse burns
        store.ingest(7.5, {"c": _counter(v + 350)})
        assert eng.evaluate(now=7.5) == {"hot_rate": "firing"}   # 5.5s >= 5
        assert eng.evaluate(now=8.0) == {"hot_rate": "firing"}   # no re-fire
        rep = eng.report(now=8.0)
        assert rep["active"][0]["alert"] == "hot_rate"
        assert rep["active"][0]["firing_s"] == pytest.approx(0.5)
        # traffic stops: flat samples age the deltas out of the window
        for t in (12.0, 16.0, 20.0):
            store.ingest(t, {"c": _counter(v + 350)})
        assert eng.evaluate(now=20.0) == {"hot_rate": "inactive"}
        assert eng.firing() == []
    finally:
        telemetry.remove_sink(sink)
    fired = [r for r in sink.records if r["kind"] == "alert_fired"]
    resolved = [r for r in sink.records if r["kind"] == "alert_resolved"]
    assert len(fired) == 1 and len(resolved) == 1  # transitions only
    assert fired[0]["alert"] == "hot_rate"
    assert fired[0]["severity"] == "critical"
    assert fired[0]["value"] > 50.0
    assert resolved[0]["active_s"] == pytest.approx(12.5)
    for rec in ("alert_fired", "alert_resolved"):
        [ev] = [r for r in sink.records if r["kind"] == rec]
        assert telemetry.validate_event(ev) == []
    snap = telemetry.snapshot()
    assert snap["alerts.fired"]["value"] == 1
    assert snap["alerts.resolved"]["value"] == 1


def test_deadman_never_seen_is_a_missing_heartbeat():
    store = timeseries.SeriesStore()
    rule = AlertRule(name="dm", metric="hb", kind="deadman", window_s=10.0)
    eng = AlertEngine([rule], store=store)
    # the metric was never ingested: that IS the breach (for_s=0 -> fires)
    assert eng.evaluate(now=0.0) == {"dm": "firing"}
    # heartbeat appears -> resolves; stops moving past the window -> refires
    store.ingest(1.0, {"hb": _counter(1)})
    assert eng.evaluate(now=1.0) == {"dm": "inactive"}
    store.ingest(5.0, {"hb": _counter(1)})  # scraped but UNCHANGED
    assert eng.evaluate(now=12.0) == {"dm": "firing"}


def test_default_rules_and_scraper_self_watch():
    names = {r.name for r in default_alert_rules(0.05)}
    assert names == {"scraper_deadman", "health_probe_deadman",
                     "stream_commit_deadman"}
    # the scraper's own tick counter feeds its deadman: attach() rides the
    # scrape tick, so a live scraper keeps its self-watch quiet
    telemetry.enable()
    sc = timeseries.Scraper(interval_s=1.0)
    eng = AlertEngine([AlertRule(name="scraper_deadman",
                                 metric="timeseries.scrapes",
                                 kind="deadman", window_s=4.0)]).attach(sc)
    assert eng.store is sc.store
    sc.scrape_once(now=1.0)  # tick 1: scrapes counter ingested NEXT tick
    sc.scrape_once(now=2.0)
    assert eng.evaluate(now=2.0) == {"scraper_deadman": "inactive"}
    assert eng.evaluations == 3  # two hook rides + the explicit call
    # the scraper dies: nothing moves the counter -> the watch fires
    assert eng.evaluate(now=30.0) == {"scraper_deadman": "firing"}


def test_ops_server_alertz_and_healthz_alerts_block():
    store = timeseries.SeriesStore()
    eng = AlertEngine([AlertRule(name="dm", metric="hb", kind="deadman",
                                 window_s=1.0)], store=store)
    eng.evaluate(now=0.0)
    handle = start_ops_thread(alerts=eng)
    try:
        base = "http://%s:%s" % handle.address
        az = json.loads(urllib.request.urlopen(base + "/alertz").read())
        assert az["states"] == {"dm": "firing"} and az["rules"] == 1
        assert az["active"][0]["rule_kind"] == "deadman"
        hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert hz["alerts"] == {"firing": ["dm"], "count": 1}
    finally:
        handle.stop()
    # an engine-less plane still answers the same shape (fleet scraping
    # stays uniform across hosts with and without rules)
    empty = ops.OpsServer().alertz()
    assert empty == {"active": [], "resolved": [], "rules": 0,
                     "states": {}, "evaluations": 0}


# ---------------------------------------------------------------------------
# snapshot merging
# ---------------------------------------------------------------------------
def test_merge_snapshots_bit_exact_and_skips():
    h = {"type": "histogram", "buckets": [1.0, 2.0], "counts": [1, 2, 3],
         "sum": 4.5, "count": 6}
    h2 = {"type": "histogram", "buckets": [1.0, 2.0], "counts": [4, 5, 6],
          "sum": 2.5, "count": 15}
    bad = {"type": "histogram", "buckets": [9.0], "counts": [1, 1],
           "sum": 1.0, "count": 2}
    big_a, big_b = 2**53 + 1, 3  # float addition would round 2**53+1 away
    out = merge_snapshots({
        "a": {"c": _counter(big_a), "h": h,
              "g": {"type": "gauge", "value": 3.0, "ts": 1.0},
              "mix": _counter(1)},
        "b": {"c": _counter(big_b), "h": h2, "mix": bad},
    })
    assert out["merged"]["c"]["value"] == big_a + big_b  # bit-exact int sum
    assert out["merged"]["h"]["counts"] == [5, 7, 9]
    assert out["merged"]["h"]["sum"] == pytest.approx(7.0)
    assert out["merged"]["h"]["count"] == 21
    # gauges never sum: per-host only
    assert out["gauges"]["g"]["a"]["value"] == 3.0 and "g" not in out["merged"]
    # a counter/histogram type conflict is skipped, never fudged
    assert out["skipped"] == ["mix"] and "mix" not in out["merged"]


def test_merge_skips_boundary_mismatch():
    h1 = {"type": "histogram", "buckets": [1.0, 2.0], "counts": [1, 1, 1],
          "sum": 3.0, "count": 3}
    h3 = {"type": "histogram", "buckets": [1.0, 3.0], "counts": [2, 2, 2],
          "sum": 6.0, "count": 6}
    out = merge_snapshots({"a": {"h": h1}, "b": {"h": h3}})
    assert out["skipped"] == ["h"] and out["merged"] == {}


# ---------------------------------------------------------------------------
# FleetGateway with injectable clock + fetch (deterministic host kill)
# ---------------------------------------------------------------------------
class _FakeFleet:
    """Two synthetic hosts behind a (label, path) -> dict fetch."""

    def __init__(self):
        self.snaps = {
            "a": {"bp.shots": _counter(1000)},
            "b": {"bp.shots": _counter(2000)},
        }
        self.dead: set = set()

    def fetch(self, label, path):
        if label in self.dead:
            raise ConnectionError(f"{label} is down")
        if path == "/varz":
            return {"metrics": self.snaps[label]}
        if path == "/healthz":
            return {"ok": True}
        return {"active": [], "resolved": []}


def test_gateway_host_kill_flips_healthz_and_fires_deadman():
    fake = _FakeFleet()
    gw = FleetGateway({"a": "http://a:1", "b": "http://b:1"},
                      interval_s=5.0, down_after_s=12.0,
                      now=lambda: 0.0, fetch=fake.fetch)
    assert gw.scrape_once(now=0.0) == {"a": True, "b": True}
    assert gw.scrape_once(now=5.0) == {"a": True, "b": True}
    hz = gw.healthz(now=5.0)
    assert hz["ok"] is True and hz["up"] == 2 and hz["down"] == []
    assert gw.merged()["merged"]["bp.shots"]["value"] == 3000
    # kill b: inside the grace window the host is still "up" (one missed
    # scrape must not page), past down_after_s the deadman fires
    fake.dead.add("b")
    assert gw.scrape_once(now=10.0) == {"a": True, "b": False}
    assert gw.healthz(now=10.0)["ok"] is True
    gw.scrape_once(now=20.0)  # b's heartbeat age: 15s > 12s
    assert gw.alerts.firing() == ["host_down:b"]
    hz = gw.healthz(now=20.0)
    assert hz["ok"] is False and hz["down"] == ["b"]
    assert hz["hosts"]["a"]["up"] is True
    assert hz["hosts"]["b"]["error"].startswith("ConnectionError")
    az = gw.alertz(now=20.0)
    assert [(a["alert"], a["host"]) for a in az["active"]] == \
        [("host_down:b", "fleet")]
    # the host comes back: heartbeat moves again, the alert resolves
    fake.dead.discard("b")
    gw.scrape_once(now=25.0)
    assert gw.alerts.firing() == []
    assert gw.healthz(now=25.0)["ok"] is True
    assert [r["alert"] for r in gw.alertz(now=25.0)["resolved"]] == \
        ["host_down:b"]


# ---------------------------------------------------------------------------
# live end-to-end federation over two real ops HTTP servers
# ---------------------------------------------------------------------------
class _StaticOps(ops.OpsServer):
    """An ops plane serving a FIXED registry snapshot, so two in-process
    servers can report DISTINCT per-host metrics (the real registry is
    process-global)."""

    def __init__(self, snap):
        super().__init__()
        self._snap = snap

    def varz(self):
        return {"metrics": self._snap}


def _start_static(snap):
    server = _StaticOps(snap)
    loop, thread = ops.spawn_server_loop(server.start, "test-static-ops",
                                         "static ops")
    return ops.OpsHandle(server, loop, thread)


def test_fleet_federates_two_live_ops_servers():
    buckets = [0.01, 0.1, 1.0]
    ca, cb = [90, 8, 2, 0], [10, 60, 25, 5]
    snap_a = {"bp.shots": _counter(3_000_000_001),
              "serve.latency_s": {"type": "histogram", "buckets": buckets,
                                  "counts": ca, "sum": 1.5, "count": 100},
              "serve.queue_depth": {"type": "gauge", "value": 3.0,
                                    "max": 5.0, "ts": 1.0}}
    snap_b = {"bp.shots": _counter(4_000_000_007),
              "serve.latency_s": {"type": "histogram", "buckets": buckets,
                                  "counts": cb, "sum": 9.0, "count": 100},
              "serve.queue_depth": {"type": "gauge", "value": 5.0,
                                    "max": 7.0, "ts": 2.0}}
    ha, hb = _start_static(snap_a), _start_static(snap_b)
    clk = {"t": 0.0}
    gw = FleetGateway(
        {"a": "http://%s:%s" % ha.address, "b": "http://%s:%s" % hb.address},
        interval_s=5.0, down_after_s=12.0, now=lambda: clk["t"])
    fh = start_fleet_thread(gw, scrape=False)  # the test steps the clock
    try:
        base = "http://%s:%s" % fh.address
        assert gw.scrape_once(now=0.0) == {"a": True, "b": True}

        # merged /varz: counter sum is the exact integer sum of what each
        # host reported; histogram bucket vectors add element-wise
        varz = json.loads(urllib.request.urlopen(base + "/varz").read())
        assert varz["merged"]["bp.shots"]["value"] == 7_000_000_008
        assert varz["merged"]["serve.latency_s"]["counts"] == \
            [a + b for a, b in zip(ca, cb)]
        assert varz["merge_skipped"] == []

        # the merge preserves quantiles: a quantile over the merged bucket
        # vector equals the quantile over the union of both hosts' data
        merged_counts = varz["merged"]["serve.latency_s"]["counts"]
        p99 = timeseries.hist_quantile(buckets, merged_counts, 0.99)
        assert p99 == timeseries.hist_quantile(
            buckets, [a + b for a, b in zip(ca, cb)], 0.99)
        assert p99 > timeseries.hist_quantile(buckets, ca, 0.99)

        # /metrics: exposition-format conformance + per-host labels
        resp = urllib.request.urlopen(base + "/metrics")
        assert "version=0.0.4" in resp.headers["Content-Type"]
        text = resp.read().decode()
        lines = text.splitlines()
        assert "qldpc_bp_shots 7000000008" in lines
        assert 'qldpc_bp_shots{host="a"} 3000000001' in lines
        assert 'qldpc_bp_shots{host="b"} 4000000007' in lines
        # gauges are per-host ONLY (a queue depth does not sum)
        assert 'qldpc_serve_queue_depth{host="a"} 3.0' in lines
        assert not any(ln.startswith("qldpc_serve_queue_depth ")
                       for ln in lines)
        # cumulative histogram over the merged vector, +Inf = total count
        assert 'qldpc_serve_latency_s_bucket{le="+Inf"} 200' in lines
        # every # TYPE is introduced by a # HELP for the same family
        for i, ln in enumerate(lines):
            if ln.startswith("# TYPE"):
                fam = ln.split()[2]
                assert lines[i - 1].startswith(f"# HELP {fam} ")

        hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert hz["ok"] is True and hz["up"] == 2

        # kill host b for real: its server stops accepting, the fleet
        # health flips and the host-down deadman fires past the window
        hb.stop()
        clk["t"] = 20.0
        assert gw.scrape_once(now=20.0) == {"a": True, "b": False}
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/healthz")
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert body["ok"] is False and body["down"] == ["b"]
        assert gw.alerts.firing() == ["host_down:b"]
        az = json.loads(urllib.request.urlopen(base + "/alertz").read())
        assert [(a["alert"], a["host"]) for a in az["active"]] == \
            [("host_down:b", "fleet")]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/nope")
        assert exc.value.code == 404
    finally:
        fh.stop()
        ha.stop()


# ---------------------------------------------------------------------------
# exposition conformance on the LOCAL plane + the v7 frozen chain
# ---------------------------------------------------------------------------
def test_local_metrics_exposition_conformance():
    telemetry.enable()
    telemetry.count("bp.shots", 7)
    telemetry.set_gauge("serve.queue_depth", 2)
    telemetry.observe("serve.latency_s", 0.05)
    telemetry.set_metric_help("custom.thing", "does a thing\nwith newline")
    telemetry.count("custom.thing")
    handle = start_ops_thread()
    try:
        base = "http://%s:%s" % handle.address
        resp = urllib.request.urlopen(base + "/metrics")
        # the exposition-format version real Prometheus scrapers negotiate
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        lines = resp.read().decode().splitlines()
    finally:
        handle.stop()
        telemetry.set_metric_help("custom.thing", None)
    for i, ln in enumerate(lines):
        if ln.startswith("# TYPE"):
            fam = ln.split()[2]
            assert lines[i - 1].startswith(f"# HELP {fam} ")
    # registered help text is served, newline escaped per the format spec
    assert "# HELP qldpc_custom_thing does a thing\\nwith newline" in lines
    # unregistered metrics fall back to a generated description
    assert any(ln.startswith("# HELP qldpc_bp_shots ") for ln in lines)
    # gauges expose their high-water twin as its own helped family
    assert "# TYPE qldpc_serve_queue_depth_max gauge" in lines


def test_v7_frozen_chain():
    # the frozen-version chain (append-never): v7 adds exactly the alert
    # transition kinds, and every frozen set up the chain still validates
    assert telemetry._V7_EVENT_KINDS == frozenset(
        {"alert_fired", "alert_resolved"})
    for ks in (telemetry._V1_EVENT_KINDS, telemetry._V2_EVENT_KINDS,
               telemetry._V3_EVENT_KINDS, telemetry._V4_EVENT_KINDS,
               telemetry._V5_EVENT_KINDS, telemetry._V6_EVENT_KINDS,
               telemetry._V7_EVENT_KINDS):
        assert ks <= set(telemetry.EVENT_SCHEMAS)
    assert telemetry.EVENT_SCHEMA_VERSION >= 7


# ---------------------------------------------------------------------------
# the fleet_gateway CLI's target parsing
# ---------------------------------------------------------------------------
def test_fleet_gateway_cli_parse_targets():
    import fleet_gateway as fg

    got = fg.parse_targets(["a=http://h1:9100", "http://h2:9100/"])
    assert got == {"a": "http://h1:9100", "host1": "http://h2:9100/"}
    with pytest.raises(SystemExit):
        fg.parse_targets(["a=http://h1:9100", "a=http://h2:9100"])


def test_telemetry_report_fleet_only_renders_degraded_healthz(capsys):
    """telemetry_report --fleet works standalone (no JSONL — the operator
    on a gateway box has none) and still renders when the fleet /healthz
    answers 503: the degraded body is the whole point of looking."""
    import telemetry_report as tr

    ha = _start_static({"bp.shots": _counter(41)})
    clk = {"t": 0.0}
    gw = FleetGateway(
        # port 9 (discard) has no listener: host b is down from the start
        {"a": "http://%s:%s" % ha.address, "b": "http://127.0.0.1:9"},
        interval_s=5.0, down_after_s=12.0, now=lambda: clk["t"])
    fh = start_fleet_thread(gw, scrape=False)
    try:
        gw.scrape_once(now=0.0)
        clk["t"] = 20.0
        gw.scrape_once(now=20.0)
        assert gw.alerts.firing() == ["host_down:b"]
        assert tr.main(["--fleet", "http://%s:%s" % fh.address]) == 0
    finally:
        fh.stop()
        ha.stop()
    out = capsys.readouterr().out
    assert "DOWN: b" in out          # the 503 body was parsed, not dropped
    assert "host_down:b" in out      # active-alert block rides along
    assert "bp.shots" in out and "41" in out
    with pytest.raises(SystemExit):  # no JSONL and no --fleet: usage error
        tr.main([])


def test_telemetry_report_renders_router_placement_and_handoffs():
    """--fleet against a ROUTER ops view (RouterFleetServer varz): the
    placement table and the last-handoff ages render alongside the
    gateway block, and a plain gateway varz (no router keys) still
    renders without them."""
    import telemetry_report as tr

    out = tr.render_fleet({"varz": {
        "targets": {"h0": "http://a", "h1": "http://b"},
        "scrapes": 4,
        "placement": {"fam-a1020d": {"owner": "h0", "successor": "h1",
                                     "epoch": 2}},
        "handoffs": {"fam-a1020d": {"age_s": 3.2, "epoch": 2,
                                    "from": "h1", "to": "h0",
                                    "reason": "host_down:h1"}},
        "down_hosts": ["h1"],
    }})
    assert "family placement (router)" in out
    assert "fam-a1020d" in out
    assert "DOWN hosts: h1" in out
    assert "last handoffs" in out
    assert "h1 -> h0" in out and "host_down:h1" in out
    assert "3.2s ago" in out
    plain = tr.render_fleet({"varz": {"targets": {}, "scrapes": 0}})
    assert "placement" not in plain and "handoffs" not in plain
