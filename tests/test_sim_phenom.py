import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BPDecoder,
    BPOSD_Decoder,
    ST_BP_Decoder_syndrome,
)
from qldpc_fault_tolerance_tpu.sim import (
    CodeSimulator_Phenon,
    CodeSimulator_Phenon_SpaceTime,
)


def _surface(d=3):
    return hgp(rep_code(d), rep_code(d))


def _phenom_sim(code, p, q, **kw):
    hx_ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    hz_ext = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
    probs_ext_z = np.concatenate([np.full(code.N, p), np.full(code.hx.shape[0], q)])
    probs_ext_x = np.concatenate([np.full(code.N, p), np.full(code.hz.shape[0], q)])
    dec1_z = BPDecoder(hx_ext, probs_ext_z, max_iter=15)
    dec1_x = BPDecoder(hz_ext, probs_ext_x, max_iter=15)
    dec2_z = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=15, osd_order=4)
    dec2_x = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=15, osd_order=4)
    return CodeSimulator_Phenon(
        code=code, decoder1_x=dec1_x, decoder1_z=dec1_z,
        decoder2_x=dec2_x, decoder2_z=dec2_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], q=q, **kw
    )


def test_zero_noise_no_failures():
    sim = _phenom_sim(_surface(3), 1e-9, 0.0, batch_size=32)
    fails = sim.run_batch(jax.random.PRNGKey(0), num_rounds=3, batch_size=32)
    assert fails.sum() == 0


def test_failure_rate_grows_with_rounds():
    code = _surface(3)
    p, q = 0.04, 0.04
    sim = _phenom_sim(code, p, q, batch_size=256)
    f1 = sim.run_batch(jax.random.PRNGKey(1), num_rounds=1, batch_size=256).mean()
    f7 = sim.run_batch(jax.random.PRNGKey(1), num_rounds=7, batch_size=256).mean()
    assert f7 >= f1


def test_wer_accepts_even_cycles():
    """The published checkpoint notebooks sweep EVEN cycle counts (they
    predate the reference's odd-cycles assert); the inversion must accept
    them so the notebooks run unmodified (sim/common.wer_per_cycle)."""
    sim = _phenom_sim(_surface(3), 0.02, 0.02, batch_size=16)
    wer, _ = sim.WordErrorRate(num_rounds=4, num_samples=16)
    assert 0.0 <= wer <= 1.0


def test_word_error_probability_in_range():
    sim = _phenom_sim(_surface(3), 0.03, 0.03, batch_size=128)
    wep, eb = sim.WordErrorProbability(num_rounds=3, num_samples=128)
    assert 0 <= wep <= 1
    assert eb is not None


def _st_sim(code, p, q, num_rep, **kw):
    dec1_z = ST_BP_Decoder_syndrome(code.hx, p_data=p, p_synd=q, max_iter=30,
                                    num_rep=num_rep)
    dec1_x = ST_BP_Decoder_syndrome(code.hz, p_data=p, p_synd=q, max_iter=30,
                                    num_rep=num_rep)
    dec2_z = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=15, osd_order=4)
    dec2_x = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=15, osd_order=4)
    return CodeSimulator_Phenon_SpaceTime(
        code=code, decoder1_x=dec1_x, decoder1_z=dec1_z,
        decoder2_x=dec2_x, decoder2_z=dec2_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], q=q, num_rep=num_rep, **kw
    )


def test_st_zero_noise_no_failures():
    sim = _st_sim(_surface(3), 1e-9, 0.0, num_rep=2, batch_size=32)
    fails = sim.run_batch(jax.random.PRNGKey(0), num_rounds=3, batch_size=32)
    assert fails.sum() == 0


def test_st_rep1_statistically_matches_plain_phenom():
    """With num_rep=1 the space-time matrix is exactly [H|I], so the ST engine
    must reproduce the plain phenomenological engine's statistics."""
    code = _surface(3)
    p = q = 0.05
    n_shots = 768
    sim_st = _st_sim(code, p, q, num_rep=1, batch_size=n_shots, seed=3)
    sim_pl = _phenom_sim(code, p, q, batch_size=n_shots, seed=4)
    f_st = sim_st.run_batch(jax.random.PRNGKey(5), num_rounds=5).mean()
    f_pl = sim_pl.run_batch(jax.random.PRNGKey(6), num_rounds=5).mean()
    # binomial 3-sigma band around each other
    sigma = np.sqrt(max(f_pl * (1 - f_pl), 1e-4) / n_shots)
    assert abs(f_st - f_pl) < 6 * sigma + 0.05, (f_st, f_pl)


def test_st_wer_cycle_accounting():
    sim = _st_sim(_surface(3), 0.02, 0.02, num_rep=3, batch_size=64)
    # num_cycles=13 -> num_rounds=5, total cycles=13 (odd) — demo config shape
    wer, _ = sim.WordErrorRate(num_cycles=13, num_samples=64)
    assert 0 <= wer <= 1
