"""Cell-fused sweep execution (sweep/fused.py + the cell-axis engines):
bit-exactness vs the serial per-cell path, adaptive shot reallocation,
per-cell resume, fit-path equivalence, and the retrace-budget guard.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder, BP_Decoder_Class
from qldpc_fault_tolerance_tpu.sim import common as simc
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sweep import CodeFamily, CodeFamily_SpaceTime
from qldpc_fault_tolerance_tpu.utils import faultinject, resilience, telemetry
from qldpc_fault_tolerance_tpu.utils.checkpoint import SweepCheckpoint


def family(codes, batch_size=64, seed=1, ratio2=6):
    """Plain-BP family: pure-device decoders keep every cell on the fused
    megabatch unit."""
    return CodeFamily(
        codes,
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(ratio2, "minimum_sum", 0.625),
        batch_size=batch_size, seed=seed)


def data_sim(code, p, lt="Total", batch_size=64, seed=0, scan_chunk=2):
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    return CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 2] * 3, eval_logical_type=lt,
        batch_size=batch_size, seed=seed, scan_chunk=scan_chunk)


TINY = [hgp(rep_code(3), rep_code(3)), hgp(rep_code(4), rep_code(4))]


# ------------------------------------------------------- tier-1 fast smoke
def test_fused_data_grid_bitexact_smoke():
    """2 codes x 3 p tiny-HGP data grid: the fused default must reproduce
    the serial packed path bit for bit, seed for seed."""
    p_list = [0.02, 0.05, 0.08]
    serial = family(TINY).EvalWER("data", "Total", p_list, num_samples=256,
                                  if_plot=False, fused=False)
    fused = family(TINY).EvalWER("data", "Total", p_list, num_samples=256,
                                 if_plot=False)
    np.testing.assert_array_equal(fused, serial)


def test_fused_phenl_grid_bitexact():
    serial = family([TINY[0]]).EvalWER(
        "phenl", "Total", [0.01, 0.03], num_samples=128, num_cycles=3,
        if_plot=False, fused=False)
    fused = family([TINY[0]]).EvalWER(
        "phenl", "Total", [0.01, 0.03], num_samples=128, num_cycles=3,
        if_plot=False)
    np.testing.assert_array_equal(fused, serial)


def test_fused_dense_path_bitexact():
    """fused=True with packed=False engines: the dense pipeline fuses too
    (the planner inherits whatever substrate the rep sim runs)."""
    sims = [data_sim(TINY[0], p) for p in (0.03, 0.06)]
    for s in sims:
        s._packed = False
    prog = CodeSimulator_DataError.fused_cells_program(sims, 256)
    f, sh, _ = simc.fused_cell_finish(simc.fused_cell_launch(prog)[0])
    for i, p in enumerate((0.03, 0.06)):
        ref = data_sim(TINY[0], p)
        ref._packed = False
        _, key = jax.random.split(ref._base_key)
        wer = ref.WordErrorRate(int(sh[i]), key=key)
        assert prog.wer_fn(f[i], sh[i])[0] == wer[0]


def test_fused_mixed_logical_types_one_program():
    """Cells of different logical types fuse into ONE bucket: each lane
    selects its count with a traced index, results equal the serial runs."""
    sims = [data_sim(TINY[0], 0.05, lt) for lt in ("X", "Z", "Total")]
    prog = CodeSimulator_DataError.fused_cells_program(sims, 512)
    f, sh, _ = simc.fused_cell_finish(simc.fused_cell_launch(prog)[0])
    for i, lt in enumerate(("X", "Z", "Total")):
        ref = data_sim(TINY[0], 0.05, lt)
        _, key = jax.random.split(ref._base_key)
        assert prog.wer_fn(f[i], sh[i])[0] == ref.WordErrorRate(
            512, key=key)[0]


def test_fused_data_folded_decode_bitexact():
    """Exercise the DATA folded-decode branch in tier-1 (two-phase
    decoders, max_iter >= TWO_PHASE_MIN_ITER — the tiny-code smoke tests
    stay below it and only hit the vmapped unit)."""
    from qldpc_fault_tolerance_tpu.ops import bp

    codes = [hgp(rep_code(5), rep_code(5))]
    fam = family(codes, ratio2=4)
    rep = fam._data_sim(codes[0], 0.02, "Total")
    for dec in (rep.decoder_x, rep.decoder_z):
        assert dec.device_static[0] == "bp"
        assert dec.device_static[1] >= bp.TWO_PHASE_MIN_ITER, (
            "config regression: this test must hit the folded branch")
    serial = family(codes, ratio2=4).EvalWER(
        "data", "Total", [0.02, 0.06], num_samples=256, if_plot=False,
        fused=False)
    fused = family(codes, ratio2=4).EvalWER(
        "data", "Total", [0.02, 0.06], num_samples=256, if_plot=False)
    np.testing.assert_array_equal(fused, serial)


def test_serial_phenl_target_failures_early_stops():
    """The phenom engine's serial megabatch early stop (fused=False +
    target_failures): stops at megabatch granularity with the shots
    actually run as denominator, and matches a fixed run over that count."""
    from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder

    code = TINY[0]
    p = 0.06
    ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    extz = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])

    def sim():
        d1 = lambda h: BPDecoder(  # noqa: E731
            h, np.full(h.shape[1], p), max_iter=4)
        d2 = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=4)  # noqa: E731
        return CodeSimulator_Phenon(
            code=code, decoder1_x=d1(extz), decoder1_z=d1(ext),
            decoder2_x=d2(code.hz), decoder2_z=d2(code.hx),
            pauli_error_probs=[p / 2] * 3, q=p, batch_size=32, seed=5,
            scan_chunk=2)

    s = sim()
    _, key = jax.random.split(s._base_key)
    wer_t = s.WordErrorRate(3, 32 * 64, key=key, target_failures=10)
    cnt, total = sim()._count_failures(3, 32 * 64, key=key)
    assert total == 32 * 64  # full run really is bigger
    # replay a fixed run over the early-stopped shot count: identical WER
    stopped_shots = None
    for n_batches in range(2, 65, 2):
        ref = sim()
        wer_ref = ref.WordErrorRate(3, 32 * n_batches, key=key)
        if wer_ref[0] == wer_t[0] and wer_ref[1] == wer_t[1]:
            stopped_shots = 32 * n_batches
            break
    assert stopped_shots is not None and stopped_shots < 32 * 64


def test_adaptive_progress_not_resumed_by_fixed_stream(tmp_path):
    """A killed adaptive (target_failures) sweep must NOT seed a later
    fixed-budget rerun: the modes advance cells differently, so the
    fingerprints differ and the fixed rerun restarts the bucket clean."""
    p_list = [0.01, 0.08]  # the low-p cell needs many megabatches
    shots = 64 * 64
    clean = family(TINY[:1]).EvalWER("data", "Total", p_list,
                                     num_samples=shots, if_plot=False)
    path = str(tmp_path / "sweep.jsonl")
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=2,
                          count=99)])
    pol = resilience.RetryPolicy(max_attempts=1, base_delay=0.0, jitter=0.0,
                                 reset_caches=False)
    with resilience.policy_override(pol), plan.active():
        with pytest.raises(faultinject.InjectedFault):
            family(TINY[:1]).EvalWER(
                "data", "Total", p_list, num_samples=shots, if_plot=False,
                target_failures=100, checkpoint=SweepCheckpoint(path))
    with pytest.warns(UserWarning, match="fingerprint"):
        resumed = family(TINY[:1]).EvalWER(
            "data", "Total", p_list, num_samples=shots, if_plot=False,
            checkpoint=SweepCheckpoint(path))
    np.testing.assert_array_equal(resumed, clean)


def test_fused_phenl_folded_decode_bitexact():
    """Exercise the phenom FOLDED-decode branch (two-phase decoders: every
    per-round and final decode runs on the folded lane*shot batch): needs
    max_iter >= TWO_PHASE_MIN_ITER, which the tiny rep3 configs of the
    other phenl tests never reach."""
    from qldpc_fault_tolerance_tpu.ops import bp

    codes = [hgp(rep_code(5), rep_code(5))]
    fam = family(codes, ratio2=4)
    rep = fam._phenl_sim(codes[0], 0.01, "Total")
    for dec in (rep.decoder1_x, rep.decoder1_z, rep.decoder2_x,
                rep.decoder2_z):
        assert dec.device_static[0] == "bp"
        assert dec.device_static[1] >= bp.TWO_PHASE_MIN_ITER, (
            "config regression: this test must hit the folded branch")
    serial = family(codes, ratio2=4).EvalWER(
        "phenl", "Total", [0.01, 0.03], num_samples=128, num_cycles=3,
        if_plot=False, fused=False)
    fused = family(codes, ratio2=4).EvalWER(
        "phenl", "Total", [0.01, 0.03], num_samples=128, num_cycles=3,
        if_plot=False)
    np.testing.assert_array_equal(fused, serial)


def test_fused_spacetime_data_branch_bitexact():
    fam_args = dict(
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(6, "minimum_sum", 0.625),
        batch_size=64, seed=1)
    serial = CodeFamily_SpaceTime([TINY[0]], **fam_args).EvalWER(
        "data", "Total", [0.03, 0.06], num_samples=128, if_plot=False,
        fused=False)
    fused = CodeFamily_SpaceTime([TINY[0]], **fam_args).EvalWER(
        "data", "Total", [0.03, 0.06], num_samples=128, if_plot=False)
    np.testing.assert_array_equal(fused[0][0], serial[0][0])


# ----------------------------------------------- adaptive shot reallocation
def test_adaptive_reallocation_counts_bitexact_and_counted():
    """Adaptive early stop: every batch a cell executes draws from its
    serial positional stream — its failure count over the shots it ran
    equals a serial fixed run over the same shots — and converged cells'
    lanes are reallocated (telemetry counters prove it)."""
    telemetry.reset()
    telemetry.enable()
    try:
        sims = [data_sim(TINY[0], 0.02), data_sim(TINY[0], 0.08)]
        prog = CodeSimulator_DataError.fused_cells_program(sims, 64 * 40)
        f, sh, _ = simc.fused_cell_adaptive(prog, target_failures=15,
                                            tele_on=True)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    # the high-p cell converges first; its lane budget moved to the low-p
    # cell, so the grid ran reallocated shots and both cells early-stopped
    assert snap["sweep.reallocated_shots"]["value"] > 0
    assert snap["driver.early_stops"]["value"] >= 1
    for i, p in enumerate((0.02, 0.08)):
        assert f[i] >= 15
        ref = data_sim(TINY[0], p)
        _, key = jax.random.split(ref._base_key)
        cnt, _, _ = ref._device_run_stats(key, 64, int(sh[i]) // 64)
        assert int(cnt) == f[i]


def test_eval_wer_target_failures_fused():
    wer = family(TINY).EvalWER("data", "Total", [0.02, 0.08],
                               num_samples=64 * 32, if_plot=False,
                               target_failures=10)
    assert wer.shape == (2, 2)
    assert (wer > 0).all()


def test_plan_lanes_covers_disjoint_batches():
    cursors = np.array([8, 4, 0, 12])
    base, stride, cell, active, advance, realloc = simc.plan_lanes(
        cursors, [0, 2], n_lanes=4, k_inner=2, max_batches=40)
    assert active.all()
    # every (lane, scan-step) batch index is unique and contiguous per cell
    for c in (0, 2):
        lanes = [l for l in range(4) if cell[l] == c]
        covered = sorted(
            int(base[l]) + j * int(stride[l]) for l in lanes
            for j in range(2))
        assert covered == list(range(int(cursors[c]),
                                     int(cursors[c]) + len(lanes) * 2))
        assert advance[c] == len(lanes) * 2
    assert realloc == 2 * 2  # one extra lane per cell, k_inner batches each


def test_plan_lanes_caps_at_budget_and_idles_leftovers():
    cursors = np.array([38, 0])
    base, stride, cell, active, advance, realloc = simc.plan_lanes(
        cursors, [0], n_lanes=4, k_inner=2, max_batches=40)
    # one megabatch of budget left -> one lane, three idle
    assert active.sum() == 1 and advance[0] == 2 and realloc == 0


# ------------------------------------------------------- resume / progress
def test_fused_sweep_kill_resume_bitexact(tmp_path):
    """A fused sweep killed mid-bucket resumes through the v2 per-cell
    cursors and reproduces the uninterrupted grid bit for bit."""
    pytest.importorskip("qldpc_fault_tolerance_tpu.utils.faultinject")
    p_list = [0.05, 0.08]
    shots = 64 * 32
    clean = family(TINY[:1]).EvalWER("data", "Total", p_list,
                                     num_samples=shots, if_plot=False)
    path = str(tmp_path / "sweep.jsonl")
    plan = faultinject.FaultPlan([
        faultinject.Fault(site="megabatch_dispatch", kind="raise", after=2,
                          count=99)])
    pol = resilience.RetryPolicy(max_attempts=1, base_delay=0.0, jitter=0.0,
                                 reset_caches=False)
    with resilience.policy_override(pol), plan.active():
        with pytest.raises(faultinject.InjectedFault):
            family(TINY[:1]).EvalWER(
                "data", "Total", p_list, num_samples=shots, if_plot=False,
                checkpoint=SweepCheckpoint(path))
    ckpt = SweepCheckpoint(path)
    assert len(ckpt) < len(p_list)  # the kill landed mid-bucket
    resumed = family(TINY[:1]).EvalWER(
        "data", "Total", p_list, num_samples=shots, if_plot=False,
        checkpoint=SweepCheckpoint(path))
    np.testing.assert_array_equal(resumed, clean)


def test_fused_checkpoint_cells_interchange_with_serial(tmp_path):
    """Finished-cell records written by the fused path are keyed exactly
    like the serial path's, so either can resume the other's sweep."""
    path = str(tmp_path / "sweep.jsonl")
    p_list = [0.04, 0.07]
    fused = family(TINY[:1]).EvalWER(
        "data", "Total", p_list, num_samples=256, if_plot=False,
        checkpoint=SweepCheckpoint(path))
    # serial rerun against the same file: every cell must come from records
    telemetry.reset()
    telemetry.enable()
    try:
        serial = family(TINY[:1]).EvalWER(
            "data", "Total", p_list, num_samples=256, if_plot=False,
            fused=False, checkpoint=SweepCheckpoint(path))
        ran = telemetry.snapshot().get("sim.runs", {}).get("value", 0)
    finally:
        telemetry.disable()
    assert ran == 0
    np.testing.assert_array_equal(fused, serial)


# ------------------------------------------------------------- fit paths
def test_fits_consume_fused_results_identically():
    """ThresholdEst_extrapolation / DistanceEst see bit-identical WER
    arrays from the fused grid, so the fitted p_c / d_eff match the serial
    path to float tolerance."""
    est = 0.08
    kw = dict(noise_model="data", eval_logical_type="Total",
              eval_method="extrapolation", est_threshold=est,
              num_samples=256)

    def serial_family():
        fam = family(TINY, seed=3)
        orig = fam.EvalWER

        def eval_serial(*a, **k):
            k["fused"] = False
            return orig(*a, **k)

        fam.EvalWER = eval_serial
        return fam

    pc_serial = serial_family().EvalThreshold(**kw)
    pc_fused = family(TINY, seed=3).EvalThreshold(**kw)
    assert pc_fused == pytest.approx(pc_serial, rel=1e-12, abs=1e-15)

    d_serial = serial_family().EvalEffectiveDistances(**kw)
    d_fused = family(TINY, seed=3).EvalEffectiveDistances(**kw)
    np.testing.assert_allclose(d_fused, d_serial, rtol=1e-12)


# ------------------------------------------------- factory light state path
def test_get_decoder_state_matches_full_build():
    """The BP factory's GetDecoderState fast path must expose exactly the
    (static, state) the full GetDecoder build would — statics equal, LLR
    priors bit-identical, graphs the same memoized object."""
    code = TINY[1]
    cls = BP_Decoder_Class(4, "minimum_sum", 0.625)
    for params in (
            {"h": code.hz, "p_data": 0.03},
            {"h": np.hstack([code.hx, np.eye(code.hx.shape[0],
                                             dtype=np.uint8)]),
             "p_data": 0.02, "p_syndrome": 0.01},
    ):
        dec = cls.GetDecoder(dict(params))
        static, state = cls.GetDecoderState(dict(params))
        assert static == dec.device_static
        np.testing.assert_array_equal(np.asarray(state["llr0"]),
                                      np.asarray(dec.llr0))
        assert state["graph"] is dec.graph  # per-H memo object
        assert state["pallas"] is dec._pallas_head


def test_stack_from_overrides_matches_generic_stacking():
    sims = [data_sim(TINY[0], p) for p in (0.02, 0.05, 0.08)]
    states = [s._dev_state for s in sims]
    g_stacked, g_treedef, g_axes = simc.stack_cell_states(states)
    rep = states[0]
    # sims share no leaves by identity except the memoized graphs, so build
    # the overrides from the generically-stacked result itself
    o_stacked, o_treedef, o_axes = simc.stack_from_overrides(rep, {
        ("probs",): jnp.stack([s["probs"] for s in states]),
        ("dx", "llr0"): jnp.stack([s["dx"]["llr0"] for s in states]),
        ("dz", "llr0"): jnp.stack([s["dz"]["llr0"] for s in states]),
    })
    assert o_treedef == g_treedef
    assert o_axes == g_axes
    for a, b in zip(jax.tree_util.tree_leaves(o_stacked),
                    jax.tree_util.tree_leaves(g_stacked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(KeyError):
        simc.stack_from_overrides(rep, {("nope",): jnp.zeros(3)})


def test_bposd_bucket_fuses_and_matches_serial():
    """ISSUE 13: a BPOSD bucket (device OSD by default on every backend)
    now FUSES — the whole BP->OSD->check pipeline rides the cell-axis
    megabatch carry — and the fused grid must equal the serial per-cell
    run bit for bit, with zero OSD host round-trips and no fallback."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class

    fam_args = dict(
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(2, "minimum_sum", 0.625,
                                           "osd_e", 4),
        batch_size=64, seed=1)
    p_list = [0.06, 0.1]
    serial = CodeFamily([TINY[0]], **fam_args).EvalWER(
        "data", "Total", p_list, num_samples=128, if_plot=False,
        fused=False)
    telemetry.reset()
    telemetry.enable()
    try:
        fused = CodeFamily([TINY[0]], **fam_args).EvalWER(
            "data", "Total", p_list, num_samples=128, if_plot=False)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    np.testing.assert_array_equal(fused, serial)
    assert snap.get("sweep.fused_fallback_cells", {}).get("value", 0) == 0
    assert snap.get("osd.host_round_trips", {}).get("value", 0) == 0


def test_unfusable_bucket_falls_back_serially(monkeypatch):
    """A bucket whose builder cannot fuse must fall back per bucket and
    still return the serial result.  (BPOSD buckets fuse since ISSUE 13,
    so the unfusable condition is injected at the builder.)"""
    def boom(*a, **kw):
        # the builder signals "run serially" with ValueError (the same
        # channel _check_rep_fusable and the static-mismatch guards use)
        raise ValueError("injected: bucket cannot fuse")

    monkeypatch.setattr(CodeFamily, "_data_bucket_program",
                        lambda self, *a, **kw: boom())
    p_list = [0.03, 0.06]
    serial = family([TINY[0]]).EvalWER(
        "data", "Total", p_list, num_samples=128, if_plot=False,
        fused=False)
    telemetry.reset()
    telemetry.enable()
    try:
        fused = family([TINY[0]]).EvalWER(
            "data", "Total", p_list, num_samples=128, if_plot=False)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
    np.testing.assert_array_equal(fused, serial)
    assert snap["sweep.fused_fallback_cells"]["value"] == len(p_list)


# ------------------------------------------------------------ mesh sharding
def test_fused_mesh_shards_shot_axis():
    from qldpc_fault_tolerance_tpu.parallel import shot_mesh

    mesh = shot_mesh()
    n_dev = mesh.devices.size
    assert n_dev == 8  # conftest forces the 8-device virtual CPU mesh
    sims = [data_sim(TINY[0], p) for p in (0.03, 0.08)]
    prog = CodeSimulator_DataError.fused_cells_program(sims, 128, mesh=mesh)
    f, sh, _ = simc.fused_cell_finish(simc.fused_cell_launch(prog)[0])
    # every lane-batch runs on all devices: shots scale by the mesh size
    assert (sh == prog.n_batches * 64 * n_dev).all()
    assert (f >= 0).all() and (f <= sh).all()


# ------------------------------------------------------ retrace-budget guard
def test_retrace_budget_one_compile_per_shape_bucket():
    """PR-2 compile tracker: a warm fused sweep over NEW p-values (same
    shapes) must add ZERO retraces — the p-dependent state is traced, so
    baking a p into a program (the regression this guards) would recompile
    per p-point."""
    telemetry.reset()
    telemetry.enable()
    try:
        family(TINY, seed=7).EvalWER(
            "data", "Total", [0.021, 0.043, 0.065], num_samples=128,
            if_plot=False)
        before = telemetry.compile_stats().get("jax.retraces", 0)
        family(TINY, seed=7).EvalWER(
            "data", "Total", [0.03, 0.055, 0.077], num_samples=128,
            if_plot=False)
        after = telemetry.compile_stats().get("jax.retraces", 0)
    finally:
        telemetry.disable()
    assert after - before == 0, (
        f"{after - before} retraces on a same-shape p-sweep: some program "
        "is baking p (or another cell value) into its compile key")


# --------------------------------------------------------------- slow e2e
@pytest.mark.slow
def test_fused_end_to_end_family_sweep_slow():
    """Full-size fused family sweep (threshold-fit shaped): bigger codes,
    6 p-points, early stop + checkpoint, fused vs serial bit-exact."""
    codes = [hgp(rep_code(5), rep_code(5)), hgp(rep_code(7), rep_code(7))]
    p_list = list(10 ** np.linspace(np.log10(0.02), np.log10(0.08), 6))
    fam_args = dict(batch_size=128, seed=11, ratio2=4)
    serial = family(codes, **fam_args).EvalWER(
        "data", "Total", p_list, num_samples=1024, if_plot=False,
        fused=False)
    fused = family(codes, **fam_args).EvalWER(
        "data", "Total", p_list, num_samples=1024, if_plot=False)
    np.testing.assert_array_equal(fused, serial)
