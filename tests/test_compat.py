"""Tests: compat shims expose the reference API; par2gen utilities."""
import numpy as np
import pytest


def test_compat_install_reference_modules():
    import qldpc_fault_tolerance_tpu.compat as compat

    compat.install()
    from Simulators import CodeFamily, CodeSimulator_DataError, parmap  # noqa
    from Simulators_SpaceTime import CodeSimulator_Circuit_SpaceTime  # noqa
    from Decoders import BPOSD_Decoder_Class, GetSpaceTimeCheckMat  # noqa
    from Decoders_SpaceTime import ST_BPOSD_Decoder_Circuit_Class  # noqa
    from ErrorPlugin import AddCXError  # noqa
    from CircuitScheduling import ColorationCircuit  # noqa
    from QuantumExanderCodesGene import Girth, RandomaGraphs  # noqa
    from par2gen import LinearBlockCode  # noqa

    assert parmap(lambda x: x * 2, [3, 1, 2]) == [6, 2, 4]


def test_compat_third_party_stubs():
    import qldpc_fault_tolerance_tpu.compat as compat

    compat.install()
    from bposd.hgp import hgp  # noqa: the notebooks' import
    from ldpc.codes import ring_code, rep_code
    import ldpc.mod2 as mod2

    q = hgp(rep_code(3), rep_code(3))
    assert (q.N, q.K) == (13, 1)
    assert mod2.rank(ring_code(4)) == 3


def test_compat_girth_and_graphs():
    import qldpc_fault_tolerance_tpu.compat as compat

    compat.install()
    from QuantumExanderCodesGene import Girth, RandomaGraphs, TannerGraphToCheckMat

    H = RandomaGraphs(3, 4, 3)
    assert TannerGraphToCheckMat(H) is not None
    assert Girth(H) >= 4


# ------------------------------------------------------------- par2gen
HAMMING_P = np.array([[1, 1, 0], [0, 1, 1], [1, 1, 1], [1, 0, 1]])


@pytest.fixture
def hamming():
    from qldpc_fault_tolerance_tpu.utils import LinearBlockCode

    G = np.concatenate([HAMMING_P, np.eye(4, dtype=int)], axis=1)
    return LinearBlockCode(G=G)


def test_linear_block_code_params(hamming):
    assert (hamming.n(), hamming.k()) == (7, 4)
    assert hamming.dmin() == 3
    assert hamming.t() == 1
    assert hamming.errorDetectionCapability() == 2


def test_linear_block_code_weight_distribution(hamming):
    # [7,4,3] Hamming: A = [1,0,0,7,7,0,0,1]
    assert list(hamming.A()) == [1, 0, 0, 7, 7, 0, 0, 1]
    assert hamming.Ai(3) == 7


def test_linear_block_code_h_g_round_trip(hamming):
    from qldpc_fault_tolerance_tpu.utils import GtoH, HtoG

    H = hamming.H()
    assert not (H @ hamming.G().T % 2).any()  # H G^T = 0
    assert np.array_equal(HtoG(GtoH(hamming.G())), hamming.G())


def test_linear_block_code_syndrome_decode(hamming):
    m = np.array([1, 0, 1, 1])
    c = hamming.c(m)
    r = c.copy()
    r[4] ^= 1  # single error: within t=1
    assert np.array_equal(hamming.syndromeDecode(r), c)


def test_linear_block_code_probabilities(hamming):
    # PU at p=0 is 0 and increases with p; Pe bounded
    assert hamming.PU(0.0) == 0.0
    assert 0 < hamming.PU(0.01) < hamming.PU(0.1)
    assert 0 <= hamming.Pe(0.01) <= 1
