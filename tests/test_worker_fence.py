"""Tunneled-worker crash fence (README "Known frontiers").

The axon-tunneled worker deterministically crashes OSD-bearing decode
programs at batch >= 4096 (environment regression since round 2).  The
fence clamps the batch into the measured safe envelope ON THE TUNNELED
WORKER ONLY.  Crucially, that worker REPORTS ``jax.default_backend() ==
'tpu'`` — not 'axon' (ADVICE round-5 high: a fence gated on the literal
backend name 'axon' is inert in production).  These tests therefore drive
the fence through the backend string it actually sees in production
('tpu' + the axon-tunnel signal); a fence regressed to ``backend ==
'axon'`` gating FAILS them.  scripts/fence_proof.py runs the heavyweight
full-shape CPU counter-proof.
"""
import warnings

import jax
import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder, BPDecoder
from qldpc_fault_tolerance_tpu.sim import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sim.common import (
    WORKER_OSD_BATCH_SAFE,
    apply_worker_batch_fence,
    on_tunneled_worker,
)


def _bposd_sim(batch_size, device_osd=False):
    """Host-OSD BPOSD sim (the fence's scope since ISSUE 13 narrowed it to
    host-round-trip OSD stages); ``device_osd=True`` builds the default
    device-resident config, which the fence must NOT clamp."""
    code = hgp(rep_code(5), rep_code(5))
    p = 0.02
    dec = lambda h: BPOSD_Decoder(  # noqa: E731
        h, np.full(code.N, p), max_iter=12, osd_method="osd_0",
        device_osd=device_osd)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=batch_size, seed=3,
    )


def _as_tunneled_worker(monkeypatch):
    """Impersonate the production worker: backend name 'tpu' (what the
    tunnel actually reports) plus the AXON env marker tunnel signal."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("AXON_WORKER", "1")


def test_fence_clamps_osd_batch_on_tunneled_tpu_worker(monkeypatch):
    """THE regression test for the inert-fence bug: the worker reports
    'tpu', so a fence that only fires on backend 'axon' never fires in
    production — this test fails against such a fence."""
    sim = _bposd_sim(8192)
    _as_tunneled_worker(monkeypatch)
    assert on_tunneled_worker()
    with pytest.warns(UserWarning, match="worker fence"):
        apply_worker_batch_fence(sim)
    assert sim.batch_size == WORKER_OSD_BATCH_SAFE
    # idempotent: a second call neither warns nor re-clamps
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        apply_worker_batch_fence(sim)
    assert sim.batch_size == WORKER_OSD_BATCH_SAFE


def test_fence_ignores_plain_tpu_without_tunnel_signal(monkeypatch):
    """A direct (non-tunneled) TPU has no crash envelope: backend 'tpu'
    alone must NOT clamp.  Every tunnel-signal source is scrubbed — AXON*
    env markers AND the registered-platform sets (dev images that eagerly
    initialize the axon plugin leave 'axon' in xla_bridge's factory
    registry even after _clear_backends)."""
    sim = _bposd_sim(8192)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for k in list(__import__("os").environ):
        if k.startswith("AXON"):
            monkeypatch.delenv(k)
    # unrelated AXON-prefixed vars / disable-intent values are NOT signals
    monkeypatch.setenv("AXON_LOG_LEVEL", "debug")
    monkeypatch.setenv("AXON_WORKER", "0")
    from jax._src import xla_bridge as xb

    for reg in ("_backend_factories", "_backends"):
        cur = getattr(xb, reg, {})
        monkeypatch.setattr(
            xb, reg, {k: v for k, v in cur.items() if k != "axon"},
            raising=False)
    assert not on_tunneled_worker()
    apply_worker_batch_fence(sim)
    assert sim.batch_size == 8192


def test_fence_accepts_literal_axon_backend(monkeypatch):
    """Configurations that register the tunnel as the default platform
    report 'axon' directly; the fence still fires."""
    sim = _bposd_sim(8192)
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    assert on_tunneled_worker()
    with pytest.warns(UserWarning, match="worker fence"):
        apply_worker_batch_fence(sim)
    assert sim.batch_size == WORKER_OSD_BATCH_SAFE


def test_fence_leaves_device_resident_bposd_alone(monkeypatch):
    """ISSUE 13: the fence is scoped to HOST-round-trip OSD stages — the
    default device-resident BPOSD program runs at the flagship batch size
    even on the tunneled worker."""
    sim = _bposd_sim(8192, device_osd=True)
    assert not sim._needs_host
    _as_tunneled_worker(monkeypatch)
    assert on_tunneled_worker()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        apply_worker_batch_fence(sim)
    assert sim.batch_size == 8192


def test_fence_leaves_plain_bp_alone(monkeypatch):
    code = hgp(rep_code(5), rep_code(5))
    p = 0.02
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=12)  # noqa: E731
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=16384, seed=3,
    )
    _as_tunneled_worker(monkeypatch)
    apply_worker_batch_fence(sim)
    assert sim.batch_size == 16384  # flagship plain-BP batches stay untouched


def test_full_batch_osd_runs_on_cpu():
    """The exact crash-envelope batch (8192 >= 4096, OSD stage) on the CPU
    backend: must run and produce a sane WER — no clamp, no crash.  Uses
    the default device-resident BPOSD (host-OSD configs have no engine
    path since ISSUE 13)."""
    sim = _bposd_sim(8192, device_osd=True)
    apply_worker_batch_fence(sim)
    assert sim.batch_size == 8192  # cpu backend: fence is a no-op
    wer, eb = sim.WordErrorRate(8192)
    assert 0.0 <= wer <= 1.0 and eb >= 0.0
