"""Tunneled-worker crash fence (README "Known frontiers").

The axon worker deterministically crashes OSD-bearing decode programs at
batch >= 4096, and hgp_34_n1600 phenomenological cells (environment
regression since round 2).  The fence clamps the batch into the measured
safe envelope ON THE AXON BACKEND ONLY; these tests prove (a) the clamp
logic itself, and (b) that the same configs run CORRECTLY at full batch on
the CPU mesh — i.e. the crash is a worker property, not a framework limit
(scripts/fence_proof.py runs the heavyweight full-shape versions).
"""
import warnings

import jax
import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder, BPDecoder
from qldpc_fault_tolerance_tpu.sim import CodeSimulator_DataError
from qldpc_fault_tolerance_tpu.sim.common import (
    WORKER_OSD_BATCH_SAFE,
    apply_worker_batch_fence,
)


def _bposd_sim(batch_size):
    code = hgp(rep_code(5), rep_code(5))
    p = 0.02
    dec = lambda h: BPOSD_Decoder(  # noqa: E731
        h, np.full(code.N, p), max_iter=12, osd_method="osd_0")
    return CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=batch_size, seed=3,
    )


def test_fence_clamps_osd_batch_on_axon(monkeypatch):
    sim = _bposd_sim(8192)
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    with pytest.warns(UserWarning, match="worker fence"):
        apply_worker_batch_fence(sim)
    assert sim.batch_size == WORKER_OSD_BATCH_SAFE
    # idempotent: a second call neither warns nor re-clamps
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        apply_worker_batch_fence(sim)
    assert sim.batch_size == WORKER_OSD_BATCH_SAFE


def test_fence_leaves_plain_bp_alone(monkeypatch):
    code = hgp(rep_code(5), rep_code(5))
    p = 0.02
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=12)  # noqa: E731
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=16384, seed=3,
    )
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    apply_worker_batch_fence(sim)
    assert sim.batch_size == 16384  # flagship plain-BP batches stay untouched


def test_full_batch_osd_runs_on_cpu():
    """The exact crash-envelope batch (8192 >= 4096, OSD stage) on the CPU
    backend: must run and produce a sane WER — no clamp, no crash."""
    sim = _bposd_sim(8192)
    apply_worker_batch_fence(sim)
    assert sim.batch_size == 8192  # cpu backend: fence is a no-op
    wer, eb = sim.WordErrorRate(8192)
    assert 0.0 <= wer <= 1.0 and eb >= 0.0
