"""qldpc-lint (ISSUE 12): fixture suite for the AST invariant analyzer.

Each rule gets at least one positive (fires on the distilled violation)
and one negative (stays quiet on the blessed idiom) snippet, plus
suppression-comment, baseline round-trip, and the tier-1 full-package
gate: the analyzer over the real library + scripts with the checked-in
baseline must be clean, so a PR that silently violates a contract fails
here with a file:line instead of shipping.
"""
import json
import os
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from qldpc_fault_tolerance_tpu import analysis  # noqa: E402
from qldpc_fault_tolerance_tpu.analysis import (  # noqa: E402
    AnalysisContext,
    Baseline,
    BarePrintRule,
    BareSleepRule,
    CompileSiteRule,
    DonationRule,
    FaultSiteRule,
    HostSyncRule,
    KernelContractRule,
    LockDisciplineRule,
    PRNGKeyRule,
    SchemaDriftRule,
    SourceModule,
    TracerSafetyRule,
    run_analysis,
)
from qldpc_fault_tolerance_tpu.analysis.rules_kernels import (  # noqa: E402
    KernelContract,
)

PKG = "qldpc_fault_tolerance_tpu/"
FIX = PKG + "sim/_fixture.py"


def run_src(rule, src, rel=FIX, extra=None, schema_rel=None):
    """Run one rule over snippet modules; returns the AnalysisResult."""
    sources = {rel: src}
    sources.update(extra or {})
    modules = [SourceModule.parse(r, textwrap.dedent(s))
               for r, s in sources.items()]
    ctx = AnalysisContext(modules, schema_module_rel=schema_rel or
                          PKG + "utils/telemetry.py")
    return run_analysis(modules, [rule], ctx=ctx)


def findings_of(rule, src, **kw):
    res = run_src(rule, src, **kw)
    return [f for f in res.findings if f.rule == rule.id]


# ---------------------------------------------------------------------------
# R001 host-sync discipline
# ---------------------------------------------------------------------------
SYNC_POS = """
    import jax
    import jax.numpy as jnp

    def f(a):
        x = jnp.sum(a)
        n = x.item()
        host = jax.device_get(x)
        return n, host
"""


def test_r001_fires_on_sync_outside_blessed_sites():
    found = findings_of(HostSyncRule(), SYNC_POS)
    assert len(found) == 2
    assert ".item()" in found[0].message
    assert "device_get" in found[1].message


def test_r001_allowlisted_module_is_exempt():
    assert not findings_of(HostSyncRule(), SYNC_POS,
                           rel=PKG + "parallel/_fixture.py")
    assert not findings_of(HostSyncRule(), SYNC_POS,
                           rel=PKG + "sim/common.py")


def test_r001_deferred_lambda_fetch_is_exempt():
    src = """
        import jax
        import jax.numpy as jnp

        def f(a):
            x = jnp.sum(a)
            fetch = lambda: jax.device_get(x)
            return fetch
    """
    assert not findings_of(HostSyncRule(), src)


def test_r001_numpy_values_never_fire():
    src = """
        import jax
        import numpy as np

        def f(a):
            y = np.ravel(a)
            return y.tolist(), float(np.sum(a))
    """
    assert not findings_of(HostSyncRule(), src)


# ---------------------------------------------------------------------------
# R002 PRNG key hygiene
# ---------------------------------------------------------------------------
def test_r002_fires_on_straight_line_reuse():
    src = """
        import jax

        def f(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))
            return a + b
    """
    found = findings_of(PRNGKeyRule(), src)
    assert len(found) == 1 and "reused" in found[0].message


def test_r002_fires_on_loop_invariant_consumption():
    src = """
        import jax

        def f(key):
            out = []
            for i in range(4):
                out.append(jax.random.uniform(key, (2,)))
            return out
    """
    found = findings_of(PRNGKeyRule(), src)
    assert len(found) == 1 and "inside a loop" in found[0].message


def test_r002_fires_on_dead_split_result():
    src = """
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            return jax.random.uniform(k1, (2,))
    """
    found = findings_of(PRNGKeyRule(), src)
    assert len(found) == 1 and "dead split" in found[0].message


def test_r002_blessed_idioms_stay_clean():
    src = """
        import jax

        def split_then_use(key):
            k1, k2 = jax.random.split(key)
            return jax.random.uniform(k1, (2,)) + jax.random.normal(k2, (2,))

        def fold_in_stream(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.uniform(
                    jax.random.fold_in(key, i), (2,)))
            return out

        def dispatch_ladder(kind, key):
            kop = jax.random.fold_in(key, 1)
            if kind == "a":
                return jax.random.uniform(kop, (2,))
            if kind == "b":
                return jax.random.normal(kop, (2,))
            raise AssertionError(kind)
    """
    assert not findings_of(PRNGKeyRule(), src)


# ---------------------------------------------------------------------------
# R003 tracer safety
# ---------------------------------------------------------------------------
def test_r003_fires_on_clock_and_branch_in_jit():
    src = """
        import time

        import jax

        @jax.jit
        def f(x):
            t0 = time.time()
            if x > 0:
                x = x + 1
            return x, t0
    """
    found = findings_of(TracerSafetyRule(), src)
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2
    assert "host clock" in msgs and "`if` on traced value 'x'" in msgs


def test_r003_fires_in_scan_body():
    src = """
        import jax
        import jax.numpy as jnp

        def body(c, x):
            while x > 0:
                x = x - 1
            return c + x, x

        def run(xs):
            return jax.lax.scan(body, 0, xs)
    """
    found = findings_of(TracerSafetyRule(), src)
    assert len(found) == 1 and "`while`" in found[0].message


def test_r003_static_params_are_exempt():
    src = """
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "fast":
                return x + 1
            return x

        def kernel(x_ref, o_ref, *, early_stop):
            if early_stop:
                o_ref[:] = x_ref[:]

        def g(h, x):
            jitted = jax.jit(h, static_argnums=0)
            return jitted("bp", x)

        def h(kind, x):
            if kind == "bp":
                return x + 1
            return x
    """
    assert not findings_of(TracerSafetyRule(), src)


# ---------------------------------------------------------------------------
# R004 donation safety
# ---------------------------------------------------------------------------
def test_r004_fires_on_use_after_donation():
    src = """
        import jax

        def f(step, carry, xs):
            g = jax.jit(step, donate_argnums=(0,))
            out = g(carry, xs)
            return out + carry
    """
    found = findings_of(DonationRule(), src)
    assert len(found) == 1 and "donated" in found[0].message


def test_r004_rebind_ends_the_donated_lifetime():
    src = """
        import jax

        def f(step, carry, xs):
            g = jax.jit(step, donate_argnums=(0,))
            carry = g(carry, xs)
            return carry
    """
    assert not findings_of(DonationRule(), src)


# ---------------------------------------------------------------------------
# R005 schema drift
# ---------------------------------------------------------------------------
SCHEMA_STUB = """
    EVENT_SCHEMAS = {
        "wer_run": {"required": {"engine": str, "shots": int},
                    "optional": {}},
        "snapshot": {"required": {}, "optional": {}},
    }
    _V1_EVENT_KINDS = frozenset({"wer_run", "snapshot"})
"""
STUB_REL = PKG + "utils/telemetry.py"


def _schema_rule(**floors):
    return SchemaDriftRule(frozen_floors=floors or
                           {"_V1_EVENT_KINDS": 2})


def test_r005_fires_on_unregistered_kind():
    src = """
        from ..utils import telemetry

        def f():
            telemetry.event("not_a_kind", x=1)
    """
    found = findings_of(_schema_rule(), src,
                        extra={STUB_REL: SCHEMA_STUB})
    assert len(found) == 1 and "not_a_kind" in found[0].message


def test_r005_fires_on_missing_required_field():
    src = """
        from ..utils import telemetry

        def f():
            telemetry.event("wer_run", engine="data")
    """
    found = findings_of(_schema_rule(), src,
                        extra={STUB_REL: SCHEMA_STUB})
    assert len(found) == 1 and "'shots'" in found[0].message


def test_r005_fires_when_frozen_set_shrinks():
    shrunk = SCHEMA_STUB.replace(
        'frozenset({"wer_run", "snapshot"})', 'frozenset({"wer_run"})')
    found = findings_of(_schema_rule(), "x = 1",
                        extra={STUB_REL: shrunk})
    assert len(found) == 1 and "shrank" in found[0].message


def test_r005_fires_on_frozen_kind_without_schema():
    grown = SCHEMA_STUB.replace(
        'frozenset({"wer_run", "snapshot"})',
        'frozenset({"wer_run", "snapshot", "ghost"})')
    found = findings_of(_schema_rule(), "x = 1",
                        extra={STUB_REL: grown})
    assert len(found) == 1 and "'ghost'" in found[0].message


def test_r005_clean_emissions_pass():
    src = """
        from ..utils import telemetry
        from ..utils.observability import get_logger, log_record

        def f(fields):
            telemetry.event("wer_run", engine="data", shots=64)
            telemetry.event("wer_run", **fields)
            log_record(get_logger(), "snapshot")
    """
    assert not findings_of(_schema_rule(), src,
                           extra={STUB_REL: SCHEMA_STUB})


# ---------------------------------------------------------------------------
# R006 lock discipline
# ---------------------------------------------------------------------------
def test_r006_fires_on_unlocked_module_state_write():
    src = """
        import threading

        _REGISTRY = {}
        _EVENTS = []

        def register(name, obj):
            _REGISTRY[name] = obj

        def emit(e):
            _EVENTS.append(e)

        def reset():
            global _REGISTRY
            _REGISTRY = {}
    """
    found = findings_of(LockDisciplineRule(),
                        src, rel=PKG + "utils/_fixture.py")
    assert len(found) == 3


def test_r006_locked_and_threadlocal_writes_pass():
    src = """
        import threading

        _LOCK = threading.Lock()
        _REGISTRY = {}
        _TL = threading.local()
        _SNAPSHOT = ()

        def register(name, obj):
            with _LOCK:
                _REGISTRY[name] = obj

        def set_tl(x):
            _TL.value = x

        def swap(t):
            global _SNAPSHOT
            _SNAPSHOT = tuple(t)
    """
    assert not findings_of(LockDisciplineRule(),
                           src, rel=PKG + "serve/_fixture.py")


def test_r006_only_scopes_serve_and_utils():
    src = """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """
    assert not findings_of(LockDisciplineRule(), src,
                           rel=PKG + "codes/_fixture.py")


# ---------------------------------------------------------------------------
# R007 kernel contracts
# ---------------------------------------------------------------------------
CONTRACT_REL = PKG + "ops/_fixture.py"


def _contract_rule():
    return KernelContractRule(contracts=(
        KernelContract("fixture", CONTRACT_REL, "kern", "twin",
                       ("_shared",)),))


def test_r007_fires_on_copy_paste_drift():
    src = """
        def _shared(x):
            return x + 1

        def kern(x):
            return _shared(x)

        def twin(x):
            return x + 1
    """
    found = findings_of(_contract_rule(), src, rel=CONTRACT_REL)
    assert len(found) == 1
    assert "twin" in found[0].message and "_shared" in found[0].message


def test_r007_fires_on_renamed_entry_point():
    src = """
        def _shared(x):
            return x + 1

        def kern(x):
            return _shared(x)
    """
    found = findings_of(_contract_rule(), src, rel=CONTRACT_REL)
    assert len(found) == 1 and "no longer exists" in found[0].message


def test_r007_shared_body_reached_through_imports():
    helper_rel = PKG + "ops/_fixture_body.py"
    helper = """
        def _shared(x):
            return x + 1
    """
    src = """
        from ._fixture_body import _shared

        def kern(x):
            return _shared(x)

        def twin(x):
            return _shared(x) * 1
    """
    assert not findings_of(_contract_rule(), src, rel=CONTRACT_REL,
                           extra={helper_rel: helper})


def test_r007_role_shared_pins_directional_bodies():
    """The ISSUE 15 extension: a directional pair (wire codec) pins
    per-role bodies on top of the common ones — a pack that stops
    reaching pack_shots is a finding even while the common layout helper
    is still reached."""
    rule = KernelContractRule(contracts=(
        KernelContract("fixture", CONTRACT_REL, "kern", "twin",
                       ("_layout",),
                       role_shared=(("_pack",), ("_unpack",))),))
    good = """
        def _layout(x):
            return x

        def _pack(x):
            return x + 1

        def _unpack(x):
            return x - 1

        def kern(x):
            return _pack(_layout(x))

        def twin(x):
            return _unpack(_layout(x))
    """
    assert not findings_of(rule, good, rel=CONTRACT_REL)
    drifted = good.replace("return _pack(_layout(x))",
                           "return _layout(x) + 1")
    found = findings_of(rule, drifted, rel=CONTRACT_REL)
    assert len(found) == 1
    assert "kern" in found[0].message and "_pack" in found[0].message


def test_r007_registry_covers_declared_kernel_twin_pairs():
    names = {c.name for c in analysis.KERNEL_CONTRACTS}
    assert {"bp_v2_head", "bp_v1_v2_loop", "fused_sample",
            "fused_residual", "fused_decode",
            "packed_residual", "wire_packed_codec"} <= names


# ---------------------------------------------------------------------------
# R101 / R102 migrated guards
# ---------------------------------------------------------------------------
def test_r101_fires_on_bare_print():
    found = findings_of(BarePrintRule(), "def f():\n    print('x')\n")
    assert len(found) == 1


def test_r101_exemptions_and_docstrings():
    rule = BarePrintRule()
    assert not findings_of(rule, "def f():\n    print('x')\n",
                           rel=PKG + "utils/par2gen.py")
    # the old regex guard needed string-prefix special-casing; the AST
    # rule is immune to prints inside docstrings by construction
    assert not findings_of(rule, 'def f():\n    """print(x)"""\n')


def test_r102_fires_on_sleep_and_retry_loop():
    src = """
        import time

        def f():
            for attempt in range(3):
                time.sleep(0.1)
    """
    found = findings_of(BareSleepRule(), src)
    assert len(found) == 2


def test_r102_catches_from_import_sleep():
    src = """
        from time import sleep

        def f():
            sleep(1.0)
    """
    found = findings_of(BareSleepRule(), src)
    assert len(found) == 1 and "time.sleep" in found[0].message


def test_r003_catches_from_import_clock_and_random():
    src = """
        from random import random
        from time import perf_counter

        import jax

        @jax.jit
        def f(x):
            return x + random() + perf_counter()
    """
    found = findings_of(TracerSafetyRule(), src)
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2
    assert "perf_counter" in msgs and "random.random" in msgs


def test_r102_exempts_resilience_and_plain_loops():
    rule = BareSleepRule()
    src = "import time\n\ndef f():\n    time.sleep(1)\n"
    assert not findings_of(rule, src, rel=PKG + "utils/resilience.py")
    assert not findings_of(rule, "def f():\n    for i in range(3):\n"
                                 "        pass\n")


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------
def test_suppression_on_same_line_and_line_above():
    src = """
        def f():
            print('a')  # qldpc: ignore[R101]
            # qldpc: ignore[R101]
            print('b')
    """
    res = run_src(BarePrintRule(), src)
    assert not res.findings and res.suppressed == 2


def test_unused_suppression_is_a_finding():
    src = """
        def f():
            return 1  # qldpc: ignore[R101]
    """
    res = run_src(BarePrintRule(), src)
    assert len(res.findings) == 1
    assert res.findings[0].rule == "R000"
    assert "unused suppression" in res.findings[0].message


def test_suppression_only_masks_listed_rules():
    src = """
        import time

        def f():
            print('x')  # qldpc: ignore[R102]
    """
    res = run_src(BarePrintRule(), src)
    rules = {f.rule for f in res.findings}
    # the print still fires; the R102 suppression is NOT reported unused
    # because R102 did not run
    assert rules == {"R101"}


# ---------------------------------------------------------------------------
# R008 faultinject site discipline (ISSUE 14)
# ---------------------------------------------------------------------------
FAULT_MOD = PKG + "utils/faultinject.py"
FAULT_SITES_SRC = """
    SITES = {
        "alpha_site": "module a's failure point",
        "ckpt_site": "checkpoint append",
    }
"""


def run_fault_rule(sources):
    all_sources = {FAULT_MOD: FAULT_SITES_SRC}
    all_sources.update(sources)
    modules = [SourceModule.parse(r, textwrap.dedent(s))
               for r, s in all_sources.items()]
    ctx = AnalysisContext(modules)
    return run_analysis(modules, [FaultSiteRule()], ctx=ctx)


def test_r008_fires_on_unregistered_site_literal():
    res = run_fault_rule({FIX: """
        from ..utils import faultinject

        def f():
            faultinject.site("alfa_site")  # typo'd: never in SITES
    """})
    found = [f for f in res.findings if f.rule == "R008"]
    # the typo'd literal + the now-unplanted registered names
    assert any("not registered" in f.message and "alfa_site" in f.message
               for f in found)


def test_r008_fires_on_duplicate_site_across_modules():
    res = run_fault_rule({
        PKG + "sim/_fa.py": """
            from ..utils import faultinject

            def f():
                faultinject.site("alpha_site")
        """,
        PKG + "sim/_fb.py": """
            from ..utils import faultinject

            def g():
                faultinject.site("alpha_site")
                faultinject.truncate_fraction("ckpt_site")
        """,
    })
    found = [f for f in res.findings if f.rule == "R008"]
    assert len(found) == 1
    assert found[0].file == PKG + "sim/_fb.py"
    assert "also planted at" in found[0].message
    assert "sim/_fa.py" in found[0].message


def test_r008_fires_on_stale_sites_table_entry():
    res = run_fault_rule({FIX: """
        from ..utils import faultinject

        def f():
            faultinject.site("alpha_site")
    """})
    found = [f for f in res.findings if f.rule == "R008"]
    assert len(found) == 1
    assert found[0].file == FAULT_MOD
    assert "ckpt_site" in found[0].message and "plant" in found[0].message


def test_r008_quiet_on_registered_unique_and_dynamic_sites():
    res = run_fault_rule({FIX: """
        from ..utils import faultinject

        def f(site_name):
            faultinject.site("alpha_site")
            faultinject.truncate_fraction("ckpt_site")
            faultinject.site(site_name)       # dynamic: out of scope
            faultinject.site("wer." + "x")    # non-literal: out of scope
    """})
    assert [f for f in res.findings if f.rule == "R008"] == []


# ---------------------------------------------------------------------------
# R009 program-cache compile-site discipline (ISSUE 20)
# ---------------------------------------------------------------------------
def test_r009_fires_on_chained_lower_compile():
    found = findings_of(CompileSiteRule(), """
        import jax

        def f(fn, x):
            prog = jax.jit(fn).lower(x).compile()
            return prog(x)
    """)
    assert len(found) == 1
    assert "progcache.compile_cached" in found[0].message


def test_r009_fires_on_lower_then_compile_via_name():
    found = findings_of(CompileSiteRule(), """
        import jax

        def f(fn, x):
            lowered = jax.jit(fn).lower(x)
            return lowered.compile()
    """)
    # the bare lower fires once, the .compile() on its name fires once
    assert len(found) == 2


def test_r009_fires_on_bare_lower_with_args():
    found = findings_of(CompileSiteRule(), """
        def f(jitted, x):
            return jitted.lower(x).as_text()
    """)
    assert len(found) == 1
    assert ".lower(" in found[0].message


def test_r009_quiet_on_str_lower_and_exempt_modules():
    # argless .lower() is string casing, never an AOT lowering
    assert findings_of(CompileSiteRule(), """
        def f(name):
            return name.lower().strip()
    """) == []
    # the blessed compile site and the probe harnesses are exempt
    for rel in ("qldpc_fault_tolerance_tpu/utils/progcache.py",
                "qldpc_fault_tolerance_tpu/utils/profiling.py",
                "scripts/vmem_calibrate.py"):
        res = run_src(CompileSiteRule(), """
            import jax

            def f(fn, x):
                return jax.jit(fn).lower(x).compile()
        """, rel=rel)
        assert [f for f in res.findings if f.rule == "R009"] == []


def test_r009_suppressible_inline():
    res = run_src(CompileSiteRule(), """
        import jax

        def probe(fn, x):
            jax.jit(fn).lower(  # qldpc: ignore[R009]
                x).compile()
    """)
    assert [f for f in res.findings if f.rule == "R009"] == []


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------
def test_baseline_roundtrip(tmp_path):
    src = "def f():\n    print('a')\n    print('b')\n"
    raw = run_src(BarePrintRule(), src)
    assert len(raw.findings) == 2

    base = Baseline.from_findings(raw.findings)
    path = str(tmp_path / "baseline.json")
    base.save(path)
    loaded = Baseline.load(path)
    assert loaded.entries[0].count == 2
    assert "unreviewed" in loaded.entries[0].reason

    modules = [SourceModule.parse(FIX, src)]
    res = run_analysis(modules, [BarePrintRule()], loaded)
    assert not res.findings and res.baselined == 2
    # reasons survive a regeneration
    loaded.entries[0].reason = "teaching module"
    regen = Baseline.from_findings(raw.findings, previous=loaded)
    assert regen.entries[0].reason == "teaching module"


def test_baseline_budget_overflow_and_stale():
    src = "def f():\n    print('a')\n    print('b')\n"
    modules = [SourceModule.parse(FIX, src)]
    budget1 = Baseline.from_findings(
        [f for f in run_analysis(modules, [BarePrintRule()],
                                 Baseline()).findings][:1])
    res = run_analysis(modules, [BarePrintRule()], budget1)
    assert len(res.findings) == 1  # one over budget still reported

    clean = [SourceModule.parse(FIX, "def f():\n    return 1\n")]
    res2 = run_analysis(clean, [BarePrintRule()], budget1)
    assert not res2.findings and res2.stale_baseline


# ---------------------------------------------------------------------------
# Tier-1 gate: the real codebase
# ---------------------------------------------------------------------------
def test_full_package_has_no_unbaselined_findings():
    """THE gate: parse the library + scripts once, run every rule, apply
    inline suppressions and the checked-in baseline — anything left is a
    contract violation this PR introduced.  Budget: well under 10 s on
    the 2-core container (BASELINE.md records the measured figure)."""
    res = analysis.analyze_repo()
    assert not res.findings, \
        "qldpc-lint violations:\n" + "\n".join(
            f.render() for f in res.findings)
    assert not res.stale_baseline, \
        "stale baseline entries (ratchet down with --update-baseline): " \
        + ", ".join(f"{e.file} [{e.rule}]" for e in res.stale_baseline)
    assert res.files > 100  # the walk really covered the codebase
    assert set(res.rules) == {"R001", "R002", "R003", "R004", "R005",
                              "R006", "R007", "R008", "R009", "R101",
                              "R102"}


def test_nonexistent_lint_target_is_an_error():
    """A typo'd path must exit 2, never '0 files, clean' (a CI hook with
    a wrong path would otherwise pass forever while checking nothing)."""
    import pytest

    with pytest.raises(FileNotFoundError):
        analysis.collect_modules(["no/such/path.py"])
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "lint.py"),
         "no/such/path.py"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 2 and "does not exist" in out.stderr


def test_update_baseline_partial_run_keeps_other_entries(tmp_path):
    """--update-baseline over a path subset must not delete curated
    budgets (and reasons) for files outside the analyzed set."""
    from qldpc_fault_tolerance_tpu.analysis.__main__ import main

    path = str(tmp_path / "baseline.json")
    Baseline([analysis.BaselineEntry(
        PKG + "sim/phenom.py", "R001", 8, "curated reason")]).save(path)
    rc = main(["--baseline", path, "--update-baseline",
               "qldpc_fault_tolerance_tpu/analysis"])
    assert rc == 0
    kept = Baseline.load(path)
    assert len(kept.entries) == 1
    assert kept.entries[0].reason == "curated reason"


def test_r005_checks_the_schema_modules_own_emissions():
    stub = SCHEMA_STUB + (
        "\n    def emit():\n"
        "        event(\"not_registered\", x=1)\n")
    found = findings_of(_schema_rule(), "x = 1",
                        extra={STUB_REL: stub})
    assert len(found) == 1 and "not_registered" in found[0].message


def test_cli_json_output_is_stable():
    """`scripts/lint.py --json` exits 0 on the clean tree and emits the
    deterministic document bench_compare-style diffing needs."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "lint.py"),
         "--json", "--select", "R101,R102",
         "qldpc_fault_tolerance_tpu/analysis"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["version"] == 1 and doc["findings"] == []
    assert doc["rules"] == ["R101", "R102"]
    assert set(doc) == {"version", "files", "rules", "findings",
                        "counts", "suppressed", "baselined",
                        "stale_baseline"}
