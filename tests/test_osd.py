import numpy as np
import pytest

from qldpc_fault_tolerance_tpu._native import load_native
from qldpc_fault_tolerance_tpu.codes import gf2, hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders.osd import _osd_numpy, osd_decode_batch


def test_native_builds():
    assert load_native() is not None, "C++ native lib failed to build"


def test_native_gf2_rank_matches_numpy():
    lib = load_native()
    if lib is None:
        pytest.skip("no native lib")
    import ctypes

    rng = np.random.default_rng(3)
    for _ in range(5):
        h = (rng.random((17, 29)) < 0.25).astype(np.uint8)
        r = lib.qldpc_gf2_rank(
            np.ascontiguousarray(h).ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            17,
            29,
        )
        assert r == gf2.rank(h)


def _random_case(rng, m=12, n=24, wt=3):
    h = (rng.random((m, n)) < 0.25).astype(np.uint8)
    h[:, rng.integers(0, n)] |= 0  # keep arbitrary
    e = np.zeros(n, dtype=np.uint8)
    e[rng.choice(n, size=wt, replace=False)] = 1
    s = h @ e % 2
    return h, e, s


@pytest.mark.parametrize("method", ["osd_0", "osd_e", "osd_cs"])
def test_osd_satisfies_syndrome(method):
    rng = np.random.default_rng(11)
    h, e, s = _random_case(rng)
    p = np.full(24, 0.05)
    llr = np.log((1 - p) / p) * (1 - 2 * e)  # soft info pointing at the true error
    dec = osd_decode_batch(h, s[None], llr[None], p, osd_method=method, osd_order=6)
    assert np.array_equal(dec[0] @ h.T % 2 if False else h @ dec[0] % 2, s)


def test_osd_zero_syndrome_returns_zero():
    rng = np.random.default_rng(5)
    h = (rng.random((8, 16)) < 0.3).astype(np.uint8)
    p = np.full(16, 0.01)
    llr = np.log((1 - p) / p) * np.ones(16)
    dec = osd_decode_batch(h, np.zeros((1, 8), np.uint8), llr[None], p)
    assert not dec.any()


def test_osd_finds_min_weight_on_repetition_code():
    # rep code: syndrome from single flip in the middle; min-weight solution is that flip
    h = rep_code(9)
    e = np.zeros(9, np.uint8)
    e[4] = 1
    s = h @ e % 2
    p = np.full(9, 0.05)
    llr = np.full(9, np.log((1 - 0.05) / 0.05))  # uninformative (all "no error")
    dec = osd_decode_batch(h, s[None], llr[None], p, osd_method="osd_e", osd_order=6)
    assert np.array_equal(dec[0], e)


def test_cpp_matches_numpy_oracle():
    if load_native() is None:
        pytest.skip("no native lib")
    rng = np.random.default_rng(17)
    for trial in range(10):
        h, e, s = _random_case(rng, m=10, n=20, wt=2)
        p = rng.uniform(0.01, 0.2, size=20)
        llr = rng.normal(size=20)
        cost = np.maximum(np.log((1 - p) / p), 1e-12)
        for method in (0, 1, 2):
            a = _osd_numpy(h, s[None].astype(np.uint8), llr[None], cost, method, 5)
            b = osd_decode_batch(
                h, s[None], llr[None], p,
                osd_method={0: "osd_0", 1: "osd_e", 2: "osd_cs"}[method],
                osd_order=5,
            )
            # both must satisfy the syndrome and have equal cost (tie-breaking may differ)
            assert np.array_equal(h @ a[0] % 2, s)
            assert np.array_equal(h @ b[0] % 2, s)
            ca, cb = cost @ a[0], cost @ b[0]
            assert abs(ca - cb) < 1e-9, f"trial {trial} method {method}: {ca} vs {cb}"


def test_osd_order_improves_or_matches():
    # higher order can only lower (or keep) the solution cost
    rng = np.random.default_rng(23)
    code = hgp(rep_code(4), rep_code(4))
    h = code.hz
    n = code.N
    e = np.zeros(n, np.uint8)
    e[[1, 5]] = 1
    s = h @ e % 2
    p = np.full(n, 0.05)
    llr = np.full(n, 1.0)
    cost = np.maximum(np.log((1 - p) / p), 1e-12)
    d0 = osd_decode_batch(h, s[None], llr[None], p, osd_method="osd_0")
    d10 = osd_decode_batch(h, s[None], llr[None], p, osd_method="osd_e", osd_order=10)
    assert cost @ d10[0] <= cost @ d0[0] + 1e-9


def test_osd_prior_above_half_prefers_setting_bit():
    """A channel prior > 1/2 gives a *negative* flip cost: the most probable
    coset element sets that bit even when a cheaper-weight alternative
    exists.  (A clamp-to-positive cost would silently invert this.)"""
    import numpy as np

    from qldpc_fault_tolerance_tpu.decoders.osd import osd_decode_batch

    h = np.array([[1, 1, 0], [0, 1, 1]], dtype=np.uint8)
    # bit 2 prior 0.9: for syndrome (0,1) candidates are {e2} (cost
    # log(.1/.9) < 0) and {e0, e1}; the negative-cost single bit must win
    probs = np.array([0.01, 0.01, 0.9])
    out = osd_decode_batch(
        h, np.array([[0, 1]], np.uint8), np.zeros((1, 3), np.float32), probs,
        osd_method="osd_e", osd_order=3,
    )
    assert out[0].tolist() == [0, 0, 1]
    # and for syndrome (0,0): setting bit 2 alone violates check 2, but the
    # all-zero word costs MORE than {e1, e2}? cost(e1)+cost(e2) =
    # log(99)+log(1/9) > 0 -> all-zero still wins
    out0 = osd_decode_batch(
        h, np.array([[0, 0]], np.uint8), np.zeros((1, 3), np.float32), probs,
        osd_method="osd_e", osd_order=3,
    )
    assert out0[0].tolist() == [0, 0, 0]
