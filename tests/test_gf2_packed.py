"""Bit-packed GF(2) layer (ops/gf2_packed) vs the dense uint8 reference.

Every packed op must be BIT-EXACT against the dense path — packing is a
layout change, not an approximation — including on ragged
(non-multiple-of-32) batches where the padding lanes must never leak into
results.  The WER test at the bottom is the end-to-end guarantee: the
packed pipeline is seed-for-seed identical to the dense one on a real
codes_lib code.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.codes.gf2 import pack_bitplane, unpack_bitplane
from qldpc_fault_tolerance_tpu.noise import (
    bit_flips,
    bit_flips_packed,
    depolarizing_xz,
    depolarizing_xz_packed,
)
from qldpc_fault_tolerance_tpu.ops.gf2_packed import (
    lane_mask,
    num_words,
    pack_shots,
    packed_any,
    packed_count,
    packed_gf2_matmul,
    packed_parity_apply,
    packed_per_shot_weight,
    unpack_shots,
)
from qldpc_fault_tolerance_tpu.ops.linalg import ParityOp, gf2_matmul

RAGGED_BATCHES = [1, 31, 32, 33, 100, 256]


def _rand_bits(rng, b, n):
    return (rng.random((b, n)) < 0.3).astype(np.uint8)


@pytest.mark.parametrize("b", RAGGED_BATCHES)
def test_pack_unpack_roundtrip(b):
    rng = np.random.default_rng(b)
    bits = _rand_bits(rng, b, 17)
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(pack_shots(bits), b)), bits)
    # host reference (codes/gf2.py) pins the same layout with numpy only
    np.testing.assert_array_equal(
        np.asarray(pack_shots(bits)), pack_bitplane(bits))
    np.testing.assert_array_equal(unpack_bitplane(pack_bitplane(bits), b), bits)


def test_lane_layout_lsb_first():
    # shot 32*w + j lands in bit j of word w
    bits = np.zeros((70, 1), np.uint8)
    bits[0] = bits[33] = bits[69] = 1
    packed = np.asarray(pack_shots(bits))
    assert packed.shape == (3, 1)
    assert packed[0, 0] == 1            # shot 0 -> word 0 bit 0
    assert packed[1, 0] == 1 << 1       # shot 33 -> word 1 bit 1
    assert packed[2, 0] == 1 << 5       # shot 69 -> word 2 bit 5


@pytest.mark.parametrize("b", RAGGED_BATCHES)
def test_packed_parity_apply_matches_dense(b):
    rng = np.random.default_rng(100 + b)
    n, m = 37, 23
    h = (rng.random((m, n)) < 0.15).astype(np.uint8)
    h[:, 0] = 1  # no empty rows/cols edge weirdness
    par = ParityOp(h)
    bits = _rand_bits(rng, b, n)
    dense = np.asarray(par(jnp.asarray(bits)))
    packed = packed_parity_apply(par.nbr, par.mask, pack_shots(bits))
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(packed, b)), dense)


@pytest.mark.parametrize("b", RAGGED_BATCHES)
def test_packed_gf2_matmul_matches_dense(b):
    rng = np.random.default_rng(200 + b)
    n, k = 29, 5
    h_t = (rng.random((n, k)) < 0.4).astype(np.uint8)
    bits = _rand_bits(rng, b, n)
    dense = np.asarray(gf2_matmul(jnp.asarray(bits), jnp.asarray(h_t)))
    packed = packed_gf2_matmul(pack_shots(bits), jnp.asarray(h_t))
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(packed, b)), dense)


@pytest.mark.parametrize("b", RAGGED_BATCHES)
def test_packed_reductions_mask_ragged_padding(b):
    rng = np.random.default_rng(300 + b)
    n = 11
    bits = _rand_bits(rng, b, n)
    packed = pack_shots(bits)
    flags = packed_any(packed)
    np.testing.assert_array_equal(
        np.asarray(unpack_shots(flags, b)), bits.any(axis=1).astype(np.uint8))
    # count masks the padding lanes even if they were (artificially) set
    poisoned = jnp.asarray(np.asarray(flags) | ~np.asarray(lane_mask(b)))
    assert int(packed_count(poisoned, b)) == int(bits.any(axis=1).sum())
    np.testing.assert_array_equal(
        np.asarray(packed_per_shot_weight(packed, b)),
        bits.sum(axis=1).astype(np.int32))
    assert num_words(b) == -(-b // 32)


@pytest.mark.parametrize("b", [32, 100, 512])
def test_packed_samplers_bit_exact(b):
    key = jax.random.PRNGKey(b)
    probs = (0.01, 0.005, 0.02)
    ex, ez = depolarizing_xz(key, (b, 40), probs)
    exp, ezp = depolarizing_xz_packed(key, (b, 40), probs)
    np.testing.assert_array_equal(np.asarray(unpack_shots(exp, b)),
                                  np.asarray(ex))
    np.testing.assert_array_equal(np.asarray(unpack_shots(ezp, b)),
                                  np.asarray(ez))
    flips = bit_flips(key, (b, 15), 0.1)
    flips_p = bit_flips_packed(key, (b, 15), 0.1)
    np.testing.assert_array_equal(np.asarray(unpack_shots(flips_p, b)),
                                  np.asarray(flips))


def _wer(code, packed, batch_size, shots, key):
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    p = 0.05
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=20)  # noqa: E731
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=batch_size, seed=0,
        scan_chunk=2, packed=packed,
    )
    wer, eb = sim.WordErrorRate(shots, key=key)
    return wer, eb, sim.min_logical_weight


def test_wer_seed_for_seed_packed_equals_dense_hgp225():
    """End-to-end: the packed pipeline on hgp_34_n225 is bit-identical to
    the dense uint8 pipeline — same failure count, error bar and min
    logical weight for the same key."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    npz = os.path.join(here, "codes_lib_tpu", "hgp_34_n225.npz")
    if os.path.exists(npz):
        from qldpc_fault_tolerance_tpu.codes import load_code

        code = load_code(npz)
    else:  # regenerated lib missing: equivalent structural stand-in
        code = hgp(rep_code(8), rep_code(8))
    key = jax.random.PRNGKey(42)
    got_p = _wer(code, True, 512, 1024, key)
    got_d = _wer(code, False, 512, 1024, key)
    assert got_p == got_d, (got_p, got_d)


def test_wer_seed_for_seed_packed_equals_dense_ragged_batch():
    """Ragged batch (not a multiple of 32): padding lanes must not alter
    counts."""
    code = hgp(rep_code(5), rep_code(5))
    key = jax.random.PRNGKey(7)
    got_p = _wer(code, True, 100, 300, key)
    got_d = _wer(code, False, 100, 300, key)
    assert got_p == got_d, (got_p, got_d)
