"""Chaos-hardened serving (ISSUE 14): seeded fault schedules against a
LIVE decode server, with the serving invariants asserted after every
schedule — every accepted request answered exactly once, answered
corrections bit-exact vs the offline ``decode_batch``, ``/healthz`` back
to 200 with zero operator action, and postmortem/trace artifacts naming
every affected request.  Plus the unit halves: self-healing sessions
(background heal + HealthProbe), exactly-once re-dispatch (journal,
dedupe, bounded re-queue), client reconnect/hedging (torn sockets,
dropped connections), elastic mesh degrade (device loss mid-run replans
onto the survivors, counts exactly equal), and the drain-vs-disconnect
race the scheduler must win."""
import glob
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import (
    BP_Decoder_Class,
    BPDecoder,
    ST_BP_Decoder_Class,
)
from qldpc_fault_tolerance_tpu.parallel import shot_mesh
from qldpc_fault_tolerance_tpu.serve import (
    ContinuousBatcher,
    DecodeClient,
    DecodeSession,
    HealthProbe,
    LocalFleet,
    SLOEngine,
    SLOPolicy,
    start_ops_thread,
    start_server_thread,
)
from qldpc_fault_tolerance_tpu.serve.session import family_digest
from qldpc_fault_tolerance_tpu.utils import (
    faultinject,
    resilience,
    telemetry,
    tracing,
)

pytestmark = pytest.mark.faults

DEC_CLS = BP_Decoder_Class(4, "minimum_sum", 0.625)
CODE3 = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
CODE4 = hgp(rep_code(4), rep_code(4), name="hgp_rep4")
P = 0.05

# fast, deterministic retry behavior for the dispatcher thread (the
# scheduler consults the PROCESS default policy, not a thread-local
# override — the dispatch runs on its own thread)
FAST_POLICY = resilience.RetryPolicy(
    max_attempts=2, base_delay=0.01, backoff=1.0, jitter=0.0,
    reset_caches=False, degrade_after=1)
TRIVIAL_POLICY = resilience.RetryPolicy(max_attempts=1)


@pytest.fixture(autouse=True)
def _clean_world():
    telemetry.disable()
    telemetry.reset()
    faultinject.deactivate()
    prev_policy = resilience.current_policy()
    tracing.recorder().clear()
    yield
    resilience.set_default_policy(prev_policy)
    faultinject.deactivate()
    tracing.configure(postmortem_dir="")
    telemetry.disable()
    telemetry.reset()


def _params(code):
    return {"h": code.hx, "p_data": P}


def _session(code, name=None, buckets=(8, 32)):
    return DecodeSession(name or code.name, decoder_class=DEC_CLS,
                         params=_params(code), buckets=buckets)


def _synd(code, k, rng):
    err = (rng.random((k, code.N)) < P).astype(np.uint8)
    return (err @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)


def _offline(code, synd):
    return DEC_CLS.GetDecoder(_params(code)).decode_batch(synd)


def _counter(name):
    return telemetry.snapshot().get(name, {}).get("value", 0)


# ---------------------------------------------------------------------------
# Self-healing sessions
# ---------------------------------------------------------------------------
def test_session_heal_swaps_in_background_and_stays_bitexact():
    """heal() rebuilds state + recompiles the warm bucket set off to the
    side and swaps atomically: generation bumps, the warm decode path
    stays retrace-free, and corrections are bit-exact across the swap."""
    telemetry.enable()
    sess = _session(CODE3)
    sess.warm()
    rng = np.random.default_rng(0)
    synd = _synd(CODE3, 5, rng)
    before_heal = sess.decode(synd)
    gen0, compiles0 = sess.generation, sess.compiles
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        n = sess.heal(reason="test")
    finally:
        telemetry.remove_sink(sink)
    assert n == len(sess.buckets)  # every warm bucket recompiled
    assert sess.generation == gen0 + 1 and sess.heals == 1
    assert sess.compiles == compiles0 + n
    heals = [r for r in sink.records if r["kind"] == "serve_session"
             and r.get("event") == "heal"]
    assert len(heals) == 1 and heals[0]["reason"] == "test"
    assert heals[0]["programs"] == n
    assert telemetry.validate_event(heals[0]) == []
    # post-heal serving: zero retraces, bit-exact with pre-heal output
    retr0 = telemetry.compile_stats().get("jax.retraces", 0)
    after_heal = sess.decode(synd)
    assert telemetry.compile_stats().get("jax.retraces", 0) == retr0
    assert np.array_equal(after_heal.corrections, before_heal.corrections)
    assert np.array_equal(after_heal.corrections, _offline(CODE3, synd))


def test_health_probe_heals_on_incident_and_on_device_reset():
    """The probe converts dispatcher incidents and device-reset epoch
    moves into background heals — no request has to fail to trigger
    recovery, and no operator action is involved."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    sess = _session(CODE3)
    sess.warm()
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=64,
                            max_wait_s=0.002, max_dispatch_attempts=3)
    probe = HealthProbe(bat, start=False)  # drive probe_once by hand
    try:
        rng = np.random.default_rng(1)
        # a transient dispatch death: the request re-queues (answered
        # fine), the incident lands in the feed
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch", kind="raise")])
        with plan.active():
            res = bat.submit("hgp_rep3", _synd(CODE3, 3, rng)).result(
                timeout=60)
        assert res.corrections.shape == (3, CODE3.N)
        gen0 = sess.generation
        healed = probe.probe_once()
        assert healed == ["hgp_rep3"]
        assert sess.generation > gen0 and sess.heals >= 1
        # quiescent probe: nothing to do
        assert probe.probe_once() == []
        # a device reset anywhere in the process heals every session
        from qldpc_fault_tolerance_tpu import reset_device_state

        gen1 = sess.generation
        reset_device_state()
        assert probe.probe_once() == ["hgp_rep3"]
        assert sess.generation > gen1
        rep = probe.report()
        assert rep["heals"] == probe.heals >= 2
        # served output after both heals is still bit-exact
        synd = _synd(CODE3, 4, rng)
        out = bat.submit("hgp_rep3", synd).result(timeout=60)
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
    finally:
        probe.stop()
        bat.drain()


def test_health_probe_retries_a_failed_heal():
    """A heal that fails (the device may still be flapping right after
    the restart that triggered it) must NOT consume the signal: the
    session stays owing and the next probe pass retries it."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    sess = _session(CODE3)
    sess.warm()
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    probe = HealthProbe(bat, start=False)
    real_heal = sess.heal
    calls = []

    def flaky_heal(reason="probe"):
        calls.append(reason)
        if len(calls) == 1:
            raise RuntimeError("device still flapping")
        return real_heal(reason=reason)

    sess.heal = flaky_heal
    try:
        from qldpc_fault_tolerance_tpu import reset_device_state

        reset_device_state()
        assert probe.probe_once() == []  # heal attempt failed ...
        assert probe.report()["pending_heals"] == 1  # ... still owing
        assert probe.probe_once() == ["hgp_rep3"]  # retried, healed
        assert probe.report()["pending_heals"] == 0
        assert calls == ["device_reset", "device_reset"]
    finally:
        sess.heal = real_heal
        probe.stop()
        bat.drain()


# ---------------------------------------------------------------------------
# Exactly-once re-dispatch (scheduler level)
# ---------------------------------------------------------------------------
def test_failed_dispatch_requeues_and_answers_every_request():
    """A dispatch that dies after its in-dispatch retries re-queues its
    batch; the next flush answers every request with bit-exact
    corrections — no request dropped, no error surfaced."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002,
                            max_dispatch_attempts=4)
    try:
        rng = np.random.default_rng(2)
        synds = [_synd(CODE3, 3, rng) for _ in range(4)]
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch", kind="raise",
                               count=2)])
        with plan.active():
            futs = [bat.submit("hgp_rep3", s, idem=f"req-{i}")
                    for i, s in enumerate(synds)]
            outs = [f.result(timeout=60) for f in futs]
        for s, o in zip(synds, outs):
            assert np.array_equal(o.corrections, _offline(CODE3, s))
        assert bat.failed == 0 and bat.completed == len(synds)
        assert bat.redispatched > 0
        assert _counter("serve.redispatches") > 0
        assert _counter("serve.errors") == 0
        # the journal drained with the answers
        assert bat.health()["journal_inflight"] == 0
    finally:
        bat.drain()


def test_redispatch_attempts_bounded_then_structured_error():
    """A session that keeps dying exhausts the per-request attempt budget
    and answers a structured error — answered, never dropped, never
    retried forever."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002,
                            max_dispatch_attempts=2)
    try:
        rng = np.random.default_rng(3)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch", kind="raise",
                               count=99)])
        with plan.active():
            fut = bat.submit("hgp_rep3", _synd(CODE3, 2, rng),
                             idem="doomed")
            with pytest.raises(faultinject.InjectedFault):
                fut.result(timeout=60)
        assert bat.failed == 1 and bat.completed == 0
        assert bat.redispatched == 1  # exactly max_dispatch_attempts - 1
        assert bat.health()["journal_inflight"] == 0  # journal drained
    finally:
        bat.drain()


def test_idem_dedupe_replays_answered_and_attaches_inflight():
    """The journal dedupes both duplicate windows: a duplicate of an
    ANSWERED request replays the cached result (no second decode), and a
    duplicate of an IN-FLIGHT request attaches to the pending decode —
    one decode, several answers, all identical."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.05)
    try:
        rng = np.random.default_rng(4)
        synd = _synd(CODE3, 3, rng)
        r1 = bat.submit("hgp_rep3", synd, idem="dup").result(timeout=60)
        batches_after_first = _counter("serve.batches")
        r2 = bat.submit("hgp_rep3", synd, idem="dup").result(timeout=60)
        assert np.array_equal(r1.corrections, r2.corrections)
        assert _counter("serve.batches") == batches_after_first
        assert _counter("serve.dedup.replayed") == 1
        # in-flight attach: stall the dispatch so the duplicate lands
        # while the original is queued/decoding
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch", kind="stall",
                               stall_s=0.3)])
        with plan.active():
            f1 = bat.submit("hgp_rep3", synd, idem="race")
            f2 = bat.submit("hgp_rep3", synd, idem="race")
            a, b = f1.result(timeout=60), f2.result(timeout=60)
        assert np.array_equal(a.corrections, b.corrections)
        assert np.array_equal(a.corrections, _offline(CODE3, synd))
        assert _counter("serve.dedup.attached") == 1
    finally:
        bat.drain()


def test_idem_dedupe_is_scoped_per_tenant():
    """The idem string is wire-controlled: two TENANTS sending the same
    key must each get their own decode — an unscoped journal would hand
    tenant B tenant A's corrections (cross-tenant disclosure, and a
    wrong-shaped answer for a different request)."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    try:
        rng = np.random.default_rng(12)
        sa, sb = _synd(CODE3, 2, rng), _synd(CODE3, 5, rng)
        ra = bat.submit("hgp_rep3", sa, tenant="A",
                        idem="shared-key").result(timeout=60)
        rb = bat.submit("hgp_rep3", sb, tenant="B",
                        idem="shared-key").result(timeout=60)
        assert ra.corrections.shape == (2, CODE3.N)
        assert rb.corrections.shape == (5, CODE3.N)  # NOT A's cached rows
        assert np.array_equal(rb.corrections, _offline(CODE3, sb))
        assert _counter("serve.dedup.replayed") == 0
        # same tenant + session + key DOES replay
        ra2 = bat.submit("hgp_rep3", sa, tenant="A",
                         idem="shared-key").result(timeout=60)
        assert np.array_equal(ra2.corrections, ra.corrections)
        assert _counter("serve.dedup.replayed") == 1
    finally:
        bat.drain()
    # a resubmit of an ANSWERED request replays even after drain: its
    # decode completed, so refusing it would surface a logically-complete
    # request as an error (the reconnect-during-shutdown window)
    ra3 = bat.submit("hgp_rep3", sa, tenant="A",
                     idem="shared-key").result(timeout=60)
    assert np.array_equal(ra3.corrections, ra.corrections)
    with pytest.raises(RuntimeError):  # NEW work is still refused
        bat.submit("hgp_rep3", sa, tenant="A", idem="post-drain-new")


# ---------------------------------------------------------------------------
# Client transport resilience
# ---------------------------------------------------------------------------
def test_client_broken_pipe_is_per_request_transient_error():
    """Satellite: a broken pipe mid-submit surfaces on THAT request's
    future as a transient ConnectionError — the client object survives
    and later submits fail the same controlled way (regression test with
    a torn raw socket)."""
    srv = socket.create_server(("127.0.0.1", 0))
    host, port = srv.getsockname()

    def tear():
        conn, _ = srv.accept()
        conn.close()  # torn immediately: client's socket dies

    t = threading.Thread(target=tear, daemon=True)
    t.start()
    cli = DecodeClient(host, port, timeout=5.0)
    t.join(timeout=5)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            fut = cli.submit("s", np.zeros((1, 4), np.uint8))
            try:
                fut.result(timeout=5)
            except ConnectionError:
                break  # the per-request transient error
            except RuntimeError as exc:  # pragma: no cover - impossible
                pytest.fail(f"non-transient failure: {exc}")
            # the first submit may still have been buffered before the
            # RST arrived; keep going until the dead socket surfaces
        else:
            pytest.fail("dead socket never surfaced as ConnectionError")
        assert resilience.classify_error(ConnectionError()) == "transient"
        # the client is NOT poisoned: another submit returns a future
        # (failed the same controlled way), no exception escapes
        fut2 = cli.submit("s", np.zeros((1, 4), np.uint8))
        with pytest.raises((ConnectionError, RuntimeError)):
            fut2.result(timeout=5)
        # ping after permanent transport death fails IMMEDIATELY too —
        # with no reader alive a buffered send would otherwise block the
        # caller for the full timeout with an orphaned pong future.
        # (ConnectionError from the _dead gate, or the raw OSError if the
        # ping races the reader's death notice — never a blocking wait)
        t_ping = time.monotonic()
        with pytest.raises(OSError):
            cli.ping()
        assert time.monotonic() - t_ping < 2.0
    finally:
        cli.close()
        srv.close()


def test_client_reconnects_and_resubmits_through_conn_drop():
    """conn_drop chaos: the server hard-drops the connection on a frame;
    the reconnect client redials, resubmits with the SAME idempotency
    key, and every logical request is answered exactly once."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(5)
        synds = [_synd(CODE3, 2, rng) for _ in range(6)]
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_conn_rx", kind="conn_drop",
                               after=1)])
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=30.0) as cli:
                futs = [cli.submit("hgp_rep3", s) for s in synds]
                outs = [f.result(timeout=60) for f in futs]
        for s, o in zip(synds, outs):
            assert np.array_equal(o.corrections, _offline(CODE3, s))
        assert _counter("serve.chaos.conn_drops") == 1
        assert _counter("serve.client.reconnects") >= 1
        assert bat.failed == 0
    finally:
        handle.stop(drain=True)


def test_response_drop_replays_from_answered_cache_never_decodes_twice():
    """conn_drop at serve_respond: the decode completed but its response
    died on the wire.  The client's resubmit must be answered from the
    journal's answered-LRU — exactly-once pinned via the dedupe counter
    and the decoded-batch count."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(6)
        synd = _synd(CODE3, 3, rng)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_respond", kind="conn_drop")])
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=30.0) as cli:
                out = cli.submit("hgp_rep3", synd).result(timeout=60)
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
        assert _counter("serve.dedup.replayed") >= 1
        assert bat.completed == 1  # ONE decode answered the logical req
    finally:
        handle.stop(drain=True)


def test_torn_frame_recovery():
    """torn_frame chaos: the server answers with a length header promising
    more bytes than follow, then drops.  The client treats the torn wire
    as a dead connection, redials and resubmits — answered exactly once,
    bit-exact."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(7)
        synd = _synd(CODE3, 2, rng)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_conn_rx", kind="torn_frame")])
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=30.0) as cli:
                out = cli.submit("hgp_rep3", synd).result(timeout=60)
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
        assert _counter("serve.client.reconnects") >= 1
    finally:
        handle.stop(drain=True)


def test_hedged_resubmit_attaches_server_side():
    """A request unanswered past the hedge deadline is resubmitted with
    the same idempotency key; the server attaches the duplicate to the
    in-flight decode — tail latency bounded, work never duplicated."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(8)
        synd = _synd(CODE3, 2, rng)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch", kind="stall",
                               stall_s=0.5)])
        with plan.active():
            with DecodeClient(host, port, hedge_s=0.05,
                              timeout=30.0) as cli:
                out = cli.submit("hgp_rep3", synd).result(timeout=60)
        assert np.array_equal(out.corrections, _offline(CODE3, synd))
        assert _counter("serve.client.hedges") >= 1
        assert (_counter("serve.dedup.attached")
                + _counter("serve.dedup.replayed")) >= 1
        assert bat.completed == 1
    finally:
        handle.stop(drain=True)


def test_server_side_stall_is_async_not_loop_freezing():
    """A stall-kind fault at a server wire site sleeps ASYNC on that one
    connection: a second client's traffic keeps flowing while the first
    connection's frame is stalled — the event loop never blocks."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(11)
        synd = _synd(CODE3, 2, rng)
        # the FIRST frame received server-side stalls 1.5s; frames on the
        # other connection must be served meanwhile
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_conn_rx", kind="stall",
                               stall_s=1.5)])
        with plan.active():
            with DecodeClient(host, port, timeout=30.0) as slow, \
                    DecodeClient(host, port, timeout=30.0) as fast:
                t0 = time.monotonic()
                slow_fut = slow.submit("hgp_rep3", synd)
                resilience.sleep_for(0.05)  # let the stall engage
                fast_res = fast.decode("hgp_rep3", synd)
                fast_dt = time.monotonic() - t0
                slow_res = slow_fut.result(timeout=30)
        assert fast_dt < 1.0, (
            f"second connection waited {fast_dt:.2f}s — the stall froze "
            "the event loop instead of one connection")
        assert np.array_equal(fast_res.corrections,
                              _offline(CODE3, synd))
        assert np.array_equal(slow_res.corrections,
                              _offline(CODE3, synd))
    finally:
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# Drain racing disconnects + dispatch failure (satellite)
# ---------------------------------------------------------------------------
def test_drain_races_client_disconnects_and_dispatch_failure():
    """Satellite: drain() while clients vanish mid-flight AND the dispatch
    is dying.  Drain must still resolve every accepted request (error or
    result), never hang, and the server must come down clean."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=16, max_wait_s=0.2,
                            max_dispatch_attempts=2)
    handle = start_server_thread(bat)
    host, port = handle.address
    rng = np.random.default_rng(9)
    clients = [DecodeClient(host, port, timeout=10.0) for _ in range(2)]
    plan = faultinject.FaultPlan(
        [faultinject.Fault(site="serve_dispatch", kind="raise", count=99)])
    try:
        with plan.active():
            for cli in clients:
                for _ in range(5):
                    cli.submit("hgp_rep3", _synd(CODE3, 2, rng))
            # rip the client sockets out mid-window while drain flushes
            # the queue into a failing dispatch
            killer = threading.Thread(
                target=lambda: [c.close() for c in clients], daemon=True)
            stopper = threading.Thread(
                target=lambda: handle.stop(drain=True, timeout=30),
                daemon=True)
            stopper.start()
            killer.start()
            killer.join(timeout=30)
            stopper.join(timeout=60)
            assert not stopper.is_alive(), "drain hung"
        # every accepted request was resolved one way or the other
        assert bat.completed + bat.failed == 10
        assert bat.health()["stopped"] is True
        assert bat.health()["journal_inflight"] == 0
    finally:
        for cli in clients:
            cli.close()


# ---------------------------------------------------------------------------
# Elastic mesh degrade
# ---------------------------------------------------------------------------
def _mesh_sim(mesh, batch_size=64, seed=7):
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )

    dec_x = BPDecoder(CODE3.hz, np.full(CODE3.N, P), max_iter=10)
    dec_z = BPDecoder(CODE3.hx, np.full(CODE3.N, P), max_iter=10)
    return CodeSimulator_DataError(
        code=CODE3, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[P / 3] * 3, batch_size=batch_size, mesh=mesh,
        seed=seed)


def test_mesh_device_loss_replans_with_exact_counts():
    """ISSUE 14 acceptance (mesh half): a faultinjected device loss
    mid-run completes on the surviving device by replaying the identical
    per-logical-device key streams — counts EXACTLY equal to the
    uninterrupted mesh run, with the mesh_replan degrade emitted for the
    dashboard's ladder_degrade anomaly."""
    key = jax.random.PRNGKey(11)
    # 2048 shots / (64-shot batches x 8 devices) = 4 mesh dispatches, so
    # after=1 kills the run MID-stream (the second dispatch)
    clean = _mesh_sim(shot_mesh()).WordErrorRate(2048, key=key)
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sim = _mesh_sim(shot_mesh())
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="mesh_dispatch",
                               kind="mesh_device_loss", after=1)])
        with plan.active():
            degraded = sim.WordErrorRate(2048, key=key)
    finally:
        telemetry.remove_sink(sink)
    assert degraded == clean  # exact, not just 3-sigma-consistent
    degrades = [r for r in sink.records if r["kind"] == "degrade"]
    assert [r["rung"] for r in degrades] == ["mesh_replan"]
    assert telemetry.validate_event(degrades[0]) == []
    assert _counter("mesh.replans") == 1
    injected = [r for r in sink.records if r["kind"] == "fault_injected"]
    assert injected and injected[0]["fault_kind"] == "mesh_device_loss"
    # the loss PERSISTS on the simulator: later cells go straight to the
    # replay path (no per-cell watchdog deadline re-proving the mesh is
    # dead, no second degrade), and counts stay exact
    assert sim.__dict__.get("_mesh_lost") is True
    again = sim.WordErrorRate(2048, key=key)
    assert again == clean
    assert _counter("mesh.replans") == 1
    assert _counter("resilience.degrades") == 1


def test_mesh_device_loss_inside_sweep_emits_ladder_degrade_anomaly():
    """The replan is visible where operators look: inside a sweep-run
    scope the rung lands as a ladder_degrade anomaly naming the cell —
    the record scripts/sweep_dashboard.py renders with the '!' mark."""
    from qldpc_fault_tolerance_tpu.utils import diagnostics

    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        with diagnostics.sweep_run({"test": "mesh_degrade"}) as run:
            sim = _mesh_sim(shot_mesh())
            plan = faultinject.FaultPlan(
                [faultinject.Fault(site="mesh_dispatch",
                                   kind="mesh_device_loss")])
            with plan.active():
                wer = sim.WordErrorRate(256, key=jax.random.PRNGKey(3))
            run.note_cell({"code": "hgp_rep3", "noise": "data",
                           "type": "single", "p": P}, wer[0], {})
    finally:
        telemetry.remove_sink(sink)
    anomalies = [r for r in sink.records if r["kind"] == "anomaly"
                 and r.get("anomaly") == "ladder_degrade"]
    assert anomalies and "mesh_replan" in anomalies[0]["rungs"]
    assert telemetry.validate_event(anomalies[0]) == []


def test_cell_fused_mesh_degrade_exact_counts():
    """CellFusedDriver mesh fold: a device loss steps the driver's
    mesh_replan rung; the retry re-dispatches the intact carry on the
    replay program and the per-cell counters come out exactly equal to
    the uninterrupted mesh run's."""
    import jax.numpy as jnp

    from qldpc_fault_tolerance_tpu.parallel.shots import CellFusedDriver

    batch = 128

    def stats_fn(keys, lane_cell, active):
        def one(k, cell):
            u = jax.random.uniform(k, (batch,))
            thresh = 0.02 * (1.0 + cell.astype(jnp.float32))
            cnt = (u < thresh).sum().astype(jnp.int32)
            return cnt, jnp.int32(3) + cell
        return jax.vmap(one)(keys, lane_cell)

    def run(plan_faults):
        drv = CellFusedDriver(stats_fn, n_cells=3, batch_size=batch,
                              k_inner=2, min_init=99,
                              mesh=shot_mesh(jax.devices()[:2]))
        key = jax.random.PRNGKey(5)
        if plan_faults:
            with faultinject.FaultPlan(plan_faults).active():
                carry, n_run = drv.run_plan(key, 4)
        else:
            carry, n_run = drv.run_plan(key, 4)
        return drv, jax.device_get(carry), n_run

    _, clean, n_clean = run([])
    telemetry.enable()
    drv, degraded, n_deg = run(
        [faultinject.Fault(site="megabatch_dispatch",
                           kind="mesh_device_loss", after=1)])
    assert n_deg == n_clean
    assert drv.mesh_degraded is True
    for a, b in zip(clean, degraded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert _counter("mesh.replans") == 1
    assert _counter("resilience.degrades") == 1


# ---------------------------------------------------------------------------
# Postmortems
# ---------------------------------------------------------------------------
def test_postmortem_atomic_and_names_affected_requests(tmp_path):
    """Satellite + invariant: postmortem dumps are atomic (tmp+rename —
    no torn JSONL, no stray .tmp) and name exactly the requests that were
    in flight with the dead dispatch."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    pm = tmp_path / "pm"
    tracing.configure(postmortem_dir=str(pm))
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.002,
                            max_dispatch_attempts=1)
    try:
        rng = np.random.default_rng(10)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_dispatch",
                               kind="deterministic")])
        with plan.active():
            fut = bat.submit("hgp_rep3", _synd(CODE3, 2, rng),
                             request_id="pm-req-1", idem="pm-1")
            with pytest.raises(faultinject.InjectedDeterministicFault):
                fut.result(timeout=60)
        files = glob.glob(str(pm / "postmortem-*serve_dispatch_failed*"))
        assert len(files) >= 1
        assert not glob.glob(str(pm / "*.tmp"))  # atomic: no torn temp
        with open(files[0], encoding="utf-8") as fh:
            lines = [json.loads(ln) for ln in fh]  # every line parses
        header = lines[0]
        assert header["kind"] == "postmortem"
        assert header["request_ids"] == ["pm-req-1"]
        # the ring carried the injected fault AND the accepted request
        kinds = {r.get("kind") for r in lines[1:]}
        assert {"request", "fault_injected", "failure"} <= kinds
    finally:
        bat.drain()


# ---------------------------------------------------------------------------
# The live-server chaos schedules
# ---------------------------------------------------------------------------
def _storm(handle, codes, n_per_tenant, tenants=2, seed=0, hedge_s=None):
    """Closed-loop request storm with reconnect clients; returns
    [(code_name, syndromes, corrections)] across all tenants (raises on
    any unanswered/failed request)."""
    host, port = handle.address
    names = sorted(codes)
    results, errors = [], []

    def worker(idx):
        try:
            rng = np.random.default_rng(1000 * seed + idx)
            with DecodeClient(host, port, tenant=f"t{idx}", reconnect=True,
                              hedge_s=hedge_s, timeout=60.0) as cli:
                pending = []
                for i in range(n_per_tenant):
                    name = names[(i + idx) % len(names)]
                    synd = _synd(codes[name], int(rng.integers(1, 8)), rng)
                    pending.append((name, synd,
                                    cli.submit(name, synd)))
                for name, synd, fut in pending:
                    res = fut.result(timeout=120)
                    results.append((name, synd, res.corrections))
        except Exception as exc:  # noqa: BLE001 — surfaced by the test
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors, errors
    return results


def _healthz_until_200(ops_handle, timeout=30.0) -> dict:
    host, port = ops_handle.address
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=5).read())
        except urllib.error.HTTPError as exc:
            last = exc.code
        except OSError:
            pass
        resilience.sleep_for(0.05)
    pytest.fail(f"/healthz never returned 200 (last status {last})")


def test_chaos_acceptance_combined_schedule(tmp_path):
    """ISSUE 14 acceptance: a seeded schedule combining device_restart +
    conn_drop + stalled_dispatch (+ session_evict for good measure)
    against a LIVE server with the HealthProbe attached.  Invariants:
    every accepted request answered exactly once with corrections
    bit-exact vs offline decode_batch, /healthz back to 200 with zero
    operator action, and the postmortem names every in-flight request of
    the dead dispatch."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    tracing.configure(postmortem_dir=str(tmp_path / "pm"))
    codes = {"hgp_rep3": CODE3, "hgp_rep4": CODE4}
    sessions = {n: _session(c, name=n) for n, c in codes.items()}
    for s in sessions.values():
        s.warm()
    bat = ContinuousBatcher(sessions, max_batch_shots=32,
                            max_wait_s=0.002, max_dispatch_attempts=4)
    probe = HealthProbe(bat, interval_s=0.05)
    handle = start_server_thread(bat)
    ops = start_ops_thread(batcher=bat, probe=probe)
    try:
        # `after`s chosen so every fault fires within the storm's minimum
        # hit counts (>= 4 dispatches incl. retry re-hits, >= 24 frames
        # received, >= 24 responses written)
        plan = faultinject.FaultPlan([
            # count=2 exhausts BOTH in-dispatch retry attempts, so the
            # batch takes the re-queue path and the dispatch death ships
            # a postmortem naming its in-flight requests
            faultinject.Fault(site="serve_dispatch", kind="device_restart",
                              after=1, count=2),
            faultinject.Fault(site="serve_dispatch", kind="stall",
                              after=3, stall_s=0.2),  # stalled_dispatch
            faultinject.Fault(site="serve_dispatch", kind="session_evict",
                              after=4),
            faultinject.Fault(site="serve_conn_rx", kind="conn_drop",
                              after=3),
            faultinject.Fault(site="serve_respond", kind="conn_drop",
                              after=6),
        ], seed=14)
        with plan.active():
            results = _storm(handle, codes, n_per_tenant=12, tenants=2,
                             seed=14)
        # --- every accepted request answered exactly once, bit-exact ---
        assert len(results) == 24
        for name in codes:
            rows = [(s, c) for n, s, c in results if n == name]
            synd = np.concatenate([s for s, _ in rows])
            served = np.concatenate([c for _, c in rows])
            assert np.array_equal(served, _offline(codes[name], synd)), \
                name
        assert bat.failed == 0
        snap = telemetry.snapshot()

        def cnt(n):
            return snap.get(n, {}).get("value", 0)

        assert cnt("faultinject.injected") >= 5  # the schedule ran
        # exactly-once: the server accepted each of the 24 logical
        # requests once (a broken dedupe would re-accept a resubmit and
        # push serve.requests past 24) and completed each exactly once
        assert cnt("serve.requests") == 24
        assert bat.completed == 24
        assert bat.health()["journal_inflight"] == 0
        # --- /healthz returns to 200 with zero operator action ---------
        hz = _healthz_until_200(ops)
        assert hz["ok"] is True
        assert hz["probe"]["heals"] >= 1  # the self-healing loop fired
        # --- artifacts name the affected requests ----------------------
        pm_files = glob.glob(str(tmp_path / "pm" / "postmortem-*"))
        assert pm_files  # the device_restart dispatch death shipped one
        named = set()
        for path in pm_files:
            with open(path, encoding="utf-8") as fh:
                header = json.loads(fh.readline())
            named.update(header.get("request_ids") or [])
        assert named  # specific in-flight requests are named
    finally:
        probe.stop()
        ops.stop()
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# Satellite: bench_compare gates the journal A/B + chaos rounds
# ---------------------------------------------------------------------------
def test_bench_compare_gates_journal_ab_and_chaos_rounds(tmp_path):
    """The idempotency journal's steady-state cost and the chaos smoke's
    recovery/throughput join the regression ledger: the journaled arm's
    throughput regresses DOWN, the chaos round's recovery headline (unit
    's') regresses UP, its under-fault QPS regresses DOWN."""
    import importlib

    scripts = os.path.join(REPO_ROOT, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_compare = importlib.import_module("bench_compare")

    def serve_round(n, journaled_sps):
        obj = {"schema": 2, "round": n,
               "result": {"metric": "decode-service sustained QPS",
                          "value": 500.0, "unit": "req/s",
                          "journal_ab": {
                              "journaled_shots_per_s": journaled_sps,
                              "overhead_pct": 1.0,
                              "overhead_le_2pct": True}}}
        p = tmp_path / f"BENCH_J_r{n:02d}.json"
        p.write_text(json.dumps(obj))
        return str(p)

    bad = [serve_round(1, 8000.0), serve_round(2, 4000.0)]
    assert bench_compare.main(bad + ["--gate", "--tolerance", "10"]) == 1
    ok = [serve_round(3, 8000.0), serve_round(4, 8100.0)]
    assert bench_compare.main(ok + ["--gate", "--tolerance", "10"]) == 0

    def chaos_round(n, recovery_s, qps):
        obj = {"schema": 2, "round": n,
               "result": {"metric": "chaos smoke recovery",
                          "value": recovery_s, "unit": "s",
                          "chaos_qps": qps}}
        p = tmp_path / f"BENCH_C_r{n:02d}.json"
        p.write_text(json.dumps(obj))
        return str(p)

    slow = [chaos_round(1, 0.5, 20.0), chaos_round(2, 5.0, 20.0)]
    assert bench_compare.main(slow + ["--gate", "--tolerance", "10"]) == 1
    dropped = [chaos_round(3, 0.5, 20.0), chaos_round(4, 0.5, 5.0)]
    assert bench_compare.main(dropped
                              + ["--gate", "--tolerance", "10"]) == 1
    fine = [chaos_round(5, 0.5, 20.0), chaos_round(6, 0.45, 21.0)]
    assert bench_compare.main(fine + ["--gate", "--tolerance", "10"]) == 0


@pytest.mark.parametrize("seed", [1, 2])
def test_seeded_random_schedule_invariants(seed):
    """Randomized chaos schedules drawn from a seeded menu (bounded so
    recovery is always possible: per-site raise counts stay under the
    re-dispatch budget).  Every schedule must preserve the serving
    invariants — the same assertions, whatever the draw."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    rng = np.random.default_rng(seed)
    menu = [
        ("serve_dispatch", "raise"),
        ("serve_dispatch", "stall"),
        ("serve_dispatch", "device_restart"),
        ("serve_dispatch", "session_evict"),
        ("serve_conn_rx", "conn_drop"),
        ("serve_conn_rx", "torn_frame"),
        ("serve_respond", "conn_drop"),
    ]
    faults = []
    for _ in range(int(rng.integers(2, 5))):
        site, kind = menu[int(rng.integers(0, len(menu)))]
        faults.append(faultinject.Fault(
            site=site, kind=kind, after=int(rng.integers(0, 6)),
            stall_s=0.1))
    plan = faultinject.FaultPlan(faults, seed=seed)
    codes = {"hgp_rep3": CODE3}
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=32, max_wait_s=0.002,
                            max_dispatch_attempts=6)
    probe = HealthProbe(bat, interval_s=0.05)
    handle = start_server_thread(bat)
    try:
        with plan.active():
            results = _storm(handle, codes, n_per_tenant=10, tenants=2,
                             seed=seed)
        assert len(results) == 20
        synd = np.concatenate([s for _, s, _ in results])
        served = np.concatenate([c for _, _, c in results])
        assert np.array_equal(served, _offline(CODE3, synd))
        assert bat.failed == 0
        assert bat.health()["journal_inflight"] == 0
    finally:
        probe.stop()
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# Streaming decode under chaos (ISSUE 16)
# ---------------------------------------------------------------------------
ST_CLS = ST_BP_Decoder_Class(2, "minimum_sum", 0.625)
ST_W = 3
ST_PARAMS = {"h": CODE3.hx, "p_data": P, "p_syndrome": True,
             "num_rep": ST_W}


def _st_stream_session(lanes=4):
    return DecodeSession("st3", decoder_class=ST_CLS, params=ST_PARAMS,
                         buckets=(lanes, 4 * lanes))


def test_stream_kill_mid_window_resumes_from_committed_exactly_once():
    """stream_kill chaos: the connection dies mid-window (chunk read,
    nothing committed).  The reconnecting client retries the SAME seq;
    the commit ledger lands every window exactly once — the resumed
    stream's corrections are bit-exact vs the offline windowed decode,
    the commit counter equals the window count (no double-commit), and
    the watermark agrees."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    lanes, T = 4, 6
    sess = _st_stream_session(lanes)
    bat = ContinuousBatcher({"st3": sess}, max_batch_shots=64,
                            max_wait_s=0.002)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="serve_stream_step", kind="stream_kill",
                               after=2)])
        rng = np.random.default_rng(21)
        offline = ST_CLS.GetDecoder(ST_PARAMS)
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=30.0) as cli:
                ack = cli.stream_open("st3", lanes=lanes)
                sid, width = ack["stream"], ack["width"]
                for seq in range(1, T + 1):
                    chunk = (rng.random((lanes, width)) < P)\
                        .astype(np.uint8)
                    # stream_step retries the same seq through the
                    # reconnect; a killed attempt was never committed, a
                    # committed-but-unanswered one replays from cache
                    res = cli.stream_step(sid, seq, chunk)
                    assert res.get("ok"), res
                    assert res["committed"] == seq
                    ref = offline.decode_batch(
                        chunk.reshape(lanes, ST_W, -1))
                    assert np.array_equal(
                        np.asarray(res["corrections"], np.uint8),
                        np.asarray(ref, np.uint8)), f"seq {seq}"
                wm = cli.stream_commit(sid)
                assert wm["committed"] == T
                assert wm["committed_cycles"] == T * ST_W
                cli.stream_commit(sid, close=True)
        assert _counter("faultinject.stream_kill") >= 1
        assert _counter("serve.client.reconnects") >= 1
        # exactly-once: every window committed once, none twice
        assert _counter("stream.commits") == T
        assert _counter("stream.cycles") == T * ST_W
        assert bat.failed == 0
    finally:
        handle.stop(drain=True)


def test_slo_burn_sheds_whole_stream_with_structured_error():
    """The streaming SLO rung: burn-rate pressure sheds the WHOLE stream
    — the chunk gets a structured shed response, a ``stream_shed`` event
    fires (schema-valid), the stream's state is dropped, and subsequent
    chunks answer "unknown stream" instead of half-serving a backlog the
    tenant's budget can't pay for."""
    resilience.set_default_policy(TRIVIAL_POLICY)
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    lanes = 4
    sess = _st_stream_session(lanes)
    slo = SLOEngine(SLOPolicy(latency_target_s=0.01, min_requests=5,
                              eval_interval_s=0.0))
    # pre-burn the default tenant far past the shed threshold: every
    # observed request blew the 10ms target (timestamps pinned near the
    # server's monotonic clock so the window is live at admission time)
    now0 = time.monotonic()
    for i in range(10):
        slo.observe_request("default", 0.5, ok=True,
                            now=now0 + i * 0.001)
    slo.evaluate(now=now0 + 0.1)
    bat = ContinuousBatcher({"st3": sess}, max_batch_shots=64,
                            max_wait_s=0.002, slo=slo)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        rng = np.random.default_rng(23)
        with DecodeClient(host, port, reconnect=True,
                          timeout=30.0) as cli:
            ack = cli.stream_open("st3", lanes=lanes)
            sid, width = ack["stream"], ack["width"]
            chunk = (rng.random((lanes, width)) < P).astype(np.uint8)
            res = cli.stream_step(sid, 1, chunk)
            assert res.get("shed") and res.get("stream_shed"), res
            assert not res.get("ok")
            assert res["committed"] == 0
            # the stream is gone, not half-alive
            gone = cli.stream_step(sid, 2, chunk)
            assert gone.get("stream_unknown"), gone
        assert _counter("stream.shed") == 1
        assert _counter("stream.commits") == 0
        shed_events = [r for r in sink.records
                       if r.get("kind") == "stream_shed"]
        assert len(shed_events) == 1
        assert telemetry.validate_event(shed_events[0]) == []
        assert shed_events[0]["stream"] == sid
        assert shed_events[0]["signal"] == "shed"
    finally:
        handle.stop(drain=True)
        telemetry.remove_sink(sink)


# ---------------------------------------------------------------------------
# Multi-host serving fabric under chaos (ISSUE 18)
# ---------------------------------------------------------------------------
def _fam(sess) -> str:
    return f"fam-{family_digest(sess.family)}"


def _fleet_storm(fleet, codes, n_per_tenant, tenants=2, seed=0):
    """The fleet variant of ``_storm``: clients talk to the ROUTER, and
    each collected result ticks the fleet's chaos site — a seeded
    ``host_kill`` plan therefore fires mid-storm, with the remaining
    requests in flight."""
    host, port = fleet.address
    names = sorted(codes)
    results, errors = [], []

    def worker(idx):
        try:
            rng = np.random.default_rng(1000 * seed + idx)
            with DecodeClient(host, port, tenant=f"t{idx}", reconnect=True,
                              timeout=60.0) as cli:
                pending = []
                for i in range(n_per_tenant):
                    name = names[(i + idx) % len(names)]
                    synd = _synd(codes[name], int(rng.integers(1, 8)), rng)
                    pending.append((name, synd, cli.submit(name, synd)))
                for name, synd, fut in pending:
                    res = fut.result(timeout=120)
                    results.append((name, synd, res.corrections))
                    fleet.chaos_tick()
        except Exception as exc:  # noqa: BLE001 — surfaced by the test
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    return results


def _wait_for_handoff(router, fam, timeout=30.0):
    deadline = time.monotonic() + timeout
    while fam not in router.handoff_report():
        assert time.monotonic() < deadline, \
            f"no handoff for {fam} within {timeout}s"
        resilience.sleep_for(0.02)


def test_fleet_host_kill_mid_storm_exactly_once_via_deadman():
    """ISSUE 18 acceptance: a seeded ``host_kill`` mid-storm against a
    2-host in-process fleet.  Every accepted request — batch AND stream —
    is answered exactly once, bit-exact vs the offline decode, and the
    handoff is driven end to end by the PR 17 gateway deadman: nothing in
    this test fails a host over manually."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    codes = {"hgp_rep3": CODE3, "hgp_rep4": CODE4}

    def factory():
        return {"hgp_rep3": _session(CODE3, name="hgp_rep3"),
                "hgp_rep4": _session(CODE4, name="hgp_rep4",
                                     buckets=(8, 32, 64)),
                "st3": _st_stream_session(4)}

    fleet = LocalFleet(factory, n_hosts=2)
    try:
        st_fam = _fam(fleet.sessions["h0"]["st3"])
        b3_fam = _fam(fleet.sessions["h0"]["hgp_rep3"])
        placement = fleet.router.placement()
        victim = placement[st_fam]["owner"]
        survivor = placement[st_fam]["successor"]
        # the bucket configs above deliberately co-locate the stream and
        # the rep3 batch family on ONE host, so the kill disrupts both
        # planes; a family-digest change that splits them must fail HERE,
        # loudly, instead of silently weakening the schedule
        assert placement[b3_fam]["owner"] == victim, placement
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="fleet_host_tick", kind="host_kill",
                               after=5, target=st_fam)], seed=18)
        host, port = fleet.address
        offline_st = ST_CLS.GetDecoder(ST_PARAMS)
        rng = np.random.default_rng(18)
        with DecodeClient(host, port, reconnect=True,
                          timeout=60.0) as st_cli:
            ack = st_cli.stream_open("st3", lanes=4)
            assert ack.get("ok"), ack
            sid, width = ack["stream"], ack["width"]
            chunks = [(rng.random((4, width)) < P).astype(np.uint8)
                      for _ in range(6)]

            def step(seq):
                res = st_cli.stream_step(sid, seq, chunks[seq - 1])
                assert res.get("ok"), res
                assert res["committed"] == seq
                ref = offline_st.decode_batch(
                    chunks[seq - 1].reshape(4, ST_W, -1))
                assert np.array_equal(
                    np.asarray(res["corrections"], np.uint8),
                    np.asarray(ref, np.uint8)), f"seq {seq}"

            # windows 1..3 commit on the original owner (and replicate)
            for seq in (1, 2, 3):
                step(seq)
            with plan.active():
                results = _fleet_storm(fleet, codes, n_per_tenant=8,
                                       tenants=2, seed=18)
                # the stream rides the SAME handoff: the rebuilt ledger on
                # the successor continues from the replicated watermark,
                # windows 4..6 commit exactly-once, still bit-exact
                for seq in (4, 5, 6):
                    step(seq)
            wm = st_cli.stream_commit(sid)
            assert wm["committed"] == 6
            st_cli.stream_commit(sid, close=True)
        # --- the handoff was deadman-driven and complete ---------------
        assert _counter("serve.host_kills") == 1
        assert _counter("faultinject.host_kill") == 1
        assert f"host_down:{victim}" in fleet.gateway.alerts.firing()
        assert fleet.router.down == {victim}
        place2 = fleet.router.placement()
        assert place2[st_fam]["owner"] == survivor
        assert place2[b3_fam]["owner"] == survivor
        assert place2[st_fam]["epoch"] == 2
        report = fleet.router.handoff_report()
        assert report[st_fam]["reason"] == f"host_down:{victim}"
        assert _counter("router.handoffs") >= 2  # both of the victim's fams
        assert _counter("router.handoff_drops") == 0
        # --- every batch request answered exactly once, bit-exact ------
        assert len(results) == 16
        for name, code in codes.items():
            rows = [(s, c) for n, s, c in results if n == name]
            synd = np.concatenate([s for s, _ in rows])
            served = np.concatenate([c for _, c in rows])
            assert np.array_equal(served, _offline(code, synd)), name
        # --- the stream committed each window exactly once, fleet-wide --
        assert _counter("stream.commits") == 6
    finally:
        fleet.stop()


def test_fleet_journal_lag_handoff_blocks_on_watermark_catch_up():
    """``journal_lag`` chaos: every replication PUSH fails while the lag
    lasts (the eager fetch still drains the dying host's journal into the
    router's buffer).  The handoff must BLOCK on the watermark catch-up —
    the successor owns the family only after every answered entry landed —
    so a post-handoff duplicate of a pre-kill request replays from the
    imported journal instead of re-decoding."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    fleet = LocalFleet(lambda: {"hgp_rep3": _session(CODE3)}, n_hosts=2)
    try:
        fam = _fam(fleet.sessions["h0"]["hgp_rep3"])
        victim = fleet.router.placement()[fam]["owner"]
        host, port = fleet.address
        rng = np.random.default_rng(19)
        answered = []

        def ask(cli):
            synd = _synd(CODE3, int(rng.integers(1, 8)), rng)
            res = cli.submit("hgp_rep3", synd).result(timeout=120)
            answered.append((synd, res.corrections))

        with DecodeClient(host, port, reconnect=True,
                          timeout=60.0) as cli:
            for _ in range(6):  # replicated at the steady-state cadence
                ask(cli)
            plan = faultinject.FaultPlan([
                faultinject.Fault(site="router_replicate",
                                  kind="journal_lag", after=0, count=150),
                faultinject.Fault(site="fleet_host_tick",
                                  kind="host_kill", after=0, target=fam),
            ], seed=19)
            with plan.active():
                for _ in range(4):  # answered under the lag: fetched, not
                    ask(cli)        # yet pushed
                resilience.sleep_for(0.1)  # >= a few fetch ticks
                fleet.chaos_tick()  # host_kill -> deadman -> handoff
                _wait_for_handoff(fleet.router, fam)
            # a fresh request routes to the new owner, bit-exact
            ask(cli)
        assert _counter("faultinject.journal_lag") >= 1
        assert _counter("router.replication_errors") >= 1  # pushes failed
        assert _counter("router.handoff_drops") == 0       # none dropped
        report = fleet.router.handoff_report()
        assert report[fam]["epoch"] == 2
        new_owner = fleet.router.placement()[fam]["owner"]
        assert new_owner != victim
        # the successor's journal holds EVERY pre-kill answered key: the
        # gate only opened once the flush loop pushed through the lag
        snap = fleet.batchers[new_owner].export_journal(0)
        assert len(snap["entries"]) >= 10
        for synd, corrections in answered:
            assert np.array_equal(corrections, _offline(CODE3, synd))
        # exactly-once across the handoff: a duplicate of a pre-kill idem
        # key REPLAYS the imported answer (no second decode)
        entry = snap["entries"][0]
        tenant, sess_name, idem = entry["key"]
        width = fleet.sessions[new_owner]["hgp_rep3"].syndrome_width
        before = _counter("serve.dedup.replayed")
        fut = fleet.batchers[new_owner].submit(
            sess_name, np.zeros((1, width), np.uint8), tenant=tenant,
            idem=idem)
        replay = fut.result(timeout=60)
        assert np.array_equal(replay.corrections,
                              np.asarray(entry["corrections"], np.uint8))
        assert _counter("serve.dedup.replayed") == before + 1
    finally:
        fleet.stop()


def test_fleet_router_partition_fence_refuses_and_reforwards():
    """``router_partition`` chaos: one frame forwards with a deliberately
    stale epoch, as a partitioned router's would.  The owner's fence must
    refuse it (``route_stale``) — never dispatch — and the router's
    re-forward path must answer the request anyway, bit-exact, without
    tripping a spurious handoff."""
    resilience.set_default_policy(FAST_POLICY)
    telemetry.enable()
    fleet = LocalFleet(lambda: {"hgp_rep3": _session(CODE3)}, n_hosts=2)
    try:
        host, port = fleet.address
        rng = np.random.default_rng(20)
        plan = faultinject.FaultPlan(
            [faultinject.Fault(site="router_route",
                               kind="router_partition", after=2, count=1)],
            seed=20)
        with plan.active():
            with DecodeClient(host, port, reconnect=True,
                              timeout=60.0) as cli:
                for _ in range(6):
                    synd = _synd(CODE3, int(rng.integers(1, 8)), rng)
                    res = cli.submit("hgp_rep3", synd).result(timeout=120)
                    assert np.array_equal(res.corrections,
                                          _offline(CODE3, synd))
        assert _counter("router.partition_injected") == 1
        assert _counter("serve.route_stale") >= 1     # the fence refused
        assert _counter("router.stale_reforwards") >= 1
        assert _counter("router.handoffs") == 0       # fence, not failover
    finally:
        fleet.stop()


def test_bench_compare_gates_fleet_round(tmp_path):
    """The fleet storm bench joins the regression ledger: under-chaos
    req/s regresses DOWN, the handoff wall clock (p99, ms) regresses UP;
    rounds that lack the keys gate unchanged."""
    import importlib

    scripts = os.path.join(REPO_ROOT, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    bench_compare = importlib.import_module("bench_compare")

    def fleet_round(n, rps, p99):
        obj = {"schema": 2, "round": n,
               "result": {"metric": "fleet storm sustained req/s",
                          "value": rps, "unit": "req/s",
                          "fleet": {"req_per_s": rps,
                                    "handoff_p99_ms": p99,
                                    "handoffs": 1}}}
        p = tmp_path / f"BENCH_F_r{n:02d}.json"
        p.write_text(json.dumps(obj))
        return str(p)

    dropped = [fleet_round(1, 200.0, 80.0), fleet_round(2, 100.0, 80.0)]
    assert bench_compare.main(dropped
                              + ["--gate", "--tolerance", "10"]) == 1
    lagged = [fleet_round(3, 200.0, 80.0), fleet_round(4, 200.0, 300.0)]
    assert bench_compare.main(lagged
                              + ["--gate", "--tolerance", "10"]) == 1
    fine = [fleet_round(5, 200.0, 80.0), fleet_round(6, 210.0, 70.0)]
    assert bench_compare.main(fine + ["--gate", "--tolerance", "10"]) == 0
