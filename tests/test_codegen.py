"""Tests for random biregular code generation and girth optimization."""
import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import (
    GetClassicalCodeParams,
    QuantumExpanderFromCheckMat,
    improve_girth,
    min_cycle_edges,
    random_biregular_tanner,
    tanner_girth,
)


def test_biregular_degrees():
    H = random_biregular_tanner(5, 4, 3, rng=0)
    assert H.shape == (15, 20)
    assert (H.sum(1) == 4).all()
    assert (H.sum(0) == 3).all()
    assert H.max() == 1  # simple graph


def test_girth_known_graphs():
    # 4-cycle: two checks sharing two bits
    H = np.array([[1, 1], [1, 1]], dtype=np.uint8)
    assert tanner_girth(H) == 4
    # tree: no cycle
    H = np.array([[1, 1, 0], [0, 0, 1]], dtype=np.uint8)
    assert tanner_girth(H) >= 1e6
    # 6-cycle: 3 checks, 3 bits in a ring
    H = np.array([[1, 1, 0], [0, 1, 1], [1, 0, 1]], dtype=np.uint8)
    assert tanner_girth(H) == 6
    g, edges = min_cycle_edges(H)
    assert g == 6 and len(edges) == 6  # every edge on the hexagon


def test_improve_girth_raises_girth():
    rng = np.random.default_rng(42)
    H = random_biregular_tanner(5, 4, 3, rng=rng)
    g0 = tanner_girth(H)
    H2, ok = improve_girth(H, target_girth=6, max_iter=4000, rng=rng)
    assert ok
    assert tanner_girth(H2) >= 6 >= g0
    # degree sequence invariant
    assert (H2.sum(1) == 4).all() and (H2.sum(0) == 3).all()


def test_classical_code_params():
    # [7,4,3] Hamming code
    H = np.array([
        [1, 0, 1, 0, 1, 0, 1],
        [0, 1, 1, 0, 0, 1, 1],
        [0, 0, 0, 1, 1, 1, 1],
    ], dtype=np.uint8)
    n, k, d, lam2 = GetClassicalCodeParams(H)
    assert (n, k, d) == (7, 4, 3)
    assert lam2 > 0


def test_quantum_expander_construction():
    rng = np.random.default_rng(7)
    H = random_biregular_tanner(3, 4, 3, rng=rng)
    H, _ = improve_girth(H, target_girth=6, max_iter=3000, rng=rng)
    code = QuantumExpanderFromCheckMat(H, compute_distance=False)
    m, n = H.shape
    assert code.N == n * n + m * m
    # CSS validity: hx hz^T = 0
    assert not (code.hx @ code.hz.T % 2).any()
    assert code.K == code.lx.shape[0] == code.lz.shape[0]
