"""Tests for observability and sweep checkpointing."""
import os

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.utils import (
    SweepCheckpoint,
    reset_timings,
    stage_timer,
    timings,
)


def test_stage_timer_accumulates():
    reset_timings()
    with stage_timer("unit-test-stage"):
        pass
    with stage_timer("unit-test-stage"):
        pass
    t = timings()["unit-test-stage"]
    assert t["count"] == 2
    assert t["total_s"] >= 0
    reset_timings()


def test_sweep_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt.jsonl")
    ck = SweepCheckpoint(path)
    key = {"code": "x", "noise": "data", "p": 0.01, "cycles": 3, "samples": 10}
    assert ck.get(key) is None
    ck.put(key, {"wer": 0.125})
    assert ck.get(key) == {"wer": 0.125}
    # reload from disk
    ck2 = SweepCheckpoint(path)
    assert len(ck2) == 1
    assert ck2.get(dict(key)) == {"wer": 0.125}
    # float keys are canonicalized
    key_float = dict(key, p=0.010000000000001)
    assert ck2.get(key_float) == {"wer": 0.125}


def test_code_family_resumes_from_checkpoint(tmp_path):
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class, BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    code = hgp(rep_code(3), rep_code(3))
    fam = CodeFamily(
        [code],
        decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(3, "minimum_sum", 0.625, "osd_e", 2),
        batch_size=64, seed=0,
    )
    path = str(tmp_path / "sweep.jsonl")
    ck = SweepCheckpoint(path)
    wer1 = fam.EvalWER("data", "Total", [0.02, 0.05], 128, if_plot=False,
                       checkpoint=ck)
    assert len(ck) == 2
    # rerun with a poisoned cell value: resumed sweep must read it back
    # verbatim (proving the cells were skipped, not recomputed)
    ck2 = SweepCheckpoint(path)
    key = {"code": code.name or f"code0_N{code.N}K{code.K}",
           "noise": "data", "type": "Total", "p": 0.02, "cycles": 1,
           "samples": 128}
    ck2.put(key, {"wer": 0.424242})
    wer2 = fam.EvalWER("data", "Total", [0.02, 0.05], 128, if_plot=False,
                       checkpoint=SweepCheckpoint(path))
    assert wer2[0, 0] == 0.424242
    assert wer2[0, 1] == wer1[0, 1]


def test_engine_stage_timings_populate():
    """"What fraction is OSD" must stay answerable after ISSUE 13 moved
    BPOSD fully on device: a device-BPOSD sweep attributes its time
    through the profiling waterfall (heartbeat event: dispatch/host_sync
    decomposition — OSD now lives inside the dispatch) and the demoted
    host-oracle path still records its ``osd_host`` stage timer."""
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
    from qldpc_fault_tolerance_tpu.utils import telemetry
    from qldpc_fault_tolerance_tpu.utils.observability import (
        reset_timings,
        timings,
    )

    reset_timings()
    code = hgp(rep_code(3), rep_code(3))
    p = 0.08  # high enough that some shots fail BP and reach OSD
    dec_x = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=4)
    dec_z = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=4)
    assert not dec_x.needs_host_postprocess  # device OSD default
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], batch_size=64, seed=0,
    )
    telemetry.reset()
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sim.WordErrorRate(256)
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    hb = [r for r in sink.records if r["kind"] == "heartbeat"]
    assert hb and "waterfall" in hb[0]
    wf = hb[0]["waterfall"]
    assert wf["n_dispatches"] >= 1 and "host_sync_s" in wf["stages"]
    # the demoted host-oracle path still carries its own stage timer
    host = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=2,
                         device_osd=False)
    rng = np.random.default_rng(0)
    errs = (rng.random((32, code.N)) < 0.2).astype(np.uint8)
    host.decode_batch((errs @ code.hx.T % 2).astype(np.uint8))
    assert "osd_host" in timings()
