"""Tests for observability and sweep checkpointing."""
import os

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.utils import (
    SweepCheckpoint,
    reset_timings,
    stage_timer,
    timings,
)


def test_stage_timer_accumulates():
    reset_timings()
    with stage_timer("unit-test-stage"):
        pass
    with stage_timer("unit-test-stage"):
        pass
    t = timings()["unit-test-stage"]
    assert t["count"] == 2
    assert t["total_s"] >= 0
    reset_timings()


def test_sweep_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt.jsonl")
    ck = SweepCheckpoint(path)
    key = {"code": "x", "noise": "data", "p": 0.01, "cycles": 3, "samples": 10}
    assert ck.get(key) is None
    ck.put(key, {"wer": 0.125})
    assert ck.get(key) == {"wer": 0.125}
    # reload from disk
    ck2 = SweepCheckpoint(path)
    assert len(ck2) == 1
    assert ck2.get(dict(key)) == {"wer": 0.125}
    # float keys are canonicalized
    key_float = dict(key, p=0.010000000000001)
    assert ck2.get(key_float) == {"wer": 0.125}


def test_code_family_resumes_from_checkpoint(tmp_path):
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class, BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    code = hgp(rep_code(3), rep_code(3))
    fam = CodeFamily(
        [code],
        decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(3, "minimum_sum", 0.625, "osd_e", 2),
        batch_size=64, seed=0,
    )
    path = str(tmp_path / "sweep.jsonl")
    ck = SweepCheckpoint(path)
    wer1 = fam.EvalWER("data", "Total", [0.02, 0.05], 128, if_plot=False,
                       checkpoint=ck)
    assert len(ck) == 2
    # rerun with a poisoned cell value: resumed sweep must read it back
    # verbatim (proving the cells were skipped, not recomputed)
    ck2 = SweepCheckpoint(path)
    key = {"code": code.name or f"code0_N{code.N}K{code.K}",
           "noise": "data", "type": "Total", "p": 0.02, "cycles": 1,
           "samples": 128}
    ck2.put(key, {"wer": 0.424242})
    wer2 = fam.EvalWER("data", "Total", [0.02, 0.05], 128, if_plot=False,
                       checkpoint=SweepCheckpoint(path))
    assert wer2[0, 0] == 0.424242
    assert wer2[0, 1] == wer1[0, 1]


def test_engine_stage_timings_populate():
    """After a BPOSD sweep, timings() must show the per-stage breakdown
    (launch / finish / osd_host) so "what fraction is OSD" is answerable
    without external profiling."""
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError
    from qldpc_fault_tolerance_tpu.utils.observability import (
        reset_timings,
        timings,
    )

    reset_timings()
    code = hgp(rep_code(3), rep_code(3))
    p = 0.08  # high enough that some shots fail BP and reach OSD
    dec_x = BPOSD_Decoder(code.hz, np.full(code.N, p), max_iter=4)
    dec_z = BPOSD_Decoder(code.hx, np.full(code.N, p), max_iter=4)
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], batch_size=64, seed=0,
    )
    sim.WordErrorRate(256)
    t = timings()
    assert "launch" in t and "finish" in t
    assert t["launch"]["count"] >= 4
    # OSD stage appears whenever any shot failed BP (overwhelmingly likely
    # at p=0.08 over 256 shots; tolerate the alternative)
    if "osd_host" in t:
        assert t["osd_host"]["total_s"] >= 0
