import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder
from qldpc_fault_tolerance_tpu.parallel import (
    sharded_failure_count,
    shot_mesh,
    split_keys_for_mesh,
)
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError


def test_eight_cpu_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def _make_sim(mesh=None, batch_size=64, seed=0):
    code = hgp(rep_code(3), rep_code(3))
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3],
        batch_size=batch_size, mesh=mesh, seed=seed,
    )


def test_sharded_count_matches_per_device_runs():
    mesh = shot_mesh()
    sim = _make_sim(mesh=mesh, batch_size=32)
    key = jax.random.PRNGKey(3)
    keys = split_keys_for_mesh(key, mesh)
    total = int(sim._sharded_runner()(keys))
    # reference computation: same per-device batches run unsharded
    expect = sum(int(sim.run_batch(k, 32).sum()) for k in keys)
    assert total == expect


def test_mesh_wer_consistent_with_single_device():
    mesh = shot_mesh()
    sim_mesh = _make_sim(mesh=mesh, batch_size=64, seed=7)
    sim_one = _make_sim(mesh=None, batch_size=64, seed=7)
    wer_m, _ = sim_mesh.WordErrorRate(512, key=jax.random.PRNGKey(11))
    wer_s, _ = sim_one.WordErrorRate(512, key=jax.random.PRNGKey(11))
    # different shot streams, same statistics: both in [0, 1] and same regime
    assert 0 <= wer_m <= 1 and 0 <= wer_s <= 1
    if wer_s > 0:
        assert abs(wer_m - wer_s) < 10 * max(wer_s, 0.02)


def test_generic_sharded_failure_count():
    mesh = shot_mesh()

    def dev_fn(key, bs):
        return jax.random.uniform(key, (bs,)) < 0.25

    run = sharded_failure_count(dev_fn, mesh, 128)
    keys = split_keys_for_mesh(jax.random.PRNGKey(0), mesh)
    total = int(run(keys))
    assert 0 < total < 8 * 128
    np.testing.assert_allclose(total / (8 * 128), 0.25, atol=0.08)


def test_process_grid_single_process_identity():
    import numpy as np
    from qldpc_fault_tolerance_tpu.parallel import (
        merge_cell_results,
        process_cell_owner,
    )

    owned = process_cell_owner(5)
    assert owned.all()  # single-process: owns every cell
    vals = np.array([1.0, 2.0, 3.0])
    assert np.array_equal(merge_cell_results(vals), vals)


def test_code_family_sharded_flag_single_process():
    import numpy as np
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    fam = CodeFamily(
        [hgp(rep_code(3), rep_code(3))],
        decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        batch_size=64, seed=0,
    )
    a = fam.EvalWER("data", "Total", [0.03], 128, if_plot=False)
    b = fam.EvalWER("data", "Total", [0.03], 128, if_plot=False,
                    shard_across_processes=True)
    assert a.shape == b.shape == (1, 1)
    assert not np.isnan(b).any()
