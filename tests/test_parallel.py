import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder
from qldpc_fault_tolerance_tpu.parallel import (
    sharded_failure_count,
    shot_mesh,
    split_keys_for_mesh,
)
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError


def test_eight_cpu_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def _make_sim(mesh=None, batch_size=64, seed=0):
    code = hgp(rep_code(3), rep_code(3))
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3],
        batch_size=batch_size, mesh=mesh, seed=seed,
    )


def test_sharded_count_matches_per_device_runs():
    mesh = shot_mesh()
    sim = _make_sim(mesh=mesh, batch_size=32)
    key = jax.random.PRNGKey(3)
    keys = split_keys_for_mesh(key, mesh)
    total = int(sim._sharded_runner()(keys))
    # reference computation: same per-device batches run unsharded
    expect = sum(int(sim.run_batch(k, 32).sum()) for k in keys)
    assert total == expect


def test_mesh_wer_consistent_with_single_device():
    mesh = shot_mesh()
    sim_mesh = _make_sim(mesh=mesh, batch_size=64, seed=7)
    sim_one = _make_sim(mesh=None, batch_size=64, seed=7)
    wer_m, _ = sim_mesh.WordErrorRate(512, key=jax.random.PRNGKey(11))
    wer_s, _ = sim_one.WordErrorRate(512, key=jax.random.PRNGKey(11))
    # different shot streams, same statistics: both in [0, 1] and same regime
    assert 0 <= wer_m <= 1 and 0 <= wer_s <= 1
    if wer_s > 0:
        assert abs(wer_m - wer_s) < 10 * max(wer_s, 0.02)


def test_generic_sharded_failure_count():
    mesh = shot_mesh()

    def dev_fn(key, bs):
        return jax.random.uniform(key, (bs,)) < 0.25

    run = sharded_failure_count(dev_fn, mesh, 128)
    keys = split_keys_for_mesh(jax.random.PRNGKey(0), mesh)
    total = int(run(keys))
    assert 0 < total < 8 * 128
    np.testing.assert_allclose(total / (8 * 128), 0.25, atol=0.08)
