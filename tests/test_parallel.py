import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder
from qldpc_fault_tolerance_tpu.parallel import (
    sharded_batch_stats,
    shot_mesh,
    split_keys_for_mesh,
)
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError


def test_eight_cpu_devices_available():
    assert len(jax.devices()) == 8, jax.devices()


def _make_sim(mesh=None, batch_size=64, seed=0):
    code = hgp(rep_code(3), rep_code(3))
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3],
        batch_size=batch_size, mesh=mesh, seed=seed,
    )


def test_sharded_count_matches_per_device_runs():
    mesh = shot_mesh()
    sim = _make_sim(mesh=mesh, batch_size=32)
    key = jax.random.PRNGKey(3)
    keys = split_keys_for_mesh(key, mesh)
    run = sharded_batch_stats(lambda k: sim._device_batch_stats(k, 32), mesh)
    total, _ = (int(v) for v in run(keys))
    # reference computation: same per-device batches run unsharded
    expect = sum(int(sim.run_batch(k, 32).sum()) for k in keys)
    assert total == expect


def _expected_mesh_wer(sim, stats_fn, num_samples, key, wer_fn):
    """Replay the mesh path's exact shot stream unsharded: same per-device
    keys, same batch stats function, summed/min-reduced on one device.
    Returns (wer_result, min_logical_weight)."""
    from qldpc_fault_tolerance_tpu.sim.common import ShotBatcher

    mesh = shot_mesh()
    batcher = ShotBatcher(num_samples, sim.batch_size * mesh.devices.size)
    count, min_w = 0, sim.N
    for i in batcher:
        for k in split_keys_for_mesh(jax.random.fold_in(key, i), mesh):
            c, w = stats_fn(k)
            count += int(c)
            min_w = min(min_w, int(w))
    return wer_fn(count, batcher.total), min_w


def test_mesh_wer_equals_unsharded_replay_data_engine():
    from qldpc_fault_tolerance_tpu.sim.common import wer_single_shot

    mesh = shot_mesh()
    sim = _make_sim(mesh=mesh, batch_size=64, seed=7)
    key = jax.random.PRNGKey(11)
    wer_m, _ = sim.WordErrorRate(512, key=key)
    sim_ref = _make_sim(mesh=None, batch_size=64, seed=7)
    (wer_e, _), min_w_e = _expected_mesh_wer(
        sim_ref, lambda k: sim_ref._device_batch_stats(k, 64), 512, key,
        lambda c, t: wer_single_shot(c, t, sim_ref.K),
    )
    assert wer_m == wer_e
    # the pmin-reduced diagnostic must equal the unsharded replay's minimum
    assert sim.min_logical_weight == min(sim.N, min_w_e)


def test_mesh_wer_equals_unsharded_replay_phenom_engine():
    from qldpc_fault_tolerance_tpu.sim.common import wer_per_cycle
    from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon

    code = hgp(rep_code(3), rep_code(3))
    p, q = 0.04, 0.04

    def make(mesh):
        hx_ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
        hz_ext = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
        d1x = BPDecoder(hz_ext, np.concatenate([np.full(code.N, p),
                                                np.full(code.hz.shape[0], q)]),
                        max_iter=8)
        d1z = BPDecoder(hx_ext, np.concatenate([np.full(code.N, p),
                                                np.full(code.hx.shape[0], q)]),
                        max_iter=8)
        d2x = BPDecoder(code.hz, np.full(code.N, p), max_iter=8)
        d2z = BPDecoder(code.hx, np.full(code.N, p), max_iter=8)
        return CodeSimulator_Phenon(
            code=code, decoder1_x=d1x, decoder1_z=d1z, decoder2_x=d2x,
            decoder2_z=d2z, pauli_error_probs=[p / 3, p / 3, p / 3], q=q,
            batch_size=32, mesh=mesh,
        )

    key = jax.random.PRNGKey(5)
    sim_m = make(shot_mesh())
    wer_m, _ = sim_m.WordErrorRate(5, 256, key=key)
    sim_s = make(None)
    (wer_e, _), min_w_e = _expected_mesh_wer(
        sim_s, lambda k: sim_s._device_batch_stats(k, 5, 32), 256, key,
        lambda c, t: wer_per_cycle(c, t, sim_s.K, 5),
    )
    assert wer_m == wer_e
    # the pmin-reduced diagnostic must equal the unsharded replay's minimum
    assert sim_m.min_logical_weight == min(sim_m.N, min_w_e)


def test_mesh_wer_equals_unsharded_replay_circuit_engines():
    from qldpc_fault_tolerance_tpu.decoders import (
        ST_BP_Decoder_Circuit,
    )
    from qldpc_fault_tolerance_tpu.sim.circuit import CodeSimulator_Circuit
    from qldpc_fault_tolerance_tpu.sim.circuit_spacetime import (
        CodeSimulator_Circuit_SpaceTime,
    )
    from qldpc_fault_tolerance_tpu.sim.common import wer_per_cycle

    code = hgp(rep_code(3), rep_code(3))
    p = 0.01
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0}

    def make_plain(mesh):
        m = code.hx.shape[0]
        hx_ext = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
        d1 = BPDecoder(hx_ext, np.concatenate([np.full(code.N, p),
                                               np.full(m, p)]), max_iter=8)
        d2 = BPDecoder(code.hx, np.full(code.N, p), max_iter=8)
        sim = CodeSimulator_Circuit(
            code=code, decoder1_z=d1, decoder2_z=d2, p=p, num_cycles=3,
            error_params=ep, batch_size=32, mesh=mesh,
        )
        return sim

    key = jax.random.PRNGKey(9)
    sim_m = make_plain(shot_mesh())
    wer_m, _ = sim_m.WordErrorRate(256, key=key)
    sim_s = make_plain(None)
    sim_s._ensure_circuit()
    (wer_e, _), _ = _expected_mesh_wer(
        sim_s, lambda k: sim_s._device_batch_stats(k, 32), 256, key,
        lambda c, t: wer_per_cycle(c, t, sim_s.K, 3),
    )
    assert wer_m == wer_e

    def make_st(mesh):
        sim = CodeSimulator_Circuit_SpaceTime(
            code=code, p=p, num_cycles=7, num_rep=3, error_params=ep,
            batch_size=32, mesh=mesh,
        )
        sim._generate_circuit()
        sim._generate_circuit_graph()
        g = sim.circuit_graph
        sim.decoder1_z = ST_BP_Decoder_Circuit(g["h1"], g["channel_ps1"],
                                               max_iter=8)
        sim.decoder2_z = ST_BP_Decoder_Circuit(g["h2"], g["channel_ps2"],
                                               max_iter=8)
        return sim

    sim_m = make_st(shot_mesh())
    wer_m, _ = sim_m.WordErrorRate(256, key=key)
    sim_s = make_st(None)
    (wer_e, _), _ = _expected_mesh_wer(
        sim_s, lambda k: sim_s._device_batch_stats(k, 32), 256, key,
        lambda c, t: wer_per_cycle(c, t, sim_s.K, 7),
    )
    assert wer_m == wer_e


def test_mesh_wer_equals_unsharded_replay_phenom_st_engine():
    from qldpc_fault_tolerance_tpu.decoders import ST_BP_Decoder_syndrome
    from qldpc_fault_tolerance_tpu.sim.common import wer_per_cycle
    from qldpc_fault_tolerance_tpu.sim.phenom_spacetime import (
        CodeSimulator_Phenon_SpaceTime,
    )

    code = hgp(rep_code(3), rep_code(3))
    p, q, num_rep = 0.03, 0.03, 2

    def make(mesh):
        d1x = ST_BP_Decoder_syndrome(code.hz, p_data=p, p_synd=q,
                                     max_iter=8, num_rep=num_rep)
        d1z = ST_BP_Decoder_syndrome(code.hx, p_data=p, p_synd=q,
                                     max_iter=8, num_rep=num_rep)
        d2x = BPDecoder(code.hz, np.full(code.N, p), max_iter=8)
        d2z = BPDecoder(code.hx, np.full(code.N, p), max_iter=8)
        return CodeSimulator_Phenon_SpaceTime(
            code=code, decoder1_x=d1x, decoder1_z=d1z, decoder2_x=d2x,
            decoder2_z=d2z, pauli_error_probs=[p / 3, p / 3, p / 3], q=q,
            num_rep=num_rep, batch_size=32, mesh=mesh,
        )

    key = jax.random.PRNGKey(13)
    sim_m = make(shot_mesh())
    wer_m, _ = sim_m.WordErrorRate(5, 256, key=key)
    num_rounds = int((5 - 1) / num_rep + 1)
    total_cycles = (num_rounds - 1) * num_rep + 1
    sim_s = make(None)
    (wer_e, _), min_w_e = _expected_mesh_wer(
        sim_s, lambda k: sim_s._device_batch_stats(k, num_rounds, 32), 256,
        key, lambda c, t: wer_per_cycle(c, t, sim_s.K, total_cycles),
    )
    assert wer_m == wer_e
    assert sim_m.min_logical_weight == min(sim_m.N, min_w_e)


def test_generic_sharded_batch_stats():
    import jax.numpy as jnp

    mesh = shot_mesh()

    def stats_fn(key):
        fail = jax.random.uniform(key, (128,)) < 0.25
        weights = jax.random.randint(key, (128,), 0, 100)
        return (fail.sum(dtype=jnp.int32),
                jnp.where(fail, weights, 1000).min().astype(jnp.int32))

    run = sharded_batch_stats(stats_fn, mesh)
    keys = split_keys_for_mesh(jax.random.PRNGKey(0), mesh)
    total, min_w = (int(v) for v in run(keys))
    # exact replay on one device
    exp_total, exp_min = 0, 1000
    for k in keys:
        c, w = stats_fn(k)
        exp_total += int(c)
        exp_min = min(exp_min, int(w))
    assert total == exp_total
    assert min_w == exp_min


def test_process_grid_single_process_identity():
    import numpy as np
    from qldpc_fault_tolerance_tpu.parallel import (
        merge_cell_results,
        process_cell_owner,
    )

    owned = process_cell_owner(5)
    assert owned.all()  # single-process: owns every cell
    vals = np.array([1.0, 2.0, 3.0])
    assert np.array_equal(merge_cell_results(vals), vals)


def test_code_family_sharded_flag_single_process():
    import numpy as np
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    fam = CodeFamily(
        [hgp(rep_code(3), rep_code(3))],
        decoder1_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(3, "minimum_sum", 0.625),
        batch_size=64, seed=0,
    )
    a = fam.EvalWER("data", "Total", [0.03], 128, if_plot=False)
    b = fam.EvalWER("data", "Total", [0.03], 128, if_plot=False,
                    shard_across_processes=True)
    assert a.shape == b.shape == (1, 1)
    assert not np.isnan(b).any()
