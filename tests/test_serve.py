"""Decode-as-a-service tests (ISSUE 8): session cache semantics (warm-path
zero retraces, eviction/rebuild, bit-exact served decodes vs the offline
path), continuous-batching coalescing + tenant fairness, graceful drain
(scheduler- and server-level — no request dropped on shutdown), the TCP
front-end round trip, the per-H decoder-state memo's thread safety, the
cold-start parent-dir creation of the checkpoint/ledger/JSONL writers, the
v2 event-schema back-compat guarantee, and the bench_compare serve gate
(QPS/p99 join the regression ledger)."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
from qldpc_fault_tolerance_tpu.serve import (
    ContinuousBatcher,
    DecodeClient,
    DecodeSession,
    SessionCache,
    assemble_round_robin,
    start_server_thread,
)
from qldpc_fault_tolerance_tpu.serve.scheduler import _Request, _SessionQueue
from qldpc_fault_tolerance_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

DEC_CLS = BP_Decoder_Class(4, "minimum_sum", 0.625)
CODE3 = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
CODE4 = hgp(rep_code(4), rep_code(4), name="hgp_rep4")
P = 0.05


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _params(code):
    return {"h": code.hx, "p_data": P}


def _session(code, name=None, buckets=(8, 32, 128)):
    return DecodeSession(name or code.name, decoder_class=DEC_CLS,
                         params=_params(code), buckets=buckets)


def _synd(code, k, rng):
    err = (rng.random((k, code.N)) < P).astype(np.uint8)
    return (err @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)


def _offline(code, synd):
    return DEC_CLS.GetDecoder(_params(code)).decode_batch(synd)


# ---------------------------------------------------------------------------
# DecodeSession: bit-exactness, padding, chunking
# ---------------------------------------------------------------------------
def test_session_decode_bitexact_vs_offline_padded_and_chunked():
    """Served decodes — padded to a bucket, or chunked past the largest
    bucket — must be bit-exact with the offline decode path on the
    identical syndromes (the acceptance gate: request boundaries and
    megabatch padding must not leak into results)."""
    rng = np.random.default_rng(0)
    sess = _session(CODE3)
    for k in (1, 5, 8, 31, 40, 300):  # pad-only, exact-bucket, chunked
        synd = _synd(CODE3, k, rng)
        out = sess.decode(synd)
        assert out.corrections.shape == (k, CODE3.N)
        assert np.array_equal(out.corrections, _offline(CODE3, synd)), k
        assert out.shots == k
        assert out.padded_shots >= k
        assert out.converged is not None and out.converged.shape == (k,)


def test_session_rejects_bad_input():
    sess = _session(CODE3)
    with pytest.raises(ValueError):
        sess.decode(np.zeros((4, sess.syndrome_width + 1), np.uint8))
    with pytest.raises(ValueError):
        sess.decode(np.zeros((0, sess.syndrome_width), np.uint8))
    with pytest.raises(ValueError):
        DecodeSession("x", decoder_class=DEC_CLS)  # params missing
    with pytest.raises(ValueError):
        DecodeSession("x")  # neither decoder nor factory


def test_session_factory_path_rejects_host_osd_config(monkeypatch):
    """The factory path must apply the same pure-device guard as the
    decoder path: a BPOSD factory forced onto host OSD (the env demotion
    knob — osd_cs itself is device-resident since ISSUE 19) has a
    device_static that silently degrades to plain BP — serving it would
    break the bit-exact-vs-offline guarantee instead of failing loudly."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class

    monkeypatch.setenv("QLDPC_DEVICE_OSD", "0")
    cls = BPOSD_Decoder_Class(10, "minimum_sum", 0.625, "osd_cs", 10)
    with pytest.raises(ValueError, match="host"):
        DecodeSession("x", decoder_class=cls, params=_params(CODE3))


def test_bposd_session_serves_device_osd_bit_exact():
    """ISSUE 13 acceptance: a BPOSD DecodeSession (the default osd_e
    factory, accepted on every backend now that device OSD is the default)
    serves corrections matching offline ``decode_batch`` bit-for-bit, with
    zero warm-path retraces and the session naming its OSD backend."""
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class

    cls = BPOSD_Decoder_Class(8, "minimum_sum", 0.625, "osd_e", 6)
    sess = DecodeSession("bposd_dev", decoder_class=cls,
                         params=_params(CODE3), buckets=(32, 64, 128))
    assert sess.osd_backend == "device"
    assert sess.static[0] == "bposd_dev"
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        sess.warm()
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    compiles = [r for r in sink.records if r["kind"] == "serve_session"
                and r.get("event") == "compile"]
    assert compiles and all(r["osd_backend"] == "device" for r in compiles)
    assert all(telemetry.validate_event(r) == [] for r in compiles)
    rng = np.random.default_rng(7)
    # high-weight errors so a fraction of shots actually reach the OSD
    # stage inside the compiled program
    h = CODE3.hx
    errs = (rng.random((90, CODE3.N)) < 0.2).astype(np.uint8)
    synds = (errs @ h.T % 2).astype(np.uint8)
    offline = cls.GetDecoder(_params(CODE3)).decode_batch(synds)
    telemetry.enable()
    try:
        before = telemetry.compile_stats().get("jax.retraces", 0)
        compiles_before = sess.compiles
        out = sess.decode(synds)
        assert sess.compiles == compiles_before
        assert telemetry.compile_stats().get("jax.retraces", 0) == before
    finally:
        telemetry.disable()
    assert np.array_equal(out.corrections, offline)
    assert out.converged is not None and not out.converged.all()


def test_session_warm_cache_zero_retraces():
    """The AOT program cache is the point of the session: after warmup the
    served path performs ZERO retraces (PR-2 compile tracker), no matter
    how request sizes vary within the warmed buckets."""
    telemetry.enable()
    try:
        sess = _session(CODE4, name="warm4")
        sess.warm()
        rng = np.random.default_rng(1)
        for k in (2, 8, 30):  # one warm pass per bucket (device transfers)
            sess.decode(_synd(CODE4, k, rng))
        before = telemetry.compile_stats().get("jax.retraces", 0)
        compiles_before = sess.compiles
        for k in (1, 3, 7, 8, 9, 17, 31, 32, 100, 128):
            sess.decode(_synd(CODE4, k, rng))
        after = telemetry.compile_stats().get("jax.retraces", 0)
    finally:
        telemetry.disable()
    assert sess.compiles == compiles_before
    assert after - before == 0, (
        f"{after - before} retraces on the warm serve path: something is "
        "tracing per request instead of hitting the AOT program cache")


def test_session_cache_eviction_and_rebuild():
    """Bounded LRU semantics: a third (H, shape) session evicts the least
    recently used; re-requesting it rebuilds (fresh factory call + fresh
    compiles)."""
    builds = []

    def factory(name, code):
        def make():
            builds.append(name)
            return _session(code, name=name)
        return make

    cache = SessionCache(max_sessions=2)
    a = cache.get_or_create("a", factory("a", CODE3))
    cache.get_or_create("b", factory("b", CODE4))
    assert cache.get_or_create("a", factory("a", CODE3)) is a  # hit, no build
    assert builds == ["a", "b"]
    cache.get_or_create("c", factory("c", CODE3))  # evicts b (LRU)
    assert len(cache) == 2 and "b" not in cache and "a" in cache
    b2 = cache.get_or_create("b", factory("b", CODE4))  # rebuild ("a" LRU now? no: a was touched)
    assert builds == ["a", "b", "c", "b"]
    assert b2.compiles == 0  # fresh session: programs compile on demand
    rng = np.random.default_rng(2)
    out = b2.decode(_synd(CODE4, 4, rng))
    assert b2.compiles == 1  # rebuilt program compiled again
    assert out.corrections.shape[0] == 4


# ---------------------------------------------------------------------------
# ContinuousBatcher: coalescing, fairness, drain
# ---------------------------------------------------------------------------
def test_scheduler_coalesces_across_tenants_and_codes_bitexact():
    """Requests from several tenants against two codes coalesce into a few
    megabatches (serve.batches << serve.requests) and every request's
    corrections stay bit-exact vs the offline decode of its own rows."""
    telemetry.enable()
    try:
        sessions = {"hgp_rep3": _session(CODE3), "hgp_rep4": _session(CODE4)}
        for s in sessions.values():
            s.warm()
        bat = ContinuousBatcher(sessions, max_batch_shots=128,
                                max_wait_s=0.2)
        rng = np.random.default_rng(3)
        subs = []
        for i in range(12):
            code = CODE3 if i % 2 == 0 else CODE4
            synd = _synd(code, int(rng.integers(1, 9)), rng)
            subs.append((code, synd, bat.submit(
                code.name, synd, tenant=f"t{i % 3}", request_id=str(i))))
        for code, synd, fut in subs:
            res = fut.result(timeout=60)
            assert np.array_equal(res.corrections, _offline(code, synd))
            assert res.latency_s > 0
        bat.drain()
        snap = telemetry.snapshot()
        assert snap["serve.requests"]["value"] == 12
        batches = snap["serve.batches"]["value"]
        assert 2 <= batches < 12  # coalesced (>= one per session)
        assert snap["serve.tenant.t0.requests"]["value"] == 4
    finally:
        telemetry.disable()


def _mk_req(tenant, shots, t0=0.0):
    from concurrent.futures import Future

    return _Request(request_id=None, tenant=tenant, session="s",
                    syndromes=np.zeros((shots, 4), np.uint8),
                    future=Future(), t0=t0)


def test_assemble_round_robin_fairness():
    """A flooding tenant cannot starve the others: with A holding 10
    queued requests and B one, B's request rides the FIRST flush, and A
    only gets its rotating share of the batch."""
    q = _SessionQueue()
    for i in range(10):
        q.add(_mk_req("A", 4, t0=float(i)))
    q.add(_mk_req("B", 4, t0=99.0))
    batch = assemble_round_robin(q, max_shots=16)
    tenants = [r.tenant for r in batch]
    assert "B" in tenants  # fairness: B made the first batch
    assert sum(r.shots for r in batch) <= 16
    assert tenants.count("A") <= 3  # A capped at its share, not the queue
    # bookkeeping survives a partial flush
    assert q.shots == sum(r.shots
                          for dq in q.tenants.values() for r in dq)
    # force mode (drain) empties everything regardless of the cap
    rest = assemble_round_robin(q, max_shots=16, force=True)
    assert q.empty() and q.shots == 0
    assert len(batch) + len(rest) == 11


def test_scheduler_graceful_drain_no_request_dropped():
    """Acceptance: drain() resolves EVERY submitted request (partial
    batches included) before stopping; submits after drain are rejected
    loudly, not queued into the void."""
    sessions = {"hgp_rep3": _session(CODE3)}
    # huge wait + huge batch: nothing would flush without the drain
    bat = ContinuousBatcher(sessions, max_batch_shots=10_000,
                            max_wait_s=60.0)
    rng = np.random.default_rng(4)
    subs = [(s := _synd(CODE3, 3, rng),
             bat.submit("hgp_rep3", s, tenant=f"t{i % 2}"))
            for i in range(25)]
    assert not any(fut.done() for _, fut in subs)  # all parked in queue
    bat.drain()
    for synd, fut in subs:
        res = fut.result(timeout=1)  # resolved by the drain flush
        assert np.array_equal(res.corrections, _offline(CODE3, synd))
    with pytest.raises(RuntimeError):
        bat.submit("hgp_rep3", _synd(CODE3, 1, rng))
    assert bat.completed == 25 and bat.failed == 0


def test_scheduler_survives_session_evicted_between_submit_and_flush():
    """A session evicted from the cache while its requests sit queued must
    fail THOSE futures (answered, not dropped) and leave the dispatcher
    thread alive for subsequent traffic — an escaping KeyError would
    silently hang the whole service."""
    cache = SessionCache(max_sessions=1)
    cache.get_or_create("a", lambda: _session(CODE3, name="a"))
    bat = ContinuousBatcher(cache, max_batch_shots=10_000, max_wait_s=60.0)
    rng = np.random.default_rng(8)
    fut = bat.submit("a", _synd(CODE3, 2, rng))
    cache.get_or_create("b", lambda: _session(CODE4, name="b"))  # evicts a
    fut_b = bat.submit("b", _synd(CODE4, 2, rng))
    bat.drain()
    with pytest.raises(KeyError):
        fut.result(timeout=1)
    res = fut_b.result(timeout=1)  # dispatcher survived the failed batch
    assert res.corrections.shape[0] == 2
    assert bat.failed == 1 and bat.completed == 1


def test_scheduler_validates_on_submit():
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.01)
    with pytest.raises(KeyError):
        bat.submit("nope", np.zeros((1, 6), np.uint8))
    with pytest.raises(ValueError):
        bat.submit("hgp_rep3", np.zeros((1, 7), np.uint8))
    bat.drain()


# ---------------------------------------------------------------------------
# TCP front-end
# ---------------------------------------------------------------------------
def test_server_roundtrip_ping_error_and_graceful_drain():
    """Full-stack: frames over TCP, streamed responses matched by id,
    structured error replies, and the shutdown drain answering every
    in-flight request (none dropped)."""
    sessions = {"hgp_rep3": _session(CODE3), "hgp_rep4": _session(CODE4)}
    for s in sessions.values():
        s.warm(32)
    bat = ContinuousBatcher(sessions, max_batch_shots=64, max_wait_s=0.01)
    handle = start_server_thread(bat)
    rng = np.random.default_rng(5)
    cli = DecodeClient(*handle.address, tenant="alice")
    try:
        pong = cli.ping()
        assert pong["ok"] and set(pong["sessions"]) == set(sessions)
        # pipelined mixed-code submits
        subs = []
        for i in range(10):
            code = CODE3 if i % 2 else CODE4
            synd = _synd(code, int(rng.integers(1, 6)), rng)
            subs.append((code, synd, cli.submit(code.name, synd)))
        for code, synd, fut in subs:
            res = fut.result(timeout=60)
            assert np.array_equal(res.corrections, _offline(code, synd))
            assert res.server_latency_ms is not None
        # structured error for an unknown session — answered, not dropped
        with pytest.raises(RuntimeError, match="unknown session"):
            cli.decode("nope", np.zeros((1, 6), np.uint8))
        # graceful drain: submit, then stop the server before waiting
        synd3 = _synd(CODE3, 3, rng)
        pending = [cli.submit("hgp_rep3", synd3) for _ in range(8)]
        handle.stop(drain=True)
        for fut in pending:
            res = fut.result(timeout=10)
            assert np.array_equal(res.corrections, _offline(CODE3, synd3))
    finally:
        cli.close()


def test_scheduler_drain_timeout_raises_instead_of_lying():
    """A drain that cannot finish in time must raise — returning normally
    would let the server tear connections down mid-flight and silently
    break the no-request-dropped guarantee."""
    sess = _session(CODE3)
    orig = sess.decode

    def slow(synd):
        time.sleep(0.5)
        return orig(synd)

    sess.decode = slow
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=1,
                            max_wait_s=0.0)
    rng = np.random.default_rng(9)
    futs = [bat.submit("hgp_rep3", _synd(CODE3, 1, rng)) for _ in range(3)]
    with pytest.raises(TimeoutError):
        bat.drain(timeout=0.2)
    bat.drain(timeout=60.0)  # the flush itself kept going; finish it
    for f in futs:
        assert f.result(timeout=5).corrections.shape[0] == 1


def test_server_abandon_shutdown_stops_worker_and_answers():
    """shutdown(drain=False) is the fast abandon: queued futures fail
    immediately (no max_wait sit-out) and the dispatcher thread stops
    instead of leaking into the embedding process."""
    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=10_000, max_wait_s=60.0)
    handle = start_server_thread(bat)
    cli = DecodeClient(*handle.address)
    rng = np.random.default_rng(10)
    futs = [cli.submit("hgp_rep3", _synd(CODE3, 2, rng)) for _ in range(4)]
    time.sleep(0.4)  # let the frames reach the (parked) batcher queue
    t0 = time.perf_counter()
    handle.stop(drain=False)
    assert time.perf_counter() - t0 < 10  # not the 60s deadline
    for f in futs:  # answered with the abandon error, not dropped silently
        with pytest.raises((RuntimeError, ConnectionError)):
            f.result(timeout=5)
    assert not bat._thread.is_alive()
    cli.close()


def test_server_answers_non_object_json_frame():
    """Valid JSON that is not an object gets a structured error reply and
    the connection keeps serving the pipelined requests behind it."""
    import socket

    from qldpc_fault_tolerance_tpu.serve.wire import HEADER

    sess = _session(CODE3)
    sess.warm(8)
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=64,
                            max_wait_s=0.01)
    handle = start_server_thread(bat)
    raw = socket.create_connection(handle.address)
    body = b"[1,2,3]"
    raw.sendall(HEADER.pack(len(body)) + body)
    head = b""
    while len(head) < 4:
        head += raw.recv(4 - len(head))
    (length,) = HEADER.unpack(head)
    reply = b""
    while len(reply) < length:
        reply += raw.recv(length - len(reply))
    msg = json.loads(reply)
    assert msg["ok"] is False and "JSON object" in msg["error"]
    raw.close()
    cli = DecodeClient(*handle.address)  # connection handling still alive
    try:
        res = cli.decode("hgp_rep3", _synd(CODE3, 2,
                                           np.random.default_rng(15)))
        assert res.corrections.shape[0] == 2
        handle.stop(drain=True)
    finally:
        cli.close()


def test_server_survives_midframe_disconnect():
    """A client dying after the frame header but before the body must take
    the clean-disconnect path; the server keeps serving other clients."""
    import socket
    import struct

    bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                            max_batch_shots=64, max_wait_s=0.01)
    handle = start_server_thread(bat)
    raw = socket.create_connection(handle.address)
    raw.sendall(struct.pack(">I", 100) + b"partial")  # header, torn body
    raw.close()
    time.sleep(0.2)
    cli = DecodeClient(*handle.address)
    try:
        res = cli.decode("hgp_rep3",
                         _synd(CODE3, 2, np.random.default_rng(12)))
        assert res.corrections.shape[0] == 2
        handle.stop(drain=True)
    finally:
        cli.close()


# ---------------------------------------------------------------------------
# Satellite: per-H decoder-state memo thread safety
# ---------------------------------------------------------------------------
def test_decoder_state_memo_thread_safe(monkeypatch):
    """Concurrent GetDecoderState for the SAME H (the serve session
    construction path) must build the Tanner graph exactly once and hand
    every caller the identical memoized objects — the _LruCache lock
    regression test (an unlocked OrderedDict races move_to_end/insert and
    can rebuild or corrupt)."""
    from qldpc_fault_tolerance_tpu.ops import bp as bp_mod

    bp_mod._graph_host_cache.clear()
    bp_mod._graph_dev_cache.clear()
    calls = []
    orig = bp_mod._build_tanner_graph_host

    def counting(h):
        calls.append(threading.get_ident())
        time.sleep(0.02)  # widen the unlocked race window
        return orig(h)

    monkeypatch.setattr(bp_mod, "_build_tanner_graph_host", counting)
    n_threads = 8
    barrier = threading.Barrier(n_threads)
    results = [None] * n_threads
    errors = []

    def worker(i):
        try:
            barrier.wait()
            results[i] = DEC_CLS.GetDecoderState(_params(CODE4))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(calls) == 1, (
        f"{len(calls)} Tanner-graph builds for one H under concurrency — "
        "the per-H memo raced")
    g0 = results[0][1]["graph"]
    for static, state in results[1:]:
        assert static == results[0][0]
        assert state["graph"] is g0  # the memoized object, not a rebuild


# ---------------------------------------------------------------------------
# Satellite: cold-start parent-directory creation
# ---------------------------------------------------------------------------
def test_memo_builds_for_different_keys_overlap():
    """Single-flight is per KEY: two threads building DIFFERENT keys must
    run their makes concurrently (a multi-code service cold start must not
    serialize seconds-long graph builds behind one cache-wide lock)."""
    from qldpc_fault_tolerance_tpu.ops.bp import _LruCache

    cache = _LruCache()
    barrier = threading.Barrier(2, timeout=5)  # trips only if concurrent

    def make(tag):
        def m():
            barrier.wait()
            return tag
        return m

    out, errors = {}, []

    def worker(tag):
        try:
            out[tag] = cache.get((tag,), make(tag))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(t,)) for t in (1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors  # BrokenBarrierError = builds serialized
    assert out == {1: 1, 2: 2}


def test_session_from_decoder_invalidate_reuploads_fresh_state():
    """decoder=-built sessions must survive invalidate() (the recompile
    recovery rung): the rebuild re-uploads from a construction-time host
    snapshot instead of re-serving the decoder's original device pytree
    (which a worker restart would have killed)."""
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder

    dec = BPDecoder(CODE3.hx, np.full(CODE3.N, P), max_iter=6)
    sess = DecodeSession("d3", decoder=dec, buckets=(8,))
    rng = np.random.default_rng(13)
    synd = _synd(CODE3, 4, rng)
    before = sess.decode(synd).corrections
    state_before = sess.state
    sess.invalidate()
    assert sess.state is not state_before  # genuinely re-resolved
    after = sess.decode(synd).corrections
    assert np.array_equal(before, after)
    assert np.array_equal(before, dec.decode_batch(synd))


def test_client_reader_survives_idle_longer_than_socket_timeout():
    """An idle gap longer than the socket timeout must not kill the
    reader thread — a low-traffic client's later requests still resolve."""
    sess = _session(CODE3)
    sess.warm(8)
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=64,
                            max_wait_s=0.01)
    handle = start_server_thread(bat)
    cli = DecodeClient(*handle.address, timeout=1.0)
    try:
        assert cli.ping()["ok"]
        time.sleep(1.5)  # > the 1.0s socket timeout, reader must survive
        res = cli.decode("hgp_rep3", _synd(CODE3, 2,
                                           np.random.default_rng(14)))
        assert res.corrections.shape[0] == 2
        handle.stop(drain=True)
    finally:
        cli.close()


def test_memo_on_evict_hook_runs_outside_the_lock():
    """The eviction hook must run with the map lock RELEASED: hook I/O
    must not stall concurrent lookups, and a hook touching the cache
    (here: len(), which takes the lock) must not deadlock."""
    from qldpc_fault_tolerance_tpu.ops.bp import _LruCache

    cache = _LruCache(maxsize=1)
    seen = []
    cache.on_evict = lambda k, v: seen.append((k, v, len(cache)))
    cache.get("a", lambda: 1)
    cache.get("b", lambda: 2)  # evicts "a"; hook re-enters the cache
    assert seen == [("a", 1, 1)]


def test_memo_clear_mid_build_is_not_cached():
    """A clear() landing while a build is in flight (reset_device_state
    after a worker restart) invalidates that build: the in-flight caller
    still gets its value (its enclosing retry re-resolves), but the stale
    value — whose device buffers may live on the dead worker — must NOT
    be cached for later callers."""
    from qldpc_fault_tolerance_tpu.ops.bp import _LruCache

    cache = _LruCache()
    started, release = threading.Event(), threading.Event()

    def make():
        started.set()
        release.wait(5)
        return "stale"

    out = {}
    t = threading.Thread(target=lambda: out.update(v=cache.get("k", make)))
    t.start()
    assert started.wait(5)
    cache.clear()  # the worker-restart reset, mid-build
    release.set()
    t.join(5)
    assert out["v"] == "stale"
    assert cache.get("k", lambda: "fresh") == "fresh"


def test_memo_failed_build_retries_clean():
    from qldpc_fault_tolerance_tpu.ops.bp import _LruCache

    cache = _LruCache()
    with pytest.raises(RuntimeError):
        cache.get("k", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    assert cache.get("k", lambda: 42) == 42  # no poisoned entry left


def test_tenant_counter_cardinality_is_bounded():
    """The tenant label comes off the wire: a unique-tenant-per-request
    client must not grow the metrics registry without bound — overflow
    tenants fold into one __other__ counter."""
    telemetry.enable()
    try:
        bat = ContinuousBatcher({"hgp_rep3": _session(CODE3)},
                                max_batch_shots=256, max_wait_s=0.05)
        bat.max_tenant_counters = 5
        rng = np.random.default_rng(11)
        futs = [bat.submit("hgp_rep3", _synd(CODE3, 1, rng),
                           tenant=f"uuid-{i}") for i in range(20)]
        for f in futs:
            f.result(timeout=60)
        bat.drain()
        snap = telemetry.snapshot()
        tenant_counters = [n for n in snap if n.startswith("serve.tenant.")]
        assert len(tenant_counters) == 6  # 5 named + __other__
        assert snap["serve.tenant.__other__.requests"]["value"] == 15
    finally:
        telemetry.disable()


def test_wire_frame_cap_enforced_on_send():
    from qldpc_fault_tolerance_tpu.serve import wire

    small = wire.encode_frame({"ok": True})
    assert wire.HEADER.unpack(small[:4])[0] == len(small) - 4
    orig = wire.MAX_FRAME_BYTES
    wire.MAX_FRAME_BYTES = 16
    try:
        with pytest.raises(ValueError, match="exceeds"):
            wire.encode_frame({"corrections": [[0, 1]] * 100})
    finally:
        wire.MAX_FRAME_BYTES = orig


def test_checkpoint_cold_start_creates_parent_dirs(tmp_path):
    """A fresh service host points the checkpoint/ledger/telemetry writers
    at directories that don't exist yet; the first append must create
    them, not crash (exist_ok semantics)."""
    from qldpc_fault_tolerance_tpu.utils.checkpoint import SweepCheckpoint

    path = tmp_path / "state" / "nested" / "sweep.jsonl"
    ckpt = SweepCheckpoint(str(path))
    ckpt.put({"code": "c", "p": 0.1}, {"wer": 0.5})
    assert path.exists()
    again = SweepCheckpoint(str(path))
    assert again.get({"code": "c", "p": 0.1}) == {"wer": 0.5}


def test_jsonl_sink_cold_start_creates_parent_dirs(tmp_path):
    path = tmp_path / "tele" / "run.jsonl"
    telemetry.enable(str(path))
    try:
        telemetry.event("telemetry_enabled", pid=1)
    finally:
        telemetry.disable()
    lines = [json.loads(x) for x in path.read_text().splitlines()]
    assert any(e["kind"] == "telemetry_enabled" for e in lines)


# ---------------------------------------------------------------------------
# Satellite: event schema v2 — serve kinds validate, v1 still validates
# ---------------------------------------------------------------------------
def _serve_events_from_real_run():
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    try:
        sessions = {"hgp_rep3": _session(CODE3)}
        bat = ContinuousBatcher(sessions, max_batch_shots=32,
                                max_wait_s=0.01)
        rng = np.random.default_rng(6)
        futs = [bat.submit("hgp_rep3", _synd(CODE3, 2, rng),
                           tenant=f"t{i % 2}", request_id=str(i))
                for i in range(6)]
        for f in futs:
            f.result(timeout=60)
        bat.drain()
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()
    return sink.records


def test_serve_events_validate_against_schema_v2():
    events = _serve_events_from_real_run()
    kinds = {e["kind"] for e in events}
    assert {"serve_session", "serve_request", "serve_batch",
            "serve_drain"} <= kinds
    problems = [p for e in events for p in telemetry.validate_event(e)]
    assert problems == [], problems


def test_v1_events_still_validate_after_schema_bump():
    """The v2 bump is additive: representative v1 events (one per frozen
    v1 kind) must still validate unchanged."""
    v1_samples = {
        "telemetry_enabled": {"pid": 1},
        "snapshot": {"metrics": {}, "compile": {}},
        "wer_run": {"engine": "data", "shots": 10, "failures": 1,
                    "wer": 0.1},
        "heartbeat": {"engine": "data", "shots": 10},
        "cell_done": {"code": "c", "noise": "data", "type": "Total",
                      "p": 0.1},
        "cell_progress": {"engine": "data", "cells": [], "failures": [],
                          "shots": [], "ci_low": [], "ci_high": []},
        "cell_resume": {"key": {}, "batches_done": 3},
        "fit_report": {"fit": "threshold", "converged": True},
        "anomaly": {"anomaly": "non_monotone_wer"},
        "ledger": {"run_id": "r", "fingerprint": "f", "cells": 1,
                   "fits": 0, "anomalies": 0},
        "fused_fallback": {"reason": "x", "cells": 2},
        "fault_injected": {"site": "s", "fault_kind": "raise", "seed": 0},
        "degrade": {"rung": "packed->dense"},
        "retry": {"label": "l", "attempt": 1, "wait_s": 0.5, "error": "e"},
        "retry_exhausted": {"label": "l", "attempts": 3, "error": "e"},
        "fail_fast": {"label": "l", "error": "e"},
        "watchdog_timeout": {"label": "l", "timeout_s": 5.0},
        "program_cost": {"label": "megabatch.data"},
    }
    assert set(v1_samples) == set(telemetry._V1_EVENT_KINDS)
    assert telemetry.EVENT_SCHEMA_VERSION >= 2
    for kind, fields in v1_samples.items():
        rec = {"ts": 1.0, "kind": kind, **fields}
        assert telemetry.validate_event(rec) == [], (kind, fields)


def test_v2_events_still_validate_after_v3_bump():
    """The v3 (rare-event) bump is additive too: representative v2 serve
    events — one per frozen v2 kind — must still validate unchanged, and
    the v1/v2 kind sets stay frozen."""
    v2_samples = {
        "serve_session": {"session": "hgp_rep3", "event": "open"},
        "serve_request": {"session": "hgp_rep3", "tenant": "t0",
                          "shots": 4},
        "serve_batch": {"session": "hgp_rep3", "requests": 2, "shots": 8,
                        "bucket": 32},
        "serve_drain": {"pending_requests": 0, "completed": 6},
    }
    assert set(v2_samples) == set(telemetry._V2_EVENT_KINDS)
    assert telemetry.EVENT_SCHEMA_VERSION >= 3
    assert not (telemetry._V1_EVENT_KINDS & telemetry._V2_EVENT_KINDS)
    for kind, fields in v2_samples.items():
        rec = {"ts": 1.0, "kind": kind, **fields}
        assert telemetry.validate_event(rec) == [], (kind, fields)


def test_v3_events_still_validate_after_v4_bump():
    """The v4 (operational observability) bump is additive: the frozen v3
    rare-event kind still validates, the three kind sets stay disjoint,
    and representative v4 events validate."""
    v3_samples = {
        "rare_stratum": {"stratum": 3, "shots": 100, "failures": 2,
                         "weight": 0.01, "rate": 0.02},
    }
    assert set(v3_samples) == set(telemetry._V3_EVENT_KINDS)
    assert telemetry.EVENT_SCHEMA_VERSION >= 4
    frozen = (telemetry._V1_EVENT_KINDS, telemetry._V2_EVENT_KINDS,
              telemetry._V3_EVENT_KINDS)
    for i, a in enumerate(frozen):
        for b in frozen[i + 1:]:
            assert not (a & b)
    for kind, fields in v3_samples.items():
        rec = {"ts": 1.0, "kind": kind, **fields}
        assert telemetry.validate_event(rec) == [], (kind, fields)
    v4_samples = {
        "trace": {"trace_id": "t", "span_id": "s", "name": "queue_wait",
                  "dur_s": 0.01, "parent_id": "p", "tenant": "t0",
                  "amortized_over": 4, "ok": True},
        "slo_alert": {"tenant": "t0", "signal": "shed",
                      "prev_signal": "admit", "burn_rate": 8.5,
                      "objective": "latency", "window_s": 30.0},
        "process_info": {"pid": 1, "hostname": "h", "git_sha": None,
                         "jax": "0.4.37", "backend": "cpu"},
    }
    for kind, fields in v4_samples.items():
        rec = {"ts": 1.0, "kind": kind, **fields}
        assert telemetry.validate_event(rec) == [], (kind, fields)


# ---------------------------------------------------------------------------
# Satellite: report + dashboard render serve events instead of dropping them
# ---------------------------------------------------------------------------
def test_telemetry_report_and_dashboard_render_serve(tmp_path):
    import importlib

    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    try:
        sessions = {"hgp_rep3": _session(CODE3)}
        bat = ContinuousBatcher(sessions, max_batch_shots=32,
                                max_wait_s=0.01)
        rng = np.random.default_rng(7)
        futs = [bat.submit("hgp_rep3", _synd(CODE3, 2, rng),
                           tenant=f"t{i % 2}") for i in range(4)]
        for f in futs:
            f.result(timeout=60)
        bat.drain()
        telemetry.write_snapshot_event()
        events = list(sink.records)
    finally:
        telemetry.remove_sink(sink)
        telemetry.disable()

    report = importlib.import_module("scripts.telemetry_report")
    summary = report.summarize(events)
    assert summary["serve"]["requests"] == 4
    assert summary["serve"]["batches"] >= 1
    assert summary["serve"]["tenants"] == {"t0": 2, "t1": 2}
    text = report.render(summary)
    assert "serve (decode service)" in text and "tenant t0" in text

    dash = importlib.import_module("scripts.sweep_dashboard")
    grid = dash.build_grid(events)
    srv = grid["serve"]["sessions"]["hgp_rep3"]
    assert srv["requests"] == 4 and srv["tenants"] == {"t0", "t1"}
    text = dash.render_grid(grid)
    assert "serve (decode service)" in text and "hgp_rep3" in text


# ---------------------------------------------------------------------------
# Satellite: bench_compare gates QPS + p99 for serve rounds
# ---------------------------------------------------------------------------
def test_bench_compare_gates_serve_qps_and_p99(tmp_path):
    import importlib

    bench_compare = importlib.import_module("bench_compare")

    def write_round(n, qps, p99, shots_per_s):
        obj = {"schema": 2, "round": n,
               "result": {"metric": "decode-service sustained QPS",
                          "value": qps, "unit": "req/s",
                          "p99_ms": p99, "shots_per_s": shots_per_s}}
        p = tmp_path / f"BENCH_SERVE_r{n:02d}.json"
        p.write_text(json.dumps(obj))
        return str(p)

    # p99 regression (latency RISES) fires even with the QPS headline flat
    paths = [write_round(1, 500.0, 100.0, 8000.0),
             write_round(2, 500.0, 180.0, 8000.0)]
    assert bench_compare.main(paths + ["--gate", "--tolerance", "10"]) == 1
    # improving latency + QPS passes
    ok = [write_round(3, 500.0, 100.0, 8000.0),
          write_round(4, 520.0, 80.0, 8200.0)]
    assert bench_compare.main(ok + ["--gate", "--tolerance", "10"]) == 0
    # QPS regression fires
    bad = [write_round(5, 500.0, 100.0, 8000.0),
           write_round(6, 300.0, 100.0, 8000.0)]
    assert bench_compare.main(bad + ["--gate", "--tolerance", "10"]) == 1


def test_bench_compare_gates_tracing_ab_fields(tmp_path):
    """ISSUE 11 satellite: the tracing A/B's robust companions join the
    regression ledger — traced throughput regresses DOWN, traced tail
    latency regresses UP; rounds without the block still gate."""
    import importlib

    bench_compare = importlib.import_module("bench_compare")

    def write_round(n, traced_sps, traced_p99):
        obj = {"schema": 2, "round": n,
               "result": {"metric": "decode-service sustained QPS",
                          "value": 500.0, "unit": "req/s",
                          "tracing_ab": {
                              "traced_shots_per_s": traced_sps,
                              "traced_p99_ms": traced_p99,
                              "overhead_pct": 1.0,
                              "overhead_le_2pct": True}}}
        p = tmp_path / f"BENCH_TRACE_r{n:02d}.json"
        p.write_text(json.dumps(obj))
        return str(p)

    # traced-arm throughput collapse fires
    bad = [write_round(1, 8000.0, 100.0), write_round(2, 4000.0, 100.0)]
    assert bench_compare.main(bad + ["--gate", "--tolerance", "10"]) == 1
    # traced-arm tail-latency blowup fires
    slow = [write_round(3, 8000.0, 100.0), write_round(4, 8000.0, 300.0)]
    assert bench_compare.main(slow + ["--gate", "--tolerance", "10"]) == 1
    # healthy pair passes; a legacy round without the block still gates
    ok = [write_round(5, 8000.0, 100.0), write_round(6, 8100.0, 95.0)]
    assert bench_compare.main(ok + ["--gate", "--tolerance", "10"]) == 0
    legacy = {"schema": 2, "round": 7,
              "result": {"metric": "decode-service sustained QPS",
                         "value": 505.0, "unit": "req/s"}}
    p7 = tmp_path / "BENCH_TRACE_r07.json"
    p7.write_text(json.dumps(legacy))
    assert bench_compare.main([ok[1], str(p7),
                               "--gate", "--tolerance", "10"]) == 0
