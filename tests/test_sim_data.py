import numpy as np
import pytest

import jax

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BPDecoder, BPOSD_Decoder
from qldpc_fault_tolerance_tpu.sim.common import wer_per_cycle, wer_single_shot
from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError


def _surface(d=3):
    return hgp(rep_code(d), rep_code(d))


def _make_sim(code, p, dec_cls=BPOSD_Decoder, **kw):
    dec_x = dec_cls(code.hz, np.full(code.N, p), max_iter=20)
    dec_z = dec_cls(code.hx, np.full(code.N, p), max_iter=20)
    probs = [p / 3, p / 3, p / 3]
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z, pauli_error_probs=probs, **kw
    )


def test_zero_noise_never_fails():
    code = _surface(3)
    sim = _make_sim(code, 1e-9, batch_size=64)
    wer, eb = sim.WordErrorRate(64)
    assert wer == 0.0


def test_heavy_noise_mostly_fails():
    code = _surface(3)
    sim = _make_sim(code, 0.75, batch_size=128)
    fail = sim.run_batch(jax.random.PRNGKey(0), 128)
    assert fail.mean() > 0.5


def test_wer_decreases_with_p():
    code = _surface(3)
    wers = []
    for p in (0.15, 0.03):
        sim = _make_sim(code, p, batch_size=256, seed=1)
        wer, _ = sim.WordErrorRate(512)
        wers.append(wer)
    assert wers[1] < wers[0]


def test_surface_d3_failure_scaling():
    """d=3 surface code with OSD: single errors always corrected, so the
    failure probability must be O(p^2) — check it is well below the physical
    rate at small p."""
    code = _surface(3)
    p = 0.01
    sim = _make_sim(code, p, batch_size=1024, seed=2)
    fails = sim.run_batch(jax.random.PRNGKey(2), 1024)
    assert fails.mean() < 5 * p  # p^2-suppressed; generous stat bound


def test_eval_logical_type_consistency():
    code = _surface(3)
    p = 0.08
    key = jax.random.PRNGKey(5)
    rates = {}
    for t in ("X", "Z", "Total"):
        sim = _make_sim(code, p, batch_size=512)
        sim.eval_logical_type = t
        rates[t] = sim.run_batch(key, 512).mean()
    assert rates["Total"] >= max(rates["X"], rates["Z"]) - 1e-9


def test_plain_bp_stays_on_device():
    code = _surface(3)
    sim = _make_sim(code, 0.05, dec_cls=BPDecoder, batch_size=128)
    assert not sim._needs_host
    fail = sim.run_batch(jax.random.PRNGKey(1), 128)
    assert fail.shape == (128,)


def test_wer_math_matches_reference_formulas():
    # src/Simulators.py:174-188
    wer, eb = wer_single_shot(10, 1000, K=17)
    pl = 10 / 1000
    assert np.isclose(wer, 1 - (1 - pl) ** (1 / 17))
    pl_eb = np.sqrt((1 - pl) * pl / 1000)
    assert np.isclose(eb, pl_eb * ((1 - pl_eb) ** (1 / 17 - 1)) / 17)
    # src/Simulators.py:353-361
    w, _ = wer_per_cycle(100, 1000, K=4, num_cycles=5)
    per_qubit = 1 - (1 - 0.1) ** (1 / 4)
    assert np.isclose(w, (1 - (1 - 2 * per_qubit) ** (1 / 5)) / 2)
    # Even cycle counts are accepted (notebook-era behavior kept so the
    # published checkpoint sweeps run unmodified — sim/common.py docstring);
    # the current reference asserts odd at src/Simulators.py:353.
    w_even, eb_even = wer_per_cycle(1, 10, K=2, num_cycles=4)
    assert 0.0 <= w_even <= 1.0 and eb_even >= 0.0
    # notebook-era eb propagation (src/Simulators.py:340-351 commented block)
    plc = (1 - (1 - 2 * 0.1) ** (1 / 5)) / 2
    plc_eb = np.sqrt((1 - plc) * plc / 1000)
    w5, eb5 = wer_per_cycle(100, 1000, K=4, num_cycles=5)
    assert np.isclose(eb5, plc_eb * ((1 - plc_eb) ** (1 / 4 - 1)) / 4)


def test_reproducible_with_same_key():
    code = _surface(3)
    sim = _make_sim(code, 0.06, batch_size=256)
    f1 = sim.run_batch(jax.random.PRNGKey(9), 256)
    f2 = sim.run_batch(jax.random.PRNGKey(9), 256)
    assert np.array_equal(f1, f2)
