"""Dispatch-amortized megabatch driver (parallel/shots.py) — the tier-1
smoke of the packed megabatch path: one compiled scan per ``k_inner``
batches, dispatch accounting the bench relies on, and result equality with
the naive one-dispatch-per-batch loop.
"""
import numpy as np

import jax
import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.parallel import (
    MegabatchDriver,
    drain_double_buffered,
)


def _counting_driver(k_inner):
    calls = []

    def stats(key, bias):
        calls.append(1)
        draw = jax.random.randint(key, (), 0, 100, jnp.int32)
        return draw + bias, -draw

    driver = MegabatchDriver(
        stats,
        lambda c, o: (c[0] + o[0], jnp.minimum(c[1], o[1])),
        lambda: (jnp.zeros((), jnp.int32), jnp.asarray(10 ** 6, jnp.int32)),
        k_inner=k_inner,
    )
    return driver, calls


def test_driver_matches_naive_loop_and_counts_dispatches():
    key = jax.random.PRNGKey(0)
    bias = jnp.asarray(3, jnp.int32)
    driver, _ = _counting_driver(k_inner=4)
    (total, mn), n_run = driver.run(key, 8, bias)
    assert n_run == 8 and driver.dispatches == 2
    # naive reference: same fold_in stream, one "dispatch" per batch
    want_t, want_m = 0, 10 ** 6
    for j in range(8):
        d = jax.random.randint(jax.random.fold_in(key, j), (), 0, 100,
                               jnp.int32)
        want_t, want_m = want_t + int(d) + 3, min(want_m, -int(d))
    assert int(total) == want_t and int(mn) == want_m


def test_driver_rounds_up_to_k_inner_multiple():
    driver, _ = _counting_driver(k_inner=4)
    (_, _), n_run = driver.run(jax.random.PRNGKey(1), 5, jnp.int32(0))
    assert n_run == 8 and driver.dispatches == 2


def test_run_keys_streams_every_megabatch():
    key = jax.random.PRNGKey(2)
    driver, _ = _counting_driver(k_inner=2)
    snaps = list(driver.run_keys(key, 6, jnp.int32(0)))
    assert [done for _, done in snaps] == [2, 4, 6]
    # monotone accumulation; final snapshot equals a fresh full run
    totals = [int(c[0]) for c, _ in snaps]
    assert totals == sorted(totals)
    driver2, _ = _counting_driver(k_inner=2)
    (total, _), _ = driver2.run(key, 6, jnp.int32(0))
    assert totals[-1] == int(total)


def test_drain_double_buffered_preserves_order():
    launched, finished = [], []
    out = list(drain_double_buffered(
        lambda i: (launched.append(i), i)[1],
        lambda i: (finished.append(i), i * 10)[1],
        range(5), depth=2,
    ))
    assert out == [0, 10, 20, 30, 40]
    assert launched == list(range(5)) and finished == list(range(5))


def _tiny_sim(batch_size=64, scan_chunk=2, **kw):
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = hgp(rep_code(3), rep_code(3))
    p = kw.pop("p", 0.02)
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    return CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=batch_size, seed=0,
        scan_chunk=scan_chunk, packed=True, **kw)


def test_target_failures_early_stop():
    """WordErrorRate(target_failures=...) drains megabatch counts
    double-buffered and stops once the cumulative count reaches the
    target — fewer dispatches than the full budget, and the WER uses the
    shots actually run as its denominator."""
    import pytest

    sim = _tiny_sim(p=0.2)  # high p so failures arrive in the first chunk
    wer, _ = sim.WordErrorRate(64 * 16, key=jax.random.PRNGKey(3),
                               target_failures=1)
    assert 0.0 < wer <= 1.0
    assert sim.last_dispatches < 8  # stopped before the 16-batch budget
    # host-postprocess decoders have no engine path at all (ISSUE 13):
    # loud, not silent
    sim2 = _tiny_sim()
    sim2._needs_host = True
    with pytest.raises(ValueError, match="host-OSD"):
        sim2.WordErrorRate(128, key=jax.random.PRNGKey(0), target_failures=1)


def test_packed_megabatch_smoke_cpu():
    """One packed megabatch through the real data-error engine on CPU —
    the driver path the bench uses, kept tiny so tier-1 always exercises
    it."""
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = hgp(rep_code(3), rep_code(3))
    p = 0.02
    dec = lambda h: BPDecoder(h, np.full(code.N, p), max_iter=6)  # noqa: E731
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=64, seed=0,
        scan_chunk=2, packed=True,
    )
    wer, eb = sim.WordErrorRate(256, key=jax.random.PRNGKey(5))
    assert 0.0 <= wer <= 1.0 and eb >= 0.0
    assert sim.last_dispatches == 2  # 4 batches / k_inner 2
