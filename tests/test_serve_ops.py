"""Live ops plane + end-to-end tracing tests (ISSUE 11): SLO burn-rate
evaluation and shed/defer admission signals (engine-level, batcher-level,
and through the TCP front-end), deferred-tenant batch assembly, the
/metrics /healthz /varz /tracez endpoints (direct and over live HTTP),
the full traced-request span tree through the real TCP stack (retrievable
by trace id from the JSONL stream and /tracez, with zero warm-path
retraces), and the flight-recorder postmortem a faultinject-killed
dispatch ships naming the in-flight requests."""
import json
import os
import sys
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
from qldpc_fault_tolerance_tpu.serve import (
    AdmissionError,
    ContinuousBatcher,
    DecodeClient,
    DecodeSession,
    OpsServer,
    SLOEngine,
    SLOPolicy,
    assemble_round_robin,
    start_ops_thread,
    start_server_thread,
)
from qldpc_fault_tolerance_tpu.serve.scheduler import _Request, _SessionQueue
from qldpc_fault_tolerance_tpu.utils import faultinject, telemetry, tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

DEC_CLS = BP_Decoder_Class(4, "minimum_sum", 0.625)
CODE3 = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
P = 0.05


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    tracing.recorder().clear()
    tracing.configure(postmortem_dir="")
    yield
    telemetry.disable()
    telemetry.reset()
    tracing.recorder().clear()
    tracing.configure(postmortem_dir="")


def _session(code=CODE3, buckets=(8, 32)):
    return DecodeSession(code.name, decoder_class=DEC_CLS,
                         params={"h": code.hx, "p_data": P},
                         buckets=buckets)


def _synd(code, k, rng):
    err = (rng.random((k, code.N)) < P).astype(np.uint8)
    return (err @ np.asarray(code.hx, np.uint8).T % 2).astype(np.uint8)


# ---------------------------------------------------------------------------
# SLO engine: burn rates, transitions, admission
# ---------------------------------------------------------------------------
def _engine(**pol):
    pol.setdefault("min_requests", 10)
    pol.setdefault("eval_interval_s", 0.0)
    return SLOEngine(SLOPolicy(**pol))


def test_burn_rate_math_latency_objective():
    """100 requests, 4 over the latency target, 1% budget -> burn 4.0:
    the defer band (>=2, <6) with the default thresholds."""
    slo = _engine()
    for i in range(100):
        lat = 10.0 if i < 4 else 0.001
        slo.observe_request("t", lat, ok=True, now=100.0)
    report = slo.evaluate(now=100.0)["t"]
    assert report["burn_rate"] == pytest.approx(4.0)
    assert report["objective"] == "latency"
    assert report["signal"] == "defer"
    assert slo.admission("t", now=100.0) == "defer"
    assert slo.deferred_tenants() == frozenset({"t"})


def test_burn_rate_shed_and_error_objective():
    slo = _engine()
    for i in range(50):
        slo.observe_request("t", 0.001, ok=(i % 2 == 0), now=5.0)
    report = slo.evaluate(now=5.0)["t"]
    # 50% errors against a 0.1% budget: deep into shed
    assert report["objective"] == "errors"
    assert report["signal"] == "shed"
    with pytest.raises(AdmissionError) as exc:
        slo.check_admission("t", now=5.0)
    assert exc.value.tenant == "t"
    assert exc.value.burn_rate > 6.0


def test_cold_tenant_and_stale_window_admit():
    slo = _engine(min_requests=20)
    for _ in range(5):  # below min_requests: judged on nothing
        slo.observe_request("cold", 99.0, now=1.0)
    assert slo.evaluate(now=1.0)["cold"]["signal"] == "admit"
    slo2 = _engine()
    for _ in range(50):
        slo2.observe_request("old", 99.0, now=1.0)
    assert slo2.evaluate(now=1.0)["old"]["signal"] == "shed"
    # the same observations aged out of the rolling window: the tenant
    # recovers AND its state is garbage-collected from the report
    assert "old" not in slo2.evaluate(now=1000.0)
    assert slo2.admission("old", now=1000.0) == "admit"


def test_tenant_state_is_bounded_and_stale_tenants_gc():
    """Tenant names are wire input: beyond max_tenants new names are not
    judged (admitted, counted as overflow), and tenants whose whole
    window aged out are garbage-collected — a quiet shed tenant gets its
    recovery transition on the way out."""
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    slo = _engine(max_tenants=2)
    for _ in range(50):
        slo.observe_request("t0", 99.0, now=1.0)
        slo.observe_request("t1", 1e-4, now=1.0)
        slo.observe_request("overflow", 99.0, now=1.0)  # beyond the cap
    assert slo.evaluate(now=1.0)["t0"]["signal"] == "shed"
    assert "overflow" not in slo._windows
    assert slo.admission("overflow", now=1.0) == "admit"
    assert telemetry.snapshot()[
        "serve.slo.tenant_overflow"]["value"] == 50
    # both tenants age out: state drops to zero and the shed tenant
    # transitions back to admit
    report = slo.evaluate(now=1000.0)
    assert report == {}
    assert slo._windows == {} and slo._signals == {}
    alerts = [e for e in sink.records if e["kind"] == "slo_alert"]
    assert ("shed", "admit") in {(a["prev_signal"], a["signal"])
                                 for a in alerts}
    # a returning tenant is judged fresh
    for _ in range(50):
        slo.observe_request("t0", 1e-4, now=1000.0)
    assert slo.evaluate(now=1000.0)["t0"]["signal"] == "admit"


def test_deferred_tenants_safe_against_concurrent_evaluate():
    """deferred_tenants() snapshots under the engine lock: a first-ever
    tenant's evaluate() inserting keys concurrently must never
    RuntimeError the scheduler loop's iteration."""
    import threading

    slo = _engine(min_requests=1)
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            for _ in range(2):
                slo.observe_request(f"t{i}", 99.0, now=float(i))
            slo.evaluate(now=float(i))
            i += 1

    def read():
        try:
            while not stop.is_set():
                slo.deferred_tenants()
        except RuntimeError as exc:  # pragma: no cover — the bug
            errors.append(exc)

    threads = [threading.Thread(target=churn),
               threading.Thread(target=read)]
    for t in threads:
        t.start()
    import time as _time

    _time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    assert errors == []


def test_evaluate_prunes_aged_entries_from_live_windows():
    """Evaluation cost must track the LIVE window, not the deque's
    high-water mark: entries older than the window are popped during
    evaluate (it runs synchronously inside submits, including on the
    server's event-loop thread)."""
    slo = _engine()
    for _ in range(100):
        slo.observe_request("t", 1e-4, now=1.0)
    for _ in range(5):
        slo.observe_request("t", 1e-4, now=100.0)
    report = slo.evaluate(now=105.0)  # window_s=30: the 100 aged out
    assert len(slo._windows["t"]) == 5
    assert report["t"]["requests"] == 5


def test_slo_alert_events_on_transitions_only():
    sink = telemetry.MemorySink()
    telemetry.enable()
    telemetry.add_sink(sink)
    slo = _engine()
    for _ in range(50):
        slo.observe_request("t", 99.0, now=1.0)
    slo.evaluate(now=1.0)   # admit -> shed: one alert
    slo.evaluate(now=2.0)   # steady state: silent
    slo.evaluate(now=500.0)  # window aged out: shed -> admit
    alerts = [e for e in sink.records if e["kind"] == "slo_alert"]
    assert [(a["prev_signal"], a["signal"]) for a in alerts] == \
        [("admit", "shed"), ("shed", "admit")]
    assert all(telemetry.validate_event(a) == [] for a in alerts)
    assert alerts[0]["tenant"] == "t"


# ---------------------------------------------------------------------------
# deferred-tenant assembly
# ---------------------------------------------------------------------------
def _req(tenant, shots, rng):
    return _Request(request_id=None, tenant=tenant, session="s",
                    syndromes=np.zeros((shots, 4), np.uint8),
                    future=Future(), t0=0.0)


def test_deferred_tenant_rides_spare_capacity_only():
    rng = np.random.default_rng(0)
    q = _SessionQueue()
    for _ in range(3):
        q.add(_req("noisy", 4, rng))
    for _ in range(3):
        q.add(_req("good", 4, rng))
    batch = assemble_round_robin(q, max_shots=16,
                                 deferred=frozenset({"noisy"}))
    # every admitted request first; the deferred tenant gets the leftover
    tenants = [r.tenant for r in batch]
    assert tenants[:3] == ["good", "good", "good"]
    assert tenants[3:] == ["noisy"]  # 16-shot cap: one deferred rides


def test_deferred_tenant_rides_even_when_admitted_request_too_big():
    """Spare capacity — not 'the admitted pass ran dry' — admits the
    deferred pass: when the NEXT admitted request is too big to fit, a
    smaller deferred request must still ride the leftover, else a
    sustained admitted flood starves 'defer' tenants outright (worse
    than shed, which at least fails fast)."""
    rng = np.random.default_rng(0)
    q = _SessionQueue()
    q.add(_req("flood", 12, rng))
    q.add(_req("flood", 12, rng))  # 12+12 > 16: ends the admitted pass
    q.add(_req("noisy", 4, rng))
    batch = assemble_round_robin(q, max_shots=16,
                                 deferred=frozenset({"noisy"}))
    assert [r.tenant for r in batch] == ["flood", "noisy"]
    # the unfitted admitted request stays queued for the next flush
    assert [r.tenant for qq in q.tenants.values() for r in qq] == ["flood"]


def test_deferred_tenant_alone_still_dispatches():
    rng = np.random.default_rng(0)
    q = _SessionQueue()
    q.add(_req("noisy", 4, rng))
    batch = assemble_round_robin(q, max_shots=16,
                                 deferred=frozenset({"noisy"}))
    assert [r.tenant for r in batch] == ["noisy"]  # deprioritized != starved


def test_batcher_sheds_offending_tenant_under_storm():
    """The acceptance scenario: a tenant burning its SLO budget is shed at
    submit while a healthy tenant keeps being admitted."""
    slo = _engine()
    bat = ContinuousBatcher({"hgp_rep3": _session()}, max_batch_shots=32,
                            max_wait_s=0.005, slo=slo)
    try:
        # synthetic storm: the engine sees the bad tenant blowing the
        # latency target, the good tenant well under it
        for _ in range(50):
            slo.observe_request("bad", 99.0)
            slo.observe_request("good", 1e-4)
        slo.evaluate()
        rng = np.random.default_rng(3)
        with pytest.raises(AdmissionError):
            bat.submit("hgp_rep3", _synd(CODE3, 2, rng), tenant="bad")
        fut = bat.submit("hgp_rep3", _synd(CODE3, 2, rng), tenant="good")
        assert fut.result(timeout=60).corrections.shape[0] == 2
    finally:
        bat.drain()


# ---------------------------------------------------------------------------
# ops endpoints
# ---------------------------------------------------------------------------
def test_healthz_and_varz_direct():
    bat = ContinuousBatcher({"hgp_rep3": _session()}, max_batch_shots=32,
                            max_wait_s=0.005)
    slo = _engine()
    ops = OpsServer(batcher=bat, slo=slo)
    rng = np.random.default_rng(1)
    bat.submit("hgp_rep3", _synd(CODE3, 2, rng)).result(timeout=60)
    body = ops.healthz()
    assert body["ok"] is True
    assert body["completed"] == 1 and body["failed"] == 0
    assert body["sessions"] == 1
    assert body["session_names"] == ["hgp_rep3"]
    assert body["last_dispatch_age_s"] is not None
    assert "slo" in body
    telemetry.enable()
    varz = ops.varz()
    assert set(varz) == {"metrics", "compile", "process"}
    assert varz["process"]["pid"] == os.getpid()
    bat.drain()
    assert ops.healthz()["ok"] is False  # stopped -> 503 body


def test_tracez_direct_query_and_filters():
    ctx = tracing.TraceContext()
    tracing.record_span("device_decode", ctx, dur_s=0.4)
    tracing.record_span("slice", ctx, dur_s=0.01)
    other = tracing.TraceContext()
    tracing.record_span("queue_wait", other, dur_s=0.001, ok=False,
                        error="boom")
    ops = OpsServer()
    by_id = ops.tracez({"trace_id": [ctx.trace_id]})
    assert by_id["trace_id"] == ctx.trace_id
    assert len(by_id["spans"]) == 2
    slow = ops.tracez({"slow_ms": ["100"]})
    assert [t["trace_id"] for t in slow["traces"]] == [ctx.trace_id]
    errored = ops.tracez({"errored": ["1"]})
    assert [t["trace_id"] for t in errored["traces"]] == [other.trace_id]
    assert ops.tracez({"limit": ["1"]})["traces"]


def test_ops_plane_live_http_round_trip():
    telemetry.enable()
    bat = ContinuousBatcher({"hgp_rep3": _session()}, max_batch_shots=32,
                            max_wait_s=0.005, slo=_engine())
    ops = start_ops_thread(batcher=bat, slo=bat.slo)
    try:
        host, port = ops.address
        base = f"http://{host}:{port}"
        rng = np.random.default_rng(2)
        ctx = tracing.TraceContext()
        bat.submit("hgp_rep3", _synd(CODE3, 3, rng),
                   trace=ctx).result(timeout=60)

        metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "serve_requests" in metrics.replace(".", "_")
        hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert hz["ok"] is True and hz["completed"] == 1
        varz = json.loads(urllib.request.urlopen(base + "/varz").read())
        assert "serve.requests" in varz["metrics"]
        tz = json.loads(urllib.request.urlopen(
            base + f"/tracez?trace_id={ctx.trace_id}").read())
        assert {s["name"] for s in tz["spans"]} >= {
            "queue_wait", "batch_assemble", "pad", "device_decode",
            "slice"}
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/nope")
        assert exc.value.code == 404
        bat.drain()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/healthz")
        assert exc.value.code == 503  # stopped service answers unhealthy
    finally:
        ops.stop()
        if not bat._stopped:
            bat.drain()


# ---------------------------------------------------------------------------
# end-to-end tracing through the TCP stack
# ---------------------------------------------------------------------------
def test_traced_request_full_stack_span_tree(tmp_path):
    """The acceptance scenario: a traced request through the real TCP
    server yields a COMPLETE span tree — stage spans under the
    serve.request root — retrievable by trace id from the telemetry JSONL
    and from /tracez, with zero warm-path retraces."""
    from telemetry_report import load_events, render_trace_tree

    jsonl = tmp_path / "serve.jsonl"
    sess = _session()
    sess.warm()
    telemetry.enable(str(jsonl))
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=32,
                            max_wait_s=0.005)
    handle = start_server_thread(bat)
    ops = start_ops_thread(batcher=bat)
    try:
        host, port = handle.address
        before = telemetry.compile_stats().get("jax.retraces", 0)
        with DecodeClient(host, port, traced=True) as cli:
            rng = np.random.default_rng(5)
            synd = _synd(CODE3, 4, rng)
            res = cli.decode("hgp_rep3", synd)
        assert res.trace_id  # echoed on the response
        assert telemetry.compile_stats().get("jax.retraces", 0) == before

        expected = {"queue_wait", "batch_assemble", "pad", "device_decode",
                    "slice", "respond", "serve.request"}
        # from the JSONL stream
        events = load_events(str(jsonl))
        spans = tracing.traces_from_records(events)[res.trace_id]
        assert {s["name"] for s in spans} == expected
        tree = tracing.trace_tree(spans)
        assert len(tree["roots"]) == 1  # everything under serve.request
        root = tree["roots"][0]
        assert root["span"]["name"] == "serve.request"
        assert {c["span"]["name"] for c in root["children"]} == \
            expected - {"serve.request"}
        rendered = render_trace_tree(spans)
        assert "serve.request" in rendered and "device_decode" in rendered
        # batch stages carry their amortization factor
        dd = next(s for s in spans if s["name"] == "device_decode")
        assert dd["amortized_over"] >= 1
        # every span event validates against the v4 schema
        assert all(telemetry.validate_event(s) == [] for s in spans)
        # from /tracez
        ohost, oport = ops.address
        tz = json.loads(urllib.request.urlopen(
            f"http://{ohost}:{oport}/tracez?trace_id={res.trace_id}")
            .read())
        assert {s["name"] for s in tz["spans"]} == expected
    finally:
        ops.stop()
        handle.stop(drain=True)


def test_untraced_frames_are_wire_compatible():
    """Old clients (no trace field) keep working and produce NO spans."""
    sess = _session()
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=32,
                            max_wait_s=0.005)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        with DecodeClient(host, port) as cli:
            rng = np.random.default_rng(6)
            res = cli.decode("hgp_rep3", _synd(CODE3, 2, rng))
        assert res.trace_id is None
        assert tracing.traces_from_records(
            tracing.recorder().snapshot()) == {}
    finally:
        handle.stop(drain=True)


def test_malformed_trace_field_does_not_fail_decode():
    sess = _session()
    bat = ContinuousBatcher({"hgp_rep3": sess}, max_batch_shots=32,
                            max_wait_s=0.005)
    handle = start_server_thread(bat)
    try:
        host, port = handle.address
        with DecodeClient(host, port) as cli:
            rng = np.random.default_rng(7)
            fut = cli.submit("hgp_rep3", _synd(CODE3, 2, rng))
            fut.result(timeout=60)
            # hand-roll a frame with a junk trace annotation
            from qldpc_fault_tolerance_tpu.serve.client import _Inflight

            with cli._plock:
                import time as _time

                req = _Inflight({}, _time.perf_counter())
                req.rids.add("junk-trace")
                cli._reqs["junk-trace"] = req
            cli._send({"op": "decode", "id": "junk-trace",
                       "session": "hgp_rep3",
                       "syndromes": _synd(CODE3, 2, rng).tolist(),
                       "trace": {"trace_id": 42}})
            res = req.future.result(timeout=60)
            assert res.corrections.shape[0] == 2
            assert res.trace_id is None  # dropped, not errored
    finally:
        handle.stop(drain=True)


def test_shed_tenant_answered_with_structured_error_over_tcp():
    slo = _engine()
    bat = ContinuousBatcher({"hgp_rep3": _session()}, max_batch_shots=32,
                            max_wait_s=0.005, slo=slo)
    handle = start_server_thread(bat)
    try:
        for _ in range(50):
            slo.observe_request("bad", 99.0)
        slo.evaluate()
        host, port = handle.address
        with DecodeClient(host, port, tenant="bad") as cli:
            rng = np.random.default_rng(8)
            ctx = tracing.TraceContext()
            with pytest.raises(RuntimeError) as exc:
                cli.decode("hgp_rep3", _synd(CODE3, 2, rng), trace=ctx)
            assert "AdmissionError" in str(exc.value)
            assert "bad" in str(exc.value)
        # a TRACED rejection still yields its root span: the refused
        # requests are exactly the ones an operator hunts in /tracez
        spans = tracing.traces_from_records(
            tracing.recorder().snapshot())[ctx.trace_id]
        assert len(spans) == 1
        root = spans[0]
        assert root["name"] == "serve.request"
        assert root["ok"] is False
        assert "AdmissionError" in root["error"]
        assert root["parent_id"] == ctx.span_id
    finally:
        handle.stop(drain=True)


# ---------------------------------------------------------------------------
# flight-recorder postmortem from a killed dispatch
# ---------------------------------------------------------------------------
def test_faultinject_killed_dispatch_ships_postmortem(tmp_path):
    """The acceptance scenario: a dispatch killed by fault injection
    produces a postmortem naming exactly the in-flight requests (ids,
    tenants, and their trace)."""
    tracing.configure(postmortem_dir=str(tmp_path))
    bat = ContinuousBatcher({"hgp_rep3": _session()}, max_batch_shots=64,
                            max_wait_s=0.02)
    plan = faultinject.FaultPlan([faultinject.Fault(
        site="serve_dispatch", kind="deterministic", after=0, count=1)])
    try:
        rng = np.random.default_rng(9)
        ctx = tracing.TraceContext()
        with plan.active():
            futs = [bat.submit("hgp_rep3", _synd(CODE3, 2, rng),
                               tenant="t0", request_id="req-a", trace=ctx),
                    bat.submit("hgp_rep3", _synd(CODE3, 3, rng),
                               tenant="t1", request_id="req-b")]
            for f in futs:
                with pytest.raises(faultinject.InjectedDeterministicFault):
                    f.result(timeout=60)
        dumps = list(tmp_path.glob(
            "postmortem-*-serve_dispatch_failed.jsonl"))
        assert len(dumps) == 1
        lines = [json.loads(x) for x in dumps[0].read_text().splitlines()]
        header = lines[0]
        assert header["reason"] == "serve_dispatch_failed"
        failure = next(r for r in lines if r["kind"] == "failure")
        assert sorted(failure["request_ids"]) == ["req-a", "req-b"]
        assert failure["tenants"] == ["t0", "t1"]
        # the ring the dump shipped holds the accepted requests AND the
        # injected fault that killed them
        kinds = {r["kind"] for r in lines}
        assert {"request", "fault_injected", "failure"} <= kinds
        reqs = [r for r in lines if r.get("kind") == "request"]
        assert any(r.get("trace_id") == ctx.trace_id for r in reqs)
        # the traced request's device_decode span carries the error
        spans = tracing.traces_from_records(
            tracing.recorder().snapshot())[ctx.trace_id]
        dd = next(s for s in spans if s["name"] == "device_decode")
        assert dd["ok"] is False and "Injected" in dd["error"]
    finally:
        bat.drain()
