import numpy as np
import pytest

import jax.numpy as jnp

from qldpc_fault_tolerance_tpu.codes import gf2, hgp, rep_code
from qldpc_fault_tolerance_tpu.ops import (
    bp_decode,
    build_tanner_graph,
    gf2_matmul,
    llr_from_probs,
)


def test_gf2_matmul_matches_numpy():
    rng = np.random.default_rng(0)
    h = (rng.random((20, 35)) < 0.2).astype(np.uint8)
    e = (rng.random((7, 35)) < 0.3).astype(np.uint8)
    got = np.asarray(gf2_matmul(jnp.asarray(e), jnp.asarray(h.T)))
    want = e @ h.T % 2
    assert np.array_equal(got, want)


def test_tanner_graph_roundtrip():
    h = np.array([[1, 1, 0, 1], [0, 1, 1, 0], [1, 0, 1, 1]], dtype=np.uint8)
    g = build_tanner_graph(h)
    chk_nbr = np.asarray(g.chk_nbr)
    chk_mask = np.asarray(g.chk_mask)
    # every nonzero of H appears exactly once in the row adjacency
    rebuilt = np.zeros_like(h)
    for i in range(h.shape[0]):
        for s in range(chk_nbr.shape[1]):
            if chk_mask[i, s]:
                rebuilt[i, chk_nbr[i, s]] ^= 1
    assert np.array_equal(rebuilt, h)
    # cross slot maps are mutually consistent
    var_nbr = np.asarray(g.var_nbr)
    var_slot = np.asarray(g.var_nbr_slot)
    chk_slot = np.asarray(g.chk_nbr_slot)
    for i in range(h.shape[0]):
        for s in range(chk_nbr.shape[1]):
            if not chk_mask[i, s]:
                continue
            j, t = chk_nbr[i, s], chk_slot[i, s]
            assert var_nbr[j, t] == i
            assert var_slot[j, t] == s


def test_minsum_single_check_hand_computed():
    # H = [1 1 1], llr = [1, 2, 3], syndrome = [1], scale = 1:
    # check->var msgs: v0: -min(2,3) = -2 ; v1: -min(1,3) = -1 ; v2: -min(1,2) = -1
    # posteriors: [-1, 1, 2] -> error = [1,0,0]; matches syndrome -> converged iter 1
    g = build_tanner_graph(np.array([[1, 1, 1]], dtype=np.uint8))
    p = 1.0 / (1.0 + np.exp(np.array([1.0, 2.0, 3.0])))  # probs giving those llrs
    res = bp_decode(
        g,
        jnp.asarray([[1]], dtype=jnp.uint8),
        llr_from_probs(p),
        max_iter=5,
        ms_scaling_factor=1.0,
    )
    assert np.array_equal(np.asarray(res.error)[0], [1, 0, 0])
    assert bool(res.converged[0])
    assert int(res.iterations[0]) == 1
    np.testing.assert_allclose(np.asarray(res.posterior_llr)[0], [-1.0, 1.0, 2.0], atol=1e-3)


def test_minsum_scaling_factor_applied():
    g = build_tanner_graph(np.array([[1, 1, 1]], dtype=np.uint8))
    p = 1.0 / (1.0 + np.exp(np.array([1.0, 2.0, 3.0])))
    res = bp_decode(
        g,
        jnp.asarray([[0]], dtype=jnp.uint8),
        llr_from_probs(p),
        max_iter=1,
        ms_scaling_factor=0.5,
        early_stop=False,
    )
    # zero syndrome: messages positive, scaled by 0.5: posteriors = llr + 0.5*min_excl
    np.testing.assert_allclose(
        np.asarray(res.posterior_llr)[0], [1 + 1.0, 2 + 0.5, 3 + 0.5], atol=1e-3
    )


@pytest.mark.parametrize("method", ["minimum_sum", "product_sum"])
def test_repetition_code_corrects_single_error(method):
    h = rep_code(7)
    g = build_tanner_graph(h)
    e = np.zeros(7, dtype=np.uint8)
    e[3] = 1
    synd = h @ e % 2
    res = bp_decode(
        g,
        jnp.asarray(synd[None]),
        llr_from_probs(np.full(7, 0.05)),
        max_iter=20,
        method=method,
    )
    assert bool(res.converged[0])
    assert np.array_equal(np.asarray(res.error)[0], e)


def test_converged_implies_syndrome_match_batch():
    rng = np.random.default_rng(42)
    code = hgp(rep_code(5), rep_code(5))  # d5 surface code
    h = code.hz
    g = build_tanner_graph(h)
    errs = (rng.random((64, code.N)) < 0.03).astype(np.uint8)
    synds = errs @ h.T % 2
    res = bp_decode(
        g, jnp.asarray(synds), llr_from_probs(np.full(code.N, 0.03)), max_iter=30
    )
    conv = np.asarray(res.converged)
    dec = np.asarray(res.error)
    assert conv.mean() > 0.5  # most low-weight shots converge
    resid_synd = dec @ h.T % 2
    assert np.array_equal(resid_synd[conv], synds[conv])


def test_decode_deterministic():
    h = rep_code(9)
    g = build_tanner_graph(h)
    synd = np.zeros((4, 8), dtype=np.uint8)
    synd[:, 2] = 1
    r1 = bp_decode(g, jnp.asarray(synd), llr_from_probs(np.full(9, 0.01)), max_iter=15)
    r2 = bp_decode(g, jnp.asarray(synd), llr_from_probs(np.full(9, 0.01)), max_iter=15)
    assert np.array_equal(np.asarray(r1.error), np.asarray(r2.error))
    # identical shots decode identically within the batch
    assert np.array_equal(np.asarray(r1.error)[0], np.asarray(r1.error)[3])


def test_nonuniform_channel_probs_break_ties():
    # two-bit check with syndrome 1: the more error-prone bit should be flipped
    h = np.array([[1, 1]], dtype=np.uint8)
    g = build_tanner_graph(h)
    res = bp_decode(
        g,
        jnp.asarray([[1]], dtype=jnp.uint8),
        llr_from_probs(np.array([0.01, 0.2])),
        max_iter=10,
    )
    assert np.array_equal(np.asarray(res.error)[0], [0, 1])


def test_two_phase_matches_plain_bp():
    """bp_decode_two_phase must be bit-identical to bp_decode, including when
    the overflow fallback triggers."""
    import jax
    import jax.numpy as jnp
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.ops import bp
    from qldpc_fault_tolerance_tpu.ops.linalg import gf2_matmul

    code = hgp(rep_code(5), rep_code(5))
    graph = bp.build_tanner_graph(code.hx)
    llr0 = bp.llr_from_probs(np.full(code.N, 0.05))
    for p, cap in ((0.02, 16), (0.3, 4)):  # low p: compaction; high p: overflow
        err = (jax.random.uniform(jax.random.PRNGKey(3), (128, code.N)) < p
               ).astype(jnp.uint8)
        synd = gf2_matmul(err, jnp.asarray(code.hx.T))
        a = bp.bp_decode(graph, synd, llr0, max_iter=30)
        b = bp.bp_decode_two_phase(graph, synd, llr0, max_iter=30,
                                   head_iters=4, tail_capacity=cap)
        assert np.array_equal(np.asarray(a.error), np.asarray(b.error))
        assert np.array_equal(np.asarray(a.converged), np.asarray(b.converged))


def test_two_phase_progressive_deepen_matches_plain_bp():
    """The progressive head-deepening branch (stragglers after the first
    head overflow every tail tier, but fit after the deepened head) must be
    bit-identical to plain bp_decode — the regime the BP+OSD bench point
    (p=0.05) exercises."""
    import jax
    import jax.numpy as jnp
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.ops import bp
    from qldpc_fault_tolerance_tpu.ops.linalg import gf2_matmul

    code = hgp(rep_code(5), rep_code(5))
    graph = bp.build_tanner_graph(code.hx)
    # heavy noise: conv@head(1) is low so n_bad overflows both tiers
    # (4, 16), engaging the deepen segment; after the 12-iteration deepened
    # head the stragglers fit tier 16 (measured: 50 bad@1, 15 bad@12)
    llr0 = bp.llr_from_probs(np.full(code.N, 0.03))
    err = (jax.random.uniform(jax.random.PRNGKey(9), (128, code.N)) < 0.03
           ).astype(jnp.uint8)
    synd = gf2_matmul(err, jnp.asarray(code.hx.T))
    a = bp.bp_decode(graph, synd, llr0, max_iter=30)
    b = bp.bp_decode_two_phase(graph, synd, llr0, max_iter=30,
                               head_iters=1, tail_capacity=4)
    # the branch structure: n_bad@1 must exceed the big tier (16) but fit
    # it after the 12-iteration deepened head (sanity of the scenario)
    it = np.asarray(a.iterations)
    conv = np.asarray(a.converged)
    assert int((~(conv & (it <= 1))).sum()) > 16, "scenario must overflow tiers"
    assert int((~(conv & (it <= 12))).sum()) <= 16, "scenario must fit deepen"
    for f in ("error", "converged", "iterations", "posterior_llr"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f
