"""Performance-attribution layer tests (ISSUE 6): cost-model capture
round-trip, waterfall accounting sums to wall clock, probe-harness
fallback on compile failure, VMEM calibration table consumption, the
bench_compare regression gate (synthetic regression + the checked-in
BENCH history), per-engine heartbeat events, and bit-exact WER with
profiling on vs off."""
import json
import os
import sys

import numpy as np
import pytest

from qldpc_fault_tolerance_tpu.utils import profiling, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


@pytest.fixture(autouse=True)
def _clean_profiling():
    """Every test starts with profiling+telemetry off, empty cost table,
    and the default calibration table; leaves nothing enabled behind."""
    profiling.disable()
    profiling.reset_costs()
    telemetry.disable()
    telemetry.reset()
    yield
    profiling.disable()
    profiling.reset_costs()
    profiling.reset_vmem_table_cache()
    telemetry.disable()
    telemetry.reset()


def _small_code():
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code

    return hgp(rep_code(3), rep_code(3))


def _data_sim(**kw):
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    code = _small_code()
    p = 0.05
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=10)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=10)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=32, seed=0, **kw)


# ---------------------------------------------------------------------------
# cost-model capture
# ---------------------------------------------------------------------------
def test_capture_jit_cost_roundtrip():
    import jax
    import jax.numpy as jnp

    profiling.enable()
    telemetry.enable()
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    cost = profiling.capture_jit_cost("unit.matmul", f, x)
    assert cost is not None
    assert cost.flops > 0 and cost.bytes_accessed > 0
    assert cost.peak_bytes >= cost.argument_bytes
    table = profiling.program_costs()
    assert "unit.matmul" in table
    assert table["unit.matmul"]["flops"] == cost.flops
    # published as telemetry gauges
    snap = telemetry.snapshot()
    assert snap["cost.unit.matmul.flops"]["value"] == cost.flops
    assert snap["cost.unit.matmul.peak_bytes"]["value"] == cost.peak_bytes


def test_capture_jit_cost_memoized_and_disabled():
    import jax
    import jax.numpy as jnp

    calls = []
    f = jax.jit(lambda x: x * 2)

    class Probe:
        def lower(self, *a, **k):
            calls.append(1)
            return f.lower(*a, **k)

    x = jnp.ones((8,))
    # disabled: no capture at all
    assert profiling.capture_jit_cost("unit.memo", Probe(), x) is None
    assert not calls
    profiling.enable()
    c1 = profiling.capture_jit_cost("unit.memo", Probe(), x)
    c2 = profiling.capture_jit_cost("unit.memo", Probe(), x)
    assert c1 is not None and c2 is not None
    assert len(calls) == 1  # second call hit the (label, avals) memo


def test_derive_utilization_consistency():
    cost = {"flops": 1e6, "bytes_accessed": 2e6, "peak_bytes": 123}
    peaks = {"flops_per_s": 1e12, "hbm_bytes_per_s": 1e11}
    util = profiling.derive_utilization(cost, 100, 1000.0, peaks=peaks)
    assert util["flops_per_shot"] == pytest.approx(1e4)
    assert util["bytes_per_shot"] == pytest.approx(2e4)
    # rate * per-shot / peak
    assert util["mfu"] == pytest.approx(1000 * 1e4 / 1e12)
    assert util["hbm_util"] == pytest.approx(1000 * 2e4 / 1e11)
    assert profiling.derive_utilization({}, 100, 1000.0) == {}


def test_cost_capture_in_real_run():
    """The megabatch driver auto-captures its program cost when profiling
    is enabled."""
    import jax

    sim = _data_sim()
    profiling.enable()
    sim.WordErrorRate(64, key=jax.random.PRNGKey(0))
    costs = profiling.program_costs()
    assert any(k.startswith("megabatch.") for k in costs), costs
    c = next(v for k, v in costs.items() if k.startswith("megabatch."))
    assert c["flops"] > 0


def test_cost_capture_fused_sweep():
    """The fused-cell driver (sweep/fused.py buckets) captures its program
    cost under its own label."""
    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily

    profiling.enable()
    CodeFamily(
        [hgp(rep_code(3), rep_code(3), name="r3")],
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        batch_size=32, seed=1,
    ).EvalWER("data", "Total", [0.02, 0.05], num_samples=32,
              if_plot=False, fused=True)
    costs = profiling.program_costs()
    assert any(k.startswith("fused_cells.") for k in costs), costs


# ---------------------------------------------------------------------------
# waterfall accounting
# ---------------------------------------------------------------------------
def test_engine_scope_accounting_sums():
    profiling.enable()
    with profiling.engine_scope("unit") as acct:
        assert acct is not None
        profiling.record_dispatch(0.25)
        profiling.record_dispatch(0.05)
        profiling.record_host_sync(0.2)
        wf = acct.waterfall(wall_s=1.0)
    stages = wf["stages"]
    assert stages["dispatch_launch_s"] == pytest.approx(0.30)
    assert stages["host_sync_s"] == pytest.approx(0.2)
    assert stages["host_gap_s"] == pytest.approx(0.5)
    assert wf["dispatch_gap_fraction"] == pytest.approx(0.5)
    assert wf["n_dispatches"] == 2 and wf["n_syncs"] == 1
    # stages decompose the wall exactly (passive mode: launch+sync+gap)
    assert sum(stages.values()) == pytest.approx(1.0)
    # no active scope -> records are dropped, heartbeat is None
    profiling.record_dispatch(99.0)
    assert profiling.run_heartbeat() is None


def test_engine_scope_inactive_when_disabled():
    with profiling.engine_scope("unit") as acct:
        assert acct is None
    # with only telemetry on, the scope still activates (heartbeats need it)
    telemetry.enable()
    with profiling.engine_scope("unit") as acct:
        assert acct is not None


def test_deep_timed_run_waterfall_sums_to_wall():
    """A deep-timed real run: device + sync + gap must reproduce the
    measured wall clock (the run decomposition is exact by construction,
    and device_s must dominate a compute-bound CPU run)."""
    import time

    import jax

    sim = _data_sim()
    key = jax.random.PRNGKey(1)
    sim.WordErrorRate(64, key=key)  # warm
    profiling.enable()
    sim.WordErrorRate(64, key=key)  # cost capture outside the timed run
    with profiling.deep_timing(), profiling.engine_scope("unit") as acct:
        t0 = time.perf_counter()
        sim.WordErrorRate(64, key=key)
        wf = acct.waterfall(time.perf_counter() - t0)
    st = wf["stages"]
    assert wf["deep_timed"] and "device_s" in st
    assert st["device_s"] > 0
    # stage values round to 6 decimals independently, so allow a few
    # ulp-of-rounding of absolute slop
    assert (st["device_s"] + st["host_sync_s"] + st["host_gap_s"]
            == pytest.approx(wf["wall_s"], abs=5e-6))
    assert 0 <= wf["dispatch_gap_fraction"] <= 1


def test_heartbeat_event_every_engine():
    """Tier-1 guard (ISSUE 6 satellite): every engine's WordErrorRate
    emits a heartbeat event with the waterfall stage decomposition when
    telemetry is enabled."""
    import jax

    from qldpc_fault_tolerance_tpu.decoders import (
        BPDecoder,
        ST_BP_Decoder_Circuit,
        ST_BP_Decoder_syndrome,
    )
    from qldpc_fault_tolerance_tpu.sim import (
        CodeSimulator_Circuit,
        CodeSimulator_Circuit_SpaceTime,
    )
    from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon
    from qldpc_fault_tolerance_tpu.sim.phenom_spacetime import (
        CodeSimulator_Phenon_SpaceTime,
    )

    code = _small_code()
    p = 0.03
    m = code.hx.shape[0]
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 0.004,
          "p_idling_gate": 0}

    def run_data():
        _data_sim().WordErrorRate(64, key=jax.random.PRNGKey(0))

    def run_phenom():
        ext = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
        extz = np.hstack([code.hz, np.eye(code.hz.shape[0],
                                          dtype=np.uint8)])
        sim = CodeSimulator_Phenon(
            code=code,
            decoder1_x=BPDecoder(extz, np.full(extz.shape[1], p),
                                 max_iter=6),
            decoder1_z=BPDecoder(ext, np.full(ext.shape[1], p), max_iter=6),
            decoder2_x=BPDecoder(code.hz, np.full(code.N, p), max_iter=6),
            decoder2_z=BPDecoder(code.hx, np.full(code.N, p), max_iter=6),
            pauli_error_probs=[p / 3] * 3, q=p, batch_size=32, seed=0)
        sim.WordErrorRate(num_rounds=2, num_samples=32)

    def run_circuit():
        hx_ext = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
        sim = CodeSimulator_Circuit(
            code=code,
            decoder1_z=BPDecoder(hx_ext, np.full(hx_ext.shape[1], p),
                                 max_iter=6),
            decoder2_z=BPDecoder(code.hx, np.full(code.N, p), max_iter=6),
            p=0.004, num_cycles=2, error_params=ep, batch_size=32, seed=7)
        sim.WordErrorRate(32, key=jax.random.PRNGKey(2))

    def run_circuit_st():
        sim = CodeSimulator_Circuit_SpaceTime(
            code=code, p=0.004, num_cycles=5, num_rep=2, error_params=ep,
            batch_size=32, seed=0)
        sim._generate_circuit()
        sim._generate_circuit_graph()
        g = sim.circuit_graph
        sim.decoder1_z = ST_BP_Decoder_Circuit(g["h1"], g["channel_ps1"],
                                               max_iter=6)
        sim.decoder2_z = ST_BP_Decoder_Circuit(g["h2"], g["channel_ps2"],
                                               max_iter=6)
        sim.WordErrorRate(32, key=jax.random.PRNGKey(3))

    def run_phenom_st():
        sim = CodeSimulator_Phenon_SpaceTime(
            code=code,
            decoder1_x=ST_BP_Decoder_syndrome(code.hz, p_data=p, p_synd=p,
                                              max_iter=6, num_rep=2),
            decoder1_z=ST_BP_Decoder_syndrome(code.hx, p_data=p, p_synd=p,
                                              max_iter=6, num_rep=2),
            decoder2_x=BPDecoder(code.hz, np.full(code.N, p), max_iter=6),
            decoder2_z=BPDecoder(code.hx, np.full(code.N, p), max_iter=6),
            pauli_error_probs=[p / 3] * 3, q=p, num_rep=2, batch_size=32,
            seed=0)
        sim.WordErrorRate(2, 32, key=jax.random.PRNGKey(4))

    engines = {
        "data": run_data,
        "phenl": run_phenom,
        "circuit": run_circuit,
        "circuit_st": run_circuit_st,
        "phenl_st": run_phenom_st,
    }
    for engine, run in engines.items():
        telemetry.disable()
        telemetry.reset()
        sink = telemetry.MemorySink()
        telemetry.enable()
        telemetry.add_sink(sink)
        try:
            run()
        finally:
            telemetry.remove_sink(sink)
            telemetry.disable()
        hbs = [r for r in sink.records
               if r["kind"] == "heartbeat" and r["engine"] == engine]
        assert hbs, f"engine {engine} emitted no heartbeat event"
        wf = hbs[-1].get("waterfall")
        assert wf and "stages" in wf and \
            wf.get("dispatch_gap_fraction") is not None, (engine, hbs[-1])


def test_wer_bitexact_profiling_on_vs_off():
    import jax

    sim = _data_sim()
    key = jax.random.PRNGKey(5)
    wer_off = sim.WordErrorRate(128, key=key)
    profiling.enable()
    with profiling.deep_timing():
        wer_on = sim.WordErrorRate(128, key=key)
    assert wer_on == wer_off


# ---------------------------------------------------------------------------
# VMEM probe harness + calibration table
# ---------------------------------------------------------------------------
def test_probe_max_block_picks_largest_working():
    def try_compile(b):
        if b > 128:
            raise RuntimeError("scoped vmem oom")
        return True

    best, attempts = profiling.probe_max_block(try_compile,
                                               (512, 256, 128, 64))
    assert best == 128
    # stops at the first success; failures recorded with their error
    assert [a[0] for a in attempts] == [512, 256, 128]
    assert attempts[0][1] is False and "oom" in attempts[0][2]
    assert attempts[-1][1] is True and attempts[-1][2] is None


def test_probe_max_block_fallback_when_nothing_compiles():
    def try_compile(b):
        raise RuntimeError("mosaic panic")

    best, attempts = profiling.probe_max_block(try_compile, (64, 32, 8))
    assert best == 0
    assert len(attempts) == 3 and not any(ok for _, ok, _ in attempts)


def test_vmem_table_lookup_and_fallbacks(tmp_path, monkeypatch):
    table = {
        "schema": 1,
        "ratios": {"bp_head": 1.83},
        "gates": {"bp_head_scat_limit_bytes": 12 * 1024 * 1024},
        "entries": [
            {"kernel": "bp_head", "rw": 6, "m": 100, "n": 400,
             "measured": True, "per_shot_bytes": 55555.0},
            {"kernel": "bp_head", "rw": 6, "m": 100, "n": 500,
             "measured": False, "per_shot_bytes": 77777.0},
        ],
    }
    path = tmp_path / "vmem_table.json"
    path.write_text(json.dumps(table))
    monkeypatch.setenv("QLDPC_VMEM_TABLE", str(path))
    profiling.reset_vmem_table_cache()
    # measured entry overrides the analytic default
    assert profiling.calibrated_per_shot_bytes(
        "bp_head", {"rw": 6, "m": 100, "n": 400}, 111.0) == 55555.0
    # unmeasured entries never override
    assert profiling.calibrated_per_shot_bytes(
        "bp_head", {"rw": 6, "m": 100, "n": 500}, 111.0) == 111.0
    # missing shape -> default; missing kernel ratio -> default
    assert profiling.calibrated_per_shot_bytes(
        "bp_head", {"rw": 1, "m": 2, "n": 3}, 42.0) == 42.0
    assert profiling.calibration_ratio("bp_head", 2.0) == 1.83
    assert profiling.calibration_ratio("nope", 2.0) == 2.0
    # corrupt table -> empty, everything falls back
    path.write_text("{not json")
    profiling.reset_vmem_table_cache()
    assert profiling.vmem_table() == {"entries": []}
    assert profiling.calibration_ratio("bp_head", 2.0) == 2.0


def test_bp_pallas_consumes_calibration(tmp_path, monkeypatch):
    """A measured calibration entry changes the head kernel's tile choice;
    a calibrated gate limit changes fits_vmem."""
    from qldpc_fault_tolerance_tpu.ops import bp, bp_pallas

    code = _small_code()
    graph = bp.build_tanner_graph_host(code.hx)
    pg = bp_pallas.build_pallas_head(graph)
    base_block = pg.max_block_b(4096)
    assert base_block > 0
    # a huge measured per-shot cost forces the tile to 0 (XLA fallback)
    table = {
        "schema": 1,
        "gates": {"bp_head_scat_limit_bytes": 1},
        "entries": [{
            "kernel": "bp_head", "rw": pg.rw, "m": pg.m, "n": pg.n,
            "measured": True, "per_shot_bytes": 1e9,
        }],
    }
    path = tmp_path / "t.json"
    path.write_text(json.dumps(table))
    monkeypatch.setenv("QLDPC_VMEM_TABLE", str(path))
    profiling.reset_vmem_table_cache()
    assert pg.per_shot_bytes() == 1e9
    assert pg.max_block_b(4096) == 0
    assert not pg.fits_vmem()  # 1-byte calibrated gate
    monkeypatch.delenv("QLDPC_VMEM_TABLE")
    profiling.reset_vmem_table_cache()
    assert pg.max_block_b(4096) == base_block


def test_gf2_vmem_gate(monkeypatch):
    """The calibrated VMEM gate routes infeasible shapes to the XLA twin
    instead of attempting a doomed mosaic compile."""
    from qldpc_fault_tolerance_tpu.ops import gf2_pallas

    code = _small_code()
    spec = gf2_pallas.build_fused_spec(code.hx, code.hz, code.lx, code.lz,
                                       (0.003,) * 3)
    # estimate grows monotonically with block_w and is feasible for the
    # small code at the default block
    e1 = gf2_pallas.estimate_vmem_bytes(
        code.N, code.hx.shape[0], code.hz.shape[0], 8)
    e2 = gf2_pallas.estimate_vmem_bytes(
        code.N, code.hx.shape[0], code.hz.shape[0], 16)
    assert 0 < e1 < e2
    assert gf2_pallas.vmem_feasible(spec, 8)
    # an infeasible estimate (shrunken cap) gates the pallas path off even
    # when backend/divisibility would allow it
    monkeypatch.setattr(gf2_pallas, "_KERNEL_VMEM_LIMIT", 1)
    assert not gf2_pallas.vmem_feasible(spec, 8)
    assert not gf2_pallas._use_pallas(4096, "auto", spec, 8)
    # explicit backend="pallas" stays an override (probe harnesses)
    assert gf2_pallas._use_pallas(4096, "pallas", spec, 8)


def test_checked_in_calibration_table_is_consistent():
    """The repo ships a generated table: schema 1, every entry carries its
    kernel + probe provenance, and CPU-generated entries never carry the
    consumed ``per_shot_bytes`` key (only TPU probes are evidence)."""
    path = os.path.join(REPO, "calibration", "vmem_table.json")
    assert os.path.exists(path), "calibration/vmem_table.json not checked in"
    with open(path) as fh:
        table = json.load(fh)
    assert table["schema"] == 1
    assert table["generated_by"] == "scripts/vmem_calibrate.py"
    assert table["entries"], "table has no entries"
    for e in table["entries"]:
        assert e["kernel"] in ("bp_head", "bp_head_v2", "fused_decode",
                               "gf2_sample_synd", "gf2_residual",
                               "osd_cs_sweep")
        assert "measured" in e and "attempts" in e
        if not e["measured"]:
            assert "per_shot_bytes" not in e
    # the big-code shapes the ROADMAP Open item 2 targets are probed
    probed_n = {e.get("n") for e in table["entries"]}
    assert {1225, 1600} <= probed_n


def test_note_unmeasured_gates_one_shot(tmp_path, monkeypatch):
    """ISSUE 20 satellite: a table shipping gates without probe evidence
    (gates_measured=false) surfaces ONCE at decoder construction — a
    counter sized by the gate count, a schema-valid ``unmeasured_gates``
    event — and re-arms only with the table cache."""
    path = tmp_path / "t.json"
    path.write_text(json.dumps({
        "schema": 1, "backend": "cpu", "generated_at": "2026-01-01",
        "entries": [], "ratios": {},
        "gates": {"a_limit": 1, "b_limit": 2}, "gates_measured": False}))
    monkeypatch.setenv("QLDPC_VMEM_TABLE", str(path))
    profiling.reset_vmem_table_cache()
    telemetry.enable()
    sink = telemetry.MemorySink()
    telemetry.add_sink(sink)
    try:
        assert profiling.note_unmeasured_gates() is True
        assert profiling.note_unmeasured_gates() is False  # one-shot
        snap = telemetry.snapshot()
        assert snap["calibration.unmeasured_gates"]["value"] == 2
        [ev] = [r for r in sink.records
                if r["kind"] == "unmeasured_gates"]
        assert telemetry.validate_event(ev) == []
        assert ev["gates"] == ["a_limit", "b_limit"]
    finally:
        telemetry.remove_sink(sink)
    # a measured table never notes
    path.write_text(json.dumps({
        "schema": 1, "entries": [], "gates": {"a_limit": 1},
        "gates_measured": True}))
    profiling.reset_vmem_table_cache()  # also re-arms the one-shot
    assert profiling.note_unmeasured_gates() is False


def test_vmem_calibrate_incremental_reuses_unchanged_entries(monkeypatch):
    """ISSUE 20 satellite: ``--incremental`` re-probes only (kernel, code)
    pairs whose fingerprint (jaxlib/backend/batch/shape) changed; carried
    entries are byte-identical."""
    import vmem_calibrate

    calls = []

    def fake(kernel):
        def probe(*a, **k):
            calls.append(kernel)
            return {"kernel": kernel, "measured": False, "attempts": []}
        return probe

    monkeypatch.setattr(vmem_calibrate, "_bp_head_probe",
                        lambda hx, t, b: fake("bp_head")())
    monkeypatch.setattr(vmem_calibrate, "_bp_head_v2_probe",
                        lambda hx, t, b: fake("bp_head_v2")())
    monkeypatch.setattr(
        vmem_calibrate, "_fused_decode_probe",
        lambda n, hx, hz, lx, lz, t, b: fake("fused_decode")())
    monkeypatch.setattr(vmem_calibrate, "_osd_cs_probe",
                        lambda n, hx, t, b: fake("osd_cs_sweep")())
    monkeypatch.setattr(
        vmem_calibrate, "_gf2_probe",
        lambda n, hx, hz, lx, lz, t, b: [fake("gf2_sample_synd")(),
                                         fake("gf2_residual")()])

    t1 = vmem_calibrate.build_table(["hgp_rep3"], quick=True)
    assert len(t1["entries"]) == 6
    assert len(calls) == 6
    assert all(e.get("fingerprint") for e in t1["entries"])

    # unchanged fingerprints: everything carries over, nothing re-probes
    calls.clear()
    t2 = vmem_calibrate.build_table(["hgp_rep3"], quick=True, prev=t1)
    assert calls == []
    assert t2["entries"] == t1["entries"]

    # the probe batch is part of the fingerprint: full re-probe
    calls.clear()
    t3 = vmem_calibrate.build_table(["hgp_rep3"], quick=False, prev=t1)
    assert len(calls) == 6
    assert all(e["fingerprint"] != o["fingerprint"]
               for e, o in zip(t3["entries"], t1["entries"]))

    # a legacy table without fingerprints is never trusted for reuse
    legacy = dict(t1)
    legacy["entries"] = [
        {k: v for k, v in e.items() if k != "fingerprint"}
        for e in t1["entries"]]
    calls.clear()
    vmem_calibrate.build_table(["hgp_rep3"], quick=True, prev=legacy)
    assert len(calls) == 6


# ---------------------------------------------------------------------------
# bench_compare regression gate
# ---------------------------------------------------------------------------
def _write_round(tmp_path, n, value, schema=1, unit="shots/s", extra=None):
    if schema == 1:
        obj = {"n": n, "cmd": "bench", "rc": 0,
               "parsed": {"metric": "m", "value": value, "unit": unit,
                          **(extra or {})}}
    else:
        obj = {"schema": 2, "round": n,
               "result": {"metric": "m", "value": value, "unit": unit,
                          **(extra or {})}}
    p = tmp_path / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps(obj))
    return str(p)


def test_bench_compare_gate_fires_on_synthetic_regression(tmp_path):
    import bench_compare

    paths = [
        _write_round(tmp_path, 1, 1000.0),
        _write_round(tmp_path, 2, 1100.0, schema=2),   # mixed schemas OK
        _write_round(tmp_path, 3, 700.0),              # -36%: regression
    ]
    assert bench_compare.main(paths + ["--tolerance", "10"]) == 0  # no gate
    assert bench_compare.main(paths + ["--gate", "--tolerance", "10"]) == 1
    # improvements and in-band noise pass
    ok = [
        _write_round(tmp_path, 4, 1000.0),
        _write_round(tmp_path, 5, 980.0),
        _write_round(tmp_path, 6, 2000.0, schema=2),
    ]
    assert bench_compare.main(ok + ["--gate", "--tolerance", "10"]) == 0


def test_bench_compare_gates_stage_fields_and_wallclock(tmp_path):
    import bench_compare

    # stage-rate field regression fires even when the headline holds
    paths = [
        _write_round(tmp_path, 1, 1000.0,
                     extra={"sample_synd_shots_per_s": {"packed": 500.0}}),
        _write_round(tmp_path, 2, 1000.0,
                     extra={"sample_synd_shots_per_s": {"packed": 300.0}}),
    ]
    assert bench_compare.main(paths + ["--gate"]) == 1
    # wall-clock metrics regress UP
    wall = [
        _write_round(tmp_path, 3, 100.0, unit="s"),
        _write_round(tmp_path, 4, 150.0, unit="s"),
    ]
    assert bench_compare.main(wall + ["--gate"]) == 1
    wall_ok = [
        _write_round(tmp_path, 5, 100.0, unit="s"),
        _write_round(tmp_path, 6, 95.0, unit="s"),
    ]
    assert bench_compare.main(wall_ok + ["--gate"]) == 0
    # the rendered labels must AGREE with the gate for wall-clock rounds:
    # a speedup (time down) renders improved, a slowdown REGRESSED
    fast = bench_compare.compare(bench_compare.load_history([
        _write_round(tmp_path, 7, 100.0, unit="s"),
        _write_round(tmp_path, 8, 70.0, unit="s")]), 10.0)
    assert "REGRESSED" not in bench_compare.render(fast)
    assert not fast["violations"]
    slow = bench_compare.compare(bench_compare.load_history([
        _write_round(tmp_path, 9, 100.0, unit="s"),
        _write_round(tmp_path, 10, 130.0, unit="s")]), 10.0)
    assert "REGRESSED" in bench_compare.render(slow)
    assert slow["violations"]


def test_bench_compare_gate_passes_checked_in_history(capsys):
    """Tier-1 guard (ISSUE 6 acceptance): the r01..r05 history gates
    clean — r01->r02 is a 10x improvement, r02..r05 sit within the band."""
    import bench_compare

    paths = sorted(
        os.path.join(REPO, f)
        for f in os.listdir(REPO)
        if f.startswith("BENCH_r") and f.endswith(".json"))
    assert len(paths) >= 5
    assert bench_compare.main(paths + ["--gate"]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out and "r01" in out


def test_bench_compare_normalize_rejects_junk():
    import bench_compare

    assert bench_compare.normalize_round({"foo": 1}) is None
    assert bench_compare.normalize_round({"parsed": {"metric": "m"}}) is None
    rec = bench_compare.normalize_round(
        {"metric": "m", "value": 1.0, "unit": "shots/s"}, fallback_round=7)
    assert rec["round"] == 7 and rec["schema"] == 0


# ---------------------------------------------------------------------------
# percentiles (observability + telemetry_report spans)
# ---------------------------------------------------------------------------
def test_timings_percentiles():
    from qldpc_fault_tolerance_tpu.utils.observability import (
        _TIMINGS,
        _TIMINGS_LOCK,
        reset_timings,
        timings,
    )

    reset_timings()
    with _TIMINGS_LOCK:
        _TIMINGS["stage"] = [0.01] * 90 + [0.5] * 9 + [1.0]
    t = timings()["stage"]
    assert t["count"] == 100
    assert t["p50_s"] == pytest.approx(0.01)
    assert 0.01 < t["p95_s"] <= 0.5
    assert t["max_s"] == pytest.approx(1.0)
    assert t["p50_s"] <= t["p95_s"] <= t["max_s"]
    reset_timings()


def test_telemetry_report_span_percentiles(tmp_path):
    import telemetry_report

    telemetry.enable()
    for v in (0.001, 0.002, 0.003, 0.5):
        telemetry.registry().histogram("span.unit.seconds").observe(v)
    snap = telemetry.snapshot()
    events = [{"ts": 0.0, "kind": "snapshot", "metrics": snap,
               "compile": {}}]
    summary = telemetry_report.summarize(events)
    span = summary["spans"]["unit"]
    assert span["p50_s"] is not None and span["p95_s"] is not None
    assert span["p50_s"] <= span["p95_s"]
    assert "p50_s" in telemetry_report.render(summary)


# ---------------------------------------------------------------------------
# trace parser (synthetic chrome trace)
# ---------------------------------------------------------------------------
def test_parse_trace_synthetic(tmp_path):
    trace = {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "python"}},
            {"ph": "X", "name": "fusion.1", "pid": 1, "dur": 2000},
            {"ph": "X", "name": "fusion.1", "pid": 1, "dur": 1000},
            {"ph": "X", "name": "host_compute", "pid": 2, "dur": 500},
            {"ph": "B", "name": "ignored", "pid": 2},
        ],
    }
    d = tmp_path / "plugins"
    d.mkdir()
    (d / "run.trace.json").write_text(json.dumps(trace))
    out = profiling.parse_trace(str(tmp_path))
    assert out["files"] == 1
    assert out["device_s"] == pytest.approx(0.003)
    assert out["host_s"] == pytest.approx(0.0005)
    assert out["events"]["fusion.1"] == pytest.approx(0.003)
    # empty dir -> empty summary, no crash
    empty = profiling.parse_trace(str(tmp_path / "nope"))
    assert empty["files"] == 0 and empty["events"] == {}
