"""Benchmark: decoded shots/sec on the code-capacity pipeline.

Config matches BASELINE.json config 1 / the north star: hgp_34 family code,
depolarizing noise p=0.01, 50-iteration min-sum BP, full pipeline per shot
(sample -> both syndromes -> BP decode both sectors -> residual
stabilizer/logical checks), all on device.

Baseline: the reference sustains ~36 shots/s on a laptop CPU pool with
BP+OSD (Single-Shot checkpoint cell 4: 16k shots in 449.7 s); vs_baseline is
measured against that figure.  Prints ONE json line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _bench_code():
    """Prefer the regenerated hgp_34_n625 (north-star config); fall back to
    the shipped n225."""
    from qldpc_fault_tolerance_tpu.codes import load_code, load_pickle_code

    here = os.path.dirname(os.path.abspath(__file__))
    n625 = os.path.join(here, "codes_lib_tpu", "hgp_34_n625.npz")
    if os.path.exists(n625):
        return load_code(n625)
    return load_pickle_code("/root/reference/codes_lib/hgp_34_n225.pkl")


def main():
    import jax

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = _bench_code()
    p = 0.01
    batch = int(os.environ.get("BENCH_BATCH", "16384"))
    n_batches = int(os.environ.get("BENCH_BATCHES", "128"))
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=50)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=50)
    sim = CodeSimulator_DataError(
        code=code,
        decoder_x=dec_x,
        decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3],
        batch_size=batch,
        seed=0,
        # the whole timed run is one scan dispatch + one host sync (the
        # tunneled chip pays ~50-100ms per dispatch/fetch round-trip)
        scan_chunk=n_batches,
    )

    key = jax.random.PRNGKey(123)
    # warmup / compile (same compiled scan shape as the timed run)
    sim.WordErrorRate(n_batches * batch, key=jax.random.fold_in(key, 0))
    # timed steady state; median of 3 runs for a stable number
    shots = n_batches * batch
    times = []
    for rep in range(3):
        t0 = time.perf_counter()
        sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1 + rep))
        times.append(time.perf_counter() - t0)
    rate = shots / sorted(times)[1]

    baseline_rate = 36.0  # reference CPU shots/s (SURVEY §6)
    print(
        json.dumps(
            {
                "metric": f"decoded shots/sec/chip ({code.name or 'hgp'}, N={code.N}, BP-50, p=0.01)",
                "value": round(rate, 1),
                "unit": "shots/s",
                "vs_baseline": round(rate / baseline_rate, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
