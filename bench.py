"""Benchmark: decoded shots/sec on the code-capacity pipeline.

Config matches BASELINE.json config 1 / the north star: hgp_34 family code,
depolarizing noise p=0.01, 50-iteration min-sum BP, full pipeline per shot
(sample -> both syndromes -> BP decode both sectors -> residual
stabilizer/logical checks), all on device.

Baseline: the reference sustains ~36 shots/s on a laptop CPU pool with
BP+OSD (Single-Shot checkpoint cell 4: 16k shots in 449.7 s); vs_baseline is
measured against that figure.  Prints ONE json line.
"""
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


@contextlib.contextmanager
def _no_env_jsonl():
    """Suppress the QLDPC_TELEMETRY_JSONL fallback around bench-internal
    enable() calls: an operator streaming parity sweeps must not have bench
    A/B events appended to their file, and the per-event flush of a JSONL
    sink inside a timed region would inflate the measured overhead."""
    saved = os.environ.pop("QLDPC_TELEMETRY_JSONL", None)
    try:
        yield
    finally:
        if saved is not None:
            os.environ["QLDPC_TELEMETRY_JSONL"] = saved


@contextlib.contextmanager
def _tele_region():
    """Fresh telemetry region for a bench counters pass: reset + enable
    (enable() re-baselines the retrace fallback itself; the env JSONL
    fallback is suppressed), and ALWAYS disable — an exception inside one
    mode must not leak the enabled switch into the next."""
    from qldpc_fault_tolerance_tpu.utils import telemetry

    with _no_env_jsonl():
        telemetry.reset()
        telemetry.enable()
        try:
            yield
        finally:
            telemetry.disable()


def _tele_counters_block(snap=None, stats=None, **extra):
    """Uniform ``telemetry`` block for the BENCH json: headline counters
    from the registry snapshot + retrace count (utils.telemetry)."""
    from qldpc_fault_tolerance_tpu.utils import telemetry

    snap = telemetry.snapshot() if snap is None else snap
    stats = telemetry.compile_stats() if stats is None else stats

    def val(name):
        return snap.get(name, {}).get("value", 0)

    it = snap.get("bp.iterations", {})
    bp_shots = val("bp.shots")
    return {
        "shots": val("sim.shots"),
        "failures": val("sim.failures"),
        "dispatches": val("driver.dispatches"),
        "bp_converged_fraction": (round(val("bp.converged") / bp_shots, 4)
                                  if bp_shots else None),
        "bp_iterations_mean": (round(it["mean"], 2)
                               if it.get("mean") is not None else None),
        "osd_invocations": val("osd.invocations"),
        "osd_shots": val("osd.shots") + val("osd.device_shots"),
        # device-resident OSD accounting (ISSUE 13): shots the in-carry OSD
        # stage decoded, host round-trips (0 for default BPOSD pipelines),
        # and the straggler-compaction tier occupancy
        "osd_device_shots": val("osd.device_shots"),
        "osd_host_round_trips": val("osd.host_round_trips"),
        "osd_tiers": {"none": val("osd.tier_none"),
                      "compacted": val("osd.tier_compacted"),
                      "full": val("osd.tier_full")},
        "retraces": stats.get("jax.retraces", 0),
        **extra,
    }


def _bench_code():
    """Prefer the regenerated hgp_34_n625 (north-star config); fall back to
    the shipped n225."""
    from qldpc_fault_tolerance_tpu.codes import load_code, load_pickle_code

    here = os.path.dirname(os.path.abspath(__file__))
    n625 = os.path.join(here, "codes_lib_tpu", "hgp_34_n625.npz")
    if os.path.exists(n625):
        return load_code(n625)
    return load_pickle_code("/root/reference/codes_lib/hgp_34_n225.pkl")


def _bp_utilization(dec_x, dec_z, code, p, rate, key):
    """LEGACY hand-modeled utilization fields for a decode rate (VERDICT
    round-2 #6; roofline reconciled per VERDICT round-3 #6).  Since ISSUE 6
    the headline ``mfu`` / ``hbm_util`` come from the MEASURED XLA cost
    model (utils.profiling, ``_cost_model_block``); these keys emit with a
    ``_legacy`` suffix for one more round of cross-checking and then go.

    Decodes one diagnostic batch per sector to measure the real iteration
    distribution, then models the HBM traffic the decode ACTUALLY pays:

      * when the decoder's two-phase Pallas path runs (mirrored branch by
        branch from ops/bp.py, constants imported from there), the head,
        progressive-deepen segment AND straggler tail are all VMEM-resident
        — messages never touch HBM and the kernel's HBM cost is its I/O:
        syndromes in (m_s bytes/shot), error out (n), posterior LLRs out
        (4n), flags (~8) per sector;
      * branches that fall off the Pallas path stream the padded message
        planes (m_s*rw_s + n*cw_s f32 elements) ~3x per iteration: the
        XLA tail (when the compacted capacity has no feasible Pallas
        tile), the full-batch fallback (measured straggler count above the
        big tier even after the deepened head), and plain streaming
        decode (two_phase disabled / small batch / small max_iter);
      * mfu_proxy uses ~8 flops/edge/iteration over the measured MEAN
        iteration count (head work included — flops are paid in VMEM too).

    Component accounting for the headline mode (measured round 4,
    scripts/profile_bp.py, batch 16384 at p=0.01): the full fused pipeline
    runs at the same rate as sample+syndrome ALONE — 98% of shots converge
    within 2-3 head iterations (mean 1.35), so the whole BP stage is a
    3-iteration VMEM kernel plus a B/16 tail, and the pipeline is bound by
    the PRNG sampler + syndrome SpMV + fixed per-dispatch latency of the
    tunneled chip, NOT by HBM.  The round-3 model (50 streamed XLA
    iterations -> 149KB/shot -> hbm_util 0.26) double-counted traffic the
    VMEM head never pays; the corrected model reports the ~2-20KB/shot the
    chip actually moves, and the honest conclusion is that hbm_util is
    SMALL because the workload's arithmetic intensity is high (VMEM reuse),
    not because bandwidth is wasted.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.ops import bp as bp_mod

    diag_b = 4096
    iters_mean_all = []
    bytes_per_shot = 0.0
    edges = int(code.hx.sum() + code.hz.sum())
    for dec, h in ((dec_x, code.hz), (dec_z, code.hx)):
        err = jax.random.bernoulli(key, 2 * p / 3, (diag_b, code.N))
        synd = (err.astype(jnp.uint8) @ jnp.asarray(h.T)) % 2
        res = dec.bp_batch_device(synd.astype(jnp.uint8))
        it = np.asarray(res.iterations, np.float64)
        iters_mean_all.append(float(it.mean()))
        m_s, n_s = h.shape
        planes = m_s * int(h.sum(1).max()) + n_s * int(h.sum(0).max())
        io_bytes = m_s + n_s + 4 * n_s + 8  # synd + error + posterior + flags
        # Mirror bp_batch_device's ACTUAL branch structure (constants
        # imported from ops/bp.py so this model cannot silently rot):
        head = bp_mod.TWO_PHASE_HEAD_ITERS
        pallas = getattr(dec, "_pallas_head", None)
        two_phase_runs = (getattr(dec, "two_phase", True)
                          and diag_b >= bp_mod.TWO_PHASE_MIN_BATCH
                          and dec.max_iter >= bp_mod.TWO_PHASE_MIN_ITER)
        pallas_runs = (two_phase_runs and pallas is not None
                       and pallas.max_block_b(diag_b) > 0)
        if pallas_runs:
            # head/deepen/tail are VMEM-resident (tail reuses
            # bp_head_pallas with early_stop): the kernel's HBM cost is its
            # I/O unless a branch falls off the Pallas path —
            # (a) straggler tail whose compacted capacity has no feasible
            #     Pallas tile streams via XLA; (b) the full-batch fallback
            #     (stragglers exceed the big tier even after the deepened
            #     head) streams the whole batch.
            tail_cap = max(1, diag_b // bp_mod.TWO_PHASE_TAIL_DIV)
            big_tier = tail_cap * bp_mod.TWO_PHASE_BIG_TIER_MULT
            head2 = bp_mod.two_phase_head2_iters(head, dec.max_iter)
            stream_per_iter = 3 * 4 * planes
            tail_streams = pallas.max_block_b(tail_cap) == 0
            n_bad_head = float((~((it <= head))).mean()) * diag_b
            n_bad_deep = float((it > head2).mean()) * diag_b
            if n_bad_deep > big_tier:          # full-batch XLA fallback
                bytes_per_shot += io_bytes + it.mean() * stream_per_iter
            elif tail_streams:                 # XLA tail on stragglers
                tail_frac = min(n_bad_head, big_tier) / diag_b
                tail_it = float(it[it > head].mean()) if n_bad_head else 0.0
                bytes_per_shot += io_bytes + tail_frac * tail_it * \
                    stream_per_iter
            else:                              # all-VMEM
                bytes_per_shot += io_bytes
        elif two_phase_runs:
            # XLA two-phase: head + compacted tail stream message planes
            tail_frac = float((it > head).mean())
            tail_it = float(it[it > head].mean()) if tail_frac else 0.0
            bytes_per_shot += io_bytes + (
                min(it.mean(), head) + tail_frac * tail_it) * 3 * 4 * planes
        else:
            bytes_per_shot += io_bytes + it.mean() * 3 * 4 * planes
    iters_mean = float(np.mean(iters_mean_all))
    flops_per_shot = 8 * edges * iters_mean
    return {
        "bp_iters_per_shot": round(iters_mean, 2),
        "model_bytes_per_shot_legacy": int(bytes_per_shot),
        "hbm_gbps_legacy": round(rate * bytes_per_shot / 1e9, 1),
        "hbm_util_legacy": round(rate * bytes_per_shot / 819e9, 3),
        "mfu_proxy_legacy": round(rate * flops_per_shot / 197e12, 6),
    }


def _sample_synd_rates(code, p, batch, key):
    """Measured shots/s of the sample→syndrome stage alone, all three
    substrates: dense uint8 planes, packed lane words (bit-exact same
    draws), and the fused counter-PRNG path (ops/gf2_pallas, own stream,
    syndromes-only writes).  A scalar reduction forces materialization
    without adding a transfer."""
    import jax
    import jax.numpy as jnp

    from qldpc_fault_tolerance_tpu.noise import (
        depolarizing_xz,
        depolarizing_xz_packed,
    )
    from qldpc_fault_tolerance_tpu.ops import gf2_pallas
    from qldpc_fault_tolerance_tpu.ops.gf2_packed import packed_parity_apply
    from qldpc_fault_tolerance_tpu.ops.linalg import ParityOp

    hx, hz = ParityOp(code.hx), ParityOp(code.hz)
    probs = (p / 3, p / 3, p / 3)
    spec = gf2_pallas.build_fused_spec(code.hx, code.hz, code.lx, code.lz,
                                       probs)

    @jax.jit
    def dense(k):
        ex, ez = depolarizing_xz(k, (batch, code.N), probs)
        return hx(ez).sum(dtype=jnp.int32) + hz(ex).sum(dtype=jnp.int32)

    @jax.jit
    def packed(k):
        exp, ezp = depolarizing_xz_packed(k, (batch, code.N), probs)
        a = packed_parity_apply(hx.nbr, hx.mask, ezp)
        b = packed_parity_apply(hz.nbr, hz.mask, exp)
        pc = jax.lax.population_count
        return pc(a).sum(dtype=jnp.int32) + pc(b).sum(dtype=jnp.int32)

    @jax.jit
    def fused(k):
        sx, sz = gf2_pallas.sample_syndrome(spec, k, batch,
                                            emit_errors=False)
        pc = jax.lax.population_count
        return pc(sx).sum(dtype=jnp.int32) + pc(sz).sum(dtype=jnp.int32)

    out = {}
    for name, f in (("dense", dense), ("packed", packed), ("fused", fused)):
        f(key).block_until_ready()
        times = []
        for rep in range(5):
            t0 = time.perf_counter()
            f(jax.random.fold_in(key, rep)).block_until_ready()
            times.append(time.perf_counter() - t0)
        out[name] = round(batch / sorted(times)[2], 1)
    return out


def _device_stage_times(sim, key, reps=5):
    """Blocked per-stage device times of ONE pipeline batch (the
    sample→syndrome / BP / residual-check split of the waterfall).

    Measures cumulative prefixes of the engine's own jitted pipeline
    (sample+syndrome, +decode, full stats) and differences them — the
    boundaries then can't disagree about where work materializes.  Uses
    the sim's actual substrate (packed/dense) and decoder statics."""
    import jax
    import jax.numpy as jnp

    from qldpc_fault_tolerance_tpu.noise import (
        depolarizing_xz,
        depolarizing_xz_packed,
    )
    from qldpc_fault_tolerance_tpu.ops.gf2_packed import packed_parity_apply
    from qldpc_fault_tolerance_tpu.sim import data_error as de
    from qldpc_fault_tolerance_tpu.utils import profiling

    batch = sim.batch_size
    # pin the fused-sampler flag OFF: the sample/bp prefixes below measure
    # the packed (or dense) pipeline, so the full-stats prefix must run
    # the SAME substrate — differencing a fused-pipeline total against a
    # packed-sampler prefix would misattribute the stage split (and clamp
    # the residual stage to 0) under BENCH_FUSED=1
    cfg = sim._cfg(batch)[:6] + (False, False)
    state = sim._dev_state
    probs = tuple(sim.channel_probs)

    if sim._packed:
        @jax.jit
        def f_sample(k):
            ex_p, ez_p = depolarizing_xz_packed(k, (batch, sim.N), probs)
            szp = packed_parity_apply(state["hx_par"][0],
                                      state["hx_par"][1], ez_p)
            sxp = packed_parity_apply(state["hz_par"][0],
                                      state["hz_par"][1], ex_p)
            return sxp.sum(dtype=jnp.int32) + szp.sum(dtype=jnp.int32)

        sbp = jax.jit(de._sample_and_bp_packed, static_argnums=0)
    else:
        @jax.jit
        def f_sample(k):
            ex, ez = depolarizing_xz(k, (batch, sim.N), probs)
            sz = de._parity(state["hx_par"], ez)
            sx = de._parity(state["hz_par"], ex)
            return sx.sum(dtype=jnp.int32) + sz.sum(dtype=jnp.int32)

        sbp = jax.jit(de._sample_and_bp, static_argnums=0)
    full = jax.jit(de._stats_one_batch, static_argnums=0)

    cum = profiling.measure_stages([
        ("sample_syndrome", lambda: f_sample(key)),
        ("plus_bp", lambda: sbp(cfg, state, key)),
        ("pipeline", lambda: full(cfg, state, key)),
    ], reps=reps)
    return {
        "sample_syndrome": cum["sample_syndrome"],
        "bp": max(0.0, cum["plus_bp"] - cum["sample_syndrome"]),
        "residual": max(0.0, cum["pipeline"] - cum["plus_bp"]),
    }


def _profiling_blocks(sim, shots, key, wer_main, rate):
    """The ISSUE-6 performance-attribution blocks of the bp mode:

      * ``profiling``   — interleaved on/off A/B (the <2% overhead gate;
        profiling is host-side only, so WER must be bit-exact on vs off);
      * ``cost_model``  — MEASURED flops/bytes of the megabatch program
        (``compiled.cost_analysis()`` captured by the driver) normalized
        per scan-body batch — the XLA cost model counts loop bodies ONCE,
        so one inner batch is the honest unit — with ``mfu`` /
        ``hbm_util`` derived from the measured rate (these replace the
        hand-modeled ``*_legacy`` fields);
      * ``waterfall``   — per-stage device times of one pipeline batch
        (sample→syndrome→BP→residual), plus a deep-timed run decomposition
        (dispatch launch / device / host sync / gap) whose
        ``dispatch_gap_fraction`` quantifies how idle the chip is between
        dispatches.

    BENCH_PROF=0 skips all three (mirroring BENCH_TELE/BENCH_AB)."""
    import jax

    from qldpc_fault_tolerance_tpu.utils import profiling

    if os.environ.get("BENCH_PROF", "1") == "0":
        skip = {"skipped": "BENCH_PROF=0"}
        return {"profiling": skip, "cost_model": skip, "waterfall": skip}

    # --- overhead A/B: order-alternating min-of-4 (BASELINE.md protocol;
    # sequential A/B showed ±30% phantom deltas on a shared CPU).  The
    # one-time cost capture (extra lower+compile) is paid in the warmup,
    # outside the timed reps.
    profiling.reset_costs()
    profiling.enable()
    sim.WordErrorRate(shots, key=jax.random.fold_in(key, 0))  # capture+warm
    profiling.disable()
    times_off, times_on, wer_prof = [], [], [None]

    def _rep(arm_on: bool):
        if arm_on:
            profiling.enable()
        try:
            t0 = time.perf_counter()
            wer = sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
            dt = time.perf_counter() - t0
        finally:
            profiling.disable()
        (times_on if arm_on else times_off).append(dt)
        if arm_on:
            wer_prof[0] = wer

    try:
        for rep in range(4):
            first, second = (False, True) if rep % 2 == 0 else (True, False)
            _rep(first)
            _rep(second)
    finally:
        profiling.disable()
    wer_prof = wer_prof[0]
    rate_off = shots / min(times_off)
    rate_on = shots / min(times_on)
    prof_block = {
        "enabled_shots_per_s": round(rate_on, 1),
        "disabled_shots_per_s": round(rate_off, 1),
        "overhead_pct": round((rate_off - rate_on) / rate_off * 100, 2),
        "wer_bitexact_vs_disabled": bool(wer_prof[0] == wer_main[0]
                                         and wer_prof[1] == wer_main[1]),
    }

    # --- measured cost model -> mfu / hbm_util -------------------------
    costs = profiling.program_costs()
    label = next((k for k in costs if k.startswith("megabatch.")),
                 next(iter(costs), None))
    cost_block = {"skipped": "no program cost captured"}
    if label is not None:
        util = profiling.derive_utilization(costs[label], sim.batch_size,
                                            rate)
        cost_block = {
            "program": label,
            "backend": costs[label].get("backend"),
            "normalization": "per scan-body batch "
                             "(XLA cost model counts loop bodies once)",
            "peaks": profiling.device_peaks(),
            **util,
        }

    # --- stage + run waterfall (deep-timed attribution pass) -----------
    stages = _device_stage_times(sim, jax.random.fold_in(key, 97))
    dev_total = sum(stages.values()) or 1.0
    profiling.enable()
    try:
        with profiling.deep_timing(), profiling.engine_scope("bench.bp") \
                as acct:
            t0 = time.perf_counter()
            sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
            run_wf = acct.waterfall(time.perf_counter() - t0)
    finally:
        profiling.disable()
    waterfall = {
        "device_stages_s_per_batch": {k: round(v, 6)
                                      for k, v in stages.items()},
        "device_stage_fractions": {k: round(v / dev_total, 4)
                                   for k, v in stages.items()},
        "run": run_wf,
        "dispatch_gap_fraction": run_wf["dispatch_gap_fraction"],
    }
    return {"profiling": prof_block, "cost_model": cost_block,
            "waterfall": waterfall}


def mode_bp():
    """Headline: plain-BP code-capacity throughput (BASELINE.json config 1 /
    the 1e6 shots/s north star).

    Default arm is the bit-packed GF(2) pipeline (ops/gf2_packed, 32 shots
    per uint32 lane) through the dispatch-amortized megabatch driver; a
    dense-uint8 A/B arm runs the SAME config + key and the result records
    both rates plus the bit-exactness of the packed WER (the packed layer's
    acceptance gate).  Env knobs: BENCH_BATCH / BENCH_BATCHES (shapes),
    BENCH_PACKED=0 (dense headline), BENCH_FUSED=1 (opt-in counter-PRNG
    fused sampler — its own PRNG stream, so the A/B equality field is
    skipped), BENCH_AB=0 (skip the dense arm)."""
    import jax

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = _bench_code()
    p = 0.01
    batch = int(os.environ.get("BENCH_BATCH", "16384"))
    n_batches = int(os.environ.get("BENCH_BATCHES", "128"))
    packed = os.environ.get("BENCH_PACKED", "1") != "0"
    # the fused sampler rides on the packed substrate; BENCH_PACKED=0 wins.
    # BENCH_FUSED=1 -> two-dispatch v1 fused path, BENCH_FUSED=2 -> the
    # whole-pipeline fused v2 program (sample->syndrome->BP->residual in
    # one kernel per megabatch tile, ISSUE 9)
    fused = ({"1": True, "2": "v2"}.get(os.environ.get("BENCH_FUSED", "0"),
                                        False) if packed else False)
    run_ab = os.environ.get("BENCH_AB", "1") != "0"
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=50)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=50)

    def make_sim(packed_arm):
        return CodeSimulator_DataError(
            code=code,
            decoder_x=dec_x,
            decoder_z=dec_z,
            pauli_error_probs=[p / 3, p / 3, p / 3],
            batch_size=batch,
            seed=0,
            # the whole timed run is one megabatch dispatch + one host sync
            # (the tunneled chip pays ~50-100ms per dispatch/fetch
            # round-trip)
            scan_chunk=n_batches,
            packed=packed_arm,
            # NOTE: not `fused and packed_arm` — fused may be the string
            # "v2", and `"v2" and True` evaluates to True (the v1 path)
            fused_sampler=fused if packed_arm else False,
        )

    sim = make_sim(packed)
    key = jax.random.PRNGKey(123)
    # warmup / compile (same compiled scan shape as the timed run)
    sim.WordErrorRate(n_batches * batch, key=jax.random.fold_in(key, 0))
    # timed steady state; median of 3 runs for a stable number
    shots = n_batches * batch
    times, wer_main = [], None
    for rep in range(3):
        t0 = time.perf_counter()
        wer_rep = sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
        times.append(time.perf_counter() - t0)
        wer_main = wer_rep
    rate = shots / sorted(times)[1]

    # telemetry A/B arm — the <2% overhead acceptance gate of ISSUE 2.
    # Same config/key/median-of-3 protocol, but the on/off reps INTERLEAVE
    # (off, on, off, on, ...) so machine drift hits both arms equally; a
    # sequential A-then-B run showed ±30% phantom deltas on a shared CPU.
    # The telemetry fold is part of the compiled program, so the enabled
    # arm gets its own warmup.  BENCH_TELE=0 skips the arm (7 extra
    # full-size runs) for quick perf checks, mirroring BENCH_AB.
    from qldpc_fault_tolerance_tpu.utils import telemetry

    if os.environ.get("BENCH_TELE", "1") != "0":
        try:
            with _no_env_jsonl():
                telemetry.reset()
                telemetry.enable()
                sim.WordErrorRate(shots, key=jax.random.fold_in(key, 0))
                telemetry.disable()
                times_off, times_tel, wer_tel = [], [], None
                for rep in range(3):
                    t0 = time.perf_counter()
                    sim.WordErrorRate(
                        shots, key=jax.random.fold_in(key, 1))
                    times_off.append(time.perf_counter() - t0)
                    telemetry.reset()  # counters = final enabled rep only
                    telemetry.enable()
                    t0 = time.perf_counter()
                    wer_tel = sim.WordErrorRate(
                        shots, key=jax.random.fold_in(key, 1))
                    times_tel.append(time.perf_counter() - t0)
                    telemetry.disable()
        finally:
            telemetry.disable()  # never leak the switch into later modes
        rate_off = shots / sorted(times_off)[1]
        rate_tel = shots / sorted(times_tel)[1]
        # snapshot()/compile_stats() read the registry regardless of the
        # switch, so the block sees the final enabled rep's counters
        tele_block = _tele_counters_block(
            enabled_shots_per_s=round(rate_tel, 1),
            disabled_shots_per_s=round(rate_off, 1),
            overhead_pct=round((rate_off - rate_tel) / rate_off * 100, 2),
            wer_bitexact_vs_disabled=bool(wer_tel[0] == wer_main[0]
                                          and wer_tel[1] == wer_main[1]),
        )
    else:
        tele_block = {"skipped": "BENCH_TELE=0"}

    # resilience A/B arm — the <2% zero-fault-overhead acceptance gate of
    # ISSUE 3.  The wrapped path (engine-level retry closure + per-dispatch
    # guard + fault-injection site checks) is ALWAYS compiled in; the
    # togglable part is the active RetryPolicy, so the off arm scopes
    # policy_override(None) (pure pass-through).  Same interleaved
    # median-of-3 protocol as the telemetry arm (sequential A/B showed
    # ±30% phantom deltas on a shared CPU); no warmup needed — the policy
    # is host-side only, both arms run the same compiled program.
    from qldpc_fault_tolerance_tpu.utils import resilience as _res

    if os.environ.get("BENCH_RES", "1") != "0":
        # order ALTERNATES per rep (off/on, on/off, ...) so slow machine
        # drift cancels instead of biasing one arm; min-of-4 per arm (the
        # quiet-rep protocol BASELINE.md uses for the telemetry A/B) keeps
        # load spikes from reading as policy overhead
        times_off_res, times_on_res, wer_res = [], [], None

        def _rep_off():
            with _res.policy_override(None):
                t0 = time.perf_counter()
                sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
                times_off_res.append(time.perf_counter() - t0)

        def _rep_on():
            nonlocal wer_res
            t0 = time.perf_counter()
            wer_res = sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
            times_on_res.append(time.perf_counter() - t0)

        for rep in range(4):
            first, second = ((_rep_off, _rep_on) if rep % 2 == 0
                             else (_rep_on, _rep_off))
            first()
            second()
        rate_res_off = shots / min(times_off_res)
        rate_res_on = shots / min(times_on_res)
        pol = _res.current_policy()
        res_block = {
            "wrapped_shots_per_s": round(rate_res_on, 1),
            "unwrapped_shots_per_s": round(rate_res_off, 1),
            "overhead_pct": round(
                (rate_res_off - rate_res_on) / rate_res_off * 100, 2),
            "wer_bitexact_vs_unwrapped": bool(
                wer_res[0] == wer_main[0] and wer_res[1] == wer_main[1]),
            "policy": (None if pol is None else {
                "max_attempts": pol.max_attempts,
                "base_delay_s": pol.base_delay,
                "watchdog_s": pol.watchdog_s,
            }),
        }
    else:
        res_block = {"skipped": "BENCH_RES=0"}

    # diagnostics A/B arm — the <2% overhead acceptance gate of ISSUE 7's
    # statistical-observability layer.  Diagnostics ride the telemetry
    # event stream, so BOTH arms run telemetry-enabled (whose own overhead
    # the telemetry block already gates); the toggled part is the
    # uncertainty enrichment itself (Wilson intervals on wer_run/heartbeat
    # events + cell-scope capture, forced off via diagnostics.disable()).
    # Same order-alternating min-of-4 protocol as the resilience/profiling
    # arms (BASELINE.md: sequential A/B showed ±30% phantom deltas on a
    # shared CPU).  BENCH_DIAG=0 skips the arm.
    from qldpc_fault_tolerance_tpu.utils import diagnostics as _diag

    if os.environ.get("BENCH_DIAG", "1") != "0":
        times_doff, times_don, wer_diag = [], [], None
        try:
            with _no_env_jsonl():
                telemetry.reset()
                telemetry.enable()
                # warm: the telemetry-enabled program variant is already
                # compiled by the telemetry arm; one rep settles caches
                sim.WordErrorRate(shots, key=jax.random.fold_in(key, 0))

                def _rep_diag(arm_on: bool):
                    nonlocal wer_diag
                    if arm_on:
                        _diag.enable()
                    else:
                        _diag.disable()
                    try:
                        t0 = time.perf_counter()
                        wer = sim.WordErrorRate(
                            shots, key=jax.random.fold_in(key, 1))
                        dt = time.perf_counter() - t0
                    finally:
                        _diag.auto()
                    (times_don if arm_on else times_doff).append(dt)
                    if arm_on:
                        wer_diag = wer

                for rep in range(4):
                    first, second = ((False, True) if rep % 2 == 0
                                     else (True, False))
                    _rep_diag(first)
                    _rep_diag(second)
        finally:
            _diag.auto()
            telemetry.disable()
        rate_doff = shots / min(times_doff)
        rate_don = shots / min(times_don)
        diag_block = {
            "enabled_shots_per_s": round(rate_don, 1),
            "disabled_shots_per_s": round(rate_doff, 1),
            "overhead_pct": round(
                (rate_doff - rate_don) / rate_doff * 100, 2),
            "wer_bitexact_vs_disabled": bool(
                wer_diag[0] == wer_main[0] and wer_diag[1] == wer_main[1]),
        }
    else:
        diag_block = {"skipped": "BENCH_DIAG=0"}

    # time-series scraper A/B arm — the <2% overhead acceptance gate of
    # ISSUE 17's fleet observability plane.  The scraper + alert engine
    # ride the telemetry registry, so BOTH arms run telemetry-enabled (the
    # switch's own cost is gated by the telemetry arm above); the toggled
    # part is a live background Scraper on an aggressive 50 ms interval
    # with the default alert rules evaluated on every tick — 100x the
    # production 5 s cadence, so a pass here bounds the real deployment
    # with margin.  Same order-alternating min-of-4 protocol as the other
    # arms.  BENCH_TS=0 skips.
    from qldpc_fault_tolerance_tpu.serve import ops as _ops
    from qldpc_fault_tolerance_tpu.utils import timeseries as _ts

    if os.environ.get("BENCH_TS", "1") != "0":
        times_tsoff, times_tson, wer_ts = [], [], None
        scraper = _ts.Scraper(interval_s=0.05, retention=4096)
        engine = _ops.AlertEngine(rules=_ops.default_alert_rules(0.05))
        engine.attach(scraper)
        try:
            with _no_env_jsonl():
                telemetry.reset()
                telemetry.enable()
                # warm: the telemetry-enabled program variant is already
                # compiled by the telemetry arm; one rep settles caches
                sim.WordErrorRate(shots, key=jax.random.fold_in(key, 0))

                def _rep_ts(arm_on: bool):
                    nonlocal wer_ts
                    if arm_on:
                        scraper.start()
                    try:
                        t0 = time.perf_counter()
                        wer = sim.WordErrorRate(
                            shots, key=jax.random.fold_in(key, 1))
                        dt = time.perf_counter() - t0
                    finally:
                        if arm_on:
                            scraper.stop()
                    (times_tson if arm_on else times_tsoff).append(dt)
                    if arm_on:
                        wer_ts = wer

                for rep in range(4):
                    first, second = ((False, True) if rep % 2 == 0
                                     else (True, False))
                    _rep_ts(first)
                    _rep_ts(second)
                # counters survive disable(): snapshot() reads the registry
                # regardless of the switch
                n_scrapes = telemetry.snapshot().get(
                    "timeseries.scrapes", {}).get("value", 0)
        finally:
            scraper.stop()
            telemetry.disable()
        rate_tsoff = shots / min(times_tsoff)
        rate_tson = shots / min(times_tson)
        ts_block = {
            "scraper_on_shots_per_s": round(rate_tson, 1),
            "scraper_off_shots_per_s": round(rate_tsoff, 1),
            "overhead_pct": round(
                (rate_tsoff - rate_tson) / rate_tsoff * 100, 2),
            "wer_bitexact_vs_off": bool(
                wer_ts[0] == wer_main[0] and wer_ts[1] == wer_main[1]),
            "scrape_interval_s": 0.05,
            "scrapes": int(n_scrapes),
            "alert_rules": len(engine.rules()),
            "alerts_firing": engine.firing(),
        }
    else:
        ts_block = {"skipped": "BENCH_TS=0"}

    # --- BP kernel v1/v2 A/B arm (ISSUE 9): same sim config + key, the
    # decoders pinned to each Pallas generation (dense one-hot stack vs
    # sparse index-gather incidence).  The two kernels share one arithmetic
    # (ops/bp_pallas._minsum_plane_loop), so WER must be bit-exact across
    # arms.  Order-alternating min-of-4 per the BASELINE.md A/B protocol.
    # Meaningful only where the kernels actually serve (TPU): when both
    # arms resolve to the same variant (CPU -> xla_twin) the arm is skipped
    # with the resolved variant recorded.  BENCH_KERNEL_AB=0 skips.
    from qldpc_fault_tolerance_tpu.sim.common import joint_kernel_variant

    def make_kernel_sim(bp_kernel, quantize=None):
        dx = BPDecoder(code.hz, np.full(code.N, p), max_iter=50,
                       bp_kernel=bp_kernel, quantize=quantize)
        dz = BPDecoder(code.hx, np.full(code.N, p), max_iter=50,
                       bp_kernel=bp_kernel, quantize=quantize)
        # A/B arms pin the NON-fused substrate: under BENCH_FUSED=2 the
        # fused-v2 program runs BP inside the kernel and only lifts
        # (max_iter, msf, quantize) off the statics — a bp_kernel pin
        # would not change the executed program and the arm would
        # benchmark noise as a kernel delta
        return CodeSimulator_DataError(
            code=code, decoder_x=dx, decoder_z=dz,
            pauli_error_probs=[p / 3, p / 3, p / 3], batch_size=batch,
            seed=0, scan_chunk=n_batches, packed=packed,
            fused_sampler=False), dx, dz

    def ab_min4(sim_a, sim_b):
        """Order-alternating min-of-4 of two sims on the main key; returns
        (rate_a, rate_b, wer_a, wer_b)."""
        sim_a.WordErrorRate(shots, key=jax.random.fold_in(key, 0))  # warm
        sim_b.WordErrorRate(shots, key=jax.random.fold_in(key, 0))
        times_a, times_b, wers = [], [], [None, None]

        def run_arm(s, times, slot):
            t0 = time.perf_counter()
            wers[slot] = s.WordErrorRate(shots,
                                         key=jax.random.fold_in(key, 1))
            times.append(time.perf_counter() - t0)

        for rep in range(4):
            order = [(sim_a, times_a, 0), (sim_b, times_b, 1)]
            if rep % 2:
                order.reverse()
            for s, t, slot in order:
                run_arm(s, t, slot)
        return (shots / min(times_a), shots / min(times_b),
                wers[0], wers[1])

    bp_kernel_variant = joint_kernel_variant(dec_x, dec_z,
                                             batch_size=batch)
    if os.environ.get("BENCH_KERNEL_AB", "1") != "0":
        sim_v1, d1x, d1z = make_kernel_sim("v1")
        sim_v2, d2x, d2z = make_kernel_sim("v2")
        var_v1 = joint_kernel_variant(d1x, d1z, batch_size=batch)
        var_v2 = joint_kernel_variant(d2x, d2z, batch_size=batch)
        if var_v1 == var_v2:
            kernel_ab = {"skipped": f"both arms resolve to {var_v1} "
                                    "(kernels only serve on TPU)"}
        else:
            try:
                r_v1, r_v2, wer_v1, wer_v2 = ab_min4(sim_v1, sim_v2)
                kernel_ab = {
                    "v1_shots_per_s": round(r_v1, 1),
                    "v2_shots_per_s": round(r_v2, 1),
                    "v2_speedup_vs_v1": round(r_v2 / r_v1, 2),
                    "v1_variant": var_v1,
                    "v2_variant": var_v2,
                    "wer_bitexact_v1_vs_v2": bool(
                        wer_v1[0] == wer_v2[0] and wer_v1[1] == wer_v2[1]),
                }
            except Exception as e:  # an arm failing must not kill the round
                kernel_ab = {"error": f"{type(e).__name__}: {e}"[:300]}
    else:
        kernel_ab = {"skipped": "BENCH_KERNEL_AB=0"}

    # --- int8 quantization A/B arm (BENCH_QUANT=1): quantize="int8"
    # decoders against the main arm, WER gated by the documented
    # quantization contract (ops/bp_pallas.int8_parity_tolerance) instead
    # of bit-exactness — int8 is a different numeric decoder by design.
    if os.environ.get("BENCH_QUANT", "0") == "1":
        from qldpc_fault_tolerance_tpu.ops.bp_pallas import (
            INT8_WER_RTOL, int8_parity_tolerance)

        try:
            sim_f32, _, _ = make_kernel_sim(None)
            sim_q, dqx, dqz = make_kernel_sim(None, quantize="int8")
            r_f32, r_q, wer_f32, wer_q = ab_min4(sim_f32, sim_q)
            tol = int8_parity_tolerance(wer_f32[0], shots)
            quant_ab = {
                "f32_shots_per_s": round(r_f32, 1),
                "int8_shots_per_s": round(r_q, 1),
                "int8_speedup_vs_f32": round(r_q / r_f32, 2),
                "int8_variant": joint_kernel_variant(dqx, dqz,
                                                     batch_size=batch),
                "wer_f32": wer_f32[0],
                "wer_int8": wer_q[0],
                "wer_abs_delta": abs(wer_q[0] - wer_f32[0]),
                "wer_tolerance": tol,
                "wer_rtol": INT8_WER_RTOL,
                "wer_parity_ok": bool(abs(wer_q[0] - wer_f32[0]) <= tol),
            }
        except Exception as e:  # an arm failing must not kill the round
            quant_ab = {"error": f"{type(e).__name__}: {e}"[:300]}
    else:
        quant_ab = {"skipped": "BENCH_QUANT!=1"}

    out_ab = {}
    if run_ab:
        # dense-uint8 A/B arm: same shapes, same key, same median-of-3
        # timing protocol as the main arm -> the packed arm must be
        # bit-exact (identical WER tuple) and faster
        other = make_sim(not packed)
        other.WordErrorRate(shots, key=jax.random.fold_in(key, 0))  # warmup
        times_other, wer_other = [], None
        for rep in range(3):
            t0 = time.perf_counter()
            wer_other = other.WordErrorRate(shots,
                                            key=jax.random.fold_in(key, 1))
            times_other.append(time.perf_counter() - t0)
        rate_other = shots / sorted(times_other)[1]
        # label the main arm by what actually ran: the fused sampler is a
        # different substrate (own PRNG stream), not the packed layer
        main = (("fused_v2" if fused == "v2" else "fused") if fused
                else ("packed" if packed else "dense"))
        ab_other = "dense" if packed else "packed"
        out_ab = {
            f"{main}_shots_per_s": round(rate, 1),
            f"{ab_other}_shots_per_s": round(rate_other, 1),
            f"{main}_speedup_vs_{ab_other}": round(rate / rate_other, 2),
        }
        if not fused:  # fused sampler is a different PRNG stream
            out_ab["wer_bitexact_vs_dense"] = bool(
                wer_main[0] == wer_other[0] and wer_main[1] == wer_other[1])

    # performance-attribution blocks (ISSUE 6): overhead A/B, measured
    # cost model (the mfu/hbm_util that replace the legacy hand model),
    # and the stage/run waterfall with dispatch_gap_fraction
    with _no_env_jsonl():
        prof_blocks = _profiling_blocks(sim, shots, key, wer_main, rate)

    # sample+syndrome stage traffic model: the dense path writes two uint8
    # error planes, both syndrome planes, and re-reads the errors for the
    # residual checks; the packed path moves the same planes as uint32 lane
    # words — 1 bit/shot/plane, an 8x byte drop (BASELINE.md "Packed
    # bitplane layout")
    mx, mz = code.hx.shape[0], code.hz.shape[0]
    dense_bps = 4 * code.N + mx + mz
    baseline_rate = 36.0  # reference CPU shots/s (SURVEY §6)
    cost_block = prof_blocks["cost_model"]
    return {
        "metric": f"decoded shots/sec/chip ({code.name or 'hgp'}, N={code.N}, BP-50, p=0.01)",
        "value": round(rate, 1),
        "unit": "shots/s",
        "vs_baseline": round(rate / baseline_rate, 1),
        "packed": packed,
        "fused_sampler": fused,
        # ISSUE 9: which BP kernel served the headline arm (the decoders'
        # resolved routing — dense_onehot/sparse_gather/sparse_int8/
        # xla_twin), plus the kernel and quantization A/B blocks
        "bp_kernel_variant": bp_kernel_variant,
        "kernel_ab": kernel_ab,
        "quant_ab": quant_ab,
        "dispatches_per_run": int(sim.last_dispatches),
        "shots_per_dispatch": batch * min(n_batches, sim._scan_chunk),
        "sample_synd_bytes_per_shot_dense": dense_bps,
        "sample_synd_bytes_per_shot_packed": round(dense_bps / 8, 1),
        "sample_synd_shots_per_s": _sample_synd_rates(
            code, p, batch, jax.random.fold_in(key, 98)),
        # headline utilization: MEASURED cost model, not the hand model
        "mfu": cost_block.get("mfu"),
        "hbm_util": cost_block.get("hbm_util"),
        "hbm_gbps": cost_block.get("hbm_gbps"),
        "telemetry": tele_block,
        "resilience": res_block,
        "diagnostics": diag_block,
        "timeseries_ab": ts_block,
        **prof_blocks,
        **out_ab,
        **_bp_utilization(dec_x, dec_z, code, p, rate,
                          jax.random.fold_in(key, 99)),
    }


def _osd_device_host_ab():
    """Device-vs-host BPOSD A/B: the SAME decode_batch workload (full
    BP+OSD pipeline) through the device-resident OSD stage vs the demoted
    host C++/numpy rung, order-alternating with min-of-4 readings per arm
    (single-reading A/B swings on a shared host — serve-bench protocol).
    The shape is deliberately CPU-feasible (small surface code, order-10
    OSD-E) so the block is measured — never fabricated — on the CPU
    container too; every compared shot is additionally checked for cost
    parity against the numpy-oracle semantics (bit-equal, or a float32/64
    cost tie on a syndrome-consistent candidate)."""
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.decoders.osd import _channel_cost
    from qldpc_fault_tolerance_tpu.utils import telemetry

    code = hgp(rep_code(5), rep_code(5))
    h = code.hz
    n = code.N
    p = 0.12  # high enough that a sizable fraction of shots reach OSD
    shots = 512
    rng = np.random.default_rng(13)
    errs = (rng.random((shots, n)) < p).astype(np.uint8)
    synds = (errs @ h.T % 2).astype(np.uint8)

    def make(device):
        return BPOSD_Decoder(h, np.full(n, p), max_iter=6,
                             osd_method="osd_e", osd_order=10,
                             device_osd=device)

    dev, host = make(True), make(False)
    out_dev = dev.decode_batch(synds)    # warmup (compiles) + parity data
    out_host = host.decode_batch(synds)
    times = {"device": [], "host": []}
    arms = [("device", dev), ("host", host)]
    for r in range(4):
        for name, dec in (arms if r % 2 == 0 else arms[::-1]):
            t0 = time.perf_counter()
            dec.decode_batch(synds)
            times[name].append(time.perf_counter() - t0)
    rate_dev = shots / min(times["device"])
    rate_host = shots / min(times["host"])
    # cost parity on every compared shot: bit-equal, or float-tied cost on
    # a syndrome-consistent candidate (the documented f32-vs-f64 boundary)
    cost = _channel_cost(np.full(n, p))
    exact = (out_dev == out_host).all(axis=1)
    synd_ok = ((out_dev @ h.T % 2) == synds).all(axis=1)
    tie = np.abs((out_dev * cost[None]).sum(1)
                 - (out_host * cost[None]).sum(1)) < 1e-4
    parity_ok = bool((exact | (tie & synd_ok)).all())
    # the device arm must really have run on device: zero host round-trips
    # AND zero silent host fallbacks (the resilience rung would otherwise
    # make this an honest-looking host-vs-host comparison)
    with _tele_region():
        dev.decode_batch(synds)
        snap = telemetry.snapshot()
    rt = snap.get("osd.host_round_trips", {}).get("value", 0)
    fb = snap.get("osd.host_fallbacks", {}).get("value", 0)
    return {
        "workload": f"decode_batch BP+OSD(osd_e,10) {shots} shots "
                    f"(surface d5, N={n}, p={p})",
        "device_shots_per_s": round(rate_dev, 1),
        "host_shots_per_s": round(rate_host, 1),
        "device_vs_host": round(rate_dev / rate_host, 2),
        "cost_parity_ok": parity_ok,
        "exact_match_fraction": round(float(exact.mean()), 4),
        "device_host_round_trips": int(rt),
        "device_host_fallbacks": int(fb),
        "device_path_ok": bool(rt == 0 and fb == 0),
        "readings": 4,
        "protocol": "order-alternating, min-of-4 per arm",
    }


def _osd_cs_device_host_ab():
    """Device-vs-host OSD-CS A/B (ISSUE 19): the SAME decode_batch
    workload (full BP + order-10 combination sweep) through the batched
    device sweep vs the demoted host combination loop, order-alternating
    with min-of-4 readings per arm (serve-bench protocol).  Every
    compared shot is WER/cost-parity checked against the host's
    enumeration semantics (bit-equal, or a float32/64 cost tie on a
    syndrome-consistent candidate — the documented boundary), and the
    device arm is asserted to really run on device (zero host
    round-trips, zero silent fallbacks)."""
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.decoders.osd import _channel_cost
    from qldpc_fault_tolerance_tpu.ops.osd_cs_device import cs_sweep_shape
    from qldpc_fault_tolerance_tpu.utils import telemetry

    code = hgp(rep_code(5), rep_code(5))
    h = code.hz
    n = code.N
    # higher p than the osd_e arm: nearly every shot reaches OSD, so the
    # block measures the combination sweep itself, not the shared BP stage
    p = 0.2
    shots = 512
    rng = np.random.default_rng(29)
    errs = (rng.random((shots, n)) < p).astype(np.uint8)
    synds = (errs @ h.T % 2).astype(np.uint8)

    def make(device):
        return BPOSD_Decoder(h, np.full(n, p), max_iter=6,
                             osd_method="osd_cs", osd_order=10,
                             device_osd=device)

    dev, host = make(True), make(False)
    out_dev = dev.decode_batch(synds)    # warmup (compiles) + parity data
    out_host = host.decode_batch(synds)
    times = {"device": [], "host": []}
    arms = [("device", dev), ("host", host)]
    for r in range(4):
        for name, dec in (arms if r % 2 == 0 else arms[::-1]):
            t0 = time.perf_counter()
            dec.decode_batch(synds)
            times[name].append(time.perf_counter() - t0)
    rate_dev = shots / min(times["device"])
    rate_host = shots / min(times["host"])
    cost = _channel_cost(np.full(n, p))
    exact = (out_dev == out_host).all(axis=1)
    synd_ok = ((out_dev @ h.T % 2) == synds).all(axis=1)
    tie = np.abs((out_dev * cost[None]).sum(1)
                 - (out_host * cost[None]).sum(1)) < 1e-4
    parity_ok = bool((exact | (tie & synd_ok)).all())
    with _tele_region():
        dev.decode_batch(synds)
        snap = telemetry.snapshot()
    rt = snap.get("osd.host_round_trips", {}).get("value", 0)
    fb = snap.get("osd.host_fallbacks", {}).get("value", 0)
    st = dev.device_static
    n_cand, n_chunks = cs_sweep_shape(int(st[2]), int(st[3]), int(st[4]))
    return {
        "workload": f"decode_batch BP+OSD(osd_cs,10) {shots} shots "
                    f"(surface d5, N={n}, p={p})",
        "device_cs_shots_per_s": round(rate_dev, 1),
        "host_cs_shots_per_s": round(rate_host, 1),
        "device_vs_host": round(rate_dev / rate_host, 2),
        "cost_parity_ok": parity_ok,
        "exact_match_fraction": round(float(exact.mean()), 4),
        "cs_candidates": int(n_cand),
        "cs_chunks": int(n_chunks),
        "device_host_round_trips": int(rt),
        "device_host_fallbacks": int(fb),
        "device_path_ok": bool(rt == 0 and fb == 0),
        "readings": 4,
        "protocol": "order-alternating, min-of-4 per arm",
    }


def mode_bposd():
    """Data-noise BP+OSD throughput, the reference Single-Shot cell 4
    workload (BPOSD osd_e-10, N/10 iters): its 16k shots took 449.7 s on the
    reference's CPU pool (~36 shots/s, BASELINE.md).  Since ISSUE 13 the
    whole BP->OSD pipeline is device-resident and dispatch-amortized (the
    megabatch carry owns it; zero OSD host round-trips), and the mode emits
    a device-vs-host order-alternating A/B block."""
    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.sim.data_error import CodeSimulator_DataError

    code = _bench_code()
    p = 0.05  # low end of the cell-4 grid (0.05..0.13)
    two_thirds = 2 * p / 3
    mi = int(code.N / 10)
    dec_x = BPOSD_Decoder(code.hz, np.full(code.N, two_thirds), max_iter=mi,
                          osd_method="osd_e", osd_order=10)
    dec_z = BPOSD_Decoder(code.hx, np.full(code.N, two_thirds), max_iter=mi,
                          osd_method="osd_e", osd_order=10)
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3, p / 3, p / 3], batch_size=2048, seed=0,
    )
    key = jax.random.PRNGKey(7)
    # the reference cell ran 16k shots per (code, p) cell; matching it also
    # amortizes the ~200ms fixed dispatch+sync latency of the tunneled chip
    # (scripts/profile_bposd.py decomposition) over the same work the
    # reference's own timer covered
    shots = 16384
    # warmup at the SAME shot count: the scan-chunk length is a static shape
    sim.WordErrorRate(shots, key=jax.random.fold_in(key, 0))
    # headline timed run stays telemetry-DISABLED so the metric definition
    # matches the PR-1 baselines; the enabled counters pass (same
    # shots/key) compiles its OWN program variant — the device-resident
    # pipeline folds the telemetry vector through the megabatch carry, so
    # tele-on is a different traced program (untimed; counters only)
    t0 = time.perf_counter()
    sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
    rate = shots / (time.perf_counter() - t0)
    with _tele_region():
        sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
        tele_block = _tele_counters_block(telemetry_enabled=True)
    cs_ab = _osd_cs_device_host_ab()
    return {
        "metric": f"BP+OSD(osd_e,10) data-noise shots/sec ({code.name or 'hgp'}, N={code.N}, p=0.05)",
        "value": round(rate, 1),
        "unit": "shots/s",
        "vs_baseline": round(rate / 36.0, 1),
        "telemetry": tele_block,
        # bench_compare gates these across rounds (bposd.shots_per_s and
        # the osd_ab arms are rate fields; host_round_trips must stay 0)
        "bposd": {
            "shots_per_s": round(rate, 1),
            "osd_backend": "device" if not dec_x.needs_host_postprocess
            else "host",
            "device_shots": tele_block.get("osd_device_shots", 0),
            "host_round_trips": tele_block.get("osd_host_round_trips", 0),
            # ISSUE 19: the osd_cs path must be as host-free as osd_e —
            # bench_compare gates this at 0 (lower-is-better)
            "cs_host_round_trips": cs_ab["device_host_round_trips"],
            "tiers": tele_block.get("osd_tiers"),
        },
        "osd_ab": _osd_device_host_ab(),
        "cs_ab": cs_ab,
        **_bp_utilization(dec_x, dec_z, code, p, rate,
                          jax.random.fold_in(key, 99)),
    }


def mode_st_circuit():
    """Space-time circuit-level throughput on the SpaceTimeDecodingDemo
    config (toric d3, p_CX=1e-3, num_rep=3, 13 cycles, BP window + BPOSD
    final).  Baseline: the reference's circuit-level toric threshold runs
    (Threshold ckpt cell 39) sustain ~1890 samples/s on its CPU pool
    (450k samples / 238 s at 6 cycles) — the closest published circuit-level
    rate; the demo itself prints no wall-clock."""
    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
    from qldpc_fault_tolerance_tpu.decoders import (
        ST_BP_Decoder_Circuit,
        ST_BPOSD_Decoder_Circuit,
    )
    from qldpc_fault_tolerance_tpu.sim import CodeSimulator_Circuit_SpaceTime

    code = hgp(ring_code(3), ring_code(3), name="toric_d3")
    p = 1e-3
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p, "p_idling_gate": 0}
    sim = CodeSimulator_Circuit_SpaceTime(
        code=code, p=p, num_cycles=13, num_rep=3, error_params=ep,
        eval_logical_type="Z", rand_scheduling_seed=1, batch_size=4096, seed=0,
    )
    sim._generate_circuit()
    sim._generate_circuit_graph()
    g = sim.circuit_graph
    mi = int(code.N / 10)
    sim.decoder1_z = ST_BP_Decoder_Circuit(g["h1"], g["channel_ps1"], max_iter=mi)
    sim.decoder2_z = ST_BPOSD_Decoder_Circuit(g["h2"], g["channel_ps2"],
                                              max_iter=mi, osd_method="osd_e",
                                              osd_order=10)
    key = jax.random.PRNGKey(11)
    shots = 16384
    sim.WordErrorRate(4096, key=jax.random.fold_in(key, 0))  # warmup/compile
    # headline timed run telemetry-DISABLED (PR-1 metric definition); the
    # enabled counters pass reuses the warm program (host-windowed engine,
    # no telemetry program variant)
    t0 = time.perf_counter()
    sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
    rate = shots / (time.perf_counter() - t0)
    with _tele_region():
        sim.WordErrorRate(shots, key=jax.random.fold_in(key, 1))
        tele_block = _tele_counters_block(telemetry_enabled=True)
    return {
        "metric": "ST-circuit shots/sec (SpaceTimeDecodingDemo config: toric d3, 13 cycles, BP+BPOSD)",
        "value": round(rate, 1),
        "unit": "shots/s",
        "vs_baseline": round(rate / 1890.0, 1),
        "telemetry": tele_block,
    }


def _warm_sweep_elapsed(experiment: str, cycles: int):
    """Run one parity sweep in a subprocess with --warmup and return
    ``(warm elapsed_s, telemetry block)`` (see mode_phenl_cell for the
    protocol).  The subprocess streams its telemetry to a JSONL file via
    ``QLDPC_TELEMETRY_JSONL`` (scripts/parity.py enables on that env var);
    the final snapshot event becomes the mode's ``telemetry`` block."""
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "parity.py")
    tele_dir = tempfile.mkdtemp(prefix="qldpc_bench_tele_")
    tele_path = os.path.join(tele_dir, "run.jsonl")
    env = dict(os.environ, QLDPC_TELEMETRY_JSONL=tele_path)
    try:
        try:
            proc = subprocess.run(
                [_sys.executable, script, experiment, "--cycles", str(cycles),
                 "--seeds", "1", "--warmup", "--no-record"],
                check=True, capture_output=True, text=True, env=env,
            )
        except subprocess.CalledProcessError as e:
            _sys.stderr.write(e.stderr or "")
            raise
        recs = [json.loads(line) for line in proc.stdout.splitlines()
                if line.startswith("{")]
        # unlike bp/bposd/st_circuit, the cell modes' elapsed_s IS measured
        # with telemetry on: the sweep runs once in one subprocess, and
        # doubling a multi-minute cell for a disabled arm isn't worth the
        # <2% (A/B-gated, within noise) it would isolate — the flag below
        # keeps the metric definition explicit for cross-PR comparisons
        tele = {"scope": "subprocess", "telemetry_enabled": True,
                "headline_includes_telemetry": True}
        try:
            with open(tele_path, encoding="utf-8") as fh:
                events = [json.loads(line) for line in fh if line.strip()]
            snaps = [e for e in events if e.get("kind") == "snapshot"]
            if snaps:
                tele.update(_tele_counters_block(snaps[-1].get("metrics", {}),
                                                 snaps[-1].get("compile", {})))
        except OSError:
            pass
    finally:
        shutil.rmtree(tele_dir, ignore_errors=True)
    # --no-record: the bench races the workload for wall-clock only; parity
    # evidence is the multi-seed sweeps recorded by scripts/parity.py runs,
    # and a bench rerun must not append duplicate single-seed rows
    return recs[-1]["elapsed_s"], tele


def mode_phenl_cell():
    """Wall-clock of one toric phenl threshold point (Threshold ckpt cell 25,
    cycles=10): 18 (code, p) cells x 3000 samples with BP(N/30) rounds and a
    BPOSD(N/10) final round.  Reference: 111.3 s (cell 25 second output).

    Timing protocol mirrors the reference's: the 111.3 s notebook entry is a
    warm-process measurement (cell 25 sweeps cycles {6,10,...} sequentially
    in one kernel session, so the cycles-10 timer starts with everything
    already imported/constructed/hot).  ``--warmup`` runs a tiny-scale pass
    of the same cells first, then the recorded ``elapsed_s`` measures the
    warm sweep alone."""
    elapsed, tele = _warm_sweep_elapsed("toric_phenl", 10)
    return {
        "metric": "toric phenl threshold point wall-clock (Threshold cell 25, cycles=10)",
        "value": round(elapsed, 1),
        "unit": "s",
        "vs_baseline": round(111.3 / elapsed, 2),  # >1 = faster than reference
        "telemetry": tele,
    }


def mode_circuit_cell():
    """Wall-clock of one hgp circuit-level threshold point (Threshold ckpt
    cell 29, cycles=10): 18 (code, p) cells x 1800 samples, full circuit
    synthesis + Pauli-frame detector sampling + per-round BP decoding with
    a BPOSD final round.  Reference: 318.2 s (cell 29 third output).  Same
    warm-process protocol as mode_phenl_cell."""
    elapsed, tele = _warm_sweep_elapsed("hgp_circuit", 10)
    return {
        "metric": "hgp circuit threshold point wall-clock (Threshold cell 29, cycles=10)",
        "value": round(elapsed, 1),
        "unit": "s",
        "vs_baseline": round(318.2 / elapsed, 2),
        "telemetry": tele,
    }


def mode_sweep():
    """Whole-GRID wall clock: the metric the ROADMAP north star actually
    serves (threshold/distance fits are grids of (code, p) cells, and
    BENCH_r05 showed the chip nearly idle between cells — hbm_util 0.012 —
    because the serial grid loop pays per-cell dispatch chains, warmups and
    host syncs).

    Runs a 2-code x 4-p data-noise grid through CodeFamily.EvalWER twice —
    fused cell path (sweep/fused.py, the default) vs the serial per-cell
    loop — with the order-alternating min-of-N protocol from BASELINE.md
    (sequential A/B showed ±30% phantom deltas on a shared CPU).  Both arms
    rebuild decoders/simulators per call, exactly as a user sweep does; the
    warmup rep compiles both arms' programs (the serial value-based
    pipeline also compiles once per shape bucket).

    The headline grid sits in the DISPATCH-BOUND regime (per-cell device
    work small against per-cell dispatch/sync/build overhead) — the regime
    the tunneled TPU lives in at ~50-100ms fixed latency per dispatch,
    emulated on CPU by keeping per-cell compute small.  A secondary
    ``compute_bound`` A/B reports the opposite regime (large per-cell
    compute on this 2-core CPU, where fused and serial pay identical
    decode flops and the fused win shrinks to the overhead share).

    Extra fields: aggregate cells/s and shots/s of the fused arm, per-cell
    WER bit-exactness fused-vs-serial (the fused path's acceptance gate),
    and an adaptive-reallocation pass (target_failures early stop) whose
    reallocated-shot count and lane-idle fraction come from the telemetry
    registry.  Env knobs: BENCH_SWEEP_SAMPLES / BENCH_SWEEP_BATCH /
    BENCH_SWEEP_REPS.
    """
    import logging

    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.sweep import CodeFamily
    from qldpc_fault_tolerance_tpu.utils import telemetry
    from qldpc_fault_tolerance_tpu.utils.observability import get_logger

    # the per-cell cell_done INFO lines are equal absolute cost in both
    # arms — which still biases the RATIO (they weigh more against the
    # faster arm) — so the timed region runs at WARNING, like bench's
    # telemetry-JSONL suppression
    _bench_log_level = logging.WARNING

    samples = int(os.environ.get("BENCH_SWEEP_SAMPLES", "128"))
    batch = int(os.environ.get("BENCH_SWEEP_BATCH", "128"))
    reps = int(os.environ.get("BENCH_SWEEP_REPS", "9"))
    codes = [hgp(rep_code(3), rep_code(3), name="hgp_rep3"),
             hgp(rep_code(4), rep_code(4), name="hgp_rep4")]
    p_list = [0.02, 0.04, 0.06, 0.08]
    fam_args = dict(
        decoder1_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        decoder2_class=BP_Decoder_Class(4, "minimum_sum", 0.625),
        batch_size=batch, seed=1,
    )

    def grid(fused, n=None):
        return CodeFamily(codes, **fam_args).EvalWER(
            "data", "Total", p_list, num_samples=n or samples,
            if_plot=False, fused=fused)

    def ab(run, n_reps):
        """Order-alternating min-of-N over both arms (BASELINE.md)."""
        t_fused, t_serial = [], []
        for rep in range(n_reps):
            arms = ((t_fused, True), (t_serial, False))
            if rep % 2:
                arms = arms[::-1]
            for sink, fused in arms:
                t0 = time.perf_counter()
                run(fused)
                sink.append(time.perf_counter() - t0)
        return min(t_fused), min(t_serial)

    # warmup/compile both arms (programs memoize module-wide, so fresh
    # CodeFamily instances in the timed reps hit warm caches — the steady
    # state a threshold/distance fit loop runs in)
    wer_fused = grid(True)
    wer_serial = grid(False)
    logger = get_logger()
    saved_level = logger.level
    logger.setLevel(_bench_log_level)
    try:
        fused_s, serial_s = ab(grid, reps)
        # secondary regime: 8x the shot budget per cell -> compute-dominated
        cb_samples = 8 * samples
        grid(True, cb_samples)
        grid(False, cb_samples)
        cb_fused, cb_serial = ab(lambda f: grid(f, cb_samples),
                                 max(2, reps - 2))
    finally:
        logger.setLevel(saved_level)
    n_cells = len(codes) * len(p_list)
    # per-cell shots: ShotBatcher rounds to whole chunk-multiples of batch
    shots_per_cell = -(-samples // batch) * batch
    compute_bound = {
        "samples_per_cell": cb_samples,
        "fused_s": round(cb_fused, 3),
        "serial_s": round(cb_serial, 3),
        "fused_speedup_vs_serial": round(cb_serial / cb_fused, 2),
    }

    # adaptive-reallocation pass: early-stop grid with a shot budget of
    # many megabatches per cell, so converged (high-p) cells actually hand
    # lanes to the undecided (near-threshold) ones; counters from telemetry
    with _tele_region():
        target = 40
        CodeFamily(codes, **fam_args).EvalWER(
            "data", "Total", p_list, num_samples=32 * samples,
            if_plot=False, target_failures=target)
        snap = telemetry.snapshot()

        def val(name):
            return snap.get(name, {}).get("value", 0)

        adaptive = {
            "target_failures": target,
            "reallocated_shots": val("sweep.reallocated_shots"),
            "lane_idle_fraction": val("sweep.lane_idle_fraction"),
            "early_stopped_cells": val("driver.early_stops"),
            "shots_run": val("sim.shots"),
        }

    return {
        "metric": "whole-grid data-noise sweep wall-clock "
                  f"({len(codes)} codes x {len(p_list)} p, fused vs serial)",
        "value": round(fused_s, 3),
        "unit": "s",
        "vs_baseline": round(serial_s / fused_s, 2),  # >1 = fused faster
        "grid": {
            "codes": [c.name for c in codes],
            "p_points": len(p_list), "samples_per_cell": samples,
            "batch": batch, "cells": n_cells,
        },
        "fused_s": round(fused_s, 3),
        "serial_s": round(serial_s, 3),
        "fused_speedup_vs_serial": round(serial_s / fused_s, 2),
        "cells_per_s": round(n_cells / fused_s, 1),
        "shots_per_s": round(n_cells * shots_per_cell / fused_s, 1),
        "wer_bitexact_vs_serial": bool(np.array_equal(wer_fused,
                                                      wer_serial)),
        "compute_bound": compute_bound,
        "adaptive": adaptive,
    }


def mode_serve():
    """Decode-as-a-service (ISSUE 8 / ISSUE 15): sustained QPS + tail
    latency under a mixed-code multi-tenant request storm through the FULL
    stack — TCP length-prefixed frames -> asyncio front-end -> continuous
    batcher -> persistent AOT sessions (qldpc_fault_tolerance_tpu/serve).

    The ISSUE 15 scaling half makes the headline arm many-tenants-one-
    program: requests ship on the PACKED BINARY wire codec (serve/wire.py
    v2 — syndromes/corrections in the gf2_packed lane-word layout) and
    co-bucketed sessions' rounds ride ONE cross-session fused dispatch
    (session = cell axis).  The storm runs two sessions of one bucket
    family (same code shape, different channel priors) plus a third
    session of a second code, so fused dispatch, per-session fallback and
    both wire codecs are all on the timed path.

    Storm profile (BASELINE.md "Scaling-half bench protocol"): every
    tenant runs its own connection + thread, rotates sessions per request,
    draws request sizes from a seeded RNG (32..128 shots), and keeps a fixed
    window of requests in flight (closed-loop with pipelining).  Warmup
    discipline: all shape buckets AND fused lane programs are precompiled
    and short untimed storms (both codecs) warm the wire/dispatch path —
    the timed storms perform ZERO retraces (gated).  Latency is
    CLIENT-side (submit -> response parsed).

    Arms (order rotated per rep, each rep resets the registry):
      fused_packed   packed wire + cross-session fused dispatch (HEADLINE)
      json_persess   JSON v1 wire + per-session dispatch (the baseline the
                     >=2x headline gate compares against)
      packed_persess packed wire + per-session dispatch (isolates the wire
                     codec: wire_ab)
      traced / journal  fused_packed + tracing / idempotency journal (the
                     ISSUE 11/14 overhead A/Bs, <2% gates)

    ``fused_ab`` additionally A/Bs per-session vs cross-session dispatch
    BATCHER-DIRECT (no TCP) over an 8-session bucket family under tiny
    pipelined requests, where dispatch overhead — the thing fusion
    removes — dominates; gated >= 2x.

    Served corrections are verified bit-exact against the offline
    decode-batch path on the identical syndromes over EVERY arm and rep.
    Env knobs: BENCH_SERVE_TENANTS / BENCH_SERVE_REQS / BENCH_SERVE_BATCH
    / BENCH_SERVE_WAIT_MS / BENCH_SERVE_P / BENCH_SERVE_SHOTS_MIN/MAX /
    BENCH_SERVE_REP_A/B / BENCH_FUSED_AB_*."""
    from collections import deque

    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import (
        ContinuousBatcher,
        DecodeClient,
        DecodeSession,
        start_server_thread,
    )
    from qldpc_fault_tolerance_tpu.utils import telemetry

    tenants = int(os.environ.get("BENCH_SERVE_TENANTS", "3"))
    reqs = int(os.environ.get("BENCH_SERVE_REQS", "150"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "256"))
    max_wait_s = float(os.environ.get("BENCH_SERVE_WAIT_MS", "2")) / 1e3
    p = float(os.environ.get("BENCH_SERVE_P", "0.05"))
    shots_lo = int(os.environ.get("BENCH_SERVE_SHOTS_MIN", "32"))
    shots_hi = int(os.environ.get("BENCH_SERVE_SHOTS_MAX", "128"))
    rep_a = int(os.environ.get("BENCH_SERVE_REP_A", "4"))
    rep_b = int(os.environ.get("BENCH_SERVE_REP_B", "3"))
    window = 16
    code_a = hgp(rep_code(rep_a), rep_code(rep_a), name=f"hgp_rep{rep_a}")
    code_b = hgp(rep_code(rep_b), rep_code(rep_b), name=f"hgp_rep{rep_b}")
    cls = BP_Decoder_Class(4, "minimum_sum", 0.625)
    # two sessions of ONE bucket family (same shape, different priors) +
    # one session of a second code: fused dispatch covers the family,
    # the second code dispatches per-session alongside it
    family_n = int(os.environ.get("BENCH_SERVE_FAMILY", "3"))
    members = {
        f"hgp_rep{rep_a}_{chr(97 + i)}": (code_a,
                                          min(0.3, (1.0 + 0.3 * i) * p))
        for i in range(family_n)
    }
    members[f"hgp_rep{rep_b}"] = (code_b, p)
    params = {name: {"h": c.hx, "p_data": pp}
              for name, (c, pp) in members.items()}
    sessions = {name: DecodeSession(name, decoder_class=cls,
                                    params=params[name],
                                    buckets=(32, 64, 128, 256, 512))
                for name in members}
    names = sorted(sessions)
    h_t = {name: np.asarray(c.hx, np.uint8).T
           for name, (c, _pp) in members.items()}
    n_bits = {name: c.N for name, (c, _pp) in members.items()}
    p_of = {name: pp for name, (_c, pp) in members.items()}

    def make_synd(name, k, rng):
        err = (rng.random((k, n_bits[name])) < p_of[name]).astype(np.uint8)
        return (err @ h_t[name] % 2).astype(np.uint8)

    batcher = ContinuousBatcher(sessions, max_batch_shots=max_batch,
                                max_wait_s=max_wait_s)
    handle = start_server_thread(batcher)
    host, port = handle.address

    def storm(n_reqs, collect, traced=False, idem=False, codec=2,
              sizes=None):
        """One storm: ``tenants`` client threads, each with its own
        connection (negotiating ``codec``), window-pipelined submits,
        sessions rotating per request.  ``collect`` gathers (session,
        syndromes, corrections, latency).  ``sizes`` cycles deterministic
        request sizes (warmup: cover every packed lane-word shape)."""
        errors = []

        def worker(idx):
            try:
                cli = DecodeClient(host, port, tenant=f"tenant{idx}",
                                   traced=traced, idempotent=idem,
                                   codec=codec)
                rng = np.random.default_rng(1000 + idx)
                pending = deque()

                def finish_one():
                    name, synd, fut = pending.popleft()
                    res = fut.result(timeout=120)
                    collect.append((name, synd, res.corrections,
                                    res.latency_s))

                for i in range(n_reqs):
                    name = names[(i + idx) % len(names)]
                    k = (sizes[i % len(sizes)] if sizes else
                         int(rng.integers(shots_lo, shots_hi + 1)))
                    synd = make_synd(name, k, rng)
                    pending.append((name, synd, cli.submit(name, synd)))
                    if len(pending) >= window:
                        finish_one()
                while pending:
                    finish_one()
                cli.close()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        import threading

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(tenants)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return time.perf_counter() - t0

    def run_fused_ab():
        """Per-session vs cross-session dispatch, BATCHER-DIRECT: a
        6-session bucket family (one tiny code at 6 priors) under small
        pipelined requests, so per-dispatch overhead — what fusion
        amortizes — dominates the round.  Same seeded request schedule
        both arms, order-alternating min-of-N, bit-exact vs offline,
        zero retraces after warmup."""
        code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
        n_sess = int(os.environ.get("BENCH_FUSED_AB_SESSIONS", "8"))
        ab_reqs = int(os.environ.get("BENCH_FUSED_AB_REQS", "320"))
        ab_reps = int(os.environ.get("BENCH_FUSED_AB_REPS", "3"))
        shots_ab = int(os.environ.get("BENCH_FUSED_AB_SHOTS", "2"))
        params_ab = {f"ab{i}": {"h": code.hx,
                                "p_data": 0.01 + 0.01 * i}
                     for i in range(n_sess)}
        sess_ab = {k: DecodeSession(k, decoder_class=cls, params=v,
                                    buckets=(8, 16, 32, 64))
                   for k, v in params_ab.items()}
        bat = ContinuousBatcher(sess_ab, max_batch_shots=64,
                                max_wait_s=0.001)
        bat.warm()
        h_t3 = np.asarray(code.hx, np.uint8).T
        rngab = np.random.default_rng(7)
        sched = []
        for i in range(ab_reqs):
            err = (rngab.random((shots_ab, code.N)) < 0.02).astype(np.uint8)
            sched.append((f"ab{i % n_sess}",
                          (err @ h_t3 % 2).astype(np.uint8)))

        def drive():
            futs = deque()
            done = []
            for name, sy in sched:
                futs.append((name, sy, bat.submit(name, sy, tenant="ab")))
                if len(futs) >= 96:
                    n_, s_, f_ = futs.popleft()
                    done.append((n_, s_, f_.result(timeout=60)))
            while futs:
                n_, s_, f_ = futs.popleft()
                done.append((n_, s_, f_.result(timeout=60)))
            return done

        for fused in (True, False):  # warm both dispatch paths
            bat.fused = fused
            drive()
        before = telemetry.compile_stats().get("jax.retraces", 0)
        times = {True: [], False: []}
        all_rows = []
        for rep in range(ab_reps):
            order = (True, False) if rep % 2 == 0 else (False, True)
            for fused in order:
                bat.fused = fused
                t0 = time.perf_counter()
                all_rows.extend(drive())
                times[fused].append(time.perf_counter() - t0)
        retr = telemetry.compile_stats().get("jax.retraces", 0) - before
        ok = True
        for name in sess_ab:
            sy = np.concatenate([s for n_, s, _r in all_rows
                                 if n_ == name])
            served = np.concatenate([r.corrections
                                     for n_, _s, r in all_rows
                                     if n_ == name])
            ok = ok and bool(np.array_equal(
                served, cls.GetDecoder(params_ab[name]).decode_batch(sy)))
        fused_t, pers_t = min(times[True]), min(times[False])
        dispatches = int(bat.fused_dispatches)
        fallbacks = int(bat.fused_fallbacks)
        bat.drain(timeout=30.0)
        return {
            "sessions": n_sess,
            "requests": ab_reqs,
            "shots_per_request": shots_ab,
            "reps": ab_reps,
            "persess_req_per_s": round(ab_reqs / pers_t, 1),
            "fused_req_per_s": round(ab_reqs / fused_t, 1),
            "fused_speedup": round(pers_t / fused_t, 2),
            "fused_dispatches": dispatches,
            "fused_fallbacks": fallbacks,
            "bitexact": ok,
            "retraces": int(retr),
        }

    storm_reps = int(os.environ.get("BENCH_SERVE_STORM_REPS", "3"))
    all_results: list = []
    # arm -> (client codec, fused dispatch on, traced, idem)
    ARM_CFG = {
        "fused_packed": (2, True, False, False),    # the headline
        "json_persess": (1, False, False, False),   # the >=2x baseline
        "packed_persess": (2, False, False, False),  # wire_ab companion
        "traced": (2, True, True, False),
        "journal": (2, True, False, True),
    }
    ARMS = tuple(ARM_CFG)
    best = {arm: None for arm in ARMS}
    warm_sizes = sorted({1, min(8, shots_hi), 31, 32, 33,
                         shots_hi} & set(range(1, shots_hi + 1)))
    with _tele_region():
        # warmup discipline: compile every shape bucket AND every fused
        # lane program, then warm the wire/dispatch path with short
        # untimed storms on BOTH codecs covering every packed lane-word
        # shape the timed storms can produce
        batcher.warm()
        for codec in (1, 2):
            for fused in (False, True):
                batcher.fused = fused
                storm(2 * len(warm_sizes) * len(names), collect=[],
                      codec=codec, sizes=warm_sizes)
        # quiet-rep protocol (BASELINE.md): the closed-loop storm is
        # Python/asyncio/thread-scheduling heavy, so single runs swing
        # ~2x on the shared container — run the timed storm several
        # times and report the BEST rep (headline + latencies + counters
        # all from the same rep).  Each rep resets the registry so its
        # snapshot covers only its own traffic (warmup included in none).
        retraces_total = 0
        for rep in range(storm_reps):
            shift = rep % len(ARMS)
            for arm in ARMS[shift:] + ARMS[:shift]:
                codec, fused, traced, idem = ARM_CFG[arm]
                batcher.fused = fused
                telemetry.reset()
                before = telemetry.compile_stats().get("jax.retraces", 0)
                results: list = []
                elapsed = storm(reqs, collect=results, traced=traced,
                                idem=idem, codec=codec)
                retraces_total += (telemetry.compile_stats()
                                   .get("jax.retraces", 0) - before)
                all_results.extend(results)
                snap_arm = telemetry.snapshot()
                nbytes = (snap_arm.get("serve.bytes_rx", {})
                          .get("value", 0)
                          + snap_arm.get("serve.bytes_tx", {})
                          .get("value", 0))
                rec = {"qps": len(results) / elapsed, "elapsed": elapsed,
                       "shots_per_s": sum(s.shape[0] for _, s, _, _
                                          in results) / elapsed,
                       "bytes_per_req": nbytes / max(1, len(results)),
                       "results": results, "snap": snap_arm}
                if best[arm] is None or rec["qps"] > best[arm]["qps"]:
                    best[arm] = rec
        retraces = retraces_total  # 0 across EVERY timed rep AND all arms
        snap = best["fused_packed"]["snap"]  # headline arm
        results = best["fused_packed"]["results"]
        elapsed = best["fused_packed"]["elapsed"]
        telemetry.reset()
        fused_ab = run_fused_ab()

    handle.stop(drain=True)

    headline_sps = best["fused_packed"]["shots_per_s"]
    traced_sps = best["traced"]["shots_per_s"]
    journal_sps = best["journal"]["shots_per_s"]
    overhead_pct = 100.0 * (1.0 - traced_sps / headline_sps) \
        if headline_sps else 0.0
    journal_overhead_pct = 100.0 * (1.0 - journal_sps / headline_sps) \
        if headline_sps else 0.0

    def val(name, field="value"):
        return snap.get(name, {}).get(field, 0)

    # served corrections must be bit-exact vs the offline decode path on
    # the identical syndromes (request boundaries, megabatch padding,
    # fused lane padding and the wire codec must not leak into the
    # estimate) — verified over EVERY timed rep of EVERY arm
    bitexact = True
    for name in names:
        rows = [(s, c) for (n, s, c, _) in all_results if n == name]
        if not rows:  # tiny storms (1 tenant, few reqs) may skip a code
            continue
        synd = np.concatenate([s for s, _ in rows])
        served = np.concatenate([c for _, c in rows])
        offline = cls.GetDecoder(params[name]).decode_batch(synd)
        bitexact = bitexact and bool(np.array_equal(served, offline))

    lats_ms = np.asarray([lat for *_, lat in results]) * 1e3
    total_shots = int(sum(s.shape[0] for _, s, _, _ in results))
    occ = snap.get("serve.batch_occupancy", {})
    qps = len(results) / elapsed
    json_qps = best["json_persess"]["qps"]
    packed_qps = best["packed_persess"]["qps"]
    json_bpr = best["json_persess"]["bytes_per_req"]
    packed_bpr = best["packed_persess"]["bytes_per_req"]
    speedup_vs_json = qps / json_qps if json_qps else None
    bytes_ratio = json_bpr / packed_bpr if packed_bpr else None
    return {
        "metric": "decode-service sustained QPS, fused dispatch + packed "
                  f"wire ({len(names)} sessions x {tenants} tenants, TCP "
                  f"front-end, window {window})",
        "value": round(qps, 1),
        "unit": "req/s",
        # decoded shots/s against the reference CPU pool's ~36 shots/s —
        # the same anchor the offline modes use
        "vs_baseline": round(total_shots / elapsed / 36.0, 1),
        "shots_per_s": round(total_shots / elapsed, 1),
        "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
        "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
        "requests": len(results),
        "shots": total_shots,
        "tenants": tenants,
        "codes": names,
        "request_shots": [shots_lo, shots_hi],
        "max_batch_shots": max_batch,
        "max_wait_ms": round(max_wait_s * 1e3, 2),
        "batches": val("serve.batches"),
        "requests_per_batch": (round(len(results) / val("serve.batches"), 2)
                               if val("serve.batches") else None),
        "batch_occupancy_mean": (round(occ["mean"], 4)
                                 if occ.get("mean") is not None else None),
        "padded_shot_fraction": (round(val("serve.padded_shots")
                                       / (val("serve.padded_shots")
                                          + total_shots), 4)
                                 if total_shots else None),
        "queue_depth_max": val("serve.queue_depth", "max"),
        "errors": val("serve.errors"),
        "fused_dispatches": val("serve.fused.dispatches"),
        "fused_fallbacks": val("serve.fused.fallbacks"),
        "bytes_rx": val("serve.bytes_rx"),
        "bytes_tx": val("serve.bytes_tx"),
        "storm_reps": storm_reps,
        "bitexact_vs_offline": bitexact,  # every rep of EVERY arm
        "retraces_after_warmup": int(retraces),
        "graceful_drain": True,
        "speedup_vs_json_persess": (round(speedup_vs_json, 2)
                                    if speedup_vs_json else None),
        # wire codec A/B (ISSUE 15): same storm, per-session dispatch
        # both arms — isolates JSON v1 vs packed v2.  bytes_per_req
        # counts BOTH directions' framed bytes from the serve.bytes_*
        # counters; the >=10x ratio is the acceptance gate
        "wire_ab": {
            "json_req_per_s": round(json_qps, 1),
            "packed_req_per_s": round(packed_qps, 1),
            "json_bytes_per_req": round(json_bpr, 1),
            "packed_bytes_per_req": round(packed_bpr, 1),
            "bytes_ratio": (round(bytes_ratio, 2)
                            if bytes_ratio else None),
            "wire_speedup": (round(packed_qps / json_qps, 2)
                             if json_qps else None),
        },
        # cross-session fused dispatch A/B (ISSUE 15): batcher-direct
        "fused_ab": fused_ab,
        # tracing on/off A/B (ISSUE 11): per-request span recording must
        # stay in the noise — gate at <2% decoded-shots/s overhead vs
        # the headline arm (same codec + dispatch config)
        "tracing_ab": {
            "untraced_shots_per_s": round(headline_sps, 1),
            "traced_shots_per_s": round(traced_sps, 1),
            "traced_qps": round(best["traced"]["qps"], 1),
            "traced_p99_ms": round(float(np.percentile(
                np.asarray([lat for *_, lat in best["traced"]["results"]])
                * 1e3, 99)), 2),
            "overhead_pct": round(overhead_pct, 2),
            "overhead_le_2pct": bool(overhead_pct <= 2.0),
        },
        # idempotency-journal on/off A/B (ISSUE 14) vs the headline arm
        "journal_ab": {
            "plain_shots_per_s": round(headline_sps, 1),
            "journaled_shots_per_s": round(journal_sps, 1),
            "journaled_qps": round(best["journal"]["qps"], 1),
            "overhead_pct": round(journal_overhead_pct, 2),
            "overhead_le_2pct": bool(journal_overhead_pct <= 2.0),
        },
        "gates": {
            "bitexact_vs_offline": bitexact,
            "zero_retraces": bool(retraces == 0),
            # the combined fused+packed TCP storm must never lose to
            # the per-session JSON baseline; the >=2x combined headline
            # is a TPU-regime target — on this container the dispatcher
            # is COMPUTE-bound at 32..128-shot requests, so the isolated
            # A/Bs carry the scaling-half acceptance gates (BASELINE.md
            # "Scaling-half bench protocol")
            "headline_ge_json_baseline": bool(speedup_vs_json is not None
                                              and speedup_vs_json >= 1.0),
            "wire_bytes_ratio_ge_10": bool(bytes_ratio is not None
                                           and bytes_ratio >= 10.0),
            "fused_ab_speedup_ge_2": bool(
                fused_ab["fused_speedup"] >= 2.0),
            "fused_ab_bitexact": bool(fused_ab["bitexact"]),
        },
    }


def mode_rare():
    """Rare-event estimation (ISSUE 10): variance-reduction factor of the
    importance-sampled (tilted) WER estimator vs direct Monte-Carlo on a
    DEEP sub-threshold cell — the regime where the effective-distance fit
    needs points direct MC cannot produce (a 1e-10 WER needs ~1e12 direct
    shots).

    Cell: hgp_rep3 data noise at p = BENCH_RARE_P (default 0.005 —
    well under p_c/3 for this family's ~0.06 nominal threshold), pure-device
    min-sum BP, tilt from ``rare.auto_tilt`` (proposal mean error weight
    aimed at d_eff/2 flips).  Both arms run the SAME shot budget through
    the same sample->syndrome->decode->check pipeline (the weighted arm
    additionally carries the per-shot log-weight plane and weight-moment
    folds), order-alternating min-of-N wall clock per BASELINE.md.

    Headline: variance-reduction factor at FIXED WALL CLOCK — the
    equal-shot-budget factor ``(r(1-r)/n) / Var[weighted]`` scaled by the
    measured throughput ratio (estimator variance is ∝ 1/t for both arms).
    Gates: vrf_equal_shots >= 10 (the acceptance floor), weighted-vs-direct
    WER consistency within 3 combined sigma on the same cell, and zero-tilt
    bit-exactness seed-for-seed against BOTH the data and phenom direct
    engines.  Env knobs: BENCH_RARE_SAMPLES / BENCH_RARE_BATCH /
    BENCH_RARE_P / BENCH_RARE_REPS.
    """
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.rare import (
        auto_tilt,
        tilt_channel,
        variance_reduction,
    )
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError,
    )
    from qldpc_fault_tolerance_tpu.sim.phenom import CodeSimulator_Phenon

    samples = int(os.environ.get("BENCH_RARE_SAMPLES", "32768"))
    batch = int(os.environ.get("BENCH_RARE_BATCH", "4096"))
    p = float(os.environ.get("BENCH_RARE_P", "0.005"))
    reps = int(os.environ.get("BENCH_RARE_REPS", "5"))
    p_c_nominal = 0.06  # this family's data-noise threshold scale
    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")

    def mk(seed=5):
        dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=12)
        dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=12)
        return CodeSimulator_DataError(
            code=code, decoder_x=dec_x, decoder_z=dec_z,
            pauli_error_probs=[p / 3] * 3, batch_size=batch, seed=seed)

    q_total = auto_tilt(p, n=code.N, d_eff=3.0)
    tilt = tilt_channel([p / 3] * 3, q_total)

    # warmup/compile both arms
    mk().WordErrorRate(batch)
    mk().WeightedWordErrorRate(batch, tilt_probs=tilt)

    # order-alternating min-of-N (BASELINE.md): same shot budget both arms
    t_direct, t_weighted = [], []
    direct_wer = weighted_stats = None
    for rep in range(reps):
        arms = [("d", t_direct), ("w", t_weighted)]
        if rep % 2:
            arms = arms[::-1]
        for which, sink in arms:
            sim = mk()
            t0 = time.perf_counter()
            if which == "d":
                direct_wer = sim.WordErrorRate(samples)
                direct_sim = sim
            else:
                sim.WeightedWordErrorRate(samples, tilt_probs=tilt)
                weighted_stats = sim.last_weighted
            sink.append(time.perf_counter() - t0)
    td, tw = min(t_direct), min(t_weighted)

    ws = weighted_stats
    vrf = variance_reduction(ws)
    # fixed-wall-clock factor: variance ∝ 1/t for both estimators, so the
    # equal-shot factor scales by the throughput ratio
    vrf_wall = vrf * (td / tw) if vrf is not None else None

    # WER consistency on the SAME cell: weighted rate vs direct binomial
    # rate within 3 combined sigma (both estimate the same physical rate;
    # the direct failure rate comes back through the exact inverse of the
    # wer_single_shot transform)
    rate_d = 1.0 - (1.0 - direct_wer[0]) ** direct_sim.K
    var_d = rate_d * (1.0 - rate_d) / samples
    sigma = (ws.variance + var_d) ** 0.5
    consistent = (abs(ws.rate - rate_d) <= 3.0 * sigma) if sigma > 0 \
        else ws.rate == rate_d

    # zero-tilt bit-exactness, seed-for-seed, both engines
    za, zb = mk(seed=9), mk(seed=9)
    wd = za.WordErrorRate(4 * batch)
    wz = zb.WeightedWordErrorRate(4 * batch)
    zt_data = (wd[0] == wz[0]
               and zb.last_weighted.s1 == zb.last_weighted.failures
               and zb.last_weighted.w1 == zb.last_weighted.shots)

    pp, qq = 0.02, 0.02
    hx_ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    hz_ext = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])

    def mk_ph(seed=9):
        pz = np.concatenate([np.full(code.N, pp),
                             np.full(code.hx.shape[0], qq)])
        px = np.concatenate([np.full(code.N, pp),
                             np.full(code.hz.shape[0], qq)])
        return CodeSimulator_Phenon(
            code=code,
            decoder1_x=BPDecoder(hz_ext, px, max_iter=10),
            decoder1_z=BPDecoder(hx_ext, pz, max_iter=10),
            decoder2_x=BPDecoder(code.hz, np.full(code.N, pp), max_iter=10),
            decoder2_z=BPDecoder(code.hx, np.full(code.N, pp), max_iter=10),
            pauli_error_probs=[pp / 3] * 3, q=qq, batch_size=batch,
            seed=seed)

    pd = mk_ph().WordErrorRate(num_rounds=3, num_samples=batch)
    pw = mk_ph().WeightedWordErrorRate(num_rounds=3, num_samples=batch)
    zt_phenl = pd[0] == pw[0]

    return {
        "metric": "rare-event variance-reduction factor, tilted IS vs "
                  f"direct MC (hgp_rep3 data p={p:g}, equal wall clock)",
        "value": round(vrf_wall, 1) if vrf_wall is not None else None,
        "unit": "x",
        # direct MC at equal budget IS the baseline (factor 1)
        "vs_baseline": round(vrf_wall, 1) if vrf_wall is not None else None,
        "cell": {"code": "hgp_rep3", "p": p, "tilt": round(q_total, 6),
                 "p_c_nominal": p_c_nominal,
                 "sub_threshold_ratio": round(p / p_c_nominal, 4),
                 "samples": samples, "batch": batch},
        "vrf_equal_shots": round(vrf, 1) if vrf is not None else None,
        "vrf_fixed_wallclock": (round(vrf_wall, 1)
                                if vrf_wall is not None else None),
        "direct_s": round(td, 3),
        "weighted_s": round(tw, 3),
        "weighted_shots_per_s": round(samples / tw, 1),
        "weighted": {
            "rate": ws.rate, "failures": ws.failures, "shots": ws.shots,
            "ess": round(ws.ess, 1), "rse": (round(ws.rse, 4)
                                             if ws.rse is not None else None),
        },
        "direct": {"rate": rate_d,
                   "failures": int(round(rate_d * samples)),
                   "shots": samples, "wer": direct_wer[0]},
        "gates": {
            "vrf_ge_10": bool(vrf is not None and vrf >= 10.0),
            "wer_consistent_3sigma": bool(consistent),
            "zero_tilt_bitexact_data": bool(zt_data),
            "zero_tilt_bitexact_phenl": bool(zt_phenl),
        },
    }


def mode_chaos():
    """Chaos smoke (ISSUE 14): a short SEEDED fault schedule — a
    device-restart dispatch death that exhausts the in-dispatch retries,
    a dropped connection, a stalled dispatch, a dropped response —
    against a LIVE decode server with the self-healing HealthProbe
    attached, driven by reconnect+idempotent clients.

    Headline: recovery wall clock — storm end until /healthz reports a
    quiescent, healthy service (ok, empty queue, empty journal, no
    unconsumed incidents) with ZERO operator action.  Gates: zero
    dropped (every submitted request answered, none with an error), zero
    duplicated (completed == logical accepted requests; resubmits and
    hedges deduped by the journal), served corrections bit-exact vs the
    offline decode path, recovery within BENCH_CHAOS_RECOVERY_S.
    Env knobs: BENCH_CHAOS_REQS / BENCH_CHAOS_SEED /
    BENCH_CHAOS_RECOVERY_S."""
    import threading
    import urllib.request
    from collections import deque

    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import (
        ContinuousBatcher,
        DecodeClient,
        DecodeSession,
        HealthProbe,
        start_ops_thread,
        start_server_thread,
    )
    from qldpc_fault_tolerance_tpu.utils import (
        faultinject,
        resilience,
        telemetry,
    )

    reqs = int(os.environ.get("BENCH_CHAOS_REQS", "40"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "14"))
    recovery_budget_s = float(os.environ.get("BENCH_CHAOS_RECOVERY_S",
                                             "30"))
    tenants = 2
    window = 8
    p = 0.05
    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    cls = BP_Decoder_Class(4, "minimum_sum", 0.625)
    params = {"h": code.hx, "p_data": p}
    h_t = np.asarray(code.hx, np.uint8).T

    prev_policy = resilience.current_policy()
    resilience.set_default_policy(resilience.RetryPolicy(
        max_attempts=2, base_delay=0.05, backoff=1.0, jitter=0.0,
        reset_caches=False, degrade_after=1))
    try:
        with _tele_region():
            sess = DecodeSession("hgp_rep3", decoder_class=cls,
                                 params=params, buckets=(32, 64, 128))
            sess.warm()
            bat = ContinuousBatcher({"hgp_rep3": sess},
                                    max_batch_shots=64, max_wait_s=0.002,
                                    max_dispatch_attempts=4)
            probe = HealthProbe(bat, interval_s=0.05)
            handle = start_server_thread(bat)
            ops = start_ops_thread(batcher=bat, probe=probe)
            host, port = handle.address
            ohost, oport = ops.address
            # the seeded schedule: deterministic given BENCH_CHAOS_SEED
            sched_rng = np.random.default_rng(seed)
            plan = faultinject.FaultPlan([
                faultinject.Fault(site="serve_dispatch",
                                  kind="device_restart",
                                  after=int(sched_rng.integers(1, 3)),
                                  count=2),
                faultinject.Fault(site="serve_dispatch", kind="stall",
                                  after=int(sched_rng.integers(4, 6)),
                                  stall_s=0.2),
                faultinject.Fault(site="serve_conn_rx", kind="conn_drop",
                                  after=int(sched_rng.integers(2, 6))),
                faultinject.Fault(site="serve_respond", kind="conn_drop",
                                  after=int(sched_rng.integers(6, 12))),
            ], seed=seed)
            results, errors = [], []

            def worker(idx):
                try:
                    cli = DecodeClient(host, port, tenant=f"tenant{idx}",
                                       reconnect=True, timeout=60.0)
                    rng = np.random.default_rng(1000 + idx)
                    pending = deque()

                    def finish_one():
                        synd, fut = pending.popleft()
                        res = fut.result(timeout=120)
                        results.append((synd, res.corrections))

                    for _ in range(reqs):
                        k = int(rng.integers(1, 9))
                        err = (rng.random((k, code.N)) < p).astype(
                            np.uint8)
                        synd = (err @ h_t % 2).astype(np.uint8)
                        pending.append((synd,
                                        cli.submit("hgp_rep3", synd)))
                        if len(pending) >= window:
                            finish_one()
                    while pending:
                        finish_one()
                    cli.close()
                except Exception as exc:  # noqa: BLE001 — gated below
                    errors.append(f"{type(exc).__name__}: {exc}")

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(tenants)]
            t0 = time.perf_counter()
            with plan.active():
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            storm_s = time.perf_counter() - t0
            # recovery: the service must report quiescent-healthy with
            # zero operator action — queue drained, journal empty, every
            # incident consumed by the probe
            rec_t0 = time.perf_counter()
            recovered = False
            while time.perf_counter() - rec_t0 < recovery_budget_s:
                try:
                    hz = json.loads(urllib.request.urlopen(
                        f"http://{ohost}:{oport}/healthz",
                        timeout=5).read())
                    if (hz.get("ok") and hz.get("queue_depth") == 0
                            and hz.get("journal_inflight") == 0
                            and hz.get("incidents_pending") == 0):
                        recovered = True
                        break
                except Exception:  # noqa: BLE001 — poll until budget
                    pass
                resilience.sleep_for(0.05)
            recovery_s = time.perf_counter() - rec_t0
            snap = telemetry.snapshot()
            heals = probe.heals
            probe.stop()
            ops.stop()
            handle.stop(drain=True)
    finally:
        resilience.set_default_policy(prev_policy)

    def val(name):
        return snap.get(name, {}).get("value", 0)

    answered = len(results)
    submitted = reqs * tenants
    synd = np.concatenate([s for s, _ in results]) if results else None
    served = np.concatenate([c for _, c in results]) if results else None
    offline = (cls.GetDecoder(params).decode_batch(synd)
               if synd is not None else None)
    bitexact = bool(results
                    and np.array_equal(served, offline))
    zero_dropped = bool(not errors and answered == submitted
                        and bat.failed == 0)
    # exactly-once: the server ACCEPTED each logical request exactly once
    # (serve.requests counts journal-new accepts — a broken dedupe that
    # re-accepted a resubmit would push it past the submitted count) and
    # completed each exactly once.  completed==serve.requests alone would
    # be tautological: both increment per accepted request.
    zero_duplicated = bool(val("serve.requests") == submitted
                           and bat.completed == submitted)
    return {
        "metric": f"chaos smoke recovery (seeded schedule seed={seed}, "
                  f"{submitted} reqs x {tenants} reconnect tenants)",
        "value": round(recovery_s, 3),
        "unit": "s",
        "vs_baseline": None,
        "seed": seed,
        "requests": submitted,
        "answered": answered,
        "storm_s": round(storm_s, 3),
        "chaos_qps": round(answered / storm_s, 1) if storm_s else None,
        "recovery_s": round(recovery_s, 3),
        "recovery_budget_s": recovery_budget_s,
        "faults_injected": val("faultinject.injected"),
        "redispatches": val("serve.redispatches"),
        "reconnects": val("serve.client.reconnects"),
        "dedup_attached": val("serve.dedup.attached"),
        "dedup_replayed": val("serve.dedup.replayed"),
        "heals": int(heals),
        "client_errors": errors[:4],
        "gates": {
            "zero_dropped": zero_dropped,
            "zero_duplicated": zero_duplicated,
            "bitexact_vs_offline": bitexact,
            "recovered_in_budget": bool(recovered),
            "faults_fired": bool(val("faultinject.injected") >= 4),
        },
    }


def mode_stream():
    """Streaming space-time decode (ISSUE 16): sustained committed
    cycles/s per stream and p99 commit latency vs window size on the
    LIVE serve path (stream_open / stream_chunk / stream_commit over the
    packed v2 wire), plus the windowed-vs-whole-history A/B gated on
    compute per COMMITTED cycle.

    A/B protocol (BASELINE.md): at total history T = 10*w cycles, the
    whole-history arm re-decodes the full T-cycle ST program to commit
    its next w cycles (cost/cycle = t_T / w) where the windowed arm
    decodes only its w-cycle window (cost/cycle = t_w / w) — the ratio
    t_T / t_w is the acceptance metric (>= 5x at T >= 10*w).  Arms are
    interleaved sample-by-sample and take medians, so ambient drift
    (thermal, background load) lands on both equally.
    Env knobs: BENCH_STREAM_STEPS / BENCH_STREAM_LANES /
    BENCH_STREAM_AB_REPS."""
    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import ST_BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import (
        ContinuousBatcher,
        DecodeClient,
        DecodeSession,
        start_server_thread,
    )
    from qldpc_fault_tolerance_tpu.utils import telemetry

    steps = int(os.environ.get("BENCH_STREAM_STEPS", "120"))
    lanes = int(os.environ.get("BENCH_STREAM_LANES", "8"))
    ab_reps = int(os.environ.get("BENCH_STREAM_AB_REPS", "9"))
    windows = (2, 4, 8)
    p = 0.01
    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    cls = ST_BP_Decoder_Class(2, "minimum_sum", 0.625)
    rng = np.random.default_rng(16)

    with _tele_region():
        # -- serve-path sustained streaming, one session per window size --
        per_window = {}
        sessions = {
            f"st_w{w}": DecodeSession(
                f"st_w{w}", decoder_class=cls,
                params={"h": code.hx, "p_data": p, "p_syndrome": True,
                        "num_rep": w},
                buckets=(lanes,))
            for w in windows
        }
        bat = ContinuousBatcher(sessions, max_batch_shots=max(lanes, 64),
                                max_wait_s=0.002)
        handle = start_server_thread(bat)
        host, port = handle.address
        try:
            for w in windows:
                cli = DecodeClient(host, port, reconnect=True)
                try:
                    ack = cli.stream_open(f"st_w{w}", lanes=lanes)
                    sid = ack["stream"]
                    width = ack["width"]
                    # warm the AOT program + the stream path off the clock
                    warm = (rng.random((lanes, width)) < 0.02).astype(
                        np.uint8)
                    cli.stream_step(sid, 1, warm)
                    lat_ms = []
                    t0 = time.perf_counter()
                    for seq in range(2, steps + 2):
                        chunk = (rng.random((lanes, width)) < 0.02).astype(
                            np.uint8)
                        t1 = time.perf_counter()
                        res = cli.stream_step(sid, seq, chunk)
                        lat_ms.append(1e3 * (time.perf_counter() - t1))
                        assert res.get("ok"), res
                    wall = time.perf_counter() - t0
                    cli.stream_commit(sid, close=True)
                    per_window[str(w)] = {
                        "cycles_per_s": round(steps * w / wall, 1),
                        "steps_per_s": round(steps / wall, 1),
                        "p50_commit_ms": round(
                            float(np.percentile(lat_ms, 50)), 3),
                        "p99_commit_ms": round(
                            float(np.percentile(lat_ms, 99)), 3),
                    }
                finally:
                    cli.close()
        finally:
            handle.stop(drain=True)
        # -- windowed-vs-whole-history A/B (device programs, interleaved) --
        w = 4
        T = 10 * w
        ab_batch = int(os.environ.get("BENCH_STREAM_AB_BATCH", "512"))
        params_w = {"h": code.hx, "p_data": p, "p_syndrome": True,
                    "num_rep": w}
        params_T = {"h": code.hx, "p_data": p, "p_syndrome": True,
                    "num_rep": T}
        dec_w = cls.GetDecoder(params_w)
        dec_T = cls.GetDecoder(params_T)
        m = np.asarray(code.hx).shape[0]
        # the A/B runs at a compute-bound batch so per-call dispatch
        # overhead doesn't mask the O(window)-vs-O(T) work difference the
        # arms exist to measure (lanes-sized calls are latency-bound)
        hist = (rng.random((ab_batch, T, m)) < 0.02).astype(np.uint8)

        import jax.numpy as jnp

        def _time_decode(dec, arr):
            t1 = time.perf_counter()
            folded, _ = dec.decode_batch_device(jnp.asarray(arr))
            jax.block_until_ready(folded)
            return time.perf_counter() - t1

        _time_decode(dec_w, hist[:, :w])   # compile both arms off-clock
        _time_decode(dec_T, hist)
        t_w, t_T = [], []
        for _ in range(ab_reps):           # interleaved arms
            t_w.append(_time_decode(dec_w, hist[:, :w]))
            t_T.append(_time_decode(dec_T, hist))
        med_w = float(np.median(t_w))
        med_T = float(np.median(t_T))
        # each update commits w cycles: windowed decodes w of them, the
        # whole-history arm re-decodes all T
        ratio = med_T / med_w if med_w else float("inf")
        tele_block = _tele_counters_block(telemetry_enabled=True)

    headline = per_window[str(max(windows))]
    return {
        "metric": f"stream decode sustained cycles/s "
                  f"(w={max(windows)}, {lanes} lanes, live serve path)",
        "value": headline["cycles_per_s"],
        "unit": "cycles/s",
        "vs_baseline": None,
        "stream": {
            "cycles_per_s": headline["cycles_per_s"],
            "p99_commit_ms": headline["p99_commit_ms"],
            "ab_compute_per_cycle_ratio": round(ratio, 2),
            "per_window": per_window,
            "ab": {
                "w": w, "T": T, "reps": ab_reps, "batch": ab_batch,
                "windowed_ms_per_cycle": round(1e3 * med_w / w, 4),
                "whole_ms_per_cycle": round(1e3 * med_T / w, 4),
            },
        },
        "telemetry": tele_block,
        "gates": {
            # the acceptance floor: windowed overlap-commit is >= 5x
            # cheaper per committed cycle than whole-history re-decode
            # at T = 10*w
            "ab_ratio_ge_5x": bool(ratio >= 5.0),
            "all_windows_streamed": bool(
                len(per_window) == len(windows)),
        },
    }


def mode_fleet():
    """Multi-host serving fabric (ISSUE 18): a 2-host in-process fleet
    behind the family-sticky router, a closed-loop reconnect storm, and a
    seeded ``host_kill`` mid-storm — the family's owner dies hard, the
    gateway's deadman fires, and the router hands the family off to the
    successor while the storm keeps running.

    Headline: sustained fleet req/s THROUGH the kill.  Also reported:
    handoff p99 (gate -> flush -> adopt -> reopen wall clock).  Gates:
    every submitted request answered exactly once (zero client errors,
    answered == submitted), corrections bit-exact vs offline decode,
    the handoff actually fired (deadman-driven — nothing in the storm
    calls failover by hand).  Env knobs: BENCH_FLEET_REQS /
    BENCH_FLEET_SEED."""
    import threading
    from collections import deque

    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import (
        DecodeClient,
        DecodeSession,
        LocalFleet,
    )
    from qldpc_fault_tolerance_tpu.utils import (
        faultinject,
        resilience,
        telemetry,
    )

    reqs = int(os.environ.get("BENCH_FLEET_REQS", "40"))
    seed = int(os.environ.get("BENCH_FLEET_SEED", "18"))
    tenants = 2
    window = 8
    p = 0.05
    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    cls = BP_Decoder_Class(4, "minimum_sum", 0.625)
    params = {"h": code.hx, "p_data": p}
    h_t = np.asarray(code.hx, np.uint8).T

    prev_policy = resilience.current_policy()
    resilience.set_default_policy(resilience.RetryPolicy(
        max_attempts=2, base_delay=0.05, backoff=1.0, jitter=0.0,
        reset_caches=False, degrade_after=1))
    try:
        with _tele_region():
            fleet = LocalFleet(
                lambda: {"hgp_rep3": DecodeSession(
                    "hgp_rep3", decoder_class=cls, params=params,
                    buckets=(32, 64, 128))},
                n_hosts=2, warm=True,
                batcher_kwargs={"max_batch_shots": 64,
                                "max_wait_s": 0.002,
                                "max_dispatch_attempts": 4})
            host, port = fleet.address
            # the kill lands mid-storm: the tick site counts one hit per
            # finished request across all tenants
            plan = faultinject.FaultPlan([
                faultinject.Fault(site="fleet_host_tick",
                                  kind="host_kill", after=reqs)
            ], seed=seed)
            results, errors = [], []

            def worker(idx):
                try:
                    cli = DecodeClient(host, port, tenant=f"tenant{idx}",
                                       reconnect=True, timeout=60.0)
                    rng = np.random.default_rng(1000 * seed + idx)
                    pending = deque()

                    def finish_one():
                        synd, fut = pending.popleft()
                        res = fut.result(timeout=120)
                        results.append((synd, res.corrections))
                        fleet.chaos_tick()

                    for _ in range(reqs):
                        k = int(rng.integers(1, 9))
                        err = (rng.random((k, code.N)) < p).astype(
                            np.uint8)
                        synd = (err @ h_t % 2).astype(np.uint8)
                        pending.append((synd,
                                        cli.submit("hgp_rep3", synd)))
                        if len(pending) >= window:
                            finish_one()
                    while pending:
                        finish_one()
                    cli.close()
                except Exception as exc:  # noqa: BLE001 — gated below
                    errors.append(f"{type(exc).__name__}: {exc}")

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(tenants)]
            t0 = time.perf_counter()
            with plan.active():
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            storm_s = time.perf_counter() - t0
            snap = telemetry.snapshot()
            handoff_durs = fleet.router.handoff_durations()
            handoffs = fleet.router.handoff_report()
            fleet.stop()
    finally:
        resilience.set_default_policy(prev_policy)

    def val(name):
        return snap.get(name, {}).get("value", 0)

    answered = len(results)
    submitted = reqs * tenants
    synd = np.concatenate([s for s, _ in results]) if results else None
    served = np.concatenate([c for _, c in results]) if results else None
    offline = (cls.GetDecoder(params).decode_batch(synd)
               if synd is not None else None)
    bitexact = bool(results and np.array_equal(served, offline))
    exactly_once = bool(not errors and answered == submitted)
    handoff_p99_ms = (round(float(np.percentile(
        1e3 * np.asarray(handoff_durs), 99)), 2)
        if handoff_durs else None)
    req_per_s = round(answered / storm_s, 1) if storm_s else None
    return {
        "metric": f"fleet storm through host_kill (seed={seed}, "
                  f"{submitted} reqs x {tenants} tenants, 2 hosts)",
        "value": req_per_s,
        "unit": "req/s",
        "vs_baseline": None,
        "seed": seed,
        "requests": submitted,
        "answered": answered,
        "storm_s": round(storm_s, 3),
        "fleet": {
            "req_per_s": req_per_s,
            "handoff_p99_ms": handoff_p99_ms,
            "handoffs": handoffs,
        },
        "host_kills": val("serve.host_kills"),
        "replication_pushes": val("router.replication_pushes"),
        "journal_imported": val("serve.journal.imported"),
        "dedup_replayed": val("serve.dedup.replayed"),
        "route_stale": val("serve.route_stale"),
        "reconnects": val("serve.client.reconnects"),
        "client_errors": errors[:4],
        "gates": {
            "exactly_once": exactly_once,
            "bitexact_vs_offline": bitexact,
            "handoff_fired": bool(val("router.handoffs") >= 1
                                  and val("serve.host_kills") >= 1),
        },
    }


def mode_coldstart():
    """Persistent AOT program cache (ISSUE 20): cold-vs-warm time-to-first
    -decode on the session ladder, and fleet handoff latency with the
    warm-start push enabled.

    Arm 1 (TTFD): a fresh empty program cache, then a DecodeSession ladder
    warm + first decode (cold = every rung compiles).  Restart is then
    simulated — ``jax.clear_caches()`` wipes every jit/trace cache and a
    NEW session is built — with only the program cache surviving: the warm
    TTFD is the ladder resolving entirely from cached programs.  Gates:
    warm corrections bit-exact vs the cold (fresh-compile) arm, zero
    compiles and zero retraces on the warm path, speedup >= 5x.

    Arm 2 (handoff): the mode_fleet storm with a seeded ``host_kill``,
    run twice — program cache disabled (cold successor: first adopted
    frame pays a compile) then enabled (router pre-pushes the failing
    family's program keys with the journal; the successor installs them
    at adopt time, BEFORE the first frame arrives).  Gates: warm-push
    fired and missed nothing, exactly-once, bit-exact vs offline.

    ``exec_roundtrip_supported`` is reported so a CPU container's numbers
    (in-memory + stablehlo-fallback artifacts) aren't mistaken for the
    accelerator story, where serialized executables round-trip the disk.
    Env knobs: BENCH_COLDSTART_REQS / BENCH_COLDSTART_SEED."""
    import shutil
    import tempfile
    import threading
    from collections import deque

    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, rep_code
    from qldpc_fault_tolerance_tpu.decoders import BP_Decoder_Class
    from qldpc_fault_tolerance_tpu.serve import (
        DecodeClient,
        DecodeSession,
        LocalFleet,
    )
    from qldpc_fault_tolerance_tpu.utils import (
        faultinject,
        progcache,
        resilience,
        telemetry,
    )

    reqs = int(os.environ.get("BENCH_COLDSTART_REQS", "24"))
    seed = int(os.environ.get("BENCH_COLDSTART_SEED", "20"))
    p = 0.05
    code = hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    cls = BP_Decoder_Class(4, "minimum_sum", 0.625)
    params = {"h": code.hx, "p_data": p}
    h_t = np.asarray(code.hx, np.uint8).T
    buckets = (8, 32, 128)
    rng = np.random.default_rng(seed)
    err0 = (rng.random((8, code.N)) < p).astype(np.uint8)
    synd0 = (err0 @ h_t % 2).astype(np.uint8)

    def ladder_ttfd():
        """Build the session, warm every rung, decode one frame — the
        wall clock a recovering replica pays before its first answer."""
        t0 = time.perf_counter()
        sess = DecodeSession("hgp_rep3", decoder_class=cls, params=params,
                             buckets=buckets)
        sess.warm()
        out = sess.decode(synd0)
        return sess, out, time.perf_counter() - t0

    tmp = tempfile.mkdtemp(prefix="qldpc_progcache_bench_")
    try:
        with _tele_region():
            # --- arm 1: session-ladder TTFD, cold vs warm ---------------
            progcache.configure(tmp)   # fresh dir + empty memory: cold
            sess_cold, out_cold, ttfd_cold = ladder_ttfd()
            cold_compiles = sess_cold.compiles
            # simulated restart: jit/trace caches gone, new session
            # object — only the program cache survives (the in-memory
            # layer models same-process adoption: SessionCache
            # evict/recreate, LocalFleet handoff; the disk layer carries
            # backends whose executables round-trip serialization)
            jax.clear_caches()
            sess_warm, out_warm, ttfd_warm = ladder_ttfd()
            warm_compiles = sess_warm.compiles
            warm_loads = sess_warm.loads
            # zero-retrace warm path: repeat frames must not touch the
            # compiler at all
            before = telemetry.compile_stats().get("jax.retraces", 0)
            out_repeat = sess_warm.decode(synd0)
            retraces = (telemetry.compile_stats().get("jax.retraces", 0)
                        - before)
            bitexact = bool(
                np.array_equal(out_warm.corrections, out_cold.corrections)
                and np.array_equal(out_repeat.corrections,
                                   out_cold.corrections))
            ttfd_stats = progcache.stats()
            ttfd_hit_rate = progcache.hit_rate()

        # --- arm 2: fleet handoff, cold vs warm push --------------------
        prev_policy = resilience.current_policy()
        resilience.set_default_policy(resilience.RetryPolicy(
            max_attempts=2, base_delay=0.05, backoff=1.0, jitter=0.0,
            reset_caches=False, degrade_after=1))

        def storm(arm_seed):
            fleet = LocalFleet(
                lambda: {"hgp_rep3": DecodeSession(
                    "hgp_rep3", decoder_class=cls, params=params,
                    buckets=(32, 64, 128))},
                # warm=False: hosts come up COLD (programs compile on
                # demand), so the successor's family really is unwarmed at
                # adopt time — the push-vs-no-push arms differ only in
                # whether the adopt can load instead of leaving the first
                # frame to compile
                n_hosts=2, warm=False,
                batcher_kwargs={"max_batch_shots": 64,
                                "max_wait_s": 0.002,
                                "max_dispatch_attempts": 4})
            host, port = fleet.address
            plan = faultinject.FaultPlan([
                faultinject.Fault(site="fleet_host_tick",
                                  kind="host_kill", after=reqs)
            ], seed=arm_seed)
            results, errors = [], []

            def worker(idx):
                try:
                    cli = DecodeClient(host, port, tenant=f"tenant{idx}",
                                       reconnect=True, timeout=60.0)
                    w_rng = np.random.default_rng(1000 * arm_seed + idx)
                    pending = deque()

                    def finish_one():
                        synd, fut = pending.popleft()
                        res = fut.result(timeout=120)
                        results.append((synd, res.corrections))
                        fleet.chaos_tick()

                    for _ in range(reqs):
                        k = int(w_rng.integers(1, 9))
                        err = (w_rng.random((k, code.N)) < p).astype(
                            np.uint8)
                        synd = (err @ h_t % 2).astype(np.uint8)
                        pending.append((synd,
                                        cli.submit("hgp_rep3", synd)))
                        if len(pending) >= 8:
                            finish_one()
                    while pending:
                        finish_one()
                    cli.close()
                except Exception as exc:  # noqa: BLE001 — gated below
                    errors.append(f"{type(exc).__name__}: {exc}")

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(2)]
            with plan.active():
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            snap = telemetry.snapshot()
            durs = fleet.router.handoff_durations()
            fleet.stop()
            return results, errors, snap, durs

        try:
            with _tele_region():
                progcache.reset()          # cache OFF: cold successor
                jax.clear_caches()
                res_c, err_c, snap_c, durs_c = storm(seed)
            with _tele_region():
                progcache.configure(tmp)   # cache ON: warm-start push
                jax.clear_caches()
                res_w, err_w, snap_w, durs_w = storm(seed + 1)
        finally:
            resilience.set_default_policy(prev_policy)

        def val(snap, name):
            return snap.get(name, {}).get("value", 0)

        def p99_ms(durs):
            return (round(float(np.percentile(
                1e3 * np.asarray(durs), 99)), 2) if durs else None)

        def check_storm(results, errors):
            answered = len(results)
            synd = (np.concatenate([s for s, _ in results])
                    if results else None)
            served = (np.concatenate([c for _, c in results])
                      if results else None)
            offline = (cls.GetDecoder(params).decode_batch(synd)
                       if synd is not None else None)
            return (bool(not errors and answered == 2 * reqs),
                    bool(results and np.array_equal(served, offline)))

        exact_c, bit_c = check_storm(res_c, err_c)
        exact_w, bit_w = check_storm(res_w, err_w)
        warm_pushed = val(snap_w, "serve.session.warm_loads")
        warm_missed = val(snap_w, "serve.session.warm_load_misses")
        # read the round-trip verdict while the cache is still configured —
        # reset() clears the probe result and would report null
        exec_rt = progcache.exec_roundtrip_supported()
    finally:
        progcache.reset()
        shutil.rmtree(tmp, ignore_errors=True)

    speedup = (round(ttfd_cold / ttfd_warm, 1) if ttfd_warm else None)
    return {
        "metric": "session-ladder TTFD cold vs warm (program cache)",
        "value": speedup,
        "unit": "x_speedup",
        "vs_baseline": None,
        "seed": seed,
        "exec_roundtrip_supported": exec_rt,
        "coldstart": {
            "ttfd_s": round(ttfd_warm, 4),
            "ttfd_cold_s": round(ttfd_cold, 4),
            "ttfd_speedup": speedup,
            "progcache_hit_rate": round(ttfd_hit_rate, 3),
            "handoff_warm_p99_ms": p99_ms(durs_w),
            "handoff_cold_p99_ms": p99_ms(durs_c),
        },
        "ladder": {
            "buckets": list(buckets),
            "cold_compiles": int(cold_compiles),
            "warm_compiles": int(warm_compiles),
            "warm_loads": int(warm_loads),
            "progcache_stats": ttfd_stats,
        },
        "handoff": {
            "requests_per_arm": 2 * reqs,
            "cold": {"answered": len(res_c), "exactly_once": exact_c,
                     "bitexact_vs_offline": bit_c,
                     "host_kills": val(snap_c, "serve.host_kills"),
                     "warm_loads": val(snap_c, "serve.session.warm_loads"),
                     "warm_load_misses": val(
                         snap_c, "serve.session.warm_load_misses"),
                     "client_errors": err_c[:4]},
            "warm": {"answered": len(res_w), "exactly_once": exact_w,
                     "bitexact_vs_offline": bit_w,
                     "host_kills": val(snap_w, "serve.host_kills"),
                     "warm_loads": int(warm_pushed),
                     "warm_load_misses": int(warm_missed),
                     "client_errors": err_w[:4]},
        },
        "gates": {
            "bitexact_vs_fresh_compile": bitexact,
            "warm_compiles_zero": bool(warm_compiles == 0),
            "retraces_after_warmup": int(retraces),
            "ttfd_speedup_ge_5x": bool(speedup is not None
                                       and speedup >= 5.0),
            "handoff_warm_push_fired": bool(warm_pushed >= 1
                                            and warm_missed == 0),
            "handoff_exactly_once": bool(exact_c and exact_w),
            "handoff_bitexact": bool(bit_c and bit_w),
        },
    }


MODES = {
    "bp": mode_bp,
    "bposd": mode_bposd,
    "st_circuit": mode_st_circuit,
    "phenl_cell": mode_phenl_cell,
    "circuit_cell": mode_circuit_cell,
    "sweep": mode_sweep,
    "serve": mode_serve,
    "rare": mode_rare,
    "chaos": mode_chaos,
    "stream": mode_stream,
    "fleet": mode_fleet,
    "coldstart": mode_coldstart,
}


def main():
    mode = os.environ.get("BENCH_MODE", "bp")
    if mode == "all":
        results = {}
        # subprocess modes first: they need the (single, exclusively-held)
        # TPU chip, so they must run before this process's own JAX
        # initialization claims it for the other modes
        for name in ("phenl_cell", "circuit_cell", "bp", "bposd",
                     "st_circuit", "sweep", "serve", "rare", "chaos"):
            results[name] = MODES[name]()
            print(json.dumps(results[name]))
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BENCH_MODES.json"), "w") as f:
            json.dump(results, f, indent=1)
        return
    # driver contract: exactly ONE json line
    print(json.dumps(MODES[mode]()))


if __name__ == "__main__":
    main()
