"""Device-mesh sharding of the Monte-Carlo shot axis.

The reference's only parallelism is a fork/queue process pool over shots
(parmap, src/Simulators.py:45-61) with mp.Queue as the "communication
backend".  The TPU-native mapping: shots are a batch axis inside one chip
(vmap-style batching in the kernels) and shard across chips over ICI via
``shard_map`` on a 1-D ``Mesh``; the only collective is a ``psum`` of failure
counts.  Multi-host sweeps additionally split the (code, p, cycles) grid by
``jax.process_index()`` (see sweep/family.py) so only scalar results cross
DCN.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["shot_mesh", "sharded_failure_count", "split_keys_for_mesh"]

SHOT_AXIS = "shots"


def shot_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices with a 'shots' axis."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices, (SHOT_AXIS,))


def split_keys_for_mesh(key, mesh: Mesh):
    """One PRNG key per mesh device, stacked on the shot axis."""
    n = mesh.devices.size
    return jax.random.split(key, n)


def sharded_failure_count(device_fn, mesh: Mesh, per_device_batch: int):
    """Build a jitted function (keys (n_dev,) -> total failures scalar).

    ``device_fn(key, batch_size) -> (B,) bool/int failure flags`` must be pure
    device code (no host callbacks).  Each mesh device runs its own batch from
    its own key; counts are psum-reduced over ICI.
    """

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(SHOT_AXIS),),
        out_specs=P(),
    )
    def run(keys):
        fail = device_fn(keys[0], per_device_batch)
        local = jnp.sum(fail.astype(jnp.int32))
        return jax.lax.psum(local, SHOT_AXIS)

    return run
