"""Device-mesh sharding of the Monte-Carlo shot axis.

The reference's only parallelism is a fork/queue process pool over shots
(parmap, src/Simulators.py:45-61) with mp.Queue as the "communication
backend".  The TPU-native mapping: shots are a batch axis inside one chip
(vmap-style batching in the kernels) and shard across chips over ICI via
``shard_map`` on a 1-D ``Mesh``; the only collective is a ``psum`` of failure
counts.  Multi-host sweeps additionally split the (code, p, cycles) grid by
``jax.process_index()`` (see sweep/family.py) so only scalar results cross
DCN.
"""
from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = [
    "shot_mesh",
    "sharded_batch_stats",
    "split_keys_for_mesh",
]

SHOT_AXIS = "shots"


def shot_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices with a 'shots' axis."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices, (SHOT_AXIS,))


def split_keys_for_mesh(key, mesh: Mesh):
    """One PRNG key per mesh device, stacked on the shot axis."""
    n = mesh.devices.size
    return jax.random.split(key, n)


def sharded_batch_stats(stats_fn, mesh: Mesh):
    """Build a jitted function (keys (n_dev,) -> (count, min_weight) scalars).

    ``stats_fn(key) -> (int32 failure count, int32 min logical weight)`` runs
    one per-device batch of pure device code (no host callbacks).  This is
    the mesh unit shared by every MC engine: the count psum-reduces and the
    diagnostic min-logical-weight pmin-reduces over ICI — the only
    cross-device traffic is these two scalars.
    """

    # check_vma=False: engine internals scan with replicated zero-init
    # carries that become shot-varying after the first step; the varying-
    # manual-axes checker rejects that even though the program is correct.
    # Engines stay mesh-agnostic; correctness is pinned by the exact
    # sharded-vs-replay equality tests (tests/test_parallel.py).
    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(SHOT_AXIS),),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def run(keys):
        count, min_w = stats_fn(keys[0])
        return (
            jax.lax.psum(count, SHOT_AXIS),
            jax.lax.pmin(min_w, SHOT_AXIS),
        )

    return run
