"""Device-mesh sharding of the Monte-Carlo shot axis + the dispatch-amortized
megabatch driver.

The reference's only parallelism is a fork/queue process pool over shots
(parmap, src/Simulators.py:45-61) with mp.Queue as the "communication
backend".  The TPU-native mapping: shots are a batch axis inside one chip
(vmap-style batching in the kernels) and shard across chips over ICI via
``shard_map`` on a 1-D ``Mesh``; the only collective is a ``psum`` of failure
counts.  Multi-host sweeps additionally split the (code, p, cycles) grid by
``jax.process_index()`` (see sweep/family.py) so only scalar results cross
DCN.

Dispatch amortization (``MegabatchDriver``): the tunneled chip pays
~40-100ms of fixed latency per dispatch and per host fetch, so per-batch
dispatches dominate short sweeps.  The driver scans ``k_inner`` batches
inside ONE compiled dispatch (a ``lax.scan`` over the batch index, with the
accumulator carry donated so XLA reuses the buffers in place) and drains
results to the host double-buffered: while megabatch d+1 computes, megabatch
d's values cross the wire.  Fixed latency is paid once per ``k_inner``
batches instead of once per batch.
"""
from __future__ import annotations

import functools
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.bp import _LruCache  # shared bounded memo (see ops/bp.py)
from ..utils import faultinject, profiling, resilience, telemetry

__all__ = [
    "shot_mesh",
    "sharded_batch_stats",
    "split_keys_for_mesh",
    "replay_fold",
    "MegabatchDriver",
    "CellFusedDriver",
    "count_min_driver",
    "cell_fused_driver",
    "drain_double_buffered",
]


def replay_fold(outs, n_w: int = 0, has_tele: bool = False):
    """Fold per-logical-device stats outputs exactly as the mesh
    collectives would — counts psum→sum, min-weight pmin→minimum, the
    ``n_w`` float weight-moment tracks sum, trailing telemetry vector sum
    — sequentially in device order.  The ONE implementation of the
    ``mesh_replan`` exactness contract (integer folds are order-free, so
    replayed counts are bit-exact with the collective; float moments
    agree up to summation order), shared by ``CellFusedDriver``'s replay
    step and ``sim/common.mesh_batch_stats``'s replay runner so the two
    paths cannot drift.  ``outs[i]`` is ``(count, min_w, *moments[,
    tele])`` for logical device ``i``."""
    width = 2 + n_w + (1 if has_tele else 0)
    res = list(outs[0][:width])
    for out in outs[1:]:
        res[0] = res[0] + out[0]
        res[1] = jnp.minimum(res[1], out[1])
        for i in range(n_w):
            res[2 + i] = res[2 + i] + out[2 + i]
        if has_tele:
            res[2 + n_w] = res[2 + n_w] + out[2 + n_w]
    return tuple(res)

# engine stats drivers, memoized on (tag, cfg, k_inner) — see count_min_driver
_engine_driver_cache = _LruCache()

SHOT_AXIS = "shots"


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma):
    """jax.shard_map across the 0.4/0.5+ API move (jax.experimental.shard_map
    with ``check_rep`` -> jax.shard_map with ``check_vma``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def shot_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices with a 'shots' axis."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices, (SHOT_AXIS,))


def split_keys_for_mesh(key, mesh: Mesh):
    """One PRNG key per mesh device, stacked on the shot axis."""
    n = mesh.devices.size
    return jax.random.split(key, n)


def sharded_batch_stats(stats_fn, mesh: Mesh, has_tele: bool = False):
    """Build a jitted function (keys (n_dev,) -> (count, min_weight) scalars).

    ``stats_fn(key) -> (int32 failure count, int32 min logical weight)`` runs
    one per-device batch of pure device code (no host callbacks).  This is
    the mesh unit shared by every MC engine: the count psum-reduces and the
    diagnostic min-logical-weight pmin-reduces over ICI — the only
    cross-device traffic is these two scalars.

    ``has_tele``: ``stats_fn`` returns a third element, the (TELE_LEN,)
    int32 device telemetry vector (utils.telemetry), which psum-reduces
    alongside the count so sharded runs report decoder statistics too.
    """

    # check_vma=False: engine internals scan with replicated zero-init
    # carries that become shot-varying after the first step; the varying-
    # manual-axes checker rejects that even though the program is correct.
    # Engines stay mesh-agnostic; correctness is pinned by the exact
    # sharded-vs-replay equality tests (tests/test_parallel.py).
    @jax.jit
    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(SHOT_AXIS),),
        out_specs=(P(), P(), P()) if has_tele else (P(), P()),
        check_vma=False,
    )
    def run(keys):
        stats = stats_fn(keys[0])
        out = (
            jax.lax.psum(stats[0], SHOT_AXIS),
            jax.lax.pmin(stats[1], SHOT_AXIS),
        )
        if has_tele:
            out = out + (jax.lax.psum(stats[2], SHOT_AXIS),)
        return out

    return run


# ---------------------------------------------------------------------------
# Dispatch-amortized megabatch driver
# ---------------------------------------------------------------------------
def _carry_donation() -> bool:
    """Donate the accumulator carry into dispatches except on backends that
    don't implement donation (CPU), where it only produces warning noise."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


class MegabatchDriver:
    """Run ``stats_fn(key, *extra)`` for many batches, ``k_inner`` per
    dispatch.

    stats_fn: (key, *extra) -> pytree of device values (typically scalars).
              ``extra`` rides through ``run`` untraced-by-name (arrays /
              pytrees — e.g. an engine's device state), so one driver keyed
              on a hashable config serves every same-shape simulator (a
              p-sweep compiles once).
    combine:  (carry, out) -> carry — the on-device fold (count sums,
              min-weights jnp.minimum, ...).
    init_fn:  () -> initial carry pytree (device values).

    ``run`` folds everything on device and returns the carry WITHOUT a host
    sync — the caller's materialization is the only round-trip.  ``run_keys``
    streams per-megabatch carries to the host double-buffered for callers
    that need intermediate values (target-failure early stopping).

    The carry is donated into each dispatch (`donate_argnums`) so XLA
    accumulates in place instead of allocating a fresh buffer chain; donation
    is skipped on backends that don't implement it (CPU) to keep test logs
    clean.
    """

    def __init__(self, stats_fn, combine, init_fn, k_inner: int = 8):
        self.k_inner = max(1, int(k_inner))
        self._init_fn = init_fn
        self.dispatches = 0  # cumulative, observable by bench
        # an optional dispatch-level DegradationLadder (CellFusedDriver
        # installs its mesh_replan rung here): stepped by the retry policy
        # on repeated transient faults, and immediately on "resource"
        # faults like MeshDeviceLoss — where retrying the same program is
        # a guaranteed loss but a replan clears it
        self._dispatch_ladder = None
        # cost-model accounting label (utils.profiling.capture_jit_cost):
        # the factory helpers overwrite it with the engine tag
        self.cost_label = "megabatch"

        def mega(carry, key, offset, *extra):
            def body(c, j):
                out = stats_fn(jax.random.fold_in(key, offset + j), *extra)
                return combine(c, out), None

            carry, _ = jax.lax.scan(body, carry, jnp.arange(self.k_inner))
            return carry

        self._donated = _carry_donation()
        self._mega = jax.jit(
            mega, donate_argnums=(0,) if self._donated else ())
        # persistent-cache identity (ISSUE 20): the memo factories set it
        # from their (repr-stable) memo key; None = jit-only dispatch.
        # With a progkey AND an active utils.progcache, dispatches resolve
        # an AOT executable through the cache so a rerun in a fresh
        # process loads the fused-sweep programs instead of compiling.
        self.progkey = None
        self._aot = None  # (mem generation, arg signature, compiled)

    def _aot_program(self, args):
        """The persistent-cache AOT executable for ``args``, or None (cache
        inactive / no progkey / mesh-degraded program — the replay program
        is keyed by runtime damage, exactly what a content cache must not
        serve).  Resolution is memoized per (cache generation, arg
        signature); a ``reset_device_state`` bumps the generation, so dead
        device handles are never redispatched."""
        if self.progkey is None or getattr(self, "mesh_degraded", False):
            return None
        from ..utils import progcache

        if not progcache.active():
            return None
        gen = progcache.memory_generation()
        argsig = tuple(
            (tuple(np.shape(x)), str(getattr(x, "dtype",
                                             type(x).__name__)))
            for x in jax.tree_util.tree_leaves(args))
        cached = self._aot
        if cached is not None and cached[0] == gen and cached[1] == argsig:
            return cached[2]
        try:
            compiled, _source = progcache.compile_cached(
                self._mega, args, kind="driver.megabatch",
                parts={"progkey": self.progkey, "avals": argsig,
                       "donate": bool(self._donated),
                       "k_inner": self.k_inner},
                label=str(self.cost_label))
        except Exception:  # noqa: BLE001 — cache trouble: jit path serves
            telemetry.count("driver.progcache_errors")
            self.progkey = None
            return None
        self._aot = (gen, argsig, compiled)
        return compiled

    def _dispatch(self, carry, key, start, *extra):
        """One guarded megabatch dispatch.  Transient faults retry under the
        active resilience policy with the SAME pre-dispatch carry (intact
        for injected faults and submit-time failures) — but only on
        non-donating backends: with donation the failed dispatch may
        already have consumed the carry buffer, so the fault escalates to
        the engine-level retry, which restarts or resumes the run."""

        def attempt():
            faultinject.site("megabatch_dispatch")
            args = (carry, key, jnp.asarray(start, jnp.int32)) + extra
            if profiling.enabled():
                # one extra lower+compile per (label, shape), memoized —
                # the cost table entry every profiled run derives
                # mfu/hbm_util from (lower() reads avals only; it cannot
                # consume the donated carry)
                profiling.capture_jit_cost(self.cost_label, self._mega,
                                           *args)
            prog = self._aot_program(args)
            with telemetry.span("megabatch_dispatch"):
                t0 = time.perf_counter()
                if prog is not None:
                    try:
                        out = prog(*args)
                    except (TypeError, ValueError):
                        # an argument the AOT signature refuses (raised at
                        # argument binding, before the donated carry is
                        # consumed): dispatch through jit and stop trying
                        telemetry.count("driver.progcache_fallbacks")
                        self.progkey = None
                        self._aot = None
                        out = self._mega(*args)
                else:
                    out = self._mega(*args)
                launch_s = time.perf_counter() - t0
                if profiling.deep_timing_enabled():
                    jax.block_until_ready(out)
                    profiling.record_dispatch(launch_s,
                                              time.perf_counter() - t0)
                else:
                    profiling.record_dispatch(launch_s)
            self.dispatches += 1
            telemetry.count("driver.dispatches")
            return out

        if self._donated:
            return attempt()
        ladder = self._dispatch_ladder
        return resilience.run_cell(
            attempt, label="megabatch_dispatch",
            degrade=None if ladder is None else ladder.step)

    def run(self, key, n_batches: int, *extra, start: int = 0, carry0=None):
        """Fold ``n_batches`` batches (rounded UP to a k_inner multiple so
        every dispatch reuses one compiled scan shape).  Returns
        ``(carry, batches_run)``; the carry is unsynced device values.
        ``start``/``carry0`` resume the fold mid-stream: batches before
        ``start`` are skipped and ``carry0`` (their recorded fold) seeds
        the carry — the key stream is positional (``fold_in(key, start+j)``)
        so a resumed run replays the exact remaining draws."""
        k = self.k_inner
        n_run = -(-int(n_batches) // k) * k
        carry = self._init_fn() if carry0 is None else carry0
        for s in range(int(start), n_run, k):
            carry = self._dispatch(carry, key, s, *extra)
        telemetry.count("driver.batches", max(0, n_run - int(start)))
        return carry, n_run

    def run_keys(self, key, n_batches: int, *extra, start: int = 0,
                 carry0=None):
        """Like ``run`` but yields ``(carry_after_megabatch, batches_so_far)``
        per dispatch, double-buffered via ``drain_double_buffered``:
        megabatch d's carry is snapshotted while d+1 computes, so
        early-stopping callers see fresh counts at ~zero added latency.
        The snapshot copies the carry (the live carry keeps accumulating /
        being donated).  Drain fetches run under the resilience watchdog
        (a ``device_get`` on a dead worker otherwise blocks forever) and a
        timed-out or transiently-failed fetch retries against the live
        snapshot — bit-exact, the device values survive the retry.
        ``start``/``carry0`` resume mid-stream as in ``run``."""
        k = self.k_inner
        n_run = -(-int(n_batches) // k) * k
        carry_box = [self._init_fn() if carry0 is None else carry0]

        def launch(s):
            carry_box[0] = self._dispatch(carry_box[0], key, s, *extra)
            telemetry.count("driver.batches", k)
            snap = jax.tree_util.tree_map(lambda x: x + 0, carry_box[0])
            return snap, s + k

        def finish(item):
            snap, done = item

            def fetch():
                faultinject.site("megabatch_drain")
                return jax.device_get(snap)

            with telemetry.span("megabatch_drain"):
                t0 = time.perf_counter()
                host = resilience.guarded_fetch(fetch,
                                                label="megabatch_drain")
                profiling.record_host_sync(time.perf_counter() - t0)
                return host, done

        yield from drain_double_buffered(launch, finish,
                                         range(int(start), n_run, k))


def count_min_driver(tag: str, cfg, k_inner: int, stats_fn,
                     min_init: int, tele_len: int = 0,
                     weighted: bool = False) -> MegabatchDriver:
    """Memoized MegabatchDriver for the engines' shared stats shape: a
    ``(failure count, min logical weight)`` fold.  Keyed on
    ``(tag, cfg, k_inner, tele_len, weighted)`` so same-structure simulator
    instances (p- and cycle-sweeps: state values change, program doesn't)
    reuse one compiled scan.  ``stats_fn(key, *extra) -> (i32 count,
    i32 min_w)``; ``min_init`` seeds the min-weight track (the code
    length N).

    ``tele_len > 0``: the stats tuple carries a trailing element — a
    ``(tele_len,)`` int32 device telemetry vector (utils.telemetry slot
    layout) summed across batches alongside the counts, so per-shot decoder
    statistics reach the host at the run's one existing sync.

    ``weighted``: the importance-sampled carry — ``stats_fn`` returns
    ``(count, min_w, s1, s2, w1, w2[, tele])`` with the four float32
    weight moments (Σw·I, Σw²·I, Σw, Σw²) summed through the fold exactly
    like the counts, so a weighted run keeps the engines'
    one-sync-per-megabatch discipline."""

    def make():
        n_w = 4 if weighted else 0

        def combine(c, o):
            out = [c[0] + o[0], jnp.minimum(c[1], o[1])]
            out += [c[2 + i] + o[2 + i] for i in range(n_w)]
            if tele_len:
                out.append(c[2 + n_w] + o[2 + n_w])
            return tuple(out)

        def init():
            carry = [jnp.zeros((), jnp.int32),
                     jnp.asarray(min_init, jnp.int32)]
            carry += [jnp.zeros((), jnp.float32)] * n_w
            if tele_len:
                carry.append(jnp.zeros((tele_len,), jnp.int32))
            return tuple(carry)

        driver = MegabatchDriver(stats_fn, combine, init, k_inner=k_inner)
        driver.cost_label = f"megabatch.{tag}"
        # the memo key doubles as the persistent-cache identity: the cfg
        # tuples are primitives + device_static tuples (repr-stable), so
        # a rerun in a fresh process addresses the same artifact
        driver.progkey = (tag, cfg, k_inner, tele_len, weighted, min_init)
        return driver

    return _engine_driver_cache.get(
        (tag, cfg, k_inner, tele_len, weighted), make)


# ---------------------------------------------------------------------------
# Cell-fused megabatch driver (p-axis batching of a sweep grid)
# ---------------------------------------------------------------------------
class CellFusedDriver(MegabatchDriver):
    """Megabatch driver for a FUSED sweep bucket: one dispatch advances
    ``n_cells`` lanes, each running ``k_inner`` batches of one (code, p,
    logical_type) cell's pipeline, folding a cell-masked carry of per-CELL
    counters instead of the base class's scalar fold.

    stats_fn: ``(keys (L,), lane_cell (L,), active (L,), *extra) ->
    (count (L,) i32, min_w (L,) i32[, tele (tele_len,) i32])`` — the
    per-lane batch statistics.  The stats_fn owns the cell-state gather
    (lane ``l`` runs cell ``lane_cell[l]``'s p-dependent state under vmap)
    and masks its own telemetry by ``active``; the driver masks counts.

    Carry: ``(failures (C,), shots (C,), min_w (C,)[, tele (T,)])`` int32.

    The lane plan rides through every dispatch as TRACED vectors, so
    reallocating lanes between megabatches (adaptive shot reallocation)
    reuses one compiled program:

      lane_base (L,)    absolute batch index of lane l's first batch
      lane_stride (L,)  index step between lane l's successive batches
                        (= lanes co-serving that cell, so they interleave
                        disjoint indices)
      lane_cell (L,)    cell index served by lane l
      active (L,)       inactive lanes compute but accumulate nothing

    Batch ``j`` of lane ``l`` draws from
    ``fold_in(key, lane_base[l] + j*lane_stride[l])`` — the same positional
    stream the serial megabatch driver uses — so every cell's draws are
    bit-exact with its unfused run no matter which lane (or how many lanes)
    execute them.

    ``mesh``: shard the fused batch on the SHOT axis — every mesh device
    runs all lanes at the lane batch size with its own fold of the key
    (``fold_in(key_lane, axis_index)``, matching the serial mesh path's
    per-device streams) and the per-lane counts psum-reduce over ICI.
    Shots per lane-batch then scale by the device count.

    ``weighted``: the importance-sampled cell fold — ``stats_fn``
    additionally returns four (L,) float32 per-lane weight moments
    ``(s1, s2, w1, w2)`` after ``(count, min_w)``, accumulated into
    per-CELL planes through the same lane-plan scatter as the counts, so
    rare-event cells ride the adaptive lane reallocation unchanged.  Carry
    becomes ``(failures, shots, min_w, s1, s2, w1, w2[, tele])``.

    Elastic mesh degrade (ISSUE 14): a mesh-sharded driver installs a
    one-rung dispatch-level DegradationLadder — ``mesh_replan`` — that the
    retry policy steps when a dispatch dies with a device-loss /
    "resource" fault.  ``degrade_mesh()`` rebuilds the mega program with
    the SAME per-logical-device key folds (``fold_in(key_lane, d)`` for
    every d of the ORIGINAL device count) executed sequentially on the
    surviving default device instead of collectively over ICI, so the
    replanned run consumes the identical key streams: integer counts and
    min-weights are bit-exact with the uninterrupted mesh run, float
    weight moments agree up to collective-vs-sequential summation order.
    Shots accounting is unchanged (the logical stream count is what it
    was).  The retry then re-dispatches the intact pre-dispatch carry —
    mid-megabatch recovery with no lost or double-counted batches.  On
    DONATING backends (TPU) the dispatch-level retry is disabled (the
    carry may already be consumed), so a device loss escalates to the
    cell-level retry as before — the replan rung serves the non-donating
    (CPU / forced-host) paths and the chaos tests that prove the
    semantics.
    """

    def __init__(self, stats_fn, n_cells: int, batch_size: int,
                 k_inner: int, min_init: int, tele_len: int = 0, mesh=None,
                 weighted: bool = False):
        self.k_inner = max(1, int(k_inner))
        self.n_cells = int(n_cells)
        self.batch_size = int(batch_size)
        self.tele_len = int(tele_len)
        self.weighted = bool(weighted)
        self._mesh = mesh
        self.dispatches = 0
        self.cost_label = "fused_cells"
        self.mesh_degraded = False
        n_dev = 1 if mesh is None else mesh.devices.size
        self._n_dev = n_dev
        shots_inc = jnp.int32(self.batch_size * n_dev)
        big = jnp.int32(np.iinfo(np.int32).max)
        n_w = 4 if weighted else 0

        def init_fn():
            carry = (jnp.zeros((self.n_cells,), jnp.int32),
                     jnp.zeros((self.n_cells,), jnp.int32),
                     jnp.full((self.n_cells,), min_init, jnp.int32))
            carry += (jnp.zeros((self.n_cells,), jnp.float32),) * n_w
            if tele_len:
                carry += (jnp.zeros((tele_len,), jnp.int32),)
            return carry

        def step_mesh(keys, lane_cell, active, *extra):
            if mesh is None:
                return stats_fn(keys, lane_cell, active, *extra)

            def local(keys, lane_cell, active, *extra):
                d = jax.lax.axis_index(SHOT_AXIS)
                dev_keys = jax.vmap(
                    lambda k0: jax.random.fold_in(k0, d))(keys)
                out = stats_fn(dev_keys, lane_cell, active, *extra)
                res = (jax.lax.psum(out[0], SHOT_AXIS),
                       jax.lax.pmin(out[1], SHOT_AXIS))
                res += tuple(jax.lax.psum(out[2 + i], SHOT_AXIS)
                             for i in range(n_w))
                if tele_len:
                    res += (jax.lax.psum(out[2 + n_w], SHOT_AXIS),)
                return res

            # all inputs replicated, outputs reduced -> replicated; the
            # only cross-device traffic is the per-cell count vectors
            return _shard_map(
                local, mesh=mesh,
                in_specs=(P(),) * (3 + len(extra)),
                out_specs=(P(), P()) + (P(),) * n_w
                + ((P(),) if tele_len else ()),
                check_vma=False,
            )(keys, lane_cell, active, *extra)

        def step_replay(keys, lane_cell, active, *extra):
            # the mesh_replan rung: run the SAME n_dev logical key streams
            # sequentially on the surviving device and fold them exactly
            # as the psum/pmin would — integer-exact, key-identical
            outs = []
            for d in range(n_dev):
                dev_keys = jax.vmap(
                    lambda k0, _d=d: jax.random.fold_in(k0, _d))(keys)
                outs.append(stats_fn(dev_keys, lane_cell, active, *extra))
            return replay_fold(outs, n_w=n_w, has_tele=bool(tele_len))

        def make_mega(step):
            def mega(carry, key, lane_base, lane_stride, lane_cell, active,
                     *extra):
                def body(c, j):
                    b_idx = lane_base + j * lane_stride
                    keys = jax.vmap(
                        lambda b: jax.random.fold_in(key, b))(b_idx)
                    out = step(keys, lane_cell, active, *extra)
                    cnt, mw = out[0], out[1]
                    fail = c[0].at[lane_cell].add(
                        jnp.where(active, cnt, 0), mode="drop")
                    shots = c[1].at[lane_cell].add(
                        jnp.where(active, shots_inc, 0), mode="drop")
                    mws = c[2].at[lane_cell].min(
                        jnp.where(active, mw, big), mode="drop")
                    new = (fail, shots, mws)
                    new += tuple(
                        c[3 + i].at[lane_cell].add(
                            jnp.where(active, out[2 + i], 0.0), mode="drop")
                        for i in range(n_w))
                    if tele_len:
                        new += (c[3 + n_w] + out[2 + n_w],)
                    return new, None

                carry, _ = jax.lax.scan(body, carry,
                                        jnp.arange(self.k_inner))
                return carry

            return mega

        self._init_fn = init_fn
        self._donated = _carry_donation()
        self._jit_mega = lambda step: jax.jit(
            make_mega(step), donate_argnums=(0,) if self._donated else ())
        self._step_replay = step_replay
        self._mega = self._jit_mega(step_mesh)
        self.progkey = None
        self._aot = None
        self._dispatch_ladder = None
        if mesh is not None:
            self._dispatch_ladder = resilience.DegradationLadder(
                [("mesh_replan", self.degrade_mesh)])
        # lane plan of the fixed-budget stream, hoisted (device constants):
        # lane l <-> cell l, every cell advancing in lockstep —
        # bit-identical boundaries to the serial per-cell megabatch stream
        self._uniform = (jnp.ones((self.n_cells,), jnp.int32),
                         jnp.arange(self.n_cells, dtype=jnp.int32),
                         jnp.ones((self.n_cells,), bool))

    def degrade_mesh(self) -> None:
        """The ``mesh_replan`` rung: swap the mega program for the
        logical-stream replay (see class docstring).  Idempotent; a no-op
        for unmeshed drivers.  The NEXT dispatch attempt — typically the
        retry re-dispatching the intact carry — runs replanned."""
        if self._mesh is None or self.mesh_degraded:
            return
        self.mesh_degraded = True
        telemetry.count("mesh.replans")
        self._mega = self._jit_mega(self._step_replay)
        # the cached AOT program is the MESH program; mesh_degraded also
        # short-circuits _aot_program so the replay never hits the cache
        self._aot = None

    def dispatch_plan(self, carry, key, plan, *extra):
        """One guarded dispatch under an explicit host lane plan
        ``(lane_base, lane_stride, lane_cell, active)`` (adaptive mode)."""
        base, stride, cell, active = plan
        telemetry.count("driver.batches",
                        self.k_inner * int(np.asarray(active).sum()))
        return self._dispatch(
            carry, key, np.asarray(base, np.int32),
            np.asarray(stride, np.int32), np.asarray(cell, np.int32),
            np.asarray(active, bool), *extra)

    def run_plan(self, key, n_batches: int, *extra, start: int = 0,
                 carry0=None):
        """Fixed-budget fold: every cell runs batches ``[start, n_run)``
        (rounded up to a k_inner multiple), one lane per cell, no host
        sync — the caller's materialization is the only round-trip.
        Delegates to the base ``run`` with the hoisted uniform lane plan
        threaded through ``extra`` (the scalar dispatch start broadcasts
        against the stride vector inside the mega program);
        ``start``/``carry0`` resume the fold mid-stream as there.  The
        extra batch accounting covers the lanes beyond the base class's
        one-batch-per-step count."""
        stride, lane_cell, active = self._uniform
        carry, n_run = self.run(key, n_batches, stride, lane_cell, active,
                                *extra, start=start, carry0=carry0)
        telemetry.count("driver.batches",
                        max(0, n_run - int(start)) * (self.n_cells - 1))
        return carry, n_run

    def run_plan_keys(self, key, n_batches: int, *extra, start: int = 0,
                      carry0=None):
        """Like ``run_plan`` but yields ``(host_carry, batches_done)`` per
        dispatch — the base ``run_keys`` double-buffered watchdog-guarded
        drain under the uniform lane plan; the streaming path for per-cell
        progress persistence."""
        stride, lane_cell, active = self._uniform
        for host, done in self.run_keys(key, n_batches, stride, lane_cell,
                                        active, *extra, start=start,
                                        carry0=carry0):
            telemetry.count("driver.batches",
                            self.k_inner * (self.n_cells - 1))
            yield host, done


def cell_fused_driver(tag: str, cfg, n_cells: int, k_inner: int, stats_fn,
                      *, min_init: int, batch_size: int, tele_len: int = 0,
                      mesh=None, state_key=(),
                      weighted: bool = False) -> CellFusedDriver:
    """Memoized CellFusedDriver, keyed on the fused program identity:
    engine tag + hashable cfg + cell count + chunk + telemetry length +
    mesh + ``state_key`` (the bucket's state-stacking layout — which leaves
    are per-cell vs shared changes the traced program) + the weighted-carry
    flag.  Same-shape buckets (another code of equal shape, the next p-grid
    over the same code) reuse one compiled scan."""

    def make():
        driver = CellFusedDriver(stats_fn, n_cells, batch_size, k_inner,
                                 min_init, tele_len=tele_len, mesh=mesh,
                                 weighted=weighted)
        driver.cost_label = f"fused_cells.{tag}"
        # persistent-cache identity: the memo key minus the raw mesh
        # object (whose repr carries process-local device ids) — the mesh
        # contributes its device count; the fingerprint half of the cache
        # key already pins device kind and topology
        driver.progkey = ("cells", tag, cfg, n_cells, k_inner, tele_len,
                          driver._n_dev, state_key, batch_size, weighted,
                          min_init)
        return driver

    return _engine_driver_cache.get(
        ("cells", tag, cfg, n_cells, k_inner, tele_len, mesh, state_key,
         batch_size, weighted), make)


def drain_double_buffered(launch, finish, items, depth: int = 2):
    """Generic double-buffered async host drain: keep ``depth`` launched
    device payloads in flight; yield ``finish(payload)`` host results in
    order.  ``launch`` must only enqueue async device work; ``finish`` is
    where the device->host transfer (and any host postprocess) happens, so
    megabatch d+1's compute overlaps megabatch d's drain."""
    pending = deque()
    for it in items:
        pending.append(launch(it))
        telemetry.set_gauge("driver.drain_depth", len(pending))
        if len(pending) >= depth:
            yield finish(pending.popleft())
    while pending:
        yield finish(pending.popleft())
