"""Multi-host sharding of the sweep grid.

Shot batches shard across the chips of one host over ICI (shots.py).  Across
hosts, the (code, p, cycles) *grid* is what scales: every JAX process owns a
round-robin subset of cells, runs them on its local chips, and only the
scalar per-cell results cross DCN in one allgather at the end — the TPU
mapping of the reference's single-host process pool (SURVEY §2.3).
"""
from __future__ import annotations

import numpy as np

__all__ = ["process_cell_owner", "merge_cell_results"]


def process_cell_owner(num_cells: int):
    """Boolean mask of the cells this process owns (round-robin)."""
    import jax

    pi, pc = jax.process_index(), jax.process_count()
    return np.asarray([(i % pc) == pi for i in range(num_cells)])


def merge_cell_results(local_values: np.ndarray) -> np.ndarray:
    """Combine per-cell results across processes.

    ``local_values``: float array with this process's cells filled and every
    remote cell NaN.  Returns the fully-populated array on every process
    (single-process: identity).  Uses a max-reduce over the process axis —
    NaN-safe because exactly one process owns each cell.
    """
    import jax

    if jax.process_count() == 1:
        return local_values
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(
        np.nan_to_num(local_values, nan=-np.inf)
    )
    merged = np.max(stacked, axis=0)
    if np.isneginf(merged).any():
        raise RuntimeError("some sweep cells were computed by no process")
    return merged
