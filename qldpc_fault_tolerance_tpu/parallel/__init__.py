from .shots import SHOT_AXIS, sharded_failure_count, shot_mesh, split_keys_for_mesh

__all__ = ["SHOT_AXIS", "sharded_failure_count", "shot_mesh", "split_keys_for_mesh"]
