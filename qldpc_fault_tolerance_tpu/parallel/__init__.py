from .grid import merge_cell_results, process_cell_owner
from .shots import (
    SHOT_AXIS,
    MegabatchDriver,
    count_min_driver,
    drain_double_buffered,
    replay_fold,
    sharded_batch_stats,
    shot_mesh,
    split_keys_for_mesh,
)

__all__ = [
    "SHOT_AXIS",
    "MegabatchDriver",
    "count_min_driver",
    "drain_double_buffered",
    "replay_fold",
    "sharded_batch_stats",
    "shot_mesh",
    "split_keys_for_mesh",
    "process_cell_owner",
    "merge_cell_results",
]
