"""TPU-native QLDPC fault-tolerance simulation framework.

A ground-up JAX/XLA rebuild of the capabilities of
deltaXdeltaQ/QLDPC_Fault_Tolerance: logical-error-rate / threshold /
effective-distance estimation for CSS LDPC codes under code-capacity,
phenomenological, and circuit-level noise, with BP / BP+OSD and space-time
decoders.

Layers (bottom to top):
  codes/     CSS code objects, GF(2) linalg, HGP construction, loaders, code gen
  ops/       TPU kernels: batched min-sum/product-sum BP, GF(2) matmul
  noise/     PRNG-keyed error samplers (pure JAX)
  decoders/  decoder objects + factory classes (params-dict contract of the
             reference's DecoderClass.GetDecoder), host C++ OSD fallback
  circuits/  circuit IR, CX scheduling, noise plugin, TPU Pauli-frame detector
             sampler, detector-error-model extraction
  sim/       Monte-Carlo engines (data / phenom / phenom-ST / circuit / circuit-ST)
  parallel/  device-mesh sharding of the shot/grid axes
  sweep/     code-family orchestration, threshold & distance fits
  compat/    drop-in shims for the reference module/API names
"""

__version__ = "0.1.0"

from . import codes

__all__ = ["codes", "__version__"]


def __getattr__(name):
    # heavier subpackages (jit compilation, scipy) load lazily
    if name in ("ops", "noise", "decoders", "circuits", "sim", "parallel",
                "sweep", "compat", "utils"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
