"""TPU-native QLDPC fault-tolerance simulation framework.

A ground-up JAX/XLA rebuild of the capabilities of
deltaXdeltaQ/QLDPC_Fault_Tolerance: logical-error-rate / threshold /
effective-distance estimation for CSS LDPC codes under code-capacity,
phenomenological, and circuit-level noise, with BP / BP+OSD and space-time
decoders.

Layers (bottom to top):
  codes/     CSS code objects, GF(2) linalg, HGP construction, loaders, code gen
  ops/       TPU kernels: batched min-sum/product-sum BP, GF(2) matmul
  noise/     PRNG-keyed error samplers (pure JAX)
  decoders/  decoder objects + factory classes (params-dict contract of the
             reference's DecoderClass.GetDecoder), host C++ OSD fallback
  circuits/  circuit IR, CX scheduling, noise plugin, TPU Pauli-frame detector
             sampler, detector-error-model extraction
  sim/       Monte-Carlo engines (data / phenom / phenom-ST / circuit / circuit-ST)
  parallel/  device-mesh sharding of the shot/grid axes
  sweep/     code-family orchestration, threshold & distance fits
  rare/      rare-event estimation: importance-sampled (tilted / stratified)
             WER for deep sub-threshold cells, weighted fused sweeps
  serve/     decode-as-a-service: persistent AOT sessions, continuous
             batching, asyncio front-end
  compat/    drop-in shims for the reference module/API names
"""

__version__ = "0.1.0"

import os as _os
import sys as _sys


def _enable_compilation_cache():
    """Turn on JAX's persistent compilation cache process-wide.

    Every sweep/parity/notebook subprocess otherwise pays a fresh 20-45s XLA
    compile per (code-shape, pipeline) pair; with the cache, only the first
    process ever does.  Opt out with QLDPC_TPU_NO_COMPILE_CACHE=1; relocate
    with QLDPC_TPU_COMPILE_CACHE=<dir>.
    """
    if _os.environ.get("QLDPC_TPU_NO_COMPILE_CACHE", "").lower() in ("1", "true", "yes"):
        return
    cache_dir = _os.environ.get(
        "QLDPC_TPU_COMPILE_CACHE",
        _os.path.expanduser("~/.cache/qldpc_tpu/jax"),
    )
    try:
        _os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return
    # env vars so merely importing this package does not import jax; they are
    # the documented equivalents of the jax.config names
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    # traceback frames embedded in MLIR locations depend on process history
    # (what was traced earlier); they leak into Mosaic kernel payloads and
    # change the cache key of otherwise-identical programs — strip them
    _os.environ.setdefault("JAX_TRACEBACK_IN_LOCATIONS_LIMIT", "0")
    if "jax" in _sys.modules:  # jax imported first: env defaults already read
        import jax

        # never override a cache the user already configured (env var read
        # at jax import, or an explicit jax.config.update)
        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_traceback_in_locations_limit", 0)


_enable_compilation_cache()


def reset_device_state():
    """Recover from a TPU-worker crash/restart without restarting Python.

    The tunneled worker occasionally dies mid-run (kernel fault /
    infrastructure flake); after its automatic restart, every cached
    device buffer is dead.  This drops all device-resident memos (Tanner
    graphs, Pallas incidence stacks, OSD packings, compiled samplers) and
    jax's jit caches, so the next dispatch rebuilds/re-uploads — with the
    persistent compilation cache absorbing the recompiles.  Long sweeps
    wrap per-cell work in try/except JaxRuntimeError -> reset -> retry
    (see scripts/parity.py)."""
    import jax

    from .ops import bp as _bp

    _bp._graph_host_cache.clear()
    _bp._graph_dev_cache.clear()
    try:
        from .ops import bp_pallas as _bpp

        _bpp._head_cache.clear()
    except Exception:
        pass
    try:
        from .ops import osd_device as _osd

        _osd._pack_cache.clear()
    except Exception:
        pass
    try:
        from .circuits.sampler import FrameSampler

        FrameSampler._CACHE.clear()
    except Exception:
        pass
    try:
        # in-process AOT programs may hold dead device handles; the DISK
        # artifacts stay valid — the next request re-loads, not recompiles
        from .utils import progcache as _progcache

        _progcache.clear_memory()
    except Exception:
        pass
    jax.clear_caches()
    # bump the device-reset epoch LAST: the serve-side self-healing probe
    # (serve/ops.py) watches it, and healing against half-cleared caches
    # would re-memoize dead buffers
    from .utils import resilience as _resilience

    _resilience.note_device_reset()


from . import codes

__all__ = ["codes", "__version__"]


def __getattr__(name):
    # heavier subpackages (jit compilation, scipy) load lazily
    if name in ("ops", "noise", "decoders", "circuits", "sim", "parallel",
                "serve", "sweep", "compat", "utils"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
