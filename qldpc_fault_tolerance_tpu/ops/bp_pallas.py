"""Pallas TPU kernel for the BP head phase: VMEM-resident min-sum.

Motivation (measured on v5e): the XLA BP iteration is HBM-bound — every
iteration streams the (m, rw, B) message arrays through HBM, and the padded
adjacency gathers scale superlinearly with graph size.  This kernel keeps the
messages in VMEM for the whole iteration loop and replaces both gathers with
one-hot matmuls on the MXU, so per-iteration HBM traffic is zero.

Formulation (gather-free, slot-major):
  * Edges are grouped by check-side slot: slot s holds edge (check i, s-th
    neighbor).  All state is a stack of (m, B_tile) planes — rw_pad planes of
    v2c messages — so every array is a cleanly tiled 2D (sublane x lane)
    block and the per-check reduction is a static loop over <=rw_pad planes.
  * The only irregular data movement in BP — moving values between the
    check-edge grouping and the variable grouping — becomes matmuls with the
    per-slot one-hot incidence matrix S_s (m, n), S_s[i, v] = 1 iff
    chk_nbr[i, s] == v (zero row for padding):
       totals  = llr0 + sum_s S_s^T @ c2v_s          (scatter-accumulate)
       t_e_s   = S_s @ totals                         (broadcast/gather)
       v2c_s   = t_e_s - c2v_s                        (self-exclusion)
    One-hot matmuls are exact gathers; the scatter-sum accumulates in f32 on
    the MXU.
  * Convergence is checked every iteration (hard-decision parity per check,
    from the same t_e_s planes) and outputs freeze per shot at first
    convergence — the same ldpc return-on-convergence semantics as
    ops/bp.bp_decode.

Messages are bf16 (HBM->VMEM footprint and MXU rate); the posterior totals
accumulate in f32 and hard decisions are taken on the f32 totals.  Decodes
are deterministic but may differ from the f32 XLA path in rare near-tie
shots; converged shots always satisfy their syndrome exactly (the parity
check is exact).  Use ``bp_decode`` for bit-exact f32 reference behavior.

The kernel is used as the head phase of two-phase decoding
(``decoders.BPDecoder``): stragglers are re-decoded by the exact XLA tail.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams
from .bp import TannerGraph, BPResult

__all__ = ["PallasHeadGraph", "build_pallas_head", "bp_head_pallas"]

_BIG = 1e30  # python float: jnp.float32 here would be captured as a traced
             # constant inside the pallas kernel (disallowed)

# VMEM budget for the resident one-hot incidence stack; above this the
# caller should fall back to the XLA path
_SCAT_VMEM_LIMIT = 8 * 1024 * 1024


class PallasHeadGraph(NamedTuple):
    """Precompiled per-H data for the head kernel.

    All static dims derive from array shapes so the tuple stays a plain
    pytree of arrays (jit-traceable argument).
    """

    scat: jnp.ndarray      # (rw, m, n) bf16 one-hot incidence per slot
    mask: jnp.ndarray      # (rw, m) f32 1.0 for real edges, 0.0 for padding

    @property
    def rw(self) -> int:
        return self.scat.shape[0]

    @property
    def m(self) -> int:
        return self.scat.shape[1]

    @property
    def n(self) -> int:
        return self.scat.shape[2]

    @property
    def scat_bytes(self) -> int:
        return int(np.prod(self.scat.shape)) * 2

    def fits_vmem(self) -> bool:
        """Incidence-stack residency gate.  The conservative 8MB default
        stands until a TPU-probed calibration table raises it (a
        ``gates.bp_head_scat_limit_bytes`` entry — the n1225/n1600 unlock
        path, which needs try-compile evidence, not a bigger constant)."""
        from ..utils import profiling

        limit = profiling.vmem_table().get("gates", {}).get(
            "bp_head_scat_limit_bytes")
        if not isinstance(limit, (int, float)) or limit <= 0:
            limit = _SCAT_VMEM_LIMIT
        return self.scat_bytes <= limit

    @property
    def analytic_per_shot_bytes(self) -> int:
        """Naive-plane-sum per-shot VMEM estimate with the 1.7x-mosaic +
        2x-slack fudge — the UNcalibrated prior (see ``per_shot_bytes``)."""
        return 2 * (4 * self.rw * self.m + 20 * self.n + 16 * self.m)

    def per_shot_bytes(self) -> float:
        """Per-shot VMEM bytes the tile sizing uses: the calibration
        table's measured value for this (rw, m, n) when one exists
        (calibration/vmem_table.json via utils.profiling — the try-compile
        probes of scripts/vmem_calibrate.py turn the known ~1.8x mosaic
        temporary undercount into per-shape data), else the analytic
        prior."""
        from ..utils import profiling

        return profiling.calibrated_per_shot_bytes(
            "bp_head", {"rw": self.rw, "m": self.m, "n": self.n},
            self.analytic_per_shot_bytes)

    def max_block_b(self, b: int, want: int = 512) -> int:
        """Largest batch tile <= ``want`` that divides ``b`` and keeps the
        kernel's scoped-VMEM stack under the 32MB compiler limit; 0 when no
        feasible tile exists (callers fall back to the XLA path).

        Per-shot bytes come from the VMEM calibration table when this
        shape has a probed entry (``per_shot_bytes``); the fallback is the
        empirical fit (~1.7x the naive array-plane sum — mosaic stacks
        temporaries) with 2x slack.  Too-small estimates fail at COMPILE
        time with a scoped-vmem OOM, so err conservative."""
        per_shot = self.per_shot_bytes()
        budget = 30 * 1024 * 1024 - self.scat_bytes
        top = min(want, b)
        for bt in [top] + [1 << k for k in range(9, 2, -1)]:
            if bt <= top and b % bt == 0 and bt * per_shot <= budget:
                return bt
        return 0


from .bp import _LruCache  # noqa: E402  (shared bounded memo)

_head_cache = _LruCache()


def build_pallas_head(graph: TannerGraph) -> PallasHeadGraph:
    """Build the slot-major one-hot incidence stack from a TannerGraph.

    Pass a numpy-leaved graph (``build_tanner_graph_host``) to avoid
    device->host round-trips.  Memoized on the adjacency contents."""
    chk_nbr = np.asarray(graph.chk_nbr)
    chk_mask = np.asarray(graph.chk_mask)
    n = graph.var_nbr.shape[0]
    key = (chk_nbr.shape, n, chk_nbr.tobytes(), chk_mask.tobytes())
    return _head_cache.get(key, lambda: _build_pallas_head(chk_nbr, chk_mask, n))


def _build_pallas_head(chk_nbr, chk_mask, n: int) -> PallasHeadGraph:
    m, rw = chk_nbr.shape
    scat = np.zeros((rw, m, n), dtype=np.float32)
    for s in range(rw):
        rows = np.nonzero(chk_mask[:, s])[0]
        scat[s, rows, chk_nbr[rows, s]] = 1.0
    import ml_dtypes

    return PallasHeadGraph(
        scat=jax.device_put(scat.astype(ml_dtypes.bfloat16)),
        mask=jax.device_put(chk_mask.T.astype(np.float32)),
    )


def _head_kernel(synd_ref, scat_ref, mask_ref, llr0_ref,
                 err_ref, conv_ref, llr_ref, iters_ref,
                 *, rw: int, head_iters: int, scale: float,
                 early_stop: bool = False):
    """One batch tile: full iteration loop in VMEM.

    With ``early_stop`` the loop is a while that exits when every shot in
    the tile has converged — used for the straggler tail, where typical
    convergence is far below max_iter.
    """
    f32 = jnp.float32
    synd_sign = 1.0 - 2.0 * synd_ref[:]                        # (m, Bt) f32 in
    llr0 = llr0_ref[:].astype(f32)                              # (n, 1)
    bt = synd_sign.shape[1]
    n = llr0.shape[0]

    mask = [mask_ref[s][:, None] for s in range(rw)]            # (m, 1) each
    scale_f = f32(scale)

    def slot_mat(s):
        return scat_ref[s]                                      # (m, n) bf16

    # v2c init: channel LLRs broadcast onto edges; messages are carried in
    # bf16 (halves the VMEM working set — the limiter on tile width)
    llr0_b = llr0.astype(jnp.bfloat16)
    v2c0 = [
        (
            jnp.dot(slot_mat(s), llr0_b, preferred_element_type=f32)
            * jnp.ones((1, bt), f32)
        ).astype(jnp.bfloat16)
        for s in range(rw)
    ]

    def body(it, carry):
        v2c, err, llr, done, iters = carry

        # --- check update (scaled min-sum, streaming top-2 over slots) ---
        min1 = jnp.full((v2c[0].shape[0], bt), _BIG, f32)
        min2 = min1
        amin = jnp.zeros(min1.shape, jnp.int32)
        sgn_tot = synd_sign
        sgn = []
        for s in range(rw):
            v = v2c[s].astype(f32)
            mag = jnp.where(mask[s] > 0, jnp.abs(v), _BIG)
            sg = jnp.where((mask[s] > 0) & (v < 0), -1.0, 1.0)
            sgn.append(sg)
            sgn_tot = sgn_tot * sg
            is_new = mag < min1
            min2 = jnp.where(is_new, min1, jnp.minimum(min2, mag))
            amin = jnp.where(is_new, s, amin)
            min1 = jnp.minimum(min1, mag)

        # --- var update via one-hot matmuls ---
        totals = llr0 * jnp.ones((1, bt), f32)
        c2v = []
        for s in range(rw):
            excl_min = jnp.where(amin == s, min2, min1)
            c = mask[s] * (scale_f * sgn_tot * sgn[s] * jnp.minimum(excl_min, _BIG))
            c2v.append(c)
            totals = totals + jnp.dot(
                slot_mat(s).T, c.astype(jnp.bfloat16),
                preferred_element_type=f32,
            )

        err_new = jnp.where(totals < 0.0, 1.0, 0.0)             # (n, Bt)
        tot_b = totals.astype(jnp.bfloat16)
        parity = jnp.zeros((v2c[0].shape[0], bt), f32)
        v2c_new = []
        for s in range(rw):
            t_e = jnp.dot(slot_mat(s), tot_b, preferred_element_type=f32)
            v2c_new.append((t_e - c2v[s]).astype(jnp.bfloat16))
            parity = parity + jnp.where((t_e < 0.0) & (mask[s] > 0), 1.0, 0.0)

        # hard-decision parity mod 2 must equal the syndrome at every check
        par_mod2 = parity - 2.0 * jnp.floor(parity * 0.5)       # {0., 1.}
        ok = jnp.where((1.0 - 2.0 * par_mod2) == synd_sign, 1.0, 0.0)
        match = jnp.min(ok, axis=0, keepdims=True)              # (1, Bt) {0,1}

        newly = match * (1.0 - done)
        err = done * err + (1.0 - done) * err_new
        llr = done * llr + (1.0 - done) * totals
        iters = jnp.where(newly > 0, it + 1, iters)
        done = jnp.maximum(done, match)
        return (v2c_new, err, llr, done, iters)

    init = (
        v2c0,
        jnp.zeros((n, bt), f32),
        llr0 * jnp.ones((1, bt), f32),
        jnp.zeros((1, bt), f32),
        jnp.full((1, bt), head_iters, jnp.int32),
    )
    if early_stop:
        def w_cond(c):
            it, carry = c
            done = carry[3]
            return (it < head_iters) & (jnp.min(done) < 0.5)

        def w_body(c):
            it, carry = c
            return (it + 1, body(it, carry))

        _, (v2c, err, llr, done, iters) = jax.lax.while_loop(
            w_cond, w_body, (jnp.int32(0), init)
        )
    else:
        v2c, err, llr, done, iters = jax.lax.fori_loop(
            0, head_iters, body, init
        )
    # mosaic supports f32->i32 but not f32->u8; callers narrow outside
    err_ref[:] = err.astype(jnp.int32)
    conv_ref[:] = done.astype(jnp.int32)
    llr_ref[:] = llr
    iters_ref[:] = iters


@functools.partial(
    jax.jit,
    static_argnames=(
        "head_iters", "ms_scaling_factor", "block_b", "interpret",
        "early_stop",
    ),
)
def bp_head_pallas(
    pgraph: PallasHeadGraph,
    syndromes,
    channel_llr,
    *,
    head_iters: int,
    ms_scaling_factor: float = 0.625,
    block_b: int = 256,
    interpret: bool = False,
    early_stop: bool = False,
) -> BPResult:
    """Decode a (B, m) syndrome batch in VMEM; B must divide by block_b.

    Returns a BPResult (batch-major) with the same field contract as
    ``bp.bp_decode`` run for ``head_iters`` iterations (``early_stop`` makes
    it the full early-exit decode — the straggler-tail configuration).
    """
    syndromes = jnp.asarray(syndromes)
    b, m = syndromes.shape
    assert m == pgraph.m and b % block_b == 0, (b, m, pgraph.m, block_b)
    n = pgraph.n
    llr0 = jnp.asarray(channel_llr, jnp.float32).reshape(n, 1)

    kernel = functools.partial(
        _head_kernel,
        rw=pgraph.rw,
        head_iters=head_iters,
        scale=float(ms_scaling_factor),
        early_stop=early_stop,
    )
    grid = (b // block_b,)
    # a unique deterministic kernel name per instantiation: mosaic's
    # name-uniquing of same-named kernels is process-history-dependent,
    # which perturbs the serialized payload and breaks the persistent
    # compilation cache's key stability
    kname = (f"bp_head_{m}x{n}r{pgraph.rw}_i{head_iters}_b{b}x{block_b}"
             f"{'_es' if early_stop else ''}")
    err, conv, llr, iters = pl.pallas_call(
        kernel,
        name=kname,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, block_b), lambda t: (0, t)),       # syndromes.T
            pl.BlockSpec((pgraph.rw, m, n), lambda t: (0, 0, 0)),
            pl.BlockSpec((pgraph.rw, m), lambda t: (0, 0)),
            pl.BlockSpec((n, 1), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, b), jnp.int32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
            jax.ShapeDtypeStruct((n, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
        ],
        compiler_params=CompilerParams(
            # the default 16MB scoped-vmem cap is conservative; v5e has
            # 128MiB of physical VMEM and the kernel's working set (incidence
            # stack + message planes) is what makes it fast
            vmem_limit_bytes=32 * 1024 * 1024,
        ),
        interpret=interpret,
    )(syndromes.T.astype(jnp.float32), pgraph.scat, pgraph.mask, llr0)

    return BPResult(
        error=err.T.astype(jnp.uint8),
        converged=conv[0].astype(jnp.bool_),
        posterior_llr=llr.T,
        iterations=iters[0],
    )
