"""Pallas TPU kernel for the BP head phase: VMEM-resident min-sum.

Motivation (measured on v5e): the XLA BP iteration is HBM-bound — every
iteration streams the (m, rw, B) message arrays through HBM, and the padded
adjacency gathers scale superlinearly with graph size.  This kernel keeps the
messages in VMEM for the whole iteration loop and replaces both gathers with
one-hot matmuls on the MXU, so per-iteration HBM traffic is zero.

Formulation (gather-free, slot-major):
  * Edges are grouped by check-side slot: slot s holds edge (check i, s-th
    neighbor).  All state is a stack of (m, B_tile) planes — rw_pad planes of
    v2c messages — so every array is a cleanly tiled 2D (sublane x lane)
    block and the per-check reduction is a static loop over <=rw_pad planes.
  * The only irregular data movement in BP — moving values between the
    check-edge grouping and the variable grouping — becomes matmuls with the
    per-slot one-hot incidence matrix S_s (m, n), S_s[i, v] = 1 iff
    chk_nbr[i, s] == v (zero row for padding):
       totals  = llr0 + sum_s S_s^T @ c2v_s          (scatter-accumulate)
       t_e_s   = S_s @ totals                         (broadcast/gather)
       v2c_s   = t_e_s - c2v_s                        (self-exclusion)
    One-hot matmuls are exact gathers; the scatter-sum accumulates in f32 on
    the MXU.
  * Convergence is checked every iteration (hard-decision parity per check,
    from the same t_e_s planes) and outputs freeze per shot at first
    convergence — the same ldpc return-on-convergence semantics as
    ops/bp.bp_decode.

Messages are bf16 (HBM->VMEM footprint and MXU rate); the posterior totals
accumulate in f32 and hard decisions are taken on the f32 totals.  Decodes
are deterministic but may differ from the f32 XLA path in rare near-tie
shots; converged shots always satisfy their syndrome exactly (the parity
check is exact).  Use ``bp_decode`` for bit-exact f32 reference behavior.

The kernel is used as the head phase of two-phase decoding
(``decoders.BPDecoder``): stragglers are re-decoded by the exact XLA tail.

BP kernel v2 (sparse incidence)
-------------------------------
The v1 stack above keeps the whole (rw, m, n) bf16 one-hot incidence
RESIDENT in VMEM, which busts the 8 MB gate at N>=1225 and routes the
paper's large HGP codes off the fast path entirely.  ``SparseHeadGraph``
replaces it with the index-gather edge representation: slot-major
``(rw, m)`` int32 column indices plus a validity mask — a few KB instead of
MBs — and each slot's one-hot operand is SYNTHESIZED in-register from the
indices (``idx[s][:, None] == iota_n``) at the moment the MXU needs it, so
incidence data never occupies standing VMEM and never streams from HBM.
The synthesized operand carries the exact same 0.0/1.0 bf16 values the v1
stack loads, and the iteration loop is shared (``_minsum_plane_loop``), so
the v2 kernel is bit-exact with v1 and with its own XLA twin
(``bp_head_sparse(backend="xla")`` — the same body on plain jnp arrays).

``quantize="int8"`` switches the loop to int8 min-sum
(``_minsum_int8_loop``): messages are stored as int8 with one dynamic scale
per iteration per batch tile, the scatter-accumulate runs as an exact
int8xint8->int32 MXU product (order-independent — the XLA twin's
index-scatter produces identical integers), and the posterior accumulates
through bf16 totals.  The int8 path is NOT bit-exact with the f32/bf16
decoders — its contract is statistical WER parity within
``INT8_WER_RTOL`` (see README "BP kernel v2"); kernel vs twin stays
bit-exact by integer exactness.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams
from .bp import TannerGraph, BPResult

__all__ = [
    "PallasHeadGraph", "build_pallas_head", "bp_head_pallas",
    "SparseHeadGraph", "build_sparse_head", "bp_head_sparse",
    "KERNEL_VARIANTS", "INT8_WER_RTOL", "int8_parity_tolerance",
]

# the kernel-variant vocabulary the telemetry layer reports
# (bp.kernel_variant gauge + wer_run event field): which BP program
# actually serves a decode —
#   dense_onehot  — v1 Pallas kernel (resident one-hot stack)
#   sparse_gather — v2 Pallas kernel (index-synthesized incidence, bf16)
#   sparse_int8   — v2 Pallas kernel, int8 min-sum messages
#   xla_twin      — any XLA-served decode (plain f32 bp_decode or the v2
#                   twin on non-TPU backends / VMEM-gated shapes)
KERNEL_VARIANTS = ("dense_onehot", "sparse_gather", "sparse_int8",
                   "xla_twin")

# The int8 quantization contract (README "BP kernel v2", BASELINE.md): an
# int8 decode's WER must match the unquantized decoder's within
# INT8_WER_RTOL relative, with a floor of INT8_WER_NSIGMA combined
# binomial standard errors (so near-zero-failure cells don't fail on
# counting noise).  bench.py's BENCH_QUANT arm and the tier-1 parity test
# both consume int8_parity_tolerance so the gate can never drift from the
# documented contract.
INT8_WER_RTOL = 0.1
INT8_WER_NSIGMA = 4.0


def int8_parity_tolerance(wer_ref: float, shots: int) -> float:
    """Allowed |wer_int8 - wer_ref| per the quantization contract."""
    import math

    sigma = math.sqrt(max(wer_ref * (1.0 - wer_ref), 1e-12) / max(shots, 1))
    return max(INT8_WER_RTOL * wer_ref, INT8_WER_NSIGMA * sigma)

_BIG = 1e30  # python float: jnp.float32 here would be captured as a traced
             # constant inside the pallas kernel (disallowed)

# VMEM budget for the resident one-hot incidence stack; above this the
# caller should fall back to the XLA path
_SCAT_VMEM_LIMIT = 8 * 1024 * 1024


class PallasHeadGraph(NamedTuple):
    """Precompiled per-H data for the head kernel.

    All static dims derive from array shapes so the tuple stays a plain
    pytree of arrays (jit-traceable argument).
    """

    scat: jnp.ndarray      # (rw, m, n) bf16 one-hot incidence per slot
    mask: jnp.ndarray      # (rw, m) f32 1.0 for real edges, 0.0 for padding

    @property
    def rw(self) -> int:
        return self.scat.shape[0]

    @property
    def m(self) -> int:
        return self.scat.shape[1]

    @property
    def n(self) -> int:
        return self.scat.shape[2]

    @property
    def scat_bytes(self) -> int:
        return int(np.prod(self.scat.shape)) * 2

    def fits_vmem(self) -> bool:
        """Incidence-stack residency gate.  The conservative 8MB default
        stands until a TPU-probed calibration table raises it (a
        ``gates.bp_head_scat_limit_bytes`` entry — the n1225/n1600 unlock
        path, which needs try-compile evidence, not a bigger constant)."""
        from ..utils import profiling

        limit = profiling.vmem_table().get("gates", {}).get(
            "bp_head_scat_limit_bytes")
        if not isinstance(limit, (int, float)) or limit <= 0:
            limit = _SCAT_VMEM_LIMIT
        return self.scat_bytes <= limit

    @property
    def analytic_per_shot_bytes(self) -> int:
        """Naive-plane-sum per-shot VMEM estimate with the 1.7x-mosaic +
        2x-slack fudge — the UNcalibrated prior (see ``per_shot_bytes``)."""
        return 2 * (4 * self.rw * self.m + 20 * self.n + 16 * self.m)

    def per_shot_bytes(self) -> float:
        """Per-shot VMEM bytes the tile sizing uses: the calibration
        table's measured value for this (rw, m, n) when one exists
        (calibration/vmem_table.json via utils.profiling — the try-compile
        probes of scripts/vmem_calibrate.py turn the known ~1.8x mosaic
        temporary undercount into per-shape data), else the analytic
        prior."""
        from ..utils import profiling

        return profiling.calibrated_per_shot_bytes(
            "bp_head", {"rw": self.rw, "m": self.m, "n": self.n},
            self.analytic_per_shot_bytes)

    def max_block_b(self, b: int, want: int = 512) -> int:
        """Largest batch tile <= ``want`` that divides ``b`` and keeps the
        kernel's scoped-VMEM stack under the 32MB compiler limit; 0 when no
        feasible tile exists (callers fall back to the XLA path).

        Per-shot bytes come from the VMEM calibration table when this
        shape has a probed entry (``per_shot_bytes``); the fallback is the
        empirical fit (~1.7x the naive array-plane sum — mosaic stacks
        temporaries) with 2x slack.  Too-small estimates fail at COMPILE
        time with a scoped-vmem OOM, so err conservative."""
        per_shot = self.per_shot_bytes()
        budget = 30 * 1024 * 1024 - self.scat_bytes
        top = min(want, b)
        for bt in [top] + [1 << k for k in range(9, 2, -1)]:
            if bt <= top and b % bt == 0 and bt * per_shot <= budget:
                return bt
        return 0


from .bp import _LruCache  # noqa: E402  (shared bounded memo)

_head_cache = _LruCache()


def build_pallas_head(graph: TannerGraph) -> PallasHeadGraph:
    """Build the slot-major one-hot incidence stack from a TannerGraph.

    Pass a numpy-leaved graph (``build_tanner_graph_host``) to avoid
    device->host round-trips.  Memoized on the adjacency contents."""
    chk_nbr = np.asarray(graph.chk_nbr)
    chk_mask = np.asarray(graph.chk_mask)
    n = graph.var_nbr.shape[0]
    key = (chk_nbr.shape, n, chk_nbr.tobytes(), chk_mask.tobytes())
    return _head_cache.get(key, lambda: _build_pallas_head(chk_nbr, chk_mask, n))


def _build_pallas_head(chk_nbr, chk_mask, n: int) -> PallasHeadGraph:
    m, rw = chk_nbr.shape
    scat = np.zeros((rw, m, n), dtype=np.float32)
    for s in range(rw):
        rows = np.nonzero(chk_mask[:, s])[0]
        scat[s, rows, chk_nbr[rows, s]] = 1.0
    import ml_dtypes

    return PallasHeadGraph(
        scat=jax.device_put(scat.astype(ml_dtypes.bfloat16)),
        mask=jax.device_put(chk_mask.T.astype(np.float32)),
    )


def _minsum_plane_loop(synd_sign, slot_mat, mask, llr0, *, rw: int,
                       head_iters: int, scale: float, early_stop: bool):
    """Slot-major scaled-min-sum iteration loop over VMEM planes — the ONE
    body shared by the v1 dense-one-hot kernel, the v2 sparse-incidence
    kernel and the v2 XLA twin, so the three can never drift numerically.

    ``slot_mat(s)`` supplies slot s's (m, n) bf16 one-hot operand (loaded
    in v1, synthesized from int32 indices in v2 — same 0.0/1.0 values);
    ``mask`` is the per-slot (m, 1) f32 validity column list.  Returns
    ``(err, done, llr, iters)`` batch-last planes with the same freeze-at-
    convergence semantics as ``bp.bp_decode``.
    """
    f32 = jnp.float32
    bt = synd_sign.shape[1]
    n = llr0.shape[0]
    scale_f = f32(scale)

    # v2c init: channel LLRs broadcast onto edges; messages are carried in
    # bf16 (halves the VMEM working set — the limiter on tile width)
    llr0_b = llr0.astype(jnp.bfloat16)
    v2c0 = [
        (
            jnp.dot(slot_mat(s), llr0_b, preferred_element_type=f32)
            * jnp.ones((1, bt), f32)
        ).astype(jnp.bfloat16)
        for s in range(rw)
    ]

    def body(it, carry):
        v2c, err, llr, done, iters = carry

        # --- check update (scaled min-sum, streaming top-2 over slots) ---
        min1 = jnp.full((v2c[0].shape[0], bt), _BIG, f32)
        min2 = min1
        amin = jnp.zeros(min1.shape, jnp.int32)
        sgn_tot = synd_sign
        sgn = []
        for s in range(rw):
            v = v2c[s].astype(f32)
            mag = jnp.where(mask[s] > 0, jnp.abs(v), _BIG)
            sg = jnp.where((mask[s] > 0) & (v < 0), -1.0, 1.0)
            sgn.append(sg)
            sgn_tot = sgn_tot * sg
            is_new = mag < min1
            min2 = jnp.where(is_new, min1, jnp.minimum(min2, mag))
            amin = jnp.where(is_new, s, amin)
            min1 = jnp.minimum(min1, mag)

        # --- var update via one-hot matmuls ---
        totals = llr0 * jnp.ones((1, bt), f32)
        c2v = []
        for s in range(rw):
            excl_min = jnp.where(amin == s, min2, min1)
            c = mask[s] * (scale_f * sgn_tot * sgn[s] * jnp.minimum(excl_min, _BIG))
            c2v.append(c)
            totals = totals + jnp.dot(
                slot_mat(s).T, c.astype(jnp.bfloat16),
                preferred_element_type=f32,
            )

        err_new = jnp.where(totals < 0.0, 1.0, 0.0)             # (n, Bt)
        tot_b = totals.astype(jnp.bfloat16)
        parity = jnp.zeros((v2c[0].shape[0], bt), f32)
        v2c_new = []
        for s in range(rw):
            t_e = jnp.dot(slot_mat(s), tot_b, preferred_element_type=f32)
            v2c_new.append((t_e - c2v[s]).astype(jnp.bfloat16))
            parity = parity + jnp.where((t_e < 0.0) & (mask[s] > 0), 1.0, 0.0)

        # hard-decision parity mod 2 must equal the syndrome at every check
        par_mod2 = parity - 2.0 * jnp.floor(parity * 0.5)       # {0., 1.}
        ok = jnp.where((1.0 - 2.0 * par_mod2) == synd_sign, 1.0, 0.0)
        match = jnp.min(ok, axis=0, keepdims=True)              # (1, Bt) {0,1}

        newly = match * (1.0 - done)
        err = done * err + (1.0 - done) * err_new
        llr = done * llr + (1.0 - done) * totals
        iters = jnp.where(newly > 0, it + 1, iters)
        done = jnp.maximum(done, match)
        return (v2c_new, err, llr, done, iters)

    init = (
        v2c0,
        jnp.zeros((n, bt), f32),
        llr0 * jnp.ones((1, bt), f32),
        jnp.zeros((1, bt), f32),
        jnp.full((1, bt), head_iters, jnp.int32),
    )
    if early_stop:
        def w_cond(c):
            it, carry = c
            done = carry[3]
            return (it < head_iters) & (jnp.min(done) < 0.5)

        def w_body(c):
            it, carry = c
            return (it + 1, body(it, carry))

        _, (v2c, err, llr, done, iters) = jax.lax.while_loop(
            w_cond, w_body, (jnp.int32(0), init)
        )
    else:
        v2c, err, llr, done, iters = jax.lax.fori_loop(
            0, head_iters, body, init
        )
    return err, done, llr, iters


def _head_kernel(synd_ref, scat_ref, mask_ref, llr0_ref,
                 err_ref, conv_ref, llr_ref, iters_ref,
                 *, rw: int, head_iters: int, scale: float,
                 early_stop: bool = False):
    """One batch tile: full iteration loop in VMEM (v1, loaded one-hots).

    With ``early_stop`` the loop is a while that exits when every shot in
    the tile has converged — used for the straggler tail, where typical
    convergence is far below max_iter.
    """
    synd_sign = 1.0 - 2.0 * synd_ref[:]                        # (m, Bt) f32 in
    llr0 = llr0_ref[:].astype(jnp.float32)                      # (n, 1)
    mask = [mask_ref[s][:, None] for s in range(rw)]            # (m, 1) each

    err, done, llr, iters = _minsum_plane_loop(
        synd_sign, lambda s: scat_ref[s], mask, llr0,
        rw=rw, head_iters=head_iters, scale=scale, early_stop=early_stop)
    # mosaic supports f32->i32 but not f32->u8; callers narrow outside
    err_ref[:] = err.astype(jnp.int32)
    conv_ref[:] = done.astype(jnp.int32)
    llr_ref[:] = llr
    iters_ref[:] = iters


@functools.partial(
    jax.jit,
    static_argnames=(
        "head_iters", "ms_scaling_factor", "block_b", "interpret",
        "early_stop",
    ),
)
def bp_head_pallas(
    pgraph: PallasHeadGraph,
    syndromes,
    channel_llr,
    *,
    head_iters: int,
    ms_scaling_factor: float = 0.625,
    block_b: int = 256,
    interpret: bool = False,
    early_stop: bool = False,
) -> BPResult:
    """Decode a (B, m) syndrome batch in VMEM; B must divide by block_b.

    Returns a BPResult (batch-major) with the same field contract as
    ``bp.bp_decode`` run for ``head_iters`` iterations (``early_stop`` makes
    it the full early-exit decode — the straggler-tail configuration).
    """
    syndromes = jnp.asarray(syndromes)
    b, m = syndromes.shape
    assert m == pgraph.m and b % block_b == 0, (b, m, pgraph.m, block_b)
    n = pgraph.n
    llr0 = jnp.asarray(channel_llr, jnp.float32).reshape(n, 1)

    kernel = functools.partial(
        _head_kernel,
        rw=pgraph.rw,
        head_iters=head_iters,
        scale=float(ms_scaling_factor),
        early_stop=early_stop,
    )
    grid = (b // block_b,)
    # a unique deterministic kernel name per instantiation: mosaic's
    # name-uniquing of same-named kernels is process-history-dependent,
    # which perturbs the serialized payload and breaks the persistent
    # compilation cache's key stability
    kname = (f"bp_head_{m}x{n}r{pgraph.rw}_i{head_iters}_b{b}x{block_b}"
             f"{'_es' if early_stop else ''}")
    err, conv, llr, iters = pl.pallas_call(
        kernel,
        name=kname,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, block_b), lambda t: (0, t)),       # syndromes.T
            pl.BlockSpec((pgraph.rw, m, n), lambda t: (0, 0, 0)),
            pl.BlockSpec((pgraph.rw, m), lambda t: (0, 0)),
            pl.BlockSpec((n, 1), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, b), jnp.int32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
            jax.ShapeDtypeStruct((n, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
        ],
        compiler_params=CompilerParams(
            # the default 16MB scoped-vmem cap is conservative; v5e has
            # 128MiB of physical VMEM and the kernel's working set (incidence
            # stack + message planes) is what makes it fast
            vmem_limit_bytes=32 * 1024 * 1024,
        ),
        interpret=interpret,
    )(syndromes.T.astype(jnp.float32), pgraph.scat, pgraph.mask, llr0)

    return BPResult(
        error=err.T.astype(jnp.uint8),
        converged=conv[0].astype(jnp.bool_),
        posterior_llr=llr.T,
        iterations=iters[0],
    )


# ===========================================================================
# BP kernel v2: sparse (index-gather) incidence + optional int8 min-sum
# ===========================================================================

# conservative count of synthesized (m, n) bf16 one-hot operands the mosaic
# scheduler may keep live simultaneously (current slot + transpose copy +
# pipelining) — the transient that replaces the v1 RESIDENT (rw, m, n) stack
_V2_ONEHOT_LIVE = 3

# default cap on the v2 kernel's fixed (batch-independent) VMEM overhead:
# index planes + live synthesized one-hots.  Overridden by a TPU-probed
# ``gates.bp_head_v2_fixed_limit_bytes`` (scripts/vmem_calibrate.py).
_V2_FIXED_LIMIT = 16 * 1024 * 1024


class SparseHeadGraph(NamedTuple):
    """v2 per-H data: slot-major edge indices instead of a one-hot stack.

    ``chk_idx[s, i]`` is the variable index of check i's slot-s edge (0 for
    padding; ``mask`` kills padded slots).  ``nvar`` is a zero-byte (0, n)
    shape carrier so the tuple stays a plain array pytree while ``n`` rides
    statically.  Incidence bytes drop from rw*m*n*2 (v1, 17.2 MB at n1600)
    to rw*m*8 (21.5 KB) — the one-hot operand is synthesized in-register
    per slot, so large HGP codes stay on the VMEM path.
    """

    chk_idx: jnp.ndarray   # (rw, m) int32
    mask: jnp.ndarray      # (rw, m) f32 — 1.0 real edge, 0.0 padding
    nvar: jnp.ndarray      # (0, n) int8 — static shape carrier only

    @property
    def rw(self) -> int:
        return self.chk_idx.shape[0]

    @property
    def m(self) -> int:
        return self.chk_idx.shape[1]

    @property
    def n(self) -> int:
        return self.nvar.shape[1]

    @property
    def idx_bytes(self) -> int:
        return int(np.prod(self.chk_idx.shape)) * 8  # idx i32 + mask f32

    @property
    def fixed_overhead_bytes(self) -> int:
        """Batch-independent VMEM working set: the index/mask planes plus
        the transient synthesized one-hot operands."""
        return self.idx_bytes + _V2_ONEHOT_LIVE * self.m * self.n * 2

    def fits_vmem(self) -> bool:
        """v2 residency gate: the FIXED overhead must leave room for batch
        tiles.  Calibrated via ``gates.bp_head_v2_fixed_limit_bytes``; the
        conservative default admits n1225/n1600 (fixed ~4.4/7.4 MB), which
        the v1 scat gate rejects."""
        from ..utils import profiling

        limit = profiling.vmem_table().get("gates", {}).get(
            "bp_head_v2_fixed_limit_bytes")
        if not isinstance(limit, (int, float)) or limit <= 0:
            limit = _V2_FIXED_LIMIT
        return self.fixed_overhead_bytes <= limit

    @property
    def analytic_per_shot_bytes(self) -> int:
        """Same per-shot plane structure as v1 (bf16 message planes + f32
        totals/outputs) with the 1.7x-mosaic + 2x-slack fudge; the int8
        variant only shrinks it, so this is the conservative bound the
        tile sizing uses for both."""
        return 2 * (4 * self.rw * self.m + 20 * self.n + 16 * self.m)

    def per_shot_bytes(self) -> float:
        from ..utils import profiling

        return profiling.calibrated_per_shot_bytes(
            "bp_head_v2", {"rw": self.rw, "m": self.m, "n": self.n},
            self.analytic_per_shot_bytes)

    def max_block_b(self, b: int, want: int = 512) -> int:
        """Largest batch tile <= ``want`` that divides ``b`` and fits the
        scoped-VMEM budget after the fixed overhead; 0 = no feasible tile
        (callers fall back to the XLA path)."""
        per_shot = self.per_shot_bytes()
        budget = 30 * 1024 * 1024 - self.fixed_overhead_bytes
        top = min(want, b)
        for bt in [top] + [1 << k for k in range(9, 2, -1)]:
            if bt <= top and b % bt == 0 and bt * per_shot <= budget:
                return bt
        return 0


_sparse_cache = _LruCache()


def build_sparse_head(graph: TannerGraph) -> SparseHeadGraph:
    """Build the slot-major index planes from a TannerGraph (memoized on
    the adjacency contents, like ``build_pallas_head``)."""
    chk_nbr = np.asarray(graph.chk_nbr)
    chk_mask = np.asarray(graph.chk_mask)
    n = graph.var_nbr.shape[0]
    key = ("v2", chk_nbr.shape, n, chk_nbr.tobytes(), chk_mask.tobytes())

    def make():
        return SparseHeadGraph(
            chk_idx=jax.device_put(
                np.ascontiguousarray(chk_nbr.T.astype(np.int32))),
            mask=jax.device_put(
                np.ascontiguousarray(chk_mask.T.astype(np.float32))),
            nvar=jax.device_put(np.zeros((0, n), np.int8)),
        )

    return _sparse_cache.get(key, make)


def _synth_onehot(idx_col, mask_col, n: int, dtype):
    """Slot s's one-hot operand synthesized from its index column:
    ``(m, n)`` with exactly the 0/1 values the v1 stack stores (zero rows
    for padding).  ``idx_col``/``mask_col`` are (m, 1)."""
    m = idx_col.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (m, n), 1)
    cond = (idx_col == cols) & (mask_col > 0)
    return jnp.where(cond, 1.0, 0.0).astype(dtype)


_BIG_I32 = np.int32(2 ** 30)


def _minsum_int8_loop(synd_sign, gather_tot, scatter_i8, mask, llr0, *,
                      rw: int, head_iters: int, scale: float,
                      early_stop: bool):
    """int8 min-sum loop shared by the v2 kernel and its XLA twin.

    Messages are int8 with ONE dynamic scale per iteration per batch tile
    (``qv`` for stored v2c, ``qc`` for the scattered c2v), so check-node
    mins run on raw int magnitudes and the scatter-accumulate is exact
    int32 — order-independent, which is what makes the MXU int8 product
    (kernel) and the index scatter-add (twin) produce identical integers.
    Only the quantization rounding itself is lossy; its WER contract is
    ``int8_parity_tolerance``.

    ``gather_tot(s, tot_b)``: exact per-edge read of (n, Bt) bf16 totals
    -> (m, Bt) f32, zero at padded slots.  ``scatter_i8(c2v_i8_list)``:
    exact int32 scatter-add of the per-slot int8 messages -> (n, Bt).
    """
    f32 = jnp.float32
    bt = synd_sign.shape[1]
    n = llr0.shape[0]
    scale_f = f32(scale)
    eps = f32(1e-30)

    def tile_max(planes):
        acc = jnp.zeros((1, 1), f32)
        for p in planes:
            acc = jnp.maximum(acc, jnp.max(jnp.abs(p), axis=(0, 1),
                                           keepdims=True))
        return acc

    def quantize_planes(planes, q):
        return [jnp.round(jnp.clip(p / q, -127.0, 127.0)).astype(jnp.int8)
                for p in planes]

    # init: channel prior gathered onto edges, quantized at a shared scale
    llr0_tile = (llr0 * jnp.ones((1, bt), f32)).astype(jnp.bfloat16)
    t0 = [gather_tot(s, llr0_tile) for s in range(rw)]
    qv0 = jnp.maximum(tile_max(t0) / 127.0, eps)
    v2c0 = quantize_planes(t0, qv0)

    def body(it, carry):
        v2c, qv, err, llr, done, iters = carry

        # --- check update on raw int8 magnitudes (min order is scale-
        # invariant: all planes share qv) ---
        min1 = jnp.full((mask[0].shape[0], bt), _BIG_I32, jnp.int32)
        min2 = min1
        amin = jnp.zeros(min1.shape, jnp.int32)
        sgn_tot = synd_sign
        sgn = []
        for s in range(rw):
            v = v2c[s].astype(jnp.int32)
            mag = jnp.where(mask[s] > 0, jnp.abs(v), _BIG_I32)
            sg = jnp.where((mask[s] > 0) & (v < 0), -1.0, 1.0)
            sgn.append(sg)
            sgn_tot = sgn_tot * sg
            is_new = mag < min1
            min2 = jnp.where(is_new, min1, jnp.minimum(min2, mag))
            amin = jnp.where(is_new, s, amin)
            min1 = jnp.minimum(min1, mag)

        # --- c2v in f32 (dequantized), then requantized at a fresh scale
        # for the exact integer scatter ---
        c2v_f = []
        for s in range(rw):
            excl = jnp.minimum(jnp.where(amin == s, min2, min1), _BIG_I32)
            c2v_f.append(mask[s] * (scale_f * sgn_tot * sgn[s]
                                    * (excl.astype(f32) * qv[0, 0])))
        qc = jnp.maximum(tile_max(c2v_f) / 127.0, eps)
        c2v_i8 = quantize_planes(c2v_f, qc)

        tot_i = scatter_i8(c2v_i8)                              # (n, Bt) i32
        totals = llr0 * jnp.ones((1, bt), f32) \
            + qc[0, 0] * tot_i.astype(f32)

        err_new = jnp.where(totals < 0.0, 1.0, 0.0)
        tot_b = totals.astype(jnp.bfloat16)
        parity = jnp.zeros((mask[0].shape[0], bt), f32)
        v2c_new_f = []
        for s in range(rw):
            t_e = gather_tot(s, tot_b)
            # subtract exactly what was scattered (the QUANTIZED message)
            v2c_new_f.append(t_e - qc[0, 0] * c2v_i8[s].astype(f32))
            parity = parity + jnp.where((t_e < 0.0) & (mask[s] > 0),
                                        1.0, 0.0)

        par_mod2 = parity - 2.0 * jnp.floor(parity * 0.5)
        ok = jnp.where((1.0 - 2.0 * par_mod2) == synd_sign, 1.0, 0.0)
        match = jnp.min(ok, axis=0, keepdims=True)

        newly = match * (1.0 - done)
        err = done * err + (1.0 - done) * err_new
        llr = done * llr + (1.0 - done) * totals
        iters = jnp.where(newly > 0, it + 1, iters)
        done = jnp.maximum(done, match)
        qv_new = jnp.maximum(tile_max(v2c_new_f) / 127.0, eps)
        return (quantize_planes(v2c_new_f, qv_new), qv_new,
                err, llr, done, iters)

    init = (
        v2c0,
        qv0,
        jnp.zeros((n, bt), f32),
        llr0 * jnp.ones((1, bt), f32),
        jnp.zeros((1, bt), f32),
        jnp.full((1, bt), head_iters, jnp.int32),
    )
    if early_stop:
        def w_cond(c):
            it, carry = c
            return (it < head_iters) & (jnp.min(carry[4]) < 0.5)

        def w_body(c):
            it, carry = c
            return (it + 1, body(it, carry))

        _, out = jax.lax.while_loop(w_cond, w_body, (jnp.int32(0), init))
    else:
        out = jax.lax.fori_loop(0, head_iters, body, init)
    _, _, err, llr, done, iters = out
    return err, done, llr, iters


def _onehot_matmul_ops(onehot, rw: int):
    """The MXU gather/scatter pair over synthesized one-hot operands —
    ONE definition shared by the standalone v2 kernel and the fused-v2
    pipeline kernel (gf2_pallas), because kernel/twin bit-exactness rests
    on these bodies staying identical.  ``onehot(s, dtype)`` must return
    slot s's (m, n) one-hot (mask included)."""

    def gather_tot(s, tot_b):
        return jnp.dot(onehot(s, jnp.bfloat16), tot_b,
                       preferred_element_type=jnp.float32)

    def scatter_i8(c2v_i8):
        acc = None
        for s in range(rw):
            part = jax.lax.dot_general(
                onehot(s, jnp.int8), c2v_i8[s],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)            # (n, Bt)
            acc = part if acc is None else acc + part
        return acc

    return gather_tot, scatter_i8


def _run_minsum_tile(idx_planes, mask_planes, synd_sign, llr0, *, rw: int,
                     n: int, head_iters: int, scale: float,
                     early_stop: bool, quantize):
    """One v2 tile over index planes (shared by the standalone kernel and
    the fused-v2 pipeline kernel): synthesizes the one-hot operands and
    runs the bf16 or int8 loop.  ``idx_planes[s]`` is (m,)."""
    mask = [mask_planes[s][:, None] for s in range(rw)]

    def onehot(s, dtype):
        return _synth_onehot(idx_planes[s][:, None], mask[s], n, dtype)

    if quantize is None:
        return _minsum_plane_loop(
            synd_sign, lambda s: onehot(s, jnp.bfloat16), mask, llr0,
            rw=rw, head_iters=head_iters, scale=scale,
            early_stop=early_stop)
    gather_tot, scatter_i8 = _onehot_matmul_ops(onehot, rw)
    return _minsum_int8_loop(
        synd_sign, gather_tot, scatter_i8, mask, llr0,
        rw=rw, head_iters=head_iters, scale=scale, early_stop=early_stop)


def _sparse_head_kernel(synd_ref, idx_ref, mask_ref, llr0_ref,
                        err_ref, conv_ref, llr_ref, iters_ref,
                        *, rw: int, n: int, head_iters: int, scale: float,
                        early_stop: bool, quantize):
    """v2 batch tile: same loop as v1, one-hot operands synthesized from
    the resident (rw, m) int32 index planes at use time."""
    synd_sign = 1.0 - 2.0 * synd_ref[:]                        # (m, Bt)
    llr0 = llr0_ref[:].astype(jnp.float32)                      # (n, 1)

    err, done, llr, iters = _run_minsum_tile(
        [idx_ref[s] for s in range(rw)],
        [mask_ref[s] for s in range(rw)],
        synd_sign, llr0, rw=rw, n=n, head_iters=head_iters, scale=scale,
        early_stop=early_stop, quantize=quantize)
    err_ref[:] = err.astype(jnp.int32)
    conv_ref[:] = done.astype(jnp.int32)
    llr_ref[:] = llr
    iters_ref[:] = iters


def _sparse_twin_tile(chk_idx, mask_planes, synd_sign, llr0, *, rw: int,
                      n: int, head_iters: int, scale: float,
                      early_stop: bool, quantize):
    """One (m, Bt) tile of the XLA twin — the SAME loop bodies on plain
    jnp arrays.  The bf16 variant synthesizes the identical one-hot
    operands; the int8 variant uses true index gathers / integer
    scatter-adds, which match the kernel's int8 MXU products exactly
    (integer arithmetic is order-independent)."""
    if quantize is None:
        return _run_minsum_tile(
            [chk_idx[s] for s in range(rw)],
            [mask_planes[s] for s in range(rw)],
            synd_sign, llr0, rw=rw, n=n, head_iters=head_iters,
            scale=scale, early_stop=early_stop, quantize=None)

    mask = [mask_planes[s][:, None] for s in range(rw)]
    bt = synd_sign.shape[1]

    def gather_tot(s, tot_b):
        t = jnp.take(tot_b, chk_idx[s], axis=0)                # (m, Bt)
        return jnp.where(mask[s] > 0, t.astype(jnp.float32), 0.0)

    # padded slots scatter into a scratch row n, sliced off below
    flat_idx = jnp.concatenate([
        jnp.where(mask_planes[s] > 0, chk_idx[s], n) for s in range(rw)])

    def scatter_i8(c2v_i8):
        vals = jnp.concatenate([c.astype(jnp.int32) for c in c2v_i8],
                               axis=0)                          # (rw*m, Bt)
        out = jnp.zeros((n + 1, bt), jnp.int32).at[flat_idx].add(vals)
        return out[:n]

    return _minsum_int8_loop(
        synd_sign, gather_tot, scatter_i8, mask, llr0, rw=rw,
        head_iters=head_iters, scale=scale, early_stop=early_stop)


@functools.partial(
    jax.jit,
    static_argnames=("head_iters", "ms_scaling_factor", "block_b",
                     "early_stop", "quantize"),
)
def _bp_head_sparse_xla(sgraph: SparseHeadGraph, syndromes, channel_llr, *,
                        head_iters: int, ms_scaling_factor: float,
                        block_b: int, early_stop: bool, quantize):
    """XLA twin: the batch reshapes into the kernel's (B/block_b, block_b)
    tiles and the tile body vmaps over them, so the int8 per-tile scales —
    and therefore every output bit — match the Pallas kernel exactly."""
    syndromes = jnp.asarray(syndromes)
    b, m = syndromes.shape
    n = sgraph.n
    llr0 = jnp.asarray(channel_llr, jnp.float32).reshape(n, 1)
    synd_sign = 1.0 - 2.0 * syndromes.T.astype(jnp.float32)     # (m, B)
    tiles = b // block_b
    ss = synd_sign.reshape(m, tiles, block_b).swapaxes(0, 1)

    def tile(s_tile):
        return _sparse_twin_tile(
            sgraph.chk_idx, sgraph.mask, s_tile, llr0, rw=sgraph.rw, n=n,
            head_iters=head_iters, scale=float(ms_scaling_factor),
            early_stop=early_stop, quantize=quantize)

    err, done, llr, iters = jax.vmap(tile)(ss)

    def unfold(x):
        return x.swapaxes(0, 1).reshape(x.shape[1], b)

    return BPResult(
        error=unfold(err).T.astype(jnp.uint8),
        converged=unfold(done)[0] > 0.5,
        posterior_llr=unfold(llr).T,
        iterations=unfold(iters)[0],
    )


@functools.partial(
    jax.jit,
    static_argnames=("head_iters", "ms_scaling_factor", "block_b",
                     "interpret", "early_stop", "quantize"),
)
def _bp_head_sparse_pallas(sgraph: SparseHeadGraph, syndromes, channel_llr,
                           *, head_iters: int, ms_scaling_factor: float,
                           block_b: int, interpret: bool, early_stop: bool,
                           quantize):
    syndromes = jnp.asarray(syndromes)
    b, m = syndromes.shape
    assert m == sgraph.m and b % block_b == 0, (b, m, sgraph.m, block_b)
    n = sgraph.n
    llr0 = jnp.asarray(channel_llr, jnp.float32).reshape(n, 1)

    kernel = functools.partial(
        _sparse_head_kernel,
        rw=sgraph.rw, n=n,
        head_iters=head_iters,
        scale=float(ms_scaling_factor),
        early_stop=early_stop,
        quantize=quantize,
    )
    grid = (b // block_b,)
    kname = (f"bp_head_v2_{m}x{n}r{sgraph.rw}_i{head_iters}_b{b}x{block_b}"
             f"{'_es' if early_stop else ''}"
             f"{'_q8' if quantize else ''}")
    err, conv, llr, iters = pl.pallas_call(
        kernel,
        name=kname,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, block_b), lambda t: (0, t)),       # syndromes.T
            pl.BlockSpec((sgraph.rw, m), lambda t: (0, 0)),     # indices
            pl.BlockSpec((sgraph.rw, m), lambda t: (0, 0)),     # mask
            pl.BlockSpec((n, 1), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
            pl.BlockSpec((n, block_b), lambda t: (0, t)),
            pl.BlockSpec((1, block_b), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, b), jnp.int32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
            jax.ShapeDtypeStruct((n, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024,
        ),
        interpret=interpret,
    )(syndromes.T.astype(jnp.float32), sgraph.chk_idx, sgraph.mask, llr0)

    return BPResult(
        error=err.T.astype(jnp.uint8),
        converged=conv[0].astype(jnp.bool_),
        posterior_llr=llr.T,
        iterations=iters[0],
    )


def sparse_serves_pallas() -> bool:
    """True when ``bp_head_sparse(backend="auto")`` routes to the mosaic
    kernel (the telemetry variant resolver keys on this)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_V2_MOSAIC_PROBE: dict = {}


def v2_mosaic_supported(quantize: str | None = None) -> bool:
    """One-time per-process probe that the v2 kernel's mosaic lowering
    (in-register one-hot synthesis: broadcasted_iota + eq + select; plus
    the int8 MXU product for ``quantize="int8"``) holds on this
    toolchain: compiles one small real kernel the first time the v2 head
    is selected on TPU.  A bf16 failure routes the process's default
    kernel selection back to v1 (``_maybe_pallas_head``) instead of
    crashing every decode — the variant telemetry then shows
    ``dense_onehot``, so the fallback is visible, not silent; an int8
    failure makes ``quantize="int8"`` construction fail fast.  Off-TPU
    (twin path) this is trivially True and compiles nothing."""
    if quantize in _V2_MOSAIC_PROBE:
        return _V2_MOSAIC_PROBE[quantize]
    if not sparse_serves_pallas():
        ok = True
    else:
        try:
            from .bp import build_tanner_graph_host, llr_from_probs

            h = np.zeros((6, 13), np.uint8)  # hgp_rep3's hx shape
            h[:, :6] += np.eye(6, dtype=np.uint8)
            h[:, 6:12] += np.eye(6, dtype=np.uint8)
            h[:, 12] = 1
            sg = build_sparse_head(build_tanner_graph_host(h))
            synd = jnp.zeros((128, 6), jnp.uint8)
            _bp_head_sparse_pallas.lower(  # qldpc: ignore[R009] — capability probe, result never cached
                sg, synd, llr_from_probs(np.full(13, 0.01)),
                head_iters=2, ms_scaling_factor=0.625, block_b=128,
                interpret=False, early_stop=False, quantize=quantize,
            ).compile()
            ok = True
        except Exception:
            ok = False
    _V2_MOSAIC_PROBE[quantize] = ok
    return ok


def bp_head_sparse(
    sgraph: SparseHeadGraph,
    syndromes,
    channel_llr,
    *,
    head_iters: int,
    ms_scaling_factor: float = 0.625,
    block_b: int = 256,
    interpret: bool = False,
    early_stop: bool = False,
    quantize: str | None = None,
    backend: str = "auto",
) -> BPResult:
    """v2 decode of a (B, m) syndrome batch; B must divide by block_b.

    Same BPResult contract as ``bp_head_pallas``.  ``backend`` routes:
    "auto" = Pallas kernel on TPU, XLA twin elsewhere (bit-exact with the
    kernel — shared bodies, matching batch tiles); "pallas"/"xla" force a
    path (tests, probes).  ``quantize="int8"`` selects the int8 min-sum
    loop on either path.
    """
    if quantize not in (None, "int8"):
        raise ValueError(f"unknown quantize mode {quantize!r}")
    use_kernel = interpret or backend == "pallas" or (
        backend == "auto" and sparse_serves_pallas())
    if use_kernel:
        return _bp_head_sparse_pallas(
            sgraph, syndromes, channel_llr, head_iters=head_iters,
            ms_scaling_factor=float(ms_scaling_factor), block_b=block_b,
            interpret=interpret, early_stop=early_stop, quantize=quantize)
    return _bp_head_sparse_xla(
        sgraph, syndromes, channel_llr, head_iters=head_iters,
        ms_scaling_factor=float(ms_scaling_factor), block_b=block_b,
        early_stop=early_stop, quantize=quantize)
