"""Batched ordered-statistics decoding on TPU.

The host C++ OSD (_native/osd.cpp) is exact but sequential per shot — on a
small-core host it caps every BP+OSD pipeline at O(100) shots/s.  This
module runs the same algorithm for a whole batch on device:

  * One Gaussian elimination serves all shots: H's GF(2) rank is a property
    of the matrix, not the shot, so every per-shot array has static shape
    (rank r*, free count n-r*) — only the column *order* (by posterior
    reliability) differs per shot.
  * Rows are bit-packed into uint32 words; the elimination is a
    ``lax.while_loop`` over reliability-ordered columns with all-shots
    row-XOR updates (traffic O(steps * B * m * n/32) bytes), exiting as
    soon as every shot reaches full rank.
  * OSD-E reprocessing scores all 2^w candidate free-bit patterns with MXU
    matmuls ((T @ P) mod 2 and cost contractions), scanned in chunks so
    nothing of size (B, r*, 2^w) is materialized; only the winning
    pattern's solution is reconstructed.

Semantics mirror _native/osd.cpp exactly (same stable reliability sort,
first-available-row pivoting, strict-< candidate preference in pattern
order); decoders/osd.py's numpy oracle doubles as this kernel's test
oracle.  Costs are float32 on device (the C++ uses float64) — candidates
whose costs tie within float32 may legitimately differ; the tests compare
costs, not just patterns.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["OsdPlan", "build_osd_plan", "osd_decode_device"]


from ._pallas_compat import CompilerParams
from .bp import _LruCache  # shared bounded memo (see ops/bp.py)

_pack_cache = _LruCache()


def _pack_h(h: np.ndarray):
    """(rank, device bit-packed rows) of H — p-independent, memoized so
    p-sweeps rebuilding BPOSD decoders per cell don't re-rank/re-upload."""
    from ..codes import gf2

    def make():
        m, n = h.shape
        words = (n + 31) // 32
        hp = np.pad(h, ((0, 0), (0, words * 32 - n)))
        packed = (
            hp.reshape(m, words, 32).astype(np.uint64)
            << np.arange(32, dtype=np.uint64)
        ).sum(axis=2).astype(np.uint32)
        return int(gf2.rank(h)), jax.device_put(packed)

    return _pack_cache.get((h.shape, h.tobytes()), make)


class OsdPlan:
    """Static per-H data for device OSD (hashable: used in jit cache keys)."""

    def __init__(self, h: np.ndarray, channel_cost: np.ndarray):
        h = (np.asarray(h) != 0).astype(np.uint8)
        self.m, self.n = h.shape
        self.words = (self.n + 31) // 32
        self.rank, self.packed = _pack_h(h)
        self.cost = jax.device_put(np.asarray(channel_cost, np.float32))
        self._key = (self.m, self.n, self.rank,
                     h.tobytes(), np.asarray(channel_cost).tobytes())

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, OsdPlan) and self._key == other._key


def build_osd_plan(h, channel_probs) -> OsdPlan:
    # single source of truth for the signed-cost convention (priors > 1/2
    # get negative flip costs) shared with the host path
    from ..decoders.osd import _channel_cost

    return OsdPlan(h, _channel_cost(channel_probs))


def _permute_and_pack(h01, perm):
    """Per-shot column-permuted bit-packed rows, **batch-last**: (W, m, B)
    uint32 with permuted column t at word t>>5, bit t&31.

    Batch-last mirrors the BP kernel's layout lesson: every elimination-loop
    tensor keeps the shot batch on the 128-lane minor axis (full vector
    utilization), and the loop's column extraction is a contiguous
    ``dynamic_slice`` on the leading word axis — no per-shot gathers.

    Implementation: gather COLUMN-packed words (each permuted column's bits
    over rows, (B, n, mW) — the smallest gatherable representation, ~8x less
    traffic than gathering unpacked (m, B, n) bytes), then convert to
    row-packed with a vectorized 32x32 bit-matrix transpose (5 masked
    shift/combine rounds, Hacker's Delight 7-3)."""
    B, n = perm.shape
    m = h01.shape[0]
    W = (n + 31) // 32
    mW = (m + 31) // 32
    # column-packed H: colpack[t, rw] = bits of column t at rows rw*32..+31
    ht = jnp.pad(h01.T, ((0, 0), (0, mW * 32 - m)))           # (n, mW*32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    colpack = jnp.sum(
        ht.reshape(n, mW, 32).astype(jnp.uint32) << shifts, axis=2,
        dtype=jnp.uint32)                                     # (n, mW)
    g = colpack[perm]                                         # (B, n, mW)
    pad = W * 32 - n
    if pad:
        g = jnp.pad(g, ((0, 0), (0, pad), (0, 0)))
    x = jnp.moveaxis(g, 0, -1).reshape(W, 32, mW, B)          # j-axis = 1
    # 32x32 bit transpose of (word-index j, bit-index r) -> (r, j); the
    # shift network transposes the bit-reversed orientation, so reverse the
    # j-axis going in and the r-axis coming out
    x = x[:, ::-1]
    for sh in (16, 8, 4, 2, 1):
        mask = jnp.uint32(sum(((1 << sh) - 1) << off
                              for off in range(0, 32, 2 * sh)))
        x2 = x.reshape(W, 32 // (2 * sh), 2, sh, mW, B)
        lo, hi = x2[:, :, 0], x2[:, :, 1]
        t = (lo ^ (hi >> jnp.uint32(sh))) & mask
        lo = lo ^ t
        hi = hi ^ (t << jnp.uint32(sh))
        x = jnp.stack([lo, hi], axis=2).reshape(W, 32, mW, B)
    x = x[:, ::-1]                                            # (W, r, rw, B)
    out = jnp.moveaxis(x, 1, 2).reshape(W, mW * 32, B)        # row = rw*32+r
    return out[:, :m]


def _eliminate_blocked(plan, perm, syndromes):
    """All-shots RREF processing 32 reliability-ordered columns per loop step.

    Same contract and results as ``_eliminate`` (same first-available-row
    pivoting in the same column order), restructured for TPU wall-clock:

      * **Phase A** (per 32-column word block): a micro-elimination runs on
        the current word slice ``cw`` (m, B) only, unrolled over its 32 bit
        positions.  Alongside the slice it maintains ``aug`` (m, B) uint32 —
        bit j of ``aug[r]`` says "block-start pivot row j is XORed into row
        r by this block's row ops".  The augmented bookkeeping linearizes
        the cascade: row updates inside the block compose as
        ``aug_r ^= aug_piv ^ (1 << j)``, so the block's total effect on ANY
        word of the matrix is a plain GF(2) combination of block-start
        pivot rows.
      * **Phase B**: gather the 32 block-start pivot rows G0 (32, W, B)
        once, then update the whole packed matrix in ONE fused pass:
        ``packed ^= XOR_j bit_j(aug) & G0[j]``.

    The per-column variant touches the full (W, m, B) matrix once per
    column; this touches it ~twice per 32 columns — an order of magnitude
    less HBM traffic — and runs ~n/32 while-loop iterations instead of ~n
    (each XLA loop iteration costs fixed dispatch latency).
    """
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    W = (n + 31) // 32
    h01 = _unpack_rows(plan.packed, n)
    rows_m = jnp.arange(m, dtype=jnp.int32)[:, None]          # (m, 1)
    slots = jnp.arange(r_star, dtype=jnp.int32)[:, None]      # (r*, 1)
    one = jnp.uint32(1)

    def cond(state):
        t_word, packed, synd, used, rank, pr, pc, ipw = state
        return (t_word < W) & jnp.any(rank < r_star)

    def step(state):
        t_word, packed, synd, used, rank, pr, pc, ipw = state
        cw = jax.lax.dynamic_slice(
            packed, (t_word, 0, 0), (1, m, B))[0]              # (m, B) u32
        aug = jnp.zeros((m, B), jnp.uint32)
        pivword = jnp.zeros((m, B), jnp.uint32)
        # block-local per-step records, stacked for the post-block updates
        pivs, hass, ranks = [], [], []
        for j in range(32):
            bits = ((cw >> jnp.uint32(j)) & one).astype(bool)  # (m, B)
            avail = bits & ~used & (rank < r_star)[None, :]
            has = avail.any(axis=0)                            # (B,)
            piv = jnp.argmax(avail, axis=0).astype(jnp.int32)  # first True
            onehot = (rows_m == piv[None, :]) & has[None, :]   # (m, B)
            prow_w = jnp.sum(jnp.where(onehot, cw, one * 0), axis=0,
                             dtype=jnp.uint32)                 # (B,)
            ps = jnp.sum(jnp.where(onehot, synd, jnp.uint8(0)), axis=0,
                         dtype=jnp.uint8)                      # (B,)
            paug = jnp.sum(jnp.where(onehot, aug, one * 0), axis=0,
                           dtype=jnp.uint32)                   # (B,)
            clear = (bits & ~onehot & has[None, :]).astype(jnp.uint32)
            cw = cw ^ (clear * prow_w[None, :])
            synd = synd ^ (clear.astype(jnp.uint8) * ps[None, :])
            aug = aug ^ (clear * ((paug ^ (one << jnp.uint32(j)))[None, :]))
            pivword = pivword | (onehot.astype(jnp.uint32) << jnp.uint32(j))
            used = used | onehot
            pivs.append(piv)
            hass.append(has)
            ranks.append(rank)
            rank = rank + has.astype(jnp.int32)
        pivs = jnp.stack(pivs)                                 # (32, B)
        hass = jnp.stack(hass)                                 # (32, B)
        ranks = jnp.stack(ranks)                               # (32, B)
        # slot bookkeeping: each slot is written at most once over the whole
        # elimination (rank strictly increases), so the block's contribution
        # is a masked sum over its 32 steps — one fused reduction instead of
        # 32 full-array writes
        match = (ranks[:, None, :] == slots[None, :, :]) & hass[:, None, :]
        pr = pr + jnp.sum(jnp.where(match, pivs[:, None, :], 0), axis=0,
                          dtype=jnp.int32)                     # (r*, B)
        t0 = t_word * 32
        tcols = t0 + jnp.arange(32, dtype=jnp.int32)[:, None, None]
        pc = pc + jnp.sum(jnp.where(match, tcols, 0), axis=0,
                          dtype=jnp.int32)                     # (r*, B)
        # pivot-column bitmap, packed a word per block (unpacked by caller)
        hasword = jnp.sum(
            hass.astype(jnp.uint32)
            << jnp.arange(32, dtype=jnp.uint32)[:, None],
            axis=0, dtype=jnp.uint32,
        )                                                      # (B,)
        ipw = jax.lax.dynamic_update_slice(ipw, hasword[None, :], (t_word, 0))
        # Phase B: gather the 32 block-start pivot rows in one pass, then one
        # fused 32-term XOR applies the whole block to every word.  Rows at
        # steps with no pivot (has=False) gather row piv=0 — harmless, their
        # aug bit is never set so the mask zeroes them.
        idx = jnp.broadcast_to(pivs[None], (W, 32, B))
        g0 = jnp.take_along_axis(packed, idx, axis=1)          # (W, 32, B)
        delta = jnp.zeros((W, m, B), jnp.uint32)
        for j in range(32):
            sel = 0 - ((aug >> jnp.uint32(j)) & one)           # (m, B) mask
            delta = delta ^ (sel[None, :, :] & g0[:, j, None, :])
        packed = packed ^ delta
        return (t_word + 1, packed, synd, used, rank, pr, pc, ipw)

    state = (
        jnp.int32(0),
        _permute_and_pack(h01, perm),
        syndromes.astype(jnp.uint8).T,                         # (m, B)
        jnp.zeros((m, B), bool),
        jnp.zeros((B,), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((W, B), jnp.uint32),
    )
    _, packed, synd, used, rank, pr, pc, ipw = jax.lax.while_loop(
        cond, step, state)
    # unpack the pivot-column bitmap to (n, B) bool
    shifts = jnp.arange(32, dtype=jnp.uint32)
    ip = ((ipw[:, None, :] >> shifts[:, None]) & one).astype(bool)
    ip = ip.reshape(W * 32, B)[:n]
    u_piv = jnp.take_along_axis(synd, pr, axis=0)              # (r*, B)
    return u_piv, pr, pc, ip, packed


def _eliminate(plan, perm, syndromes):
    """All-shots RREF over per-shot reliability-permuted columns.

    All loop state is batch-last.  Returns (u_piv (r*, B) reduced syndrome
    at pivot rows, pivot_rows (r*, B), pivot_cols_perm (r*, B) PERMUTED
    column ids, is_pivot_perm (n, B) bool, packed (W, m, B) reduced
    permuted rows).  Callers map permuted ids to original via ``perm``."""
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    h01 = _unpack_rows(plan.packed, n)
    rows_m = jnp.arange(m, dtype=jnp.int32)[:, None]          # (m, 1)
    slots = jnp.arange(r_star, dtype=jnp.int32)[:, None]      # (r*, 1)
    cols_n = jnp.arange(n, dtype=jnp.int32)[:, None]          # (n, 1)

    def cond(state):
        t, packed, synd, used, rank, pr, pc, ip = state
        return (t < n) & jnp.any(rank < r_star)

    def step(state):
        t, packed, synd, used, rank, pr, pc, ip = state
        # permuted column t lives at a *shot-independent* word/bit position
        word_t = (t >> 5).astype(jnp.int32)
        bit_t = (t & 31).astype(jnp.uint32)
        col_words = jax.lax.dynamic_slice(
            packed, (word_t, 0, 0), (1, m, B))[0]             # (m, B)
        bits = ((col_words >> bit_t) & 1).astype(bool)
        active = rank < r_star                                # (B,)
        avail = bits & ~used & active[None, :]
        has = avail.any(axis=0)                               # (B,)
        piv = jnp.argmax(avail, axis=0).astype(jnp.int32)     # first True
        # pivot row/syndrome via masked reduction instead of a per-shot
        # (lane-varying) gather: one fused pass over packed at full HBM
        # bandwidth, exact because exactly one row is selected per shot
        onehot = (rows_m == piv[None, :])                     # (m, B)
        prow = jnp.sum(
            jnp.where(onehot[None], packed, jnp.uint32(0)), axis=1,
            dtype=jnp.uint32,
        )                                                     # (W, B)
        ps = jnp.sum(jnp.where(onehot, synd, jnp.uint8(0)), axis=0,
                     dtype=jnp.uint8)                         # (B,)
        clear = bits & ~onehot & has[None, :]                 # (m, B)
        packed = packed ^ (clear[None].astype(jnp.uint32) * prow[:, None, :])
        synd = synd ^ (clear.astype(jnp.uint8) * ps[None, :])
        at_slot = (slots == rank[None, :]) & has[None, :]     # (r*, B)
        pr = jnp.where(at_slot, piv[None, :], pr)
        pc = jnp.where(at_slot, t, pc)
        ip = ip | ((cols_n == t) & has[None, :])              # (n, B)
        used = used | (onehot & has[None, :])
        rank = rank + has.astype(jnp.int32)
        return (t + 1, packed, synd, used, rank, pr, pc, ip)

    body = step

    state = (
        jnp.int32(0),
        _permute_and_pack(h01, perm),
        syndromes.astype(jnp.uint8).T,                        # (m, B)
        jnp.zeros((m, B), bool),
        jnp.zeros((B,), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((n, B), bool),
    )
    _, packed, synd, used, rank, pr, pc, ip = jax.lax.while_loop(
        cond, body, state)
    u_piv = jnp.take_along_axis(synd, pr, axis=0)             # (r*, B)
    return u_piv, pr, pc, ip, packed


def _unpack_rows(packed, n):
    """(m, W) uint32 -> (m, n) uint8."""
    m, W = packed.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((packed[:, :, None] >> shifts) & 1).astype(jnp.uint8)
    return bits.reshape(m, W * 32)[:, :n]


# ---------------------------------------------------------------------------
# Pallas elimination (EXPERIMENTAL, opt-in via QLDPC_PALLAS_OSD=1): the same
# RREF loop with all state resident in VMEM, one kernel launch per batch
# tile, bit-exact vs the XLA path (integer ops throughout; validated by
# interpret-mode equality tests).  Status: measured op-bound under mosaic on
# v5e (slower than the XLA while_loop for hgp-sized codes) — retained as
# the starting point for future kernel tuning, not as the default path.
def _elim_kernel(packed_ref, synd_ref, out_packed_ref, out_synd_ref,
                 pr_ref, pc_ref, ip_ref, work_ref, used_ref, rank_ref,
                 *, W: int, m: int, n: int, r_star: int, bt: int):
    """One batch tile; the evolving matrix lives in the ``work_ref`` VMEM
    scratch (mosaic lowers dynamic ``pl.ds`` loads on refs, not on values,
    so the per-column word extraction reads the scratch)."""
    i32 = jnp.int32
    rows_m = jax.lax.broadcasted_iota(i32, (m, bt), 0)
    slots = jax.lax.broadcasted_iota(i32, (r_star, bt), 0)
    cols = jax.lax.broadcasted_iota(i32, (n, bt), 0)

    work_ref[:] = packed_ref[:]
    out_synd_ref[:] = synd_ref[:]
    used_ref[:] = jnp.zeros((m, bt), i32)
    rank_ref[:] = jnp.zeros((8, bt), i32)
    pr_ref[:] = jnp.zeros((r_star, bt), i32)
    pc_ref[:] = jnp.zeros((r_star, bt), i32)
    ip_ref[:] = jnp.zeros((n, bt), i32)

    # all loop state lives in refs — a large while-loop carry would be
    # copied every iteration; the carry is just the column counter
    def cond(t):
        return (t < n) & (jnp.min(rank_ref[0, :]) < r_star)

    def body(t):
        wt = t >> 5
        bit = t & 31
        rank = rank_ref[0, :]                                    # (bt,)
        used = used_ref[:]
        colw = work_ref[pl.ds(wt, 1)][0]                         # (m, bt)
        bits = jax.lax.shift_right_logical(colw, bit) & 1        # (m, bt)
        active = jnp.where(rank < r_star, 1, 0)                  # (bt,)
        avail = bits * (1 - used) * active[None, :]
        # first available row = min row index among avail (integer argmax
        # isn't lowered by mosaic; min-index reduction is)
        cand = jnp.where(avail == 1, rows_m, m)
        piv = jnp.min(cand, axis=0)                              # (bt,)
        has = jnp.where(piv < m, 1, 0)
        piv = jnp.where(piv < m, piv, 0)
        onehot = jnp.where(rows_m == piv[None, :], 1, 0)
        packed = work_ref[:]
        synd = out_synd_ref[:]
        prow = jnp.sum(onehot[None] * packed, axis=1)            # (W, bt)
        ps = jnp.sum(onehot * synd, axis=0)                      # (bt,)
        clear = bits * (1 - onehot) * has[None, :]
        work_ref[:] = packed ^ (clear[None] * prow[:, None, :])
        out_synd_ref[:] = synd ^ (clear * ps[None, :])
        at = jnp.where((slots == rank[None, :])
                       & (has[None, :] == 1), 1, 0)              # (r*, bt)
        pr_ref[:] = jnp.where(at == 1, piv[None, :], pr_ref[:])
        pc_ref[:] = jnp.where(at == 1, t, pc_ref[:])
        ip_ref[:] = ip_ref[:] | jnp.where(
            (cols == t) & (has[None, :] == 1), 1, 0)
        used_ref[:] = used | (onehot * has[None, :])
        rank_ref[:] = jnp.broadcast_to((rank + has)[None, :], (8, bt))
        return t + 1

    jax.lax.while_loop(cond, body, jnp.int32(0))
    out_packed_ref[:] = work_ref[:]


# tile state ~ (W*m + extras) * bt * 4 bytes must fit the scoped VMEM cap
_ELIM_VMEM_LIMIT = 100 * 1024 * 1024


def _elim_pallas_ok(W, m, n, r_star, bt):
    words = (2 * W * m + 2 * m + 2 * r_star + 2 * n + 8) * bt
    return words * 4 <= _ELIM_VMEM_LIMIT


def _eliminate_pallas(plan, perm, syndromes, bt: int = 128,
                      interpret: bool = False):
    """Drop-in for _eliminate with the loop in a Pallas kernel.

    Same returns (u_piv, pivot_rows, pivot_cols_perm, is_pivot_perm,
    packed), bit-identical to the XLA path (integer arithmetic throughout).
    """
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    W = (n + 31) // 32
    h01 = _unpack_rows(plan.packed, n)
    packed0 = _permute_and_pack(h01, perm).astype(jnp.int32)   # (W, m, B)
    synd0 = syndromes.astype(jnp.int32).T                      # (m, B)

    kernel = functools.partial(
        _elim_kernel, W=W, m=m, n=n, r_star=r_star, bt=bt)
    grid = (B // bt,)
    packed, synd, pr, pc, ip = pl.pallas_call(
        kernel,
        name=f"osd_elim_percol_{m}x{n}_r{r_star}_B{B}x{bt}",
        grid=grid,
        in_specs=[
            pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)),
            pl.BlockSpec((m, bt), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)),
            pl.BlockSpec((m, bt), lambda t: (0, t)),
            pl.BlockSpec((r_star, bt), lambda t: (0, t)),
            pl.BlockSpec((r_star, bt), lambda t: (0, t)),
            pl.BlockSpec((n, bt), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((W, m, B), jnp.int32),
            jax.ShapeDtypeStruct((m, B), jnp.int32),
            jax.ShapeDtypeStruct((r_star, B), jnp.int32),
            jax.ShapeDtypeStruct((r_star, B), jnp.int32),
            jax.ShapeDtypeStruct((n, B), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((W, m, bt), jnp.int32),
            pltpu.VMEM((m, bt), jnp.int32),
            pltpu.VMEM((8, bt), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=_ELIM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(packed0, synd0)
    u_piv = jnp.take_along_axis(synd, pr, axis=0)              # (r*, B)
    return (u_piv, pr, pc, ip.astype(bool), packed.astype(jnp.uint32))


# ---------------------------------------------------------------------------
# Blocked elimination, shared kernel/twin bodies: the _eliminate_blocked
# algorithm with all per-block state VMEM-resident (Pallas) or carried
# through an XLA while_loop (twin).  Both entry points build their loops
# over the SAME phase-A / phase-B bodies below — the bit-exactness contract
# is structural (analysis/rules_kernels.py "osd_elim_blocked"), not just
# numerically pinned, so the pair cannot drift one edit at a time.
# Additionally maintains the "free panel" F — for every row, the bits at
# the first ``fcap`` pivotless (free) columns — so the caller needs neither
# the reduced matrix nor a post-loop T extraction: OSD-E's T is F gathered
# at the pivot rows.
def _blocked_stepA(j, c, *, t_word, n: int, fcap: int):
    """One micro-elimination step (bit ``j`` of the current word block) —
    THE shared phase-A body of the blocked Pallas kernel and its XLA twin:
    both run their 32-step ``fori_loop`` over this function.  Integer ops
    throughout, so kernel and twin are bit-identical by construction.
    Carry: ``(cw, synd, used, fword, rank, fcnt, aug, pivword, pr, pc,
    fpos)`` with the batch on the minor axis."""
    i32 = jnp.int32
    (cw, synd, used, fword, rank, fcnt, aug, pivword, pr, pc, fpos) = c
    m, bt = cw.shape
    r_star = pr.shape[0]
    rows_m = jax.lax.broadcasted_iota(i32, (m, bt), 0)
    slots = jax.lax.broadcasted_iota(i32, (r_star, bt), 0)
    k32 = jax.lax.broadcasted_iota(i32, (32, bt), 0)
    srl = jax.lax.shift_right_logical
    t = t_word * 32 + j
    bits = srl(cw, j) & 1
    active = jnp.where(rank < r_star, 1, 0)            # (bt,)
    avail = bits * (1 - used) * active[None, :]
    cand = jnp.where(avail == 1, rows_m, m)
    piv = jnp.min(cand, axis=0)                        # first avail
    has = jnp.where((piv < m) & (t < n), 1, 0)
    piv = jnp.where(piv < m, piv, 0)
    onehot = jnp.where(rows_m == piv[None, :], has[None, :], 0)
    prow = jnp.sum(onehot * cw, axis=0)                # (bt,)
    ps = jnp.sum(onehot * synd, axis=0)
    paug = jnp.sum(onehot * aug, axis=0)
    pf = jnp.sum(onehot * fword, axis=0)
    clear = bits * (1 - onehot) * has[None, :]
    cw = cw ^ (clear * prow[None, :])
    synd = synd ^ (clear * ps[None, :])
    jbit = jax.lax.shift_left(jnp.int32(1), j)
    aug = aug ^ (clear * ((paug ^ jbit)[None, :]))
    fword = fword ^ (clear * pf[None, :])
    pivword = pivword | jax.lax.shift_left(onehot, j)
    # free-column panel: no pivot at a real column -> record its
    # (current, reduced) bits at free slot fcnt
    grow = (1 - has) * jnp.where((fcnt < fcap) & (t < n), 1, 0)
    kshift = jnp.minimum(fcnt, 31)
    fword = fword ^ (jax.lax.shift_left(bits, kshift[None, :])
                     * grow[None, :])
    fpos = jnp.where((k32 == fcnt[None, :]) & (grow[None, :] == 1),
                     t, fpos)
    # pivot slot bookkeeping (each slot written at most once ever)
    at = jnp.where((slots == rank[None, :]) & (has[None, :] == 1), 1, 0)
    pr = jnp.where(at == 1, piv[None, :], pr)
    pc = jnp.where(at == 1, t, pc)
    used = used | onehot
    rank = rank + has
    fcnt = fcnt + grow
    return (cw, synd, used, fword, rank, fcnt, aug, pivword, pr, pc, fpos)


def _blocked_phaseB_delta(row, pivword, aug):
    """Fused 32-term block update for ONE packed word — THE shared phase-B
    body of the blocked kernel/twin pair.  ``row`` must be the word's
    block-START value: bit j of ``aug[r]`` selects step j's block-start
    pivot row into row r's XOR accumulator, reproducing the phase-A
    cascade exactly for any word of the matrix."""
    srl = jax.lax.shift_right_logical

    def term(j, acc):
        oh = srl(pivword, j) & 1
        g0 = jnp.sum(oh * row, axis=0)                 # (bt,)
        sel = 0 - (srl(aug, j) & 1)
        return acc ^ (sel & g0[None, :])

    return jax.lax.fori_loop(0, 32, term, jnp.zeros_like(row))


def _elim_blocked_kernel(packed_ref, synd_ref,
                         synd_out_ref, pr_ref, pc_ref, fword_ref, fpos_ref,
                         work_ref, used_ref, rank_ref, fcnt_ref,
                         *, W: int, m: int, n: int, r_star: int, fcap: int,
                         bt: int, full: bool = False):
    i32 = jnp.int32

    work_ref[:] = packed_ref[:]
    synd_out_ref[:] = synd_ref[:]
    used_ref[:] = jnp.zeros((m, bt), i32)
    rank_ref[:] = jnp.zeros((8, bt), i32)
    fcnt_ref[:] = jnp.zeros((8, bt), i32)
    pr_ref[:] = jnp.zeros((r_star, bt), i32)
    pc_ref[:] = jnp.zeros((r_star, bt), i32)
    fword_ref[:] = jnp.zeros((m, bt), i32)
    fpos_ref[:] = jnp.zeros((32, bt), i32)

    def cond(t_word):
        more_rank = jnp.min(rank_ref[0, :]) < r_star
        more_free = jnp.min(fcnt_ref[0, :]) < fcap
        return (t_word < W) & (more_rank | more_free)

    def body(t_word):
        cw0 = work_ref[pl.ds(t_word, 1)][0]                    # (m, bt)

        # phase A: 32 micro-elimination steps as a fori_loop over the
        # SHARED body (a traced bit index keeps the kernel ~30x smaller to
        # trace/lower than a python unroll, which matters: every (tier,
        # sector, shape) instantiates this kernel inside the simulators'
        # jitted pipelines)
        init = (cw0, synd_out_ref[:], used_ref[:], fword_ref[:],
                rank_ref[0, :], fcnt_ref[0, :],
                jnp.zeros((m, bt), i32), jnp.zeros((m, bt), i32),
                pr_ref[:], pc_ref[:], fpos_ref[:])
        (_, synd, used, fword, rank, fcnt, aug, pivword, pr, pc,
         fpos) = jax.lax.fori_loop(
            0, 32,
            functools.partial(_blocked_stepA, t_word=t_word, n=n,
                              fcap=fcap),
            init)
        synd_out_ref[:] = synd
        used_ref[:] = used
        fword_ref[:] = fword
        rank_ref[:] = jnp.broadcast_to(rank[None, :], (8, bt))
        fcnt_ref[:] = jnp.broadcast_to(fcnt[None, :], (8, bt))
        pr_ref[:] = pr
        pc_ref[:] = pc
        fpos_ref[:] = fpos

        # phase B: per word, gather the 32 block-start pivot-row words and
        # apply the fused 32-term combination.  ``row`` is read before the
        # writeback, so every g0 is a block-start value as the aug
        # bookkeeping requires — including the current word (its delta
        # reproduces the phase-A cascade exactly).  Words LEFT of the
        # current block are skipped: no later phase reads them (phase A
        # slices word t only, future g0 gathers read w >= t, and the
        # kernel's outputs — synd/pr/pc/fword/fpos — are all tracked
        # incrementally), and the current word is equally dead after its
        # phase A, so the update starts at t_word+1; the skip halves the
        # kernel's dominant cost on average.  ``full`` (the OSD-CS
        # variant) disables the skip: every word is maintained — each
        # ``row`` is still a block-START value (phase A never writes
        # work_ref, and stepB reads before writing), so the delta applied
        # at word t_word reproduces phase A exactly and the scratch ends
        # as the true fully-reduced matrix.
        def stepB(w_i, _):
            row = work_ref[pl.ds(w_i, 1)][0]                   # (m, bt)
            acc = _blocked_phaseB_delta(row, pivword, aug)
            work_ref[pl.ds(w_i, 1)] = (row ^ acc)[None]
            return 0

        jax.lax.fori_loop(0 if full else t_word + 1, W, stepB, 0)
        return t_word + 1

    jax.lax.while_loop(cond, body, jnp.int32(0))


def _elim_blocked_pallas_ok(W, m, n, r_star, bt, full: bool = False):
    # the full variant adds one (W, m, bt) output block for the reduced
    # matrix on top of the shared scratch
    words = ((3 if full else 2) * W * m + 5 * m + 2 * r_star + 2 * 32
             + 16) * bt
    return words * 4 <= _ELIM_VMEM_LIMIT


def _elim_blocked_full_kernel(packed_ref, synd_ref,
                              synd_out_ref, pr_ref, pc_ref, fword_ref,
                              fpos_ref, packed_out_ref,
                              work_ref, used_ref, rank_ref, fcnt_ref,
                              *, W: int, m: int, n: int, r_star: int,
                              fcap: int, bt: int):
    """Full-maintenance variant (OSD-CS): the same blocked loop with the
    dead-word skip disabled, plus the fully-reduced matrix as an output —
    routes through ``_elim_blocked_kernel`` (and thus the SAME shared
    phase-A/phase-B bodies the R007 "osd_elim_blocked" contract pins)."""
    _elim_blocked_kernel(
        packed_ref, synd_ref, synd_out_ref, pr_ref, pc_ref, fword_ref,
        fpos_ref, work_ref, used_ref, rank_ref, fcnt_ref,
        W=W, m=m, n=n, r_star=r_star, fcap=fcap, bt=bt, full=True)
    packed_out_ref[:] = work_ref[:]


def _eliminate_pallas_blocked(plan, perm, syndromes, fcap: int,
                              bt: int = 128, interpret: bool = False,
                              full: bool = False):
    """VMEM-resident blocked RREF.  Returns (synd (m, B) fully reduced,
    pivot_rows (r*, B), pivot_cols_perm (r*, B), fword (m, B) free-panel
    words, fpos (32, B) permuted free-column positions); with
    ``full=True`` (the OSD-CS route) additionally the fully-maintained
    reduced matrix (W, m, B) as a sixth output."""
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    W = (n + 31) // 32
    h01 = _unpack_rows(plan.packed, n)
    packed0 = _permute_and_pack(h01, perm).astype(jnp.int32)   # (W, m, B)
    synd0 = syndromes.astype(jnp.int32).T                      # (m, B)

    if full:
        kernel = functools.partial(
            _elim_blocked_full_kernel, W=W, m=m, n=n, r_star=r_star,
            fcap=int(fcap), bt=bt)
    else:
        kernel = functools.partial(
            _elim_blocked_kernel, W=W, m=m, n=n, r_star=r_star,
            fcap=int(fcap), bt=bt)
    grid = (B // bt,)
    # unique deterministic name per instantiation (see bp_pallas: mosaic's
    # same-name uniquing is process-history-dependent and breaks the
    # persistent compilation cache)
    kname = (f"osd_elim_{'full_' if full else ''}{m}x{n}_r{r_star}"
             f"_f{int(fcap)}_B{B}x{bt}")
    out_specs = [
        pl.BlockSpec((m, bt), lambda t: (0, t)),
        pl.BlockSpec((r_star, bt), lambda t: (0, t)),
        pl.BlockSpec((r_star, bt), lambda t: (0, t)),
        pl.BlockSpec((m, bt), lambda t: (0, t)),
        pl.BlockSpec((32, bt), lambda t: (0, t)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m, B), jnp.int32),
        jax.ShapeDtypeStruct((r_star, B), jnp.int32),
        jax.ShapeDtypeStruct((r_star, B), jnp.int32),
        jax.ShapeDtypeStruct((m, B), jnp.int32),
        jax.ShapeDtypeStruct((32, B), jnp.int32),
    ]
    if full:
        out_specs.append(pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)))
        out_shape.append(jax.ShapeDtypeStruct((W, m, B), jnp.int32))
    outs = pl.pallas_call(
        kernel,
        name=kname,
        grid=grid,
        in_specs=[
            pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)),
            pl.BlockSpec((m, bt), lambda t: (0, t)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((W, m, bt), jnp.int32),
            pltpu.VMEM((m, bt), jnp.int32),
            pltpu.VMEM((8, bt), jnp.int32),
            pltpu.VMEM((8, bt), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=_ELIM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(packed0, synd0)
    return tuple(outs)


def _eliminate_blocked_twin(plan, perm, syndromes, fcap: int,
                            full: bool = False):
    """XLA twin of the blocked VMEM kernel, built from the SAME phase-A /
    phase-B bodies (``_blocked_stepA`` / ``_blocked_phaseB_delta``) — the
    structural contract is registered in analysis/rules_kernels.py
    ("osd_elim_blocked") so copy-paste drift is a lint failure.  Integer
    arithmetic throughout, so twin and kernel are bit-identical; this is
    what lets ``device_osd`` engage (and default) off-TPU.

    Same returns as ``_eliminate_pallas_blocked``: ``(synd (m, B) fully
    reduced, pivot_rows (r*, B), pivot_cols_perm (r*, B), fword (m, B)
    free-panel words, fpos (32, B) permuted free-column positions)``.
    Phase B applies the fused block update only to words strictly RIGHT of
    the current block — the same dead-word skip the kernel's ``stepB``
    range encodes — so every word the loop later reads holds exactly the
    value the kernel's VMEM scratch would.  ``full=True`` (the OSD-CS
    route, mirroring the kernel's ``full`` flag) disables the skip and
    returns the fully-maintained reduced matrix (W, m, B) as a sixth
    output: each delta is computed on block-start values, so applying it
    to EVERY word — including the current one, whose delta reproduces
    phase A exactly — yields the true full RREF."""
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    W = (n + 31) // 32
    i32 = jnp.int32
    h01 = _unpack_rows(plan.packed, n)
    packed0 = _permute_and_pack(h01, perm).astype(i32)         # (W, m, B)
    synd0 = syndromes.astype(i32).T                            # (m, B)
    words = jax.lax.broadcasted_iota(i32, (W, 1, 1), 0)

    def cond(c):
        t_word, rank, fcnt = c[0], c[5], c[6]
        more_rank = jnp.min(rank) < r_star
        more_free = jnp.min(fcnt) < int(fcap)
        return (t_word < W) & (more_rank | more_free)

    def body(c):
        (t_word, packed, synd, used, fword, rank, fcnt, pr, pc, fpos) = c
        cw0 = jax.lax.dynamic_slice(packed, (t_word, 0, 0), (1, m, B))[0]
        init = (cw0, synd, used, fword, rank, fcnt,
                jnp.zeros((m, B), i32), jnp.zeros((m, B), i32), pr, pc,
                fpos)
        (_, synd, used, fword, rank, fcnt, aug, pivword, pr, pc,
         fpos) = jax.lax.fori_loop(
            0, 32,
            functools.partial(_blocked_stepA, t_word=t_word, n=n,
                              fcap=int(fcap)),
            init)
        delta = jax.vmap(
            lambda row: _blocked_phaseB_delta(row, pivword, aug))(packed)
        if full:
            packed = packed ^ delta
        else:
            live = 0 - (words > t_word).astype(i32)  # all-ones mask, w > t
            packed = packed ^ (delta & live)
        return (t_word + 1, packed, synd, used, fword, rank, fcnt, pr, pc,
                fpos)

    state = (jnp.int32(0), packed0, synd0,
             jnp.zeros((m, B), i32), jnp.zeros((m, B), i32),
             jnp.zeros((B,), i32), jnp.zeros((B,), i32),
             jnp.zeros((r_star, B), i32), jnp.zeros((r_star, B), i32),
             jnp.zeros((32, B), i32))
    (_t, packed, synd, _used, fword, _rank, _fcnt, pr, pc,
     fpos) = jax.lax.while_loop(cond, body, state)
    if full:
        return synd, pr, pc, fword, fpos, packed
    return synd, pr, pc, fword, fpos


def osd_decode_device(plan: OsdPlan, syndromes, posterior_llrs,
                      osd_order: int = 10, pat_chunk: int = 256):
    """OSD-E decode a batch on device. Returns (B, n) uint8 errors.

    ``osd_order=0`` gives OSD-0.  Matches _native/osd.cpp semantics."""
    return osd_decode_values(
        (plan.n, plan.rank, int(osd_order), int(pat_chunk),
         os.environ.get("QLDPC_OSD_ELIM", "pallas")),
        plan.packed, plan.cost, syndromes, posterior_llrs,
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def osd_decode_values(cfg, h_packed, cost, syndromes, posterior_llrs):
    """Value-based entry (composable inside the simulators' shared jitted
    pipelines): ``cfg`` = (n, rank, osd_order, pat_chunk[, elim]) is static,
    the bit-packed rows and signed costs are traced arguments — a p-sweep
    changes only ``cost`` and reuses the executable."""
    n, r_star, osd_order, pat_chunk = cfg[:4]
    elim = cfg[4] if len(cfg) > 4 else os.environ.get("QLDPC_OSD_ELIM",
                                                      "pallas")
    B = syndromes.shape[0]

    class _P:  # adapt values to the plan-shaped helpers below
        pass

    plan = _P()
    plan.m, plan.words = h_packed.shape
    plan.n, plan.rank = n, r_star
    plan.packed, plan.cost = h_packed, cost

    from ..decoders.osd import OSD_CS_MAX_ORDER, _check_osd_order

    perm = jnp.argsort(posterior_llrs, axis=1, stable=True).astype(jnp.int32)
    W = (n + 31) // 32
    bt = 128
    w = min(_check_osd_order(osd_order), n - r_star, OSD_CS_MAX_ORDER)
    # elimination strategy (QLDPC_OSD_ELIM): "pallas" (default) = the
    # VMEM-resident blocked kernel; off-TPU (or at shapes the kernel's
    # gates reject) it routes to "twin" — the XLA twin built from the SAME
    # blocked body, which is what makes device OSD the default BPOSD
    # backend on every substrate.  "blocked" / "percol" = the standalone
    # XLA variants (test oracles); "pallas_percol" = the original
    # per-column experimental kernel.
    if elim == "pallas" and not (
        B % bt == 0
        and r_star >= 1
        and _elim_blocked_pallas_ok(W, plan.m, n, r_star, bt)
        and jax.default_backend() == "tpu"
    ):
        elim = "twin"
    if elim == "twin" and r_star < 1:
        elim = "blocked"
    if elim == "pallas_percol" and not (
        B % bt == 0
        and r_star >= 1
        and _elim_pallas_ok(W, plan.m, n, r_star, bt)
        and jax.default_backend() == "tpu"
    ):
        elim = "blocked"  # same fallback the old opt-in guard provided

    if elim in ("pallas", "twin"):
        if elim == "pallas":
            synd_r, piv_rows_t, piv_cols_perm_t, fword_r, fpos = \
                _eliminate_pallas_blocked(plan, perm, syndromes,
                                          fcap=max(w, 0), bt=bt)
        else:
            synd_r, piv_rows_t, piv_cols_perm_t, fword_r, fpos = \
                _eliminate_blocked_twin(plan, perm, syndromes,
                                        fcap=max(w, 0))
        u_piv_t = jnp.take_along_axis(synd_r, piv_rows_t, axis=0)  # (r*, B)
        free_perm = fpos[:w] if w > 0 else None                # (w, B)
        if w > 0:
            fw_piv = jnp.take_along_axis(fword_r, piv_rows_t, axis=0)
            T = (
                (fw_piv.T[:, :, None] >> jnp.arange(w, dtype=jnp.int32)
                 [None, None, :]) & 1
            ).astype(jnp.float32)                              # (B, r*, w)
    else:
        if elim == "pallas_percol":
            u_piv_t, piv_rows_t, piv_cols_perm_t, is_pivot_perm_t, packed = \
                _eliminate_pallas(plan, perm, syndromes, bt=bt)
        elif elim == "percol":
            u_piv_t, piv_rows_t, piv_cols_perm_t, is_pivot_perm_t, packed = \
                _eliminate(plan, perm, syndromes)
        else:
            u_piv_t, piv_rows_t, piv_cols_perm_t, is_pivot_perm_t, packed = \
                _eliminate_blocked(plan, perm, syndromes)
        if w > 0:
            # free columns in reliability order = non-pivot PERMUTED
            # positions in ascending order
            free_perm = jnp.argsort(
                is_pivot_perm_t, axis=0, stable=True)[:w].astype(jnp.int32)
            # T[b, i, k]: bit of reduced pivot row i at free column k
            rows = jnp.take_along_axis(
                packed,
                jnp.broadcast_to(piv_rows_t[None], (W, r_star, B)), axis=1
            )                                                  # (W, r*, B)
            fword = jnp.broadcast_to(
                (free_perm >> 5)[:, None, :], (w, r_star, B))
            fbit = (free_perm & 31).astype(jnp.uint32)[:, None, :]
            T = ((jnp.take_along_axis(rows, fword, axis=0) >> fbit) & 1)
            T = jnp.transpose(T, (2, 1, 0)).astype(jnp.float32)  # (B, r*, w)

    u_piv = u_piv_t.T                                         # (B, r*)
    # permuted -> original column ids
    piv_cols = jnp.take_along_axis(perm, piv_cols_perm_t.T, axis=1)

    cost_piv = plan.cost[piv_cols]                            # (B, r*)
    batch_idx = jnp.arange(B)[:, None]
    if w <= 0:
        return (
            jnp.zeros((B, n), jnp.uint8)
            .at[batch_idx, piv_cols].set(u_piv.astype(jnp.uint8))
        )

    free = jnp.take_along_axis(perm, free_perm.T, axis=1)     # (B, w) orig

    cost_free = plan.cost[free]                               # (B, w)
    n_pat = 1 << w
    # chunk starts must never clamp (a clamped dynamic_slice would
    # mis-attribute chunk-local argmin indices to wrong global pattern ids):
    # round a non-dividing caller-supplied chunk down to a power of two,
    # which always divides the power-of-two n_pat (advisor finding, round 2)
    pat_chunk = min(int(pat_chunk), n_pat)
    if n_pat % pat_chunk:
        pat_chunk = 1 << (pat_chunk.bit_length() - 1)
    assert n_pat % pat_chunk == 0
    pats = jnp.arange(n_pat, dtype=jnp.int32)
    pmat = ((pats[None, :] >> jnp.arange(w)[:, None]) & 1).astype(
        jnp.float32)                                          # (w, n_pat)

    # pivot bit of candidate p: u_i XOR parity(T_i . p).  Linearized for one
    # fewer (B, r*, C) pass:  sum_i c_i*(u_i ^ par_i)
    #   = sum_i c_i*u_i + sum_i c_i*(1-2u_i)*par_i   (exact for u in {0,1})
    # so the per-candidate cost needs only the parity tensor, contracted
    # against the precomputed signed costs.
    signed_piv = cost_piv * (1.0 - 2.0 * u_piv.astype(jnp.float32))

    def score_chunk(carry, start):
        best_cost, best_pat = carry
        pchunk = jax.lax.dynamic_slice_in_dim(pmat, start, pat_chunk, axis=1)
        # the T matmul runs at default (bf16-operand) precision: operands
        # are exact 0/1 and sums are <= w <= 20, all exactly representable
        # — only the real-valued COST contractions need HIGHEST (bf16
        # rounding there can mis-rank near-tied candidates under DEM priors)
        hi = jax.lax.Precision.HIGHEST
        s = jnp.einsum("brw,wp->brp", T, pchunk,
                       preferred_element_type=jnp.float32)      # (B, r*, C)
        par = s - 2.0 * jnp.floor(s * 0.5)                      # exact ints
        c = (
            base_cost[:, None]
            + jnp.einsum("brp,br->bp", par, signed_piv, precision=hi)
            + jnp.matmul(cost_free, pchunk, precision=hi)       # (B, C)
        )
        idx = jnp.argmin(c, axis=1)                           # first min
        cmin = jnp.take_along_axis(c, idx[:, None], axis=1)[:, 0]
        better = cmin < best_cost                             # strict <
        best_pat = jnp.where(better, start + idx.astype(jnp.int32), best_pat)
        best_cost = jnp.where(better, cmin, best_cost)
        return (best_cost, best_pat), None

    # pattern 0 (pure OSD-0) is the base candidate, like the C++
    base_cost = jnp.einsum("br,br->b", u_piv.astype(jnp.float32), cost_piv,
                           precision=jax.lax.Precision.HIGHEST)
    n_chunks = -(-n_pat // pat_chunk)
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * pat_chunk
    (best_cost, best_pat), _ = jax.lax.scan(
        score_chunk, (base_cost, jnp.zeros((B,), jnp.int32)), starts)

    # reconstruct only the winning pattern's solution
    pbest = ((best_pat[:, None] >> jnp.arange(w)[None, :]) & 1).astype(
        jnp.float32)                                          # (B, w)
    piv_bits = jnp.mod(
        u_piv.astype(jnp.float32)
        + jnp.einsum("brw,bw->br", T, pbest,
                     precision=jax.lax.Precision.HIGHEST),
        2.0,
    ).astype(jnp.uint8)
    out = jnp.zeros((B, n), jnp.uint8)
    out = out.at[batch_idx, piv_cols].set(piv_bits)
    out = out.at[batch_idx, free].set(pbest.astype(jnp.uint8))
    return out
