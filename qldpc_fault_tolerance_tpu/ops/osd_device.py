"""Batched ordered-statistics decoding on TPU.

The host C++ OSD (_native/osd.cpp) is exact but sequential per shot — on a
small-core host it caps every BP+OSD pipeline at O(100) shots/s.  This
module runs the same algorithm for a whole batch on device:

  * One Gaussian elimination serves all shots: H's GF(2) rank is a property
    of the matrix, not the shot, so every per-shot array has static shape
    (rank r*, free count n-r*) — only the column *order* (by posterior
    reliability) differs per shot.
  * Rows are bit-packed into uint32 words; the elimination is a
    ``lax.while_loop`` over reliability-ordered columns with all-shots
    row-XOR updates (traffic O(steps * B * m * n/32) bytes), exiting as
    soon as every shot reaches full rank.
  * OSD-E reprocessing scores all 2^w candidate free-bit patterns with MXU
    matmuls ((T @ P) mod 2 and cost contractions), scanned in chunks so
    nothing of size (B, r*, 2^w) is materialized; only the winning
    pattern's solution is reconstructed.

Semantics mirror _native/osd.cpp exactly (same stable reliability sort,
first-available-row pivoting, strict-< candidate preference in pattern
order); decoders/osd.py's numpy oracle doubles as this kernel's test
oracle.  Costs are float32 on device (the C++ uses float64) — candidates
whose costs tie within float32 may legitimately differ; the tests compare
costs, not just patterns.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["OsdPlan", "build_osd_plan", "osd_decode_device"]


from .bp import _LruCache  # shared bounded memo (see ops/bp.py)

_pack_cache = _LruCache()


def _pack_h(h: np.ndarray):
    """(rank, device bit-packed rows) of H — p-independent, memoized so
    p-sweeps rebuilding BPOSD decoders per cell don't re-rank/re-upload."""
    from ..codes import gf2

    def make():
        m, n = h.shape
        words = (n + 31) // 32
        hp = np.pad(h, ((0, 0), (0, words * 32 - n)))
        packed = (
            hp.reshape(m, words, 32).astype(np.uint64)
            << np.arange(32, dtype=np.uint64)
        ).sum(axis=2).astype(np.uint32)
        return int(gf2.rank(h)), jax.device_put(packed)

    return _pack_cache.get((h.shape, h.tobytes()), make)


class OsdPlan:
    """Static per-H data for device OSD (hashable: used in jit cache keys)."""

    def __init__(self, h: np.ndarray, channel_cost: np.ndarray):
        h = (np.asarray(h) != 0).astype(np.uint8)
        self.m, self.n = h.shape
        self.words = (self.n + 31) // 32
        self.rank, self.packed = _pack_h(h)
        self.cost = jax.device_put(np.asarray(channel_cost, np.float32))
        self._key = (self.m, self.n, self.rank,
                     h.tobytes(), np.asarray(channel_cost).tobytes())

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, OsdPlan) and self._key == other._key


def build_osd_plan(h, channel_probs) -> OsdPlan:
    # single source of truth for the signed-cost convention (priors > 1/2
    # get negative flip costs) shared with the host path
    from ..decoders.osd import _channel_cost

    return OsdPlan(h, _channel_cost(channel_probs))


def _permute_and_pack(h01, perm):
    """Per-shot column-permuted bit-packed rows, **batch-last**: (W, m, B)
    uint32 with permuted column t at word t>>5, bit t&31.

    Batch-last mirrors the BP kernel's layout lesson: every elimination-loop
    tensor keeps the shot batch on the 128-lane minor axis (full vector
    utilization), and the loop's column extraction is a contiguous
    ``dynamic_slice`` on the leading word axis — no per-shot gathers."""
    B, n = perm.shape
    m = h01.shape[0]
    W = (n + 31) // 32
    cols = h01[:, perm]                                       # (m, B, n) u8
    pad = W * 32 - n
    if pad:
        cols = jnp.pad(cols, ((0, 0), (0, 0), (0, pad)))
    lanes = cols.reshape(m, B, W, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    packed = jnp.sum(lanes << shifts, axis=3, dtype=jnp.uint32)  # (m, B, W)
    return jnp.transpose(packed, (2, 0, 1))                   # (W, m, B)


def _eliminate(plan, perm, syndromes):
    """All-shots RREF over per-shot reliability-permuted columns.

    All loop state is batch-last.  Returns (u_piv (r*, B) reduced syndrome
    at pivot rows, pivot_rows (r*, B), pivot_cols_perm (r*, B) PERMUTED
    column ids, is_pivot_perm (n, B) bool, packed (W, m, B) reduced
    permuted rows).  Callers map permuted ids to original via ``perm``."""
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    h01 = _unpack_rows(plan.packed, n)
    rows_m = jnp.arange(m, dtype=jnp.int32)[:, None]          # (m, 1)
    slots = jnp.arange(r_star, dtype=jnp.int32)[:, None]      # (r*, 1)
    cols_n = jnp.arange(n, dtype=jnp.int32)[:, None]          # (n, 1)

    def cond(state):
        t, packed, synd, used, rank, pr, pc, ip = state
        return (t < n) & jnp.any(rank < r_star)

    def step(state):
        t, packed, synd, used, rank, pr, pc, ip = state
        # permuted column t lives at a *shot-independent* word/bit position
        word_t = (t >> 5).astype(jnp.int32)
        bit_t = (t & 31).astype(jnp.uint32)
        col_words = jax.lax.dynamic_slice(
            packed, (word_t, 0, 0), (1, m, B))[0]             # (m, B)
        bits = ((col_words >> bit_t) & 1).astype(bool)
        active = rank < r_star                                # (B,)
        avail = bits & ~used & active[None, :]
        has = avail.any(axis=0)                               # (B,)
        piv = jnp.argmax(avail, axis=0).astype(jnp.int32)     # first True
        # pivot row/syndrome via masked reduction instead of a per-shot
        # (lane-varying) gather: one fused pass over packed at full HBM
        # bandwidth, exact because exactly one row is selected per shot
        onehot = (rows_m == piv[None, :])                     # (m, B)
        prow = jnp.sum(
            jnp.where(onehot[None], packed, jnp.uint32(0)), axis=1,
            dtype=jnp.uint32,
        )                                                     # (W, B)
        ps = jnp.sum(jnp.where(onehot, synd, jnp.uint8(0)), axis=0,
                     dtype=jnp.uint8)                         # (B,)
        clear = bits & ~onehot & has[None, :]                 # (m, B)
        packed = packed ^ (clear[None].astype(jnp.uint32) * prow[:, None, :])
        synd = synd ^ (clear.astype(jnp.uint8) * ps[None, :])
        at_slot = (slots == rank[None, :]) & has[None, :]     # (r*, B)
        pr = jnp.where(at_slot, piv[None, :], pr)
        pc = jnp.where(at_slot, t, pc)
        ip = ip | ((cols_n == t) & has[None, :])              # (n, B)
        used = used | (onehot & has[None, :])
        rank = rank + has.astype(jnp.int32)
        return (t + 1, packed, synd, used, rank, pr, pc, ip)

    body = step

    state = (
        jnp.int32(0),
        _permute_and_pack(h01, perm),
        syndromes.astype(jnp.uint8).T,                        # (m, B)
        jnp.zeros((m, B), bool),
        jnp.zeros((B,), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((r_star, B), jnp.int32),
        jnp.zeros((n, B), bool),
    )
    _, packed, synd, used, rank, pr, pc, ip = jax.lax.while_loop(
        cond, body, state)
    u_piv = jnp.take_along_axis(synd, pr, axis=0)             # (r*, B)
    return u_piv, pr, pc, ip, packed


def _unpack_rows(packed, n):
    """(m, W) uint32 -> (m, n) uint8."""
    m, W = packed.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((packed[:, :, None] >> shifts) & 1).astype(jnp.uint8)
    return bits.reshape(m, W * 32)[:, :n]


# ---------------------------------------------------------------------------
# Pallas elimination (EXPERIMENTAL, opt-in via QLDPC_PALLAS_OSD=1): the same
# RREF loop with all state resident in VMEM, one kernel launch per batch
# tile, bit-exact vs the XLA path (integer ops throughout; validated by
# interpret-mode equality tests).  Status: measured op-bound under mosaic on
# v5e (slower than the XLA while_loop for hgp-sized codes) — retained as
# the starting point for future kernel tuning, not as the default path.
def _elim_kernel(packed_ref, synd_ref, out_packed_ref, out_synd_ref,
                 pr_ref, pc_ref, ip_ref, work_ref, used_ref, rank_ref,
                 *, W: int, m: int, n: int, r_star: int, bt: int):
    """One batch tile; the evolving matrix lives in the ``work_ref`` VMEM
    scratch (mosaic lowers dynamic ``pl.ds`` loads on refs, not on values,
    so the per-column word extraction reads the scratch)."""
    i32 = jnp.int32
    rows_m = jax.lax.broadcasted_iota(i32, (m, bt), 0)
    slots = jax.lax.broadcasted_iota(i32, (r_star, bt), 0)
    cols = jax.lax.broadcasted_iota(i32, (n, bt), 0)

    work_ref[:] = packed_ref[:]
    out_synd_ref[:] = synd_ref[:]
    used_ref[:] = jnp.zeros((m, bt), i32)
    rank_ref[:] = jnp.zeros((8, bt), i32)
    pr_ref[:] = jnp.zeros((r_star, bt), i32)
    pc_ref[:] = jnp.zeros((r_star, bt), i32)
    ip_ref[:] = jnp.zeros((n, bt), i32)

    # all loop state lives in refs — a large while-loop carry would be
    # copied every iteration; the carry is just the column counter
    def cond(t):
        return (t < n) & (jnp.min(rank_ref[0, :]) < r_star)

    def body(t):
        wt = t >> 5
        bit = t & 31
        rank = rank_ref[0, :]                                    # (bt,)
        used = used_ref[:]
        colw = work_ref[pl.ds(wt, 1)][0]                         # (m, bt)
        bits = jax.lax.shift_right_logical(colw, bit) & 1        # (m, bt)
        active = jnp.where(rank < r_star, 1, 0)                  # (bt,)
        avail = bits * (1 - used) * active[None, :]
        # first available row = min row index among avail (integer argmax
        # isn't lowered by mosaic; min-index reduction is)
        cand = jnp.where(avail == 1, rows_m, m)
        piv = jnp.min(cand, axis=0)                              # (bt,)
        has = jnp.where(piv < m, 1, 0)
        piv = jnp.where(piv < m, piv, 0)
        onehot = jnp.where(rows_m == piv[None, :], 1, 0)
        packed = work_ref[:]
        synd = out_synd_ref[:]
        prow = jnp.sum(onehot[None] * packed, axis=1)            # (W, bt)
        ps = jnp.sum(onehot * synd, axis=0)                      # (bt,)
        clear = bits * (1 - onehot) * has[None, :]
        work_ref[:] = packed ^ (clear[None] * prow[:, None, :])
        out_synd_ref[:] = synd ^ (clear * ps[None, :])
        at = jnp.where((slots == rank[None, :])
                       & (has[None, :] == 1), 1, 0)              # (r*, bt)
        pr_ref[:] = jnp.where(at == 1, piv[None, :], pr_ref[:])
        pc_ref[:] = jnp.where(at == 1, t, pc_ref[:])
        ip_ref[:] = ip_ref[:] | jnp.where(
            (cols == t) & (has[None, :] == 1), 1, 0)
        used_ref[:] = used | (onehot * has[None, :])
        rank_ref[:] = jnp.broadcast_to((rank + has)[None, :], (8, bt))
        return t + 1

    jax.lax.while_loop(cond, body, jnp.int32(0))
    out_packed_ref[:] = work_ref[:]


# tile state ~ (W*m + extras) * bt * 4 bytes must fit the scoped VMEM cap
_ELIM_VMEM_LIMIT = 100 * 1024 * 1024


def _elim_pallas_ok(W, m, n, r_star, bt):
    words = (2 * W * m + 2 * m + 2 * r_star + 2 * n + 8) * bt
    return words * 4 <= _ELIM_VMEM_LIMIT


def _eliminate_pallas(plan, perm, syndromes, bt: int = 128,
                      interpret: bool = False):
    """Drop-in for _eliminate with the loop in a Pallas kernel.

    Same returns (u_piv, pivot_rows, pivot_cols_perm, is_pivot_perm,
    packed), bit-identical to the XLA path (integer arithmetic throughout).
    """
    B = perm.shape[0]
    m, n, r_star = plan.m, plan.n, plan.rank
    W = (n + 31) // 32
    h01 = _unpack_rows(plan.packed, n)
    packed0 = _permute_and_pack(h01, perm).astype(jnp.int32)   # (W, m, B)
    synd0 = syndromes.astype(jnp.int32).T                      # (m, B)

    kernel = functools.partial(
        _elim_kernel, W=W, m=m, n=n, r_star=r_star, bt=bt)
    grid = (B // bt,)
    packed, synd, pr, pc, ip = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)),
            pl.BlockSpec((m, bt), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((W, m, bt), lambda t: (0, 0, t)),
            pl.BlockSpec((m, bt), lambda t: (0, t)),
            pl.BlockSpec((r_star, bt), lambda t: (0, t)),
            pl.BlockSpec((r_star, bt), lambda t: (0, t)),
            pl.BlockSpec((n, bt), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((W, m, B), jnp.int32),
            jax.ShapeDtypeStruct((m, B), jnp.int32),
            jax.ShapeDtypeStruct((r_star, B), jnp.int32),
            jax.ShapeDtypeStruct((r_star, B), jnp.int32),
            jax.ShapeDtypeStruct((n, B), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((W, m, bt), jnp.int32),
            pltpu.VMEM((m, bt), jnp.int32),
            pltpu.VMEM((8, bt), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_ELIM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(packed0, synd0)
    u_piv = jnp.take_along_axis(synd, pr, axis=0)              # (r*, B)
    return (u_piv, pr, pc, ip.astype(bool), packed.astype(jnp.uint32))


def osd_decode_device(plan: OsdPlan, syndromes, posterior_llrs,
                      osd_order: int = 10, pat_chunk: int = 256):
    """OSD-E decode a batch on device. Returns (B, n) uint8 errors.

    ``osd_order=0`` gives OSD-0.  Matches _native/osd.cpp semantics."""
    return osd_decode_values(
        (plan.n, plan.rank, int(osd_order), int(pat_chunk)),
        plan.packed, plan.cost, syndromes, posterior_llrs,
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def osd_decode_values(cfg, h_packed, cost, syndromes, posterior_llrs):
    """Value-based entry (composable inside the simulators' shared jitted
    pipelines): ``cfg`` = (n, rank, osd_order, pat_chunk) is static, the
    bit-packed rows and signed costs are traced arguments — a p-sweep
    changes only ``cost`` and reuses the executable."""
    n, r_star, osd_order, pat_chunk = cfg
    B = syndromes.shape[0]

    class _P:  # adapt values to the plan-shaped helpers below
        pass

    plan = _P()
    plan.m, plan.words = h_packed.shape
    plan.n, plan.rank = n, r_star
    plan.packed, plan.cost = h_packed, cost

    perm = jnp.argsort(posterior_llrs, axis=1, stable=True).astype(jnp.int32)
    W = (n + 31) // 32
    bt = 128
    # experimental opt-in: the Pallas elimination is bit-exact but measured
    # op-bound under mosaic (1.16s vs 0.59s XLA for B=2048 on hgp n625) —
    # kept for future tuning, off by default
    use_pallas = (
        os.environ.get("QLDPC_PALLAS_OSD", "0") == "1"
        and B % bt == 0
        and _elim_pallas_ok(W, plan.m, n, r_star, bt)
        and jax.default_backend() == "tpu"
    )
    if use_pallas:
        u_piv_t, piv_rows_t, piv_cols_perm_t, is_pivot_perm_t, packed = \
            _eliminate_pallas(plan, perm, syndromes, bt=bt)
    else:
        u_piv_t, piv_rows_t, piv_cols_perm_t, is_pivot_perm_t, packed = \
            _eliminate(plan, perm, syndromes)
    u_piv = u_piv_t.T                                         # (B, r*)
    # permuted -> original column ids
    piv_cols = jnp.take_along_axis(perm, piv_cols_perm_t.T, axis=1)

    cost_piv = plan.cost[piv_cols]                            # (B, r*)
    batch_idx = jnp.arange(B)[:, None]
    w = min(int(osd_order), n - r_star, 20)
    if w <= 0:
        return (
            jnp.zeros((B, n), jnp.uint8)
            .at[batch_idx, piv_cols].set(u_piv.astype(jnp.uint8))
        )

    # free columns in reliability order = non-pivot PERMUTED positions in
    # ascending order (positions are already reliability-sorted)
    free_perm = jnp.argsort(is_pivot_perm_t, axis=0, stable=True)[:w]
    free_perm = free_perm.astype(jnp.int32)                   # (w, B)
    free = jnp.take_along_axis(perm, free_perm.T, axis=1)     # (B, w) orig
    # T[b, i, k]: bit of reduced pivot row i at free (permuted) column k
    W = (n + 31) // 32
    rows = jnp.take_along_axis(
        packed, jnp.broadcast_to(piv_rows_t[None], (W, r_star, B)), axis=1
    )                                                         # (W, r*, B)
    fword = jnp.broadcast_to((free_perm >> 5)[:, None, :], (w, r_star, B))
    fbit = (free_perm & 31).astype(jnp.uint32)[:, None, :]    # (w, 1, B)
    T = ((jnp.take_along_axis(rows, fword, axis=0) >> fbit) & 1)
    T = jnp.transpose(T, (2, 1, 0)).astype(jnp.float32)       # (B, r*, w)

    cost_free = plan.cost[free]                               # (B, w)
    n_pat = 1 << w
    # powers of two: min(256, n_pat) always divides n_pat, so chunk starts
    # never clamp (a clamped dynamic_slice would mis-attribute pattern ids)
    pat_chunk = min(int(pat_chunk), n_pat)
    pats = jnp.arange(n_pat, dtype=jnp.int32)
    pmat = ((pats[None, :] >> jnp.arange(w)[:, None]) & 1).astype(
        jnp.float32)                                          # (w, n_pat)

    def score_chunk(carry, start):
        best_cost, best_pat = carry
        pchunk = jax.lax.dynamic_slice_in_dim(pmat, start, pat_chunk, axis=1)
        # pivot bits for every candidate: (u + T @ P) mod 2.  HIGHEST
        # precision: default TPU matmuls round operands to bf16, enough to
        # mis-rank near-tied candidates under non-uniform (DEM) priors
        hi = jax.lax.Precision.HIGHEST
        s = jnp.einsum("brw,wp->brp", T, pchunk, precision=hi)  # (B, r*, C)
        bits = jnp.mod(u_piv[:, :, None].astype(jnp.float32) + s, 2.0)
        c = (
            jnp.einsum("brp,br->bp", bits, cost_piv, precision=hi)
            + jnp.matmul(cost_free, pchunk, precision=hi)       # (B, C)
        )
        idx = jnp.argmin(c, axis=1)                           # first min
        cmin = jnp.take_along_axis(c, idx[:, None], axis=1)[:, 0]
        better = cmin < best_cost                             # strict <
        best_pat = jnp.where(better, start + idx.astype(jnp.int32), best_pat)
        best_cost = jnp.where(better, cmin, best_cost)
        return (best_cost, best_pat), None

    # pattern 0 (pure OSD-0) is the base candidate, like the C++
    base_cost = jnp.einsum("br,br->b", u_piv.astype(jnp.float32), cost_piv,
                           precision=jax.lax.Precision.HIGHEST)
    n_chunks = -(-n_pat // pat_chunk)
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * pat_chunk
    (best_cost, best_pat), _ = jax.lax.scan(
        score_chunk, (base_cost, jnp.zeros((B,), jnp.int32)), starts)

    # reconstruct only the winning pattern's solution
    pbest = ((best_pat[:, None] >> jnp.arange(w)[None, :]) & 1).astype(
        jnp.float32)                                          # (B, w)
    piv_bits = jnp.mod(
        u_piv.astype(jnp.float32)
        + jnp.einsum("brw,bw->br", T, pbest,
                     precision=jax.lax.Precision.HIGHEST),
        2.0,
    ).astype(jnp.uint8)
    out = jnp.zeros((B, n), jnp.uint8)
    out = out.at[batch_idx, piv_cols].set(piv_bits)
    out = out.at[batch_idx, free].set(pbest.astype(jnp.uint8))
    return out
