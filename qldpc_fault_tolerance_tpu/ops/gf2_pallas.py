"""Fused sample→syndrome→check kernels on bit-packed GF(2) planes.

The packed XLA layer (ops/gf2_packed) already cuts the sample+syndrome HBM
traffic ~8x, but still materializes the packed error planes between the
sampler dispatch and the syndrome dispatch, and re-reads them for the
residual checks after BP.  This module removes both hand-offs:

  * ``sample_syndrome`` draws the depolarizing errors from a COUNTER-BASED
    PRNG (Threefry-2x32 keyed on (shot, qubit) — no sampler state, any
    (shot, qubit) word is recomputable anywhere), computes both syndrome
    SpMVs in-register, and writes only packed planes.  On TPU this is ONE
    Pallas dispatch whose only HBM writes are the packed errors + syndromes.
  * ``residual_check_stats`` REGENERATES the error bits from the same
    counters instead of reading them back, XORs the BP corrections in, and
    reduces the stabilizer/logical checks to two int32 scalars per block —
    so with the Pallas path the (B, n) error planes never touch HBM at all:
    sampling → syndrome SpMV → residual stabilizer/logical checks are fused
    across exactly two dispatches with BP in between, and the inter-stage
    traffic is the syndromes and corrections only (~(mx+mz+2n)/8 bytes per
    shot).

Every kernel has an XLA twin built from the SAME ``threefry2x32`` and the
gf2_packed ops, bit-exact word for word with the kernel (asserted in
interpret mode by tests/test_gf2_pallas.py) — the twin is the fallback on
CPU / when the batch doesn't tile.  The counter-PRNG stream is deliberately
its OWN stream: it does not reproduce ``jax.random.uniform`` draws, so the
fused path is opt-in (``CodeSimulator_DataError(fused_sampler=True)``) and
the default packed path stays seed-for-seed identical to the dense one.

Layout matches gf2_packed: 32 shots per uint32 lane word, shot ``32*w + j``
in bit ``j`` (LSB-first).  Kernel arithmetic stays in int32 (mosaic-friendly
outputs); words bitcast to uint32 at the boundary.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_compat import CompilerParams
from .bp import _LruCache
from .gf2_packed import LANE, num_words, pack_shots, \
    packed_parity_apply, packed_residual_stats
from .linalg import ParityOp

__all__ = [
    "threefry2x32",
    "counter_draws",
    "depolarizing_cuts",
    "FusedSpec",
    "build_fused_spec",
    "sample_syndrome",
    "residual_check_stats",
    "pallas_feasible",
    "estimate_vmem_bytes",
    "vmem_feasible",
    "FusedDecodeSpec",
    "build_fused_decode_spec",
    "fused_decode_stats",
    "fused_decode_block_w",
    "estimate_fused_decode_bytes",
    "fused_decode_feasible",
]


# ---------------------------------------------------------------------------
# Counter-based PRNG: Threefry-2x32, 20 rounds (the jax default generator's
# block cipher).  Pure jnp bit ops, so the SAME function body runs inside the
# Pallas kernel and in the XLA twin — bit-exactness between the two paths is
# by construction, not by test luck (the test still asserts it).
_ROTATIONS = ((13, 15, 26, 6), (17, 29, 16, 24))
_PARITY_CONST = 0x1BD11BDA


def _rotl(x, r: int):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def threefry2x32(k0, k1, c0, c1):
    """Threefry-2x32(20 rounds): (key words, counter words) -> 2 uint32.

    All inputs broadcast; outputs have the broadcast shape.  Matches the
    reference cipher (Salmon et al. 2011) round for round.
    """
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    ks = (k0, k1, k0 ^ k1 ^ jnp.uint32(_PARITY_CONST))
    x0 = jnp.asarray(c0, jnp.uint32) + ks[0]
    x1 = jnp.asarray(c1, jnp.uint32) + ks[1]
    for block in range(5):
        for r in _ROTATIONS[block % 2]:
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        x0 = x0 + ks[(block + 1) % 3]
        x1 = x1 + ks[(block + 2) % 3] + jnp.uint32(block + 1)
    return x0, x1


def counter_draws(k0, k1, batch_size: int, n: int) -> jnp.ndarray:
    """(batch_size, n) uint32 draws, word (b, v) = Threefry(key, (b, v)).x0.

    The XLA twin of the in-kernel generator: same counters, same words."""
    c0 = jnp.arange(batch_size, dtype=jnp.uint32)[:, None]
    c1 = jnp.arange(n, dtype=jnp.uint32)[None, :]
    x0, _ = threefry2x32(k0, k1, c0, c1)
    return x0


def depolarizing_cuts(pauli_error_probs) -> np.ndarray:
    """[pz, pz+px, pz+px+py] as uint32 thresholds on a uniform 32-bit draw.

    Binning order matches noise.depolarizing_xz / the reference
    (src/Simulators.py:102-113): u < pz -> Z, next px -> X, next py -> Y."""
    px, py, pz = (float(p) for p in pauli_error_probs)
    edges = np.cumsum([pz, px, py])
    if edges[-1] > 1.0 + 1e-9:
        raise ValueError(f"pauli probs sum to {edges[-1]} > 1")
    return np.minimum(np.round(edges * 4294967296.0), 4294967295.0).astype(
        np.uint32)


def _errors_from_draws(r, cuts):
    """uint32 draws + cuts -> (error_x, error_z) int32 {0,1} planes."""
    cz, czx, czxy = (cuts[i] for i in range(3))
    is_z = r < cz
    is_x = (r >= cz) & (r < czx)
    is_y = (r >= czx) & (r < czxy)
    return (is_x | is_y).astype(jnp.int32), (is_z | is_y).astype(jnp.int32)


# ---------------------------------------------------------------------------
class FusedSpec(NamedTuple):
    """Per-code device data for the fused kernels (a plain array pytree, so
    it rides through jit as a value like the simulators' ``state``).

    Dense f32 transposes feed the in-kernel MXU products; the ParityOp
    adjacencies feed the XLA twin's packed XOR gathers."""

    cuts: jnp.ndarray       # (3,) uint32 depolarizing thresholds
    hx_t: jnp.ndarray       # (n, mx) f32 — syndrome_z = e_z @ hx_t
    hz_t: jnp.ndarray       # (n, mz) f32
    lx_t: jnp.ndarray       # (n, k) f32 — z-logical check
    lz_t: jnp.ndarray       # (n, k) f32
    hx_nbr: jnp.ndarray     # ParityOp(hx) adjacency (twin path)
    hx_mask: jnp.ndarray
    hz_nbr: jnp.ndarray
    hz_mask: jnp.ndarray


_spec_cache = _LruCache()


def build_fused_spec(hx, hz, lx, lz, pauli_error_probs) -> FusedSpec:
    hx = (np.asarray(hx) != 0).astype(np.uint8)
    hz = (np.asarray(hz) != 0).astype(np.uint8)
    lx = (np.asarray(lx) != 0).astype(np.uint8)
    lz = (np.asarray(lz) != 0).astype(np.uint8)
    cuts = depolarizing_cuts(pauli_error_probs)
    key = (hx.shape, hz.shape, hx.tobytes(), hz.tobytes(), lx.tobytes(),
           lz.tobytes(), cuts.tobytes())

    def make():
        hxp, hzp = ParityOp(hx), ParityOp(hz)
        return FusedSpec(
            cuts=jnp.asarray(cuts),
            hx_t=jnp.asarray(hx.T, jnp.float32),
            hz_t=jnp.asarray(hz.T, jnp.float32),
            lx_t=jnp.asarray(lx.T, jnp.float32),
            lz_t=jnp.asarray(lz.T, jnp.float32),
            hx_nbr=hxp.nbr, hx_mask=hxp.mask,
            hz_nbr=hzp.nbr, hz_mask=hzp.mask,
        )

    return _spec_cache.get(key, make)


def _key_words(key):
    kd = jax.random.key_data(key) if hasattr(jax.random, "key_data") else key
    kd = jnp.asarray(kd, jnp.uint32).reshape(-1)
    return kd[0], kd[1]


# ---------------------------------------------------------------------------
# In-kernel building blocks (shared by both kernels; plain jnp so the same
# code runs under interpret, mosaic, and in the XLA twins' tests)
def _block_draws(k0, k1, base_shot, block_w: int, n: int):
    """(block_w, LANE, n) uint32 draws for shots [base, base + 32*block_w)."""
    shape = (block_w, LANE, n)
    w_i = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    j_i = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    v_i = jax.lax.broadcasted_iota(jnp.uint32, shape, 2)
    shot = jnp.asarray(base_shot, jnp.uint32) + w_i * jnp.uint32(LANE) + j_i
    x0, _ = threefry2x32(k0, k1, shot, v_i)
    return x0


def _pack_lane_axis(bits3):
    """(W, LANE, d) int32 {0,1} -> (W, d) int32 words (bit j = lane j)."""
    shifts = jax.lax.broadcasted_iota(jnp.int32, bits3.shape, 1)
    return jax.lax.reduce(
        jax.lax.shift_left(bits3, shifts), np.int32(0),
        jax.lax.bitwise_or, (1,))


def _unpack_lane_axis(words, block_w: int, d: int):
    """(W, d) int32 words -> (W, LANE, d) int32 {0,1}."""
    shape = (block_w, LANE, d)
    shifts = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    full = jnp.broadcast_to(words[:, None, :], shape)
    return jax.lax.shift_right_logical(full, shifts) & jnp.int32(1)


def _mod2(x):
    return x - 2.0 * jnp.floor(x * 0.5)


def _gf2_dense(bits_f32, h_t_f32):
    """Exact GF(2) product on the MXU: f32 accumulate, mod 2 (row sums are
    far below 2**24 for any code here)."""
    return _mod2(jnp.dot(bits_f32, h_t_f32, preferred_element_type=jnp.float32))


# ---------------------------------------------------------------------------
# Kernel 1: counter PRNG -> packed errors + packed syndromes, one dispatch.
# The ``emit_errors=False`` variant writes ONLY the packed syndromes — the
# error planes live and die in VMEM (kernel 2 regenerates them from the same
# counters), so the sampler's HBM cost drops to (mx + mz)/8 bytes per shot.
def _sample_block(par_ref, block_w: int, n: int):
    k0 = jax.lax.bitcast_convert_type(par_ref[0, 0], jnp.uint32)
    k1 = jax.lax.bitcast_convert_type(par_ref[0, 1], jnp.uint32)
    cuts = jax.lax.bitcast_convert_type(par_ref[0, 2:5], jnp.uint32)
    base = pl.program_id(0) * (block_w * LANE)
    r = _block_draws(k0, k1, base, block_w, n)
    return _errors_from_draws(r, cuts)


def _sample_syndrome_kernel(par_ref, hx_t_ref, hz_t_ref, *out_refs,
                            block_w: int, n: int, mx: int, mz: int,
                            emit_errors: bool):
    ex, ez = _sample_block(par_ref, block_w, n)
    if emit_errors:
        exp_ref, ezp_ref, sxp_ref, szp_ref = out_refs
        exp_ref[:] = _pack_lane_axis(ex)
        ezp_ref[:] = _pack_lane_axis(ez)
    else:
        sxp_ref, szp_ref = out_refs
    bt = block_w * LANE
    sz = _gf2_dense(ez.reshape(bt, n).astype(jnp.float32), hx_t_ref[:])
    sx = _gf2_dense(ex.reshape(bt, n).astype(jnp.float32), hz_t_ref[:])
    szp_ref[:] = _pack_lane_axis(sz.astype(jnp.int32).reshape(block_w, LANE, mx))
    sxp_ref[:] = _pack_lane_axis(sx.astype(jnp.int32).reshape(block_w, LANE, mz))


def _pack_params(spec: FusedSpec, key):
    k0, k1 = _key_words(key)
    return jax.lax.bitcast_convert_type(
        jnp.stack([k0, k1, spec.cuts[0], spec.cuts[1], spec.cuts[2],
                   jnp.uint32(0), jnp.uint32(0), jnp.uint32(0)]),
        jnp.int32).reshape(1, 8)


@functools.partial(jax.jit, static_argnames=("batch_size", "block_w",
                                             "interpret", "emit_errors"))
def _sample_syndrome_pallas(spec: FusedSpec, key, batch_size: int,
                            block_w: int, interpret: bool,
                            emit_errors: bool = True):
    n, mx = spec.hx_t.shape
    mz = spec.hz_t.shape[1]
    w = num_words(batch_size)
    assert batch_size % (block_w * LANE) == 0, (batch_size, block_w)
    kernel = functools.partial(_sample_syndrome_kernel, block_w=block_w,
                               n=n, mx=mx, mz=mz, emit_errors=emit_errors)
    grid = (w // block_w,)
    err_specs = [pl.BlockSpec((block_w, n), lambda t: (t, 0)),
                 pl.BlockSpec((block_w, n), lambda t: (t, 0))]
    err_shapes = [jax.ShapeDtypeStruct((w, n), jnp.int32),
                  jax.ShapeDtypeStruct((w, n), jnp.int32)]
    out = pl.pallas_call(
        kernel,
        name=(f"gf2_sample_synd_{n}x{mx}x{mz}_w{block_w}"
              f"{'_e' if emit_errors else ''}"),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 8), lambda t: (0, 0)),
            pl.BlockSpec((n, mx), lambda t: (0, 0)),
            pl.BlockSpec((n, mz), lambda t: (0, 0)),
        ],
        out_specs=(err_specs if emit_errors else []) + [
            pl.BlockSpec((block_w, mz), lambda t: (t, 0)),
            pl.BlockSpec((block_w, mx), lambda t: (t, 0)),
        ],
        out_shape=(err_shapes if emit_errors else []) + [
            jax.ShapeDtypeStruct((w, mz), jnp.int32),
            jax.ShapeDtypeStruct((w, mx), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(_pack_params(spec, key), spec.hx_t, spec.hz_t)
    u = functools.partial(jax.lax.bitcast_convert_type,
                          new_dtype=jnp.uint32)
    return tuple(u(o) for o in out)


@functools.partial(jax.jit, static_argnames=("batch_size", "emit_errors"))
def _sample_syndrome_xla(spec: FusedSpec, key, batch_size: int,
                         emit_errors: bool = True):
    n = spec.hx_t.shape[0]
    k0, k1 = _key_words(key)
    r = counter_draws(k0, k1, batch_size, n)
    ex, ez = _errors_from_draws(r, spec.cuts)
    exp = pack_shots(ex.astype(jnp.uint8))
    ezp = pack_shots(ez.astype(jnp.uint8))
    szp = packed_parity_apply(spec.hx_nbr, spec.hx_mask, ezp)
    sxp = packed_parity_apply(spec.hz_nbr, spec.hz_mask, exp)
    if emit_errors:
        return exp, ezp, sxp, szp
    return sxp, szp


# ---------------------------------------------------------------------------
# Kernel 2: regenerate errors from the same counters, apply corrections,
# reduce residual stabilizer/logical checks to per-block scalars
def _residual_check_kernel(par_ref, corx_ref, corz_ref,
                           hx_t_ref, hz_t_ref, lx_t_ref, lz_t_ref,
                           cnt_ref, minw_ref,
                           *, block_w: int, n: int, eval_code: int):
    k0 = jax.lax.bitcast_convert_type(par_ref[0, 0], jnp.uint32)
    k1 = jax.lax.bitcast_convert_type(par_ref[0, 1], jnp.uint32)
    cuts = jax.lax.bitcast_convert_type(par_ref[0, 2:5], jnp.uint32)
    base = pl.program_id(0) * (block_w * LANE)
    r = _block_draws(k0, k1, base, block_w, n)
    ex, ez = _errors_from_draws(r, cuts)
    res_x = ex ^ _unpack_lane_axis(corx_ref[:], block_w, n)
    res_z = ez ^ _unpack_lane_axis(corz_ref[:], block_w, n)
    bt = block_w * LANE
    rx = res_x.reshape(bt, n).astype(jnp.float32)
    rz = res_z.reshape(bt, n).astype(jnp.float32)
    x_stab = jnp.max(_gf2_dense(rx, hz_t_ref[:]), axis=1)       # (bt,)
    x_log = jnp.max(_gf2_dense(rx, lz_t_ref[:]), axis=1)
    z_stab = jnp.max(_gf2_dense(rz, hx_t_ref[:]), axis=1)
    z_log = jnp.max(_gf2_dense(rz, lx_t_ref[:]), axis=1)
    x_fail = jnp.maximum(x_stab, x_log)
    z_fail = jnp.maximum(z_stab, z_log)
    if eval_code == 0:
        fail = x_fail
    elif eval_code == 1:
        fail = z_fail
    else:
        fail = jnp.maximum(x_fail, z_fail)
    cnt_ref[0, 0] = jnp.sum(fail, dtype=jnp.float32).astype(jnp.int32)
    big = jnp.float32(n)
    wx = jnp.where(x_log > 0, jnp.sum(rx, axis=1), big)
    wz = jnp.where(z_log > 0, jnp.sum(rz, axis=1), big)
    minw_ref[0, 0] = jnp.minimum(jnp.min(wx), jnp.min(wz)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("batch_size", "eval_type",
                                             "block_w", "interpret"))
def _residual_check_pallas(spec: FusedSpec, key, batch_size: int,
                           corx_p, corz_p, eval_type: str,
                           block_w: int, interpret: bool):
    n = spec.hx_t.shape[0]
    w = num_words(batch_size)
    assert batch_size % (block_w * LANE) == 0, (batch_size, block_w)
    kernel = functools.partial(
        _residual_check_kernel, block_w=block_w, n=n,
        eval_code={"X": 0, "Z": 1}.get(eval_type, 2))
    grid = (w // block_w,)
    i32 = functools.partial(jax.lax.bitcast_convert_type,
                            new_dtype=jnp.int32)
    cnt, minw = pl.pallas_call(
        kernel,
        name=f"gf2_residual_check_{n}_w{block_w}",
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 8), lambda t: (0, 0)),
            pl.BlockSpec((block_w, n), lambda t: (t, 0)),
            pl.BlockSpec((block_w, n), lambda t: (t, 0)),
            pl.BlockSpec(spec.hx_t.shape, lambda t: (0, 0)),
            pl.BlockSpec(spec.hz_t.shape, lambda t: (0, 0)),
            pl.BlockSpec(spec.lx_t.shape, lambda t: (0, 0)),
            pl.BlockSpec(spec.lz_t.shape, lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda t: (t, 0)),
            pl.BlockSpec((1, 1), lambda t: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((grid[0], 1), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(_pack_params(spec, key), i32(corx_p), i32(corz_p), spec.hx_t,
      spec.hz_t, spec.lx_t, spec.lz_t)
    return cnt.sum(dtype=jnp.int32), minw.min()


@functools.partial(jax.jit, static_argnames=("batch_size", "eval_type"))
def _residual_check_xla(spec: FusedSpec, key, batch_size: int,
                        corx_p, corz_p, eval_type: str):
    n = spec.hx_t.shape[0]
    k0, k1 = _key_words(key)
    r = counter_draws(k0, k1, batch_size, n)
    ex, ez = _errors_from_draws(r, spec.cuts)
    res_x = pack_shots(ex.astype(jnp.uint8)) ^ corx_p
    res_z = pack_shots(ez.astype(jnp.uint8)) ^ corz_p
    return packed_residual_stats(
        res_x, res_z, (spec.hz_nbr, spec.hz_mask),
        (spec.hx_nbr, spec.hx_mask), spec.lz_t != 0, spec.lx_t != 0,
        eval_type, batch_size, n)


# ---------------------------------------------------------------------------
# Public dispatchers: Pallas on TPU when the batch tiles, XLA twin otherwise
_DEFAULT_BLOCK_W = 8  # 256 shots per kernel block

# Degradation override (utils.resilience ladder): when the fused Pallas
# kernels repeatedly fault on a worker, the engines flip this to route every
# "auto" dispatch through the bit-exact XLA twins.  The flip takes effect on
# the next trace — the retry path's reset_device_state() clears the jit
# caches that baked in the old branch.
FORCE_XLA_TWIN = False


def pallas_feasible(batch_size: int, block_w: int = _DEFAULT_BLOCK_W) -> bool:
    return batch_size % (block_w * LANE) == 0


# scoped-VMEM cap the kernels compile against (compiler_params above)
_KERNEL_VMEM_LIMIT = 64 * 1024 * 1024


def estimate_vmem_bytes(n: int, mx: int, mz: int,
                        block_w: int = _DEFAULT_BLOCK_W, *,
                        kernel: str = "gf2_sample_synd",
                        emit_errors: bool = True) -> float:
    """Per-block VMEM working-set estimate for the fused kernels.

    Naive plane sum of everything resident in one grid step — the draw
    block, both error planes, the f32 MXU operand/outputs, the packed
    writes, and the dense transposes — scaled by the kernel's calibrated
    measured/estimated ratio from calibration/vmem_table.json
    (utils.profiling; the conservative 2x default stands in until a TPU
    probe records the real factor — the same class of mosaic-temporary
    undercount measured at ~1.8x on the BP head)."""
    from ..utils import profiling

    bt = block_w * LANE
    draws = bt * n * 4                    # (block_w, LANE, n) uint32
    errs = 2 * bt * n * 4                 # ex, ez int32 planes
    mxu_in = bt * n * 4                   # f32 reshape feeding the MXU
    mxu_out = bt * (mx + mz) * 4          # both syndrome products
    mats = n * (mx + mz) * 4              # resident hx_t, hz_t
    packed = block_w * (mx + mz) * 4      # packed syndrome writes
    if kernel == "gf2_residual":
        mats += 2 * n * 8 * 4             # lx_t, lz_t (k <= ~8 logicals)
        packed += 2 * block_w * n * 4     # correction planes in
    elif emit_errors:
        packed += 2 * block_w * n * 4     # packed error writes
    analytic = draws + errs + mxu_in + mxu_out + mats + packed
    return analytic * profiling.calibration_ratio(kernel, 2.0)


def vmem_feasible(spec: FusedSpec, block_w: int = _DEFAULT_BLOCK_W, *,
                  kernel: str = "gf2_sample_synd",
                  emit_errors: bool = True) -> bool:
    """True when the estimated (calibrated) per-block working set fits the
    kernel's scoped-VMEM cap — the gate half the round-5 README frontier
    asked for: infeasible shapes route to the bit-exact XLA twin instead
    of failing at compile time."""
    n, mx = spec.hx_t.shape
    mz = spec.hz_t.shape[1]
    return estimate_vmem_bytes(n, mx, mz, block_w, kernel=kernel,
                               emit_errors=emit_errors) <= _KERNEL_VMEM_LIMIT


def _use_pallas(batch_size: int, backend, spec: FusedSpec = None,
                block_w: int = _DEFAULT_BLOCK_W, *,
                kernel: str = "gf2_sample_synd",
                emit_errors: bool = True) -> bool:
    if FORCE_XLA_TWIN and backend != "pallas":
        return False
    if backend in ("xla", "cpu"):
        return False
    if backend == "pallas":
        return True
    try:
        if not (jax.default_backend() == "tpu"
                and pallas_feasible(batch_size, block_w)):
            return False
        # calibrated VMEM gate: shapes whose working set busts the scoped
        # cap fall back to the XLA twin (bit-exact) instead of OOMing the
        # mosaic compiler; backend="pallas" above stays an explicit
        # override for probe harnesses
        return spec is None or vmem_feasible(spec, block_w, kernel=kernel,
                                             emit_errors=emit_errors)
    except Exception:
        return False


def sample_syndrome(spec: FusedSpec, key, batch_size: int, *,
                    backend: str = "auto", block_w: int = _DEFAULT_BLOCK_W,
                    interpret: bool = False, emit_errors: bool = True):
    """Counter-PRNG depolarizing sample + both syndrome SpMVs, fused.

    Returns packed uint32 (ex_p, ez_p, sx_p, sz_p), or just (sx_p, sz_p)
    with ``emit_errors=False`` (the fully-fused stats pipeline — kernel 2
    regenerates the errors, so they never reach HBM).  The Pallas path and
    the XLA twin produce identical words."""
    if _use_pallas(batch_size, backend, spec, block_w,
                   emit_errors=emit_errors):
        return _sample_syndrome_pallas(spec, key, batch_size, block_w,
                                       interpret, emit_errors)
    return _sample_syndrome_xla(spec, key, batch_size, emit_errors)


def residual_check_stats(spec: FusedSpec, key, batch_size: int,
                         corx_p, corz_p, eval_type: str = "Total", *,
                         backend: str = "auto",
                         block_w: int = _DEFAULT_BLOCK_W,
                         interpret: bool = False):
    """Residual stabilizer/logical checks with in-kernel error regeneration.

    ``key`` must be the SAME key passed to ``sample_syndrome`` for this
    batch (the counters regenerate that exact error).  Returns int32 device
    scalars (failure count, min logical residual weight)."""
    if _use_pallas(batch_size, backend, spec, block_w,
                   kernel="gf2_residual"):
        return _residual_check_pallas(spec, key, batch_size, corx_p, corz_p,
                                      eval_type, block_w, interpret)
    return _residual_check_xla(spec, key, batch_size, corx_p, corz_p,
                               eval_type)


# ===========================================================================
# Fused v2: sample -> syndrome -> BP -> residual check, ONE Pallas program
# per megabatch tile.  The v1 fused path (above) still round-trips the
# packed syndromes and BP corrections through HBM between its two kernels
# and the XLA BP program; here the whole per-shot pipeline lives and dies
# in VMEM — HBM traffic per shot drops to the per-tile stats scalars plus
# (optional) 8 bytes of convergence/iteration telemetry.  BP runs the v2
# sparse-incidence loop (ops/bp_pallas) at full depth with per-tile early
# exit; ``quantize="int8"`` composes.  The XLA twin chains the existing
# twins (counter draws -> packed SpMV -> v2 BP twin -> packed residual
# stats) and is bit-exact with the kernel by shared bodies + exact GF(2).
# ===========================================================================
from .bp_pallas import (  # noqa: E402  (acyclic: bp_pallas imports only bp)
    _run_minsum_tile,
)


class FusedDecodeSpec(NamedTuple):
    """Per-(code, channel, decoder-priors) device data for the fused v2
    pipeline: the v1 FusedSpec plus both sectors' sparse BP incidence and
    channel-LLR priors.  A plain array pytree (rides through jit as a
    value; all static dims derive from shapes)."""

    base: FusedSpec
    zg_idx: jnp.ndarray     # (rw_z, mx) int32 — graph of hx (decodes synd_z)
    zg_mask: jnp.ndarray    # (rw_z, mx) f32
    xg_idx: jnp.ndarray     # (rw_x, mz) int32 — graph of hz (decodes synd_x)
    xg_mask: jnp.ndarray    # (rw_x, mz) f32
    llr_z: jnp.ndarray      # (n, 1) f32
    llr_x: jnp.ndarray      # (n, 1) f32

    @property
    def n(self) -> int:
        return self.base.hx_t.shape[0]

    @property
    def mx(self) -> int:
        return self.base.hx_t.shape[1]

    @property
    def mz(self) -> int:
        return self.base.hz_t.shape[1]


_decode_spec_cache = _LruCache()


def build_fused_decode_spec(hx, hz, lx, lz, pauli_error_probs,
                            llr_x, llr_z) -> FusedDecodeSpec:
    """Build (memoized) the fused-decode spec.  ``llr_x``/``llr_z`` are the
    decoders' channel-LLR priors ((n,) f32 — ``BPDecoder.llr0``); the BP
    incidence comes from the per-H Tanner memos (ops/bp)."""
    from .bp import build_tanner_graph_host

    hx = (np.asarray(hx) != 0).astype(np.uint8)
    hz = (np.asarray(hz) != 0).astype(np.uint8)
    llr_x = np.asarray(llr_x, np.float32).reshape(-1)
    llr_z = np.asarray(llr_z, np.float32).reshape(-1)
    base = build_fused_spec(hx, hz, lx, lz, pauli_error_probs)
    key = ("v2", hx.shape, hz.shape, hx.tobytes(), hz.tobytes(),
           np.asarray(base.cuts).tobytes(), llr_x.tobytes(),
           llr_z.tobytes())

    def make():
        gz = build_tanner_graph_host(hx)
        gx = build_tanner_graph_host(hz)
        return FusedDecodeSpec(
            base=base,
            zg_idx=jnp.asarray(np.ascontiguousarray(
                np.asarray(gz.chk_nbr).T.astype(np.int32))),
            zg_mask=jnp.asarray(np.ascontiguousarray(
                np.asarray(gz.chk_mask).T.astype(np.float32))),
            xg_idx=jnp.asarray(np.ascontiguousarray(
                np.asarray(gx.chk_nbr).T.astype(np.int32))),
            xg_mask=jnp.asarray(np.ascontiguousarray(
                np.asarray(gx.chk_mask).T.astype(np.float32))),
            llr_z=jnp.asarray(llr_z).reshape(-1, 1),
            llr_x=jnp.asarray(llr_x).reshape(-1, 1),
        )

    return _decode_spec_cache.get(key, make)


def _fused_decode_kernel(par_ref, hx_t_ref, hz_t_ref, lx_t_ref, lz_t_ref,
                         zg_idx_ref, zg_mask_ref, xg_idx_ref, xg_mask_ref,
                         llrz_ref, llrx_ref,
                         cnt_ref, minw_ref, convz_ref, iterz_ref,
                         convx_ref, iterx_ref,
                         *, block_w: int, n: int, mx: int, mz: int,
                         rwz: int, rwx: int, max_iter_z: int,
                         max_iter_x: int, scale: float, quantize,
                         eval_code: int):
    """One megabatch tile, whole pipeline in VMEM: counter-PRNG sample,
    both syndrome SpMVs, both sectors' full BP decodes, residual
    stabilizer/logical checks — only the per-tile stats (and the 8-byte
    convergence/iteration planes the telemetry vector folds) reach HBM."""
    f32 = jnp.float32
    ex, ez = _sample_block(par_ref, block_w, n)
    bt = block_w * LANE
    ex2 = ex.reshape(bt, n)
    ez2 = ez.reshape(bt, n)
    synd_z = _gf2_dense(ez2.astype(f32), hx_t_ref[:])           # (bt, mx)
    synd_x = _gf2_dense(ex2.astype(f32), hz_t_ref[:])           # (bt, mz)

    def decode(idx_ref, mask_ref, synd, llr0, rw, max_iter):
        synd_sign = (1.0 - 2.0 * synd).T                        # (m, bt)
        err, done, _llr, iters = _run_minsum_tile(
            [idx_ref[s] for s in range(rw)],
            [mask_ref[s] for s in range(rw)],
            synd_sign, llr0.astype(f32), rw=rw, n=n,
            head_iters=max_iter, scale=scale, early_stop=True,
            quantize=quantize)
        return err.T.astype(jnp.int32), done, iters             # (bt, n)

    cor_z, done_z, iters_z = decode(zg_idx_ref, zg_mask_ref, synd_z,
                                    llrz_ref[:], rwz, max_iter_z)
    cor_x, done_x, iters_x = decode(xg_idx_ref, xg_mask_ref, synd_x,
                                    llrx_ref[:], rwx, max_iter_x)

    res_x = (ex2 ^ cor_x).astype(f32)
    res_z = (ez2 ^ cor_z).astype(f32)
    x_stab = jnp.max(_gf2_dense(res_x, hz_t_ref[:]), axis=1)    # (bt,)
    x_log = jnp.max(_gf2_dense(res_x, lz_t_ref[:]), axis=1)
    z_stab = jnp.max(_gf2_dense(res_z, hx_t_ref[:]), axis=1)
    z_log = jnp.max(_gf2_dense(res_z, lx_t_ref[:]), axis=1)
    x_fail = jnp.maximum(x_stab, x_log)
    z_fail = jnp.maximum(z_stab, z_log)
    if eval_code == 0:
        fail = x_fail
    elif eval_code == 1:
        fail = z_fail
    else:
        fail = jnp.maximum(x_fail, z_fail)
    cnt_ref[0, 0] = jnp.sum(fail, dtype=f32).astype(jnp.int32)
    big = f32(n)
    wx = jnp.where(x_log > 0, jnp.sum(res_x, axis=1), big)
    wz = jnp.where(z_log > 0, jnp.sum(res_z, axis=1), big)
    minw_ref[0, 0] = jnp.minimum(jnp.min(wx), jnp.min(wz)).astype(jnp.int32)
    convz_ref[:] = done_z.astype(jnp.int32)
    iterz_ref[:] = iters_z
    convx_ref[:] = done_x.astype(jnp.int32)
    iterx_ref[:] = iters_x


def _decode_statics(spec: FusedDecodeSpec):
    return dict(n=spec.n, mx=spec.mx, mz=spec.mz,
                rwz=spec.zg_idx.shape[0], rwx=spec.xg_idx.shape[0])


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "eval_type", "max_iter_z", "max_iter_x", "scale",
    "quantize", "block_w", "interpret"))
def _fused_decode_pallas(spec: FusedDecodeSpec, key, batch_size: int,
                         eval_type: str, max_iter_z: int, max_iter_x: int,
                         scale: float, quantize, block_w: int,
                         interpret: bool):
    d = _decode_statics(spec)
    n, mx, mz = d["n"], d["mx"], d["mz"]
    rwz, rwx = d["rwz"], d["rwx"]
    assert batch_size % (block_w * LANE) == 0, (batch_size, block_w)
    bt = block_w * LANE
    grid = (batch_size // bt,)
    kernel = functools.partial(
        _fused_decode_kernel, block_w=block_w, n=n, mx=mx, mz=mz,
        rwz=rwz, rwx=rwx, max_iter_z=max_iter_z, max_iter_x=max_iter_x,
        scale=scale, quantize=quantize,
        eval_code={"X": 0, "Z": 1}.get(eval_type, 2))
    kname = (f"gf2_fused_decode_{n}x{mx}x{mz}_i{max_iter_z}_w{block_w}"
             f"{'_q8' if quantize else ''}")
    cnt, minw, convz, iterz, convx, iterx = pl.pallas_call(
        kernel,
        name=kname,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 8), lambda t: (0, 0)),
            pl.BlockSpec((n, mx), lambda t: (0, 0)),
            pl.BlockSpec((n, mz), lambda t: (0, 0)),
            pl.BlockSpec(spec.base.lx_t.shape, lambda t: (0, 0)),
            pl.BlockSpec(spec.base.lz_t.shape, lambda t: (0, 0)),
            pl.BlockSpec((rwz, mx), lambda t: (0, 0)),
            pl.BlockSpec((rwz, mx), lambda t: (0, 0)),
            pl.BlockSpec((rwx, mz), lambda t: (0, 0)),
            pl.BlockSpec((rwx, mz), lambda t: (0, 0)),
            pl.BlockSpec((n, 1), lambda t: (0, 0)),
            pl.BlockSpec((n, 1), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda t: (t, 0)),
            pl.BlockSpec((1, 1), lambda t: (t, 0)),
            pl.BlockSpec((1, bt), lambda t: (0, t)),
            pl.BlockSpec((1, bt), lambda t: (0, t)),
            pl.BlockSpec((1, bt), lambda t: (0, t)),
            pl.BlockSpec((1, bt), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((grid[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
        ],
        compiler_params=CompilerParams(
            vmem_limit_bytes=_KERNEL_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(_pack_params(spec.base, key), spec.base.hx_t, spec.base.hz_t,
      spec.base.lx_t, spec.base.lz_t, spec.zg_idx, spec.zg_mask,
      spec.xg_idx, spec.xg_mask, spec.llr_z, spec.llr_x)
    aux_z = {"converged": convz[0] > 0, "iterations": iterz[0]}
    aux_x = {"converged": convx[0] > 0, "iterations": iterx[0]}
    return cnt.sum(dtype=jnp.int32), minw.min(), aux_x, aux_z


@functools.partial(jax.jit, static_argnames=(
    "batch_size", "eval_type", "max_iter_z", "max_iter_x", "scale",
    "quantize", "block_w"))
def _fused_decode_xla(spec: FusedDecodeSpec, key, batch_size: int,
                      eval_type: str, max_iter_z: int, max_iter_x: int,
                      scale: float, quantize, block_w: int):
    """XLA twin: counter draws -> packed syndrome SpMV -> v2 BP twin (same
    batch tiles as the kernel, so int8 per-tile scales match) -> packed
    residual stats.  Bit-exact with the Pallas program word for word."""
    from .bp_pallas import SparseHeadGraph, _bp_head_sparse_xla

    from .gf2_packed import unpack_shots

    base = spec.base
    n = base.hx_t.shape[0]
    k0, k1 = _key_words(key)
    r = counter_draws(k0, k1, batch_size, n)
    ex, ez = _errors_from_draws(r, base.cuts)
    exp = pack_shots(ex.astype(jnp.uint8))
    ezp = pack_shots(ez.astype(jnp.uint8))
    sz = unpack_shots(packed_parity_apply(base.hx_nbr, base.hx_mask, ezp),
                      batch_size)
    sx = unpack_shots(packed_parity_apply(base.hz_nbr, base.hz_mask, exp),
                      batch_size)

    def decode(idx, mask, synd, llr0, max_iter):
        sg = SparseHeadGraph(
            chk_idx=idx, mask=mask,
            nvar=jnp.zeros((0, n), jnp.int8))
        return _bp_head_sparse_xla(
            sg, synd, llr0.reshape(-1), head_iters=max_iter,
            ms_scaling_factor=scale, block_b=block_w * LANE,
            early_stop=True, quantize=quantize)

    res_z = decode(spec.zg_idx, spec.zg_mask, sz, spec.llr_z, max_iter_z)
    res_x = decode(spec.xg_idx, spec.xg_mask, sx, spec.llr_x, max_iter_x)
    rx_p = exp ^ pack_shots(res_x.error)
    rz_p = ezp ^ pack_shots(res_z.error)
    cnt, minw = packed_residual_stats(
        rx_p, rz_p, (base.hz_nbr, base.hz_mask),
        (base.hx_nbr, base.hx_mask), base.lz_t != 0, base.lx_t != 0,
        eval_type, batch_size, n)
    aux_z = {"converged": res_z.converged,
             "iterations": res_z.iterations}
    aux_x = {"converged": res_x.converged,
             "iterations": res_x.iterations}
    return cnt, minw, aux_x, aux_z


def estimate_fused_decode_bytes(n: int, mx: int, mz: int, rwz: int,
                                rwx: int, block_w: int = 4, *,
                                quantize=None) -> float:
    """Per-block VMEM working-set estimate for the fused v2 program: the
    sampling/syndrome planes, the resident dense transposes, the sparse BP
    incidence + synthesized one-hot transients, and the per-shot BP plane
    stack of the wider sector — scaled by the calibrated ratio for kernel
    ``"fused_decode"`` (2x prior until a TPU probe lands)."""
    from ..utils import profiling

    bt = block_w * LANE
    draws = bt * n * 4
    errs = 2 * bt * n * 4
    mxu = bt * n * 4
    synd = bt * (mx + mz) * 4
    mats = (n * mx + n * mz + 2 * n * 8) * 4
    idx = (rwz * mx + rwx * mz) * 8
    onehot = 3 * max(mx, mz) * n * 2
    msg_elem = 1 if quantize else 2
    per_shot = max(
        (2 + msg_elem) * rwz * mx + 16 * n + 8 * mx,
        (2 + msg_elem) * rwx * mz + 16 * n + 8 * mz)
    analytic = draws + errs + mxu + synd + mats + idx + onehot \
        + bt * per_shot
    return analytic * profiling.calibration_ratio("fused_decode", 2.0)


def fused_decode_block_w(spec: FusedDecodeSpec, batch_size: int, *,
                         quantize=None) -> int:
    """Largest block_w from the ladder whose estimated working set fits the
    scoped cap and divides the batch; 0 = infeasible (callers fall back to
    the two-dispatch v1 fused path)."""
    d = _decode_statics(spec)
    for bw in (8, 4, 2, 1):
        if batch_size % (bw * LANE):
            continue
        est = estimate_fused_decode_bytes(
            d["n"], d["mx"], d["mz"], d["rwz"], d["rwx"], bw,
            quantize=quantize)
        if est <= _KERNEL_VMEM_LIMIT:
            return bw
    return 0


def fused_decode_feasible(spec: FusedDecodeSpec, batch_size: int, *,
                          quantize=None) -> bool:
    return fused_decode_block_w(spec, batch_size, quantize=quantize) > 0


def fused_decode_stats(spec: FusedDecodeSpec, key, batch_size: int, *,
                       eval_type: str = "Total", max_iter_z: int,
                       max_iter_x: int, ms_scaling_factor: float = 0.625,
                       quantize: str | None = None, backend: str = "auto",
                       block_w: int | None = None,
                       interpret: bool = False):
    """Whole-pipeline fused stats batch: returns device values
    ``(failure_count, min_weight, aux_x, aux_z)`` where the aux dicts carry
    per-shot ``converged``/``iterations`` (the telemetry vector's inputs).

    The Pallas program serves on TPU when the calibrated estimate fits the
    scoped-VMEM cap; everywhere else the bit-exact XLA twin runs (same
    counter-PRNG stream, same BP bodies, same batch tiles)."""
    if block_w is None:
        block_w = fused_decode_block_w(spec, batch_size,
                                       quantize=quantize) or 1
    if batch_size % (block_w * LANE):
        raise ValueError(
            f"fused v2 needs batch_size divisible by {block_w * LANE}, "
            f"got {batch_size}")
    scale = float(ms_scaling_factor)
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    use_kernel = interpret or backend == "pallas" or (
        backend == "auto" and not FORCE_XLA_TWIN and on_tpu
        and fused_decode_feasible(spec, batch_size, quantize=quantize))
    if use_kernel:
        return _fused_decode_pallas(
            spec, key, batch_size, eval_type, int(max_iter_z),
            int(max_iter_x), scale, quantize, int(block_w), interpret)
    return _fused_decode_xla(
        spec, key, batch_size, eval_type, int(max_iter_z),
        int(max_iter_x), scale, quantize, int(block_w))
