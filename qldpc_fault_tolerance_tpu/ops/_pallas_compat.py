"""Pallas TPU API compatibility across the jax 0.4 -> 0.5 rename.

jax 0.4.x exposes the TPU compiler-params dataclass as
``pltpu.TPUCompilerParams``; 0.5+ renamed it ``pltpu.CompilerParams``.
Every kernel module imports the resolved name from here so the repo runs on
both toolchains (the container bakes one; the tunneled worker may run the
other).
"""
from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

__all__ = ["CompilerParams"]
