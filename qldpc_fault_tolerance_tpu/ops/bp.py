"""Batched belief-propagation decoding on TPU.

This is the TPU-native replacement for ``ldpc.bp_decoder`` (consumed by the
reference at src/Decoders.py:47,52,80,207 and src/Decoders_SpaceTime.py:266):
scaled min-sum / product-sum BP over a sparse parity-check matrix,
syndrome-conditioned, returning a hard-decision error estimate plus
convergence flags and posterior LLRs (the soft input OSD needs).

Design (TPU-first, not a translation):
  * The Tanner graph is compiled once per H into padded adjacency arrays:
    check->neighbor and variable->neighbor index maps with cross slot maps, so
    one BP iteration is 2 leading-axis gathers + small-axis reductions.
  * **Batch-last layout**: all loop state is (m, rw, B) / (n, cw, B) / (n, B)
    with the shot batch on the minor (lane) axis.  The padded degrees rw/cw
    are ~4-12 — putting them minor would waste 120+ of the 128 vector lanes
    per tile; batch-minor keeps every lane busy and turns the edge gathers
    into contiguous row gathers (measured ~5x over batch-major on v5e).
  * The whole shot batch lives in one kernel invocation, iterations run in a
    ``lax.while_loop`` that exits when every shot in the batch has matched
    its syndrome (or max_iter is reached); converged shots freeze so results
    equal ldpc's return-on-convergence semantics.
  * Messages are float32 (bf16 loses too much for near-threshold LLRs).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "TannerGraph",
    "build_tanner_graph",
    "build_tanner_graph_host",
    "bp_decode",
    "bp_decode_two_phase",
    "BPResult",
    "llr_from_probs",
]

_BIG = 1e30  # stands in for +inf without producing NaN in exclusion arithmetic


class TannerGraph(NamedTuple):
    """Padded adjacency of a parity-check matrix, device-resident.

    All fields are arrays (shapes carry m/n statically through jit).
    """

    chk_nbr: jnp.ndarray          # (m, rw) int32: var index of each row nonzero (pad: 0)
    chk_nbr_slot: jnp.ndarray     # (m, rw) int32: slot of this edge in the var's list
    var_nbr: jnp.ndarray          # (n, cw) int32: check index of each col nonzero (pad: 0)
    var_nbr_slot: jnp.ndarray     # (n, cw) int32: slot of this edge in the check's list
    chk_mask: jnp.ndarray         # (m, rw) bool
    var_mask: jnp.ndarray         # (n, cw) bool
    h_t: jnp.ndarray              # (n, m) uint8 — transpose kept for host-side uses


class _LruCache:
    """Tiny bounded memo for per-H build artifacts.

    Sweeps rebuild decoders per (code, p) cell; the Tanner graph, Pallas
    incidence stack, and OSD packing depend only on H, so memoizing them
    turns per-cell decoder construction from seconds (host rebuild + device
    uploads over a tunneled chip) into a dict hit.  Bounded so long-lived
    multi-circuit sweeps don't pin retired structures (per advisor note on
    the FrameSampler cache).

    Thread-safe with per-key single-flight builds: the decode service
    (serve/) hits these memos from concurrent request paths
    (``GetDecoderState`` for the same H from many sessions), where an
    unguarded ``OrderedDict`` mutation can corrupt the map or build the
    same key twice.  Concurrent first requests for ONE key build it
    exactly once (losers wait on the builder); builds for DIFFERENT keys
    overlap — the map lock is never held across ``make()``, so a
    multi-code service cold start doesn't serialize seconds-long graph
    builds behind each other.  ``make()`` must not recursively request
    its own key (builds may consult OTHER caches freely; the device graph
    builder calling the host graph builder crosses cache instances)."""

    def __init__(self, maxsize: int = 128):
        import threading
        from collections import OrderedDict

        self._d = OrderedDict()
        self._lock = threading.Lock()
        self._building: dict = {}  # key -> Event set when the build lands
        self._gen = 0  # bumped by clear(); stale in-flight builds don't cache
        self.maxsize = maxsize
        # optional (key, value) callback on LRU eviction — the serve-layer
        # SessionCache counts/announces evicted sessions through it
        self.on_evict = None

    def get(self, key, make):
        import threading

        while True:
            with self._lock:
                try:
                    self._d.move_to_end(key)
                    return self._d[key]
                except KeyError:
                    pass
                waiter = self._building.get(key)
                if waiter is None:
                    waiter = self._building[key] = threading.Event()
                    gen = self._gen
                    break  # this thread builds
            # another thread is building this key: wait, then re-check (a
            # failed build leaves the map empty and the loop retries here)
            waiter.wait()
        try:
            val = make()
        except BaseException:
            with self._lock:
                self._building.pop(key, None)
            waiter.set()
            raise
        evicted = None
        with self._lock:
            # a clear() (reset_device_state after a worker restart) that
            # landed mid-build invalidates this value — its device buffers
            # may live on the dead worker; hand it to THIS caller (whose
            # enclosing retry re-resolves anyway) but never cache it
            if self._gen == gen:
                self._d[key] = val
                self._d.move_to_end(key)
                if len(self._d) > self.maxsize:
                    evicted = self._d.popitem(last=False)
            self._building.pop(key, None)
        waiter.set()
        # the hook runs OUTSIDE the lock (the map lock is never held
        # across user code): hook I/O must not stall concurrent lookups,
        # and a hook touching this cache must not deadlock
        if evicted is not None and self.on_evict is not None:
            try:
                self.on_evict(*evicted)
            except Exception:  # a hook must not poison the memo
                pass
        return val

    def peek(self, key):
        """Existing entry (LRU-touched), or KeyError — never builds."""
        with self._lock:
            self._d.move_to_end(key)
            return self._d[key]

    def keys(self):
        with self._lock:
            return list(self._d)

    def __len__(self):
        with self._lock:
            return len(self._d)

    def __contains__(self, key):
        with self._lock:
            return key in self._d

    def pop(self, key) -> bool:
        """Drop one entry (no-op when absent).  An in-flight build of the
        same key still lands afterwards — callers evicting for STALENESS
        (not device death) must also bump whatever keyed the build."""
        with self._lock:
            return self._d.pop(key, None) is not None

    def clear(self):
        with self._lock:
            self._d.clear()
            self._gen += 1


_graph_host_cache = _LruCache()
_graph_dev_cache = _LruCache()


def _h_key(h: np.ndarray):
    return (h.shape, h.tobytes())


def build_tanner_graph(h: np.ndarray) -> TannerGraph:
    """Host-build + one async device upload (no construction-time syncs).

    Memoized on H's contents: repeated decoder constructions against the
    same parity-check matrix (every p-sweep cell) reuse the device-resident
    graph."""
    h = (np.asarray(h) != 0).astype(np.uint8)
    return _graph_dev_cache.get(
        _h_key(h), lambda: jax.device_put(build_tanner_graph_host(h))
    )


def build_tanner_graph_host(h: np.ndarray) -> TannerGraph:
    """Compile H (host 0/1 matrix) into padded adjacency index maps.

    Returns numpy-leaved ``TannerGraph`` — callers that need host access
    (e.g. the Pallas incidence-stack builder) use this form to avoid
    device->host round-trips at decoder-construction time.  Memoized on H."""
    h = (np.asarray(h) != 0).astype(np.uint8)

    def make():
        g = _build_tanner_graph_host(h)
        for leaf in g:  # shared across callers — guard against mutation
            leaf.setflags(write=False)
        return g

    return _graph_host_cache.get(_h_key(h), make)


def _build_tanner_graph_host(h: np.ndarray) -> TannerGraph:
    h = (np.asarray(h) != 0).astype(np.uint8)
    m, n = h.shape
    rows = [np.nonzero(h[i])[0] for i in range(m)]
    cols = [np.nonzero(h[:, j])[0] for j in range(n)]
    rw = max((len(r) for r in rows), default=1) or 1
    cw = max((len(c) for c in cols), default=1) or 1

    chk_nbr = np.zeros((m, rw), dtype=np.int32)
    chk_mask = np.zeros((m, rw), dtype=bool)
    var_nbr = np.zeros((n, cw), dtype=np.int32)
    var_mask = np.zeros((n, cw), dtype=bool)
    chk_nbr_slot = np.zeros((m, rw), dtype=np.int32)
    var_nbr_slot = np.zeros((n, cw), dtype=np.int32)

    var_fill = [0] * n
    # slot of edge (i, j) in check i's list, keyed while filling rows
    for i, r in enumerate(rows):
        for s, j in enumerate(r):
            chk_nbr[i, s] = j
            chk_mask[i, s] = True
            t = var_fill[j]
            var_nbr[j, t] = i
            var_mask[j, t] = True
            chk_nbr_slot[i, s] = t      # where this edge sits in var j's list
            var_nbr_slot[j, t] = s      # where this edge sits in check i's list
            var_fill[j] += 1

    return TannerGraph(
        chk_nbr=chk_nbr,
        chk_nbr_slot=chk_nbr_slot,
        var_nbr=var_nbr,
        var_nbr_slot=var_nbr_slot,
        chk_mask=chk_mask,
        var_mask=var_mask,
        h_t=np.ascontiguousarray(h.T),
    )


class BPResult(NamedTuple):
    error: jnp.ndarray          # (B, n) uint8 hard-decision error estimate
    converged: jnp.ndarray      # (B,) bool — syndrome matched within max_iter
    posterior_llr: jnp.ndarray  # (B, n) float32 posterior LLRs at the stopping iteration
    iterations: jnp.ndarray     # (B,) int32 — iteration at which each shot converged


def llr_from_probs(channel_probs) -> jnp.ndarray:
    """Channel log-likelihood ratios log((1-p)/p), clipped away from p=0.

    Computed in numpy and uploaded with one async ``device_put``: decoder
    construction must not dispatch tiny device ops (each costs a full
    round-trip on a tunneled chip)."""
    p = np.clip(np.asarray(channel_probs, dtype=np.float32), 1e-12, 1.0 - 1e-7)
    return jax.device_put(np.log1p(-p) - np.log(p))


def _check_update_minsum(v2c, synd_sign, graph, scale):
    """Scaled min-sum check-node update with self-exclusion via top-2 mins.

    v2c: (m, rw, B); synd_sign: (m, B).  Returns (m, rw, B).
    """
    mask = graph.chk_mask[..., None]
    mag = jnp.where(mask, jnp.abs(v2c), _BIG)
    sgn = jnp.where(mask & (v2c < 0), -1.0, 1.0)

    # exclusion products: total sign / self sign  (signs are +-1)
    total_sign = jnp.prod(sgn, axis=1, keepdims=True) * synd_sign[:, None, :]
    excl_sign = total_sign * sgn

    # exclusion min via smallest + second-smallest
    min1 = jnp.min(mag, axis=1, keepdims=True)
    amin = jnp.argmin(mag, axis=1)                              # (m, B)
    rw = mag.shape[1]
    is_min = jnp.arange(rw, dtype=amin.dtype)[None, :, None] == amin[:, None, :]
    min2 = jnp.min(jnp.where(is_min, _BIG, mag), axis=1, keepdims=True)
    excl_min = jnp.where(is_min, min2, min1)
    excl_min = jnp.minimum(excl_min, _BIG)

    return jnp.where(mask, scale * excl_sign * excl_min, 0.0)


def _check_update_prodsum(v2c, synd_sign, graph, scale):
    """Product-sum (tanh rule) update in a numerically-guarded form."""
    del scale
    mask = graph.chk_mask[..., None]
    t = jnp.where(mask, jnp.tanh(jnp.clip(v2c, -30.0, 30.0) / 2.0), 1.0)
    t = jnp.where(jnp.abs(t) < 1e-12, jnp.where(t < 0, -1e-12, 1e-12), t)
    total = jnp.prod(t, axis=1, keepdims=True) * synd_sign[:, None, :]
    excl = jnp.clip(total / t, -0.9999999, 0.9999999)
    return jnp.where(mask, 2.0 * jnp.arctanh(excl), 0.0)


def _varying_zeros(ref, shape, dtype):
    """Zeros of ``shape``/``dtype`` carrying the same manual-axis "varying"
    status as ``ref`` — needed so loop-carry inits match body outputs when the
    kernel runs inside shard_map (shots sharded across a mesh)."""
    tag = ref.reshape(-1)[0]
    if dtype == jnp.bool_:
        return jnp.zeros(shape, dtype) | (tag.astype(jnp.int32) < -1)
    return jnp.zeros(shape, dtype) + (tag.astype(jnp.int32) * 0).astype(dtype)


def _edge_parity_bl(err, graph):
    """Syndrome of a hard decision, batch-last: err (n, B) -> (m, B) uint8."""
    bits = err[graph.chk_nbr]                                  # (m, rw, B)
    s = jnp.sum(
        jnp.where(graph.chk_mask[..., None], bits, 0), axis=1, dtype=jnp.uint8
    )
    return s & jnp.uint8(1)


@functools.partial(
    jax.jit,
    static_argnames=("max_iter", "method", "early_stop", "sectors"),
)
def bp_decode(
    graph: TannerGraph,
    syndromes,
    channel_llr,
    *,
    max_iter: int,
    method: str = "minimum_sum",
    ms_scaling_factor=0.625,
    early_stop: bool = True,
    sectors: tuple | None = None,
) -> BPResult:
    """Decode a batch of syndromes against one Tanner graph.

    syndromes: (B, m) {0,1}; channel_llr: (n,) or (B, n) float32.
    max_iter follows the reference convention of being precomputed by the
    decoder factories (num_qubits/max_iter_ratio, src/Decoders.py:123).

    ``sectors=((m0, m1, ...), (n0, n1, ...))`` marks the graph as a block
    diagonal of independent sub-decodes (check/var counts per block, in
    order).  Messages never cross blocks, so running them in one kernel is
    exactly ldpc running each block's decoder separately — convergence is
    tracked and outputs freeze **per sector**, preserving each sub-decoder's
    return-on-convergence semantics while sharing one iteration loop (this
    is how the simulators fuse their X- and Z-sector decodes).
    ``converged``/``iterations`` report the AND / max across sectors.

    The public interface is batch-major; internally everything runs
    batch-last (see module docstring) with cheap transposes at the boundary.
    """
    syndromes = jnp.asarray(syndromes)
    if syndromes.ndim == 1:
        syndromes = syndromes[None]
    b = syndromes.shape[0]
    n = graph.var_nbr.shape[0]
    m = graph.chk_nbr.shape[0]
    if sectors is None:
        sectors = ((m,), (n,))
    chk_sizes, var_sizes = sectors
    assert sum(chk_sizes) == m and sum(var_sizes) == n
    n_sec = len(chk_sizes)
    chk_off = np.concatenate([[0], np.cumsum(chk_sizes)]).astype(int)
    var_off = np.concatenate([[0], np.cumsum(var_sizes)]).astype(int)

    llr0 = jnp.broadcast_to(jnp.asarray(channel_llr, jnp.float32), (b, n))
    llr0_bl = llr0.T                                            # (n, B)
    synd_bl = syndromes.T                                       # (m, B)
    synd_sign = 1.0 - 2.0 * synd_bl.astype(jnp.float32)
    scale = jnp.asarray(ms_scaling_factor, jnp.float32)

    update = {"minimum_sum": _check_update_minsum, "product_sum": _check_update_prodsum}[
        method
    ]

    def one_iteration(v2c):
        c2v_chk = update(v2c, synd_sign, graph, scale)          # (m, rw, B)
        c2v_var = c2v_chk[graph.var_nbr, graph.var_nbr_slot]    # (n, cw, B)
        c2v_var = jnp.where(graph.var_mask[..., None], c2v_var, 0.0)
        total = llr0_bl + jnp.sum(c2v_var, axis=1)              # (n, B)
        v2c_var = total[:, None, :] - c2v_var                   # self-exclusion
        return v2c_var[graph.chk_nbr, graph.chk_nbr_slot], total

    def sector_matches(ok):
        """ok: (m, B) bool per-check match -> (n_sec, B) per-sector all."""
        return jnp.stack(
            [jnp.all(ok[chk_off[s]:chk_off[s + 1]], axis=0) for s in range(n_sec)]
        )

    def expand_to_vars(done_sec):
        """(n_sec, B) -> (n, B) per-variable freeze mask."""
        return jnp.concatenate(
            [
                jnp.broadcast_to(done_sec[s][None], (int(var_sizes[s]), b))
                for s in range(n_sec)
            ]
        )

    # carry inits derive a zero from the (possibly mesh-sharded) syndromes so
    # their varying-axis tags match the body outputs under shard_map
    zf = _varying_zeros(syndromes, (1, b), jnp.float32)
    init = dict(
        it=jnp.zeros((), jnp.int32),
        v2c=llr0_bl[graph.chk_nbr] + zf[None],                  # (m, rw, B)
        err=_varying_zeros(syndromes, (n, b), jnp.uint8),
        llr=llr0_bl + zf,
        done=_varying_zeros(syndromes, (n_sec, b), jnp.bool_),
        iters=jnp.full((n_sec, b), max_iter, jnp.int32)
        + _varying_zeros(syndromes, (n_sec, b), jnp.int32),
    )

    def cond(carry):
        not_all_done = ~jnp.all(carry["done"]) if early_stop else jnp.array(True)
        return (carry["it"] < max_iter) & not_all_done

    def body(carry):
        v2c_new, total = one_iteration(carry["v2c"])
        err_new = (total < 0).astype(jnp.uint8)                 # (n, B)
        ok = _edge_parity_bl(err_new, graph) == synd_bl         # (m, B)
        match = sector_matches(ok)                              # (n_sec, B)
        done_prev = carry["done"]
        newly = match & ~done_prev
        keep = expand_to_vars(done_prev)                        # (n, B)
        # outputs (err/llr/iters) freeze at first convergence — ldpc
        # return-on-convergence semantics; the messages themselves keep
        # updating (their values no longer reach any output), which saves
        # a (m, rw, B) select + rewrite per iteration
        return dict(
            it=carry["it"] + 1,
            v2c=v2c_new,
            err=jnp.where(keep, carry["err"], err_new),
            llr=jnp.where(keep, carry["llr"], total),
            done=done_prev | match,
            iters=jnp.where(newly, carry["it"] + 1, carry["iters"]),
        )

    out = jax.lax.while_loop(cond, body, init)
    return BPResult(
        error=out["err"].T,
        converged=jnp.all(out["done"], axis=0),
        posterior_llr=out["llr"].T,
        iterations=jnp.max(out["iters"], axis=0),
    )


# two-phase defaults, exported so auditing tools (bench._bp_utilization's
# roofline model) derive their branch structure from the SAME constants
# instead of hard-coding copies that silently rot
TWO_PHASE_HEAD_ITERS = 3
TWO_PHASE_TAIL_DIV = 16           # tail_capacity default = b // 16
TWO_PHASE_BIG_TIER_MULT = 4       # big tier = 4 * tail_capacity
# engagement gate (decoders/bp_decoders.py and bench.py's roofline model
# both import these — the literals must not drift apart): two-phase only
# pays off with enough shots to compact and enough iterations to skip
TWO_PHASE_MIN_BATCH = 64
TWO_PHASE_MIN_ITER = 9


def two_phase_head2_iters(head_iters: int, max_iter: int) -> int:
    """Deepened-head depth used by the progressive branch (shared with the
    bench roofline model)."""
    return min(max(4 * head_iters, 12), max_iter - 1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_iter", "method", "head_iters", "tail_capacity", "sectors",
        "pallas_block", "ms_scaling_factor", "quantize",
    ),
)
def bp_decode_two_phase(
    graph: TannerGraph,
    syndromes,
    channel_llr,
    *,
    max_iter: int,
    method: str = "minimum_sum",
    ms_scaling_factor=0.625,
    head_iters: int = TWO_PHASE_HEAD_ITERS,
    tail_capacity: int | None = None,
    sectors: tuple | None = None,
    pallas_head=None,
    pallas_block: int = 256,
    quantize: str | None = None,
) -> BPResult:
    """Straggler-compacted BP: run ``head_iters`` for the whole batch, then
    decode only the unconverged shots (gathered into a fixed-capacity
    sub-batch) for the full ``max_iter``.

    Bit-identical to ``bp_decode`` for every shot: converged head shots
    freeze at their convergence iteration (ldpc return-on-convergence
    semantics), and the tail redecodes stragglers from scratch — BP is
    deterministic, so iterations 1..head replay identically before
    continuing.  If more than ``tail_capacity`` shots are unconverged (far
    above threshold), a ``lax.cond`` falls back to full-batch decoding, so
    results never depend on the capacity.

    At code-capacity p ~= 1e-2 only a few percent of shots survive the head,
    so HBM traffic drops from O(B * max_iter) to O(B * head_iters +
    (B/8) * max_iter) — the main throughput lever for the Monte-Carlo WER
    pipelines.
    """
    syndromes = jnp.asarray(syndromes)
    if syndromes.ndim == 1:
        syndromes = syndromes[None]
    b = syndromes.shape[0]
    n = graph.var_nbr.shape[0]
    if tail_capacity is None:
        tail_capacity = max(1, b // TWO_PHASE_TAIL_DIV)
    if head_iters >= max_iter or tail_capacity >= b:
        return bp_decode(
            graph, syndromes, channel_llr, max_iter=max_iter, method=method,
            ms_scaling_factor=ms_scaling_factor, sectors=sectors,
        )
    llr0 = jnp.broadcast_to(jnp.asarray(channel_llr, jnp.float32), (b, n))

    # Head and tail run in the VMEM-resident Pallas kernel when the caller
    # provides its compiled incidence data (decoders build it once per H):
    # a v1 PallasHeadGraph (dense one-hot stack) or a v2 SparseHeadGraph
    # (index-gather incidence, optional int8 messages — the only head type
    # that honors ``quantize``).
    from .bp_pallas import SparseHeadGraph, bp_head_pallas, bp_head_sparse

    head_is_v2 = isinstance(pallas_head, SparseHeadGraph)
    use_pallas = (
        pallas_head is not None
        and sectors is None
        and method == "minimum_sum"
        and b % pallas_block == 0
        and np.ndim(channel_llr) == 1
        and pallas_head.max_block_b(b, want=pallas_block) > 0
    )

    def run_kernel(synd, iters, block, early_stop=False):
        if head_is_v2:
            return bp_head_sparse(
                pallas_head, synd, jnp.asarray(channel_llr, jnp.float32),
                head_iters=iters, ms_scaling_factor=float(ms_scaling_factor),
                block_b=block, early_stop=early_stop, quantize=quantize)
        return bp_head_pallas(
            pallas_head, synd, jnp.asarray(channel_llr, jnp.float32),
            head_iters=iters, ms_scaling_factor=float(ms_scaling_factor),
            block_b=block, early_stop=early_stop)

    def run_head(iters):
        if use_pallas:
            return run_kernel(syndromes, iters,
                              pallas_head.max_block_b(b, want=pallas_block))
        return bp_decode(
            graph, syndromes, channel_llr, max_iter=iters, method=method,
            ms_scaling_factor=ms_scaling_factor, sectors=sectors,
        )

    head = run_head(head_iters)
    bad = ~head.converged
    n_bad = bad.sum(dtype=jnp.int32)

    def full(_):
        return bp_decode(
            graph, syndromes, channel_llr, max_iter=max_iter, method=method,
            ms_scaling_factor=ms_scaling_factor, sectors=sectors,
        )

    def compacted_fn(capacity, head, bad):
        def compacted(_):
            # pad the gather with an out-of-range sentinel (b): padded rows
            # read a zero scratch syndrome (row b of the extended arrays) and
            # their scatters land in a scratch row sliced off below — no
            # duplicate writes to real shots, so nothing depends on scatter
            # ordering
            idx = jnp.nonzero(bad, size=capacity, fill_value=b)[0]
            synd_ext = jnp.concatenate(
                [syndromes,
                 jnp.zeros((1,) + syndromes.shape[1:], syndromes.dtype)]
            )
            llr_ext = jnp.concatenate([llr0, llr0[:1]])
            if use_pallas and pallas_head.max_block_b(capacity) > 0:
                # tail in the same VMEM-resident kernel, as one wide tile
                # with early exit (the XLA while-loop pays ~0.15ms of
                # sequential latency per iteration at straggler batch sizes)
                tail = run_kernel(
                    synd_ext[idx], max_iter,
                    pallas_head.max_block_b(capacity), early_stop=True,
                )
            else:
                tail = bp_decode(
                    graph, synd_ext[idx], llr_ext[idx], max_iter=max_iter,
                    method=method, ms_scaling_factor=ms_scaling_factor,
                    sectors=sectors,
                )

            def merge(head_arr, tail_arr):
                scratch = jnp.zeros((1,) + head_arr.shape[1:], head_arr.dtype)
                ext = jnp.concatenate([head_arr, scratch])
                return ext.at[idx].set(tail_arr)[:b]

            return BPResult(
                error=merge(head.error, tail.error),
                converged=merge(head.converged, tail.converged),
                posterior_llr=merge(head.posterior_llr, tail.posterior_llr),
                iterations=merge(head.iterations, tail.iterations),
            )

        return compacted

    # tiered capacities (tail_capacity, 4x, full): tail cost is linear in
    # the compacted size, and near threshold the straggler fraction can
    # exceed B/16 — the 4x tier keeps those batches off the full-batch path
    tiers = [tail_capacity]
    if tail_capacity * TWO_PHASE_BIG_TIER_MULT < b:
        tiers.append(tail_capacity * TWO_PHASE_BIG_TIER_MULT)

    # Progressive head deepening: when even the largest tier overflows
    # (heavy-noise regimes like the BP+OSD bench point at p=0.05, where
    # only ~27% of shots converge within 3 iterations), a second
    # fixed-depth full-batch segment runs before conceding to the full
    # decode.  Re-decoding from scratch is bit-identical (BP is
    # deterministic; converged shots freeze at their convergence
    # iteration), and the deeper head typically leaves few enough
    # stragglers for the big tier: cost ~ head2*B + max_iter*B/4 instead
    # of max_iter*B (~2.5x less at the bench point).
    head2_iters = two_phase_head2_iters(head_iters, max_iter)

    def deepen(_):
        head2 = run_head(head2_iters)
        bad2 = ~head2.converged
        n_bad2 = bad2.sum(dtype=jnp.int32)
        cap2 = tiers[-1]
        return jax.lax.cond(
            n_bad2 <= cap2, compacted_fn(cap2, head2, bad2), full, None)

    out = deepen if head2_iters > head_iters else full
    for cap in reversed(tiers):
        out = (lambda cap, nxt: lambda o: jax.lax.cond(
            n_bad <= cap, compacted_fn(cap, head, bad), nxt, o))(cap, out)
    return out(None)


@functools.partial(jax.jit, static_argnames=("max_restarts",))
def first_min_bp_decode(
    graph: TannerGraph,
    syndromes,
    channel_llr,
    *,
    max_restarts: int,
    ms_scaling_factor=0.9,
):
    """Sequential-restart 1-iteration BP (reference FirstMinBPDecoder,
    src/Decoders.py:49-74): repeatedly run single-iteration min-sum from fresh
    messages, accumulating the correction while the syndrome weight is
    non-increasing, for at most ``max_restarts`` accepted restarts.

    Batched as a ``lax.scan`` over restart steps with a per-shot active mask,
    batch-last like ``bp_decode``.
    Returns (correction (B,n) uint8, final syndrome weight (B,) int32).
    """
    syndromes = jnp.asarray(syndromes)
    if syndromes.ndim == 1:
        syndromes = syndromes[None]
    b = syndromes.shape[0]
    n = graph.var_nbr.shape[0]
    llr0 = jnp.broadcast_to(jnp.asarray(channel_llr, jnp.float32), (b, n))
    llr0_bl = llr0.T
    scale = jnp.asarray(ms_scaling_factor, jnp.float32)
    v2c0 = llr0_bl[graph.chk_nbr]                               # (m, rw, B)

    def one_iter_decode(synd_bl):
        synd_sign = 1.0 - 2.0 * synd_bl.astype(jnp.float32)
        c2v_chk = _check_update_minsum(v2c0, synd_sign, graph, scale)
        c2v_var = jnp.where(
            graph.var_mask[..., None],
            c2v_chk[graph.var_nbr, graph.var_nbr_slot],
            0.0,
        )
        total = llr0_bl + jnp.sum(c2v_var, axis=1)
        return (total < 0).astype(jnp.uint8)                    # (n, B)

    def step(carry, _):
        cur_synd, corr, active = carry
        err = one_iter_decode(cur_synd)
        new_synd = _edge_parity_bl(err, graph) ^ cur_synd
        accept = active & (
            jnp.sum(new_synd, axis=0).astype(jnp.int32)
            <= jnp.sum(cur_synd, axis=0).astype(jnp.int32)
        )
        corr = jnp.where(accept[None, :], corr ^ err, corr)
        cur_synd = jnp.where(accept[None, :], new_synd, cur_synd)
        return (cur_synd, corr, accept), None

    init = (
        syndromes.T.astype(jnp.uint8),
        _varying_zeros(syndromes, (n, b), jnp.uint8),
        ~_varying_zeros(syndromes, (b,), jnp.bool_),
    )
    (final_synd, corr, _), _ = jax.lax.scan(step, init, None, length=max_restarts)
    return corr.T, jnp.sum(final_synd, axis=0).astype(jnp.int32)
