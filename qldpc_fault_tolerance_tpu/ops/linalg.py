"""Device GF(2) linear algebra.

The reference computes syndromes / residual checks as host numpy
``H @ e % 2`` products per shot (src/Simulators.py:127-156).  Here they are
batched matmuls on the MXU: float32 accumulation is exact for row sums far
below 2**24, so ``mod 2`` of the product is exact.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def gf2_matmul(x, h_t):
    """Batched GF(2) product ``x @ h_t`` (mod 2).

    x: (..., n) any integer/bool dtype; h_t: (n, m) 0/1.
    Returns (..., m) uint8.
    """
    acc = jnp.matmul(x.astype(jnp.float32), h_t.astype(jnp.float32))
    return jnp.mod(acc, 2.0).astype(jnp.uint8)


class ParityOp:
    """Sparse GF(2) product ``x @ H.T % 2`` as a padded-adjacency gather.

    For the low-row-weight parity-check matrices here (rw <= ~12) the gather
    parity moves ~rw bytes per output bit vs n floats for the dense f32
    matmul — measured ~5x faster on the bench pipeline's syndrome/residual
    checks.  Built once per H on host; call with batched bit arrays.
    """

    def __init__(self, h):
        h = (np.asarray(h) != 0).astype(np.uint8)
        m, n = h.shape
        rows = [np.nonzero(h[i])[0] for i in range(m)]
        rw = max((len(r) for r in rows), default=1) or 1
        nbr = np.zeros((m, rw), dtype=np.int32)
        mask = np.zeros((m, rw), dtype=bool)
        for i, r in enumerate(rows):
            nbr[i, : len(r)] = r
            mask[i, : len(r)] = True
        self.shape = (m, n)
        self.nbr = jnp.asarray(nbr)
        self.mask = jnp.asarray(mask)

    def __call__(self, bits):
        """bits: (..., n) {0,1} -> (..., m) uint8 parity."""
        return parity_apply(self.nbr, self.mask, bits)


def parity_apply(nbr, mask, bits):
    """Padded-adjacency gather parity (the body of ParityOp, shared with the
    simulators' value-based pipelines, which carry (nbr, mask) as traced
    state)."""
    g = jnp.asarray(bits).astype(jnp.uint8)[..., nbr]
    s = jnp.sum(jnp.where(mask, g, 0), axis=-1, dtype=jnp.uint8)
    return s & jnp.uint8(1)


def syndrome(h, e):
    """Syndrome ``H @ e % 2`` for batched errors e: (..., n) -> (..., m)."""
    return gf2_matmul(e, jnp.asarray(h).T)


def as_device_gf2(a) -> jnp.ndarray:
    """Host {0,1} matrix -> device uint8 array."""
    return jnp.asarray(np.asarray(a), dtype=jnp.uint8)
